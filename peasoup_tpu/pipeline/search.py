"""Host-side search driver: the TPU equivalent of `peasoup`'s main +
Worker loop (reference: src/pipeline_multi.cu:262-419, 83-254).

The reference deals DM trials to one pthread per GPU; here a single
host process walks the DM list (optionally sharded across chips by
peasoup_tpu.parallel), launching ONE jitted program per DM trial that
covers the whole acceleration batch. Candidate bookkeeping (clustering,
distilling, scoring) is host work on tiny arrays, as in the reference.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.candidates import Candidate, CandidateCollection
from ..io.masks import read_killfile, read_zapfile
from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..obs.trace import job_span
from ..io.sigproc import Filterbank
from ..ops.dedisperse import (
    dedisperse,
    dedisperse_device,
    dedisperse_subband,
    fil_to_device,
    output_scale,
)
from ..ops.resample import accel_factor, select_span
from ..ops.zap import birdie_mask
from ..plan.accel_plan import AccelerationPlan
from ..plan.dm_plan import DMPlan
from ..plan.fft_plan import choose_fft_size
from ..utils import ProgressBar, trace_span
from .accel_search import make_batched_search_fn
from .checkpoint import SearchCheckpoint
from .distill import AccelerationDistiller, DMDistiller, HarmonicDistiller
from .folder import MultiFolder
from .score import CandidateScorer

log = get_logger("pipeline.search")


@dataclass
class SearchConfig:
    """Mirrors CmdLineOptions with the reference's defaults
    (include/utils/cmdline.hpp:69-209)."""

    outdir: str = "."
    killfilename: str = ""
    zapfilename: str = ""
    max_num_threads: int = 14
    limit: int = 1000
    size: int = 0  # fft size; 0 = prev power of two
    dm_start: float = 0.0
    dm_end: float = 100.0
    dm_tol: float = 1.10
    dm_pulse_width: float = 64.0
    acc_start: float = 0.0
    acc_end: float = 0.0
    acc_tol: float = 1.10
    acc_pulse_width: float = 64.0
    boundary_5_freq: float = 0.05
    boundary_25_freq: float = 0.5
    nharmonics: int = 4
    npdmp: int = 0
    min_snr: float = 9.0
    min_freq: float = 0.1
    max_freq: float = 1100.0
    max_harm: int = 16
    freq_tol: float = 1e-4
    verbose: bool = False
    progress_bar: bool = False
    # TPU-specific knobs (no reference equivalent)
    max_peaks: int = 128  # static peak-compaction size per spectrum
    # (small on purpose: top_k cost scales with the compaction size, and
    # chunks whose raw crossing count overflows are re-dispatched at the
    # next power of two automatically)
    dedisp_block: int = 16  # DM trials per dedispersion launch
    subbands: int = 0  # >0: two-stage subband dedispersion with this
    # many subbands (~sqrt(C)-fold less arithmetic at survey channel
    # counts; 0 = direct channel scan, the golden-exact default)
    subband_smear: float = 1.0  # max extra smear (samples) a trial may
    # suffer from sharing its group's nominal DM (0 = exact)
    subband_snr_loss: float = 0.1  # parity gate for the auto planner
    # (plan/dedisp_plan.py): max fractional matched-filter S/N loss a
    # subband plan may predict before exact is forced
    tune: bool = False  # auto-select exact-vs-subband-vs-matmul +
    # per-device tuned shape knobs via the tuning cache
    # (perf/tuning.py); an explicit --subbands overrides the planner
    dedisp_engine: str = ""  # force one dedispersion engine: "exact"
    # (gather scan) or "matmul" (MXU banded matmul) — "" lets the
    # plan/tuner decide ("subband" is forced via --subbands, whose
    # smear knob it needs). The CI three-way smoke pins candidate
    # parity across all of them
    subband_matmul: bool = False  # run the subband stages as banded
    # matmuls (bitwise-identical; normally set by the tuned plan)
    tuning_cache: str = ""  # tuning_cache.json path ("" = the
    # per-user default, PEASOUP_TUNING_CACHE overrides)
    accel_bucket: int = 16  # accel batch padded to a multiple of this
    dedupe_accel: bool = True  # collapse accel trials whose entire
    # rounded resample-shift maps provably coincide (identity or not)
    # into one dispatched representative per equivalence class
    # (bitwise-identical output, device work / class size)
    hbm_bytes: int = 0  # device memory budget override; 0 = ask the
    # device (memory_stats), falling back to the 12 GB v5e-ish default
    # — set this on chips that report no limit (or via the
    # PEASOUP_HBM_BYTES env var / --hbm_bytes CLI flag)
    dm_block: int = 0  # DM trials per device call; 0 = auto from HBM budget
    checkpoint_file: str = ""  # resumable per-DM-trial result store
    use_pallas: bool = True  # Pallas resample kernel on TPU backends
    use_pallas_peaks: bool = True  # fused threshold+cluster Pallas kernel
    # device sharding: 0 = auto (all local TPU chips up to
    # max_num_threads, single-device elsewhere); N = force an N-chip
    # 'dm' mesh (tests use this on the virtual CPU mesh)
    shard_devices: int = 0


@dataclass
class SearchResult:
    candidates: list
    dm_list: np.ndarray
    acc_list_dm0: np.ndarray
    timers: dict
    nsamps: int
    size: int
    n_accel_trials: int = 0  # effective (brute-force-equivalent) DM x
    # accel trials: identity-deduped trials count — their results are
    # produced bitwise — but fewer resamplings may have been dispatched


@dataclass
class PartialSearchResult:
    """A search stopped after the per-DM distills (run(finalize=False)):
    everything PeasoupSearch.finalize needs, per process slice. The
    reference analogue is one Worker's dm_trial_cands before the join
    merge (pipeline_multi.cu:356-359)."""

    cands: list  # per-DM-trial candidates, dm_idx GLOBAL
    trials: object  # this slice's dedispersed trials (device or host)
    trials_nsamps: int
    dm_offset: int  # global dm_idx of trials[0]
    dm_list: np.ndarray  # slice dm values in a per-process partial;
    # the GLOBAL list in a merged part (finalize copies it into
    # SearchResult.dm_list, which rank 0 writes to overview.xml)
    acc_list_dm0: np.ndarray
    timers: dict
    nsamps: int
    size: int
    n_accel_trials: int
    t_total_start: float


def _offset_dm_idx(cands: list, lo: int) -> None:
    """Shift local dm_idx to global, through the assoc trees."""
    seen: set[int] = set()
    stack = list(cands)
    while stack:
        c = stack.pop()
        if id(c) in seen:
            continue
        seen.add(id(c))
        c.dm_idx += lo
        stack.extend(c.assoc)


def _level_windows(
    size: int, nharms: int, min_freq: float, max_freq: float, tsamp: float
) -> np.ndarray:
    """[start_idx, limit) per harmonic level (peakfinder.hpp:78-84)."""
    size_spec = size // 2 + 1
    tobs = np.float32(size) * np.float32(tsamp)
    bin_width = 1.0 / float(tobs)
    nyquist = bin_width * size_spec
    orig_size = 2.0 * (size_spec - 1.0)
    rows = []
    for nh in range(nharms + 1):
        max_bin = int((max_freq / bin_width) * 2.0**nh)
        limit = min(size_spec, max_bin)
        start = int(orig_size * (min_freq / nyquist) * 2.0**nh)
        rows.append((start, limit))
    return np.asarray(rows, dtype=np.int32)


def _is_oom(exc: Exception) -> bool:
    """Device out-of-memory signature — now the shared taxonomy's
    :func:`peasoup_tpu.resilience.errors.is_resource_exhausted`
    (kept as a module function: the single-pulse driver and tests
    import it from here, and its contract is pinned against the real
    JAX OOM exception in tests/test_aux.py)."""
    from ..resilience import is_resource_exhausted

    return is_resource_exhausted(exc)


def _densify_ragged(
    vi: np.ndarray, vs: np.ndarray, cc: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Expand a per-DM ragged peak stream back to dense
    (nlev, padded, mx) slot arrays (cells C-order, slots in order) for
    the object-path fallback."""
    flat_cc = cc.reshape(-1).astype(np.int64)
    mx = max(int(flat_cc.max()) if flat_cc.size else 0, 1)
    idxs = np.zeros((flat_cc.size, mx), np.int64)
    snrs = np.zeros((flat_cc.size, mx), np.float64)
    ends = np.cumsum(flat_cc)
    cell = np.repeat(np.arange(flat_cc.size), flat_cc)
    within = np.arange(int(flat_cc.sum()), dtype=np.int64) - np.repeat(
        ends - flat_cc, flat_cc
    )
    idxs[cell, within] = vi
    snrs[cell, within] = vs
    return (
        idxs.reshape(*cc.shape, mx),
        snrs.reshape(*cc.shape, mx),
        cc,
    )


def _accel_pad(n: int, bucket: int) -> int:
    """Padded accel-column count for a dispatch list of length n: the
    usual bucket multiple, with one extra small shape (4) so searches
    whose accel lists collapse to a few distinct trials (the golden
    [0,-5,+5] list, or identity-deduped grids) don't pad 1-3 columns
    of real work to a 16-wide tile."""
    if n <= 4:
        return 4
    return int(math.ceil(n / bucket) * bucket)


def _dedupe_identity_accels(
    accel_lists, tsamp: float, size: int
) -> tuple[list, list]:
    """Collapse accel trials whose resamples are provably BITWISE
    EQUAL into one representative per equivalence class per DM.

    resample reads src = i + rn(af * quad(i)) with quad and the product
    each rounded once to f32 (ops/resample.py; shift-then-add — the
    bitwise claim depends on that formulation). Two trials whose entire
    rounded SHIFT MAPS i -> rn(f32(af)*quad[i]) coincide read identical
    sources, so their spectra, peaks, and candidates are bitwise
    identical; searching one representative and replicating its results
    host-side (_expand_accel_results) is output-identical to brute
    force. The IDENTITY class (map == 0 everywhere, exactly when
    |f32(af * max|quad|)| <= 0.5 by rn's monotonicity — rn(0.5) = 0
    under round-half-even) is the common case (the whole +-5 m/s^2
    tutorial grid at 2^17 samples), handled without building maps.

    Class detection (r4, VERDICT item 9): quad <= 0 everywhere, so
    maps are pointwise monotone in af and classes are CONTIGUOUS in
    af-sorted order — adjacent-pair comparison finds them all. Exact
    screens keep it cheap: equal f32 afs share a map trivially;
    differing rints at the max-|quad| bin mean the maps differ there
    (rint is odd, so rint(af*max|quad|) determines that bin's value);
    and a 64-point strided probe of the maps rejects most remaining
    unequal pairs before the full O(size) compare.

    Returns (dispatch_lists, expand_maps): expand_maps[dm] is None when
    nothing deduped, else an int array mapping each FULL accel index to
    its dispatch-list index.
    """
    max_abs_quad = _max_abs_quad_f32(size)
    dispatch_lists: list = []
    expand_maps: list = []
    max_ident_af = np.float32(0.0)
    for accs in accel_lists:
        n = len(accs)
        afs32 = accel_factor(np.asarray(accs), tsamp).astype(np.float32)
        if n <= 1:
            dispatch_lists.append(accs)
            expand_maps.append(None)
            continue
        prods = afs32 * max_abs_quad  # one f32 rounding each
        if (np.abs(prods) <= np.float32(0.5)).all():
            # whole list is the identity class: no maps needed
            class_of = np.zeros(n, dtype=np.int64)
            max_ident_af = max(max_ident_af, np.abs(afs32).max())
        else:
            quad = _quad_f32(size)
            probe = quad[:: max(1, size // 64)]
            rmax = np.rint(prods)  # the (negated) map value at max|quad|
            order = np.argsort(afs32, kind="stable")
            class_of = np.empty(n, dtype=np.int64)
            cid = -1
            prev_j = -1
            prev_map = None
            for j in order:
                if prev_j < 0:
                    new = True
                elif afs32[j] == afs32[prev_j]:
                    new = False
                elif rmax[j] != rmax[prev_j] or not np.array_equal(
                    np.rint(afs32[j] * probe), np.rint(afs32[prev_j] * probe)
                ):
                    new = True
                    prev_map = None
                else:
                    if prev_map is None:
                        prev_map = np.rint(afs32[prev_j] * quad)
                    cur = np.rint(afs32[j] * quad)
                    new = not np.array_equal(cur, prev_map)
                    prev_map = cur
                if new:
                    cid += 1
                class_of[j] = cid
                prev_j = j
        # representative = FIRST member (original order) of each class
        first_of: dict[int, int] = {}
        for i in range(n):
            first_of.setdefault(int(class_of[i]), i)
        if len(first_of) == n:
            dispatch_lists.append(accs)
            expand_maps.append(None)
            continue
        keep = sorted(first_of.values())
        pos = {full_i: j for j, full_i in enumerate(keep)}
        expand_maps.append(
            np.asarray(
                [pos[first_of[int(class_of[i])]] for i in range(n)],
                dtype=np.int64,
            )
        )
        dispatch_lists.append(np.asarray([accs[i] for i in keep]))
    if max_ident_af > 0:
        # belt-and-braces for the map-free identity fast path: replay
        # the device's exact shift chain for the LARGEST deduped |af|
        # (monotonicity covers the rest) and verify every shift is zero
        shifts = np.rint(max_ident_af * _quad_f32(size))
        assert not shifts.any(), (
            f"identity-dedupe invariant violated: af={max_ident_af!r} "
            f"has a nonzero resample shift (max |shift| = "
            f"{np.abs(shifts).max()})"
        )
    return dispatch_lists, expand_maps


@lru_cache(maxsize=8)
def _quad_f32(size: int) -> np.ndarray:
    """resample's f32-rounded quadratic index map: f32(i)*(f32(i)-f32(size))
    for all i (exactly the device computation, ops/resample.py)."""
    idx = np.arange(size, dtype=np.float32)
    quad = idx * (idx - np.float32(size))
    quad.setflags(write=False)  # cached: protect from caller mutation
    return quad


@lru_cache(maxsize=8)
def _max_abs_quad_f32(size: int) -> np.float32:
    return np.float32(np.abs(_quad_f32(size)).max())


def _expand_accel_results(vi, vs, cc, emap, padded_full):
    """Replicate a deduped dispatch's ragged per-(lvl, accel) results
    onto the full accel list (map-equivalent trials share their
    representative's spectrum bitwise). Stream cell order is C-order
    over (nlev, padded) — lvl-major — matching the device pack.
    Vectorised: one fancy-index gather, no per-cell Python loop."""
    nlev, nd = cc.shape
    flat = cc.astype(np.int64).reshape(-1)
    ends = np.cumsum(flat)
    starts = ends - flat
    a_count = len(emap)
    # output cells (lvl-major over the FULL accel list) -> source cells
    src_cells = (
        np.arange(nlev, dtype=np.int64)[:, None] * nd
        + np.asarray(emap, dtype=np.int64)[None, :]
    ).ravel()
    src_counts = flat[src_cells]
    cc_full = np.zeros((nlev, padded_full), dtype=cc.dtype)
    cc_full[:, :a_count] = src_counts.reshape(nlev, a_count)
    n_out = int(src_counts.sum())
    # per output entry: its source index = start of its source cell +
    # offset within the cell
    cell_of = np.repeat(np.arange(src_cells.size), src_counts)
    out_cell_start = np.concatenate(
        [[0], np.cumsum(src_counts)[:-1]]
    )
    within = np.arange(n_out, dtype=np.int64) - out_cell_start[cell_of]
    src = starts[src_cells][cell_of] + within
    return vi[src], vs[src], cc_full


def _freq_factor(size: int, nh: int, tsamp: float) -> np.float32:
    """Bin index -> frequency for level nh, replaying the reference's
    f32 rounding points exactly: ``float tobs = size*get_tsamp()`` (an
    f32 product — get_tsamp returns float, timeseries.hpp:123),
    ``float bin_width = 1.0/tobs`` (pipeline_multi.cu:118-119), then
    PeakFinder's ``float nyquist = bin_width*size`` and ``float factor``
    (peakfinder.hpp:77-89).  The candidate's stored f32 freq is
    ``f32(f32(idx) * factor)``."""
    size_spec = size // 2 + 1
    tobs = np.float32(size) * np.float32(tsamp)
    bin_width = np.float32(1.0 / np.float64(tobs))
    nyquist = np.float32(np.float64(bin_width) * np.float64(size_spec))
    return np.float32(
        1.0 / np.float64(size_spec) * np.float64(nyquist) / 2.0**nh
    )


class PeasoupSearch:
    # HBM accounting for auto dm_block sizing: total usable chip memory,
    # the spectra working-set budget carved from it (after the
    # device-resident trials), the cap on live peak-output buffers
    # queued per dispatch wave, and the trials size beyond which the
    # trial block spills to host RAM instead of living in HBM
    TOTAL_HBM = 12_000_000_000  # fallback when the device reports no limit
    MEM_BUDGET = 6_000_000_000
    WAVE_BUDGET = 1_000_000_000
    TRIALS_DEVICE_LIMIT = 4_000_000_000

    def __init__(self, config: SearchConfig):
        self.config = config
        self._dm_sharding = None
        # adaptive compaction size: raw threshold crossings per spectrum
        # are data-dependent (a bright pulsar crosses at every DM trial,
        # e.g. tutorial.fil peaks at ~276); once a wave escalates, start
        # every later wave at the learned size so steady state
        # dispatches each chunk exactly once
        self._learned_max_peaks = 0
        # speculative ragged-fetch size: each wave's peak stream is
        # compacted at this pow2 size and shipped WITH the counts in one
        # transfer; chunks whose true total exceeds it pay a second
        # exact-size fetch and raise the speculation for later waves
        self._learned_total_pad = 4096
        # size budgets from the real chip when it tells us (memory_stats
        # is absent on some backends, e.g. the CPU mesh in tests)

        devs = jax.local_devices()
        limit = config.hbm_bytes or int(
            os.environ.get("PEASOUP_HBM_BYTES", 0) or 0
        )
        if not limit:
            try:
                limit = (devs[0].memory_stats() or {}).get("bytes_limit", 0)
            except Exception:
                limit = 0
        if limit:
            self.TOTAL_HBM = int(limit)
            self.MEM_BUDGET = int(limit) // 2
            self.WAVE_BUDGET = max(int(limit) // 12, 250_000_000)
            self.TRIALS_DEVICE_LIMIT = int(limit) // 3

    def build_dm_plan(self, fil: Filterbank) -> DMPlan:
        """The GLOBAL dedispersion plan for this config (also used by
        the multi-host driver to partition the trial list — single
        construction site keeps the partitioning and the search in
        sync)."""
        cfg = self.config
        killmask = None
        if cfg.killfilename:
            killmask = read_killfile(cfg.killfilename, fil.nchans)
        return DMPlan.create(
            nsamps=fil.nsamps,
            nchans=fil.nchans,
            tsamp=fil.tsamp,
            fch1=fil.fch1,
            foff=fil.foff,
            dm_start=cfg.dm_start,
            dm_end=cfg.dm_end,
            pulse_width=cfg.dm_pulse_width,
            tol=cfg.dm_tol,
            killmask=killmask,
        )

    def _pick_devices(self) -> list:
        """Devices to shard DM trials over. Auto mode mirrors the
        reference's one-worker-per-GPU-up-to--t policy
        (pipeline_multi.cu:276-277) on TPU backends; elsewhere it stays
        single-device unless shard_devices forces a mesh (tests)."""

        devs = jax.local_devices()
        cfg = self.config
        if cfg.shard_devices > 0:
            return devs[: min(cfg.shard_devices, len(devs))]
        if devs and devs[0].platform == "tpu":
            return devs[: min(len(devs), cfg.max_num_threads)]
        return devs[:1]

    def run(
        self,
        fil: Filterbank,
        dm_slice: tuple[int, int] | None = None,
        finalize: bool = True,
    ) -> "SearchResult | PartialSearchResult":
        """Full search. With ``dm_slice=(lo, hi)`` only that contiguous
        block of the global DM-trial list is dedispersed and searched
        (candidates come back with GLOBAL dm_idx); with
        ``finalize=False`` the run stops after the per-DM distills and
        returns a PartialSearchResult for the multi-host merge
        (parallel/multihost.py:run_search)."""
        cfg = self.config
        tel = current_telemetry()
        timers: dict[str, float] = {}
        t_total = time.perf_counter()

        # --- dedispersion plan + execution ---------------------------------
        t0 = time.perf_counter()
        tel.set_stage("plan")
        dm_plan = self.build_dm_plan(fil)
        timers["plan"] = time.perf_counter() - t0
        global_ndm = dm_plan.ndm
        dm_lo = 0
        if dm_slice is not None:
            dm_lo, dm_hi = dm_slice
            dm_plan = dm_plan.subset(dm_lo, dm_hi)
        if dm_plan.ndm == 0:
            # empty multi-host slice (more processes than DM trials):
            # contribute zero candidates without touching the device
            size = choose_fft_size(fil.nsamps, cfg.size)
            acc_plan = AccelerationPlan(
                acc_lo=cfg.acc_start, acc_hi=cfg.acc_end, tol=cfg.acc_tol,
                pulse_width=cfg.acc_pulse_width, nsamps=size,
                tsamp=fil.tsamp, cfreq=fil.cfreq, bw=fil.foff,
            )
            part = PartialSearchResult(
                cands=[],
                trials=np.zeros((0, 1), dtype=np.uint8),
                trials_nsamps=dm_plan.out_nsamps,
                dm_offset=dm_lo,
                dm_list=dm_plan.dm_list,
                acc_list_dm0=acc_plan.generate_accel_list(0.0),
                timers=dict.fromkeys(
                    ("dedispersion", "search_device", "search_host",
                     "searching"), 0.0
                ),
                nsamps=fil.nsamps,
                size=size,
                n_accel_trials=0,
                t_total_start=t_total,
            )
            return part if not finalize else self.finalize(fil, part)
        # --- auto-tuned dedispersion plan ------------------------------
        # the measure -> decide -> cache -> reuse loop (ISSUE 8): an
        # explicit --subbands is an operator decision the planner
        # respects; otherwise resolve exact-vs-subband + tuned shape
        # knobs from the per-device tuning cache (warm buckets load
        # with zero measurement calls). Failures degrade to the
        # config's manual knobs — planning is an optimisation, never a
        # correctness dependency.
        subbands = cfg.subbands
        subband_smear = cfg.subband_smear
        dedisp_block = cfg.dedisp_block
        dedisp_engine = cfg.dedisp_engine  # "" = plan/tuner decides
        subband_matmul = cfg.subband_matmul
        smear_budgets = None
        self._tuned_dm_block = 0
        self._tuned_accel_bucket = 0
        if cfg.tune and cfg.subbands == 0 and not cfg.dedisp_engine:
            try:
                from ..perf.tuning import resolve_plan_for_filterbank

                dplan = resolve_plan_for_filterbank(
                    fil, "search", cfg, cache_path=cfg.tuning_cache or None
                )
            except Exception as exc:
                log.warning("dedispersion planning failed: %.200s", exc)
                dplan = None
            if dplan is not None:
                if dplan.engine == "subband":
                    subbands = dplan.subbands
                    subband_smear = dplan.subband_smear
                    subband_matmul = subband_matmul or dplan.subband_matmul
                    if dplan.smear_dm_scaled and dplan.smear_loss_budget:
                        # rebuild the DM-scaled per-trial budgets the
                        # planner grouped under (deterministic in the
                        # plan geometry, so nothing big hits the cache)
                        from ..plan.dedisp_plan import dm_smear_budgets

                        smear_budgets = dm_smear_budgets(
                            dm_plan.dm_list,
                            tsamp=fil.tsamp, fch1=fil.fch1, foff=fil.foff,
                            nchans=len(dm_plan.delays),
                            pulse_width_us=cfg.dm_pulse_width,
                            max_snr_loss=dplan.smear_loss_budget,
                            floor=dplan.subband_smear,
                        )
                elif dplan.engine == "matmul":
                    dedisp_engine = "matmul"
                dedisp_block = dplan.dedisp_block or dedisp_block
                # tuned wave knobs: an explicit config value wins; the
                # dataclass default opts into the per-device winner
                if cfg.dm_block == 0 and dplan.dm_block:
                    self._tuned_dm_block = int(dplan.dm_block)
                fields = type(cfg).__dataclass_fields__
                if (
                    cfg.accel_bucket == fields["accel_bucket"].default
                    and dplan.accel_bucket
                ):
                    self._tuned_accel_bucket = int(dplan.accel_bucket)
                tel.event("dedisp_plan", **dplan.summary())
                tel.set_context(dedisp_plan=dplan.summary())
                log.info(
                    "dedispersion plan: %s (subbands=%d, dedisp_block=%d, "
                    "gain %.2fx, predicted S/N loss %.3f, %s)",
                    dplan.engine, dplan.subbands, dplan.dedisp_block,
                    dplan.gain, dplan.predicted_loss, dplan.source,
                )
        t0 = time.perf_counter()
        tel.set_stage("dedispersion")
        # --- device selection: shard DM trials over local chips --------
        # (the reference's analogue: one worker per GPU up to -t,
        # pipeline_multi.cu:276-277). Selected BEFORE dedispersion so the
        # trial set is produced already sharded over the mesh — the
        # reference likewise dedisperses across all GPUs
        # (dedisp_create_plan_multi, dedisperser.hpp:25-31)
        devices = self._pick_devices()
        mesh = None
        if len(devices) > 1:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh({"dm": len(devices)}, devices=devices)
        # trials live on device (sliced there per chunk, no re-uploads)
        # unless the whole block would crowd out the search working set
        # — huge surveys spill to host RAM like the reference
        # (dedisperser.hpp:101-103) and pay a per-chunk upload instead.
        # When the mesh can hold the trials SHARDED (one 1/N slice per
        # chip), the spill threshold scales with the chip count.
        trials_bytes = dm_plan.ndm * dm_plan.out_nsamps
        shardable = (
            mesh is not None
            and subbands == 0
            and 4 * fil.nsamps * fil.nchans < 3_000_000_000
        )
        n_shard = len(devices) if shardable else 1
        spill = trials_bytes > self.TRIALS_DEVICE_LIMIT * n_shard
        tel.event(
            "device_plan", n_devices=len(devices),
            sharded=mesh is not None, trials_spill=bool(spill),
            trials_bytes=int(trials_bytes), ndm=int(dm_plan.ndm),
        )

        # --- checkpoint store (one construction + ONE load, shared by
        # the resume fast path below and the wave loop later) ---------
        ckpt = None
        restored: dict[int, tuple] = {}
        if cfg.checkpoint_file:
            ckpt = SearchCheckpoint(
                cfg.checkpoint_file,
                SearchCheckpoint.make_key(
                    cfg, fil, choose_fft_size(fil.nsamps, cfg.size),
                    global_ndm,
                ),
                slice_bounds=dm_slice,
            )
            restored = ckpt.load()

        # --- resume fast path: when EVERY trial of this run restores
        # from the checkpoint and nothing will be folded, the trial
        # data is never read — skip dedispersion entirely (it dominates
        # resume wall time at survey scale: tens of minutes of packed
        # upload + scan through a high-latency link for zero work)
        skip_dedisp = (
            ckpt is not None
            and cfg.npdmp == 0
            and dm_plan.ndm > 0
            and all(d in restored for d in range(dm_plan.ndm))
        )
        if skip_dedisp:
            log.info(
                "Resume fast path: all trials checkpointed and "
                "npdmp=0 — skipping dedispersion"
            )
            tel.event("resume_fast_path", ndm=int(dm_plan.ndm))
            trials = np.zeros((0, dm_plan.out_nsamps), dtype=np.uint8)
            spill = True  # host ndarray semantics; nothing device-resident
            self._trials_sharded = False
        with trace_span("Dedisperse"):  # NVTX parity: pipeline_multi.cu:318
            scale = output_scale(fil.nbits, int(dm_plan.killmask.sum()))
            # sharded dedispersion wants the whole masked f32 filterbank
            # replicated per chip; bigger inputs fall back to the
            # channel-chunked single-device engines
            shard_dd = shardable and not spill and not skip_dedisp
            self._trials_sharded = shard_dd
            if skip_dedisp:
                pass
            elif shard_dd:
                from ..parallel.sharded_dedisperse import dedisperse_sharded

                trials = dedisperse_sharded(
                    fil_to_device(fil),
                    dm_plan.delay_samples(),
                    dm_plan.killmask,
                    dm_plan.out_nsamps,
                    mesh,
                    scale=scale,
                    block=dedisp_block,
                )
            elif subbands > 0:
                # the subband engine stages the filterbank on DEVICE
                # regardless of trial spill (to_host only routes the
                # OUTPUTS), so always take the packed-upload + on-device
                # unpack path: 4x less H2D for 2-bit survey data
                trials = dedisperse_subband(
                    fil_to_device(fil),
                    dm_plan.delay_samples(),
                    dm_plan.killmask,
                    dm_plan.out_nsamps,
                    nsub=subbands,
                    max_smear=subband_smear,
                    scale=scale,
                    to_host=spill,
                    use_matmul=subband_matmul,
                    budgets=smear_budgets,
                )
            elif dedisp_engine == "matmul" and not spill:
                # the MXU banded-matmul engine (tuned winner or forced
                # via --dedisp_engine): bitwise-equal to the gather
                # scan, so the spill/sharded paths degrading to gather
                # elsewhere never changes candidates
                from ..ops.dedisperse import dedisperse_matmul

                trials = dedisperse_matmul(
                    fil_to_device(fil),
                    dm_plan.delay_samples(),
                    dm_plan.killmask,
                    dm_plan.out_nsamps,
                    scale=scale,
                )
            else:
                dd = dedisperse if spill else dedisperse_device
                trials = dd(
                    fil.data if spill else fil_to_device(fil),
                    dm_plan.delay_samples(),
                    dm_plan.killmask,
                    dm_plan.out_nsamps,
                    scale=scale,
                    block=dedisp_block,
                )
            if not spill and not skip_dedisp:
                # ASYNC dispatch: the trials stay in flight while the
                # host builds the wave plan and dispatches the first
                # search chunks, so dedispersion of the tail overlaps
                # the search of the head (XLA orders the per-trial
                # dependencies). The dedispersion timer therefore
                # records DISPATCH wall only; completion is absorbed
                # into search_device. PEASOUP_SYNC_DEDISP=1 restores
                # the old barrier (and the timer's old meaning) —
                # results are bitwise identical either way, pinned by
                # tests/test_dedisp_plan.py.
                if os.environ.get("PEASOUP_SYNC_DEDISP"):
                    jax.block_until_ready(trials)
                else:
                    tel.event(
                        "dedisp_async_dispatch",
                        dispatch_s=round(time.perf_counter() - t0, 4),
                    )
        timers["dedispersion"] = time.perf_counter() - t0
        tel.capture_device_memory("dedispersion")

        # --- search setup ---------------------------------------------------
        size = choose_fft_size(fil.nsamps, cfg.size)
        trials_nsamps = dm_plan.out_nsamps
        nsamps_valid = min(trials_nsamps, size)
        tobs = float(np.float32(size) * np.float32(fil.tsamp))
        # float bin_width = 1.0/tobs (pipeline_multi.cu:119) — every
        # downstream consumer (pos5/pos25, zap masks) sees the f32 value
        bin_width = float(np.float32(1.0 / tobs))
        # NOTE: the reference passes foff as the accel plan's "bw" —
        # the width term uses the CHANNEL width (pipeline_multi.cu:335-337)
        acc_plan = AccelerationPlan(
            acc_lo=cfg.acc_start,
            acc_hi=cfg.acc_end,
            tol=cfg.acc_tol,
            pulse_width=cfg.acc_pulse_width,
            nsamps=size,
            tsamp=fil.tsamp,
            cfreq=fil.cfreq,
            bw=fil.foff,
        )
        size_spec = size // 2 + 1
        if cfg.zapfilename:
            bf, bw_ = read_zapfile(cfg.zapfilename)
            zapmask = birdie_mask(bf, bw_, bin_width, size_spec)
        else:
            zapmask = np.zeros(size_spec, dtype=bool)
        zapmask_dev = jnp.asarray(zapmask)
        windows = jnp.asarray(
            _level_windows(size, cfg.nharmonics, cfg.min_freq, cfg.max_freq, fil.tsamp)
        )
        factors = [
            _freq_factor(size, nh, fil.tsamp) for nh in range(cfg.nharmonics + 1)
        ]
        pos5 = int(cfg.boundary_5_freq / bin_width)
        pos25 = int(cfg.boundary_25_freq / bin_width)

        harm_finder = HarmonicDistiller(cfg.freq_tol, cfg.max_harm, keep_related=False)
        acc_still = AccelerationDistiller(tobs, cfg.freq_tol, keep_related=True)

        # --- batched DM-trial search ----------------------------------------
        # DM trials are grouped by padded accel-list size and processed in
        # fixed (dm_block, accel_bucket) tiles: one compile per distinct
        # tile shape, vmapped over the block (vs the reference's per-trial
        # kernel launches). The search itself is device work; candidate
        # clustering/distilling below is tiny host work per trial.
        #
        # Host<->device protocol (the chip may sit behind a high-latency
        # link, so transfers are the enemy): trials stay device-resident,
        # every chunk of a wave is DISPATCHED asynchronously, then the
        # wave's counts come back in ONE packed D2H, and the peak arrays
        # in ONE more, trimmed to the observed per-chunk maximum count.
        t0 = time.perf_counter()
        tel.set_stage("searching")
        accel_lists = [
            acc_plan.generate_accel_list(float(dm)) for dm in dm_plan.dm_list
        ]
        # trial totals published BEFORE the wave loop so the live
        # status.json heartbeat can report progress against them
        tel.gauge("search.n_dm_trials", int(dm_plan.ndm))
        tel.gauge("search.n_accel_trials", sum(len(a) for a in accel_lists))
        tel.gauge("search.fft_size", int(size))
        # identity-trial dedupe: device programs run only the DISTINCT
        # resamplings; results replicate host-side, bitwise-identical
        # to brute force (see _dedupe_identity_accels)
        if cfg.dedupe_accel:
            dispatch_lists, self._accel_expand = _dedupe_identity_accels(
                accel_lists, fil.tsamp, size
            )
        else:
            dispatch_lists = accel_lists
            self._accel_expand = [None] * len(accel_lists)
        # the tuned accel bucket (explicit config values win; see the
        # plan-resolution block above)
        accel_bucket = self._tuned_accel_bucket or cfg.accel_bucket
        self._accel_full_pad = [
            _accel_pad(len(a), accel_bucket) for a in accel_lists
        ]
        if any(m is not None for m in self._accel_expand):
            n_full = sum(len(a) for a in accel_lists)
            n_disp = sum(len(a) for a in dispatch_lists)
            log.info(
                "accel dedupe: %d/%d distinct resamplings dispatched "
                "(trials with coinciding rounded shift maps share their "
                "representative's spectrum bitwise)", n_disp, n_full,
            )
            tel.event("accel_dedupe", dispatched=n_disp, full=n_full)
        bucket = accel_bucket
        by_bucket: dict[int, list[int]] = {}
        for dm_idx, accs in enumerate(dispatch_lists):
            padded = _accel_pad(len(accs), bucket)
            by_bucket.setdefault(padded, []).append(dm_idx)

        af_max = max(
            (float(np.abs(accel_factor(a, fil.tsamp)).max())
             for a in dispatch_lists if len(a)),
            default=0.0,
        )
        # gather-free select resample whenever the shift span is small:
        # at small spans the few-way select fuses into the surrounding
        # program and beats even the Pallas kernel (which still streams
        # a separate pass over HBM)
        select_smax = select_span(af_max, size)
        pallas_block = 0
        if cfg.use_pallas and not 0 < select_smax <= 8:
            from ..ops.pallas import probe_pallas_resample
            from ..ops.pallas.resample import choose_block

            pallas_block = choose_block(af_max, size)
            # real compile+run probe, oracle-checked: degrade to the
            # jnp twin instead of crashing (or silently corrupting) on
            # Mosaic toolchains that mis-handle this kernel
            if pallas_block and not probe_pallas_resample(size, pallas_block):
                pallas_block = 0
        # fused threshold+compact+cluster kernel: output is cluster
        # peaks, so overflow means cluster count > max_peaks (rare)
        # rather than raw crossings > max_peaks (common for bright
        # pulsars) - the escalation key switches accordingly
        pallas_peaks = False
        if cfg.use_pallas_peaks:
            from ..ops.pallas import probe_pallas_peaks

            pallas_peaks = probe_pallas_peaks(
                size_spec, cfg.nharmonics + 1,
                max(cfg.max_peaks, self._learned_max_peaks) or cfg.max_peaks,
            )
        self._pallas_peaks = pallas_peaks
        self._peaks_probe_nlev = cfg.nharmonics + 1
        self._peaks_probe_nbins = size_spec
        # fused matmul-rfft untwist + interbin + normalise kernel
        # (ops/pallas/interbin.py): one streaming pass replaces XLA's
        # FFT untwist/concat/normalise passes. Needs the peaks-kernel
        # path (its output is pre-padded to PEAKS_BLOCK), a pow2 size
        # whose half divides the block, and the bitwise oracle probe.
        # PEASOUP_FUSED_FFT=0 restores the stock XLA FFT chain.
        fused_interbin = False
        if pallas_peaks and os.environ.get("PEASOUP_FUSED_FFT", "1") != "0":
            from ..ops.fft import _MIN_N
            from ..ops.pallas import probe_pallas_interbin
            from ..ops.pallas.peaks import PEAKS_BLOCK

            if (
                size >= _MIN_N
                and not (size & (size - 1))
                and (size // 2) % PEAKS_BLOCK == 0
            ):
                fused_interbin = probe_pallas_interbin(size, PEAKS_BLOCK)
        self._fused_interbin = fused_interbin
        # harmonic+peaks mega-kernel (ops/pallas/harmpeaks.py): fuses
        # the whole harmonic-summing val chain AND the peaks walk into
        # one VMEM-resident Pallas dispatch — removes the conv chain's
        # HBM round trips and the conv->peaks layout copies. Gated on
        # the bitwise compile+run oracle; PEASOUP_MEGA_HARM=0 restores
        # the conv+peaks pair.
        mega_harm = False
        if pallas_peaks and os.environ.get("PEASOUP_MEGA_HARM", "1") != "0":
            from ..ops.pallas import probe_pallas_harmpeaks

            mega_harm = probe_pallas_harmpeaks(
                size_spec, cfg.nharmonics,
                max(cfg.max_peaks, self._learned_max_peaks) or cfg.max_peaks,
            )
        self._mega_harm = mega_harm
        # fused four-step DFT + untwist + interbin + normalise kernel
        # (ops/pallas/dftspec.py): one Pallas dispatch replaces the DFT
        # einsums, XLA's relayout copies around them, AND the interbin
        # kernel for the packed select-resample path. 3-pass HIGH-class
        # accuracy, gated by probe_pallas_dftspec's two-layer oracle
        # (per-bin envelope vs the contraction-exact twin + the
        # documented accuracy-class bound vs the HIGHEST chain);
        # shape-gated here so survey-scale m falls back to the einsum
        # chain instead of raising at trace time. PEASOUP_FUSED_DFT=0
        # restores the einsum + interbin-kernel chain (exact HIGHEST).
        # RESIDUAL RISK, shared with the peaks/harmpeaks probes at
        # escalated shapes: this probe compiles a Mosaic kernel
        # in-process at the production (n, npad); a toolchain that
        # SIGABRTs (rather than raising) on a bad compile kills the
        # process here instead of degrading — the env kill switch is
        # the documented escape hatch on such toolchains.
        fused_dft = False
        if fused_interbin and os.environ.get("PEASOUP_FUSED_DFT", "1") != "0":
            from ..ops.pallas import probe_pallas_dftspec
            from ..ops.pallas.dftspec import dftspec_supported
            from ..ops.pallas.peaks import PEAKS_BLOCK

            npad_spec = -(-size_spec // PEAKS_BLOCK) * PEAKS_BLOCK
            if dftspec_supported(size, npad_spec):
                fused_dft = probe_pallas_dftspec(size, npad_spec)
        self._fused_dft = fused_dft
        # fused once-per-trial spectrum chain (ops/pallas/specchain.py):
        # deredden -> zap -> interbin in ONE streaming pass over the
        # (dm_block, nbins) batch instead of three HBM walks. Gated on
        # the compile+run oracle probe (bitwise parts + FMA-envelope
        # amplitude); PEASOUP_FUSED_SPEC=0 restores the unfused stanza.
        fused_spec = False
        if os.environ.get("PEASOUP_FUSED_SPEC", "1") != "0":
            from ..ops.pallas import probe_pallas_specchain

            fused_spec = probe_pallas_specchain()
        self._fused_spec = fused_spec

        # --- search-side mesh wiring (mesh chosen before dedispersion) --
        if mesh is not None:
            from ..parallel.sharded_search import make_sharded_search_fn

            from jax.sharding import NamedSharding, PartitionSpec

            def build_search(pb: int, pp: bool = pallas_peaks):
                return make_sharded_search_fn(
                    mesh, cfg.min_snr, axis="dm", pallas_block=pb,
                    select_smax=select_smax if pb == 0 else 0,
                    pallas_peaks=pp, fused_interbin=fused_interbin and pp,
                    mega_harm=self._mega_harm and pp,
                    fused_dft=self._fused_dft and pp,
                )

            # stage blocks directly onto the mesh (no hop through chip 0)
            self._dm_sharding = NamedSharding(mesh, PartitionSpec("dm"))
            self._mesh = mesh
        else:

            def build_search(pb: int, pp: bool = pallas_peaks):
                return make_batched_search_fn(
                    cfg.min_snr, pb, select_smax if pb == 0 else 0,
                    pallas_peaks=pp, fused_interbin=fused_interbin and pp,
                    mega_harm=self._mega_harm and pp,
                    fused_dft=self._fused_dft and pp,
                    fused_spec=self._fused_spec,
                )

            self._dm_sharding = None
            self._mesh = None
        search_block = build_search(pallas_block)
        self._build_search = build_search
        self._cur_pallas_block = pallas_block
        self._active_search_block = search_block
        tim_len = min(size, trials.shape[1])

        # the GLOBAL-dm_idx-keyed store was built (and loaded ONCE)
        # before dedispersion; multi-host slices write per-slice sibling
        # files (no write contention) and load() unions every sibling,
        # so a checkpoint written under one process count resumes under
        # ANY other with zero re-searched trials
        # (tests/test_pipeline.py::test_checkpoint_process_count_independent)
        per_dm_results: dict[int, tuple] = restored
        if per_dm_results:
            log.info(
                "Resuming: %d/%d DM trials restored from %s",
                len(per_dm_results), dm_plan.ndm, cfg.checkpoint_file,
            )
            tel.event(
                "checkpoint_resume", restored=len(per_dm_results),
                ndm=int(dm_plan.ndm),
            )

        # chunk sizing: a PER-CHIP block of d_local trials, auto-sized
        # from a working-set budget of ~16 spectrum-sized f32 arrays per
        # (dm, accel) cell. The device call covers d_local * n_dev
        # trials; keeping the per-chip shape independent of the device
        # count makes sharded and single-device results bitwise
        # identical (same XLA program per chip), mirroring the
        # reference's share-nothing per-GPU workers.
        size_spec_b = (size // 2 + 1) * 4
        # spectra budget: what's left of PER-CHIP HBM after that chip's
        # share of the device-resident trials (1/N when sharded) and the
        # queued wave outputs
        trials_res = 0 if spill else trials_bytes // (
            len(devices) if self._trials_sharded else 1
        )
        mem_budget = min(
            self.MEM_BUDGET,
            self.TOTAL_HBM - trials_res - self.WAVE_BUDGET,
        )
        mem_budget = max(mem_budget, 500_000_000)

        def build_chunks(shrink: int) -> list[tuple[list[int], int]]:
            """(dm indices, dm_block) chunks; ``shrink`` halves the
            auto block size on device-OOM retries."""
            out: list[tuple[list[int], int]] = []
            for padded, dm_indices in sorted(by_bucket.items()):
                if cfg.dm_block > 0:
                    d_local = max(1, cfg.dm_block // shrink)
                elif self._tuned_dm_block:
                    # per-device tuned wave height, still capped by the
                    # memory-budget formula (tuning ranks throughput;
                    # the budget owns safety — OOM shrink still applies)
                    cells = max(8, int(mem_budget / (size_spec_b * 16)))
                    cap = max(1, min(128, cells // max(1, padded)))
                    d_local = max(
                        1, min(self._tuned_dm_block, cap) // shrink
                    )
                else:
                    cells = max(8, int(mem_budget / (size_spec_b * 16)))
                    d_local = max(
                        1, min(128, cells // max(1, padded)) // shrink
                    )
                    # fewer, fuller dispatches beat conservative ones
                    # (each wave pays fixed transfer round trips), so
                    # on the first attempt try the whole bucket as ONE
                    # chunk whenever an optimistic estimate fits — the
                    # OOM shrink-retry is the safety net for the
                    # workloads where the estimate is wrong. The
                    # per-chip shape is the GLOBAL bucket size (not
                    # divided by device count), preserving the bitwise
                    # sharded == single-device invariant above
                    one_shot = len(dm_indices)
                    est = one_shot * padded * size_spec_b * 12
                    if (
                        shrink == 1
                        and one_shot <= 128
                        and est < 0.9 * self.TOTAL_HBM - trials_res
                    ):
                        d_local = max(d_local, one_shot)
                    # equalise: 59 trials at d_local=56 would pad a
                    # 3-trial tail chunk to 56 rows of device work;
                    # split evenly instead (30+29 -> 30+30). Derived
                    # from the GLOBAL trial count only, so the per-chip
                    # block shape — and therefore the XLA program and
                    # its bitwise results — stays independent of the
                    # device count
                    n_parts = -(-len(dm_indices) // d_local)
                    d_local = -(-len(dm_indices) // n_parts)
                d_blk = d_local * len(devices)
                out.extend(
                    (dm_indices[s : s + d_blk], d_blk)
                    for s in range(0, len(dm_indices), d_blk)
                )
            return out

        # wave sizing: bound the live device output buffers (and give the
        # checkpoint a save point per wave)
        def chunk_out_bytes(chunk):
            dm_indices, d_blk = chunk
            padded = _accel_pad(len(dispatch_lists[dm_indices[0]]), bucket)
            # budget with the learned compaction size: later waves (and
            # repeat runs) dispatch at mp0, not cfg.max_peaks
            mp = max(cfg.max_peaks, self._learned_max_peaks)
            return d_blk * (cfg.nharmonics + 1) * padded * mp * 8

        def build_waves(chunks):
            waves: list[list[tuple[list[int], int]]] = []
            wave: list[tuple[list[int], int]] = []
            wave_bytes = 0
            for chunk in chunks:
                if wave and (
                    wave_bytes + chunk_out_bytes(chunk) > self.WAVE_BUDGET
                ):
                    waves.append(wave)
                    wave, wave_bytes = [], 0
                wave.append(chunk)
                wave_bytes += chunk_out_bytes(chunk)
            if wave:
                waves.append(wave)
            return waves

        progress = ProgressBar() if cfg.progress_bar else None
        if progress:
            progress.start()
        from ..resilience import DegradationLadder, faults

        # the memory degradation ladder: halving dm_block is one rung,
        # stepped repeatedly; at the floor the run falls THROUGH —
        # first to an exact (max_smear=0, bitwise-equal) subband
        # dedispersion with host-spilled trials, freeing the
        # device-resident trial block, then to the CPU backend (host
        # RAM dwarfs HBM; slow beats dead). Exhaustion below the CPU
        # rung propagates to the campaign attempt budget.
        ladder = DegradationLadder(
            "search.memory", ("dm_block_shrink", "subband", "cpu_backend")
        )
        shrink = 1
        cpu_mode = False
        fell_subband = False
        while True:
            chunks = build_chunks(shrink)
            waves = build_waves(chunks)
            tel.event(
                "wave_plan", n_waves=len(waves), n_chunks=len(chunks),
                shrink=shrink,
                max_dm_block=max((d for _, d in chunks), default=0),
                backend="cpu" if cpu_mode else "default",
            )
            try:
                faults.fire(
                    "device.oom",
                    context=(
                        "search:cpu" if cpu_mode
                        else f"search:shrink{shrink}"
                    ),
                )
                if cpu_mode:
                    with jax.default_device(jax.devices("cpu")[0]):
                        self._run_waves(
                            waves, len(chunks), per_dm_results, ckpt,
                            progress, build_search, dispatch_lists,
                            trials, tim_len, zapmask_dev, windows,
                            size=size, nsamps_valid=nsamps_valid,
                            pos5=pos5, pos25=pos25, tsamp=fil.tsamp,
                        )
                else:
                    self._run_waves(
                        waves, len(chunks), per_dm_results, ckpt,
                        progress, build_search, dispatch_lists,
                        trials, tim_len, zapmask_dev, windows,
                        size=size, nsamps_valid=nsamps_valid, pos5=pos5,
                        pos25=pos25, tsamp=fil.tsamp,
                    )
                break
            except Exception as exc:
                # device OOM: the per-cell working-set heuristic is an
                # estimate; halve the block and retry (finished trials
                # are in per_dm_results and are not re-searched)
                max_blk = max(d for _, d in chunks)
                if not _is_oom(exc):
                    raise
                if max_blk > (1 if cpu_mode else len(devices)):
                    shrink *= 2
                    new_blk = max(d for _, d in build_chunks(shrink))
                    log.warning(
                        "device OOM at dm_block=%d; retrying with "
                        "half-size blocks (dm_block=%d): %.200s",
                        max_blk, new_blk, exc,
                    )
                    tel.event(
                        "oom_shrink_retry", dm_block_old=max_blk,
                        dm_block_new=new_blk, shrink=shrink,
                        error=f"{exc!s:.200}",
                    )
                    # in-rung shrinks after a fall-through rung keep
                    # the event trail but not a ladder step (a ladder
                    # never climbs back up)
                    if ladder.current_rung in (None, "dm_block_shrink"):
                        ladder.step(
                            "dm_block_shrink", dm_block_old=max_blk,
                            dm_block_new=new_blk, error=f"{exc!s:.200}",
                        )
                    continue
                if (
                    not cpu_mode
                    and not fell_subband
                    and subbands == 0
                    and not skip_dedisp
                    and fil.nchans > 1
                ):
                    # subband rung: re-dedisperse two-stage at
                    # max_smear=0 (BITWISE the direct sum — every group
                    # shares identical delays) with the trial block
                    # spilled to host RAM, so HBM holds one chunk at a
                    # time instead of the whole (ndm, out_nsamps) block.
                    # Block sizing restarts: the rung changed the
                    # memory regime, and re-running at the original
                    # dm_block keeps the successful attempt's chunk
                    # shapes — and therefore its bits — identical to an
                    # untroubled run's.
                    fell_subband = True
                    shrink = 1
                    nsub = max(2, int(round(math.sqrt(fil.nchans))))
                    log.warning(
                        "device OOM with dm_block at the floor (%d); "
                        "falling through to exact subband dedispersion "
                        "(nsub=%d, host-spilled trials): %.200s",
                        max_blk, nsub, exc,
                    )
                    trials = dedisperse_subband(
                        fil_to_device(fil),
                        dm_plan.delay_samples(),
                        dm_plan.killmask,
                        dm_plan.out_nsamps,
                        nsub=nsub,
                        max_smear=0.0,
                        scale=scale,
                        to_host=True,
                    )
                    spill = True
                    self._trials_sharded = False
                    tel.event(
                        "oom_subband_fallback", nsub=nsub,
                        dm_block=max_blk, error=f"{exc!s:.200}",
                    )
                    ladder.step(
                        "subband", nsub=nsub, error=f"{exc!s:.200}"
                    )
                    continue
                if not cpu_mode:
                    # CPU rung: host-resident trials, single-device jnp
                    # programs (the Pallas kernels and the mesh are
                    # device-side optimisations, both bitwise-gated);
                    # block sizing restarts like the subband rung's
                    cpu_mode = True
                    shrink = 1
                    trials = np.asarray(trials)
                    spill = True
                    self._trials_sharded = False
                    self._dm_sharding = None
                    self._mesh = None
                    self._cur_pallas_block = 0
                    self._pallas_peaks = False
                    self._mega_harm = False
                    self._fused_interbin = False
                    self._fused_dft = False
                    zapmask_dev = np.asarray(zapmask_dev)
                    windows = np.asarray(windows)

                    def build_search(pb: int, pp: bool = False):
                        return make_batched_search_fn(
                            cfg.min_snr, 0, select_smax,
                            pallas_peaks=False, fused_interbin=False,
                            mega_harm=False, fused_dft=False,
                        )

                    self._build_search = build_search
                    self._active_search_block = build_search(0)
                    log.warning(
                        "device OOM after the subband fall-through; "
                        "retrying the search on the CPU backend: %.200s",
                        exc,
                    )
                    tel.event(
                        "oom_cpu_fallback", dm_block=max_blk,
                        error=f"{exc!s:.200}",
                    )
                    ladder.step(
                        "cpu_backend", dm_block=max_blk,
                        error=f"{exc!s:.200}",
                    )
                    continue
                ladder.exhausted(dm_block=max_blk, error=f"{exc!s:.200}")
                raise
        if progress:
            progress.stop()
        timers["search_device"] = time.perf_counter() - t0
        tel.capture_device_memory("search")

        # --- host candidate bookkeeping (ascending DM order) ----------------
        # idxs/snrs arrive ALREADY clustered (identify_unique_peaks ran
        # on device); the host only builds candidates and distils. The
        # per-accel-trial harmonic distill runs as ONE segmented native
        # call over every (dm, accel) trial of the run — Candidate
        # objects exist only for its survivors (the reference builds one
        # struct per raw detection, pipeline_multi.cu:233-238).
        t_host = time.perf_counter()
        tel.set_stage("search_host")
        from .. import native

        dm_trial_cands = CandidateCollection()
        if native.available():
            self._distill_trials_segmented(
                dm_plan, accel_lists, per_dm_results, factors, harm_finder,
                acc_still, dm_trial_cands,
            )
        else:
            for dm_idx, dm in enumerate(dm_plan.dm_list):
                idxs, snrs, ccounts = _densify_ragged(
                    *per_dm_results.pop(dm_idx)
                )
                accs = accel_lists[dm_idx]
                accel_trial_cands = CandidateCollection()
                for a_idx in range(len(accs)):
                    acc = float(accs[a_idx])
                    trial_cands: list[Candidate] = []
                    for lvl in range(cfg.nharmonics + 1):
                        n_found = int(ccounts[lvl, a_idx])
                        for b, s in zip(
                            idxs[lvl, a_idx, :n_found],
                            snrs[lvl, a_idx, :n_found],
                        ):
                            trial_cands.append(
                                Candidate(
                                    dm=float(dm),
                                    dm_idx=dm_idx,
                                    acc=acc,
                                    nh=lvl,
                                    snr=float(s),
                                    freq=float(
                                        np.float32(np.float32(b) * factors[lvl])
                                    ),
                                )
                            )
                    accel_trial_cands.append(harm_finder.distill(trial_cands))
                dm_trial_cands.append(acc_still.distill(accel_trial_cands.cands))
                log.debug(
                    "DM %.3f (%d/%d): %d accel trials, %d cands so far",
                    dm, dm_idx + 1, dm_plan.ndm, len(accs),
                    len(dm_trial_cands),
                )
        timers["search_host"] = time.perf_counter() - t_host
        timers["searching"] = time.perf_counter() - t0
        tel.gauge("candidates.per_dm_distill", len(dm_trial_cands))

        if dm_lo:
            _offset_dm_idx(dm_trial_cands.cands, dm_lo)
        part = PartialSearchResult(
            cands=dm_trial_cands.cands,
            # drop dedisperse_sharded's row padding: the folder derives
            # its owned dm_idx range from len(trials) (folder.py:91) and
            # padded rows would overlap the next multi-host slice
            trials=trials[: dm_plan.ndm],
            trials_nsamps=trials_nsamps,
            dm_offset=dm_lo,
            dm_list=dm_plan.dm_list,
            acc_list_dm0=acc_plan.generate_accel_list(0.0),
            timers=timers,
            nsamps=fil.nsamps,
            size=size,
            n_accel_trials=sum(len(a) for a in accel_lists),
            t_total_start=t_total,
        )
        if not finalize:
            return part
        return self.finalize(fil, part)

    def finalize(
        self,
        fil: Filterbank,
        part: "PartialSearchResult",
        fold_exchange=None,
    ) -> SearchResult:
        """Global distilling / scoring / folding over (possibly merged)
        per-DM-trial candidates. ``fold_exchange`` is the multi-host
        hook: callable(local fold outcomes) -> all processes' outcomes
        (parallel/multihost.py wires an allgather; None = single
        process)."""
        cfg = self.config
        tel = current_telemetry()
        timers = part.timers
        t0 = time.perf_counter()
        tel.set_stage("distilling")
        dm_still = DMDistiller(cfg.freq_tol, keep_related=True)
        harm_still = HarmonicDistiller(
            cfg.freq_tol, cfg.max_harm, keep_related=True, fractional_harms=False
        )
        tel.gauge("candidates.per_dm_total", len(part.cands))
        cands = dm_still.distill(part.cands)
        tel.gauge("candidates.post_dm_distill", len(cands))
        cands = harm_still.distill(cands)
        tel.gauge("candidates.post_harmonic_distill", len(cands))
        timers["distilling"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        tel.set_stage("scoring")
        scorer = CandidateScorer(
            fil.tsamp, fil.cfreq, fil.foff, abs(fil.foff) * fil.nchans
        )
        scorer.score_all(cands)
        timers["scoring"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        if cfg.npdmp > 0:
            tel.set_stage("folding")
            folder = MultiFolder(
                part.trials, part.trials_nsamps, fil.tsamp,
                pos5_freq=cfg.boundary_5_freq, pos25_freq=cfg.boundary_25_freq,
                dm_offset=part.dm_offset,
            )
            outcomes = folder.fold_outcomes(cands, cfg.npdmp)
            if fold_exchange is not None:
                outcomes = fold_exchange(outcomes)
            cands = folder.apply_outcomes(cands, outcomes)
            tel.gauge("candidates.folded", min(cfg.npdmp, len(cands)))
        timers["folding"] = time.perf_counter() - t0

        cands = cands[: cfg.limit]
        tel.gauge("candidates.final", len(cands))
        timers["total"] = time.perf_counter() - part.t_total_start
        return SearchResult(
            candidates=cands,
            dm_list=part.dm_list,
            acc_list_dm0=part.acc_list_dm0,
            timers=timers,
            nsamps=part.nsamps,
            size=part.size,
            n_accel_trials=part.n_accel_trials,
        )

    def _run_waves(
        self, waves, n_chunks, per_dm_results, ckpt, progress, build_search,
        dispatch_lists, trials, tim_len, zapmask_dev, windows,
        *, size, nsamps_valid, pos5, pos25, tsamp,
    ) -> None:
        disp = dict(
            size=size, nsamps_valid=nsamps_valid, pos5=pos5, pos25=pos25,
            tsamp=tsamp,
        )
        tel = current_telemetry()
        tel.set_progress(0, n_chunks, unit="chunks")
        n_done = 0
        for wi, wave in enumerate(waves):
            todo = [
                c for c in wave
                if not all(d in per_dm_results for d in c[0])
            ]
            if todo:
                # fleet-trace span (obs/trace.py, no-op outside a
                # campaign job): each search wave is one unit of the
                # job's connected timeline
                with job_span(
                    "wave", wave=wi, chunks=len(todo),
                ), trace_span("DM-Loop"):  # NVTX parity: pipeline_multi.cu:144
                    try:
                        self._search_wave(
                            todo, dispatch_lists, trials, tim_len, zapmask_dev,
                            windows, self._active_search_block,
                            per_dm_results, **disp,
                        )
                    except Exception as exc:
                        # the oracle probe runs at a reduced shape; if
                        # the Pallas kernel still fails at the full
                        # production shape (e.g. SMEM accel-table
                        # pressure — reported as RESOURCE_EXHAUSTED
                        # like a plain HBM OOM), fall back to the jnp
                        # resample and redo the wave. A true HBM OOM
                        # repeats on the retry below, whose exception
                        # is unwrapped and reaches the outer
                        # shrink-retry; only with no Pallas active is
                        # an error re-raised immediately
                        if self._cur_pallas_block == 0:
                            raise
                        log.warning(
                            "search wave failed with the Pallas resample "
                            "enabled (%r); retrying without Pallas", exc,
                        )
                        current_telemetry().event(
                            "pallas_resample_disabled",
                            pallas_block=self._cur_pallas_block,
                            error=f"{exc!r:.200}",
                        )
                        # ladder bookkeeping: Pallas kernel -> jnp twin
                        # is an ordered, observable degradation too
                        from ..resilience import DegradationLadder

                        DegradationLadder(
                            "search.pallas", ("jnp_twin",)
                        ).step(
                            "jnp_twin",
                            pallas_block=self._cur_pallas_block,
                            error=f"{exc!r:.200}",
                        )
                        self._cur_pallas_block = 0
                        self._active_search_block = build_search(
                            0, getattr(self, "_pallas_peaks", False)
                        )
                        self._search_wave(
                            todo, dispatch_lists, trials, tim_len, zapmask_dev,
                            windows, self._active_search_block,
                            per_dm_results, **disp,
                        )
                if ckpt is not None:
                    with job_span("checkpoint", wave=wi):
                        ckpt.save(per_dm_results)
                # revoke seam: a preempt/retire observed by the lease
                # renewer stops here, right after the checkpoint save,
                # so the resumed run restores exactly this state and
                # the final candidates stay bitwise-equal to an
                # uninterrupted sweep
                from ..resilience import check_revoke

                check_revoke("search.wave")
            n_done += len(wave)
            # live progress: the heartbeat derives rate/ETA from this
            # counter, and the stall watchdog treats its advance (or a
            # new event) as liveness
            tel.set_progress(n_done, n_chunks, unit="chunks")
            tel.incr(
                "search.dm_trials_done",
                sum(len(c[0]) for c in wave),
            )
            if progress:
                progress.update(n_done / n_chunks)

    def _distill_trials_segmented(
        self, dm_plan, accel_lists, per_dm_results, factors, harm_finder,
        acc_still, dm_trial_cands,
    ) -> None:
        """Vectorised candidate bookkeeping: build (freq, snr, nh) row
        arrays for every detection with numpy, harmonic-distill every
        accel trial in one segmented native call, then materialise
        Candidate objects for the survivors only. Ordering matches the
        object path exactly: rows are stably sorted S/N-descending
        within each (dm, accel) segment (the !IMPORTANT sort,
        distiller.hpp:31), so downstream stable sorts see the same tie
        order."""
        cfg = self.config
        from .. import native

        nlev = cfg.nharmonics + 1
        factors_arr = np.asarray(factors, dtype=np.float32)  # (nlev,)

        # Vectorised across DMs: per-DM numpy loops cost ~1 ms x ndm of
        # pure call overhead at survey scale. DMs are grouped by their
        # chunk's (nlev, padded) count shape (uniform stacks), each
        # group's rows built with one ragged-index pass, and the groups
        # reassembled into global dm-ascending order by a stable sort —
        # row order (dm asc, a asc, lvl asc, stream order) is IDENTICAL
        # to the per-DM loop this replaces.
        from collections import defaultdict

        by_shape: dict = defaultdict(list)
        for dm_idx in range(dm_plan.ndm):
            vi, vs, cc = per_dm_results.pop(dm_idx)
            by_shape[cc.shape].append(
                (dm_idx, vi, vs, cc, len(accel_lists[dm_idx]))
            )

        g_freq, g_snr, g_lvl, g_a, g_dmrow = [], [], [], [], []
        g_segc, g_dmseg = [], []
        for (nlev_, padded), entries in by_shape.items():
            g = len(entries)
            dm_ids = np.asarray([e[0] for e in entries])
            A_arr = np.asarray([e[4] for e in entries], dtype=np.int64)
            cc3 = np.stack([e[3] for e in entries]).reshape(g, -1)
            flat_cc = cc3.astype(np.int64)
            ends = np.cumsum(flat_cc, axis=1)
            starts = ends - flat_cc
            lens = np.asarray([len(e[1]) for e in entries], dtype=np.int64)
            base = np.concatenate([[0], np.cumsum(lens)[:-1]])
            viG = np.concatenate([e[1] for e in entries])
            vsG = np.concatenate([e[2] for e in entries])

            total_A = int(A_arr.sum())
            # ragged 0..A_d-1 per dm, then cell = (dm, a, lvl) C-order
            acat = np.arange(total_A, dtype=np.int64) - np.repeat(
                np.cumsum(A_arr) - A_arr, A_arr
            )
            a_cell = np.repeat(acat, nlev_)
            lvl_cell = np.tile(np.arange(nlev_, dtype=np.int64), total_A)
            dml_cell = np.repeat(np.repeat(np.arange(g), A_arr), nlev_)
            cellidx = lvl_cell * padded + a_cell
            csel = flat_cc[dml_cell, cellidx]
            n = int(csel.sum())
            seg_e = np.cumsum(csel)
            src = np.repeat(
                starts[dml_cell, cellidx] + base[dml_cell], csel
            ) + (np.arange(n, dtype=np.int64) - np.repeat(seg_e - csel, csel))
            lvl_rows = np.repeat(lvl_cell, csel)
            # f32(f32(idx) * f32 factor): the reference's int*float
            # multiply (peakfinder.hpp:90), widened to f64 only after
            g_freq.append(
                (viG[src].astype(np.float32) * factors_arr[lvl_rows])
                .astype(np.float32)
                .astype(np.float64)
            )
            g_snr.append(vsG[src].astype(np.float64))
            g_lvl.append(lvl_rows.astype(np.int32))
            g_a.append(np.repeat(a_cell, csel).astype(np.int32))
            g_dmrow.append(np.repeat(dm_ids[dml_cell], csel))
            g_segc.append(csel.reshape(total_A, nlev_).sum(axis=1))
            g_dmseg.append(np.repeat(dm_ids, A_arr))

        dm_of_row = np.concatenate(g_dmrow) if g_dmrow else np.zeros(0, int)
        perm = np.argsort(dm_of_row, kind="stable")
        freqs_all = np.concatenate(g_freq)[perm]
        snr_all = np.concatenate(g_snr)[perm]
        lvl_all = np.concatenate(g_lvl)[perm]
        a_all = np.concatenate(g_a)[perm]
        dm_of_seg_cat = np.concatenate(g_dmseg) if g_dmseg else np.zeros(0, int)
        segperm = np.argsort(dm_of_seg_cat, kind="stable")
        seg_counts = np.concatenate(g_segc)[segperm].astype(np.int64)
        dm_of_seg = dm_of_seg_cat[segperm]
        seg_id = np.repeat(np.arange(seg_counts.size), seg_counts)

        # within-segment S/N-descending order.  The reference's sort is
        # std::sort (UNSTABLE introsort, distiller.hpp:31) whose
        # arrangement of exact S/N ties decides distill winners — replay
        # it via the native runtime; stable lexsort is the fallback.
        seg_off0 = np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(seg_counts)]
        )
        # per-row acceleration lookup, built ONCE here and reused by
        # both the tie capture below and the post-distill s_acc lookup
        max_a = max((len(a) for a in accel_lists[: dm_plan.ndm]), default=1)
        acc_tab = np.zeros((dm_plan.ndm, max(max_a, 1)))
        for di, accs in enumerate(accel_lists[: dm_plan.ndm]):
            acc_tab[di, : len(accs)] = accs
        if os.environ.get("PEASOUP_TIE_CAPTURE"):
            # tie-stability capture (tools/tie_mc.py): the raw pre-sort
            # rows + segment structure — everything needed to replay
            # the full distill chain offline under S/N perturbations
            # (PARITY.md acc-tie analysis). Written, not kept: the
            # analysis runs in its own process.
            np.savez(
                os.environ["PEASOUP_TIE_CAPTURE"],
                freqs=freqs_all, snr=snr_all, lvl=lvl_all, a=a_all,
                seg_counts=seg_counts, dm_of_seg=dm_of_seg,
                acc_tab=acc_tab, dm_list=dm_plan.dm_list,
                harm_tol=harm_finder.tolerance,
                harm_max=harm_finder.max_harm,
                harm_frac=harm_finder.fractional_harms,
                acc_tobs_over_c=acc_still.tobs_over_c,
                acc_tol=acc_still.tolerance,
                freq_tol=cfg.freq_tol, max_harm=cfg.max_harm,
            )
        order = native.snr_sort_perm_seg(
            snr_all.astype(np.float32), seg_off0
        )
        if order is None:
            order = np.lexsort((-snr_all, seg_id))
        seg_off = seg_off0
        unique = native.harmonic_distill_seg(
            freqs_all[order], lvl_all[order], seg_off,
            harm_finder.tolerance, harm_finder.max_harm,
            harm_finder.fractional_harms,
        )

        surv = order[unique]  # original-row ids, in (segment, snr desc) order
        s_dm = dm_of_seg[seg_id[surv]]
        s_a = a_all[surv]
        s_lvl = lvl_all[surv]
        s_snr = snr_all[surv]
        s_freq = freqs_all[surv]

        # per-row acceleration values via the padded (ndm, maxA) lookup
        # built above (shared with the tie capture)
        s_acc = acc_tab[s_dm, s_a]

        # the acceleration distill runs as ONE segmented native call
        # over every DM trial (segment = DM, rows in the reference's
        # std::sort S/N-descending arrangement — the !IMPORTANT sort
        # applied to the per-DM concatenation of per-accel survivors),
        # with winner->loser edges building the assoc tree the scorer
        # reads.  s_dm is non-decreasing (segments were built dm-asc,
        # a-asc), so the per-DM slices of surv are exactly the
        # reference's accel_trial_cands input order.
        seg_bounds = np.searchsorted(s_dm, np.arange(dm_plan.ndm + 1))
        order2 = native.snr_sort_perm_seg(
            s_snr.astype(np.float32), seg_bounds.astype(np.int64)
        )
        if order2 is None:
            order2 = np.lexsort((-s_snr, s_dm))
        d_dm, d_a, d_lvl = s_dm[order2], s_a[order2], s_lvl[order2]
        d_snr, d_freq, d_acc = s_snr[order2], s_freq[order2], s_acc[order2]
        seg_off2 = np.searchsorted(d_dm, np.arange(dm_plan.ndm + 1))
        seg_res = native.accel_distill_seg(
            d_freq, d_acc, seg_off2, acc_still.tobs_over_c,
            acc_still.tolerance,
        )
        if seg_res is not None:
            unique2, esrc, edst = seg_res
            dm_vals = dm_plan.dm_list
            row_cands = [
                Candidate(
                    dm=float(dm_vals[d_dm[r]]),
                    dm_idx=int(d_dm[r]),
                    acc=float(d_acc[r]),
                    nh=int(d_lvl[r]),
                    snr=float(d_snr[r]),
                    freq=float(d_freq[r]),
                )
                for r in range(len(order2))
            ]
            for s_, t_ in zip(esrc, edst):
                row_cands[s_].append(row_cands[t_])
            for dm_idx in range(dm_plan.ndm):
                lo, hi = seg_off2[dm_idx], seg_off2[dm_idx + 1]
                dm_trial_cands.append(
                    [row_cands[r] for r in range(lo, hi) if unique2[r]]
                )
                log.debug(
                    "DM %.3f (%d/%d): %d accel trials, %d cands so far",
                    float(dm_vals[dm_idx]), dm_idx + 1, dm_plan.ndm,
                    len(accel_lists[dm_idx]), len(dm_trial_cands),
                )
            return

        bounds = np.searchsorted(s_dm, np.arange(dm_plan.ndm + 1))
        for dm_idx in range(dm_plan.ndm):
            dm = float(dm_plan.dm_list[dm_idx])
            accs = accel_lists[dm_idx]
            lo, hi = bounds[dm_idx], bounds[dm_idx + 1]
            accel_trial_cands = [
                Candidate(
                    dm=dm,
                    dm_idx=dm_idx,
                    acc=float(accs[s_a[r]]),
                    nh=int(s_lvl[r]),
                    snr=float(s_snr[r]),
                    freq=float(s_freq[r]),
                )
                for r in range(lo, hi)
            ]
            dm_trial_cands.append(acc_still.distill(accel_trial_cands))
            log.debug(
                "DM %.3f (%d/%d): %d accel trials, %d cands so far",
                dm, dm_idx + 1, dm_plan.ndm, len(accs),
                len(dm_trial_cands),
            )

    def _dispatch_chunk(
        self, chunk, dispatch_lists, trials, tim_len, zapmask_dev, windows,
        search_block, max_peaks, *, size, nsamps_valid, pos5, pos25, tsamp,
    ):
        """Asynchronously launch one (dm_block, accel_bucket) device
        tile; returns (device peaks, padded accel count)."""
        cfg = self.config
        bucket = cfg.accel_bucket
        dm_indices, dm_block = chunk
        real = len(dm_indices)
        padded = max(
            _accel_pad(len(dispatch_lists[d]), bucket) for d in dm_indices
        )
        # pad the block to its fixed shape by repeating the first trial
        # (discarded): one compile per (dm_block, padded) tile shape
        block_idx = dm_indices + [dm_indices[0]] * (dm_block - real)
        afs = np.zeros((dm_block, padded), dtype=np.float32)
        for row, dm_idx in enumerate(block_idx):
            accs = dispatch_lists[dm_idx]
            afs[row, : len(accs)] = accel_factor(accs, tsamp).astype(
                np.float32
            )

        idx = np.asarray(block_idx, dtype=np.int32)
        if isinstance(trials, np.ndarray):
            # spilled trials: slice on host, upload the chunk (sharded
            # straight onto the mesh when one is active)
            rows = trials[idx, :tim_len]
            tims_dev = (
                jax.device_put(rows, self._dm_sharding)
                if self._dm_sharding is not None
                else jnp.asarray(rows)
            )
        elif self._mesh is not None and getattr(self, "_trials_sharded", False):
            # trials live SHARDED on the mesh (dedisperse_sharded):
            # regroup the chunk's rows on-device — XLA moves only the
            # needed u8 rows chip-to-chip over ICI, no host hop
            from ..parallel.sharded_dedisperse import make_row_gather

            gather = make_row_gather(self._mesh, "dm", tim_len)
            tims_dev = gather(trials, jnp.asarray(idx))
        else:
            # single-device trials: trial rows are sliced ON DEVICE,
            # then (with a mesh active but unsharded trials, e.g. the
            # subband path) staged onto the mesh. Chunks are almost
            # always CONSECUTIVE dm rows (build_chunks deals contiguous
            # ranges; only the block-padding tail repeats row 0), so a
            # plain slice+broadcast replaces the row gather
            lo, hi = int(idx[0]), int(idx[real - 1]) + 1
            if np.array_equal(idx[:real], np.arange(lo, hi)):
                body = jax.lax.slice(trials, (lo, 0), (hi, tim_len))
                if real < len(idx):
                    pad = jnp.broadcast_to(
                        body[:1], (len(idx) - real, tim_len)
                    )
                    rows = jnp.concatenate([body, pad], axis=0)
                else:
                    rows = body
            else:
                rows = jnp.take(trials, jnp.asarray(idx), axis=0)[
                    :, :tim_len
                ]
            tims_dev = (
                jax.device_put(rows, self._dm_sharding)
                if self._dm_sharding is not None
                else rows
            )
        afs_dev = (
            jax.device_put(afs, self._dm_sharding)
            if self._dm_sharding is not None
            else jnp.asarray(afs)
        )
        peaks = search_block(
            tims_dev,
            afs_dev,
            zapmask_dev,
            windows,
            size=size,
            nsamps_valid=nsamps_valid,
            nharms=cfg.nharmonics,
            max_peaks=max_peaks,
            pos5=pos5,
            pos25=pos25,
        )
        return peaks, padded

    def _search_wave(
        self, wave, dispatch_lists, trials, tim_len, zapmask_dev, windows,
        search_block, per_dm_results, *, size, nsamps_valid, pos5, pos25,
        tsamp,
    ) -> None:
        """Dispatch every chunk of the wave, then fetch results with ONE
        packed D2H transfer: counts, cluster counts, AND the ragged peak
        stream compacted at a learned speculative size ride together.
        The link's per-transfer latency dwarfs the payload, so a second
        round trip only happens when the speculation was too small (the
        first-ever wave) or a chunk's compaction overflowed."""
        from ..ops.peaks import compact_peaks_device, pack_chunk_results

        cfg = self.config
        nlev = cfg.nharmonics + 1
        disp = dict(
            size=size, nsamps_valid=nsamps_valid, pos5=pos5, pos25=pos25,
            tsamp=tsamp,
        )
        args = (dispatch_lists, trials, tim_len, zapmask_dev, windows,
                search_block)


        mp0 = max(cfg.max_peaks, self._learned_max_peaks)
        spec_pad = self._learned_total_pad
        pend = []
        packs = []
        for chunk in wave:
            peaks, padded = self._dispatch_chunk(chunk, *args, mp0, **disp)
            # record which peaks mode produced this chunk: a mid-wave
            # degrade must not re-judge earlier fused-kernel chunks by
            # raw-crossing counts
            pend.append(
                [chunk, mp0, peaks, padded,
                 getattr(self, "_pallas_peaks", False)]
            )
            packs.append(
                pack_chunk_results(
                    peaks.idxs, peaks.snrs, peaks.counts, peaks.ccounts,
                    total_pad=spec_pad,
                )
            )

        # ONE packed transfer for the whole wave: each chunk contributes
        # [raw counts | cluster counts | speculatively compacted peak
        # stream] from a single jitted pack. Chunks whose static
        # compaction overflowed are re-dispatched with the next
        # power-of-two size (the reference sizes for 100000 up front,
        # peakfinder.hpp:61) -- rare, and only they pay extra round trips
        packed_all = np.asarray(
            packs[0] if len(packs) == 1 else jnp.concatenate(packs)
        )
        counts_list = []
        ccounts_list = []
        spec_pieces = []
        redispatched = []
        off = 0
        for entry in pend:
            chunk, max_peaks, peaks, padded, fused = entry
            n = peaks.counts.shape[0] * nlev * padded
            counts = packed_all[off : off + n].reshape(-1, nlev, padded)
            ccounts = packed_all[off + n : off + 2 * n].reshape(
                -1, nlev, padded
            )
            spec_pieces.append(
                packed_all[off + 2 * n : off + 2 * n + 2 * spec_pad]
            )
            off += 2 * n + 2 * spec_pad
            redisp = False
            # overflow: raw crossings outgrew the compaction (jnp
            # path) or clusters outgrew it (fused-kernel path)
            ov = ccounts if fused else counts
            while ov.max() > max_peaks:
                old_mp = max_peaks
                max_peaks = 1 << int(np.ceil(np.log2(ov.max())))
                self._learned_max_peaks = max(
                    self._learned_max_peaks, max_peaks
                )
                log.debug(
                    "peak compaction overflow: escalating max_peaks "
                    "%d -> %d (observed %d)", old_mp, max_peaks,
                    int(ov.max()),
                )
                current_telemetry().event(
                    "max_peaks_escalated", old=int(old_mp),
                    new=int(max_peaks), observed=int(ov.max()),
                )
                # the redispatch below runs on the CURRENT active search
                # block, which an earlier chunk's escalation may have
                # degraded after this chunk was dispatched — resync the
                # entry-local flag so the overflow semantics (raw counts
                # for the jnp path, cluster counts for the kernels) and
                # the probe gate match the block actually used
                fused = getattr(self, "_pallas_peaks", False)
                if fused:
                    # the kernels were only oracle-probed at the startup
                    # compaction size; re-probe the escalated shape and
                    # degrade (mega-kernel -> conv+peaks -> jnp) rather
                    # than running an unvalidated kernel
                    from ..ops.pallas import (
                        probe_pallas_harmpeaks, probe_pallas_peaks,
                    )

                    mega_was = getattr(self, "_mega_harm", False)
                    if mega_was and not probe_pallas_harmpeaks(
                        self._peaks_probe_nbins, self._peaks_probe_nlev - 1,
                        max_peaks,
                    ):
                        self._mega_harm = False
                        current_telemetry().event(
                            "mega_harm_disabled", max_peaks=int(max_peaks)
                        )
                    if not getattr(
                        self, "_mega_harm", False
                    ) and not probe_pallas_peaks(
                        self._peaks_probe_nbins, self._peaks_probe_nlev,
                        max_peaks,
                    ):
                        fused = False
                        self._pallas_peaks = False
                        current_telemetry().event(
                            "pallas_peaks_disabled", max_peaks=int(max_peaks)
                        )
                    if not fused or mega_was != getattr(
                        self, "_mega_harm", False
                    ):
                        search_block = self._build_search(
                            self._cur_pallas_block, fused
                        )
                        self._active_search_block = search_block
                        args = args[:5] + (search_block,)
                peaks, padded = self._dispatch_chunk(
                    chunk, *args, max_peaks, **disp
                )
                counts = np.asarray(peaks.counts)
                ccounts = np.asarray(peaks.ccounts)
                ov = ccounts if fused else counts
                entry[1:] = [max_peaks, peaks, padded, fused]
                redisp = True
            counts_list.append(counts)
            ccounts_list.append(ccounts)
            redispatched.append(redisp)

        # Unpack each chunk's ragged peak stream. The speculative piece
        # that rode the counts transfer serves whenever the chunk was
        # not re-dispatched and its true total fits spec_pad; otherwise
        # (first-ever wave, busier data, or escalation) compact at the
        # exact pow2-padded size and pay one extra transfer — and learn
        # the size so the next wave's speculation covers it.
        for i, ((chunk, max_peaks, peaks, padded, _), ccounts) in enumerate(
            zip(pend, ccounts_list)
        ):
            cc0 = np.minimum(ccounts, max_peaks)
            total = int(cc0.sum())
            total_pad = 1 << max(6, int(np.ceil(np.log2(max(1, total)))))
            # learn upward, but cap the speculation: one RFI-storm chunk
            # must not permanently inflate every later chunk's payload
            # beyond what the saved round trip is worth (~512 KiB)
            self._learned_total_pad = min(
                max(self._learned_total_pad, total_pad), 1 << 16
            )
            if not redispatched[i] and total <= spec_pad:
                piece = spec_pieces[i]
                total_pad = spec_pad
            else:
                piece = np.asarray(
                    compact_peaks_device(
                        peaks.idxs, peaks.snrs, peaks.ccounts,
                        total_pad=total_pad,
                    )
                )
            vi = piece[:total_pad]
            vs = piece[total_pad : 2 * total_pad].view(np.float32)
            cc = cc0  # (d, nlev, padded)
            # per-row entry ranges within the chunk's ragged stream
            row_ends = np.cumsum(cc.reshape(cc.shape[0], -1).sum(axis=1))
            dm_indices = chunk[0]
            for row in range(len(dm_indices)):
                lo = int(row_ends[row - 1]) if row else 0
                hi = int(row_ends[row])
                dm_idx = dm_indices[row]
                emap = self._accel_expand[dm_idx]
                if emap is None:
                    per_dm_results[dm_idx] = (vi[lo:hi], vs[lo:hi], cc[row])
                else:
                    # deduped dispatch: replicate the representative's
                    # results onto every identity accel column (bitwise
                    # what brute force would have produced)
                    per_dm_results[dm_idx] = _expand_accel_results(
                        vi[lo:hi], vs[lo:hi], cc[row], emap,
                        self._accel_full_pad[dm_idx],
                    )
