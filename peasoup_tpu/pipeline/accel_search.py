"""The per-DM-trial acceleration-search device program.

This is the TPU replacement for the reference's hot loop
(Worker::start, src/pipeline_multi.cu:144-243): where the CUDA code
runs one FFT/spectrum/harmonic/peak pass per acceleration trial, here
the WHOLE acceleration batch for a DM trial is one jitted array
program — resampling is a (A, N) gather, the FFT is one batched rfft,
and peak extraction is a masked static-size compaction per harmonic
level. Python never touches per-trial spectra.

Stages (reference line refs in parentheses):
  pad/truncate (pipeline_multi.cu:112-114,160-163) -> rfft (174) ->
  |.| (178) -> running median (182) -> deredden (186) -> zap (188-192)
  -> interbin + stats (196-200) -> irfft (204) -> per-accel: resample
  (212), rfft (216), interbin (220), normalise (224), harmonic sums
  (228), peak extraction (233-234).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.harmonics import harmonic_sums
from ..ops.peaks import cluster_peaks_device, find_peaks_device
from ..ops.rednoise import whiten_fseries
from ..ops.resample import resample_accel
from ..ops.spectrum import form_interpolated, normalise, spectrum_stats
from ..ops.zap import zap_birdies


class AccelSearchPeaks(NamedTuple):
    """Static-size peak sets for one DM trial.

    idxs/snrs: (nharms+1, A, max_peaks) — level 0 is the fundamental
    spectrum, level h the 2^h-harmonic sum. counts: (nharms+1, A) raw
    threshold crossings (the overflow-escalation signal). With
    on-device clustering (``cluster=True``, the default) idxs/snrs hold
    the min-gap CLUSTER peaks (identify_unique_peaks semantics) and
    ccounts their per-cell count; without it ccounts == counts and
    idxs/snrs are the raw crossings.
    """

    idxs: jax.Array
    snrs: jax.Array
    counts: jax.Array
    ccounts: jax.Array


def _pad_trial(tim, *, size, nsamps_valid):
    """Pad/truncate one trial to ``size`` with the reference's
    mean-padded tail (pipeline_multi.cu:160-163)."""
    x = tim[:size].astype(jnp.float32)
    if nsamps_valid < size:
        # the input trial may be shorter than size, so pad to shape first
        x = jnp.pad(x, (0, size - x.shape[0]))
        mean_head = jnp.mean(x[:nsamps_valid])
        idx = jnp.arange(size)
        x = jnp.where(idx < nsamps_valid, x, mean_head)
    return x


def _preprocess_trial(tim, zapmask, *, size, nsamps_valid, pos5, pos25):
    """Once-per-DM-trial stage: pad, whiten, zap, stats, back to time
    domain (pipeline_multi.cu:160-204). Returns (xd, mean, std)."""
    x = _pad_trial(tim, size=size, nsamps_valid=nsamps_valid)
    fser = whiten_fseries(x, pos5=pos5, pos25=pos25)
    fser = zap_birdies(fser, zapmask)
    s0 = form_interpolated(fser)
    mean, _, std = spectrum_stats(s0)
    xd = jnp.fft.irfft(fser, n=size)
    return xd, mean, std


def _pre_spectrum_parts(tim, *, size, nsamps_valid, pos5, pos25):
    """The fused-chain front half for one trial: pad, rfft, running
    median — returning the raw spectrum PARTS the fused
    deredden+zap+interbin pass consumes (vmapped over the block)."""
    from ..ops.rednoise import running_median
    from ..ops.spectrum import form_power

    x = _pad_trial(tim, size=size, nsamps_valid=nsamps_valid)
    fser = jnp.fft.rfft(x)
    med = running_median(form_power(fser), pos5=pos5, pos25=pos25)
    return (
        jnp.real(fser).astype(jnp.float32),
        jnp.imag(fser).astype(jnp.float32),
        med,
    )


def _preprocess_block_fused(
    tims, zapmask, *, size, nsamps_valid, pos5, pos25
):
    """Block-batched once-per-DM-trial stage with the spectrum-chain
    tail (deredden -> zap -> interbin) FUSED into one Pallas pass over
    the whole (D, nbins) batch (ops/pallas/specchain.py; callers gate
    on probe_pallas_specchain). Returns (xd, mean, std) like the
    vmapped :func:`_preprocess_trial`."""
    from ..ops.pallas.specchain import interp_deredden_zap_pallas

    re, im, med = jax.vmap(
        lambda tim: _pre_spectrum_parts(
            tim, size=size, nsamps_valid=nsamps_valid, pos5=pos5,
            pos25=pos25,
        )
    )(tims)
    re_d, im_d, s0 = interp_deredden_zap_pallas(re, im, med, zapmask)
    mean, _, std = spectrum_stats(s0)
    xd = jnp.fft.irfft(jax.lax.complex(re_d, im_d), n=size)
    return xd, mean, std


def _spectra_and_peaks(
    xr, mean, std, windows, *, threshold, nharms, max_peaks, stack_axis,
    cluster=True, pallas_peaks=False, fused_interbin=False,
    mega_harm=False, fused_dft=False,
):
    """Post-resample stage: batched rfft, interbin, normalise, harmonic
    sums, per-level peak compaction (pipeline_multi.cu:216-234), and —
    with ``cluster`` — the min-gap peak clustering the reference runs
    on the host (peakfinder.hpp:27-56), kept on device so only cluster
    peaks ever cross the host link. With ``pallas_peaks`` the
    compaction + clustering run as the fused streaming kernel
    (ops/pallas/peaks.py): same outputs, but idxs/snrs hold CLUSTER
    peaks sized ``max_peaks`` while raw crossings are only counted —
    overflow then means ccounts > max_peaks, not counts. ``xr`` is
    (..., A, size); mean/std broadcast against (..., A)."""
    # named scopes mirror the reference's NVTX ranges inside the jitted
    # program (pipeline_multi.cu:207, harmonicfolder.hpp:28): ops carry
    # the scope in their metadata, so profiler traces group them
    packed = isinstance(xr, tuple)  # pre-deinterleaved (even, odd) planes
    # 4-D packed planes are pre-shaped (.., n1, n2) for the fused DFT
    # kernel (resample_select_packed_planes): flat sample count is the
    # product of the two plane dims
    shaped = packed and xr[0].ndim == 4
    if shaped:
        size = 2 * xr[0].shape[-2] * xr[0].shape[-1]
    else:
        size = 2 * xr[0].shape[-1] if packed else xr.shape[-1]
    nbins = size // 2 + 1
    kernel_scales = pallas_peaks and cluster
    # per-level rsqrt(2^h) factors, applied in VMEM by the kernel paths
    # and pre-applied by harmonic_sums(scaled=True) on the jnp path
    lvl_scales = (1.0,) + tuple(
        2.0 ** (-h / 2.0) for h in range(1, nharms + 1)
    )
    with jax.named_scope("Acceleration-Loop"):
        from ..ops.fft import _use_matmul, rfft_pow2_matmul_parts
        from ..ops.spectrum import form_interpolated_parts

        if fused_interbin and kernel_scales:
            # matmul four-step packed DFT, then ONE Pallas pass does
            # untwist + interbin + normalise and emits the spectrum
            # already padded to the peaks kernel's block alignment
            # (ops/pallas/interbin.py) — callers gate on the
            # probe_pallas_interbin oracle
            from ..ops.fft import packed_dft_z, packed_dft_z_parts
            from ..ops.pallas.interbin import untwist_interbin_normalise
            from ..ops.pallas.peaks import PEAKS_BLOCK

            batch = (
                xr[0].shape[:-2] if shaped
                else xr[0].shape[:-1] if packed else xr.shape[:-1]
            )
            npad = -(-nbins // PEAKS_BLOCK) * PEAKS_BLOCK
            if fused_dft and packed:
                # one Pallas kernel does DFT + untwist + interbin +
                # normalise per row stripe in VMEM (ops/pallas/
                # dftspec.py): kills the einsum layout copies and the
                # Z round trip. 3-pass HIGH-class accuracy, validated
                # end to end by the golden-recall gate (probe-gated;
                # PEASOUP_FUSED_DFT=0 restores this einsum chain).
                # Producers send (.., n1, n2) pre-shaped planes so the
                # select writes the kernel's tile layout directly
                # (flat planes would relayout-copy here)
                from ..ops.pallas.dftspec import dft_untwist_interbin

                if shaped:
                    n1, n2 = xr[0].shape[-2:]
                    pe = xr[0].reshape(-1, n1, n2)
                    po = xr[1].reshape(-1, n1, n2)
                else:
                    half = xr[0].shape[-1]
                    pe = xr[0].reshape(-1, half)
                    po = xr[1].reshape(-1, half)
                s = dft_untwist_interbin(
                    pe, po,
                    jnp.broadcast_to(mean, batch).reshape(-1),
                    jnp.broadcast_to(std, batch).reshape(-1),
                    npad=npad,
                ).reshape(*batch, npad)
            else:
                zr, zi = (
                    packed_dft_z_parts(*xr) if packed else packed_dft_z(xr)
                )
                s = untwist_interbin_normalise(
                    zr, zi,
                    jnp.broadcast_to(mean, batch).reshape(-1),
                    jnp.broadcast_to(std, batch).reshape(-1),
                    npad=npad, block=PEAKS_BLOCK,
                ).reshape(*batch, npad)
        elif _use_matmul(xr.shape[-1]):
            # matmul four-step rfft as lazy (re, im) parts: the untwist
            # fuses into the interbin pass (no complex materialisation)
            s = form_interpolated_parts(*rfft_pow2_matmul_parts(xr))
            s = normalise(s, mean, std)
        else:
            s = form_interpolated(jnp.fft.rfft(xr, axis=-1))
            s = normalise(s, mean, std)
    if mega_harm and pallas_peaks and cluster:
        # harmonic summing FUSED into the peaks walk: one Pallas
        # dispatch gathers, accumulates, scales, thresholds and
        # clusters every level in VMEM (ops/pallas/harmpeaks.py) —
        # no conv val-chain HBM round trips, no level arrays, no
        # layout copies. Bitwise-equal outputs (probe-gated).
        with jax.named_scope("Harmonic summing"):
            from ..ops.pallas.harmpeaks import find_harmonic_cluster_peaks
            from ..ops.pallas.peaks import PEAKS_BLOCK

            npad = -(-nbins // PEAKS_BLOCK) * PEAKS_BLOCK
            if s.shape[-1] != npad:
                s = jnp.pad(
                    s, [(0, 0)] * (s.ndim - 1) + [(0, npad - s.shape[-1])]
                )
            i_, s_, c_, cc_ = find_harmonic_cluster_peaks(
                s, windows, nharms=nharms, threshold=threshold,
                max_peaks=max_peaks, scales=lvl_scales, nbins=nbins,
            )
        nb = s.ndim - 1  # batch rank
        return AccelSearchPeaks(
            idxs=jnp.moveaxis(i_, nb, stack_axis),
            snrs=jnp.moveaxis(s_, nb, stack_axis),
            counts=jnp.moveaxis(c_, nb, stack_axis),
            ccounts=jnp.moveaxis(cc_, nb, stack_axis),
        )

    # the fused kernel applies the per-level rsqrt(2^h) factor in VMEM
    # (one fewer full HBM pass per level); the jnp path scales here.
    # For the kernel path the levels also come back pre-padded to the
    # kernel's block size (block_align) so no per-level pad pass is
    # spent — the pad region is garbage the kernel's windows mask.
    with jax.named_scope("Harmonic summing"):
        if kernel_scales:
            from ..ops.pallas.peaks import PEAKS_BLOCK

            sums = harmonic_sums(
                s, nharms=nharms, scaled=False, block_align=PEAKS_BLOCK
            )
            npad = sums[0].shape[-1]
            if s.shape[-1] != npad:
                s = jnp.pad(
                    s, [(0, 0)] * (s.ndim - 1) + [(0, npad - nbins)]
                )
        else:
            sums = harmonic_sums(s, nharms=nharms, scaled=True)
    levels = [s] + sums

    if pallas_peaks and cluster:
        # ONE kernel dispatch walks every level's threshold+cluster
        # machine together (ops/pallas/peaks.py:find_cluster_peaks_multi)
        from ..ops.pallas.peaks import find_cluster_peaks_multi

        with jax.named_scope("Peaks"):
            i_, s_, c_, cc_ = find_cluster_peaks_multi(
                levels, windows, threshold=threshold, max_peaks=max_peaks,
                scales=lvl_scales, nbins=nbins,
            )
        # kernel emits (..., nlev, ...); the NamedTuple wants the level
        # axis at stack_axis
        nb = len(levels[0].shape) - 1  # batch rank
        return AccelSearchPeaks(
            idxs=jnp.moveaxis(i_, nb, stack_axis),
            snrs=jnp.moveaxis(s_, nb, stack_axis),
            counts=jnp.moveaxis(c_, nb, stack_axis),
            ccounts=jnp.moveaxis(cc_, nb, stack_axis),
        )

    idxs, snrs, counts, ccounts = [], [], [], []
    with jax.named_scope("Peaks"):
        for lvl, spec in enumerate(levels):
            i_, s_, c_ = find_peaks_device(
                spec,
                jnp.float32(threshold),
                windows[lvl, 0],
                windows[lvl, 1],
                max_peaks=max_peaks,
            )
            if cluster:
                i_, s_, cc_ = cluster_peaks_device(
                    i_, s_, jnp.int32(nbins)
                )
            else:
                cc_ = c_
            idxs.append(i_)
            snrs.append(s_)
            counts.append(c_)
            ccounts.append(cc_)
    return AccelSearchPeaks(
        idxs=jnp.stack(idxs, axis=stack_axis),
        snrs=jnp.stack(snrs, axis=stack_axis),
        counts=jnp.stack(counts, axis=stack_axis),
        ccounts=jnp.stack(ccounts, axis=stack_axis),
    )


def search_trial_core(
    tim: jax.Array,  # (>=size,) u8/f32 dedispersed time series
    afs: jax.Array,  # (A,) f32 acceleration factors a*tsamp/2c (padded)
    zapmask: jax.Array,  # (size//2+1,) bool birdie mask
    windows: jax.Array,  # (nharms+1, 2) i32 [start_idx, limit) per level
    *,
    threshold: float,
    size: int,
    nsamps_valid: int,
    nharms: int,
    max_peaks: int,
    pos5: int,
    pos25: int,
    cluster: bool = True,
) -> AccelSearchPeaks:
    """Pure search body for one DM trial; vmap/shard_map-compatible."""
    xd, mean, std = _preprocess_trial(
        tim, zapmask, size=size, nsamps_valid=nsamps_valid,
        pos5=pos5, pos25=pos25,
    )
    xr = resample_accel(xd, afs)  # (A, size)
    return _spectra_and_peaks(
        xr, mean[None], std[None], windows,
        threshold=threshold, nharms=nharms, max_peaks=max_peaks,
        stack_axis=0, cluster=cluster,
    )


@lru_cache(maxsize=None)
def make_search_fn(threshold: float):
    """Build the jitted per-DM-trial program with the S/N threshold
    bound statically (it never changes within a run). Cached so repeat
    runs with the same threshold reuse the compiled executable."""

    @partial(
        jax.jit,
        static_argnames=("size", "nsamps_valid", "nharms", "max_peaks", "pos5",
                         "pos25", "cluster"),
    )
    def search_dm_trial(tim, afs, zapmask, windows, *, size, nsamps_valid,
                        nharms, max_peaks, pos5, pos25,
                        cluster=True) -> AccelSearchPeaks:
        return search_trial_core(
            tim, afs, zapmask, windows,
            threshold=threshold, size=size, nsamps_valid=nsamps_valid,
            nharms=nharms, max_peaks=max_peaks, pos5=pos5, pos25=pos25,
            cluster=cluster,
        )

    return search_dm_trial


def search_block_core(
    tims: jax.Array,  # (D, >=size) u8/f32 dedispersed time series block
    afs: jax.Array,  # (D, A) f32 acceleration factors (padded)
    zapmask: jax.Array,
    windows: jax.Array,
    *,
    threshold: float,
    size: int,
    nsamps_valid: int,
    nharms: int,
    max_peaks: int,
    pos5: int,
    pos25: int,
    pallas_block: int = 0,
    pallas_interpret: bool = False,
    select_smax: int = 0,
    cluster: bool = True,
    pallas_peaks: bool = False,
    fused_interbin: bool = False,
    mega_harm: bool = False,
    fused_dft: bool = False,
    fused_spec: bool = False,
) -> AccelSearchPeaks:
    """Block-batched search: all per-DM preprocessing vmapped, then the
    (D, A) accel grid processed as single batched array programs. With
    ``pallas_block`` > 0 the resampling gather runs as the Pallas
    windowed-select kernel (ops/pallas/resample.py); with
    ``select_smax`` > 0 as the gather-free jnp select
    (ops/resample.py:resample_select); otherwise the jnp gather twin.
    Results are bitwise identical in all three modes. ``fused_spec``
    routes the once-per-trial deredden -> zap -> interbin tail through
    the fused Pallas pass (probe-gated by the caller).
    """
    # named scopes mirror the roofline stage taxonomy
    # (tools/scope_trace STAGE_RULES), so profiler traces attribute
    # this one jitted program's device time per stage
    with jax.named_scope("Spectrum-Chain"):
        if fused_spec:
            xd, mean, std = _preprocess_block_fused(
                tims, zapmask, size=size, nsamps_valid=nsamps_valid,
                pos5=pos5, pos25=pos25,
            )
        else:
            xd, mean, std = jax.vmap(
                lambda tim: _preprocess_trial(
                    tim, zapmask, size=size, nsamps_valid=nsamps_valid,
                    pos5=pos5, pos25=pos25,
                )
            )(tims)  # (D, size), (D,), (D,)

    with jax.named_scope("Resample"):
        if pallas_block > 0:
            from ..ops.pallas.resample import resample_block_pallas

            xr = resample_block_pallas(
                xd, afs, block=pallas_block, interpret=pallas_interpret
            )
        elif select_smax > 0:
            if fused_interbin and cluster and pallas_peaks:
                # the packed-DFT consumer wants even/odd planes:
                # selecting straight into them skips the stride-2
                # deinterleave relayout (bitwise-equal elements,
                # ops/resample.py). The fused-DFT kernel additionally
                # wants them PRE-SHAPED (.., n1, n2) so the select
                # writes its tile layout with no relayout pass
                # (resample_select_packed_planes)
                if fused_dft:
                    from ..ops.pallas.dftspec import plane_factors
                    from ..ops.resample import (
                        resample_select_packed_planes,
                    )

                    n1, n2 = plane_factors(size // 2)
                    xr = resample_select_packed_planes(
                        xd, afs, smax=select_smax, n1=n1, n2=n2
                    )
                else:
                    from ..ops.resample import resample_select_packed

                    xr = resample_select_packed(xd, afs, smax=select_smax)
            else:
                from ..ops.resample import resample_select

                xr = resample_select(xd, afs, smax=select_smax)
        else:
            xr = jax.vmap(resample_accel)(xd, afs)  # (D, A, size)

    # stack levels at axis 1 -> (D, nharms+1, A, ...) to match
    # vmap(search_trial_core)'s layout
    return _spectra_and_peaks(
        xr, mean[:, None], std[:, None], windows,
        threshold=threshold, nharms=nharms, max_peaks=max_peaks,
        stack_axis=1, cluster=cluster, pallas_peaks=pallas_peaks,
        fused_interbin=fused_interbin, mega_harm=mega_harm,
        fused_dft=fused_dft,
    )


@lru_cache(maxsize=None)
def make_batched_search_fn(
    threshold: float, pallas_block: int = 0, select_smax: int = 0,
    pallas_peaks: bool = False, fused_interbin: bool = False,
    mega_harm: bool = False, fused_dft: bool = False,
    fused_spec: bool = False,
):
    """Jitted (D, ...) -> (D, ...) search over a block of DM trials.

    A fixed (dm_block, accel_bucket) tile shape is the unit of device
    work (SURVEY.md §7): one compile covers the whole run, and the
    batching amortises dispatch — the reference instead launches ~10
    kernels per (DM, accel) pair (src/pipeline_multi.cu:209-239).
    """

    @partial(
        jax.jit,
        static_argnames=("size", "nsamps_valid", "nharms", "max_peaks", "pos5",
                         "pos25", "cluster"),
    )
    def search_dm_block(tims, afs, zapmask, windows, *, size, nsamps_valid,
                        nharms, max_peaks, pos5, pos25,
                        cluster=True) -> AccelSearchPeaks:
        return search_block_core(
            tims, afs, zapmask, windows,
            threshold=threshold, size=size, nsamps_valid=nsamps_valid,
            nharms=nharms, max_peaks=max_peaks, pos5=pos5, pos25=pos25,
            pallas_block=pallas_block, select_smax=select_smax,
            cluster=cluster, pallas_peaks=pallas_peaks,
            fused_interbin=fused_interbin, mega_harm=mega_harm,
            fused_dft=fused_dft, fused_spec=fused_spec,
        )

    return search_dm_block
