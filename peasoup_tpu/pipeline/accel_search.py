"""The per-DM-trial acceleration-search device program.

This is the TPU replacement for the reference's hot loop
(Worker::start, src/pipeline_multi.cu:144-243): where the CUDA code
runs one FFT/spectrum/harmonic/peak pass per acceleration trial, here
the WHOLE acceleration batch for a DM trial is one jitted array
program — resampling is a (A, N) gather, the FFT is one batched rfft,
and peak extraction is a masked static-size compaction per harmonic
level. Python never touches per-trial spectra.

Stages (reference line refs in parentheses):
  pad/truncate (pipeline_multi.cu:112-114,160-163) -> rfft (174) ->
  |.| (178) -> running median (182) -> deredden (186) -> zap (188-192)
  -> interbin + stats (196-200) -> irfft (204) -> per-accel: resample
  (212), rfft (216), interbin (220), normalise (224), harmonic sums
  (228), peak extraction (233-234).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.harmonics import harmonic_sums
from ..ops.peaks import find_peaks_device
from ..ops.rednoise import whiten_fseries
from ..ops.resample import resample_accel
from ..ops.spectrum import form_interpolated, normalise, spectrum_stats
from ..ops.zap import zap_birdies


class AccelSearchPeaks(NamedTuple):
    """Static-size peak sets for one DM trial.

    idxs/snrs: (nharms+1, A, max_peaks) — level 0 is the fundamental
    spectrum, level h the 2^h-harmonic sum. counts: (nharms+1, A).
    """

    idxs: jax.Array
    snrs: jax.Array
    counts: jax.Array


def search_trial_core(
    tim: jax.Array,  # (>=size,) u8/f32 dedispersed time series
    afs: jax.Array,  # (A,) f32 acceleration factors a*tsamp/2c (padded)
    zapmask: jax.Array,  # (size//2+1,) bool birdie mask
    windows: jax.Array,  # (nharms+1, 2) i32 [start_idx, limit) per level
    *,
    threshold: float,
    size: int,
    nsamps_valid: int,
    nharms: int,
    max_peaks: int,
    pos5: int,
    pos25: int,
) -> AccelSearchPeaks:
    """Pure search body for one DM trial; vmap/shard_map-compatible."""
    # --- once per DM trial ------------------------------------------------
    x = tim[:size].astype(jnp.float32)
    if nsamps_valid < size:
        # mean-pad the tail like the reference (pipeline_multi.cu:160-163);
        # the input trial may be shorter than size, so pad to shape first
        x = jnp.pad(x, (0, size - x.shape[0]))
        mean_head = jnp.mean(x[:nsamps_valid])
        idx = jnp.arange(size)
        x = jnp.where(idx < nsamps_valid, x, mean_head)
    fser = whiten_fseries(x, pos5=pos5, pos25=pos25)
    fser = zap_birdies(fser, zapmask)
    s0 = form_interpolated(fser)
    mean, _, std = spectrum_stats(s0)
    xd = jnp.fft.irfft(fser, n=size)

    # --- batched over acceleration trials ---------------------------------
    xr = resample_accel(xd, afs)  # (A, size)
    fr = jnp.fft.rfft(xr, axis=-1)  # (A, size//2+1)
    s = form_interpolated(fr)
    s = normalise(s, mean[None], std[None])
    sums = harmonic_sums(s, nharms=nharms)
    levels = [s] + sums

    idxs, snrs, counts = [], [], []
    for lvl, spec in enumerate(levels):
        i_, s_, c_ = find_peaks_device(
            spec,
            jnp.float32(threshold),
            windows[lvl, 0],
            windows[lvl, 1],
            max_peaks=max_peaks,
        )
        idxs.append(i_)
        snrs.append(s_)
        counts.append(c_)
    return AccelSearchPeaks(
        idxs=jnp.stack(idxs), snrs=jnp.stack(snrs), counts=jnp.stack(counts)
    )


@lru_cache(maxsize=None)
def make_search_fn(threshold: float):
    """Build the jitted per-DM-trial program with the S/N threshold
    bound statically (it never changes within a run). Cached so repeat
    runs with the same threshold reuse the compiled executable."""

    @partial(
        jax.jit,
        static_argnames=("size", "nsamps_valid", "nharms", "max_peaks", "pos5",
                         "pos25"),
    )
    def search_dm_trial(tim, afs, zapmask, windows, *, size, nsamps_valid,
                        nharms, max_peaks, pos5, pos25) -> AccelSearchPeaks:
        return search_trial_core(
            tim, afs, zapmask, windows,
            threshold=threshold, size=size, nsamps_valid=nsamps_valid,
            nharms=nharms, max_peaks=max_peaks, pos5=pos5, pos25=pos25,
        )

    return search_dm_trial


@lru_cache(maxsize=None)
def make_batched_search_fn(threshold: float):
    """Jitted (D, ...) -> (D, ...) search over a block of DM trials.

    A fixed (dm_block, accel_bucket) tile shape is the unit of device
    work (SURVEY.md §7): one compile covers the whole run, and the vmap
    amortises dispatch — the reference instead launches ~10 kernels per
    (DM, accel) pair (src/pipeline_multi.cu:209-239).
    """

    @partial(
        jax.jit,
        static_argnames=("size", "nsamps_valid", "nharms", "max_peaks", "pos5",
                         "pos25"),
    )
    def search_dm_block(tims, afs, zapmask, windows, *, size, nsamps_valid,
                        nharms, max_peaks, pos5, pos25) -> AccelSearchPeaks:
        return jax.vmap(
            lambda t, a: search_trial_core(
                t, a, zapmask, windows,
                threshold=threshold, size=size, nsamps_valid=nsamps_valid,
                nharms=nharms, max_peaks=max_peaks, pos5=pos5, pos25=pos25,
            )
        )(tims, afs)

    return search_dm_block
