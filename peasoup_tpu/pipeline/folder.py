"""Fold + optimise the top candidates (the reference's MultiFolder,
include/transforms/folder.hpp:337-442).

Candidates are grouped by DM trial; each needed trial is dereddened
once, then ALL of that trial's candidates are resampled and folded in
one batched device call, and every fold across all groups is optimised
in a single batched FoldOptimiser pass — versus the reference's strictly
sequential per-candidate fold+optimise loop.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.candidates import Candidate
from ..ops.fold import fold_bins_np, fold_time_series
from ..ops.fold_optimise import FoldOptimiser
from ..ops.rednoise import whiten_fseries
from ..ops.resample import accel_factor, resample_accel_quadratic
from ..plan.fft_plan import prev_power_of_two


@partial(jax.jit, static_argnames=("size", "pos5", "pos25"))
def _deredden_tim(tim: jax.Array, *, size: int, pos5: int, pos25: int) -> jax.Array:
    """u8 trial -> dereddened f32 time series, scaled like the
    reference's unnormalised inverse FFT (x size) so fold amplitudes
    match the CUDA output files (folder.hpp:382-389)."""
    fser = whiten_fseries(tim[:size], pos5=pos5, pos25=pos25)
    return jnp.fft.irfft(fser, n=size) * size


def fold_geometry(
    trials_nsamps: int,
    tsamp: float,
    pos5_freq: float = 0.05,
    pos25_freq: float = 0.5,
) -> tuple[int, float, float, int, int]:
    """(size, tsamp_f32, tobs, pos5, pos25) for one observation's fold.

    The reference's quirky constants in one place — the power-of-two
    truncation, the f32 tsamp/tobs roundings, the whitening band edges
    — shared by :class:`MultiFolder` and the survey folder
    (peasoup_tpu/sift/fold.py) so the two paths provably derive the
    same per-candidate geometry (their outputs are pinned bitwise-equal
    in tests/test_sift.py)."""
    size = prev_power_of_two(trials_nsamps)
    tsamp32 = float(np.float32(tsamp))
    tobs = float(np.float32(size) * np.float32(tsamp))
    bin_width = 1.0 / (size * tsamp32)
    return (
        size, tsamp32, tobs,
        int(pos5_freq / bin_width), int(pos25_freq / bin_width),
    )


class MultiFolder:
    min_period = 1e-3
    max_period = 10.0
    fold_bucket = 8  # candidate batches padded to a multiple of this

    def __init__(
        self,
        trials: np.ndarray,  # (ndm, nsamps) u8 dedispersed trials
        trials_nsamps: int,
        tsamp: float,
        nbins: int = 64,
        nints: int = 16,
        pos5_freq: float = 0.05,
        pos25_freq: float = 0.5,
        dm_offset: int = 0,  # global dm_idx of trials[0] (multi-host
        # slices hold only their own trial block; candidates outside
        # [dm_offset, dm_offset + len(trials)) are folded by the owner
        # process and merged via fold outcomes)
    ):
        self.trials = trials
        self.dm_offset = dm_offset
        # the reference folds with the f32 tsamp member
        # (timeseries.hpp:54; double tsamp_by_period = tsamp/period in
        # kernels.cu:641 sees the f32-rounded value) — the fold's
        # phase-bin assignment is sensitive to this at the 1e-8 level,
        # which flips ~0.06% of samples into adjacent bins over a 2^17
        # series; tobs = nsamps*tsamp is a uint*float f32 product
        # (folder.hpp:358). All derived in fold_geometry, shared with
        # the survey folder.
        (
            self.nsamps, self.tsamp, self.tobs, self.pos5, self.pos25
        ) = fold_geometry(trials_nsamps, tsamp, pos5_freq, pos25_freq)
        self.nbins = nbins
        self.nints = nints
        self.optimiser = FoldOptimiser(nbins, nints)

    def fold_n(self, cands: List[Candidate], n: int) -> List[Candidate]:
        outcomes = self.fold_outcomes(cands, n)
        return self.apply_outcomes(cands, outcomes)

    def apply_outcomes(
        self, cands: List[Candidate], outcomes: list[dict]
    ) -> List[Candidate]:
        """Write fold outcomes (possibly gathered from several
        processes) back onto the candidate list and re-sort by
        max(snr, folded_snr) (folder.hpp:25-31,433)."""
        for res in outcomes:
            ci = res["cand_idx"]
            cands[ci].folded_snr = res["opt_sn"]
            cands[ci].opt_period = res["opt_period"]
            cands[ci].fold = res["opt_fold"]
        return sorted(cands, key=lambda c: -max(c.snr, c.folded_snr))

    def fold_outcomes(self, cands: List[Candidate], n: int) -> list[dict]:
        """Fold + optimise the foldable top-``n`` candidates whose DM
        trial lives in this folder's trial block, returning one outcome
        dict per candidate (keyed back by ``cand_idx``) instead of
        mutating the list — the multi-host merge exchanges these."""
        count = min(n, len(cands))
        ndm_local = len(self.trials)
        dm_map: dict[int, list[int]] = {}
        for ii in range(count):
            p = 1.0 / cands[ii].freq
            if not self.min_period < p < self.max_period:
                continue
            local_dm = cands[ii].dm_idx - self.dm_offset
            if 0 <= local_dm < ndm_local:
                dm_map.setdefault(local_dm, []).append(ii)

        # pipelined dispatch: enqueue DM groups' deredden+resample+fold
        # chains ahead of their fetches — on a high-latency link the
        # first D2H absorbs the whole in-flight pipeline and the rest
        # are nearly free, instead of one full round trip per DM group.
        # The window is BOUNDED so peak HBM stays a few groups' worth
        # of intermediates (each ~K_pad x nsamps f32); an unbounded
        # queue could exhaust device memory at survey scale, and the
        # search driver's OOM shrink-retry does not cover the folder.
        max_inflight = 4
        pending = []
        all_folds, all_periods, all_cand_idx = [], [], []

        def drain_one():
            folds, k, periods, cand_ids = pending.pop(0)
            all_folds.append(np.asarray(folds)[:k])
            all_periods.extend(periods[:k])
            all_cand_idx.extend(cand_ids)

        for dm_idx, cand_ids in dm_map.items():
            xd = _deredden_tim(
                jnp.asarray(self.trials[dm_idx]),
                size=self.nsamps,
                pos5=self.pos5,
                pos25=self.pos25,
            )
            # pad the candidate batch to a fixed width so every DM group
            # reuses one compiled (K_pad, N) resample+fold program
            k = len(cand_ids)
            k_pad = int(np.ceil(k / self.fold_bucket) * self.fold_bucket)
            ids_pad = cand_ids + [cand_ids[0]] * (k_pad - k)
            # batched resample (the folder uses the quadratic v1 kernel,
            # folder.hpp:396 -> kernels.cu:308-332)
            # (a*tsamp) is an f32 product in the reference's launcher
            # (float a, float tsamp, kernels.cu:367) — accel_factor
            # replays it
            afs = accel_factor(
                np.asarray([cands[ci].acc for ci in ids_pad]), self.tsamp
            ).astype(np.float32)
            xr = jax.vmap(lambda af: resample_accel_quadratic(xd, af))(
                jnp.asarray(afs)
            )  # (K_pad, N)
            periods = np.array(
                [1.0 / cands[ci].freq for ci in ids_pad], dtype=np.float64
            )
            used = self.nints * (self.nsamps // self.nints)
            flat_bins = np.stack(
                [
                    fold_bins_np(self.nsamps, self.tsamp, p, self.nbins, self.nints)
                    for p in periods
                ]
            )
            folds = fold_time_series(
                xr[:, :used],
                jnp.asarray(flat_bins),
                nbins=self.nbins,
                nints=self.nints,
            )
            pending.append((folds, k, periods, cand_ids))
            if len(pending) >= max_inflight:
                drain_one()
        while pending:
            drain_one()

        if not all_cand_idx:
            return []
        folds = np.concatenate(all_folds, axis=0)
        k = folds.shape[0]
        k_pad = int(np.ceil(k / self.fold_bucket) * self.fold_bucket)
        if k_pad > k:  # fixed batch width -> one compiled optimiser
            reps = int(np.ceil(k_pad / k))
            folds = np.concatenate([folds] * reps, axis=0)[:k_pad]
            all_periods = (list(all_periods) * reps)[:k_pad]
        results = self.optimiser.optimise(
            folds, np.asarray(all_periods), self.tobs
        )[:k]
        return [
            {
                "cand_idx": ci,
                "opt_sn": res["opt_sn"],
                "opt_period": res["opt_period"],
                "opt_fold": res["opt_fold"],
            }
            for ci, res in zip(all_cand_idx, results)
        ]
