"""Candidate scoring heuristics (reference: include/transforms/scorer.hpp).

Adds is_physical (period above the per-channel DM smear), is_adjacent
(assoc spans neighbouring DM trials), and the fraction of associated
hits (count- and S/N-weighted) inside the expected DM width of the
fundamental.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.candidates import Candidate


class CandidateScorer:
    def __init__(self, tsamp: float, cfreq: float, foff: float, bw: float):
        ftop = cfreq + bw / 2.0
        fbottom = cfreq - bw / 2.0
        self.tdm_chan_partial = 8300.0 * foff / cfreq**3
        self.tdm_band_partial = 4150.0 * (1.0 / fbottom**2 - 1.0 / ftop**2)

    def score(self, cand: Candidate) -> None:
        cand.is_physical = bool(
            1.0 / cand.freq > cand.dm * self.tdm_chan_partial
        )
        # adjacency: any assoc at dm_idx +/- 1, or all at the same dm_idx
        idx = cand.dm_idx
        adjacent = False
        unique = True
        for a in cand.assoc:
            if a.dm_idx != idx:
                unique = False
            if a.dm_idx in (idx + 1, idx - 1):
                adjacent = True
                break
        cand.is_adjacent = bool(adjacent or unique)
        # delta-DM ratios (scorer.hpp:47-65)
        ddm = 1.0 / (cand.freq * self.tdm_band_partial)
        inside_count = total_count = 1
        inside_snr = total_snr = cand.snr
        for a in cand.assoc:
            total_count += 1
            total_snr += a.snr
            if abs(cand.dm - a.dm) <= ddm:
                inside_count += 1
                inside_snr += a.snr
        cand.ddm_count_ratio = inside_count / total_count
        cand.ddm_snr_ratio = inside_snr / total_snr

    def score_all(self, cands: List[Candidate]) -> None:
        for c in cands:
            self.score(c)
