"""Checkpoint/resume of per-DM-trial search results.

The reference has NO checkpointing — a crash mid-sweep loses everything
(SURVEY.md §5: errors are thrown and crash the process,
include/utils/exceptions.hpp). This module is the TPU framework's
addition: after each device block the driver persists the static-size
peak sets already searched, keyed by DM-trial index, so a long sweep
resumes where it stopped. The checkpoint is invalidated by a config
key derived from every search-affecting parameter.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np


class SearchCheckpoint:
    """Atomic .npz store of {dm_idx: (idxs, snrs, counts)}."""

    def __init__(self, path: str, config_key: str) -> None:
        self.path = path
        self.config_key = config_key

    @staticmethod
    def make_key(cfg, fil, size: int, ndm: int) -> str:
        """Config key over everything that changes per-trial results,
        including the observation's identity (header), so a checkpoint
        from one beam/file never resumes a search of another."""
        h = fil.header
        fields = (
            "v3-ragged",  # per-trial payload format version
            fil.nsamps, fil.nchans, size, ndm,
            fil.tsamp, fil.fch1, fil.foff,
            getattr(h, "tstart", None), getattr(h, "source_name", None),
            getattr(h, "nbits", None),
            cfg.dm_start, cfg.dm_end, cfg.dm_tol, cfg.dm_pulse_width,
            cfg.acc_start, cfg.acc_end, cfg.acc_tol, cfg.acc_pulse_width,
            cfg.boundary_5_freq, cfg.boundary_25_freq, cfg.nharmonics,
            cfg.min_snr, cfg.min_freq, cfg.max_freq,
            cfg.killfilename, cfg.zapfilename,
        )
        return repr(fields)

    def load(self) -> dict[int, tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Restore completed trials; {} if absent or config changed."""
        if not self.path or not os.path.exists(self.path):
            return {}
        try:
            with np.load(self.path, allow_pickle=False) as z:
                if str(z["config_key"]) != self.config_key:
                    return {}
                dm_idxs = z["dm_idxs"]
                return {
                    int(d): (z[f"idxs_{d}"], z[f"snrs_{d}"], z[f"counts_{d}"])
                    for d in dm_idxs
                }
        except (OSError, KeyError, ValueError):
            return {}  # corrupt/partial file: start over, never crash

    def save(
        self, results: dict[int, tuple[np.ndarray, np.ndarray, np.ndarray]]
    ) -> None:
        """Write-all + atomic rename (safe against mid-write crashes)."""
        if not self.path:
            return
        arrays: dict[str, np.ndarray] = {
            "config_key": np.asarray(self.config_key),
            "dm_idxs": np.asarray(sorted(results), dtype=np.int64),
        }
        for d, (idxs, snrs, counts) in results.items():
            arrays[f"idxs_{d}"] = idxs
            arrays[f"snrs_{d}"] = snrs
            arrays[f"counts_{d}"] = counts
        dirname = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(dirname, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".ckpt.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
