"""Checkpoint/resume of per-DM-trial search results.

The reference has NO checkpointing — a crash mid-sweep loses everything
(SURVEY.md §5: errors are thrown and crash the process,
include/utils/exceptions.hpp). This module is the TPU framework's
addition: after each device wave the driver persists the per-trial peak
sets already searched, keyed by GLOBAL DM-trial index, so a long sweep
resumes where it stopped. The checkpoint is invalidated by a config key
derived from every search-affecting parameter.

Multi-host layout: every process writes its own store file (base path +
a ``.dmLO-HI`` slice suffix — no write contention on shared
filesystems), but entries are GLOBAL-dm_idx-keyed and ``load()`` unions
ALL store files sharing the base path. Resuming with a DIFFERENT
process count therefore reuses every completed trial: each process
simply filters the union to its own slice.
"""

from __future__ import annotations

import glob
import os
import tempfile

import numpy as np

from ..obs import get_logger
from ..resilience import IO_RETRY, faults, load_or_recover

log = get_logger("pipeline.checkpoint")


class SearchCheckpoint:
    """Atomic .npz store(s) of {global dm_idx: (idxs, snrs, counts)}.

    ``base_path`` identifies the search; ``slice_bounds=(lo, hi)`` (the
    process's global DM slice) routes writes to a per-slice file and
    filters loads to [lo, hi). Entries are stored and returned with
    LOCAL keys (global - lo) so the driver's slice-local bookkeeping
    is unchanged.
    """

    def __init__(
        self,
        base_path: str,
        config_key: str,
        slice_bounds: tuple[int, int] | None = None,
    ) -> None:
        self.base_path = base_path
        self.config_key = config_key
        self.lo, self.hi = slice_bounds if slice_bounds else (0, None)
        self.write_path = (
            f"{base_path}.dm{self.lo}-{self.hi}" if slice_bounds else base_path
        )

    @staticmethod
    def make_key(cfg, fil, size: int, global_ndm: int) -> str:
        """Config key over everything that changes per-trial results,
        including the observation's identity (header), so a checkpoint
        from one beam/file never resumes a search of another.
        ``global_ndm`` must be the FULL trial-list length (not a
        process slice's) so stores written under any process count
        share one key."""
        h = fil.header
        fields = (
            "v4-global-dm",  # per-trial payload format version
            fil.nsamps, fil.nchans, size, global_ndm,
            fil.tsamp, fil.fch1, fil.foff,
            getattr(h, "tstart", None), getattr(h, "source_name", None),
            getattr(h, "nbits", None),
            cfg.dm_start, cfg.dm_end, cfg.dm_tol, cfg.dm_pulse_width,
            cfg.acc_start, cfg.acc_end, cfg.acc_tol, cfg.acc_pulse_width,
            cfg.boundary_5_freq, cfg.boundary_25_freq, cfg.nharmonics,
            cfg.min_snr, cfg.min_freq, cfg.max_freq,
            cfg.killfilename, cfg.zapfilename,
        )
        return repr(fields)

    def _store_files(self) -> list[str]:
        """The base file plus every per-slice sibling, existing ones —
        excluding quarantined ``*.corrupt`` siblings."""
        paths = []
        if os.path.exists(self.base_path):
            paths.append(self.base_path)
        paths.extend(
            p
            for p in sorted(
                glob.glob(glob.escape(self.base_path) + ".dm*")
            )
            if not p.endswith(".corrupt")
        )
        return paths

    def _load_store(self, path: str) -> dict[int, tuple]:
        """One store file's slice-filtered entries; raises on damage."""
        out: dict[int, tuple] = {}
        with np.load(path, allow_pickle=False) as z:
            if str(z["config_key"]) != self.config_key:
                return out
            for d in z["dm_idxs"]:
                g = int(d)
                if g < self.lo or (self.hi is not None and g >= self.hi):
                    continue
                out[g - self.lo] = (
                    z[f"idxs_{g}"], z[f"snrs_{g}"], z[f"counts_{g}"]
                )
        return out

    def load(self) -> dict[int, tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Union of all store files, filtered to this process's slice,
        returned with LOCAL keys; {} if absent or config changed.

        A truncated/corrupt store (worker SIGKILLed mid-write, torn
        copy, bad disk) must never fail the run — resume loses nothing
        but the restart time, and campaign retries (campaign/runner.py)
        depend on a damaged checkpoint degrading to "start over", not
        crashing the job again. The unified policy
        (resilience.load_or_recover) warns and quarantines the damaged
        file to ``*.corrupt`` so the torn bytes survive for forensics
        and the next save starts clean."""
        if not self.base_path:
            return {}
        out: dict[int, tuple] = {}
        for path in self._store_files():
            faults.maybe_corrupt_file(path, context=f"checkpoint:{path}")
            part = load_or_recover(
                path, self._load_store, default=None, kind="checkpoint",
                action="restarting those trials", logger=log,
            )
            if part:
                out.update(part)
        return out

    def save(
        self, results: dict[int, tuple[np.ndarray, np.ndarray, np.ndarray]]
    ) -> None:
        """Write-all + atomic rename (safe against mid-write crashes).
        ``results`` carries the driver's LOCAL keys; entries are stored
        under their GLOBAL index."""
        if not self.base_path:
            return
        arrays: dict[str, np.ndarray] = {
            "config_key": np.asarray(self.config_key),
            "dm_idxs": np.asarray(
                sorted(k + self.lo for k in results), dtype=np.int64
            ),
        }
        for d, (idxs, snrs, counts) in results.items():
            g = d + self.lo
            arrays[f"idxs_{g}"] = idxs
            arrays[f"snrs_{g}"] = snrs
            arrays[f"counts_{g}"] = counts
        dirname = os.path.dirname(os.path.abspath(self.write_path)) or "."
        os.makedirs(dirname, exist_ok=True)

        def _write_once():
            faults.fire(
                "checkpoint.write", context=self.write_path
            )
            fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".ckpt.tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez(f, **arrays)
                    # durability, not just atomicity: a preempted job's
                    # bitwise-equal resume rides this file, so it must
                    # survive a HOST crash — flush the data blocks
                    # before the rename publishes the name (PSP103)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.write_path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise

        # a checkpoint write hitting a transient error (EIO, ENOSPC
        # burp, injected checkpoint.write fault) retries; persistent
        # failure raises — the campaign attempt budget owns it
        IO_RETRY.call(
            _write_once, site="checkpoint.write", context=self.write_path
        )
