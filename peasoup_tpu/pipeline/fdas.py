"""Host-side FDAS driver: the Fourier-domain acceleration/jerk search
as a campaign-dispatchable pipeline.

Mirrors PeasoupSearch's shape — a config dataclass the runner's
``_build_config`` validates loudly, ``build_dm_plan`` for the warmup
ctx derivation, ``run(fil, dm_slice=..., finalize=...)`` for the
multi-host split (parallel/multihost.py:run_fdas_search), per-DM-block
checkpointing, stage/progress telemetry — but the device inner loop is
the FDAS correlation program (ops/fdas.py): ONE dereddened spectrum
per DM trial, correlated against the (f-dot, f-ddot) template bank
(fdas/templates.py) in fixed (dm_block, template_block) tiles, so one
compile covers the whole run.

OOM degradation: template rows are independent, so halving the
template batch is bitwise-neutral — that is the FIRST ladder rung;
halving the DM block (vmap rows, equally independent) is the second.
Both shrink paths reproduce the untroubled run's candidates exactly
(tests/test_fdas.py pins the bitwise invariance).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..core import Candidate, CandidateCollection, FdasCandidate
from ..fdas.templates import (
    SPEED_OF_LIGHT,
    auto_segment,
    build_template_bank,
)
from ..io.masks import read_killfile, read_zapfile
from ..io.sigproc import Filterbank
from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..ops.dedisperse import dedisperse, fil_to_device, output_scale
from ..ops.fdas import make_fdas_search_fn
from ..ops.zap import birdie_mask
from ..plan.dm_plan import DMPlan
from ..plan.fft_plan import choose_fft_size
from ..utils import ProgressBar
from .checkpoint import SearchCheckpoint
from .distill import AccelerationDistiller, DMDistiller, HarmonicDistiller
from .score import CandidateScorer
from .search import _freq_factor, _is_oom, _level_windows

log = get_logger("pipeline.fdas")


@dataclass
class FdasConfig:
    """FDAS search knobs. DM-plan/spectrum knobs mirror SearchConfig;
    zmax/wmax replace the time-domain acc_start/acc_end pair: they
    bound the f-dot (f-ddot) trial grid in DFT BINS over the
    observation (the PRESTO -z/-w convention), so the same knob value
    means the same physical coverage at any observation length."""

    outdir: str = "."
    killfilename: str = ""
    zapfilename: str = ""
    limit: int = 1000
    size: int = 0  # fft size; 0 = prev power of two
    dm_start: float = 0.0
    dm_end: float = 100.0
    dm_tol: float = 1.10
    dm_pulse_width: float = 64.0
    zmax: float = 64.0  # f-dot extent in bins (0 = pure periodicity)
    zstep: float = 2.0  # f-dot grid spacing in bins
    wmax: float = 0.0  # f-ddot (jerk) extent in bins; 0 = plane off
    wstep: float = 20.0  # f-ddot grid spacing in bins
    boundary_5_freq: float = 0.05
    boundary_25_freq: float = 0.5
    nharmonics: int = 4
    min_snr: float = 9.0
    min_freq: float = 0.1
    max_freq: float = 1100.0
    max_harm: int = 16
    freq_tol: float = 1e-4
    verbose: bool = False
    progress_bar: bool = False
    max_peaks: int = 128  # static peak-compaction size per spectrum
    segment: int = 0  # overlap-save FFT length; 0 = auto from width
    template_block: int = 0  # template rows per dispatch; 0 = auto
    dm_block: int = 0  # DM trials per dispatch; 0 = auto from budget
    checkpoint_file: str = ""  # resumable per-DM-trial result store


@dataclass
class FdasResult:
    candidates: list
    dm_list: np.ndarray
    zs: np.ndarray  # the f-dot trial grid (bins)
    ws: np.ndarray  # the f-ddot trial grid (bins)
    timers: dict
    nsamps: int
    size: int
    n_templates: int = 0
    n_trials: int = 0  # DM x template trials searched


@dataclass
class PartialFdasResult:
    """A run stopped after the per-DM distills (run(finalize=False)):
    everything :meth:`FdasSearch.finalize` needs, per process slice."""

    cands: list  # per-DM-trial candidates, dm_idx GLOBAL
    dm_offset: int
    dm_list: np.ndarray  # slice list per-process; GLOBAL once merged
    zs: np.ndarray
    ws: np.ndarray
    timers: dict
    nsamps: int
    size: int
    n_templates: int
    n_trials: int
    t_total_start: float


def _fdas_config_key(cfg: FdasConfig, fil, size: int, global_ndm: int) -> str:
    """Checkpoint config key over everything that changes per-trial
    FDAS results (SearchCheckpoint.make_key is SearchConfig-specific,
    so the FDAS driver supplies its own)."""
    h = fil.header
    fields = (
        "fdas-v1-global-dm",
        fil.nsamps, fil.nchans, size, global_ndm,
        fil.tsamp, fil.fch1, fil.foff,
        getattr(h, "tstart", None), getattr(h, "source_name", None),
        getattr(h, "nbits", None),
        cfg.dm_start, cfg.dm_end, cfg.dm_tol, cfg.dm_pulse_width,
        cfg.zmax, cfg.zstep, cfg.wmax, cfg.wstep,
        cfg.boundary_5_freq, cfg.boundary_25_freq, cfg.nharmonics,
        cfg.min_snr, cfg.min_freq, cfg.max_freq, cfg.max_peaks,
        cfg.killfilename, cfg.zapfilename,
    )
    return repr(fields)


class FdasSearch:
    """Dedisperse the DM plan, then correlation-search every trial."""

    # HBM accounting for auto (dm_block, template_block) sizing — the
    # same fallback budget split as PeasoupSearch
    TOTAL_HBM = 12_000_000_000
    MEM_BUDGET = 6_000_000_000

    def __init__(self, config: FdasConfig):
        self.config = config

    def build_dm_plan(self, fil: Filterbank) -> DMPlan:
        cfg = self.config
        killmask = None
        if cfg.killfilename:
            killmask = read_killfile(cfg.killfilename, fil.nchans)
        return DMPlan.create(
            nsamps=fil.nsamps,
            nchans=fil.nchans,
            tsamp=fil.tsamp,
            fch1=fil.fch1,
            foff=fil.foff,
            dm_start=cfg.dm_start,
            dm_end=cfg.dm_end,
            pulse_width=cfg.dm_pulse_width,
            tol=cfg.dm_tol,
            killmask=killmask,
        )

    # --- block geometry ---------------------------------------------

    def _auto_blocks(self, nbins: int, ntemplates: int) -> tuple[int, int]:
        """(dm_block, template_block) from the working-set budget: the
        correlation intermediates cost ~nbins complex values per
        (dm, template) cell across the overlap-save stages, plus the
        f32 spectrum levels."""
        cfg = self.config
        cell_bytes = nbins * 64
        cells = max(8, self.MEM_BUDGET // cell_bytes)
        tb = cfg.template_block or min(ntemplates, 64)
        db = cfg.dm_block or max(1, min(32, cells // max(1, tb)))
        return db, tb

    # --- the search -------------------------------------------------

    def run(
        self,
        fil: Filterbank,
        dm_slice: tuple[int, int] | None = None,
        finalize: bool = True,
    ) -> "FdasResult | PartialFdasResult":
        cfg = self.config
        tel = current_telemetry()
        timers: dict[str, float] = {}
        t_total = time.perf_counter()

        t0 = time.perf_counter()
        tel.set_stage("plan")
        dm_plan = self.build_dm_plan(fil)
        global_ndm = dm_plan.ndm
        dm_lo = 0
        if dm_slice is not None:
            dm_lo, dm_hi = dm_slice
            dm_plan = dm_plan.subset(dm_lo, dm_hi)
        size = choose_fft_size(fil.nsamps, cfg.size)
        bank = build_template_bank(
            cfg.zmax, cfg.wmax, cfg.zstep, cfg.wstep
        )
        segment = cfg.segment or auto_segment(bank.width)
        timers["plan"] = time.perf_counter() - t0
        if dm_plan.ndm == 0:
            # empty multi-host slice: contribute zero candidates
            part = PartialFdasResult(
                cands=[], dm_offset=dm_lo, dm_list=dm_plan.dm_list,
                zs=bank.zs, ws=bank.ws,
                timers=dict.fromkeys(
                    ("dedispersion", "search_device", "search_host",
                     "searching"), 0.0
                ),
                nsamps=fil.nsamps, size=size,
                n_templates=bank.ntemplates, n_trials=0,
                t_total_start=t_total,
            )
            return part if not finalize else self.finalize(fil, part)
        tel.gauge("fdas.n_dm_trials", int(dm_plan.ndm))
        tel.gauge("fdas.n_templates", int(bank.ntemplates))
        tel.gauge("fdas.fft_size", int(size))
        tel.event(
            "fdas_plan", ndm=int(dm_plan.ndm),
            n_templates=int(bank.ntemplates), width=int(bank.width),
            segment=int(segment), zmax=float(cfg.zmax),
            wmax=float(cfg.wmax), fft_size=int(size),
        )

        # --- dedispersion (host-resident trials: the FDAS chain keeps
        # HBM for the correlation working set; blocks upload per wave)
        t0 = time.perf_counter()
        tel.set_stage("dedispersion")
        trials = dedisperse(
            fil_to_device(fil),
            dm_plan.delay_samples(),
            dm_plan.killmask,
            dm_plan.out_nsamps,
            scale=output_scale(fil.nbits, int(dm_plan.killmask.sum())),
        )
        trials = np.asarray(trials)
        timers["dedispersion"] = time.perf_counter() - t0
        tel.capture_device_memory("dedispersion")

        # --- search setup -------------------------------------------
        nsamps_valid = min(dm_plan.out_nsamps, size)
        tobs = float(np.float32(size) * np.float32(fil.tsamp))
        bin_width = float(np.float32(1.0 / tobs))
        size_spec = size // 2 + 1
        if cfg.zapfilename:
            bf, bw_ = read_zapfile(cfg.zapfilename)
            zapmask = birdie_mask(bf, bw_, bin_width, size_spec)
        else:
            zapmask = np.zeros(size_spec, dtype=bool)
        windows = _level_windows(
            size, cfg.nharmonics, cfg.min_freq, cfg.max_freq, fil.tsamp
        )
        factors = [
            _freq_factor(size, nh, fil.tsamp)
            for nh in range(cfg.nharmonics + 1)
        ]
        pos5 = int(cfg.boundary_5_freq / bin_width)
        pos25 = int(cfg.boundary_25_freq / bin_width)

        ckpt = SearchCheckpoint(
            cfg.checkpoint_file,
            _fdas_config_key(cfg, fil, size, global_ndm),
            slice_bounds=dm_slice,
        )
        per_dm_results: dict[int, tuple] = ckpt.load()
        if per_dm_results:
            log.info(
                "Resuming: %d/%d DM trials restored from %s",
                len(per_dm_results), dm_plan.ndm, cfg.checkpoint_file,
            )
            tel.event(
                "checkpoint_resume", restored=len(per_dm_results),
                ndm=int(dm_plan.ndm),
            )

        t0 = time.perf_counter()
        tel.set_stage("searching")
        progress = ProgressBar() if cfg.progress_bar else None
        if progress:
            progress.start()
        try:
            self._run_blocks(
                trials, bank, zapmask, windows, per_dm_results, ckpt,
                progress, size=size, nsamps_valid=nsamps_valid,
                segment=segment, pos5=pos5, pos25=pos25,
            )
        finally:
            if progress:
                progress.stop()
        timers["search_device"] = time.perf_counter() - t0
        tel.capture_device_memory("search")

        # --- host candidate bookkeeping -----------------------------
        t_host = time.perf_counter()
        tel.set_stage("search_host")
        harm_finder = HarmonicDistiller(
            cfg.freq_tol, cfg.max_harm, keep_related=False
        )
        tmpl_still = AccelerationDistiller(
            tobs, cfg.freq_tol, keep_related=True
        )
        dm_trial_cands = CandidateCollection()
        zs, ws = bank.zs, bank.ws
        for dm_idx, dm in enumerate(dm_plan.dm_list):
            idxs, snrs, ccounts = per_dm_results.pop(dm_idx)
            tmpl_trial_cands = CandidateCollection()
            for t in range(bank.ntemplates):
                z, w = float(zs[t]), float(ws[t])
                trial_cands: list[Candidate] = []
                for lvl in range(cfg.nharmonics + 1):
                    n_found = int(ccounts[lvl, t])
                    for b, s in zip(
                        idxs[lvl, t, :n_found], snrs[lvl, t, :n_found]
                    ):
                        trial_cands.append(
                            self._candidate(
                                float(dm), dm_idx + dm_lo, z, w,
                                int(lvl), float(s), int(b),
                                factors, tobs,
                            )
                        )
                tmpl_trial_cands.append(harm_finder.distill(trial_cands))
            dm_trial_cands.append(
                tmpl_still.distill(tmpl_trial_cands.cands)
            )
        timers["search_host"] = time.perf_counter() - t_host
        timers["searching"] = time.perf_counter() - t0
        tel.gauge("candidates.per_dm_distill", len(dm_trial_cands))

        part = PartialFdasResult(
            cands=dm_trial_cands.cands,
            dm_offset=dm_lo,
            dm_list=dm_plan.dm_list,
            zs=zs, ws=ws,
            timers=timers,
            nsamps=fil.nsamps,
            size=size,
            n_templates=bank.ntemplates,
            n_trials=dm_plan.ndm * bank.ntemplates,
            t_total_start=t_total,
        )
        if not finalize:
            return part
        return self.finalize(fil, part)

    def _candidate(
        self, dm, dm_idx, z, w, lvl, snr, bin_idx, factors, tobs
    ) -> FdasCandidate:
        """One detection -> candidate. The detection bin is the
        START-of-observation frequency of the matched drifting tone
        (the correlation peak sits where the template's own response
        aligns); the REPORTED frequency is the mean over the
        observation, f = (bin + z/2 + w/6) * factor — the quantity the
        time-domain resampling search recovers, since its pinned-ends
        resampling preserves total cycle count. At z = w = 0 the
        correction vanishes and the stored f32 freq is bit-identical
        to the plain search's f32(bin * factor)."""
        factor = float(factors[lvl])
        freq = float(np.float32(np.float32(bin_idx) * factors[lvl]))
        corr = (z / 2.0 + w / 6.0) * factor
        if corr:
            freq = float(np.float32(freq + corr))
        # the template grid is indexed in drift bins at the DETECTED
        # level; the fundamental's f-dot scales by the same per-level
        # factor as the frequency
        fdot = z * factor / tobs
        fddot = w * factor / (tobs * tobs)
        acc = -fdot * SPEED_OF_LIGHT / freq if freq > 0 and fdot else 0.0
        return FdasCandidate(
            dm=dm, dm_idx=dm_idx, acc=acc, nh=lvl, snr=snr, freq=freq,
            fdot=fdot, fddot=fddot, z=z, w=w,
        )

    def _run_blocks(
        self, trials, bank, zapmask, windows, per_dm_results, ckpt,
        progress, *, size, nsamps_valid, segment, pos5, pos25,
    ) -> None:
        """Fixed (dm_block, template_block) tiles with the two-rung OOM
        ladder. Every dispatch is the SAME tile shape (short blocks are
        padded by repeating rows — template rows and DM rows are both
        independent, so padding never perturbs the kept results and the
        steady state compiles exactly one program)."""
        import jax
        import jax.numpy as jnp

        from ..resilience import DegradationLadder, faults

        cfg = self.config
        tel = current_telemetry()
        ndm = trials.shape[0]
        nbins = size // 2 + 1
        ntemplates = bank.ntemplates
        db, tb = self._auto_blocks(nbins, ntemplates)
        tb = min(tb, ntemplates)
        db = min(db, ndm)
        search_fn = make_fdas_search_fn(float(cfg.min_snr))
        zap_dev = jnp.asarray(zapmask)
        win_dev = jnp.asarray(windows)
        tim_len = min(size, trials.shape[1])
        ladder = DegradationLadder(
            "fdas.memory", ("template_block_shrink", "dm_block_shrink")
        )
        while True:
            # template batches: pad the bank to a tb multiple with
            # copies of the last row; padded rows are sliced off below
            n_tb = -(-ntemplates // tb)
            tmpl_pad = np.concatenate(
                [bank.templates,
                 np.repeat(bank.templates[-1:], n_tb * tb - ntemplates, 0)]
            )
            tmpl_dev = [
                jnp.asarray(tmpl_pad[i * tb:(i + 1) * tb])
                for i in range(n_tb)
            ]
            todo = [d for d in range(ndm) if d not in per_dm_results]
            blocks = [todo[s:s + db] for s in range(0, len(todo), db)]
            tel.event(
                "fdas_wave_plan", n_blocks=len(blocks), dm_block=db,
                template_block=tb, n_template_batches=n_tb,
            )
            tel.set_progress(ndm - len(todo), ndm, unit="dm trials")
            try:
                faults.fire(
                    "device.oom", context=f"fdas:db{db}.tb{tb}"
                )
                for dm_indices in blocks:
                    # pad short DM blocks by repeating the last trial:
                    # one (db, tb) program shape for the whole run
                    rows = dm_indices + [dm_indices[-1]] * (
                        db - len(dm_indices)
                    )
                    tims = jnp.asarray(trials[rows][:, :tim_len])
                    parts = [
                        search_fn(
                            tims, t_dev, zap_dev, win_dev,
                            size=size, nsamps_valid=nsamps_valid,
                            segment=segment, nharms=cfg.nharmonics,
                            max_peaks=cfg.max_peaks, pos5=pos5,
                            pos25=pos25,
                        )
                        for t_dev in tmpl_dev
                    ]
                    # one packed D2H per block: concat along the
                    # template axis, trim bank padding
                    idxs = np.concatenate(
                        [np.asarray(p.idxs) for p in parts], axis=2
                    )[:, :, :ntemplates]
                    snrs = np.concatenate(
                        [np.asarray(p.snrs) for p in parts], axis=2
                    )[:, :, :ntemplates]
                    ccounts = np.concatenate(
                        [np.asarray(p.ccounts) for p in parts], axis=2
                    )[:, :, :ntemplates]
                    for k, d in enumerate(dm_indices):
                        per_dm_results[d] = (
                            idxs[k].astype(np.int32),
                            snrs[k].astype(np.float32),
                            ccounts[k].astype(np.int32),
                        )
                    ckpt.save(per_dm_results)
                    done = ndm - sum(
                        1 for d in range(ndm) if d not in per_dm_results
                    )
                    tel.set_progress(done, ndm, unit="dm trials")
                    if progress:
                        progress.update(done / ndm)
                return
            except Exception as exc:
                if not _is_oom(exc):
                    raise
                if tb > 1:
                    tb = max(1, tb // 2)
                    log.warning(
                        "device OOM; halving the template batch to %d "
                        "(bitwise-neutral: template rows are "
                        "independent): %.200s", tb, exc,
                    )
                    tel.event(
                        "fdas_oom_template_shrink", template_block=tb,
                        error=f"{exc!s:.200}",
                    )
                    if ladder.current_rung in (
                        None, "template_block_shrink"
                    ):
                        ladder.step(
                            "template_block_shrink", template_block=tb,
                            error=f"{exc!s:.200}",
                        )
                    continue
                if db > 1:
                    db = max(1, db // 2)
                    log.warning(
                        "device OOM at template_block=1; halving the "
                        "DM block to %d: %.200s", db, exc,
                    )
                    tel.event(
                        "fdas_oom_dm_shrink", dm_block=db,
                        error=f"{exc!s:.200}",
                    )
                    ladder.step(
                        "dm_block_shrink", dm_block=db,
                        error=f"{exc!s:.200}",
                    )
                    continue
                ladder.exhausted(
                    dm_block=db, template_block=tb, error=f"{exc!s:.200}"
                )
                raise

    def finalize(
        self, fil: Filterbank, part: "PartialFdasResult"
    ) -> FdasResult:
        """Global distilling/scoring over (possibly merged) per-DM
        candidates — identical on every multi-host process."""
        cfg = self.config
        tel = current_telemetry()
        timers = part.timers
        t0 = time.perf_counter()
        tel.set_stage("distilling")
        dm_still = DMDistiller(cfg.freq_tol, keep_related=True)
        harm_still = HarmonicDistiller(
            cfg.freq_tol, cfg.max_harm, keep_related=True,
            fractional_harms=False,
        )
        tel.gauge("candidates.per_dm_total", len(part.cands))
        cands = dm_still.distill(part.cands)
        cands = harm_still.distill(cands)
        tel.gauge("candidates.post_harmonic_distill", len(cands))
        timers["distilling"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        tel.set_stage("scoring")
        scorer = CandidateScorer(
            fil.tsamp, fil.cfreq, fil.foff, abs(fil.foff) * fil.nchans
        )
        scorer.score_all(cands)
        timers["scoring"] = time.perf_counter() - t0

        cands = cands[: cfg.limit]
        tel.gauge("candidates.final", len(cands))
        timers["total"] = time.perf_counter() - part.t_total_start
        log.info(
            "FDAS search: %d DM x %d template trials -> %d candidates",
            len(part.dm_list), part.n_templates, len(cands),
        )
        return FdasResult(
            candidates=cands,
            dm_list=part.dm_list,
            zs=part.zs, ws=part.ws,
            timers=timers,
            nsamps=part.nsamps,
            size=part.size,
            n_templates=part.n_templates,
            n_trials=part.n_trials,
        )
