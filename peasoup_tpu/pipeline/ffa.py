"""Host-side FFA search driver: the folding-algorithm workload as a
campaign-dispatchable pipeline.

The FFA search itself lives in ops/ffa.py (the staircase transform and
the octave walk); until now its only front end was the ``peasoup-ffa``
CLI, which meant the campaign layer could not run FFA jobs through the
bucket/warmup/telemetry machinery the other two pipelines share. This
driver mirrors the SinglePulseSearch/PeasoupSearch shape — a config
dataclass the runner's ``_build_config`` validates loudly, a
``build_dm_plan`` the warmup ctx derivation can call, stage/progress
telemetry for the heartbeat — so ``pipeline: ffa`` in a job or
manifest record behaves exactly like ``search``/``spsearch``: same
claiming, same shape buckets, same done-record accounting, same
rollup.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..io.masks import read_killfile
from ..io.sigproc import Filterbank
from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..ops.dedisperse import dedisperse, fil_to_device, output_scale
from ..plan.dm_plan import DMPlan

log = get_logger("pipeline.ffa")


@dataclass
class FFAConfig:
    """FFA search knobs (reference: FFACmdLineOptions,
    include/utils/cmdline.hpp:211-292, whose implementing pipeline is
    absent from the reference tree — ops/ffa.py is the real one)."""

    outdir: str = "."
    killfilename: str = ""
    limit: int = 1000
    dm_start: float = 0.0
    dm_end: float = 100.0
    dm_tol: float = 1.10
    dm_pulse_width: float = 64.0
    p_start: float = 0.8  # shortest folded period (s)
    p_end: float = 20.0  # longest folded period (s)
    min_dc: float = 0.001  # minimum duty cycle (fraction)
    min_snr: float = 8.0
    verbose: bool = False
    progress_bar: bool = False
    # accepted for campaign config symmetry with the other pipelines
    # (FFA octaves re-fold from scratch; there is no per-trial resume)
    checkpoint_file: str = ""


@dataclass
class FFAResult:
    candidates: list  # FFACandidate records, period-collapsed
    dm_list: np.ndarray
    timers: dict
    nsamps: int


class FFASearch:
    """Dedisperse the DM plan, then staircase-FFA every trial."""

    def __init__(self, config: FFAConfig):
        self.config = config

    def build_dm_plan(self, fil: Filterbank) -> DMPlan:
        cfg = self.config
        killmask = None
        if cfg.killfilename:
            killmask = read_killfile(cfg.killfilename, fil.nchans)
        return DMPlan.create(
            nsamps=fil.nsamps,
            nchans=fil.nchans,
            tsamp=fil.tsamp,
            fch1=fil.fch1,
            foff=fil.foff,
            dm_start=cfg.dm_start,
            dm_end=cfg.dm_end,
            pulse_width=cfg.dm_pulse_width,
            tol=cfg.dm_tol,
            killmask=killmask,
        )

    def run(self, fil: Filterbank) -> FFAResult:
        from ..ops.ffa import ffa_search_block

        cfg = self.config
        tel = current_telemetry()
        timers: dict[str, float] = {}
        t_total = time.perf_counter()

        t0 = time.perf_counter()
        tel.set_stage("plan")
        dm_plan = self.build_dm_plan(fil)
        timers["plan"] = time.perf_counter() - t0
        tel.gauge("ffa.n_dm_trials", int(dm_plan.ndm))
        tel.event(
            "ffa_plan", ndm=int(dm_plan.ndm),
            p_start=float(cfg.p_start), p_end=float(cfg.p_end),
            min_dc=float(cfg.min_dc),
        )

        # trials are consumed on the host (one FFA staircase per DM
        # trial), so use the host-resident dedisperse variant: HBM
        # holds one block at a time (cli/ffa.py's deployment choice)
        t0 = time.perf_counter()
        tel.set_stage("dedispersion")
        trials = dedisperse(
            fil_to_device(fil),
            dm_plan.delay_samples(),
            dm_plan.killmask,
            dm_plan.out_nsamps,
            scale=output_scale(fil.nbits, int(dm_plan.killmask.sum())),
        )
        timers["dedispersion"] = time.perf_counter() - t0
        tel.capture_device_memory("dedispersion")

        t0 = time.perf_counter()
        tel.set_stage("ffa_search")

        def on_progress(f: float) -> None:
            tel.set_progress(round(f * 100.0, 3), 100.0, unit="%")

        cands = ffa_search_block(
            trials, fil.tsamp, cfg.p_start, cfg.p_end, cfg.min_dc,
            dm_plan.dm_list, snr_min=cfg.min_snr, progress=on_progress,
        )
        timers["ffa_search"] = time.perf_counter() - t0
        tel.capture_device_memory("ffa_search")

        out = cands[: cfg.limit]
        timers["total"] = time.perf_counter() - t_total
        tel.gauge("candidates.final", len(out))
        log.info(
            "FFA search: %d DM trials -> %d period-collapsed candidates",
            dm_plan.ndm, len(out),
        )
        return FFAResult(
            candidates=out,
            dm_list=dm_plan.dm_list,
            timers=timers,
            nsamps=fil.nsamps,
        )
