"""FFT transform-size planning (reference: utils.hpp:12-18, pipeline_multi.cu:326-331)."""

from __future__ import annotations


def prev_power_of_two(val: int) -> int:
    """Largest n = 2^k with 2n >= val (reference quirk: utils.hpp:12-18).

    Note this is NOT "largest power of two <= val": for val = 2^k the
    reference returns 2^(k-1)... actually n doubles while n*2 < val, so
    for exact powers of two it returns val/2. Preserved verbatim.
    """
    n = 1
    while n * 2 < val:
        n *= 2
    return n


def choose_fft_size(nsamps: int, requested: int = 0) -> int:
    """--fft_size semantics: 0 means prev_power_of_two(nsamps)."""
    return requested if requested else prev_power_of_two(nsamps)
