from .dm_plan import generate_dm_list, delay_table, max_delay_samples, DMPlan
from .accel_plan import AccelerationPlan
from .fft_plan import prev_power_of_two, choose_fft_size
from .dedisp_plan import DedispPlan
