"""Acceleration-trial planning (reference: include/utils/utils.hpp:140-193).

The trial step is set so that the quadratic drift mismatch between
neighbouring trials smears a pulse of effective width w by no more than
the tolerance factor: alt_a = 2 * w * 24c / tobs^2 * sqrt(tol^2 - 1),
with w^2 = tdm^2 + tpulse^2 + tsamp^2 (tdm the intra-channel DM smear).

Quirks preserved for parity:
  * 0.0 is explicitly prepended when both range ends are non-zero
    (utils.hpp:183-184), so the list is NOT sorted;
  * the walk appends acc_hi after the loop, so the last interval can be
    shorter than alt_a (utils.hpp:186-190);
  * acc_hi == acc_lo yields the single trial [0.0] (utils.hpp:169-173).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

SPEED_OF_LIGHT = 299792458.0


@dataclass
class AccelerationPlan:
    acc_lo: float
    acc_hi: float
    tol: float
    pulse_width: float  # microseconds (--acc_pulse_width)
    nsamps: int  # FFT size used for the search
    tsamp: float  # seconds
    cfreq: float  # MHz
    bw: float  # MHz (absolute total bandwidth)
    # Golden-vs-modern pulse-width semantics (full analysis: PARITY.md
    # "accel plan"): the 2014 golden binary fed pulse_width to the width
    # sum in MICROSECONDS; today's reference source (utils.hpp:165)
    # divides it by 1e3 first, shrinking alt_a ~100x.  Default False
    # matches the golden artifacts (the only parity ground truth);
    # set True to reproduce a build of the checked-in reference source.
    modern_pulse_width: bool = False

    def __post_init__(self):
        self.bw = abs(self.bw)
        self.tobs = self.nsamps * self.tsamp
        if self.modern_pulse_width:
            # current reference source: ``pulse_width /= 1.0e3`` in the
            # constructor (utils.hpp:165) — f32 division like the float
            # member it mutates
            self.pulse_width = float(
                np.float32(self.pulse_width) / np.float32(1.0e3)
            )

    def step(self, dm: float) -> float:
        """Trial spacing alt_a at the given DM (m/s^2).

        Follows the GOLDEN binary's semantics: pulse_width enters the
        width sum in MICROSECONDS (w_us = sqrt(tdm + pw^2 + tsamp^2),
        utils.hpp:175-179).  The reference repo's current utils.hpp:165
        divides pulse_width by 1e3 in the constructor — a later upstream
        change the 2014 golden artifacts demonstrably predate: with the
        division, the tutorial flags yield alt_a ~ 0.24 m/s^2 (~44 accel
        trials/DM), while the golden candidates.peasoup assoc lists
        contain exactly the accs {0, -5, +5} per DM trial, which
        requires alt_a > 10 (w_us = 64 gives ~240).  We match the
        artifacts, which are the only ground truth for parity.
        """
        # C semantics: float locals, double expression evaluation, one
        # truncation per assignment.
        f32 = np.float32
        bw = float(f32(self.bw))
        cfreq = float(f32(self.cfreq))
        tol = f32(self.tol)
        pulse_width = f32(self.pulse_width)
        tsamp = f32(self.tsamp)
        tobs = float(f32(self.nsamps) * f32(self.tsamp))  # uint*float: f32
        tdm = float(f32((8.3 * bw / cfreq**3 * float(f32(dm))) ** 2))
        tpulse = float(pulse_width * pulse_width)  # float*float: f32
        ttsamp = float(tsamp * tsamp)  # float*float: f32
        # float + float additions, then sqrt rounded once to the local
        w_us = float(f32(np.sqrt(np.float64(f32(f32(tdm + tpulse) + ttsamp)))))
        return float(
            f32(
                2.0 * w_us * 1.0e-6 * 24.0 * SPEED_OF_LIGHT / tobs / tobs
                * np.sqrt(np.float64(tol * tol) - 1.0)
            )
        )

    def generate_accel_list(self, dm: float) -> np.ndarray:
        if self.acc_hi == self.acc_lo:
            return np.zeros(1, dtype=np.float32)
        alt_a = self.step(dm)
        accs: list[float] = []
        if self.acc_hi != 0 and self.acc_lo != 0:
            accs.append(0.0)
        acc = np.float32(self.acc_lo)
        alt_a32 = np.float32(alt_a)
        while acc < self.acc_hi:
            accs.append(float(acc))
            acc = np.float32(acc + alt_a32)
        accs.append(float(self.acc_hi))
        return np.asarray(accs, dtype=np.float32)
