"""Acceleration-trial planning (reference: include/utils/utils.hpp:140-193).

The trial step is set so that the quadratic drift mismatch between
neighbouring trials smears a pulse of effective width w by no more than
the tolerance factor: alt_a = 2 * w * 24c / tobs^2 * sqrt(tol^2 - 1),
with w^2 = tdm^2 + tpulse^2 + tsamp^2 (tdm the intra-channel DM smear).

Quirks preserved for parity:
  * 0.0 is explicitly prepended when both range ends are non-zero
    (utils.hpp:183-184), so the list is NOT sorted;
  * the walk appends acc_hi after the loop, so the last interval can be
    shorter than alt_a (utils.hpp:186-190);
  * acc_hi == acc_lo yields the single trial [0.0] (utils.hpp:169-173).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

SPEED_OF_LIGHT = 299792458.0


@dataclass
class AccelerationPlan:
    acc_lo: float
    acc_hi: float
    tol: float
    pulse_width: float  # microseconds (--acc_pulse_width)
    nsamps: int  # FFT size used for the search
    tsamp: float  # seconds
    cfreq: float  # MHz
    bw: float  # MHz (absolute total bandwidth)

    def __post_init__(self):
        self.bw = abs(self.bw)
        self.tobs = self.nsamps * self.tsamp

    def step(self, dm: float) -> float:
        """Trial spacing alt_a at the given DM (m/s^2).

        Width terms mix units like the reference (pulse_width becomes ms,
        tsamp stays in s) and every intermediate is truncated to f32 the
        way the reference's float locals are (utils.hpp:162-180).
        """
        # C semantics: float locals, double expression evaluation, one
        # truncation per assignment.
        f32 = np.float32
        bw = float(f32(self.bw))
        cfreq = float(f32(self.cfreq))
        tol = float(f32(self.tol))
        pulse_width = float(f32(self.pulse_width / 1.0e3))
        tsamp = float(f32(self.tsamp))
        tobs = float(f32(f32(self.nsamps) * f32(self.tsamp)))
        tdm = float(f32((8.3 * bw / cfreq**3 * dm) ** 2))
        tpulse = float(f32(pulse_width * pulse_width))
        ttsamp = float(f32(tsamp * tsamp))
        w_us = float(f32(np.sqrt(tdm + tpulse + ttsamp)))
        return float(
            f32(
                2.0 * w_us * 1.0e-6 * 24.0 * SPEED_OF_LIGHT / tobs / tobs
                * np.sqrt(tol * tol - 1.0)
            )
        )

    def generate_accel_list(self, dm: float) -> np.ndarray:
        if self.acc_hi == self.acc_lo:
            return np.zeros(1, dtype=np.float32)
        alt_a = self.step(dm)
        accs: list[float] = []
        if self.acc_hi != 0 and self.acc_lo != 0:
            accs.append(0.0)
        acc = np.float32(self.acc_lo)
        alt_a32 = np.float32(alt_a)
        while acc < self.acc_hi:
            accs.append(float(acc))
            acc = np.float32(acc + alt_a32)
        accs.append(float(self.acc_hi))
        return np.asarray(accs, dtype=np.float32)
