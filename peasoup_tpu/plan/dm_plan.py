"""Dispersion-measure trial planning.

The reference delegates DM-list generation and the per-channel delay
table to the external ``dedisp`` CUDA library
(reference: include/transforms/dedisperser.hpp:54-62 calls
``dedisp_generate_dm_list``). We re-derive both bit-faithfully: Lina
Levin's tolerance recurrence for the trial spacing (f64 on f32-rounded
plan inputs, each trial stored through f32 — dedisp's float dm_table),
and dedisp's generate_delay_table for the per-channel delays — which
uses the ROUNDED dispersion constant 4.15e3 (its source notes the more
precise 4.148741601e3 but deliberately ships 4.15e3). Matching that
rounding is required for candidate parity: the f64 divergence oracle
(tools/divergence.py) reproduces the golden candidates.peasoup S/N to
every printed digit with 4.15e3 and is 0.3-0.6% off at high DM with the
textbook 4.148808e3, because one whole-sample delay rounds differently.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# dedisp's generate_delay_table constant (see module docstring); the
# textbook value 4.148808e3 is NOT what the reference's delays use.
DM_CONSTANT = 4.15e3  # seconds when multiplied by DM * (f_MHz^-2 diff)


def generate_dm_list(
    dm_start: float,
    dm_end: float,
    dt: float,
    ti: float,
    f0: float,
    df: float,
    nchans: int,
    tol: float,
) -> np.ndarray:
    """Generate the DM trial grid with the smearing-tolerance recurrence.

    Args:
      dm_start, dm_end: DM range (pc cm^-3).
      dt: sampling time in SECONDS.
      ti: intrinsic pulse width in MICROSECONDS (--dm_pulse_width).
      f0: frequency of channel 0 in MHz (fch1).
      df: channel width in MHz (foff, negative for descending bands).
      nchans: number of channels.
      tol: smearing tolerance (e.g. 1.10).

    Each next trial is placed where total smearing (sampling + intrinsic
    width + intra-channel dispersion + inter-trial DM error across the
    band) grows by the tolerance factor. All intermediate math in f64;
    trials are rounded through f32 to match the reference's stored list.
    """
    # dedisp receives every one of these as dedisp_float (f32): dt/f0/df
    # live in the plan struct, ti/tol are dedisp_generate_dm_list args.
    # The recurrence itself then runs in f64 on the f32-rounded values.
    dt = float(np.float32(dt))
    ti = float(np.float32(ti))
    f0 = float(np.float32(f0))
    df = float(np.float32(df))
    tol = float(np.float32(tol))
    dt_us = dt * 1e6
    f_centre_ghz = (f0 + (nchans // 2 - 0.5) * df) * 1e-3
    tol2 = tol * tol
    # Intra-channel smearing per unit DM (us): 8.3 * df_MHz / f_GHz^3
    a = 8.3 * df / f_centre_ghz**3
    a2 = a * a
    # Across-the-band smearing term for a DM *error*: the band is nchans
    # channels wide, so the band-edge delay error per unit dDM is
    # (nchans/4)*a in the same units; squared -> a2*nchans^2/16.
    b2 = a2 * (nchans * nchans / 16.0)
    c = (dt_us * dt_us + ti * ti) * (tol2 - 1.0)

    # Each trial is stored as f32 and the f32 value feeds the next
    # recurrence step, matching dedisp's float dm_table; the step itself
    # is evaluated in f64.
    dms = [np.float32(dm_start)]
    while dms[-1] < dm_end:
        prev = float(dms[-1])
        prev2 = prev * prev
        k = c + tol2 * a2 * prev2
        dm = (b2 * prev + np.sqrt(-a2 * b2 * prev2 + (b2 + a2) * k)) / (a2 + b2)
        dms.append(np.float32(dm))
    return np.asarray(dms, dtype=np.float32)


def delay_table(f0: float, df: float, nchans: int, dt: float) -> np.ndarray:
    """Per-channel dispersion delay in SAMPLES per unit DM, bit-faithful
    to dedisp's generate_delay_table: ``a = 1.f/(f0+c*df)`` and the
    difference of squares in f32 arithmetic, scaled by the f64 quotient
    ``4.15e3/dt`` and rounded once to the f32 table entry.
    """
    f0 = np.float32(f0)
    df = np.float32(df)
    c = np.arange(nchans, dtype=np.float32)
    a = (np.float32(1.0) / (f0 + c * df)).astype(np.float32)
    b = np.float32(1.0) / f0
    diff2 = (a * a - b * b).astype(np.float32)
    return (
        np.float64(DM_CONSTANT) / np.float64(np.float32(dt))
        * diff2.astype(np.float64)
    ).astype(np.float32)


def max_delay_samples(dm_max: float, delays: np.ndarray) -> int:
    """Maximum whole-sample delay at the largest trial DM: dedisp's
    ``dm_list[last] * delay_table[nchans-1] + 0.5`` truncation, with the
    product in f32 (both factors are f32 in the library).

    For standard descending bands (foff < 0) the table is monotone and
    ``abs(delays).max() == abs(delays[-1])`` exactly, so using the max
    keeps dedisp parity while staying safe for ascending-frequency
    inputs, where the largest |delay| need not sit at the last channel
    (per-channel reads would otherwise run past the input)."""
    prod = np.float32(np.float32(dm_max) * np.abs(delays).max())
    return int(np.floor(np.float64(prod) + 0.5))


@dataclass
class DMPlan:
    """The full dedispersion plan: trial list + per-channel delays."""

    dm_list: np.ndarray  # (ndm,) f32
    delays: np.ndarray  # (nchans,) f32 samples per unit DM
    killmask: np.ndarray  # (nchans,) int, 1 = keep
    max_delay: int
    out_nsamps: int

    @classmethod
    def create(
        cls,
        nsamps: int,
        nchans: int,
        tsamp: float,
        fch1: float,
        foff: float,
        dm_start: float,
        dm_end: float,
        pulse_width: float = 64.0,
        tol: float = 1.10,
        dm_list: np.ndarray | None = None,
        killmask: np.ndarray | None = None,
    ) -> "DMPlan":
        if dm_list is None:
            dm_list = generate_dm_list(
                dm_start, dm_end, tsamp, pulse_width, fch1, foff, nchans, tol
            )
        dm_list = np.asarray(dm_list, dtype=np.float32)
        delays = delay_table(fch1, foff, nchans, tsamp)
        md = max_delay_samples(float(dm_list.max()), delays)
        if killmask is None:
            killmask = np.ones(nchans, dtype=np.int32)
        return cls(
            dm_list=dm_list,
            delays=delays,
            killmask=np.asarray(killmask, dtype=np.int32),
            max_delay=md,
            out_nsamps=nsamps - md,
        )

    @property
    def ndm(self) -> int:
        return len(self.dm_list)

    def subset(self, lo: int, hi: int) -> "DMPlan":
        """The [lo, hi) slice of the trial list, keeping the GLOBAL
        max_delay/out_nsamps so every slice's trials have identical
        length — the multi-host driver deals contiguous slices to
        processes and later merges their candidates (whose dm_idx are
        re-offset to the global list)."""
        return DMPlan(
            dm_list=self.dm_list[lo:hi],
            delays=self.delays,
            killmask=self.killmask,
            max_delay=self.max_delay,
            out_nsamps=self.out_nsamps,
        )

    def delay_samples(self) -> np.ndarray:
        """Integer delay (ndm, nchans) in samples: round-half-even of
        the F32 product ``dm * delay_table[c]`` (the dedisp kernel's
        __float2uint_rn on float operands)."""
        prod = (
            self.dm_list[:, None] * np.abs(self.delays)[None, :]
        ).astype(np.float32)
        return np.rint(prod).astype(np.int32)
