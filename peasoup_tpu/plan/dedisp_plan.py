"""Dedispersion strategy planning: exact vs two-stage subband vs the
MXU banded matmul.

The pipeline ships three dedispersion engines (ops/dedisperse.py): the
direct channel scan (golden-exact), the two-stage subband engine from
"Accelerating incoherent dedispersion" (arXiv:1201.5380), and the
banded-matmul engine that recasts the shift-and-sum as a one-hot
contraction on the MXU. Which one wins — and at which shape knobs —
depends on the observation geometry and the device; the reference
picks statically. This module is the DECISION layer: a device-free
analytic cost model over the bucket's real delay table plus a
parity-tolerance gate whose inputs (max extra smear in samples, max
fractional S/N loss) are explicit plan parameters, not folklore.
"Real-Time Dedispersion ... using Auto Tuning" (arXiv:1601.01165)
shows the remaining shape knobs are best set empirically per device —
that measurement layer and its per-device cache live in
:mod:`peasoup_tpu.perf.tuning`; this module stays pure numpy so
planning is testable and auditable on any backend.

Cost model (arithmetic, in channel-sum MACs over the trial set):

* exact:    ``ndm * nchans * out_nsamps``
* subband:  ``n_groups * nchans * out_nsamps``  (stage 1, once per
  nominal DM) ``+ ndm * nsub * out_nsamps``     (stage 2, per trial)
* matmul:   ``sum_blocks ndm_b * nchans * band_b * out_nsamps`` MACs
  on the MXU (band_b the block's real one-hot band from the delay
  table), rated at ``MXU_MAC_GAIN`` gather-MACs per matmul-MAC and
  bounded below by the HBM byte traffic — an effective cost of
  ``max(macs / MXU_MAC_GAIN, bytes / HBM_BYTES_PER_MAC)``.

with ``n_groups`` computed from the bucket's actual delay table by the
same greedy smear-bounded grouping the engine executes
(:func:`subband_group_spans` is a vectorised twin of
``ops.dedisperse.subband_groups`` — identical spans, plus each group's
realised worst-case smear for the S/N gate). The classic ~sqrt(C) win
appears exactly when groups hold several trials.

The matmul engine is bitwise-equal to exact (the delay tables are
integral), so it carries no parity gate — but the MXU advantage is a
device property no analytic constant captures honestly, so
:meth:`DedispPlan.select` NEVER picks it analytically: it computes
``cost_matmul`` and flags ``matmul_candidate`` when the model puts the
engine within ``MATMUL_RACE_SLACK`` of the gather winner, and the
per-device tuner (perf/tuning.py) races the eligible engines and
selects matmul only when it MEASURES faster (the acceptance contract —
winner provenance lands in the plan's telemetry summary).

Parity gate: substituting a group nominal's intra-band delay shape
displaces each channel's read by at most the group's realised smear
``s`` samples (the grouping bound). A boxcar matched filter recovering
a pulse of effective width ``w`` samples smeared over ``w + s`` loses
S/N by the factor ``sqrt(w / (w + s))``; the plan predicts the loss
per group at that group's lowest-DM trial (narrowest effective width
— the worst case, since width grows with DM through the intra-channel
smear term) and selects subband only when the worst predicted loss
stays within ``max_snr_loss``. ``max_smear = 0`` keeps the engines
bitwise equal and the gate trivially passes.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, field
from typing import Optional

import numpy as np

from .dm_plan import DMPlan

PLAN_VERSION = 2

# structural floor for the two-stage split: below ~64 channels the
# stage-2 pass over nsub pseudo-channels plus the extra dispatches eat
# the arithmetic win (the ~sqrt(C) argument needs C >> nsub >> 1), so
# the planner never proposes subbands there — "exact must win at small
# nchans" is a plan invariant, not a tuning outcome
MIN_SUBBAND_NCHANS = 64
MIN_SUBBANDS = 8

# banded-matmul rate model (RELATIVE units — one gather-MAC of the
# channel scan is the unit of work; the empirical tuner arbitrates the
# real ratio per device): a conservative MXU-vs-VPU MAC throughput
# advantage for f32 one-hot contractions, and the HBM bytes one
# gather-MAC's time buys (the matmul engine is memory-bound once the
# band is narrow, so the byte term keeps the model honest there)
MXU_MAC_GAIN = 8.0
HBM_BYTES_PER_MAC = 2.0
# race the matmul engine on the device whenever the analytic model puts
# it within this factor of the gather winner (generous on purpose:
# measurement, not the model, decides)
MATMUL_RACE_SLACK = 4.0


def effective_subbands(nchans: int, nsub: int) -> int:
    """The engine's effective band count for a requested ``nsub``
    (ops.dedisperse.dedisperse_subband normalises the same way)."""
    w = -(-nchans // max(1, min(nsub, nchans)))
    return -(-nchans // w)


def intra_band_shapes(delay_table: np.ndarray, nsub: int) -> np.ndarray:
    """Per-trial intra-band delay shapes d1[d, c] = delay[d, c] -
    min(delay[d, band(c)]) under the engine's band grouping and
    min-reference convention (ops.dedisperse.dedisperse_subband)."""
    delay_table = np.asarray(delay_table)
    _, C = delay_table.shape
    nsub = effective_subbands(C, nsub)
    w = -(-C // nsub)
    band_of = np.minimum(np.arange(C) // w, nsub - 1)
    refdel = np.stack(
        [delay_table[:, b : b + w].min(axis=1) for b in range(0, C, w)],
        axis=1,
    )
    return delay_table - refdel[:, band_of]


def subband_group_spans(
    delay_table: np.ndarray,
    nsub: int,
    max_smear: float,
    budgets: Optional[np.ndarray] = None,
) -> list[tuple[int, int, int]]:
    """Greedy smear-bounded DM-trial grouping: the vectorised twin of
    ``ops.dedisperse.subband_groups`` (identical [lo, hi) spans — a
    test pins the equivalence) returning ``(lo, hi, err)`` with each
    group's realised worst-case intra-band smear in samples. With
    ``budgets`` each trial joins under its OWN per-trial cap (the
    DM-scaled smear budget) instead of the global ``max_smear``."""
    d1 = intra_band_shapes(delay_table, nsub)
    D = d1.shape[0]
    caps = (
        np.full(D, float(max_smear))
        if budgets is None
        else np.asarray(budgets, dtype=np.float64)
    )
    spans: list[tuple[int, int, int]] = []
    lo = 0
    step = 128
    while lo < D:
        hi = lo + 1
        err = 0
        while hi < D:
            j = min(D, hi + step)
            errs = np.abs(d1[hi:j] - d1[lo]).max(axis=1)
            bad = np.nonzero(errs > caps[hi:j])[0]
            if bad.size:
                if bad[0] > 0:
                    err = max(err, int(errs[: bad[0]].max()))
                hi += int(bad[0])
                break
            if errs.size:
                err = max(err, int(errs.max()))
            hi = j
        spans.append((lo, hi, err))
        lo = hi
    return spans


def dm_smear_budgets(
    dm_list,
    *,
    tsamp: float,
    fch1: float,
    foff: float,
    nchans: int,
    pulse_width_us: float,
    max_snr_loss: float,
    floor: float = 1.0,
) -> np.ndarray:
    """Per-trial smear budgets in samples: the largest extra smear
    whose predicted matched-filter S/N loss at that trial's effective
    width stays within ``max_snr_loss``. Inverting
    ``predicted_snr_loss(w, s) = 1 - sqrt(w / (w + s)) <= L`` gives
    ``s <= w * (1 / (1 - L)^2 - 1)`` — high-DM trials, whose intrinsic
    dispersion smearing already dominates ``w``, absorb many samples
    of grouping smear for the same loss, so they stop forcing
    conservative plans (the ISSUE's DM-dependent smear budget).
    ``floor`` keeps the low-DM budget at the classic global value."""
    loss = min(max(float(max_snr_loss), 0.0), 0.99)
    k = 1.0 / (1.0 - loss) ** 2 - 1.0
    ws = np.asarray(
        [
            effective_width_samples(
                float(dm), tsamp, pulse_width_us, fch1, foff, nchans
            )
            for dm in np.asarray(dm_list, dtype=np.float64)
        ]
    )
    return np.maximum(float(floor), ws * k)


def matmul_cost_profile(
    delay_table: np.ndarray,
    out_nsamps: int,
    block: Optional[int] = None,
    quant: Optional[int] = None,
) -> dict:
    """Analytic MAC + byte profile of the banded-matmul engine over the
    bucket's REAL delay table: per aligned DM-trial block, the one-hot
    band is the block's worst per-channel delay spread (padded to the
    engine's quantum), MACs are ``ndm_b * C * band_b * T`` and bytes
    are the block's f32 window copy plus its output. Returns
    ``{"macs", "bytes", "max_band", "effective"}`` with ``effective``
    in gather-MAC units (max of the MXU-rated MAC term and the
    HBM-rated byte term)."""
    from ..ops.dedisperse import MATMUL_BAND_QUANT, MATMUL_BLOCK, matmul_band

    block = MATMUL_BLOCK if block is None else block
    quant = MATMUL_BAND_QUANT if quant is None else quant
    dt = np.asarray(delay_table)
    D, C = dt.shape
    T = max(1, int(out_nsamps))
    macs = 0.0
    nbytes = 0.0
    max_band = 0
    for lo in range(0, D, block):
        blk = dt[lo : lo + block]
        band = matmul_band(blk, quant)
        max_band = max(max_band, band)
        db = len(blk)
        macs += float(db) * C * band * T
        nbytes += 4.0 * (C * (T + band - 1) + db * T)
    effective = max(macs / MXU_MAC_GAIN, nbytes / HBM_BYTES_PER_MAC)
    return {
        "macs": macs,
        "bytes": nbytes,
        "max_band": int(max_band),
        "effective": effective,
    }


def effective_delay_table(
    delay_table: np.ndarray,
    nsub: int,
    max_smear: float,
    budgets: Optional[np.ndarray] = None,
) -> np.ndarray:
    """The integer delay table the subband engine EFFECTIVELY applies:
    each trial reads channel c at ``refdel[d, band(c)] + d1[lo, c]``
    with ``lo`` its group's nominal. Direct dedispersion with this
    table is bitwise what the two-stage engine computes (channel sums
    of <= 8-bit samples are exact in f32, so the differing summation
    order cannot change the result) — the parity property tests pin
    that equality, and ``|effective - true| <= max_smear`` everywhere
    is the smear bound made concrete."""
    delay_table = np.asarray(delay_table)
    _, C = delay_table.shape
    nsub_eff = effective_subbands(C, nsub)
    w = -(-C // nsub_eff)
    band_of = np.minimum(np.arange(C) // w, nsub_eff - 1)
    refdel = np.stack(
        [delay_table[:, b : b + w].min(axis=1) for b in range(0, C, w)],
        axis=1,
    )
    d1 = delay_table - refdel[:, band_of]
    eff = np.empty_like(delay_table)
    spans = subband_group_spans(delay_table, nsub_eff, max_smear, budgets)
    for lo, hi, _ in spans:
        eff[lo:hi] = refdel[lo:hi][:, band_of] + d1[lo][None, :]
    return eff


def effective_width_samples(
    dm: float, tsamp: float, pulse_width_us: float,
    fch1: float, foff: float, nchans: int,
) -> float:
    """Effective pulse width in SAMPLES at one DM trial: the same
    smearing terms the DM-trial recurrence uses (plan/dm_plan.py) —
    sampling time, intrinsic width, and the per-channel dispersion
    smear 8.3 * |df_MHz| / f_GHz^3 * DM microseconds."""
    dt_us = float(tsamp) * 1e6
    f_centre_ghz = (float(fch1) + (nchans // 2 - 0.5) * float(foff)) * 1e-3
    a = 8.3 * abs(float(foff)) / max(1e-9, abs(f_centre_ghz)) ** 3
    w_us = math.sqrt(
        dt_us * dt_us
        + float(pulse_width_us) ** 2
        + (a * float(dm)) ** 2
    )
    return max(1.0, w_us / dt_us)


def predicted_snr_loss(width_samps: float, smear_samps: float) -> float:
    """Fractional matched-filter S/N loss from smearing a pulse of
    effective width ``w`` samples over ``s`` extra samples:
    1 - sqrt(w / (w + s))."""
    w = max(1e-9, float(width_samps))
    return 1.0 - math.sqrt(w / (w + max(0.0, float(smear_samps))))


def candidate_subbands(nchans: int) -> list[int]:
    """The nsub candidate grid: powers of two around sqrt(nchans),
    clipped to the structural window [MIN_SUBBANDS, nchans // 4].
    Empty below MIN_SUBBAND_NCHANS — exact wins there by plan
    invariant."""
    if nchans < MIN_SUBBAND_NCHANS:
        return []
    s0 = 1 << round(math.log2(math.sqrt(nchans)))
    cands = sorted(
        {
            min(max(s, MIN_SUBBANDS), nchans // 4)
            for s in (s0 // 2, s0, s0 * 2)
        }
    )
    return [s for s in cands if MIN_SUBBANDS <= s <= nchans // 4]


@dataclass
class DedispPlan:
    """One bucket's dedispersion strategy: the engine choice plus the
    shape knobs the drivers consume. ``source`` records provenance:
    ``analytic`` (cost model only), ``tuned`` (per-device measurements
    refined the knobs, perf/tuning.py), ``cache`` (loaded from the
    tuning cache with zero re-measurement)."""

    engine: str = "exact"  # "exact" | "subband" | "matmul" (matmul
    # only ever via the tuner's measured race — select() never picks it)
    subbands: int = 0
    subband_smear: float = 0.0
    subband_matmul: bool = False  # subband stages as banded matmuls
    dedisp_block: int = 16
    dm_block: int = 0  # 0 = driver auto-sizing
    accel_bucket: int = 0  # 0 = driver default (tuned knob)
    pallas_block: int = 0  # 0 = driver default (tuned Pallas tile)
    cost_exact: float = 0.0
    cost_subband: float = 0.0
    cost_matmul: float = 0.0  # effective gather-MAC units (MAC+bytes)
    matmul_band: int = 0  # worst one-hot band over the real table
    matmul_candidate: bool = False  # analytic model puts matmul within
    # MATMUL_RACE_SLACK of the gather winner -> the tuner races it
    gain: float = 1.0  # cost_exact / cost_subband at the chosen nsub
    predicted_loss: float = 0.0  # worst-group fractional S/N loss
    max_group_smear: int = 0  # realised worst smear (samples)
    n_groups: int = 0
    smear_dm_scaled: bool = False  # grouping used DM-scaled budgets
    smear_loss_budget: float = 0.0  # the per-trial loss fraction those
    # budgets were derived from (drivers rebuild them deterministically
    # via dm_smear_budgets; 0 = global max_smear only)
    source: str = "analytic"
    tuning_s: float = 0.0
    trials: list = field(default_factory=list)  # tuner measurements
    version: int = PLAN_VERSION

    def to_doc(self) -> dict:
        return asdict(self)

    @classmethod
    def from_doc(cls, doc: dict) -> "DedispPlan":
        names = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in doc.items() if k in names})

    def summary(self) -> dict:
        """The compact provenance record for telemetry manifests and
        the BENCH json (full tuner trials stay in the cache file)."""
        return {
            "engine": self.engine,
            "subbands": self.subbands,
            "subband_smear": self.subband_smear,
            "subband_matmul": self.subband_matmul,
            "dedisp_block": self.dedisp_block,
            "dm_block": self.dm_block,
            "accel_bucket": self.accel_bucket,
            "pallas_block": self.pallas_block,
            "gain": round(self.gain, 3),
            "predicted_loss": round(self.predicted_loss, 4),
            "n_groups": self.n_groups,
            "matmul_candidate": self.matmul_candidate,
            "cost_matmul": round(self.cost_matmul, 1),
            "smear_dm_scaled": self.smear_dm_scaled,
            "source": self.source,
            "tuning_s": round(self.tuning_s, 3),
        }

    @classmethod
    def select(
        cls,
        dm_plan: DMPlan,
        *,
        nbits: int,
        tsamp: float,
        fch1: float,
        foff: float,
        max_smear: float = 1.0,
        max_snr_loss: float = 0.1,
        min_gain: float = 1.2,
        pulse_width_us: float = 64.0,
        candidates: Optional[list[int]] = None,
        dm_scale_smear: bool = True,
    ) -> "DedispPlan":
        """Pick exact vs subband for one plan (and profile the matmul
        alternative for the tuner's race). Subband is selected exactly
        when (a) the cost model predicts at least a ``min_gain``
        arithmetic win at the best candidate nsub over the bucket's
        real delay table, AND (b) the parity gate passes: the worst
        per-group predicted S/N loss stays within ``max_snr_loss``.
        With ``dm_scale_smear`` the grouping budget scales per trial
        with its intrinsic DM smearing (:func:`dm_smear_budgets`,
        floored at ``max_smear``) instead of one global cap. The
        matmul engine is bitwise-exact so it has no gate, but its MXU
        advantage is a device property: select() only records
        ``cost_matmul`` and the ``matmul_candidate`` race flag — the
        tuner promotes it when it measures faster. Everything else —
        small bands, loose geometries, tight loss budgets — keeps the
        golden-exact direct scan."""
        D = dm_plan.ndm
        C = len(dm_plan.delays)
        T = max(1, dm_plan.out_nsamps)
        cost_exact = float(D) * C * T
        plan = cls(engine="exact", cost_exact=cost_exact)
        if D < 2:
            return plan
        delay_table = dm_plan.delay_samples()
        mm = matmul_cost_profile(delay_table, T)
        plan.cost_matmul = mm["effective"]
        plan.matmul_band = mm["max_band"]
        budgets = None
        if dm_scale_smear and max_smear > 0 and max_snr_loss > 0:
            budgets = dm_smear_budgets(
                dm_plan.dm_list, tsamp=tsamp, fch1=fch1, foff=foff,
                nchans=C, pulse_width_us=pulse_width_us,
                max_snr_loss=max_snr_loss, floor=max_smear,
            )
        cands = candidates if candidates is not None else candidate_subbands(C)
        cands = [s for s in cands if 2 <= s <= C]
        if cands:
            best: Optional[
                tuple[float, int, list[tuple[int, int, int]]]
            ] = None
            for nsub in cands:
                nsub_eff = effective_subbands(C, nsub)
                spans = subband_group_spans(
                    delay_table, nsub_eff, max_smear, budgets
                )
                cost = float(len(spans)) * C * T + float(D) * nsub_eff * T
                if best is None or cost < best[0]:
                    best = (cost, nsub_eff, spans)
            assert best is not None
            cost_sub, nsub_best, spans = best
            plan.cost_subband = cost_sub
            plan.gain = cost_exact / max(1.0, cost_sub)
            plan.n_groups = len(spans)
            plan.max_group_smear = max(
                (err for _, _, err in spans), default=0
            )
            # parity gate: worst PER-TRIAL loss — each trial's realised
            # smear under its group nominal, at that trial's own
            # effective width (the group-max-at-narrowest-width form
            # over-vetoed DM-scaled budgets, which admit large smears
            # only on trials wide enough to absorb them)
            d1 = intra_band_shapes(delay_table, nsub_best)
            widths = np.asarray(
                [
                    effective_width_samples(
                        float(dm), tsamp, pulse_width_us, fch1, foff, C
                    )
                    for dm in dm_plan.dm_list
                ]
            )
            loss = 0.0
            for lo, hi, err in spans:
                if err <= 0:
                    continue
                errs = np.abs(d1[lo:hi] - d1[lo]).max(axis=1)
                w = widths[lo:hi]
                loss = max(
                    loss,
                    float(
                        np.max(1.0 - np.sqrt(w / (w + np.maximum(errs, 0.0))))
                    ),
                )
            plan.predicted_loss = loss
            if plan.gain >= min_gain and loss <= max_snr_loss:
                plan.engine = "subband"
                plan.subbands = nsub_best
                plan.subband_smear = float(max_smear)
                plan.smear_dm_scaled = budgets is not None
                plan.smear_loss_budget = (
                    float(max_snr_loss) if budgets is not None else 0.0
                )
        gather_cost = (
            plan.cost_subband
            if plan.engine == "subband"
            else plan.cost_exact
        )
        plan.matmul_candidate = (
            plan.cost_matmul <= MATMUL_RACE_SLACK * max(1.0, gather_cost)
        )
        return plan
