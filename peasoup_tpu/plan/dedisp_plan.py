"""Dedispersion strategy planning: exact vs two-stage subband.

The pipeline ships two dedispersion engines (ops/dedisperse.py): the
direct channel scan (golden-exact) and the two-stage subband engine
from "Accelerating incoherent dedispersion" (arXiv:1201.5380). Which
one wins — and at which shape knobs — depends on the observation
geometry and the device; the reference picks statically. This module
is the DECISION layer: a device-free analytic cost model over the
bucket's real delay table plus a parity-tolerance gate whose inputs
(max extra smear in samples, max fractional S/N loss) are explicit
plan parameters, not folklore. "Real-Time Dedispersion ... using Auto
Tuning" (arXiv:1601.01165) shows the remaining shape knobs are best
set empirically per device — that measurement layer and its
per-device cache live in :mod:`peasoup_tpu.perf.tuning`; this module
stays pure numpy so planning is testable and auditable on any backend.

Cost model (arithmetic, in channel-sum MACs over the trial set):

* exact:    ``ndm * nchans * out_nsamps``
* subband:  ``n_groups * nchans * out_nsamps``  (stage 1, once per
  nominal DM) ``+ ndm * nsub * out_nsamps``     (stage 2, per trial)

with ``n_groups`` computed from the bucket's actual delay table by the
same greedy smear-bounded grouping the engine executes
(:func:`subband_group_spans` is a vectorised twin of
``ops.dedisperse.subband_groups`` — identical spans, plus each group's
realised worst-case smear for the S/N gate). The classic ~sqrt(C) win
appears exactly when groups hold several trials.

Parity gate: substituting a group nominal's intra-band delay shape
displaces each channel's read by at most the group's realised smear
``s`` samples (the grouping bound). A boxcar matched filter recovering
a pulse of effective width ``w`` samples smeared over ``w + s`` loses
S/N by the factor ``sqrt(w / (w + s))``; the plan predicts the loss
per group at that group's lowest-DM trial (narrowest effective width
— the worst case, since width grows with DM through the intra-channel
smear term) and selects subband only when the worst predicted loss
stays within ``max_snr_loss``. ``max_smear = 0`` keeps the engines
bitwise equal and the gate trivially passes.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, field
from typing import Optional

import numpy as np

from .dm_plan import DMPlan

PLAN_VERSION = 1

# structural floor for the two-stage split: below ~64 channels the
# stage-2 pass over nsub pseudo-channels plus the extra dispatches eat
# the arithmetic win (the ~sqrt(C) argument needs C >> nsub >> 1), so
# the planner never proposes subbands there — "exact must win at small
# nchans" is a plan invariant, not a tuning outcome
MIN_SUBBAND_NCHANS = 64
MIN_SUBBANDS = 8


def effective_subbands(nchans: int, nsub: int) -> int:
    """The engine's effective band count for a requested ``nsub``
    (ops.dedisperse.dedisperse_subband normalises the same way)."""
    w = -(-nchans // max(1, min(nsub, nchans)))
    return -(-nchans // w)


def intra_band_shapes(delay_table: np.ndarray, nsub: int) -> np.ndarray:
    """Per-trial intra-band delay shapes d1[d, c] = delay[d, c] -
    min(delay[d, band(c)]) under the engine's band grouping and
    min-reference convention (ops.dedisperse.dedisperse_subband)."""
    delay_table = np.asarray(delay_table)
    _, C = delay_table.shape
    nsub = effective_subbands(C, nsub)
    w = -(-C // nsub)
    band_of = np.minimum(np.arange(C) // w, nsub - 1)
    refdel = np.stack(
        [delay_table[:, b : b + w].min(axis=1) for b in range(0, C, w)],
        axis=1,
    )
    return delay_table - refdel[:, band_of]


def subband_group_spans(
    delay_table: np.ndarray, nsub: int, max_smear: float
) -> list[tuple[int, int, int]]:
    """Greedy smear-bounded DM-trial grouping: the vectorised twin of
    ``ops.dedisperse.subband_groups`` (identical [lo, hi) spans — a
    test pins the equivalence) returning ``(lo, hi, err)`` with each
    group's realised worst-case intra-band smear in samples."""
    d1 = intra_band_shapes(delay_table, nsub)
    D = d1.shape[0]
    spans: list[tuple[int, int, int]] = []
    lo = 0
    step = 128
    while lo < D:
        hi = lo + 1
        err = 0
        while hi < D:
            j = min(D, hi + step)
            errs = np.abs(d1[hi:j] - d1[lo]).max(axis=1)
            bad = np.nonzero(errs > max_smear)[0]
            if bad.size:
                if bad[0] > 0:
                    err = max(err, int(errs[: bad[0]].max()))
                hi += int(bad[0])
                break
            if errs.size:
                err = max(err, int(errs.max()))
            hi = j
        spans.append((lo, hi, err))
        lo = hi
    return spans


def effective_delay_table(
    delay_table: np.ndarray, nsub: int, max_smear: float
) -> np.ndarray:
    """The integer delay table the subband engine EFFECTIVELY applies:
    each trial reads channel c at ``refdel[d, band(c)] + d1[lo, c]``
    with ``lo`` its group's nominal. Direct dedispersion with this
    table is bitwise what the two-stage engine computes (channel sums
    of <= 8-bit samples are exact in f32, so the differing summation
    order cannot change the result) — the parity property tests pin
    that equality, and ``|effective - true| <= max_smear`` everywhere
    is the smear bound made concrete."""
    delay_table = np.asarray(delay_table)
    _, C = delay_table.shape
    nsub_eff = effective_subbands(C, nsub)
    w = -(-C // nsub_eff)
    band_of = np.minimum(np.arange(C) // w, nsub_eff - 1)
    refdel = np.stack(
        [delay_table[:, b : b + w].min(axis=1) for b in range(0, C, w)],
        axis=1,
    )
    d1 = delay_table - refdel[:, band_of]
    eff = np.empty_like(delay_table)
    for lo, hi, _ in subband_group_spans(delay_table, nsub_eff, max_smear):
        eff[lo:hi] = refdel[lo:hi][:, band_of] + d1[lo][None, :]
    return eff


def effective_width_samples(
    dm: float, tsamp: float, pulse_width_us: float,
    fch1: float, foff: float, nchans: int,
) -> float:
    """Effective pulse width in SAMPLES at one DM trial: the same
    smearing terms the DM-trial recurrence uses (plan/dm_plan.py) —
    sampling time, intrinsic width, and the per-channel dispersion
    smear 8.3 * |df_MHz| / f_GHz^3 * DM microseconds."""
    dt_us = float(tsamp) * 1e6
    f_centre_ghz = (float(fch1) + (nchans // 2 - 0.5) * float(foff)) * 1e-3
    a = 8.3 * abs(float(foff)) / max(1e-9, abs(f_centre_ghz)) ** 3
    w_us = math.sqrt(
        dt_us * dt_us
        + float(pulse_width_us) ** 2
        + (a * float(dm)) ** 2
    )
    return max(1.0, w_us / dt_us)


def predicted_snr_loss(width_samps: float, smear_samps: float) -> float:
    """Fractional matched-filter S/N loss from smearing a pulse of
    effective width ``w`` samples over ``s`` extra samples:
    1 - sqrt(w / (w + s))."""
    w = max(1e-9, float(width_samps))
    return 1.0 - math.sqrt(w / (w + max(0.0, float(smear_samps))))


def candidate_subbands(nchans: int) -> list[int]:
    """The nsub candidate grid: powers of two around sqrt(nchans),
    clipped to the structural window [MIN_SUBBANDS, nchans // 4].
    Empty below MIN_SUBBAND_NCHANS — exact wins there by plan
    invariant."""
    if nchans < MIN_SUBBAND_NCHANS:
        return []
    s0 = 1 << round(math.log2(math.sqrt(nchans)))
    cands = sorted(
        {
            min(max(s, MIN_SUBBANDS), nchans // 4)
            for s in (s0 // 2, s0, s0 * 2)
        }
    )
    return [s for s in cands if MIN_SUBBANDS <= s <= nchans // 4]


@dataclass
class DedispPlan:
    """One bucket's dedispersion strategy: the engine choice plus the
    shape knobs the drivers consume. ``source`` records provenance:
    ``analytic`` (cost model only), ``tuned`` (per-device measurements
    refined the knobs, perf/tuning.py), ``cache`` (loaded from the
    tuning cache with zero re-measurement)."""

    engine: str = "exact"  # "exact" | "subband"
    subbands: int = 0
    subband_smear: float = 0.0
    dedisp_block: int = 16
    dm_block: int = 0  # 0 = driver auto-sizing
    cost_exact: float = 0.0
    cost_subband: float = 0.0
    gain: float = 1.0  # cost_exact / cost_subband at the chosen nsub
    predicted_loss: float = 0.0  # worst-group fractional S/N loss
    max_group_smear: int = 0  # realised worst smear (samples)
    n_groups: int = 0
    source: str = "analytic"
    tuning_s: float = 0.0
    trials: list = field(default_factory=list)  # tuner measurements
    version: int = PLAN_VERSION

    def to_doc(self) -> dict:
        return asdict(self)

    @classmethod
    def from_doc(cls, doc: dict) -> "DedispPlan":
        names = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in doc.items() if k in names})

    def summary(self) -> dict:
        """The compact provenance record for telemetry manifests and
        the BENCH json (full tuner trials stay in the cache file)."""
        return {
            "engine": self.engine,
            "subbands": self.subbands,
            "subband_smear": self.subband_smear,
            "dedisp_block": self.dedisp_block,
            "dm_block": self.dm_block,
            "gain": round(self.gain, 3),
            "predicted_loss": round(self.predicted_loss, 4),
            "n_groups": self.n_groups,
            "source": self.source,
            "tuning_s": round(self.tuning_s, 3),
        }

    @classmethod
    def select(
        cls,
        dm_plan: DMPlan,
        *,
        nbits: int,
        tsamp: float,
        fch1: float,
        foff: float,
        max_smear: float = 1.0,
        max_snr_loss: float = 0.1,
        min_gain: float = 1.2,
        pulse_width_us: float = 64.0,
        candidates: Optional[list[int]] = None,
    ) -> "DedispPlan":
        """Pick exact vs subband for one plan. Subband is selected
        exactly when (a) the cost model predicts at least a
        ``min_gain`` arithmetic win at the best candidate nsub over
        the bucket's real delay table, AND (b) the parity gate passes:
        the worst per-group predicted S/N loss under the ``max_smear``
        budget stays within ``max_snr_loss``. Everything else — small
        bands, loose geometries, tight loss budgets — keeps the
        golden-exact direct scan."""
        D = dm_plan.ndm
        C = len(dm_plan.delays)
        T = max(1, dm_plan.out_nsamps)
        cost_exact = float(D) * C * T
        plan = cls(engine="exact", cost_exact=cost_exact)
        if D < 2:
            return plan
        cands = candidates if candidates is not None else candidate_subbands(C)
        cands = [s for s in cands if 2 <= s <= C]
        if not cands:
            return plan
        delay_table = dm_plan.delay_samples()
        best: Optional[tuple[float, int, list[tuple[int, int, int]]]] = None
        for nsub in cands:
            nsub_eff = effective_subbands(C, nsub)
            spans = subband_group_spans(delay_table, nsub_eff, max_smear)
            cost = float(len(spans)) * C * T + float(D) * nsub_eff * T
            if best is None or cost < best[0]:
                best = (cost, nsub_eff, spans)
        assert best is not None
        cost_sub, nsub_best, spans = best
        plan.cost_subband = cost_sub
        plan.gain = cost_exact / max(1.0, cost_sub)
        plan.n_groups = len(spans)
        plan.max_group_smear = max((err for _, _, err in spans), default=0)
        # parity gate: worst loss over groups, each at its lowest-DM
        # (narrowest-width) member
        loss = 0.0
        for lo, _, err in spans:
            if err <= 0:
                continue
            w = effective_width_samples(
                float(dm_plan.dm_list[lo]), tsamp, pulse_width_us,
                fch1, foff, C,
            )
            loss = max(loss, predicted_snr_loss(w, err))
        plan.predicted_loss = loss
        if plan.gain >= min_gain and loss <= max_snr_loss:
            plan.engine = "subband"
            plan.subbands = nsub_best
            plan.subband_smear = float(max_smear)
        return plan
