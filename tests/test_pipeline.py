"""Pipeline-level tests on a small synthetic pulsar filterbank (CPU)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu.core import Candidate
from peasoup_tpu.io import Filterbank, SigprocHeader, write_filterbank, read_filterbank
from peasoup_tpu.pipeline import (
    SearchConfig,
    PeasoupSearch,
    HarmonicDistiller,
    AccelerationDistiller,
    DMDistiller,
    CandidateScorer,
)


def make_synthetic_fil(
    tmp_path,
    nsamps=1 << 15,
    nchans=16,
    tsamp=0.000256,
    period=0.064,
    dm=20.0,
    fch1=1400.0,
    foff=-8.0,  # wide band -> real DM discrimination across trials
    amp=1.2,
    seed=7,
):
    """8-bit filterbank with a dispersed pulsar of the given period/DM."""
    rng = np.random.default_rng(seed)
    data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
    freqs = fch1 + np.arange(nchans) * foff
    delays = 4.148808e3 * dm * (freqs**-2 - fch1**-2) / tsamp  # samples
    t = np.arange(nsamps)
    for c in range(nchans):
        phase = ((t - delays[c]) * tsamp / period) % 1.0
        pulse = (phase < 0.03).astype(float)  # ~8-sample pulse
        data[:, c] += amp * 8.0 * pulse
    data = np.clip(np.rint(data), 0, 255).astype(np.uint8)
    hdr = SigprocHeader(
        source_name="FAKE", tsamp=tsamp, tstart=55000.0, fch1=fch1, foff=foff,
        nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    path = tmp_path / "fake.fil"
    write_filterbank(path, Filterbank(header=hdr, data=data))
    return path, period, dm


@pytest.fixture(scope="module")
def synthetic(tmp_path_factory):
    return make_synthetic_fil(tmp_path_factory.mktemp("fil"))


class TestEndToEnd:
    def test_recovers_pulsar(self, synthetic):
        path, period, dm = synthetic
        fil = read_filterbank(path)
        cfg = SearchConfig(dm_end=60.0, nharmonics=3, npdmp=4, limit=50)
        res = PeasoupSearch(cfg).run(fil)
        assert len(res.candidates) > 0
        top = res.candidates[0]
        # the pulsar (or a harmonic) must be the top candidate at ~the right DM
        ratio = (1.0 / top.freq) / period
        harmonic = min(
            abs(ratio - r) for r in (0.25, 0.5, 1.0, 2.0, 3.0, 4.0)
        )
        assert harmonic < 0.01
        assert abs(top.dm - dm) < 15.0
        assert top.snr > 10
        assert top.folded_snr > 5  # npdmp folded it

    def test_timers_and_lists(self, synthetic):
        path, _, _ = synthetic
        fil = read_filterbank(path)
        cfg = SearchConfig(dm_end=5.0, nharmonics=1, limit=10)
        res = PeasoupSearch(cfg).run(fil)
        for key in ("dedispersion", "searching", "folding", "total"):
            assert key in res.timers
        assert res.size == 1 << 14  # prev_power_of_two(nsamps)
        assert len(res.dm_list) >= 1
        assert len(res.candidates) <= 10

    def test_sliced_merge_matches_full_run(self, synthetic):
        """The multi-host flow — each process searches a contiguous DM
        slice, per-DM candidates are merged, every process finalizes
        with fold-outcome exchange — must reproduce the single-host
        candidate list exactly. Simulated here with two sequential
        slice runs and an in-process 'allgather'."""
        import pickle

        from peasoup_tpu.parallel.multihost import dm_slice_for_process
        from peasoup_tpu.pipeline.search import PartialSearchResult

        path, _, _ = synthetic
        fil = read_filterbank(path)
        common = dict(dm_end=60.0, nharmonics=2, npdmp=4, limit=50)
        full = PeasoupSearch(SearchConfig(**common)).run(fil)
        ndm = len(full.dm_list)

        parts = []
        for pid in range(2):
            lo, hi = dm_slice_for_process(ndm, 2, pid)
            search = PeasoupSearch(SearchConfig(**common))
            parts.append(
                (search, search.run(fil, dm_slice=(lo, hi), finalize=False))
            )
        assert [len(p.dm_list) for _, p in parts] == [ndm - ndm // 2, ndm // 2]

        # merge + finalize from each process's point of view. The real
        # flow allgathers fold outcomes concurrently; sequentially we
        # harvest each process's local outcomes in a first pass, then
        # finalize for real with the pooled set (pickled like the real
        # DCN allgather). distill mutates candidates, so every finalize
        # gets a fresh deep copy of the merged list.
        merged_cands = [c for _, p in parts for c in p.cands]

        def make_merged(part):
            return PartialSearchResult(
                cands=pickle.loads(pickle.dumps(merged_cands)),
                trials=part.trials,
                trials_nsamps=part.trials_nsamps,
                dm_offset=part.dm_offset,
                dm_list=full.dm_list,
                acc_list_dm0=part.acc_list_dm0,
                timers=dict(part.timers),
                nsamps=part.nsamps,
                size=part.size,
                n_accel_trials=sum(p.n_accel_trials for _, p in parts),
                t_total_start=part.t_total_start,
            )

        harvested: list[list] = []
        for search, part in parts:
            search.finalize(
                fil, make_merged(part),
                fold_exchange=lambda o: harvested.append(
                    pickle.loads(pickle.dumps(o))
                ) or o,
            )
        pooled = [o for out in harvested for o in out]

        results = [
            search.finalize(
                fil, make_merged(part), fold_exchange=lambda o: pooled
            )
            for search, part in parts
        ]

        assert full.n_accel_trials == results[0].n_accel_trials
        for res in results:
            assert len(res.candidates) == len(full.candidates) > 0
            for a, b in zip(full.candidates, res.candidates):
                assert a.freq == b.freq and a.snr == b.snr
                assert a.dm == b.dm and a.dm_idx == b.dm_idx
                assert a.folded_snr == b.folded_snr
                assert a.opt_period == b.opt_period

    def test_subband_dedispersion_recovers_pulsar(self, synthetic):
        """The two-stage subband path must find the same pulsar; with
        smear 0 its trials — and hence candidates — are exactly the
        direct path's."""
        path, period, dm = synthetic
        fil = read_filterbank(path)
        common = dict(dm_end=60.0, nharmonics=2, npdmp=0, limit=50)
        direct = PeasoupSearch(SearchConfig(**common)).run(fil)
        exact = PeasoupSearch(
            SearchConfig(subbands=4, subband_smear=0.0, **common)
        ).run(fil)
        assert len(exact.candidates) == len(direct.candidates) > 0
        for a, b in zip(direct.candidates, exact.candidates):
            assert a.freq == b.freq and a.snr == b.snr and a.dm == b.dm
        # with smear allowed the pulsar must still be found; DM
        # localisation may wash out a little on this tiny 16-channel
        # band (1-sample smear vs an 8-sample pulse is coarse — real
        # survey bands have far smaller per-subband spans)
        smeared = PeasoupSearch(
            SearchConfig(subbands=4, subband_smear=1.0, **common)
        ).run(fil)
        top = smeared.candidates[0]
        ratio = (1.0 / top.freq) / period
        assert min(abs(ratio - r) for r in (0.5, 1.0, 2.0)) < 0.01
        assert top.snr > 10 and abs(top.dm - dm) < 30.0

    def test_empty_dm_slice(self, synthetic):
        """More processes than DM trials: an empty slice must yield an
        empty partial (no device work, no crash) that finalizes to zero
        candidates."""
        path, _, _ = synthetic
        fil = read_filterbank(path)
        cfg = SearchConfig(dm_end=5.0, nharmonics=1, npdmp=2)
        search = PeasoupSearch(cfg)
        ndm = search.build_dm_plan(fil).ndm
        part = search.run(fil, dm_slice=(ndm, ndm), finalize=False)
        assert part.cands == [] and part.n_accel_trials == 0
        res = search.finalize(fil, part)
        assert res.candidates == []

    def test_sharded_search_matches_single_device(self, synthetic):
        """The full driver on an 8-chip 'dm' mesh must produce the same
        candidate list as the single-device path."""
        if len(jax.devices()) < 8:
            pytest.skip("need 8 devices")
        path, _, _ = synthetic
        fil = read_filterbank(path)
        common = dict(dm_end=40.0, nharmonics=2, npdmp=0, limit=100)
        single = PeasoupSearch(SearchConfig(**common)).run(fil)
        sharded = PeasoupSearch(
            SearchConfig(shard_devices=8, **common)
        ).run(fil)
        assert len(single.candidates) == len(sharded.candidates) > 0
        for a, b in zip(single.candidates, sharded.candidates):
            assert a.freq == b.freq and a.snr == b.snr
            assert a.dm == b.dm and a.acc == b.acc and a.nh == b.nh

    def test_sharded_search_with_unsharded_trials(self, synthetic):
        """Mesh active but trials from a single-device engine (the
        subband path bypasses dedisperse_sharded): the chunk dispatch
        must stage rows onto the mesh, not assume mesh-sharded trials."""
        if len(jax.devices()) < 8:
            pytest.skip("need 8 devices")
        path, _, _ = synthetic
        fil = read_filterbank(path)
        common = dict(dm_end=40.0, nharmonics=2, npdmp=0, limit=100,
                      subbands=8, subband_smear=0.0)
        single = PeasoupSearch(SearchConfig(**common)).run(fil)
        sharded = PeasoupSearch(
            SearchConfig(shard_devices=8, **common)
        ).run(fil)
        assert len(single.candidates) == len(sharded.candidates) > 0
        for a, b in zip(single.candidates, sharded.candidates):
            assert a.freq == b.freq and a.snr == b.snr


class TestDistillers:
    def test_harmonic_distiller_absorbs(self):
        c1 = Candidate(freq=10.0, snr=50.0, nh=4)
        c2 = Candidate(freq=20.00001, snr=20.0, nh=4)  # 2nd harmonic
        c3 = Candidate(freq=13.7, snr=15.0, nh=4)  # unrelated
        out = HarmonicDistiller(1e-4, 16, keep_related=True).distill([c1, c2, c3])
        freqs = sorted(c.freq for c in out)
        assert freqs == [10.0, 13.7]
        kept = [c for c in out if c.freq == 10.0][0]
        assert kept.count_assoc() >= 1

    def test_harmonic_distiller_multiplicity(self):
        # freq ratio 1:1 matches (jj,kk)=(1,1),(2,2)... -> multiple appends
        c1 = Candidate(freq=10.0, snr=50.0, nh=2)
        c2 = Candidate(freq=10.0000001, snr=20.0, nh=2)
        out = HarmonicDistiller(1e-4, 16, keep_related=True).distill([c1, c2])
        assert len(out) == 1
        # (1,1),(2,2),(3,3),(4,4) within kk<=2^nh=4 -> 4 appends
        assert out[0].count_assoc() == 4

    def test_acceleration_distiller(self):
        tobs = 40.0
        c1 = Candidate(freq=10.0, snr=50.0, acc=0.0)
        c2 = Candidate(freq=10.0001, snr=20.0, acc=1.0)
        out = AccelerationDistiller(tobs, 1e-4, keep_related=True).distill([c1, c2])
        assert len(out) == 1
        assert out[0].snr == 50.0

    def test_dm_distiller(self):
        c1 = Candidate(freq=10.0, snr=50.0, dm_idx=3)
        c2 = Candidate(freq=10.0005, snr=20.0, dm_idx=4)
        c3 = Candidate(freq=11.0, snr=30.0, dm_idx=4)
        out = DMDistiller(1e-4, keep_related=True).distill([c1, c2, c3])
        assert sorted(c.freq for c in out) == [10.0, 11.0]

    def test_sort_by_snr_desc(self):
        cands = [Candidate(freq=1.0 + i, snr=float(i)) for i in range(5)]
        out = DMDistiller(1e-9, keep_related=False).distill(cands)
        snrs = [c.snr for c in out]
        assert snrs == sorted(snrs, reverse=True)


class TestScorer:
    def make(self):
        return CandidateScorer(tsamp=0.000064, cfreq=1400.0, foff=-0.39, bw=400.0)

    def test_adjacent_unique(self):
        s = self.make()
        c = Candidate(freq=10.0, snr=20.0, dm=10.0, dm_idx=5)
        s.score(c)
        assert c.is_adjacent  # no assoc -> "unique" -> adjacent true

    def test_adjacent_neighbour(self):
        s = self.make()
        c = Candidate(freq=10.0, snr=20.0, dm=10.0, dm_idx=5)
        c.append(Candidate(freq=10.0, snr=5.0, dm=11.0, dm_idx=6))
        c.append(Candidate(freq=10.0, snr=5.0, dm=30.0, dm_idx=20))
        s.score(c)
        assert c.is_adjacent

    def test_not_adjacent(self):
        s = self.make()
        c = Candidate(freq=10.0, snr=20.0, dm=10.0, dm_idx=5)
        c.append(Candidate(freq=10.0, snr=5.0, dm=60.0, dm_idx=30))
        s.score(c)
        assert not c.is_adjacent

    def test_ddm_ratios(self):
        s = self.make()
        c = Candidate(freq=10.0, snr=20.0, dm=10.0, dm_idx=5)
        c.append(Candidate(freq=10.0, snr=10.0, dm=10.1, dm_idx=6))  # inside
        c.append(Candidate(freq=10.0, snr=10.0, dm=90.0, dm_idx=40))  # outside
        s.score(c)
        assert c.ddm_count_ratio == pytest.approx(2 / 3)
        assert c.ddm_snr_ratio == pytest.approx(30 / 40)

    def test_is_physical_foff_sign_quirk(self):
        # foff < 0 makes the smear threshold negative -> always physical
        s = self.make()
        c = Candidate(freq=1000.0, snr=20.0, dm=10000.0, dm_idx=5)
        s.score(c)
        assert c.is_physical


class TestAccelDedupe:
    def test_identity_dedupe_bitwise_equal(self, synthetic):
        """Identity-trial dedupe must produce BITWISE the brute-force
        candidate list: at this scale every |a|<=5 trial's resample
        shift stays under half a sample, so the whole accel grid is one
        identity class."""
        path, _, _ = synthetic
        fil = read_filterbank(path)
        common = dict(
            dm_end=40.0, acc_start=-5.0, acc_end=5.0,
            acc_pulse_width=0.064, nharmonics=2, npdmp=0, limit=100,
        )
        brute = PeasoupSearch(
            SearchConfig(dedupe_accel=False, **common)
        ).run(fil)
        dedup = PeasoupSearch(
            SearchConfig(dedupe_accel=True, **common)
        ).run(fil)
        assert len(brute.candidates) == len(dedup.candidates) > 0
        for a, b in zip(brute.candidates, dedup.candidates):
            assert a.freq == b.freq and a.snr == b.snr
            assert a.dm == b.dm and a.acc == b.acc and a.nh == b.nh
            assert len(a.assoc) == len(b.assoc)

    def test_nonidentity_trials_not_deduped(self):
        from peasoup_tpu.pipeline.search import _dedupe_identity_accels

        # afs large enough to shift: no dedupe
        lists = [np.asarray([0.0, 1e5, 2e5], np.float32)]
        disp, maps = _dedupe_identity_accels(lists, 0.004, 1 << 18)
        assert maps[0] is None and len(disp[0]) == 3
        # tiny accs all collapse onto the first
        lists = [np.asarray([0.0, -5.0, 5.0], np.float32)]
        disp, maps = _dedupe_identity_accels(lists, 0.00032, 1 << 17)
        assert len(disp[0]) == 1 and list(maps[0]) == [0, 0, 0]
        # mixed: identity trials (0, +-5) collapse, the fast one stays
        lists = [np.asarray([0.0, -5.0, 1e6, 5.0], np.float32)]
        disp, maps = _dedupe_identity_accels(lists, 0.00032, 1 << 17)
        assert len(disp[0]) == 2 and list(maps[0]) == [0, 0, 1, 0]

    def test_identity_criterion_exact_boundary(self):
        """The dedupe criterion is the EXACT f32 condition
        |f32(af * max|quad|)| <= 0.5 (ADVICE r3: no heuristic margin) —
        accelerations just past the boundary must NOT dedupe, and any
        deduped af must replay to all-zero shifts through resample's
        exact f32 chain."""
        from peasoup_tpu.ops.resample import accel_factor
        from peasoup_tpu.pipeline.search import (
            _dedupe_identity_accels,
            _max_abs_quad_f32,
            _quad_f32,
        )

        size, tsamp = 1 << 17, 0.00032
        mq = float(_max_abs_quad_f32(size))
        # acc whose af sits at ~the 0.5 shift boundary
        acc_half = 0.5 / mq * 2.0 * 299792458.0 / tsamp
        for frac, expect_dedupe in [(0.95, True), (1.2, False)]:
            accs = np.asarray([0.0, frac * acc_half], np.float32)
            disp, maps = _dedupe_identity_accels([accs], tsamp, size)
            deduped = maps[0] is not None
            assert deduped == expect_dedupe, (frac, disp, maps)
            if deduped:
                af = np.float32(accel_factor(accs, tsamp)[1])
                assert not np.rint(af * _quad_f32(size)).any()

    def test_equivalence_class_grouping_matches_brute_force(self):
        """r4 (VERDICT item 9): trials whose ENTIRE rounded shift maps
        coincide collapse even when not identity. The grouping must
        match a brute-force all-pairs map comparison exactly."""
        from peasoup_tpu.ops.resample import accel_factor
        from peasoup_tpu.pipeline.search import (
            _dedupe_identity_accels, _quad_f32,
        )

        size, tsamp = 1 << 14, 0.000256
        quad = _quad_f32(size)

        def af_of(a):
            return np.float32(accel_factor(np.asarray([a]), tsamp)[0])

        def shift_map(a):
            return np.rint(af_of(a) * quad)

        # find a non-identity acc whose ULP-neighbour shares its map,
        # and one step where the maps differ — the test derives ground
        # truth itself, so the search cannot go stale
        base = 2.0e6
        assert shift_map(base).any(), "need a non-identity base trial"
        twin = base
        while True:
            twin = float(np.nextafter(np.float32(twin), np.float32(np.inf)))
            if af_of(twin) != af_of(base):
                break
        far = base * 1.5
        accs = np.asarray([0.0, far, base, -5.0, twin], np.float32)
        disp, maps = _dedupe_identity_accels([accs], tsamp, size)

        # brute-force classes over the full maps
        m = [shift_map(a) for a in accs]
        brute = np.full(len(accs), -1)
        nxt = 0
        for i in range(len(accs)):
            if brute[i] < 0:
                brute[i] = nxt
                for j in range(i + 1, len(accs)):
                    if brute[j] < 0 and np.array_equal(m[i], m[j]):
                        brute[j] = nxt
                nxt += 1
        if maps[0] is None:
            got = np.arange(len(accs))
        else:
            got = np.asarray(maps[0])
        # same-partition check (labels may differ): pairwise co-membership
        for i in range(len(accs)):
            for j in range(len(accs)):
                assert (got[i] == got[j]) == (brute[i] == brute[j]), (
                    i, j, got, brute,
                    [af_of(a) for a in accs],
                )
        # the dispatch list carries exactly one rep per brute class
        assert len(disp[0]) == nxt
        # identity pair (0, -5) must have collapsed
        assert got[0] == got[3]

    def test_equivalence_dedupe_bitwise_end_to_end(self, tmp_path):
        """A grid whose accel PLAN emits map-sharing (non-identity)
        neighbours: dedupe ON is bitwise brute force, and the dedupe
        must actually fire with a nonzero representative class."""
        from peasoup_tpu.ops.resample import accel_factor
        from peasoup_tpu.pipeline.search import (
            _dedupe_identity_accels, _quad_f32,
        )

        path, _, _ = make_synthetic_fil(tmp_path, nsamps=1 << 14)
        fil = read_filterbank(path)
        # alt_a ~ 24 m/s^2 (acc_pulse_width=0.016) over a narrow band
        # around 3e5 m/s^2: at fft size 2^13 those trials have shift
        # spans of ~2 samples and adjacent trials' expected map
        # difference is ~1 bin, so MANY neighbours share their entire
        # map (measured at these exact params: 86 trials -> 23
        # dispatched, 63 nonzero-map shares) while the grid stays
        # small enough for a CPU run
        common = dict(
            dm_end=5.0, acc_start=3.0e5, acc_end=3.02e5,
            acc_pulse_width=0.016, nharmonics=1, npdmp=0, limit=100,
        )
        brute = PeasoupSearch(
            SearchConfig(dedupe_accel=False, **common)
        ).run(fil)
        ded = PeasoupSearch(SearchConfig(dedupe_accel=True, **common)).run(fil)
        assert len(brute.candidates) == len(ded.candidates) > 0
        for a, b in zip(brute.candidates, ded.candidates):
            assert a.freq == b.freq and a.snr == b.snr
            assert a.dm == b.dm and a.acc == b.acc and a.nh == b.nh
        # introspect: some non-identity class collapsed at this scale
        # (rebuild the search's accel lists the way run() does)
        from peasoup_tpu.plan.accel_plan import AccelerationPlan

        size = brute.size
        acc_plan = AccelerationPlan(
            acc_lo=common["acc_start"], acc_hi=common["acc_end"], tol=1.10,
            pulse_width=common["acc_pulse_width"], nsamps=size,
            tsamp=fil.tsamp, cfreq=fil.cfreq, bw=fil.foff,
        )
        plan = [
            acc_plan.generate_accel_list(float(dm)) for dm in brute.dm_list
        ]
        disp, maps = _dedupe_identity_accels(plan, fil.tsamp, size)
        quad = _quad_f32(size)
        fired = False
        for accs, emap in zip(plan, maps):
            if emap is None:
                continue
            emap = np.asarray(emap)
            for cls in np.unique(emap):
                members = np.nonzero(emap == cls)[0]
                if len(members) < 2:
                    continue
                af = np.float32(
                    accel_factor(np.asarray([accs[members[0]]]), fil.tsamp)[0]
                )
                if np.rint(af * quad).any():
                    fired = True
        assert fired, "expected a non-identity equivalence class"


class TestCheckpointProcessCount:
    def test_checkpoint_process_count_independent(self, tmp_path):
        """Satellite (documented contract in pipeline/checkpoint.py):
        trials completed under one process count resume under ANY
        other. Complete all trials under 2-way slicing, reload under
        1-way, and assert the union reuses every completed trial —
        then re-slice 3 ways and check each slice sees exactly its
        own trials with local keys."""
        from peasoup_tpu.parallel.multihost import dm_slice_for_process
        from peasoup_tpu.pipeline.checkpoint import SearchCheckpoint

        base = str(tmp_path / "search.ckpt")
        key = "config-key-A"
        ndm = 7

        def payload(g):
            return (
                np.full((2, 4), g, dtype=np.int32),
                np.full((4,), 0.5 * g, dtype=np.float32),
                np.asarray(g, dtype=np.int32),
            )

        # complete every trial under 2-way slicing: each process
        # writes its own .dmLO-HI sibling with LOCAL keys
        for pid in range(2):
            lo, hi = dm_slice_for_process(ndm, 2, pid)
            ck = SearchCheckpoint(base, key, slice_bounds=(lo, hi))
            ck.save({g - lo: payload(g) for g in range(lo, hi)})

        # reload under 1-way: the union must reuse every trial
        restored = SearchCheckpoint(base, key).load()
        assert sorted(restored) == list(range(ndm))
        for g in range(ndm):
            idxs, snrs, counts = restored[g]
            assert idxs[0, 0] == g
            assert snrs[0] == pytest.approx(0.5 * g)
            assert int(counts) == g

        # reload under 3-way: each slice sees exactly its trials,
        # re-keyed locally
        for pid in range(3):
            lo, hi = dm_slice_for_process(ndm, 3, pid)
            part = SearchCheckpoint(base, key, slice_bounds=(lo, hi)).load()
            assert sorted(k + lo for k in part) == list(range(lo, hi))
            for k, (idxs, _, _) in part.items():
                assert idxs[0, 0] == k + lo

        # a different config key restores nothing from any sibling
        assert SearchCheckpoint(base, "config-key-B").load() == {}


class TestCheckpointCorruption:
    def test_corrupt_store_discarded_with_warning(self, tmp_path, caplog):
        """Satellite (campaign retries depend on it): a truncated or
        garbage checkpoint file must degrade to "start over" with a
        warning — np.load raises zipfile.BadZipFile/EOFError here,
        well outside the old OSError/ValueError net."""
        import logging

        from peasoup_tpu.pipeline.checkpoint import SearchCheckpoint

        base = str(tmp_path / "search.ckpt")
        payload = {
            0: (
                np.zeros((2, 4), dtype=np.int32),
                np.zeros((4,), dtype=np.float32),
                np.asarray(0, dtype=np.int32),
            )
        }
        ck = SearchCheckpoint(base, "key")
        ck.save(payload)
        assert sorted(ck.load()) == [0]

        # truncate mid-zip: a worker SIGKILLed during a torn copy
        with open(base, "r+b") as f:
            f.truncate(20)
        with caplog.at_level(
            logging.WARNING, logger="peasoup_tpu.pipeline.checkpoint"
        ):
            assert ck.load() == {}
        assert any(
            "discarding unreadable checkpoint" in r.message
            for r in caplog.records
        )
        # unified resilience semantics: quarantined aside, not deleted
        import os

        assert os.path.exists(base + ".corrupt")
        assert not os.path.exists(base)

        # pure garbage (not even a zip): same contract
        with open(base, "wb") as f:
            f.write(b"\x00garbage" * 5)
        assert ck.load() == {}

        # and a fresh save over the damage fully recovers
        ck.save(payload)
        assert sorted(ck.load()) == [0]
