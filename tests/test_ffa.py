"""FFA search (ops/ffa.py, cli/ffa.py).

The reference advertises this pipeline (FFACmdLineOptions,
include/utils/cmdline.hpp:35-50) but its source is absent; these tests
validate our real implementation against brute-force folding oracles
and synthetic pulsar recovery.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from peasoup_tpu.ops.ffa import (
    duty_cycle_widths,
    ffa_search_series,
    ffa_transform,
)


class TestFFATransform:
    @pytest.mark.parametrize("m_pad,p0", [(4, 255), (8, 200)])
    def test_small_m_matches_linear_shift_oracle(self, m_pad, p0):
        """For small row counts the FFA's dyadic shift pattern equals
        the ideal linear fold round(i*j/(m-1)) for every row."""
        rng = np.random.default_rng(0)
        x = rng.normal(size=m_pad * p0).astype(np.float32)
        prof = np.asarray(ffa_transform(jnp.asarray(x), jnp.int32(p0), m_pad))
        rows = x.reshape(m_pad, p0)
        for j in range(m_pad):
            acc = np.zeros(p0, np.float32)
            for i in range(m_pad):
                sh = int(round(i * j / (m_pad - 1.0)))
                acc += np.roll(rows[i], -sh)
            np.testing.assert_allclose(prof[j, :p0], acc, rtol=5e-4, atol=1e-4)

    @pytest.mark.parametrize("m_pad,p0", [(16, 131), (32, 200)])
    def test_extreme_rows_exact(self, m_pad, p0):
        """Rows 0 and m-1 have exactly-linear shifts (0 and i) at ANY
        size; rows in between are the FFA's dyadic approximation."""
        rng = np.random.default_rng(0)
        x = rng.normal(size=m_pad * p0).astype(np.float32)
        prof = np.asarray(ffa_transform(jnp.asarray(x), jnp.int32(p0), m_pad))
        rows = x.reshape(m_pad, p0)
        np.testing.assert_allclose(
            prof[0, :p0], rows.sum(0), rtol=5e-4, atol=1e-4
        )
        acc = np.zeros(p0, np.float32)
        for i in range(m_pad):
            acc += np.roll(rows[i], -i)
        np.testing.assert_allclose(
            prof[m_pad - 1, :p0], acc, rtol=5e-4, atol=1e-4
        )

    def test_drifting_pulse_train_peaks_at_matching_row(self):
        """A noise-free pulse train at period p0 + j/(m-1) samples puts
        (nearly) all its power in one phase bin of row ~j."""
        p0, m = 128, 16
        for j in (0, 5, 15):
            period = p0 + j / (m - 1.0)
            n = p0 * m
            t = np.arange(n)
            x = (np.floor(t / period) != np.floor((t - 1) / period)).astype(
                np.float32
            )
            prof = np.asarray(
                ffa_transform(jnp.asarray(x), jnp.int32(p0), m)
            )
            npulses = int(n // period)
            best_row = int(np.argmax(prof[:, :p0].max(axis=1)))
            assert abs(best_row - j) <= 1, (j, best_row)
            assert prof[best_row, :p0].max() >= 0.8 * npulses

    def test_partial_final_row_zero_padded(self):
        rng = np.random.default_rng(1)
        p0, m_pad = 150, 8
        x = rng.normal(size=p0 * 7 + 40).astype(np.float32)  # 7.3 rows
        prof = np.asarray(ffa_transform(jnp.asarray(x), jnp.int32(p0), m_pad))
        assert np.isfinite(prof).all()
        # row 0 = plain fold of all complete+partial samples
        padded = np.zeros(m_pad * p0, np.float32)
        padded[: len(x)] = x
        np.testing.assert_allclose(
            prof[0, :p0], padded.reshape(m_pad, p0).sum(0), rtol=1e-5
        )


class TestFFASearch:
    def test_recovers_synthetic_pulsar(self):
        rng = np.random.default_rng(2)
        tsamp = 0.008
        n = 1 << 15
        t = np.arange(n) * tsamp
        P = 5.37
        x = rng.normal(0, 1, size=n).astype(np.float32)
        x += 8.0 * ((t % P) / P < 0.02)
        cands = ffa_search_series(x, tsamp, 0.8, 8.0, 0.01, snr_min=8.0)
        assert cands, "no candidates found"
        # the fundamental must be recovered; FFA also reports its
        # subharmonics (P/2, P/3, ...), which may outrank it
        match = [c for c in cands if abs(c.period - P) / P < 2e-3]
        assert match, [round(c.period, 3) for c in cands[:5]]
        assert match[0].snr > 8.0

    def test_no_false_alarms_in_noise(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=1 << 14).astype(np.float32)
        cands = ffa_search_series(x, 0.008, 0.8, 6.0, 0.01, snr_min=9.0)
        assert len(cands) <= 2  # pure noise: at most stray near-threshold

    def test_duty_cycle_widths(self):
        assert duty_cycle_widths(0.001) == (1, 2, 4, 8, 16, 32, 64, 128)
        assert duty_cycle_widths(0.1) == (26, 52, 104)
        assert duty_cycle_widths(0.9) == (1,)


class TestFFACli:
    def test_end_to_end(self, tmp_path):
        from peasoup_tpu.cli.ffa import main
        from peasoup_tpu.io import write_filterbank
        from peasoup_tpu.io.sigproc import Filterbank, SigprocHeader

        rng = np.random.default_rng(4)
        nsamps, nchans = 1 << 14, 8
        tsamp = 0.016
        t = np.arange(nsamps) * tsamp
        P = 2.51
        pulse = 40.0 * ((t % P) / P < 0.03)
        data = np.clip(
            rng.normal(100, 6, size=(nsamps, nchans)) + pulse[:, None],
            0, 255,
        ).astype(np.uint8)
        hdr = SigprocHeader(
            source_name="fake", data_type=1, nchans=nchans, nbits=8,
            nifs=1, tsamp=tsamp, tstart=50000.0, fch1=1500.0, foff=-1.0,
        )
        path = str(tmp_path / "ffa.fil")
        write_filterbank(path, Filterbank(header=hdr, data=data))
        out = str(tmp_path / "out.xml")
        rc = main([
            "-i", path, "-o", out, "--dm_end", "10",
            "--p_start", "1.0", "--p_end", "8.0", "--min_dc", "0.01",
        ])
        assert rc == 0
        import xml.etree.ElementTree as ET

        root = ET.parse(out).getroot()
        periods = [
            float(c.find("period").text)
            for c in root.find("candidates")
        ]
        assert periods and any(abs(p - P) / P < 2e-3 for p in periods)
