"""Driver entry-point contract tests (CPU, 8 virtual devices)."""

import importlib.util
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def load_graft():
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_compiles_and_runs():
    mod = load_graft()
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.idxs.shape[0] == 5  # nharms+1 levels
    assert np.isfinite(np.asarray(out.snrs)).all()


@pytest.mark.parametrize("n", [8, 4, 1])
def test_dryrun_multichip(n):
    mod = load_graft()
    mod.dryrun_multichip(n)
