"""End-to-end recovery of an INJECTED accelerated pulsar (VERDICT r2
item 4): the only test that proves the resample chain recovers a known
nonzero acceleration, not merely that it is bitwise-equal to its oracle.

The injected signal is built to be exactly periodic AFTER resampling at
the injected acceleration factor: resample_kernelII reads
``out[i] = in[i + af*i*(i-N)]`` (kernels.cu:314-346), so the pulse
phase in the raw series follows the inverse map
``g^-1(j) ~ j - af*j*(j-N)`` (the quadratic's second-order term is
~1e-3 samples at this scale).  Each channel is delayed by the dedisp
whole-sample delay at the injected DM.
"""

import numpy as np
import pytest

import jax

from peasoup_tpu.io.sigproc import (
    Filterbank,
    SigprocHeader,
    read_filterbank,
    write_filterbank,
)
from peasoup_tpu.ops.resample import accel_factor
from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig
from peasoup_tpu.plan.accel_plan import AccelerationPlan
from peasoup_tpu.plan.dm_plan import DMPlan

NCHANS, TSAMP = 16, 0.004
FCH1, FOFF = 1500.0, -20.0
SIZE = 1 << 18
P_INJ, DM_INJ, ACC_INJ = 0.05003, 60.0, 12.0


@pytest.fixture(scope="module")
def acc_fil(tmp_path_factory):
    rng = np.random.default_rng(11)
    plan = DMPlan.create(SIZE + 64, NCHANS, TSAMP, FCH1, FOFF, 0.0, 100.0)
    nsamps = SIZE + plan.max_delay
    af = float(accel_factor(np.array([ACC_INJ]), TSAMP)[0])

    j = np.arange(nsamps, dtype=np.float64)
    ginv = j - af * j * (j - SIZE)
    pulse = (((ginv * TSAMP / P_INJ) % 1.0) < 0.08) * 12.0

    delays = np.rint(
        (np.float32(DM_INJ) * np.abs(plan.delays)).astype(np.float32)
    ).astype(int)
    data = rng.normal(100, 8, size=(nsamps, NCHANS))
    for c in range(NCHANS):
        src = np.clip(j - delays[c], 0, nsamps - 1).astype(int)
        data[:, c] += pulse[src]
    hdr = SigprocHeader(
        source_name="acc_pulsar", data_type=1, nchans=NCHANS, nbits=8,
        nifs=1, tsamp=TSAMP, tstart=50000.0, fch1=FCH1, foff=FOFF,
    )
    path = str(tmp_path_factory.mktemp("accfil") / "acc_pulsar.fil")
    write_filterbank(
        path,
        Filterbank(header=hdr, data=np.clip(data, 0, 255).astype(np.uint8)),
    )
    return path


def _config(**kw):
    base = dict(
        dm_end=100.0, acc_start=-30.0, acc_end=30.0, acc_pulse_width=834.0,
        nharmonics=2, npdmp=1, limit=50,
    )
    base.update(kw)
    return SearchConfig(**base)


def _assert_recovered(top):
    assert abs(1.0 / top.freq - P_INJ) / P_INJ < 1e-4, 1.0 / top.freq
    assert abs(top.dm - DM_INJ) < 10.0, top.dm
    plan = AccelerationPlan(
        acc_lo=-30.0, acc_hi=30.0, tol=1.10, pulse_width=834.0,
        nsamps=SIZE, tsamp=TSAMP,
        cfreq=FCH1 + (NCHANS / 2) * FOFF, bw=FOFF,
    )
    step = plan.step(top.dm)
    assert abs(top.acc - ACC_INJ) <= 1.5 * step, (top.acc, step)
    assert top.acc != 0.0  # the whole point: a nonzero trial won
    assert top.snr > 50.0, top.snr
    assert top.folded_snr > 15.0, top.folded_snr


def test_recovers_injected_acceleration(acc_fil):
    res = PeasoupSearch(_config()).run(read_filterbank(acc_fil))
    assert res.candidates
    _assert_recovered(res.candidates[0])


def test_recovers_injected_acceleration_sharded(acc_fil):
    """Same recovery through the mesh-sharded driver, bitwise-equal to
    the single-device result."""
    if len(jax.devices()) < 8:
        pytest.skip("need 8 devices")
    fil = read_filterbank(acc_fil)
    single = PeasoupSearch(_config(npdmp=0)).run(fil)
    sharded = PeasoupSearch(_config(npdmp=0, shard_devices=8)).run(fil)
    assert len(single.candidates) == len(sharded.candidates) > 0
    for a, b in zip(single.candidates, sharded.candidates):
        assert a.freq == b.freq and a.snr == b.snr
        assert a.dm == b.dm and a.acc == b.acc and a.nh == b.nh
    top = sharded.candidates[0]
    assert abs(1.0 / top.freq - P_INJ) / P_INJ < 1e-4
    assert top.acc != 0.0
