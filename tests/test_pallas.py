"""Interpret-mode parity tests for the Pallas TPU kernels vs their
pure-jnp twins (which are themselves oracle-tested in test_ops.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from peasoup_tpu.ops.pallas.resample import (
    choose_block,
    resample_block,
    resample_block_pallas,
)
from peasoup_tpu.ops.resample import accel_factor, resample_accel


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


class TestChooseBlock:
    def test_zero_accel_gives_max(self):
        assert choose_block(0.0, 1 << 20) == 2048

    def test_scales_down_with_slope(self):
        # af*N*blk <= 2 must hold for the returned block
        n = 1 << 20
        af = 1e-9
        blk = choose_block(af, n)
        assert blk >= 128 and af * n * blk <= 2.0

    def test_extreme_slope_rejects(self):
        assert choose_block(1e-3, 1 << 23) == 0

    def test_tiny_n_rejects(self):
        assert choose_block(0.0, 128) == 0


class TestResamplePallas:
    @pytest.mark.parametrize("n,accs", [
        (4096, [0.0, 50.0, -50.0]),
        (16384, [5.0, -5.0, 125.5, -125.5]),
    ])
    def test_matches_jnp_twin_bitwise(self, rng, n, accs):
        tsamp = 256e-6
        x = rng.normal(size=(2, n)).astype(np.float32)
        afs = np.stack([
            accel_factor(np.asarray(accs), tsamp).astype(np.float32)
        ] * 2)
        af_max = float(np.abs(afs).max())
        blk = choose_block(af_max, n)
        assert blk > 0
        got = resample_block_pallas(
            jnp.asarray(x), jnp.asarray(afs), block=blk, interpret=True
        )
        want = jax.vmap(resample_accel)(jnp.asarray(x), jnp.asarray(afs))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("sign", [1.0, -1.0])
    @pytest.mark.parametrize("af_n_blk", [0.5, 1.0, 1.5, 2.0])
    def test_boundary_blocks_at_high_slope(self, rng, sign, af_n_blk):
        """Regression: with af*N*blk near the precondition limit, the
        shift varies across the window margin in the FIRST block (af>0)
        and LAST block (af<0); a clamped-window design silently
        corrupted those blocks. Must stay bitwise equal to the twin."""
        n, blk = 4096, 512
        af = np.float32(sign * af_n_blk / (n * blk))
        x = rng.normal(size=(1, n)).astype(np.float32)
        afs = np.full((1, 1), af, dtype=np.float32)
        got = resample_block_pallas(
            jnp.asarray(x), jnp.asarray(afs), block=blk, interpret=True
        )
        want = jax.vmap(resample_accel)(jnp.asarray(x), jnp.asarray(afs))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_differing_afs_per_dm_row(self, rng):
        n = 4096
        x = rng.normal(size=(3, n)).astype(np.float32)
        afs = rng.uniform(-1e-7, 1e-7, size=(3, 4)).astype(np.float32)
        got = resample_block_pallas(
            jnp.asarray(x), jnp.asarray(afs), block=512, interpret=True
        )
        want = jax.vmap(resample_accel)(jnp.asarray(x), jnp.asarray(afs))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_dispatch_fallback_on_bad_shapes(self, rng):
        # N too small for any valid block: dispatcher must fall back to
        # the jnp twin, not raise
        n = 128
        x = rng.normal(size=(1, n)).astype(np.float32)
        afs = np.zeros((1, 2), dtype=np.float32)
        out = resample_block(
            jnp.asarray(x), jnp.asarray(afs), 0.0, interpret=True
        )
        want = jax.vmap(resample_accel)(jnp.asarray(x), jnp.asarray(afs))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))

    def test_dispatch_uses_pallas_when_valid(self, rng, monkeypatch):
        # outputs are bitwise identical either way, so assert the Pallas
        # kernel actually ran (a dispatch regression would otherwise be
        # invisible)
        import peasoup_tpu.ops.pallas.resample as mod

        calls = []
        real = mod.resample_block_pallas

        def spy(*args, **kw):
            calls.append(kw.get("block"))
            return real(*args, **kw)

        monkeypatch.setattr(mod, "resample_block_pallas", spy)
        n = 2048
        x = rng.normal(size=(1, n)).astype(np.float32)
        afs = np.full((1, 2), 1e-8, dtype=np.float32)
        out = resample_block(
            jnp.asarray(x), jnp.asarray(afs), 1e-8, interpret=True
        )
        assert calls, "dispatch did not take the Pallas path"
        want = jax.vmap(resample_accel)(jnp.asarray(x), jnp.asarray(afs))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


class TestBlockCoreParity:
    """search_block_core must equal vmap(search_trial_core), with and
    without the Pallas resample path."""

    def _inputs(self, rng, size=4096, d=3, a=4, nharms=2):
        from peasoup_tpu.pipeline.search import _level_windows

        t = np.arange(size)
        tims = np.stack([
            np.clip(
                rng.normal(30, 3, size=size)
                + 12.0 * (((t * 0.000256) / 0.032) % 1.0 < 0.1),
                0, 255,
            ) for _ in range(d)
        ]).astype(np.uint8)
        accs = np.linspace(-20.0, 20.0, a)
        afs = np.stack([
            accel_factor(accs, 0.000256).astype(np.float32)
        ] * d)
        zap = jnp.zeros(size // 2 + 1, dtype=bool)
        windows = jnp.asarray(_level_windows(size, nharms, 0.1, 1100.0, 0.000256))
        return jnp.asarray(tims), jnp.asarray(afs), zap, windows, nharms

    def test_block_core_matches_vmapped_trial_core(self, rng):
        from peasoup_tpu.pipeline.accel_search import (
            search_block_core,
            search_trial_core,
        )

        tims, afs, zap, windows, nharms = self._inputs(rng)
        kw = dict(
            threshold=6.0, size=tims.shape[1], nsamps_valid=tims.shape[1],
            nharms=nharms, max_peaks=64, pos5=8, pos25=80,
        )
        blocked = search_block_core(tims, afs, zap, windows, **kw)
        trial = jax.vmap(
            lambda t_, a_: search_trial_core(t_, a_, zap, windows, **kw)
        )(tims, afs)
        np.testing.assert_array_equal(np.asarray(blocked.idxs), np.asarray(trial.idxs))
        np.testing.assert_array_equal(np.asarray(blocked.snrs), np.asarray(trial.snrs))
        np.testing.assert_array_equal(np.asarray(blocked.counts), np.asarray(trial.counts))

    def test_block_core_pallas_matches_jnp(self, rng):
        from peasoup_tpu.pipeline.accel_search import search_block_core
        from peasoup_tpu.ops.pallas.resample import choose_block

        tims, afs, zap, windows, nharms = self._inputs(rng)
        af_max = float(np.abs(np.asarray(afs)).max())
        blk = choose_block(af_max, tims.shape[1])
        assert blk > 0
        kw = dict(
            threshold=6.0, size=tims.shape[1], nsamps_valid=tims.shape[1],
            nharms=nharms, max_peaks=64, pos5=8, pos25=80,
        )
        plain = search_block_core(tims, afs, zap, windows, **kw)
        pallas = search_block_core(
            tims, afs, zap, windows, **kw,
            pallas_block=blk, pallas_interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(plain.idxs), np.asarray(pallas.idxs))
        np.testing.assert_array_equal(np.asarray(plain.snrs), np.asarray(pallas.snrs))
        np.testing.assert_array_equal(np.asarray(plain.counts), np.asarray(pallas.counts))


class TestPallasPeaks:
    """Fused threshold+compact+cluster kernel (ops/pallas/peaks.py) vs
    the jnp find_peaks_device + cluster_peaks_device pair, interpret
    mode. Covers sparse/dense crossings, window edges, multi-level
    tables, cluster overflow, and row/bin padding."""

    def test_fuzz_parity(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops.pallas.peaks import find_cluster_peaks_pallas
        from peasoup_tpu.ops.peaks import (
            cluster_peaks_device,
            find_peaks_device,
        )

        rng = np.random.default_rng(7)
        for trial in range(6):
            rows = int(rng.integers(1, 5))
            n = int(rng.integers(600, 9000))
            dense = rng.random() < 0.4
            s = (rng.normal(size=(rows, n)).astype(np.float32) ** 2) * (
                3.0 if dense else 1.0
            )
            thr = 6.0
            nlev = 3
            windows = np.stack(
                [
                    [int(rng.integers(0, n // 3)),
                     int(rng.integers(n // 2, n + 1))]
                    for _ in range(nlev)
                ]
            ).astype(np.int32)
            lvl = int(rng.integers(0, nlev))
            mx = 32
            sp = jnp.asarray(s)
            ci, cs, rc, cc = find_cluster_peaks_pallas(
                sp, jnp.asarray(windows), lvl,
                threshold=thr, max_peaks=mx, interpret=True,
            )
            i_, s_, c_ = find_peaks_device(
                sp, jnp.float32(thr), jnp.int32(windows[lvl, 0]),
                jnp.int32(windows[lvl, 1]), max_peaks=1 << 13,
            )
            ji, js, jc = cluster_peaks_device(i_, s_, jnp.int32(n))
            ci, cs, rc, cc = map(np.asarray, (ci, cs, rc, cc))
            ji, js, jc, c_ = map(np.asarray, (ji, js, jc, c_))
            np.testing.assert_array_equal(rc, c_)
            np.testing.assert_array_equal(cc, jc)
            for r in range(rows):
                k = min(int(jc[r]), mx)
                np.testing.assert_array_equal(ci[r, :k], ji[r, :k])
                np.testing.assert_array_equal(cs[r, :k], js[r, :k])
                if int(jc[r]) <= mx:
                    assert (ci[r, k:] == n).all()
                    assert (cs[r, k:] == 0).all()

    def test_block_core_pallas_peaks_matches_jnp(self):
        import jax.numpy as jnp

        from peasoup_tpu.pipeline.accel_search import search_block_core
        from peasoup_tpu.pipeline.search import _level_windows
        import peasoup_tpu.ops.pallas.peaks as ppk

        rng = np.random.default_rng(3)
        size, nharms = 2048, 2
        d, a = 2, 3
        t = np.arange(size)
        tims = jnp.asarray(
            np.clip(
                rng.normal(30, 3, size=(d, size))
                + 12.0 * (((t * 0.000256) / 0.016) % 1.0 < 0.08),
                0, 255,
            ).astype(np.uint8)
        )
        afs = jnp.asarray(np.zeros((d, a), np.float32))
        zap = jnp.zeros(size // 2 + 1, bool)
        windows = jnp.asarray(_level_windows(size, nharms, 0.1, 1100.0, 0.000256))
        kw = dict(
            threshold=6.0, size=size, nsamps_valid=size, nharms=nharms,
            max_peaks=64, pos5=8, pos25=80,
        )
        plain = search_block_core(tims, afs, zap, windows, **kw)
        # route the kernel through interpret mode for the CPU test
        # (production now uses the merged multi-level kernel)
        orig = ppk._build_multi.__wrapped__

        def interp_build(*args):
            return orig(*args[:-1], True)

        ppk._build_multi.cache_clear()
        ppk._build_multi = interp_build
        try:
            fused = search_block_core(
                tims, afs, zap, windows, **kw, pallas_peaks=True
            )
        finally:
            import functools
            ppk._build_multi = functools.lru_cache(maxsize=None)(orig)
        np.testing.assert_array_equal(
            np.asarray(plain.idxs), np.asarray(fused.idxs)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.snrs), np.asarray(fused.snrs)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.counts), np.asarray(fused.counts)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.ccounts), np.asarray(fused.ccounts)
        )


class TestPeaksPaddedLevels:
    def test_padded_garbage_tail_masked(self):
        """The production input configuration: block-aligned levels with
        a garbage tail past the true nbins plus the explicit nbins
        override — the kernel must mask the tail (window clamp) and pad
        idx slots with the TRUE nbins sentinel."""
        import jax.numpy as jnp

        import peasoup_tpu.ops.pallas.peaks as ppk

        nbins, npad, rows = 1025, 4096, 8
        rng = np.random.default_rng(5)
        s = np.abs(rng.normal(size=(rows, nbins))).astype(np.float32)
        s[:, 100] = 30.0
        sp = jnp.asarray(
            np.pad(s, ((0, 0), (0, npad - nbins)), constant_values=1e9)
        )
        # window hi deliberately set PAST nbins: the clamp must cap it
        windows = jnp.asarray(np.asarray([[10, npad]], np.int32))
        orig = ppk._build_multi.__wrapped__
        ppk._build_multi.cache_clear()
        ppk._build_multi = lambda *a: orig(*a[:-1], True)  # interpret
        try:
            ci, cs, rc, cc = ppk.find_cluster_peaks_multi(
                [sp], windows, threshold=9.0, max_peaks=16,
                scales=(1.0,), nbins=nbins,
            )
        finally:
            import functools

            ppk._build_multi = functools.lru_cache(maxsize=None)(orig)
        rc, cc, ci, cs = map(np.asarray, (rc, cc, ci, cs))
        assert (rc[:, 0] == 1).all(), rc[:, 0]  # only the planted peak
        assert (cc[:, 0] == 1).all()
        assert (ci[:, 0, 0] == 100).all()
        assert (ci[:, 0, 1:] == nbins).all()  # TRUE-nbins sentinel


class TestHarmPeaks:
    """Interpret-mode parity of the harmonic+peaks mega-kernel
    (ops/pallas/harmpeaks.py) against harmonic_sums(method="take") +
    the jnp find_peaks_device/cluster_peaks_device pair — BITWISE,
    including the in-VMEM one-hot gather accumulation, per-level
    scaling, garbage pad-tail masking, and row padding."""

    def _oracle_levels(self, s, nharms):
        import jax.numpy as jnp

        from peasoup_tpu.ops.harmonics import harmonic_sums

        return [jnp.asarray(s)] + harmonic_sums(
            jnp.asarray(s), nharms=nharms, method="take", scaled=True
        )

    @pytest.mark.parametrize("nharms,nbins,rows", [(4, 6000, 9), (2, 4500, 3)])
    def test_bitwise_vs_take_oracle(self, nharms, nbins, rows):
        import jax.numpy as jnp

        from peasoup_tpu.ops.pallas.harmpeaks import (
            find_harmonic_cluster_peaks,
        )
        from peasoup_tpu.ops.pallas.peaks import PEAKS_BLOCK
        from peasoup_tpu.ops.peaks import (
            cluster_peaks_device,
            find_peaks_device,
        )

        nlev = nharms + 1
        mx = 64
        rng = np.random.default_rng(0)
        s = np.abs(rng.normal(size=(rows, nbins))).astype(np.float32)
        s[::3, ::61] += 30.0
        s[min(1, rows - 1), nbins // 2 : nbins // 2 + 400 : 4] += 20.0
        lo, hi = nbins // 10, nbins - nbins // 16
        windows = np.tile(np.asarray([[lo, hi]], np.int32), (nlev, 1))
        npad = -(-nbins // PEAKS_BLOCK) * PEAKS_BLOCK
        # garbage past the true bins, like the fused-interbin pad region
        sp = jnp.asarray(
            np.pad(s, ((0, 0), (0, npad - nbins)), constant_values=1e9)
        )
        scales = tuple(
            1.0 if lv == 0 else 2.0 ** (-lv / 2.0) for lv in range(nlev)
        )
        ci, cs, rc, cc = find_harmonic_cluster_peaks(
            sp, jnp.asarray(windows), nharms=nharms, threshold=9.0,
            max_peaks=mx, scales=scales, nbins=nbins, interpret=True,
        )
        ci, cs, rc, cc = map(np.asarray, (ci, cs, rc, cc))
        levels = self._oracle_levels(s, nharms)
        for lv in range(nlev):
            i_, s_, c_ = find_peaks_device(
                levels[lv], jnp.float32(9.0), jnp.int32(lo), jnp.int32(hi),
                max_peaks=1 << 14,
            )
            ji, js, jc = cluster_peaks_device(i_, s_, jnp.int32(nbins))
            ji, js, jc, c_ = map(np.asarray, (ji, js, jc, c_))
            np.testing.assert_array_equal(rc[:, lv], c_)
            np.testing.assert_array_equal(cc[:, lv], jc)
            for r in range(rows):
                k = min(int(jc[r]), mx)
                np.testing.assert_array_equal(ci[r, lv, :k], ji[r, :k])
                np.testing.assert_array_equal(cs[r, lv, :k], js[r, :k])
                if int(jc[r]) <= mx:
                    assert (ci[r, lv, k:] == nbins).all()
                    assert (cs[r, lv, k:] == 0).all()

    def test_batched_shape_and_validation(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops.pallas.harmpeaks import (
            find_harmonic_cluster_peaks,
        )
        from peasoup_tpu.ops.pallas.peaks import PEAKS_BLOCK

        rng = np.random.default_rng(3)
        nbins = PEAKS_BLOCK  # exactly one block, no separate pad
        s = np.abs(rng.normal(size=(2, 3, nbins))).astype(np.float32)
        s[..., 500] = 40.0
        windows = jnp.asarray(
            np.tile(np.asarray([[10, nbins]], np.int32), (3, 1))
        )
        ci, cs, rc, cc = find_harmonic_cluster_peaks(
            jnp.asarray(s), windows, nharms=2, threshold=9.0,
            max_peaks=8, scales=(1.0, 0.5, 0.25), interpret=True,
        )
        assert ci.shape == (2, 3, 3, 8) and rc.shape == (2, 3, 3)
        # the planted tone must be the top cluster everywhere on level 0
        assert (np.asarray(ci)[..., 0, 0] == 500).all()
        with pytest.raises(ValueError, match="multiple"):
            find_harmonic_cluster_peaks(
                jnp.asarray(s[..., : nbins - 4]), windows, nharms=2,
                threshold=9.0, max_peaks=8, scales=(1.0, 0.5, 0.25),
                interpret=True,
            )
        with pytest.raises(ValueError, match="levels"):
            find_harmonic_cluster_peaks(
                jnp.asarray(s), windows, nharms=3, threshold=9.0,
                max_peaks=8, scales=(1.0, 0.5, 0.25, 0.1), interpret=True,
            )


class TestPallasDedisperse:
    """Interpret-mode parity of the Pallas dedispersion kernel
    (ops/pallas/dedisperse.py) against the jnp scan."""

    def _delays(self, d, c, dm_max=60.0):
        from peasoup_tpu.plan.dm_plan import delay_table

        k = np.abs(delay_table(1400.0, -8.0, c, 0.000256))
        dms = np.linspace(0.0, dm_max, d)
        return np.rint(dms[:, None] * k[None, :]).astype(np.int32)

    # the large/odd-row cases cost ~15 s each in the interpreter; one
    # even and one odd geometry stay in the fast run, the rest ride
    # the slow marker (the kernel itself is identical across them)
    @pytest.mark.parametrize(
        "d,c,t",
        [
            (6, 16, 4096),
            pytest.param(24, 32, 8192, marks=pytest.mark.slow),
            (8, 16, 1500),
            pytest.param(9, 17, 3000, marks=pytest.mark.slow),
        ],
    )
    def test_matches_jnp_bitwise(self, rng, d, c, t):
        from peasoup_tpu.ops.dedisperse import dedisperse
        from peasoup_tpu.ops.pallas.dedisperse import dedisperse_pallas

        delays = self._delays(d, c)
        out_nsamps = t - int(delays.max())
        fil = rng.integers(0, 4, size=(t, c)).astype(np.uint8)
        kill = (rng.random(c) > 0.2).astype(np.int32)
        ref = dedisperse(fil, delays, kill, out_nsamps, scale=0.7)
        got = np.asarray(
            dedisperse_pallas(
                fil, delays, kill, out_nsamps, scale=0.7, interpret=True
            )
        )
        np.testing.assert_array_equal(ref, got)

    def test_unquantized_f32(self, rng):
        from peasoup_tpu.ops.dedisperse import dedisperse_block
        from peasoup_tpu.ops.pallas.dedisperse import dedisperse_pallas

        delays = self._delays(8, 16)
        t = 4096
        out_nsamps = t - int(delays.max())
        fil = rng.normal(10.0, 2.0, size=(t, 16)).astype(np.float32)
        ref = np.asarray(
            dedisperse_block(
                jnp.asarray(fil), jnp.asarray(delays),
                jnp.ones(16, jnp.float32), out_nsamps=out_nsamps,
                quantize=False,
            )
        )
        got = np.asarray(
            dedisperse_pallas(
                fil, delays, np.ones(16, np.int32), out_nsamps,
                quantize=False, interpret=True,
            )
        )
        np.testing.assert_array_equal(ref, got)

    def test_plan_spread(self):
        from peasoup_tpu.ops.pallas.dedisperse import _DT, plan_spread

        delays = self._delays(3 * _DT + 2, 16)
        s = plan_spread(delays)
        assert s >= 0
        # spread of any aligned chunk never exceeds the reported max
        for lo in range(0, delays.shape[0], _DT):
            blk = delays[lo : lo + _DT]
            assert int((blk.max(0) - blk.min(0)).max()) <= s


def _twin_tol(twin):
    # the PRODUCTION envelope (single source: ops/pallas/dftspec.py
    # twin_envelope, also used by probe_pallas_dftspec) so CI and the
    # on-TPU gate can't drift apart
    from peasoup_tpu.ops.pallas.dftspec import twin_envelope

    return twin_envelope(twin)


def _assert_per_bin_twin(got, twin):
    """Per-bin structural oracle (see dftspec.twin_envelope): the twin
    replays the kernel with the same term grouping, so the only
    legitimate deviation is FMA-contraction codegen (bitwise 0 when
    both compile fresh; measured max ~1.4e-5 of the envelope
    denominator when the persistent compile cache serves an executable
    built on a different host). The bound is per bin — a structural
    fault (shifted lanes, wrong carry, bad clamp) perturbs bins by
    O(rms), five orders above it, and fails every bin it breaks (see
    the negative tests)."""
    bad = np.abs(got - twin) > _twin_tol(twin)
    assert not bad.any(), (
        f"{bad.sum()} bins beyond the FMA-class envelope; "
        f"max dev {np.abs(got - twin).max()}"
    )


class TestPallasInterbin:
    """Fused untwist+interbin+normalise kernel (ops/pallas/interbin.py),
    interpret mode, against TWO oracles:

    1. per-bin vs untwist_interbin_normalise_twin — the kernel's grid
       walk replayed in pure jnp with the same term grouping, asserted
       at the FMA-codegen envelope (see _assert_per_bin_twin): every
       bin is checked tightly, so a structural fault that keeps some
       bins correct still fails all the bins it breaks.
    2. allclose vs the differently-grouped jnp chain (packed matmul
       rfft parts -> interbin -> normalise) — guards the twin+kernel
       pair against a shared formula bug.

    On-TPU the kernel is additionally gated BITWISE against the jnp
    chain itself (probe_pallas_interbin: 0 differing bins on v5e)."""

    def _case(self, r, n, block, seed=0):
        import jax.numpy as jnp

        from peasoup_tpu.ops.fft import (
            packed_dft_z, rfft_pow2_matmul_parts,
        )
        from peasoup_tpu.ops.pallas.interbin import (
            untwist_interbin_normalise, untwist_interbin_normalise_twin,
        )
        from peasoup_tpu.ops.spectrum import (
            form_interpolated_parts, normalise,
        )

        rng = np.random.default_rng(seed)
        m = n // 2
        npad = (m // block + 1) * block
        # a tone + noise so interbin's max() takes both branches
        t = np.arange(n)
        x = rng.normal(size=(r, n)) + 3.0 * np.sin(2 * np.pi * t * 0.1317)
        x = jnp.asarray(x.astype(np.float32))
        mean = jnp.asarray(rng.normal(size=r).astype(np.float32))
        std = jnp.asarray((0.5 + rng.random(r)).astype(np.float32))
        zr, zi = packed_dft_z(x)
        got = np.asarray(
            untwist_interbin_normalise(
                zr, zi, mean, std, npad=npad, block=block, interpret=True
            )
        )
        twin = np.asarray(
            untwist_interbin_normalise_twin(
                zr, zi, mean, std, npad=npad, block=block
            )
        )
        ref = np.asarray(
            normalise(
                form_interpolated_parts(*rfft_pow2_matmul_parts(x)),
                mean, std,
            )
        )
        assert got.shape == (r, npad)
        _assert_per_bin_twin(got, twin)
        np.testing.assert_allclose(
            got[:, : m + 1], ref, rtol=1e-5, atol=1e-5
        )
        assert not got[:, m + 1 :].any()

    def test_per_bin_vs_twin_and_close_to_chain(self):
        self._case(r=9, n=1 << 14, block=1024)

    def test_negative_lane_shift_fails_oracle(self):
        # the oracle must CATCH a structural fault: a kernel that came
        # back with every lane shifted by one (classic roll-lowering
        # bug) must not pass the bitwise-vs-twin assertion
        import jax.numpy as jnp

        from peasoup_tpu.ops.fft import packed_dft_z
        from peasoup_tpu.ops.pallas.interbin import (
            untwist_interbin_normalise, untwist_interbin_normalise_twin,
        )

        rng = np.random.default_rng(7)
        r, n, block = 8, 1 << 13, 1024
        m = n // 2
        npad = (m // block + 1) * block
        x = jnp.asarray(rng.normal(size=(r, n)).astype(np.float32))
        mean = jnp.asarray(rng.normal(size=r).astype(np.float32))
        std = jnp.asarray((0.5 + rng.random(r)).astype(np.float32))
        zr, zi = packed_dft_z(x)
        good = np.asarray(
            untwist_interbin_normalise(
                zr, zi, mean, std, npad=npad, block=block, interpret=True
            )
        )
        twin = np.asarray(
            untwist_interbin_normalise_twin(
                zr, zi, mean, std, npad=npad, block=block
            )
        )
        _assert_per_bin_twin(good, twin)
        bad = np.roll(good, 1, axis=1)
        # ... and the shift breaks MOST bins by far more than the
        # envelope, not a stray ULP
        assert (np.abs(bad - twin) > _twin_tol(twin)).mean() > 0.5

    def test_row_padding_and_multi_stripe(self):
        # r not a multiple of 8 exercises the row-pad path (std pads
        # with ones so no 0/0 NaNs leak); 17 rows = 3 stripes
        self._case(r=17, n=1 << 14, block=2048, seed=3)

    def test_block_equals_m_over_two(self):
        # two z blocks + one pure-pad block past the Nyquist
        self._case(r=8, n=1 << 13, block=2048, seed=5)

    def test_geometry_validation(self):
        import jax.numpy as jnp
        import pytest

        from peasoup_tpu.ops.pallas.interbin import (
            untwist_interbin_normalise,
        )

        z = jnp.zeros((8, 4096), jnp.float32)
        v = jnp.ones((8,), jnp.float32)
        with pytest.raises(ValueError):
            untwist_interbin_normalise(z, z, v, v, npad=4096, block=4096)
        with pytest.raises(ValueError):
            untwist_interbin_normalise(z, z, v, v, npad=8192, block=2560)


class TestPallasDftspec:
    """Fused four-step DFT + untwist + interbin + normalise kernel
    (ops/pallas/dftspec.py), interpret mode, against the same two-layer
    oracle design as probe_pallas_dftspec:

    1. per-bin vs dft_untwist_interbin_twin — the kernel's helpers
       (_stripe_dft_step1/_row_dft_tail/_row_spectrum) run outside
       Pallas with identical term
       grouping, asserted at the FMA-codegen envelope
       (_assert_per_bin_twin; bitwise when both compile fresh).
    2. accuracy class vs the exact Precision.HIGHEST einsum chain:
       per-bin |amp - amp_ref| / (|amp_ref| + rms) <= 1e-3 (the 3-pass
       bf16 split class; the max sits at untwist-cancellation bins).

    Geometry floor: n1 must be a multiple of 128, so the smallest legal
    series is n = 2^15 (m = 16384 = 128 x 128)."""

    def _data(self, r, n, seed=0):
        import jax.numpy as jnp

        from peasoup_tpu.ops.pallas.dftspec import oracle_data

        x, xe, xo, mean, std = oracle_data(n, r=r, seed=seed)
        return (
            x, jnp.asarray(xe), jnp.asarray(xo),
            jnp.asarray(mean), jnp.asarray(std),
        )

    def _case(self, r, n, npad, seed=0):
        import jax.numpy as jnp

        from peasoup_tpu.ops.fft import rfft_pow2_matmul_parts
        from peasoup_tpu.ops.pallas.dftspec import (
            dft_untwist_interbin, dft_untwist_interbin_twin,
        )
        from peasoup_tpu.ops.spectrum import (
            form_interpolated_parts, normalise,
        )

        from peasoup_tpu.ops.pallas.dftspec import (
            ACC_MAX_REL, ACC_Q999_REL, accuracy_rel,
        )

        m = n // 2
        x, xe, xo, mean, std = self._data(r, n, seed)
        got = np.asarray(
            dft_untwist_interbin(xe, xo, mean, std, npad=npad, interpret=True)
        )
        twin = np.asarray(
            dft_untwist_interbin_twin(xe, xo, mean, std, npad=npad)
        )
        assert got.shape == (r, npad)
        _assert_per_bin_twin(got, twin)
        ref = np.asarray(
            normalise(
                form_interpolated_parts(
                    *rfft_pow2_matmul_parts(jnp.asarray(x))
                ),
                mean, std,
            )
        )
        rel = accuracy_rel(got, ref, np.asarray(mean), np.asarray(std), m)
        assert float(rel.max()) <= ACC_MAX_REL
        assert float(np.quantile(rel, 0.999)) <= ACC_Q999_REL
        assert not got[:, m + 1 :].any()
        stdn = np.asarray(std)[:, None]
        meann = np.asarray(mean)[:, None]
        amp_r = ref * stdn + meann
        scale = np.sqrt((amp_r**2).mean(axis=1, keepdims=True))
        return got, amp_r, scale, stdn, meann

    def test_per_bin_vs_twin_and_accuracy_class(self):
        # n2 = n1 case (one stripe + row padding: r=9 -> rpad=16)
        self._case(r=9, n=1 << 15, npad=(1 << 14) + 128)

    def test_rectangular_n2_and_wide_pad(self):
        # n1=128, n2=256 and a pad several planes past the Nyquist
        self._case(r=4, n=1 << 16, npad=(1 << 15) + 1024, seed=3)

    def test_mirror_and_nyquist_edges(self):
        # bins 0, 1, m-1, m against an f64 rfft oracle: the k=0 wrap,
        # the carried column fixes, and the Nyquist (1,1) store are the
        # structurally distinct paths in the kernel
        got, _, scale, stdn, meann = self._case(
            r=8, n=1 << 15, npad=(1 << 14) + 128, seed=5
        )
        n = 1 << 15
        m = n // 2
        x, _, _, mean, std = self._data(8, n, seed=5)
        X = np.fft.rfft(x.astype(np.float64), axis=1)
        Xl = np.concatenate([np.zeros((8, 1)), X[:, :-1]], axis=1)
        amp64 = np.maximum(np.abs(X), np.sqrt(0.5) * np.abs(X - Xl))
        amp_g = got[:, : m + 1] * stdn + meann
        for k in (0, 1, m - 1, m):
            err = np.abs(amp_g[:, k] - amp64[:, k])
            assert (err <= 1e-3 * (np.abs(amp64[:, k]) + scale[:, 0])).all()

    def test_pre_shaped_planes_match_flat(self):
        # the zero-relayout producer path: (R, n1, n2) planes give
        # bitwise the same kernel output as the flat (R, m) form (the
        # reshape happens outside the pallas program either way)
        from peasoup_tpu.ops.pallas.dftspec import (
            dft_untwist_interbin, plane_factors,
        )

        n = 1 << 15
        m = n // 2
        npad = m + 128
        _, xe, xo, mean, std = self._data(8, n, seed=11)
        n1, n2 = plane_factors(m)
        flat = np.asarray(
            dft_untwist_interbin(xe, xo, mean, std, npad=npad, interpret=True)
        )
        shaped = np.asarray(
            dft_untwist_interbin(
                xe.reshape(8, n1, n2), xo.reshape(8, n1, n2),
                mean, std, npad=npad, interpret=True,
            )
        )
        np.testing.assert_array_equal(flat, shaped)
        import pytest

        with pytest.raises(ValueError):
            dft_untwist_interbin(
                xe.reshape(8, n2 // 2, n1 * 2), xo.reshape(8, n2 // 2, n1 * 2),
                mean, std, npad=npad, interpret=True,
            )

    def test_negative_lane_shift_fails_oracle(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops.pallas.dftspec import (
            dft_untwist_interbin, dft_untwist_interbin_twin,
        )

        n = 1 << 15
        _, xe, xo, mean, std = self._data(8, n, seed=7)
        npad = (n // 2) + 128
        good = np.asarray(
            dft_untwist_interbin(xe, xo, mean, std, npad=npad, interpret=True)
        )
        twin = np.asarray(
            dft_untwist_interbin_twin(xe, xo, mean, std, npad=npad)
        )
        _assert_per_bin_twin(good, twin)
        bad = np.roll(good, 1, axis=1)
        assert (np.abs(bad - twin) > _twin_tol(twin)).mean() > 0.5

    def test_geometry_validation(self):
        import jax.numpy as jnp
        import pytest

        from peasoup_tpu.ops.pallas.dftspec import (
            dft_untwist_interbin, dftspec_supported,
        )

        v = jnp.ones((8,), jnp.float32)
        # n1 = 64 < 128 for n = 2^14: below the geometry floor
        z = jnp.zeros((8, 1 << 13), jnp.float32)
        with pytest.raises(ValueError):
            dft_untwist_interbin(z, z, v, v, npad=(1 << 13) + 128)
        # npad not a multiple of n1
        z = jnp.zeros((8, 1 << 14), jnp.float32)
        with pytest.raises(ValueError):
            dft_untwist_interbin(z, z, v, v, npad=(1 << 14) + 100)
        # npad <= m
        with pytest.raises(ValueError):
            dft_untwist_interbin(z, z, v, v, npad=1 << 14)
        assert dftspec_supported(1 << 15, (1 << 14) + 128)
        assert not dftspec_supported(1 << 14, (1 << 13) + 128)
        # survey-scale m above _MAX_M must be REJECTED by the shape
        # gate (the driver falls back instead of raising at trace time)
        assert not dftspec_supported(1 << 21, (1 << 20) + 1024)
        assert not dftspec_supported((1 << 15) + 2, (1 << 14) + 128)
