"""Per-op numeric tests against tiny NumPy oracles (SURVEY.md §4)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from peasoup_tpu.ops import (
    dedisperse,
    dedisperse_block,
    form_power,
    form_interpolated,
    spectrum_stats,
    normalise,
    median_scrunch5,
    linear_stretch,
    running_median,
    deredden,
    birdie_mask,
    zap_birdies,
    resample_accel,
    resample_accel_quadratic,
    accel_factor,
    harmonic_sums,
    find_peaks_device,
    cluster_peaks,
    fold_time_series,
    fold_time_series_np,
    coincidence_mask,
)
from peasoup_tpu.ops.fold import fold_bins_np
from peasoup_tpu.ops.fold_optimise import FoldOptimiser, calculate_sn


class TestSpectrum:
    def test_form_power(self, rng):
        z = (rng.normal(size=64) + 1j * rng.normal(size=64)).astype(np.complex64)
        out = np.asarray(form_power(jnp.asarray(z)))
        np.testing.assert_allclose(out, np.abs(z), rtol=1e-6)

    def test_form_interpolated_oracle(self, rng):
        z = (rng.normal(size=64) + 1j * rng.normal(size=64)).astype(np.complex64)
        out = np.asarray(form_interpolated(jnp.asarray(z)))
        zl = np.concatenate([[0.0 + 0j], z[:-1]])
        oracle = np.sqrt(np.maximum(np.abs(z) ** 2, 0.5 * np.abs(z - zl) ** 2))
        np.testing.assert_allclose(out, oracle, rtol=1e-5)

    def test_form_interpolated_batched(self, rng):
        z = (rng.normal(size=(3, 32)) + 1j * rng.normal(size=(3, 32))).astype(
            np.complex64
        )
        out = np.asarray(form_interpolated(jnp.asarray(z)))
        assert out.shape == (3, 32)

    def test_stats_and_normalise(self, rng):
        x = rng.normal(loc=5.0, scale=2.0, size=4096).astype(np.float32)
        mean, rms, std = spectrum_stats(jnp.asarray(x))
        assert float(mean) == pytest.approx(x.mean(), rel=1e-5)
        assert float(rms) == pytest.approx(np.sqrt((x.astype(np.float64)**2).mean()), rel=1e-5)
        assert float(std) == pytest.approx(x.std(), rel=1e-3)
        out = np.asarray(normalise(jnp.asarray(x), mean, std))
        assert abs(out.mean()) < 1e-3
        assert out.std() == pytest.approx(1.0, rel=1e-3)


class TestRednoise:
    def test_median_scrunch5_oracle(self, rng):
        x = rng.normal(size=100).astype(np.float32)
        out = np.asarray(median_scrunch5(jnp.asarray(x)))
        oracle = np.median(x.reshape(20, 5), axis=-1)
        np.testing.assert_allclose(out, oracle, rtol=1e-6)

    def test_median_scrunch5_truncates(self, rng):
        x = rng.normal(size=103).astype(np.float32)
        out = np.asarray(median_scrunch5(jnp.asarray(x)))
        assert out.shape == (20,)  # tail of 3 ignored (kernels.cu:972-973)

    def test_linear_stretch_oracle(self):
        x = np.array([0.0, 1.0, 4.0, 9.0], dtype=np.float32)
        out = np.asarray(linear_stretch(jnp.asarray(x), 7))
        step = 3.0 / 6.0
        oracle = []
        for i in range(7):
            pos = i * step
            j = int(pos)
            frac = pos - j
            if frac > 1e-5:
                oracle.append(x[j] + frac * (x[j + 1] - x[j]))
            else:
                oracle.append(x[j])
        np.testing.assert_allclose(out, oracle, rtol=1e-6)

    def test_running_median_flat_spectrum(self, rng):
        # a flat(ish) spectrum should produce a median near its level
        x = rng.normal(loc=10.0, scale=0.1, size=5**4).astype(np.float32)
        med = np.asarray(running_median(jnp.asarray(x), pos5=20, pos25=100))
        assert med.shape == x.shape
        np.testing.assert_allclose(med, 10.0, atol=0.5)

    def test_deredden_zeroes_first_bins(self, rng):
        z = (rng.normal(size=32) + 1j * rng.normal(size=32)).astype(np.complex64)
        med = np.full(32, 2.0, dtype=np.float32)
        out = np.asarray(deredden(jnp.asarray(z), jnp.asarray(med)))
        np.testing.assert_array_equal(out[:5], 0.0)
        np.testing.assert_allclose(out[5:], z[5:] / 2.0, rtol=1e-6)

    def test_running_median_tracks_red_noise(self, rng):
        # red-noise-like 1/f ramp: median should follow the ramp closely
        n = 5**5
        ramp = (1.0 + 100.0 / (np.arange(n) + 10)).astype(np.float32)
        noise = rng.normal(loc=1.0, scale=0.02, size=n).astype(np.float32)
        x = ramp * noise
        med = np.asarray(running_median(jnp.asarray(x), pos5=50, pos25=500))
        sel = slice(10, n - 200)  # away from edges
        np.testing.assert_allclose(med[sel] / ramp[sel], 1.0, atol=0.15)


class TestZap:
    def test_birdie_mask_ranges(self):
        mask = birdie_mask(np.array([10.0]), np.array([1.0]), 1.0, 64)
        # bins [floor(9), ceil(11)) = [9, 11)
        assert mask[9] and mask[10] and not mask[11] and not mask[8]

    def test_birdie_mask_clip_top_quirk(self):
        # clipped at the top: high becomes nbins-1, half-open range stops
        # at nbins-2 (kernels.cu:1054-1056)
        mask = birdie_mask(np.array([63.5]), np.array([5.0]), 1.0, 64)
        assert mask[62] and not mask[63]

    def test_zap_birdies(self, rng):
        z = (rng.normal(size=16) + 1j * rng.normal(size=16)).astype(np.complex64)
        mask = np.zeros(16, dtype=bool)
        mask[3:6] = True
        out = np.asarray(zap_birdies(jnp.asarray(z), jnp.asarray(mask)))
        np.testing.assert_array_equal(out[3:6], 1.0 + 0.0j)
        np.testing.assert_array_equal(out[~mask], z[~mask])


class TestResample:
    def test_zero_accel_identity(self, rng):
        x = rng.normal(size=1024).astype(np.float32)
        out = np.asarray(resample_accel(jnp.asarray(x), jnp.zeros(1, np.float32)))
        np.testing.assert_array_equal(out[0], x)

    def test_matches_f64_oracle(self, rng):
        n = 4096
        x = (np.arange(n) % 451).astype(np.float32)  # reference test pattern
        for a in (125.5, -125.5, 10.0):
            af = accel_factor(np.array([a]), tsamp=0.000064)
            out = np.asarray(
                resample_accel(jnp.asarray(x), jnp.asarray(af, dtype=jnp.float32))
            )[0]
            idx = np.arange(n, dtype=np.float64)
            src = np.rint(idx + af[0] * idx * (idx - n)).astype(np.int64)
            src = np.clip(src, 0, n - 1)
            oracle = x[src]
            # f32 index math may differ from f64 at round-to-half ties only
            mismatches = np.mean(out != oracle)
            assert mismatches < 1e-3

    def test_large_accel_visible_shift(self):
        n = 1 << 16
        x = np.zeros(n, dtype=np.float32)
        x[n // 2] = 1.0
        af = np.array([2e-9])  # shift at midpoint = af*n^2/4 ~ 2.1 samples
        out = np.asarray(
            resample_accel(jnp.asarray(x), jnp.asarray(af, dtype=jnp.float32))
        )[0]
        idx = np.arange(n, dtype=np.float64)
        src = np.clip(np.rint(idx + af[0] * idx * (idx - n)), 0, n - 1).astype(int)
        oracle = x[src]
        np.testing.assert_array_equal(out, oracle)
        assert out[n // 2] == 0.0  # midpoint now reads ~2 samples ahead
        assert out.sum() >= 1.0

    def test_quadratic_variant_zero_at_midpoint_shift(self, rng):
        n = 1024
        x = rng.normal(size=n).astype(np.float32)
        out = np.asarray(
            resample_accel_quadratic(jnp.asarray(x), jnp.float32(0.0))
        )
        np.testing.assert_array_equal(out, x)


class TestHarmonics:
    @staticmethod
    def oracle(p, nharms):
        n = len(p)
        outs = []
        val = p.astype(np.float64).copy()
        for h in range(1, nharms + 1):
            for k in range(1, 2 ** h, 2):
                idx = (np.arange(n) * k + 2 ** (h - 1)) >> h
                val = val + p[idx]
            outs.append(val * 2.0 ** (-h / 2.0))
        return outs

    def test_matches_float_index_oracle(self, rng):
        p = rng.normal(size=1000).astype(np.float32)
        outs = harmonic_sums(jnp.asarray(p), nharms=5)
        # cross-check integer index map == float index map of the kernel
        n = len(p)
        for h in range(1, 6):
            for k in range(1, 2 ** h, 2):
                int_idx = (np.arange(n) * k + 2 ** (h - 1)) >> h
                float_idx = (np.arange(n) * (k / 2 ** h) + 0.5).astype(np.int64)
                np.testing.assert_array_equal(int_idx, float_idx)
        oracles = self.oracle(p, 5)
        for out, oracle in zip(outs, oracles):
            # f32 accumulation vs f64 oracle
            np.testing.assert_allclose(np.asarray(out), oracle, rtol=1e-4, atol=1e-5)

    def test_block_align_bitwise_below_nbins(self, rng):
        """block_align levels are padded past nbins (garbage tail) but
        BITWISE identical to the unpadded result below it, unscaled and
        scaled alike."""
        p = rng.normal(size=(3, 1025)).astype(np.float32)
        plain = harmonic_sums(jnp.asarray(p), nharms=4, scaled=False)
        padded = harmonic_sums(
            jnp.asarray(p), nharms=4, scaled=False, block_align=4096
        )
        assert padded[0].shape[-1] == 4096
        for a, b in zip(plain, padded):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)[..., :1025]
            )

    def test_impulse_train_gains(self):
        # fundamental at bin 512 with harmonics at 256, 128, ...: the
        # harmonic sum at the fundamental grows as expected
        p = np.zeros(1024, dtype=np.float32)
        for b in (512, 256, 128, 64, 32):
            p[b] = 1.0
        outs = harmonic_sums(jnp.asarray(p), nharms=4)
        assert float(outs[0][512]) == pytest.approx(2 / np.sqrt(2))
        assert float(outs[3][512]) == pytest.approx(5 / 4.0)

    def test_batched(self, rng):
        p = rng.normal(size=(3, 256)).astype(np.float32)
        outs = harmonic_sums(jnp.asarray(p), nharms=2)
        assert outs[0].shape == (3, 256)
        single = harmonic_sums(jnp.asarray(p[1]), nharms=2)
        np.testing.assert_allclose(np.asarray(outs[0][1]), np.asarray(single[0]))

    @pytest.mark.parametrize("nbins", [96, 256, 1000, 4097])
    @pytest.mark.parametrize("method", ["mxu", "conv"])
    def test_matmul_methods_match_take_bitwise(self, rng, nbins, method):
        """The one-hot matmul/conv formulations must reproduce the
        direct gather EXACTLY (one-hot taps -> exact values; zero adds
        are exact; reference summation order preserved), on awkward
        non-multiple-of-32 sizes too."""
        p = rng.normal(size=(2, nbins)).astype(np.float32)
        got = harmonic_sums(jnp.asarray(p), nharms=5, method=method)
        take = harmonic_sums(jnp.asarray(p), nharms=5, method="take")
        for a, b in zip(got, take):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("nbins", [96, 1000, 4097])
    def test_fused_matches_take_to_ulp(self, rng, nbins):
        """"fused" sums each level's gathers in the MXU accumulator
        instead of one at a time — equal to "take" up to f32
        summation-order ULPs."""
        p = rng.normal(size=(2, nbins)).astype(np.float32)
        fused = harmonic_sums(jnp.asarray(p), nharms=5, method="fused")
        take = harmonic_sums(jnp.asarray(p), nharms=5, method="take")
        for a, b in zip(fused, take):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-6
            )


class TestPeaks:
    def test_device_compaction(self):
        spec = np.zeros(256, dtype=np.float32)
        spec[[10, 50, 51, 200]] = [5.0, 7.0, 6.0, 9.0]
        idxs, snrs, count = find_peaks_device(
            jnp.asarray(spec), 4.0, 0, 256, max_peaks=16
        )
        idxs, snrs = np.asarray(idxs), np.asarray(snrs)
        assert int(count) == 4
        np.testing.assert_array_equal(idxs[:4], [10, 50, 51, 200])
        np.testing.assert_allclose(snrs[:4], [5.0, 7.0, 6.0, 9.0])
        assert np.all(idxs[4:] == 256)

    def test_window_applied(self):
        spec = np.full(128, 10.0, dtype=np.float32)
        idxs, snrs, count = find_peaks_device(
            jnp.asarray(spec), 4.0, 30, 40, max_peaks=32
        )
        assert int(count) == 10
        np.testing.assert_array_equal(np.asarray(idxs)[:10], np.arange(30, 40))

    def test_cluster_semantics(self):
        # two clusters: [100 (snr 5), 110 (snr 8), 120 (snr 6)], [200]
        idxs = np.array([100, 110, 120, 200])
        snrs = np.array([5.0, 8.0, 6.0, 7.0])
        pi, ps = cluster_peaks(idxs, snrs, 4, min_gap=30)
        np.testing.assert_array_equal(pi, [110, 200])
        np.testing.assert_allclose(ps, [8.0, 7.0])

    def test_cluster_lastidx_quirk(self):
        # lastidx only advances on a new max: 0(5), 20(4), 40(3) ->
        # 40-0 >= 30 breaks the cluster even though 40-20 < 30
        idxs = np.array([0, 20, 40])
        snrs = np.array([5.0, 4.0, 3.0])
        pi, ps = cluster_peaks(idxs, snrs, 3, min_gap=30)
        np.testing.assert_array_equal(pi, [0, 40])

    def test_batched_shapes(self, rng):
        spec = rng.normal(size=(4, 5, 128)).astype(np.float32)
        idxs, snrs, count = find_peaks_device(
            jnp.asarray(spec), 2.0, 0, 128, max_peaks=64
        )
        assert idxs.shape == (4, 5, 64)
        assert count.shape == (4, 5)

    def test_device_cluster_matches_host_fuzz(self, rng):
        """cluster_peaks_device is an exact on-device port of the host
        identify_unique_peaks walk (quirk included)."""
        from peasoup_tpu.ops.peaks import cluster_peaks_device

        nbins = 1500
        for _ in range(60):
            spec = np.abs(rng.normal(1, 1.0, size=nbins)).astype(np.float32)
            for _ in range(rng.integers(0, 6)):
                c = int(rng.integers(0, nbins))
                w = int(rng.integers(1, 70))
                spec[max(0, c - w // 2): c + w // 2] += rng.uniform(3, 9)
            idxs, snrs, count = find_peaks_device(
                jnp.asarray(spec), 4.0, 0, nbins, max_peaks=256
            )
            idxs, snrs, count = np.asarray(idxs), np.asarray(snrs), int(count)
            if count > 256:
                continue
            hi, hs = cluster_peaks(idxs, snrs, count)
            ci, cs, cc = cluster_peaks_device(
                jnp.asarray(idxs), jnp.asarray(snrs), jnp.int32(nbins)
            )
            cc = int(cc)
            assert cc == len(hi)
            np.testing.assert_array_equal(np.asarray(ci)[:cc], hi)
            np.testing.assert_allclose(np.asarray(cs)[:cc], hs)
            assert np.all(np.asarray(ci)[cc:] == nbins)

    def test_device_cluster_full_slots(self):
        """A completely full slot axis (no padding) still flushes the
        final cluster via the appended sentinel step."""
        from peasoup_tpu.ops.peaks import cluster_peaks_device

        idxs = np.arange(0, 400, 100, dtype=np.int32)  # 4 slots, all real
        snrs = np.array([5.0, 6.0, 7.0, 8.0], dtype=np.float32)
        ci, cs, cc = cluster_peaks_device(
            jnp.asarray(idxs), jnp.asarray(snrs), jnp.int32(1000)
        )
        assert int(cc) == 4
        np.testing.assert_array_equal(np.asarray(ci), idxs)


class TestDedisperse:
    def test_realigns_dispersed_impulse(self):
        t, c, true_delay = 256, 8, 4
        fil = np.zeros((t, c), dtype=np.uint8)
        t0 = 100
        for ch in range(c):
            fil[t0 + ch * true_delay // 2, ch] = 3  # linear-ish sweep
        delays = np.array(
            [[ch * true_delay // 2 for ch in range(c)]], dtype=np.int32
        )
        out = np.asarray(
            dedisperse_block(
                jnp.asarray(fil),
                jnp.asarray(delays),
                jnp.ones(c, jnp.int32),
                out_nsamps=t - int(delays.max()),
            )
        )
        assert out.shape == (1, t - delays.max())
        assert out[0, t0] == 3 * c  # all channels realigned
        assert (out[0] > 0).sum() <= c  # everything else near-empty

    def test_killmask(self):
        t, c = 64, 4
        fil = np.ones((t, c), dtype=np.uint8)
        kill = np.array([1, 0, 1, 0], dtype=np.int32)
        out = np.asarray(
            dedisperse_block(
                jnp.asarray(fil),
                jnp.zeros((1, c), jnp.int32),
                jnp.asarray(kill),
                out_nsamps=t,
            )
        )
        np.testing.assert_array_equal(out[0], 2)

    def test_blocked_host_wrapper_matches(self, rng):
        t, c, d = 128, 8, 7
        fil = rng.integers(0, 4, size=(t, c)).astype(np.uint8)
        delays = rng.integers(0, 16, size=(d, c)).astype(np.int32)
        out_nsamps = t - int(delays.max())
        got = dedisperse(fil, delays, np.ones(c, np.int32), out_nsamps, block=3)
        oracle = np.zeros((d, out_nsamps))
        for di in range(d):
            for ch in range(c):
                oracle[di] += fil[delays[di, ch] : delays[di, ch] + out_nsamps, ch]
        np.testing.assert_array_equal(got, np.clip(np.rint(oracle), 0, 255))

    def _plan_delays(self, d=24, c=32, dm_max=80.0):
        """A realistic monotone (D, C) delay table (cold-plasma law)."""
        from peasoup_tpu.plan.dm_plan import delay_table

        dms = np.linspace(0.0, dm_max, d).astype(np.float32)
        k = np.abs(delay_table(1400.0, -8.0, c, 0.000256))
        return np.rint(dms[:, None].astype(np.float64) * k[None, :]).astype(
            np.int32
        ), dms

    def test_subband_exact_at_zero_smear(self, rng):
        """max_smear=0 forces singleton groups, where the two-stage
        decomposition telescopes: t + d[ref] + (d[c] - d[ref]) = t + d[c]
        — bitwise equal to the direct path."""
        from peasoup_tpu.ops.dedisperse import dedisperse_subband

        delays, _ = self._plan_delays()
        t = 2048 + int(delays.max())
        c = delays.shape[1]
        fil = rng.integers(0, 4, size=(t, c)).astype(np.uint8)
        out_nsamps = t - int(delays.max())
        direct = dedisperse(fil, delays, np.ones(c, np.int32), out_nsamps)
        sub = dedisperse_subband(
            fil, delays, np.ones(c, np.int32), out_nsamps,
            nsub=8, max_smear=0.0, to_host=True,
        )
        np.testing.assert_array_equal(direct, sub)

    def test_subband_grouping_bounds_smear(self, rng):
        """Grouped trials may differ from direct, but only by shifts
        bounded by max_smear: the dispersed impulse must still realign
        to (near) full amplitude at every trial."""
        from peasoup_tpu.ops.dedisperse import (
            dedisperse_subband,
            subband_groups,
        )

        delays, _ = self._plan_delays()
        d, c = delays.shape
        groups = subband_groups(delays, nsub=8, max_smear=2.0)
        assert sum(hi - lo for lo, hi in groups) == d
        assert len(groups) < d  # actually grouped something

        # impulse dispersed at trial 13's exact delays
        t = 2048 + int(delays.max())
        fil = np.zeros((t, c), dtype=np.uint8)
        t0, di = 700, 13
        for ch in range(c):
            fil[t0 + delays[di, ch], ch] = 3
        out_nsamps = t - int(delays.max())
        sub = np.asarray(
            dedisperse_subband(
                fil, delays, np.ones(c, np.int32), out_nsamps,
                nsub=8, max_smear=2.0,
            )
        )
        # energy conserved and concentrated within the smear window
        window = sub[di, t0 - 3 : t0 + 4].astype(int)
        assert window.sum() == 3 * c
        assert sub[di].astype(int).sum() == 3 * c

    def test_subband_awkward_nsub(self, rng):
        """nsub values where ceil(C/ceil(C/nsub)) != nsub (e.g. 5 bands
        over 16 channels -> width 4 -> only 4 bands) must reduce to the
        effective band count, not crash."""
        from peasoup_tpu.ops.dedisperse import dedisperse_subband

        delays, _ = self._plan_delays(d=6, c=16)
        t = 512 + int(delays.max())
        fil = rng.integers(0, 4, size=(t, 16)).astype(np.uint8)
        out_nsamps = t - int(delays.max())
        direct = dedisperse(fil, delays, np.ones(16, np.int32), out_nsamps)
        for nsub in (5, 7, 16, 40):
            sub = dedisperse_subband(
                fil, delays, np.ones(16, np.int32), out_nsamps,
                nsub=nsub, max_smear=0.0, to_host=True,
            )
            np.testing.assert_array_equal(direct, sub)

    def test_channel_chunked_device_wrapper(self, rng):
        """Tiny chunk_bytes forces channel chunking (with a padded tail
        chunk) AND the DM-segment recursion; results must equal the
        unchunked path exactly for integer inputs."""
        from peasoup_tpu.ops.dedisperse import dedisperse_device

        t, c, d = 2048, 23, 21  # awkward: c % cc != 0, d % seg != 0
        fil = rng.integers(0, 4, size=(t, c)).astype(np.uint8)
        delays = np.sort(
            rng.integers(0, 99, size=(d, c)).astype(np.int32), axis=0
        )
        kill = (rng.random(c) > 0.2).astype(np.int32)
        out_nsamps = t - int(delays.max())
        ref = np.asarray(
            dedisperse_device(fil, delays, kill, out_nsamps, scale=0.5)
        )
        got = np.asarray(
            dedisperse_device(
                fil, delays, kill, out_nsamps, scale=0.5,
                chunk_bytes=t * 4 * 5,  # 5 channels per chunk
                block=4,
            )
        )
        np.testing.assert_array_equal(ref, got)

    def test_spill_segments_match_device(self, rng):
        from peasoup_tpu.ops.dedisperse import dedisperse, dedisperse_device

        t, c, d = 1024, 8, 11
        fil = rng.integers(0, 4, size=(t, c)).astype(np.uint8)
        delays = np.sort(
            rng.integers(0, 64, size=(d, c)).astype(np.int32), axis=0
        )
        out_nsamps = t - int(delays.max())
        ref = np.asarray(
            dedisperse_device(fil, delays, np.ones(c, np.int32), out_nsamps)
        )
        got = dedisperse(fil, delays, np.ones(c, np.int32), out_nsamps,
                         block=4)
        np.testing.assert_array_equal(ref, got)

    def test_subband_killmask_and_scale(self, rng):
        from peasoup_tpu.ops.dedisperse import dedisperse_subband

        delays, _ = self._plan_delays(d=6, c=16)
        t = 1024 + int(delays.max())
        fil = rng.integers(0, 255, size=(t, 16)).astype(np.uint8)
        kill = (rng.random(16) > 0.3).astype(np.int32)
        out_nsamps = t - int(delays.max())
        a = dedisperse(fil, delays, kill, out_nsamps, scale=0.1)
        b = dedisperse_subband(
            fil, delays, kill, out_nsamps, nsub=4, max_smear=0.0,
            scale=0.1, to_host=True,
        )
        np.testing.assert_array_equal(a, b)


class TestFold:
    def test_matches_np_oracle(self, rng):
        n, nbins, nints = 4096, 32, 8
        x = rng.normal(size=n).astype(np.float32)
        period, tsamp = 0.025, 0.000064
        oracle = fold_time_series_np(x, n, tsamp, period, nbins, nints)
        flat = fold_bins_np(n, tsamp, period, nbins, nints)
        out = np.asarray(
            fold_time_series(
                jnp.asarray(x[: len(flat)]), jnp.asarray(flat), nbins=nbins, nints=nints
            )
        )
        np.testing.assert_allclose(out, oracle, rtol=1e-4)

    def test_count_bias(self):
        # constant input: output = sum/(hits+1) = hits/(hits+1) != 1
        n, nbins, nints = 1024, 16, 4
        x = np.ones(n, dtype=np.float32)
        out = fold_time_series_np(x, n, 0.000064, 0.001024, nbins, nints)
        assert np.all(out < 1.0)
        assert np.all(out > 0.5)

    def test_recovers_pulse_phase(self):
        n, nbins, nints = 1 << 15, 64, 8
        tsamp, period = 0.000064, 0.004096
        t = np.arange(n) * tsamp
        phase = (t / period) % 1.0
        x = (np.abs(phase - 0.25) < 0.02).astype(np.float32) * 10.0
        prof = fold_time_series_np(x, n, tsamp, period, nbins, nints).mean(axis=0)
        assert abs(int(np.argmax(prof)) - 16) <= 1  # 0.25 phase -> bin 16


class TestFoldOptimise:
    def make_fold(self, nbins=64, nints=16, drift_bins=6.0, width=4):
        """Pulse at drifting phase across subints (a slightly-wrong period)."""
        rng = np.random.default_rng(0)
        folds = rng.normal(0.0, 0.1, size=(nints, nbins)).astype(np.float32)
        for i in range(nints):
            centre = int(20 + drift_bins * i / nints) % nbins
            for b in range(centre - width // 2, centre + width // 2 + 1):
                folds[i, b % nbins] += 5.0
        return folds

    def test_recovers_drift(self):
        opt = FoldOptimiser(64, 16)
        folds = self.make_fold(drift_bins=6.0)
        res = opt.optimise(folds[None], np.array([0.25]), tobs=41.94)[0]
        # drift of +6 bins over the fold -> optimal shift magnitude ~6 from
        # centre (32); period correction must move away from p
        assert res["opt_sn"] > 10
        assert abs((32 - res["opt_shift"])) in range(4, 9)
        assert res["opt_period"] != pytest.approx(0.25, abs=1e-9)

    def test_zero_drift_keeps_period(self):
        opt = FoldOptimiser(64, 16)
        folds = self.make_fold(drift_bins=0.0)
        res = opt.optimise(folds[None], np.array([0.25]), tobs=41.94)[0]
        assert res["opt_shift"] == 32  # no shift -> (32-32)=0 correction
        assert res["opt_period"] == pytest.approx(0.25, rel=1e-12)
        assert res["opt_sn"] > 10

    def test_batched_equals_single(self):
        opt = FoldOptimiser(64, 16)
        f1 = self.make_fold(drift_bins=3.0)
        f2 = self.make_fold(drift_bins=-5.0)
        batch = opt.optimise(
            np.stack([f1, f2]), np.array([0.25, 0.1]), tobs=41.94
        )
        single = opt.optimise(f2[None], np.array([0.1]), tobs=41.94)[0]
        assert batch[1]["opt_shift"] == single["opt_shift"]
        assert batch[1]["opt_sn"] == pytest.approx(single["opt_sn"], rel=1e-5)

    def test_calculate_sn_width_zero(self):
        prof = np.random.default_rng(1).normal(size=64)
        sn1, sn2 = calculate_sn(prof, 10, 0, 64)
        assert sn1 == 0.0  # sqrt(0) kills sn1; sn2 -> inf -> squashed


class TestCoincidence:
    def test_mask(self):
        beams = np.zeros((4, 8), dtype=np.float32)
        beams[:, 3] = 10.0  # all beams fire at sample 3
        beams[0, 5] = 10.0  # one beam fires at sample 5
        out = np.asarray(coincidence_mask(jnp.asarray(beams), 4.0, 3))
        assert out[3] == 0.0  # multibeam -> masked
        assert out[5] == 1.0  # single beam -> kept


class TestCompactPeaks:
    """Ragged device-side peak compaction (ops/peaks.py:
    compact_peaks_device) and its host-side inverses
    (pipeline/search.py: _densify_ragged, segmented-distill reindex)."""

    def test_fuzz_against_dense(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops.peaks import compact_peaks_device
        from peasoup_tpu.pipeline.search import _densify_ragged

        rng = np.random.default_rng(5)
        for trial in range(20):
            shape = tuple(
                int(rng.integers(1, 5)) for _ in range(int(rng.integers(1, 4)))
            )
            mp = int(rng.integers(1, 9))
            idxs = rng.integers(0, 1000, size=(*shape, mp)).astype(np.int32)
            snrs = rng.normal(size=(*shape, mp)).astype(np.float32)
            # counts may exceed slot capacity (fused-kernel overflow)
            cc = rng.integers(0, mp + 3, size=shape).astype(np.int32)
            total = int(np.minimum(cc, mp).sum())
            total_pad = 1 << max(3, int(np.ceil(np.log2(max(1, total)))))
            packed = np.asarray(
                compact_peaks_device(
                    jnp.asarray(idxs), jnp.asarray(snrs), jnp.asarray(cc),
                    total_pad=total_pad,
                )
            )
            vi = packed[:total_pad]
            vs = packed[total_pad:].view(np.float32)
            # oracle: concatenate each cell's first min(cc, mp) slots
            ccl = np.minimum(cc, mp).reshape(-1)
            exp_i = np.concatenate(
                [idxs.reshape(-1, mp)[k, : ccl[k]] for k in range(ccl.size)]
                or [np.zeros(0, np.int32)]
            )
            exp_s = np.concatenate(
                [snrs.reshape(-1, mp)[k, : ccl[k]] for k in range(ccl.size)]
                or [np.zeros(0, np.float32)]
            )
            np.testing.assert_array_equal(vi[:total], exp_i)
            np.testing.assert_array_equal(vs[:total], exp_s)
            assert (vi[total:] == 0).all()
            # round-trip through the fallback densifier
            di, ds, dcc = _densify_ragged(
                vi[:total], vs[:total].astype(np.float64),
                np.minimum(cc, mp),
            )
            for k in range(ccl.size):
                np.testing.assert_array_equal(
                    di.reshape(-1, di.shape[-1])[k, : ccl[k]],
                    idxs.reshape(-1, mp)[k, : ccl[k]],
                )


class TestMatmulRFFT:
    """The packed-real four-step matmul rfft (ops/fft.py) — the TPU
    hot-path FFT — against numpy's f64 rfft."""

    @pytest.mark.parametrize("n", [1 << 14, 1 << 15, 1 << 17])
    def test_matches_numpy(self, rng, n):
        from peasoup_tpu.ops.fft import rfft_pow2_matmul

        # zero-mean like the whitened series the pipeline transforms (a
        # large DC term would dominate the error scale: absolute DFT
        # error grows with ||x||, and the CPU backend's einsum runs
        # plain f32 regardless of the precision request)
        x = rng.normal(0.0, 10.0, size=(3, n)).astype(np.float32)
        out = np.asarray(jax.jit(rfft_pow2_matmul)(jnp.asarray(x)))
        ref = np.fft.rfft(x.astype(np.float64), axis=-1)
        scale = np.sqrt(np.mean(np.abs(ref) ** 2))
        assert np.max(np.abs(out - ref)) / scale < 1e-5
        assert out.shape == (3, n // 2 + 1)

    def test_router_fallback_matches_stock(self, rng):
        """Non-pow2 or small sizes (and the CPU test backend) route to
        jnp.fft.rfft bitwise."""
        from peasoup_tpu.ops.fft import rfft

        x = jnp.asarray(rng.normal(size=(2, 1000)).astype(np.float32))
        np.testing.assert_array_equal(
            np.asarray(rfft(x)), np.asarray(jnp.fft.rfft(x))
        )


def test_resample_select_packed_bitwise():
    """resample_select_packed's planes are BITWISE the even/odd lanes
    of resample_select (same clip-to-edge gather semantics)."""
    import jax.numpy as jnp

    from peasoup_tpu.ops.resample import (
        resample_select, resample_select_packed,
    )

    rng = np.random.default_rng(7)
    n, smax = 4096, 5
    x = jnp.asarray(rng.normal(size=(3, n)).astype(np.float32))
    afs = jnp.asarray(
        np.asarray(
            [[0.0, 2.3e-7, -2.3e-7, 1.1e-7]] * 3, dtype=np.float32
        )
    )
    full = np.asarray(resample_select(x, afs, smax=smax))
    ev, od = resample_select_packed(x, afs, smax=smax)
    np.testing.assert_array_equal(np.asarray(ev), full[..., 0::2])
    np.testing.assert_array_equal(np.asarray(od), full[..., 1::2])


def test_resample_select_packed_planes_bitwise():
    """resample_select_packed_planes' (.., n1, n2) planes are BITWISE
    the row-major reshape of resample_select's even/odd lanes — the
    zero-relayout producer for the fused DFT kernel
    (ops/pallas/dftspec.py plane_factors order j = j1*n2 + j2)."""
    import jax.numpy as jnp

    from peasoup_tpu.ops.pallas.dftspec import plane_factors
    from peasoup_tpu.ops.resample import (
        resample_select, resample_select_packed_planes,
    )

    rng = np.random.default_rng(8)
    n, smax = 1 << 13, 5
    n1, n2 = plane_factors(n // 2)
    x = jnp.asarray(rng.normal(size=(3, n)).astype(np.float32))
    afs = jnp.asarray(
        np.asarray(
            [[0.0, 2.9e-8, -2.9e-8, 1.3e-8]] * 3, dtype=np.float32
        )
    )
    full = np.asarray(resample_select(x, afs, smax=smax))
    ev, od = resample_select_packed_planes(x, afs, smax=smax, n1=n1, n2=n2)
    assert ev.shape == (3, 4, n1, n2)
    np.testing.assert_array_equal(
        np.asarray(ev).reshape(3, 4, -1), full[..., 0::2]
    )
    np.testing.assert_array_equal(
        np.asarray(od).reshape(3, 4, -1), full[..., 1::2]
    )
    import pytest

    with pytest.raises(ValueError):
        resample_select_packed_planes(x, afs, smax=smax, n1=n1, n2=n2 * 2)
