"""Plan math tests: DM list vs golden, accel-list quirks, FFT sizing."""

import numpy as np
import pytest

from peasoup_tpu.plan import (
    generate_dm_list,
    delay_table,
    max_delay_samples,
    DMPlan,
    AccelerationPlan,
    prev_power_of_two,
    choose_fft_size,
)

TUTORIAL = dict(tsamp=0.00032, fch1=1510.0, foff=-1.09, nchans=64)


def test_dm_list_matches_golden(golden_dm_list):
    dms = generate_dm_list(
        0.0, 250.0, TUTORIAL["tsamp"], 64.0, TUTORIAL["fch1"], TUTORIAL["foff"],
        TUTORIAL["nchans"], 1.10000002384186,
    )
    assert len(dms) == 59
    np.testing.assert_allclose(dms, golden_dm_list, rtol=5e-7)


def test_dm_list_monotonic_and_bounded():
    dms = generate_dm_list(0.0, 100.0, 6.4e-5, 40.0, 1400.0, -0.39, 1024, 1.1)
    assert np.all(np.diff(dms) > 0)
    assert dms[0] == 0.0
    assert dms[-1] >= 100.0


def test_delay_table_signs():
    d = delay_table(TUTORIAL["fch1"], TUTORIAL["foff"], TUTORIAL["nchans"],
                    TUTORIAL["tsamp"])
    assert d[0] == 0.0
    assert np.all(np.diff(d) > 0)  # lower freq -> larger delay


def test_max_delay_tutorial():
    d = delay_table(TUTORIAL["fch1"], TUTORIAL["foff"], TUTORIAL["nchans"],
                    TUTORIAL["tsamp"])
    md = max_delay_samples(252.98102, d)  # last golden trial DM
    # ~0.045 s of dispersive delay across the band at DM~253
    assert 130 < md < 150


def test_dmplan_create():
    plan = DMPlan.create(
        nsamps=187520, nchans=64, tsamp=0.00032, fch1=1510.0, foff=-1.09,
        dm_start=0.0, dm_end=250.0,
    )
    assert plan.ndm == 59
    assert plan.out_nsamps == 187520 - plan.max_delay
    ds = plan.delay_samples()
    assert ds.shape == (59, 64)
    assert ds[0].max() == 0  # DM=0: no delays
    assert ds[-1].max() == plan.max_delay


class TestAccelPlan:
    def make(self, lo=-5.0, hi=5.0):
        return AccelerationPlan(
            acc_lo=lo, acc_hi=hi, tol=1.10000002384186, pulse_width=64.0,
            nsamps=131072, tsamp=0.00032, cfreq=1475.12, bw=69.76,
        )

    def test_zero_range_single_trial(self):
        plan = self.make(lo=0.0, hi=0.0)
        np.testing.assert_array_equal(plan.generate_accel_list(0.0), [0.0])

    def test_explicit_zero_first(self):
        plan = self.make()
        accs = plan.generate_accel_list(0.0)
        assert accs[0] == 0.0  # explicitly forced zero (utils.hpp:183-184)
        assert accs[1] == pytest.approx(-5.0)
        assert accs[-1] == pytest.approx(5.0)

    def test_step_grows_with_dm(self):
        # The width sum mixes units like the golden binary (pulse_width
        # in us, tdm term effectively dimensionless-small), so the DM
        # smear term only moves the step at enormous DM*bandwidth; the
        # step must still be monotonically non-decreasing in DM.
        plan = self.make()
        assert plan.step(100.0) >= plan.step(0.0)
        assert plan.step(1e9) > plan.step(0.0)
        n0 = len(plan.generate_accel_list(0.0))
        n100 = len(plan.generate_accel_list(100.0))
        assert n100 <= n0

    def test_modern_pulse_width_flag(self):
        # ADVICE r3: opt-in semantics of the CURRENT reference source
        # (utils.hpp:165 divides pulse_width by 1e3), vs the default
        # golden-binary microsecond semantics (PARITY.md "accel plan").
        golden = self.make()
        modern = AccelerationPlan(
            acc_lo=-5.0, acc_hi=5.0, tol=1.10000002384186, pulse_width=64.0,
            nsamps=131072, tsamp=0.00032, cfreq=1475.12, bw=69.76,
            modern_pulse_width=True,
        )
        # the shrunk width shrinks alt_a ~100x -> ~100x more trials
        assert modern.step(0.0) < golden.step(0.0) / 50
        assert len(modern.generate_accel_list(0.0)) > 10 * len(
            golden.generate_accel_list(0.0)
        )

    def test_walk_covers_range(self):
        plan = self.make()
        accs = plan.generate_accel_list(30.0)
        body = accs[1:]  # drop the prepended 0.0
        assert np.all(np.diff(body) > 0)
        step = plan.step(30.0)
        assert np.all(np.diff(body) <= step * 1.01)


def test_prev_power_of_two_quirks():
    # reference semantics: largest n with 2n < val... i.e. for exact
    # powers of two the answer halves (utils.hpp:12-18)
    assert prev_power_of_two(187520) == 131072
    assert prev_power_of_two(8) == 4
    assert prev_power_of_two(9) == 8
    assert prev_power_of_two(3) == 2
    assert choose_fft_size(187520) == 131072
    assert choose_fft_size(187520, 65536) == 65536
