"""Dedispersion planner + tuner tests: exact-vs-subband selection
(cost model + parity gate), the grouping twin's equivalence with the
engine's own grouping, subband-vs-exact parity as a property across
smear budgets and nbits, the per-device tuning cache (determinism,
zero re-measurement on warm buckets, corrupt-cache tolerance, schema
round trip), warmup-aware job claiming, the periodicity ShapeCtx
hooks, and the async dedisperse->search overlap."""

import json
import os

import numpy as np
import pytest

from peasoup_tpu.obs.schema import SchemaError
from peasoup_tpu.ops.dedisperse import (
    dedisperse_block,
    dedisperse_subband,
    output_scale,
    subband_groups,
)
from peasoup_tpu.perf import tuning
from peasoup_tpu.plan.dedisp_plan import (
    DedispPlan,
    candidate_subbands,
    effective_delay_table,
    effective_subbands,
    predicted_snr_loss,
    subband_group_spans,
)
from peasoup_tpu.plan.dm_plan import DMPlan

# a finely sampled wide-band survey geometry: one sample of smear is a
# small fraction of the intrinsic width (gate passes) and the dense
# trial grid groups several trials per nominal (cost model wins)
SURVEY = dict(
    nsamps=1 << 18, nchans=1024, tsamp=1e-5, fch1=1500.0, foff=-0.29,
    dm_start=0.0, dm_end=300.0,
)
SMALL = dict(
    nsamps=1 << 12, nchans=8, tsamp=0.000256, fch1=1400.0, foff=-16.0,
    dm_start=0.0, dm_end=20.0,
)


def _plan(geo) -> DMPlan:
    return DMPlan.create(**geo)


def _select(geo, **kw) -> DedispPlan:
    return DedispPlan.select(
        _plan(geo), nbits=kw.pop("nbits", 2), tsamp=geo["tsamp"],
        fch1=geo["fch1"], foff=geo["foff"], **kw,
    )


# --------------------------------------------------------------------------
# selection: cost model + parity gate
# --------------------------------------------------------------------------

class TestSelect:
    def test_survey_channels_pick_subband(self):
        """At survey channel counts with a fine time resolution the
        cost model predicts a win AND the parity gate passes ->
        subband, with the knobs the engine consumes."""
        p = _select(SURVEY)
        assert p.engine == "subband"
        assert p.subbands >= 8
        assert p.gain >= 1.2
        assert p.predicted_loss <= 0.1
        assert p.subband_smear == 1.0
        assert p.n_groups < _plan(SURVEY).ndm  # grouping really grouped

    def test_small_band_must_pick_exact(self):
        """Below the structural channel floor the planner never
        proposes subbands — exact wins at small nchans by invariant."""
        p = _select(SMALL, nbits=8)
        assert p.engine == "exact"
        assert p.subbands == 0
        assert candidate_subbands(SMALL["nchans"]) == []

    def test_parity_gate_blocks_despite_cost_win(self):
        """A zero S/N-loss budget forces exact even where the cost
        model predicts a win — the gate is a plan input, not
        folklore."""
        p = _select(SURVEY, max_snr_loss=0.0)
        assert p.gain >= 1.2  # the cost win is real...
        assert p.predicted_loss > 0.0
        assert p.engine == "exact"  # ...but the gate vetoes it

    def test_zero_smear_budget_blocks_the_win(self):
        """max_smear=0 gives singleton groups: bitwise-exact subband,
        but no arithmetic win -> exact."""
        p = _select(SURVEY, max_smear=0.0)
        assert p.engine == "exact"
        assert p.predicted_loss == 0.0

    def test_loss_model_monotone(self):
        assert predicted_snr_loss(8.0, 0.0) == 0.0
        assert (
            predicted_snr_loss(8.0, 1.0)
            < predicted_snr_loss(8.0, 4.0)
            < predicted_snr_loss(1.0, 4.0)
        )

    def test_plan_doc_round_trip(self):
        p = _select(SURVEY)
        doc = p.to_doc()
        assert DedispPlan.from_doc(doc) == p
        # summary is the compact manifest/BENCH record
        s = p.summary()
        assert s["engine"] == "subband" and s["source"] == "analytic"


class TestGrouping:
    def test_spans_match_engine_grouping(self):
        """The planner's vectorised grouping is span-for-span the
        engine's subband_groups — the cost model counts exactly the
        stage-1 passes the engine will run."""
        dt = _plan(SURVEY).delay_samples()[:300]
        for nsub in (8, 16, 32):
            for smear in (0.0, 1.0, 3.0):
                spans = subband_group_spans(dt, nsub, smear)
                assert [
                    (lo, hi) for lo, hi, _ in spans
                ] == subband_groups(dt, effective_subbands(1024, nsub), smear)
                # realised errs respect the budget
                assert all(err <= smear for _, _, err in spans)


# --------------------------------------------------------------------------
# subband-vs-exact parity as a property (smear budgets x nbits)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("nbits", [1, 2, 4, 8])
@pytest.mark.parametrize("max_smear", [0.0, 1.0, 4.0])
def test_subband_parity_property(nbits, max_smear):
    """The subband engine's output is EXACTLY the direct sum under the
    effective (smear-perturbed) delay table: bitwise equal for integer
    inputs (channel sums are exact in f32), with the perturbation
    bounded by the smear budget everywhere — and bitwise equal to the
    true exact sum when the budget is zero."""
    geo = dict(
        nsamps=4096, nchans=16, tsamp=0.000256, fch1=1400.0, foff=-16.0,
        dm_start=0.0, dm_end=30.0,
    )
    plan = _plan(geo)
    delays = plan.delay_samples()
    rng = np.random.default_rng(nbits)
    hi = (1 << nbits) - 1
    data = rng.integers(
        0, hi + 1, size=(geo["nsamps"], geo["nchans"]), dtype=np.uint8
    )
    kill = np.ones(geo["nchans"], dtype=np.float32)
    scale = output_scale(nbits, geo["nchans"])
    nsub = 4

    sub = np.asarray(
        dedisperse_subband(
            data, delays, kill, plan.out_nsamps, nsub=nsub,
            max_smear=max_smear, scale=scale,
        )
    )
    eff = effective_delay_table(delays, nsub, max_smear)
    assert np.abs(eff - delays).max() <= max_smear
    eff_direct = np.asarray(
        dedisperse_block(
            data, eff, kill, out_nsamps=plan.out_nsamps, scale=scale
        )
    )
    assert np.array_equal(sub, eff_direct)
    if max_smear == 0.0:
        exact = np.asarray(
            dedisperse_block(
                data, delays, kill, out_nsamps=plan.out_nsamps,
                scale=scale,
            )
        )
        assert np.array_equal(sub, exact)


# --------------------------------------------------------------------------
# tuning cache: determinism, warm = zero measurements, corruption
# --------------------------------------------------------------------------

BUCKET = (8, 8, 4096, 0.000256, 1400.0, -16.0)
OVR = {"dm_end": 20.0}


class TestTuningCache:
    def test_cold_tunes_warm_loads_with_zero_measurements(self, tmp_path):
        path = str(tmp_path / "tuning_cache.json")
        p1 = tuning.resolve_plan_for_bucket(BUCKET, "spsearch", OVR, path)
        assert p1.source == "tuned"
        assert p1.tuning_s > 0
        assert p1.trials  # the candidate grid was measured
        n = tuning.measurement_count()
        assert n > 0
        p2 = tuning.resolve_plan_for_bucket(BUCKET, "spsearch", OVR, path)
        # the acceptance contract: warm bucket -> ZERO measurement
        # calls, identical plan
        assert tuning.measurement_count() == n
        assert p2.source == "cache"
        assert p2.dedisp_block == p1.dedisp_block
        assert p2.engine == p1.engine
        assert p2.subbands == p1.subbands

    def test_corrupt_cache_retunes_with_warning(self, tmp_path, caplog):
        path = str(tmp_path / "tuning_cache.json")
        tuning.resolve_plan_for_bucket(BUCKET, "spsearch", OVR, path)
        with open(path, "w") as f:
            f.write("{definitely not json")
        with caplog.at_level("WARNING", logger="peasoup_tpu"):
            p = tuning.resolve_plan_for_bucket(
                BUCKET, "spsearch", OVR, path
            )
        assert p.source in ("tuned", "analytic")  # re-tuned, no crash
        assert any("re-tuning" in r.message for r in caplog.records)
        # unified resilience semantics: the torn cache is quarantined
        # (not deleted) before the re-tune persists a fresh one
        assert os.path.exists(path + ".corrupt")
        # and the rewritten cache is valid again
        tuning.validate_cache(tuning.load_cache(path))

    def test_schema_validates_and_rejects(self, tmp_path):
        path = str(tmp_path / "tuning_cache.json")
        tuning.resolve_plan_for_bucket(BUCKET, "spsearch", OVR, path)
        doc = tuning.load_cache(path)
        tuning.validate_cache(doc)
        dev = next(iter(doc["devices"]))
        key = next(iter(doc["devices"][dev]))
        bad = json.loads(json.dumps(doc))
        bad["devices"][dev][key]["engine"] = "warp-drive"
        with pytest.raises(SchemaError):
            tuning.validate_cache(bad)
        bad2 = json.loads(json.dumps(doc))
        bad2["devices"][dev][key]["bogus_knob"] = 1
        with pytest.raises(SchemaError):
            tuning.validate_cache(bad2)

    def test_search_bucket_records_selection_fields(self, tmp_path):
        """A periodicity bucket goes through DedispPlan.select: the
        cached doc carries the cost/gate provenance. 8 channels sit
        under the subband structural floor, so the measured engine
        race can only land on the parity-exact engines (exact or the
        bitwise-equal matmul — whichever THIS device measured
        faster)."""
        path = str(tmp_path / "tc.json")
        p = tuning.resolve_plan_for_bucket(BUCKET, "search", OVR, path)
        assert p.cost_exact > 0
        assert p.engine in ("exact", "matmul")
        assert p.subbands == 0  # structural floor: no subband plan
        doc = tuning.load_cache(path)
        dev = tuning.device_fingerprint()
        key = tuning.bucket_key(BUCKET, "search")
        assert doc["devices"][dev][key]["engine"] == p.engine

    def test_perf_tune_cli(self, tmp_path, capsys):
        from peasoup_tpu.tools.perf import main as perf_main

        cache = str(tmp_path / "tc.json")
        rc = perf_main(
            ["tune", "--bucket", "8,8,4096,0.000256,1400.0,-16.0",
             "--pipeline", "spsearch", "--config", '{"dm_end": 20}',
             "--cache", cache, "--reps", "1"]
        )
        assert rc == 0
        assert os.path.exists(cache)
        out = capsys.readouterr().out
        assert "engine" in out
        rc = perf_main(
            ["tune", "--bucket", "8,8,4096,0.000256,1400.0,-16.0",
             "--pipeline", "spsearch", "--config", '{"dm_end": 20}',
             "--cache", cache]
        )
        assert rc == 0
        assert "served from cache" in capsys.readouterr().out


class TestTuneCacheHygiene:
    """ISSUE satellite: `peasoup-perf tune --list/--prune` over
    tuning_cache.json — entries listed with age, stale device
    fingerprints pruned."""

    def _seed_cache(self, path: str) -> str:
        tuning.resolve_plan_for_bucket(BUCKET, "spsearch", OVR, path)
        doc = tuning.load_cache(path)
        fp = next(iter(doc["devices"]))
        # a stale fingerprint holding a copy of the entry, plus an
        # un-stamped legacy entry (age unknown -> infinitely old)
        doc["devices"]["tpu:fake-v9:n8"] = {
            k: dict(v) for k, v in doc["devices"][fp].items()
        }
        legacy = dict(next(iter(doc["devices"][fp].values())))
        legacy.pop("stored_unix", None)
        doc["devices"][fp]["spsearch|legacy|0|0|0|0|0"] = legacy
        tuning.save_cache(path, doc)
        return fp

    def test_entries_listed_with_age_and_staleness(self, tmp_path):
        path = str(tmp_path / "tc.json")
        fp = self._seed_cache(path)
        rows = tuning.list_entries(path)
        assert len(rows) == 3
        by_fp = {}
        for r in rows:
            by_fp.setdefault(r["fingerprint"], []).append(r)
        assert all(r["stale"] for r in by_fp["tpu:fake-v9:n8"])
        assert all(not r["stale"] for r in by_fp[fp])
        stamped = [r for r in rows if r["stored_unix"] is not None]
        assert stamped and all(
            r["age_s"] is not None and r["age_s"] >= 0 for r in stamped
        )
        legacy = [r for r in rows if r["stored_unix"] is None]
        assert len(legacy) == 1 and legacy[0]["age_s"] is None

    def test_prune_removes_stale_fingerprints_only(self, tmp_path):
        path = str(tmp_path / "tc.json")
        fp = self._seed_cache(path)
        removed = tuning.prune_cache(path, dry_run=True)
        assert {r["fingerprint"] for r in removed} == {"tpu:fake-v9:n8"}
        assert len(tuning.list_entries(path)) == 3  # dry run: intact
        removed = tuning.prune_cache(path)
        assert len(removed) == 1
        doc = tuning.load_cache(path)
        assert list(doc["devices"]) == [fp]  # empty group dropped
        tuning.validate_cache(doc)

    def test_prune_older_than_catches_legacy_unstamped(self, tmp_path):
        path = str(tmp_path / "tc.json")
        self._seed_cache(path)
        removed = tuning.prune_cache(
            path, older_than_s=3600.0, keep_stale=True
        )
        # fresh entries survive; the un-stamped legacy one reads as
        # infinitely old and goes
        assert len(removed) == 1
        assert removed[0]["stored_unix"] is None

    def test_tune_list_prune_cli(self, tmp_path, capsys):
        from peasoup_tpu.tools.perf import main as perf_main

        cache = str(tmp_path / "tc.json")
        self._seed_cache(cache)
        rc = perf_main(["tune", "--list", "--cache", cache])
        assert rc == 0
        out = capsys.readouterr().out
        assert "3 entries" in out
        assert "STALE device" in out
        assert "1 under stale fingerprints" in out
        rc = perf_main(
            ["tune", "--prune", "--dry-run", "--cache", cache]
        )
        assert rc == 0
        assert "would remove 1 entry" in capsys.readouterr().out
        rc = perf_main(["tune", "--prune", "--cache", cache])
        assert rc == 0
        assert "removed 1 entry" in capsys.readouterr().out
        assert len(tuning.list_entries(cache)) == 2
        # exactly one of --bucket/--list/--prune
        assert perf_main(["tune", "--list", "--prune"]) == 2
        assert perf_main(["tune"]) == 2


# --------------------------------------------------------------------------
# warmup-aware claiming
# --------------------------------------------------------------------------

def test_warm_bucket_claiming_beats_fifo(tmp_path):
    """A worker holding warm buckets claims every warm-bucket job
    before opening a cold bucket: the processed order is one long
    streak per bucket instead of FIFO's alternation."""
    from peasoup_tpu.campaign.queue import Job, JobQueue

    q = JobQueue(str(tmp_path))
    ba, bb = ("A", 8, 4096), ("B", 8, 8192)
    for i, b in enumerate([ba, bb, ba, bb, ba, bb]):
        q.add_job(Job(job_id=f"j{i}", input=f"/x/{i}.fil", bucket=b))

    def drain(**kw):
        order = []
        while True:
            claim = q.claim_next("w", **kw)
            if claim is None:
                break
            order.append(claim.job.bucket)
            q.complete(claim)
        return order

    order = drain(warm_buckets={bb})
    assert order == [bb, bb, bb, ba, ba, ba]

    # control: FIFO (by job id) would alternate — max streak 1; the
    # bucket-grouped default already beats it, warm ranking puts the
    # warmed bucket FIRST
    def max_streak(seq):
        best = cur = 1
        for x, y in zip(seq, seq[1:]):
            cur = cur + 1 if x == y else 1
            best = max(best, cur)
        return best

    fifo = [ba, bb, ba, bb, ba, bb]
    assert max_streak(order) == 3 > max_streak(fifo) == 1


# --------------------------------------------------------------------------
# periodicity ShapeCtx hooks
# --------------------------------------------------------------------------

def test_periodicity_shape_ctx_hooks():
    """The search-pipeline ctx derives the wave loop's production tile
    from the accel plan; the spectrum/resample/harmonics/peaks hooks
    build at it, and decline non-periodicity ctxs."""
    from peasoup_tpu.ops.registry import registered_programs
    from peasoup_tpu.perf.warmup import shape_ctx_for_bucket

    by = {s.name: s for s in registered_programs()}
    ctx = shape_ctx_for_bucket(
        BUCKET, "search", {"dm_end": 20.0, "acc_start": -5.0,
                           "acc_end": 5.0},
    )
    assert ctx.fft_size == 2048  # prev_power_of_two(4096)
    assert ctx.accel_pad >= 4
    nbins = ctx.fft_size // 2 + 1
    fn, args, kwargs = by["ops.spectrum.form_power"].build_for(ctx)
    assert args[0].shape == (ctx.dm_block, ctx.accel_pad, nbins)
    fn, args, kwargs = by["ops.harmonics.harmonic_sums"].build_for(ctx)
    assert kwargs["nharms"] == ctx.nharms
    fn, args, kwargs = by["ops.peaks.find_peaks_device"].build_for(ctx)
    assert kwargs["max_peaks"] == ctx.max_peaks
    fn, args, kwargs = by["ops.peaks.pack_chunk_results"].build_for(ctx)
    assert args[0].shape == (
        ctx.dm_block, ctx.nharms + 1, ctx.accel_pad, ctx.max_peaks
    )
    if ctx.select_smax > 0:
        fn, args, kwargs = by["ops.resample.resample_select"].build_for(ctx)
        assert kwargs["smax"] == ctx.select_smax

    sp_ctx = shape_ctx_for_bucket(BUCKET, "spsearch", {"dm_end": 20.0})
    assert sp_ctx.fft_size == 0
    for name in (
        "ops.spectrum.form_power", "ops.harmonics.harmonic_sums",
        "ops.peaks.find_peaks_device", "ops.resample.resample_select",
    ):
        assert by[name].build_for(sp_ctx) is None


def test_subband_ctx_builds_stage1():
    from peasoup_tpu.ops.registry import registered_programs
    from peasoup_tpu.perf.warmup import shape_ctx_for_bucket

    by = {s.name: s for s in registered_programs()}
    ctx = shape_ctx_for_bucket(
        (512, 2, 1 << 14, 1e-5, 1500.0, -0.29), "search",
        {"dm_end": 50.0, "subbands": 16},
    )
    assert ctx.subbands == 16
    fn, args, kwargs = by["ops.dedisperse.subband_stage1"].build_for(ctx)
    assert args[0].shape[0] == 16  # nsub bands
    assert args[0].shape[1] == 32  # 512 / 16 channels per band


# --------------------------------------------------------------------------
# async dedisperse -> search overlap
# --------------------------------------------------------------------------

def _smoke_fil(tmp_path, seed=1):
    from peasoup_tpu.io.sigproc import (
        Filterbank,
        SigprocHeader,
        write_filterbank,
    )

    nsamps, nchans, tsamp, fch1, foff = 1 << 12, 8, 0.000256, 1400.0, -16.0
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=20.0,
    )
    delays = plan.delay_samples()[plan.ndm // 2]
    rng = np.random.default_rng(seed)
    data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
    # a periodic dispersed pulse train (the periodicity search needs a
    # train, not one transient)
    for s0 in range(100, nsamps - 200, 128):
        for c in range(nchans):
            data[s0 + delays[c] : s0 + 4 + delays[c], c] += 14.0
    hdr = SigprocHeader(
        source_name="PLANSMOKE", tsamp=tsamp, tstart=55000.0, fch1=fch1,
        foff=foff, nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    os.makedirs(str(tmp_path), exist_ok=True)
    path = str(tmp_path / "smoke.fil")
    write_filterbank(
        path,
        Filterbank(
            header=hdr,
            data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
        ),
    )
    from peasoup_tpu.io.sigproc import read_filterbank

    return read_filterbank(path), path


def test_async_dedisperse_overlap(tmp_path, monkeypatch):
    """The dedisperse->search hop no longer serialises: the run emits
    the async-dispatch event, and the candidate set is bitwise the
    forced-sync run's (PEASOUP_SYNC_DEDISP=1) — deferral changes
    scheduling, never results."""
    from peasoup_tpu.obs.telemetry import RunTelemetry
    from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig

    fil, _ = _smoke_fil(tmp_path)
    cfg = SearchConfig(dm_end=20.0, min_snr=6.0)

    def run(sync: bool):
        if sync:
            monkeypatch.setenv("PEASOUP_SYNC_DEDISP", "1")
        else:
            monkeypatch.delenv("PEASOUP_SYNC_DEDISP", raising=False)
        tel = RunTelemetry()
        with tel.activate():
            res = PeasoupSearch(SearchConfig(**vars(cfg))).run(fil)
        kinds = [e["kind"] for e in tel.events]
        return res, kinds

    res_async, kinds_async = run(sync=False)
    res_sync, kinds_sync = run(sync=True)
    assert "dedisp_async_dispatch" in kinds_async
    assert "dedisp_async_dispatch" not in kinds_sync
    key = lambda c: (c.dm, c.acc, c.freq, c.snr, c.nh)  # noqa: E731
    assert [key(c) for c in res_async.candidates] == [
        key(c) for c in res_sync.candidates
    ]
    assert res_async.candidates  # the injected pulsar was found


def test_campaign_tune_end_to_end(tmp_path):
    """A tuned campaign: the first job of a bucket tunes on the warmer
    thread and persists the plan in the campaign-shared cache; every
    done record carries the chosen-plan provenance; after the run the
    bucket is warm (zero further measurements)."""
    from peasoup_tpu.campaign.runner import (
        CampaignConfig,
        CampaignRunner,
        enqueue_entries,
        save_campaign_config,
    )
    from peasoup_tpu.campaign.queue import JobQueue

    root = str(tmp_path / "camp")
    obs = []
    for i in range(2):
        _, path = _smoke_fil(tmp_path / f"o{i}", seed=i)
        obs.append({"input": path})
    campaign = save_campaign_config(
        root,
        CampaignConfig(
            pipeline="spsearch",
            config={"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6},
            tune=True,
            warmup=True,
            warmup_mode="aot",
        ),
    )
    queue = JobQueue(root)
    enqueue_entries(queue, obs, campaign.pipeline)
    tally = CampaignRunner(root, worker_id="w0").run()
    assert tally["done"] == 2
    cache = os.path.join(root, "tuning_cache.json")
    assert os.path.exists(cache)
    done = queue.done_records()
    assert len(done) == 2
    for d in done:
        assert d["dedisp_plan"]["engine"] == "exact"
    # exactly one job paid the tuning wall (the warmer's)
    assert sum("tuning_s" in d for d in done) == 1
    # the bucket is warm: resolving again measures nothing
    n = tuning.measurement_count()
    tuning.resolve_plan_for_bucket(
        tuple(done[0]["bucket"]), "spsearch", campaign.config, cache
    )
    assert tuning.measurement_count() == n


def test_tuned_search_end_to_end(tmp_path, monkeypatch):
    """--tune end to end on the search driver: the manifest context
    carries the chosen-plan provenance and a second run of the same
    bucket resolves with zero measurement calls."""
    from peasoup_tpu.obs.telemetry import RunTelemetry
    from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig

    fil, _ = _smoke_fil(tmp_path)
    cache = str(tmp_path / "tuning_cache.json")
    cfg = SearchConfig(dm_end=20.0, min_snr=6.0, tune=True,
                       tuning_cache=cache)
    tel = RunTelemetry()
    with tel.activate():
        res = PeasoupSearch(cfg).run(fil)
    assert res.candidates
    # the measured engine race can only pick a parity-exact engine at
    # this 8-channel bucket (exact or the bitwise-equal matmul)
    assert tel.context.get("dedisp_plan", {}).get("engine") in (
        "exact", "matmul",
    )
    n = tuning.measurement_count()
    tel2 = RunTelemetry()
    with tel2.activate():
        PeasoupSearch(SearchConfig(**vars(cfg))).run(fil)
    assert tuning.measurement_count() == n  # warm bucket, zero tuning
    assert tel2.context.get("dedisp_plan", {}).get("source") == "cache"
