"""Elastic-fleet tests: the worker registry (join/beat/leave/reap),
priority-class claiming, clean voluntary release, elastic-membership
scenarios (late joiners preferring warm buckets; a SIGKILLed worker's
registry entry reaped and its job re-queued exactly once — with a REAL
subprocess), the fleet soak's seeded role schedule, and the rollup's
fleet section. The full real-process fleet soak is the slow-marked
acceptance test here and the ``peasoup-chaos --mode fleet`` gate in
scripts/check.sh.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from peasoup_tpu.campaign.queue import Job, JobQueue
from peasoup_tpu.campaign.registry import WorkerRegistry
from peasoup_tpu.resilience import faults
from peasoup_tpu.resilience.stats import STATS


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    STATS.reset()
    yield
    faults.configure(None)
    STATS.reset()


# --------------------------------------------------------------------------
# worker registry
# --------------------------------------------------------------------------

class TestWorkerRegistry:
    def test_register_beat_live_deregister(self, tmp_path):
        reg = WorkerRegistry(str(tmp_path), lease_s=30.0)
        reg.register("w1")
        reg.register("w2")
        live = reg.live()
        assert sorted(e["worker_id"] for e in live) == ["w1", "w2"]
        assert all(e["pid"] == os.getpid() for e in live)
        reg.beat("w1", jobs_done=3, current_job="jobX")
        [w1] = [e for e in reg.live() if e["worker_id"] == "w1"]
        assert w1["jobs_done"] == 3 and w1["current_job"] == "jobX"
        reg.deregister("w1")
        assert [e["worker_id"] for e in reg.live()] == ["w2"]
        reg.deregister("w2")
        reg.deregister("w2")  # idempotent
        assert reg.entries() == []

    def test_expired_entry_not_live_and_reaped(self, tmp_path):
        reg = WorkerRegistry(str(tmp_path), lease_s=0.05)
        reg.register("dead")
        time.sleep(0.1)
        assert reg.live() == []
        assert reg.entries()  # still on disk until reaped
        assert reg.reap() == ["dead"]
        assert reg.entries() == []
        assert reg.reap() == []  # second reap: nothing left

    def test_beat_recreates_a_reaped_entry(self, tmp_path):
        """A worker that beats IS alive, whatever a skewed reaper
        concluded — the beat re-registers."""
        reg = WorkerRegistry(str(tmp_path), lease_s=30.0)
        reg.register("w1")
        os.unlink(reg._path("w1"))  # reaped from under it
        reg.beat("w1", jobs_done=1)
        [e] = reg.live()
        assert e["worker_id"] == "w1"

    def test_takeover_of_stale_same_id(self, tmp_path):
        reg = WorkerRegistry(str(tmp_path), lease_s=0.05)
        reg.register("w1", jobs_done=7)
        time.sleep(0.1)
        doc = reg.register("w1")  # restart reusing the id
        assert doc["jobs_done"] == 0
        [e] = reg.live()
        assert e["worker_id"] == "w1"


# --------------------------------------------------------------------------
# priority classes + clean release
# --------------------------------------------------------------------------

class TestPriorityClaiming:
    def test_priority_outranks_fifo(self, tmp_path):
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="a-first", input="a.fil", priority=0))
        q.add_job(Job(job_id="b-urgent", input="b.fil", priority=5))
        claim = q.claim_next("w1")
        assert claim.job.job_id == "b-urgent"

    def test_priority_outranks_bucket_affinity(self, tmp_path):
        """The documented ranking: priority > prefer-bucket >
        warm-bucket > FIFO. An urgent job in a COLD bucket must beat a
        plain job in the worker's own warm streak bucket."""
        q = JobQueue(str(tmp_path))
        warm = (8, 8, 4096)
        cold = (16, 8, 8192)
        q.add_job(Job(job_id="a-streak", input="a.fil", bucket=warm))
        q.add_job(
            Job(job_id="b-urgent", input="b.fil", bucket=cold, priority=1)
        )
        claim = q.claim_next(
            "w1", prefer_bucket=warm, warm_buckets={warm}
        )
        assert claim.job.job_id == "b-urgent"
        # equal priority: the streak bucket wins again
        q.complete(claim)
        q.add_job(
            Job(job_id="c-urgent2", input="c.fil", bucket=cold, priority=0)
        )
        claim2 = q.claim_next(
            "w1", prefer_bucket=warm, warm_buckets={warm}
        )
        assert claim2.job.job_id == "a-streak"

    def test_priority_round_trips_job_record(self, tmp_path):
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="p", input="p.fil", priority=3))
        assert q.get_job("p").priority == 3

    def test_clean_release_consumes_zero_attempts(self, tmp_path):
        """Satellite: a worker leaving cleanly hands its claim back
        with ZERO attempts consumed; the job is immediately claimable
        by anyone."""
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="j", input="x.fil"))
        claim = q.claim_next("leaver")
        assert claim is not None
        q.release(claim)
        assert q.state("j") == "pending"
        assert q.get_job("j").attempts == 0
        claim2 = q.claim_next("successor")
        assert claim2 is not None and claim2.worker_id == "successor"
        q.complete(claim2)
        [done] = q.done_records()
        assert done["attempts"] == 1  # the successor's only


# --------------------------------------------------------------------------
# elastic membership scenarios
# --------------------------------------------------------------------------

class TestElasticMembership:
    def test_late_joiner_prefers_warm_bucket(self, tmp_path):
        """Satellite: a worker joining mid-campaign claims warm-bucket
        jobs first — the done records other workers left behind carry
        the warm hint, and the joiner's claim ranking uses it."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            CampaignRunner,
            save_campaign_config,
        )

        root = str(tmp_path)
        save_campaign_config(root, CampaignConfig(warmup=False))
        q = JobQueue(root)
        warm = (8, 8, 4096)
        cold = (16, 8, 8192)
        # FIFO would pick the cold job (earlier id); the warm hint
        # from a finished peer's done record must override
        q.add_job(Job(job_id="a-cold", input="a.fil", bucket=cold))
        q.add_job(Job(job_id="b-warm", input="b.fil", bucket=warm))
        q.add_job(Job(job_id="c-done", input="c.fil", bucket=warm))
        peer = q.try_claim("c-done", "old-worker")
        q.complete(peer, bucket=list(warm), warmup_s=1.25)

        joiner = CampaignRunner(root, worker_id="late-joiner")
        assert tuple(warm) in joiner._warm_bucket_hint()
        claim = q.claim_next(
            "late-joiner", warm_buckets=joiner._warm_bucket_hint()
        )
        assert claim.job.job_id == "b-warm"

    def test_sigkilled_worker_reaped_and_requeued_exactly_once(
        self, tmp_path
    ):
        """Satellite: a REAL subprocess registers, claims a job, and
        is SIGKILLed holding it. The lease expires, the claim reap
        consumes exactly one attempt, and the registry reap removes
        the corpse's membership entry."""
        root = str(tmp_path)
        q = JobQueue(root, lease_s=0.5)
        q.add_job(Job(job_id="j", input="x.fil"))
        script = (
            "import sys, time\n"
            "from peasoup_tpu.campaign.queue import JobQueue\n"
            "from peasoup_tpu.campaign.registry import WorkerRegistry\n"
            "root = sys.argv[1]\n"
            "q = JobQueue(root, lease_s=0.5)\n"
            "WorkerRegistry(root, lease_s=0.5).register('victim')\n"
            "claim = q.claim_next('victim')\n"
            "assert claim is not None\n"
            "print('CLAIMED', flush=True)\n"
            "time.sleep(60)\n"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", script, root],
            stdout=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        try:
            line = proc.stdout.readline().decode()
            assert "CLAIMED" in line, line
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert q.state("j") in ("running", "stale")  # corpse holds it
        time.sleep(0.6)  # lease expires
        assert q.reap_stale() == ["j"]
        assert q.reap_stale() == []  # exactly once
        job = q.get_job("j")
        assert job.attempts == 1
        assert q.state("j") in ("pending", "backoff")
        reg = WorkerRegistry(root, lease_s=0.5)
        assert reg.reap() == ["victim"]
        assert reg.entries() == []

    def test_worker_kill_leaves_registry_entry_for_peers(self, tmp_path):
        """The in-process SIGKILL model (WorkerKilled) must leave the
        membership entry behind like a real kill — peers reap it."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            run_worker,
            save_campaign_config,
        )
        from peasoup_tpu.resilience import WorkerKilled

        root = str(tmp_path)
        save_campaign_config(
            root, CampaignConfig(warmup=False, lease_s=0.2)
        )
        q = JobQueue(root, lease_s=0.2)
        q.add_job(Job(job_id="j", input="/nonexistent/x.fil"))
        faults.configure("worker.kill:at=1")
        with pytest.raises(WorkerKilled):
            run_worker(root, worker_id="victim", poll_s=0.05)
        faults.configure(None)
        reg = WorkerRegistry(root, lease_s=0.2)
        assert [e["worker_id"] for e in reg.entries()] == ["victim"]
        time.sleep(0.25)
        assert reg.reap() == ["victim"]


# --------------------------------------------------------------------------
# fleet soak schedule + rollup fleet section
# --------------------------------------------------------------------------

class TestFleetRoles:
    def test_roles_deterministic_and_complete(self):
        from peasoup_tpu.tools.chaos import _fleet_roles

        a = _fleet_roles(11, 4)
        b = _fleet_roles(11, 4)
        c = _fleet_roles(12, 4)
        assert a == b
        assert a != c
        assert sum(r["kill"] for r in a) == 1
        assert sum(bool(r["max_jobs"]) for r in a) == 1
        assert sum(r["late"] for r in a) == 1
        # a victim is never also the late joiner, and at least one
        # plain drainer remains
        for r in a:
            assert not (r["kill"] and r["late"])
        assert any(
            not r["kill"] and not r["max_jobs"] and not r["late"]
            for r in a
        )
        # exactly one worker carries the flaky-read schedule, one
        # carries the skew, and both embed the seed
        flaky = [r for r in a if "fil.read" in r["faults"]]
        skewed = [r for r in a if "clock.skew" in r["faults"]]
        assert len(flaky) == 1 and len(skewed) == 1
        assert all("seed=11" in r["faults"] for r in flaky + skewed)
        assert not flaky[0]["kill"] and not skewed[0]["kill"]

    def test_roles_reject_fleet_without_a_drainer(self):
        from peasoup_tpu.tools.chaos import _fleet_roles

        with pytest.raises(ValueError, match="drainer"):
            _fleet_roles(1, 2, kills=1, late_joiners=1)

    def test_fleet_soak_rejects_too_few_jobs(self, tmp_path):
        from peasoup_tpu.tools.chaos import run_fleet_soak

        with pytest.raises(ValueError, match="one job per worker"):
            run_fleet_soak(str(tmp_path), None, 1, n_workers=4, n_obs=2)


class TestRollupFleetSection:
    def test_fleet_membership_and_throughput_in_rollup(self, tmp_path):
        from peasoup_tpu.campaign.rollup import build_status

        root = str(tmp_path)
        q = JobQueue(root)
        reg = WorkerRegistry(root, lease_s=30.0)
        reg.register("w1")
        reg.beat("w1", jobs_done=2, current_job="j2")
        for i, t in enumerate((100.0, 200.0)):
            q.add_job(Job(job_id=f"j{i}", input=f"{i}.fil"))
            c = q.try_claim(f"j{i}", "w1")
            q.complete(c)
            # pin finished_unix for a deterministic rate
            path = q._p("done", f"j{i}")
            with open(path) as f:
                doc = json.load(f)
            doc["finished_unix"] = t
            doc["worker_id"] = "w1"
            with open(path, "w") as f:
                json.dump(doc, f)
        st = build_status(root, q)
        [live] = st["fleet"]["live"]
        assert live["worker_id"] == "w1"
        assert live["jobs_done"] == 2 and live["current_job"] == "j2"
        w1 = st["fleet"]["workers"]["w1"]
        assert w1["done"] == 2
        assert w1["jobs_per_h"] == 36.0  # 1 interval over 100 s
        assert st["degraded_jobs"] == 0
        assert st["corrupt_artifact_files"] == 0

    def test_degraded_and_corrupt_tallies(self, tmp_path):
        from peasoup_tpu.campaign.rollup import build_status

        root = str(tmp_path)
        q = JobQueue(root)
        q.add_job(Job(job_id="j", input="x.fil"))
        c = q.try_claim("j", "w1")
        q.complete(c, degraded=True)
        (tmp_path / "jobs").mkdir()
        (tmp_path / "jobs" / "a.ckpt.corrupt").write_text("torn")
        st = build_status(root, q)
        assert st["degraded_jobs"] == 1
        assert st["corrupt_artifact_files"] == 1


# --------------------------------------------------------------------------
# the real thing (slow): 4 worker processes, kill + churn + skew
# --------------------------------------------------------------------------

@pytest.mark.slow
class TestFleetSoakEndToEnd:
    def test_fleet_soak_survives(self, tmp_path):
        from peasoup_tpu.tools.chaos import run_fleet_soak

        sec = run_fleet_soak(
            str(tmp_path), None, seed=11, n_workers=4, n_obs=6,
            lease_s=1.0,
        )
        assert sec["violations"] == []
        assert sec["queue"]["done"] == 6
        assert sec["kills"] and sec["late_joins"]
        assert sec["recovery"]["worker.kill"]["reaped_retries"] >= 1
        assert sec["recovery"]["fil.read"]["injected"] == 2
