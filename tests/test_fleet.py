"""Elastic-fleet tests: the worker registry (join/beat/leave/reap),
priority-class claiming, clean voluntary release, priority PREEMPTION
(checkpointed revoke/resume with zero attempts consumed, release
fairness, grace-deadline escalation, mid-preemption death),
gang-scheduled multi-host jobs (leader-only all-or-nothing claims, the
file-backed exchange, transient gang failure), the autoscale
controller's bounds, elastic-membership scenarios (late joiners
preferring warm buckets; a SIGKILLed worker's registry entry reaped
and its job re-queued exactly once — with a REAL subprocess), the
fleet soak's seeded role schedule, and the rollup's fleet section.
The full real-process fleet soak is the slow-marked acceptance test
here and the ``peasoup-chaos --mode fleet`` gate in scripts/check.sh.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from peasoup_tpu.campaign.queue import Job, JobQueue, job_id_for
from peasoup_tpu.campaign.registry import WorkerRegistry
from peasoup_tpu.resilience import faults
from peasoup_tpu.resilience.stats import STATS


def _write_obs(
    path, seed=5, nsamps=1 << 12, nchans=8, dm_end=20.0,
):
    """One small synthetic observation with a dispersed pulse."""
    from peasoup_tpu.io.sigproc import (
        Filterbank,
        SigprocHeader,
        write_filterbank,
    )
    from peasoup_tpu.plan.dm_plan import DMPlan

    tsamp, fch1, foff = 0.000256, 1400.0, -16.0
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=dm_end, pulse_width=64.0, tol=1.10,
    )
    delays = plan.delay_samples()[plan.ndm // 2]
    rng = np.random.default_rng(seed)
    data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
    for c in range(nchans):
        data[1500 + delays[c] : 1504 + delays[c], c] += 14.0
    hdr = SigprocHeader(
        source_name="FLEET", tsamp=tsamp, tstart=55000.0, fch1=fch1,
        foff=foff, nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    write_filterbank(
        path,
        Filterbank(
            header=hdr,
            data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
        ),
    )
    return path


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    STATS.reset()
    yield
    faults.configure(None)
    STATS.reset()


# --------------------------------------------------------------------------
# worker registry
# --------------------------------------------------------------------------

class TestWorkerRegistry:
    def test_register_beat_live_deregister(self, tmp_path):
        reg = WorkerRegistry(str(tmp_path), lease_s=30.0)
        reg.register("w1")
        reg.register("w2")
        live = reg.live()
        assert sorted(e["worker_id"] for e in live) == ["w1", "w2"]
        assert all(e["pid"] == os.getpid() for e in live)
        reg.beat("w1", jobs_done=3, current_job="jobX")
        [w1] = [e for e in reg.live() if e["worker_id"] == "w1"]
        assert w1["jobs_done"] == 3 and w1["current_job"] == "jobX"
        reg.deregister("w1")
        assert [e["worker_id"] for e in reg.live()] == ["w2"]
        reg.deregister("w2")
        reg.deregister("w2")  # idempotent
        assert reg.entries() == []

    def test_expired_entry_not_live_and_reaped(self, tmp_path):
        reg = WorkerRegistry(str(tmp_path), lease_s=0.05)
        reg.register("dead")
        time.sleep(0.1)
        assert reg.live() == []
        assert reg.entries()  # still on disk until reaped
        assert reg.reap() == ["dead"]
        assert reg.entries() == []
        assert reg.reap() == []  # second reap: nothing left

    def test_beat_recreates_a_reaped_entry(self, tmp_path):
        """A worker that beats IS alive, whatever a skewed reaper
        concluded — the beat re-registers."""
        reg = WorkerRegistry(str(tmp_path), lease_s=30.0)
        reg.register("w1")
        os.unlink(reg._path("w1"))  # reaped from under it
        reg.beat("w1", jobs_done=1)
        [e] = reg.live()
        assert e["worker_id"] == "w1"

    def test_takeover_of_stale_same_id(self, tmp_path):
        reg = WorkerRegistry(str(tmp_path), lease_s=0.05)
        reg.register("w1", jobs_done=7)
        time.sleep(0.1)
        doc = reg.register("w1")  # restart reusing the id
        assert doc["jobs_done"] == 0
        [e] = reg.live()
        assert e["worker_id"] == "w1"


# --------------------------------------------------------------------------
# priority classes + clean release
# --------------------------------------------------------------------------

class TestPriorityClaiming:
    def test_priority_outranks_fifo(self, tmp_path):
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="a-first", input="a.fil", priority=0))
        q.add_job(Job(job_id="b-urgent", input="b.fil", priority=5))
        claim = q.claim_next("w1")
        assert claim.job.job_id == "b-urgent"

    def test_priority_outranks_bucket_affinity(self, tmp_path):
        """The documented ranking: priority > prefer-bucket >
        warm-bucket > FIFO. An urgent job in a COLD bucket must beat a
        plain job in the worker's own warm streak bucket."""
        q = JobQueue(str(tmp_path))
        warm = (8, 8, 4096)
        cold = (16, 8, 8192)
        q.add_job(Job(job_id="a-streak", input="a.fil", bucket=warm))
        q.add_job(
            Job(job_id="b-urgent", input="b.fil", bucket=cold, priority=1)
        )
        claim = q.claim_next(
            "w1", prefer_bucket=warm, warm_buckets={warm}
        )
        assert claim.job.job_id == "b-urgent"
        # equal priority: the streak bucket wins again
        q.complete(claim)
        q.add_job(
            Job(job_id="c-urgent2", input="c.fil", bucket=cold, priority=0)
        )
        claim2 = q.claim_next(
            "w1", prefer_bucket=warm, warm_buckets={warm}
        )
        assert claim2.job.job_id == "a-streak"

    def test_priority_round_trips_job_record(self, tmp_path):
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="p", input="p.fil", priority=3))
        assert q.get_job("p").priority == 3

    def test_clean_release_consumes_zero_attempts(self, tmp_path):
        """Satellite: a worker leaving cleanly hands its claim back
        with ZERO attempts consumed; the job is immediately claimable
        by anyone."""
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="j", input="x.fil"))
        claim = q.claim_next("leaver")
        assert claim is not None
        q.release(claim)
        assert q.state("j") == "pending"
        assert q.get_job("j").attempts == 0
        claim2 = q.claim_next("successor")
        assert claim2 is not None and claim2.worker_id == "successor"
        q.complete(claim2)
        [done] = q.done_records()
        assert done["attempts"] == 1  # the successor's only


# --------------------------------------------------------------------------
# priority preemption: checkpointed revoke / resume
# --------------------------------------------------------------------------

class TestPreemption:
    def test_request_observe_release_zero_attempts(self, tmp_path):
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="j", input="x.fil"))
        assert not q.request_preempt("j")  # no claim yet
        claim = q.claim_next("victim")
        assert q.request_preempt("j", requester="urgent", grace_s=30.0)
        req = q.preempt_request("j")
        assert req["victim_worker"] == "victim"
        latency = q.release_preempted(claim)
        assert latency >= 0.0
        assert q.preempt_request("j") is None  # request consumed
        job = q.get_job("j")
        assert job.attempts == 0  # the revoke consumed ZERO attempts
        assert job.preemptions == 1
        assert len(job.preempt_latency_s) == 1

    def test_released_job_keeps_original_queue_position(self, tmp_path):
        """Satellite regression: a preempted high-arrival-order (older)
        job must be re-claimed before younger same-priority jobs — the
        release hands back its original position, it does not sort as
        fresh. 'z-old' sorts LAST lexically, so only arrival order can
        put it first."""
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="z-old", input="a.fil", created_unix=100.0))
        q.add_job(
            Job(job_id="a-young", input="b.fil", created_unix=200.0)
        )
        q.add_job(
            Job(job_id="b-young", input="c.fil", created_unix=300.0)
        )
        claim = q.claim_next("w1")
        assert claim.job.job_id == "z-old"  # arrival order claims first
        q.request_preempt("z-old")
        q.release_preempted(claim)
        reclaim = q.claim_next("w2")
        assert reclaim.job.job_id == "z-old"  # position preserved
        # a voluntary (clean) release preserves position too
        q.release(reclaim)
        again = q.claim_next("w3")
        assert again.job.job_id == "z-old"

    def test_grace_deadline_escalates_to_reap(self, tmp_path):
        """A victim that renews its lease but never answers the revoke
        is reaped at the grace deadline: one attempt consumed, the
        preempt request cleared — never a hung revoke."""
        q = JobQueue(str(tmp_path), lease_s=60.0)
        q.add_job(Job(job_id="j", input="x.fil"))
        claim = q.claim_next("wedged")
        q.request_preempt("j", grace_s=0.01)
        time.sleep(0.05)
        q.renew(claim)  # alive enough to renew, unresponsive to revoke
        assert q.reap_stale() == ["j"]
        assert q.reap_stale() == []  # exactly once
        job = q.get_job("j")
        assert job.attempts == 1
        assert q.preempt_request("j") is None
        assert STATS.snapshot()["preemptions"].get("reaped") == 1

    def test_self_preemption_victim_selection(self, tmp_path):
        """The decentralised trigger: the lease renewer of the
        LOWEST-priority running claim self-revokes when a pending job
        outranks it and no idle worker is live."""
        from peasoup_tpu.campaign.runner import _LeaseRenewer
        from peasoup_tpu.resilience import RevokeToken

        root = str(tmp_path)
        q = JobQueue(root)
        reg = WorkerRegistry(root)
        q.add_job(Job(job_id="a-low", input="a.fil", priority=0))
        q.add_job(Job(job_id="b-mid", input="b.fil", priority=1))
        low = q.try_claim("a-low", "w-low")
        mid = q.try_claim("b-mid", "w-mid")
        reg.register("w-low")
        reg.beat("w-low", current_job="a-low")
        reg.register("w-mid")
        reg.beat("w-mid", current_job="b-mid")
        q.add_job(Job(job_id="c-urgent", input="c.fil", priority=5))
        # the mid-priority holder is NOT the victim
        tok_mid = RevokeToken()
        _LeaseRenewer(
            q, mid, registry=reg, token=tok_mid, self_preempt=True
        )._observe_revoke()
        assert not tok_mid.is_set()
        assert q.preempt_request("b-mid") is None
        # the lowest-priority holder is
        tok_low = RevokeToken()
        _LeaseRenewer(
            q, low, registry=reg, token=tok_low, self_preempt=True
        )._observe_revoke()
        assert tok_low.is_set() and tok_low.kind == "preempt"
        assert q.preempt_request("a-low") is not None

    def test_self_preemption_defers_to_idle_worker(self, tmp_path):
        """No self-revoke while a live IDLE worker could just claim the
        urgent job."""
        from peasoup_tpu.campaign.runner import _LeaseRenewer
        from peasoup_tpu.resilience import RevokeToken

        root = str(tmp_path)
        q = JobQueue(root)
        reg = WorkerRegistry(root)
        q.add_job(Job(job_id="a-low", input="a.fil", priority=0))
        low = q.try_claim("a-low", "w-low")
        reg.register("w-low")
        reg.beat("w-low", current_job="a-low")
        reg.register("w-idle")  # current_job None
        q.add_job(Job(job_id="c-urgent", input="c.fil", priority=5))
        tok = RevokeToken()
        _LeaseRenewer(
            q, low, registry=reg, token=tok, self_preempt=True
        )._observe_revoke()
        assert not tok.is_set()

    def test_preempt_revoke_fault_suppresses_observation(self, tmp_path):
        """The preempt.revoke chaos seam: an injected delivery failure
        makes the renewer MISS the request for that beat; the next
        beat observes it."""
        from peasoup_tpu.campaign.runner import _LeaseRenewer
        from peasoup_tpu.resilience import RevokeToken

        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="j", input="x.fil"))
        claim = q.claim_next("victim")
        q.request_preempt("j")
        faults.configure("preempt.revoke:n=1")
        tok = RevokeToken()
        renewer = _LeaseRenewer(q, claim, token=tok)
        renewer._observe_revoke()
        assert not tok.is_set()  # delivery injected away
        assert STATS.snapshot()["faults_injected"].get(
            "preempt.revoke"
        ) == 1
        renewer._observe_revoke()
        assert tok.is_set()  # the next beat lands

    def test_end_to_end_preempt_checkpoint_resume(self, tmp_path):
        """The tentpole acceptance: a running job is revoked, the
        victim checkpoints at a DM-block boundary and releases with
        zero attempts consumed, the job resumes from the checkpoint,
        and its candidates are BITWISE-equal to an uninterrupted run
        — with the revoke latency in the done record."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            bucket_for_input,
            run_worker,
            save_campaign_config,
        )

        root = str(tmp_path)
        path = _write_obs(
            os.path.join(root, "obs.fil"), dm_end=150.0
        )
        cfg = dict(
            dm_end=150.0, dm_tol=1.03, min_snr=7.0, n_widths=6,
            dm_block=2,  # many chunks: plenty of revoke boundaries
        )
        save_campaign_config(
            root,
            CampaignConfig(
                pipeline="spsearch", config=cfg, lease_s=0.6,
                backoff_base_s=0.05, warmup=False,
            ),
        )
        q = JobQueue(root, lease_s=0.6, backoff_base_s=0.05)
        jid = job_id_for(path)
        q.add_job(
            Job(
                job_id=jid, input=path, pipeline="spsearch",
                bucket=bucket_for_input(path),
            )
        )
        out = {}

        def work():
            out["tally"] = run_worker(root, worker_id="w1", poll_s=0.05)

        t = threading.Thread(target=work)
        t.start()
        claim_path = os.path.join(root, "queue", "claims", f"{jid}.json")
        deadline = time.monotonic() + 60
        while not os.path.exists(claim_path):
            assert time.monotonic() < deadline, "claim never appeared"
            time.sleep(0.01)
        q.request_preempt(jid, requester="test", grace_s=120.0)
        t.join(timeout=240)
        assert not t.is_alive(), "worker did not drain"
        assert out["tally"]["released"] == 1, out["tally"]
        [done] = q.done_records()
        assert done["attempts"] == 1  # zero consumed by the revoke
        assert done["preemptions"] == 1
        assert done["preempt_latency_s"] and (
            done["preempt_latency_s"][0] >= 0.0
        )
        man = json.load(
            open(os.path.join(root, "jobs", jid, "telemetry.json"))
        )
        kinds = {e["kind"] for e in man.get("events", [])}
        assert kinds & {"sp_checkpoint_resume", "sp_resume_fast_path"}
        # bitwise equality vs an uninterrupted run of the same obs
        from peasoup_tpu.io.output import write_singlepulse
        from peasoup_tpu.io.sigproc import read_filterbank
        from peasoup_tpu.pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )

        ref_dir = os.path.join(root, "ref")
        os.makedirs(ref_dir)
        res = SinglePulseSearch(
            SinglePulseConfig(outdir=ref_dir, **cfg)
        ).run(read_filterbank(path))
        write_singlepulse(os.path.join(ref_dir, "ref.sp"), res.candidates)
        got = open(
            os.path.join(root, "jobs", jid, "candidates.singlepulse"),
            "rb",
        ).read()
        ref = open(os.path.join(ref_dir, "ref.sp"), "rb").read()
        assert got == ref
        # no revoke residue; rollup carries the attribution
        assert not os.listdir(os.path.join(root, "queue", "claims"))
        from peasoup_tpu.campaign.rollup import build_status

        st = build_status(root, q)
        assert st["preemptions"]["jobs"] == 1
        assert st["preemptions"]["latency_s"]["mean"] >= 0.0

    def test_reap_mid_preemption_resume_consumes_checkpoint(
        self, tmp_path
    ):
        """Satellite: a victim that observed the revoke and WROTE its
        checkpoint but died before releasing (claim left behind). The
        reaper requeues exactly once, and the resumed run consumes
        the victim's checkpoint — candidates bitwise-equal."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            bucket_for_input,
            run_worker,
            save_campaign_config,
        )
        from peasoup_tpu.io.sigproc import read_filterbank
        from peasoup_tpu.pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )
        from peasoup_tpu.resilience import (
            RevokeToken,
            SearchPreempted,
            activate_token,
        )

        root = str(tmp_path)
        path = _write_obs(os.path.join(root, "obs.fil"))
        cfg = dict(dm_end=20.0, min_snr=7.0, n_widths=6, dm_block=2)
        save_campaign_config(
            root,
            CampaignConfig(
                pipeline="spsearch", config=cfg, lease_s=0.4,
                backoff_base_s=0.05, warmup=False,
            ),
        )
        q = JobQueue(root, lease_s=0.4, backoff_base_s=0.05)
        jid = job_id_for(path)
        q.add_job(
            Job(
                job_id=jid, input=path, pipeline="spsearch",
                bucket=bucket_for_input(path),
            )
        )
        claim = q.claim_next("victim")
        q.request_preempt(jid, grace_s=120.0)
        # the victim's run: revoke pre-set, so the driver checkpoints
        # the first chunk and raises — then the victim "dies" without
        # releasing (no release_preempted call)
        job_dir = os.path.join(root, "jobs", jid)
        os.makedirs(job_dir, exist_ok=True)
        fil = read_filterbank(path)
        token = RevokeToken()
        token.revoke(kind="preempt", reason="test")
        vic_cfg = SinglePulseConfig(
            outdir=job_dir,
            checkpoint_file=os.path.join(job_dir, "search.ckpt.npz"),
            **cfg,
        )
        with activate_token(token), pytest.raises(SearchPreempted):
            SinglePulseSearch(vic_cfg).run(fil)
        assert os.path.exists(vic_cfg.checkpoint_file)
        # lease expires -> exactly one requeue
        time.sleep(0.45)
        assert q.reap_stale() == [jid]
        assert q.reap_stale() == []
        assert q.get_job(jid).attempts == 1
        assert q.preempt_request(jid) is None  # cleared by the reap
        # the resumed run consumes the victim's checkpoint
        tally = run_worker(root, worker_id="rescuer", poll_s=0.05)
        assert tally["done"] == 1
        [done] = q.done_records()
        assert done["attempts"] == 2  # the reap's one consumed attempt
        man = json.load(
            open(os.path.join(job_dir, "telemetry.json"))
        )
        kinds = {e["kind"] for e in man.get("events", [])}
        assert kinds & {"sp_checkpoint_resume", "sp_resume_fast_path"}
        ref_dir = os.path.join(root, "ref")
        os.makedirs(ref_dir)
        from peasoup_tpu.io.output import write_singlepulse

        res = SinglePulseSearch(
            SinglePulseConfig(outdir=ref_dir, **cfg)
        ).run(fil)
        write_singlepulse(os.path.join(ref_dir, "ref.sp"), res.candidates)
        got = open(
            os.path.join(job_dir, "candidates.singlepulse"), "rb"
        ).read()
        assert got == open(
            os.path.join(ref_dir, "ref.sp"), "rb"
        ).read()


# --------------------------------------------------------------------------
# gang-scheduled multi-host jobs
# --------------------------------------------------------------------------

class TestGangScheduling:
    def test_gang_claim_requires_full_group_no_starvation(self, tmp_path):
        """All-or-nothing with no head-of-line blocking: an
        unassemblable gang job is skipped — ordinary work still
        claims — and non-leaders never initiate gang claims."""
        q = JobQueue(str(tmp_path))
        q.add_job(
            Job(
                job_id="a-gang", input="g.fil", nprocs=2,
                created_unix=1.0,
            )
        )
        q.add_job(
            Job(job_id="b-normal", input="n.fil", created_unix=2.0)
        )
        # group of one: the gang job cannot assemble; the normal job
        # must still be claimed (the starvation pin)
        claim = q.claim_next("w1", group="pod", group_members=["w1"])
        assert claim.job.job_id == "b-normal"
        assert claim.gang is None
        q.release(claim)
        # ungrouped worker: same
        claim = q.claim_next("w1")
        assert claim.job.job_id == "b-normal"
        q.release(claim)
        # non-leader of an assembled group: never initiates the gang
        claim = q.claim_next(
            "w2", group="pod", group_members=["w1", "w2"]
        )
        assert claim.job.job_id == "b-normal"
        q.release(claim)
        # the leader of a full group gang-claims with the member set
        claim = q.claim_next(
            "w1", group="pod", group_members=["w1", "w2"]
        )
        assert claim.job.job_id == "a-gang"
        assert claim.gang["members"] == ["w1", "w2"]
        assert claim.gang["nprocs"] == 2
        # the member discovers its invitation; the leader does not
        inv = q.gang_invitation("w2")
        assert inv and inv["job_id"] == "a-gang"
        assert q.gang_invitation("w1") is None

    def test_gang_comm_timeout_is_transient(self, tmp_path):
        from peasoup_tpu.parallel.multihost import GangComm
        from peasoup_tpu.resilience import TransientIOError, is_transient

        comm = GangComm(
            str(tmp_path / "gang"), nprocs=2, rank=0,
            timeout_s=0.2, poll_s=0.01,
        )
        with pytest.raises(TransientIOError) as ei:
            comm.allgather(b"hello", context="test:join")
        assert is_transient(ei.value)

    def test_gang_comm_exchange_and_abort(self, tmp_path):
        from peasoup_tpu.parallel.multihost import GangComm
        from peasoup_tpu.resilience import TransientIOError

        d = str(tmp_path / "gang")
        a = GangComm(d, nprocs=2, rank=0, timeout_s=5.0, poll_s=0.01)
        b = GangComm(d, nprocs=2, rank=1, timeout_s=5.0, poll_s=0.01)
        out = {}

        def member():
            out["b"] = b.allgather(b"from-b", context="x")

        t = threading.Thread(target=member)
        t.start()
        got = a.allgather(b"from-a", context="x")
        t.join(timeout=5)
        assert got == [b"from-a", b"from-b"]
        assert out["b"] == got
        # an abort marker fails the next barrier fast on every member
        b.abort("member dying")
        with pytest.raises(TransientIOError, match="abort"):
            a.allgather(b"next", context="y")

    def test_gang_end_to_end_bitwise_equal(self, tmp_path):
        """Two grouped workers run one nprocs=2 job through the
        multi-host driver over the file exchange; the done record
        carries the gang provenance and the candidates are
        bitwise-equal to a single-process run."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            bucket_for_input,
            run_worker,
            save_campaign_config,
        )

        root = str(tmp_path)
        path = _write_obs(os.path.join(root, "obs.fil"), seed=7)
        cfg = dict(dm_end=20.0, min_snr=7.0, n_widths=6)
        save_campaign_config(
            root,
            CampaignConfig(
                pipeline="spsearch", config=cfg, lease_s=2.0,
                backoff_base_s=0.05, warmup=False,
                gang_assemble_s=30.0, gang_timeout_s=60.0,
            ),
        )
        q = JobQueue(root, lease_s=2.0, backoff_base_s=0.05)
        jid = job_id_for(path)
        q.add_job(
            Job(
                job_id=jid, input=path, pipeline="spsearch",
                bucket=bucket_for_input(path), nprocs=2,
            )
        )
        outs = {}

        def work(wid):
            outs[wid] = run_worker(
                root, worker_id=wid, poll_s=0.05, group="pod0"
            )

        ts = [
            threading.Thread(target=work, args=(w,))
            for w in ("gw-a", "gw-b")
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=240)
        assert all(not t.is_alive() for t in ts)
        [done] = q.done_records()
        assert done["gang"]["nprocs"] == 2
        assert sorted(done["gang"]["members"]) == ["gw-a", "gw-b"]
        from peasoup_tpu.io.output import write_singlepulse
        from peasoup_tpu.io.sigproc import read_filterbank
        from peasoup_tpu.pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )

        ref_dir = os.path.join(root, "ref")
        os.makedirs(ref_dir)
        res = SinglePulseSearch(
            SinglePulseConfig(outdir=ref_dir, **cfg)
        ).run(read_filterbank(path))
        write_singlepulse(os.path.join(ref_dir, "ref.sp"), res.candidates)
        got = open(
            os.path.join(root, "jobs", jid, "candidates.singlepulse"),
            "rb",
        ).read()
        assert got == open(
            os.path.join(ref_dir, "ref.sp"), "rb"
        ).read()
        # the exchange directory is consumed by the protocol
        import glob as _glob

        assert not _glob.glob(
            os.path.join(root, "jobs", jid, "gang-*")
        )
        # rollup counts the gang completion
        from peasoup_tpu.campaign.rollup import build_status

        assert build_status(root, q)["gang_jobs"] == 1

    def test_unassembled_gang_releases_cleanly(self, tmp_path):
        """A leader whose group never joins releases the claim with
        ZERO attempts consumed (assembly timeout, not failure)."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            CampaignRunner,
            bucket_for_input,
            save_campaign_config,
        )

        root = str(tmp_path)
        path = _write_obs(os.path.join(root, "obs.fil"))
        save_campaign_config(
            root,
            CampaignConfig(
                pipeline="spsearch",
                config=dict(dm_end=20.0, n_widths=6),
                warmup=False, gang_assemble_s=0.3,
            ),
        )
        q = JobQueue(root)
        jid = job_id_for(path)
        q.add_job(
            Job(
                job_id=jid, input=path, pipeline="spsearch",
                bucket=bucket_for_input(path), nprocs=2,
            )
        )
        runner = CampaignRunner(root, worker_id="gl", group="pod")
        runner.registry.register("gl", group="pod")
        # a second member is LIVE in the registry (so the leader
        # claims) but never actually joins the exchange
        runner.registry.register("zz-ghost", group="pod")
        claim = q.claim_next(
            "gl", group="pod", group_members=["gl", "zz-ghost"]
        )
        assert claim is not None and claim.gang
        assert runner.process_claim(claim) == "released"
        job = q.get_job(jid)
        assert job.attempts == 0
        assert q.state(jid) == "pending"

    def test_gang_member_death_fails_transiently_one_attempt(
        self, tmp_path
    ):
        """A member that joins and then dies mid-run: the leader's
        next barrier times out TRANSIENT and the job requeues as one
        consumed attempt."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            CampaignRunner,
            bucket_for_input,
            save_campaign_config,
        )
        from peasoup_tpu.parallel.multihost import GangComm

        root = str(tmp_path)
        path = _write_obs(os.path.join(root, "obs.fil"))
        save_campaign_config(
            root,
            CampaignConfig(
                pipeline="spsearch",
                config=dict(dm_end=20.0, n_widths=6),
                warmup=False, gang_assemble_s=5.0, gang_timeout_s=1.0,
            ),
        )
        q = JobQueue(root)
        jid = job_id_for(path)
        q.add_job(
            Job(
                job_id=jid, input=path, pipeline="spsearch",
                bucket=bucket_for_input(path), nprocs=2,
            )
        )
        runner = CampaignRunner(root, worker_id="gl", group="pod")
        runner.registry.register("gl", group="pod")
        runner.registry.register("zz-dying", group="pod")
        claim = q.claim_next(
            "gl", group="pod", group_members=["gl", "zz-dying"]
        )
        assert claim is not None and claim.gang

        # the dying member: joins the assembly barrier, then vanishes
        def half_member():
            comm = GangComm(
                os.path.join(
                    root, "jobs", jid, f"gang-{claim.gang['epoch']}"
                ),
                nprocs=2,
                rank=claim.gang["members"].index("zz-dying"),
                timeout_s=10.0, poll_s=0.01,
            )
            comm.allgather(b"dying", context=f"gang-join:{jid}")
            # ... and never shows up again

        t = threading.Thread(target=half_member)
        t.start()
        state = runner.process_claim(claim)
        t.join(timeout=10)
        assert state == "backoff"  # transient: retry, not quarantine
        assert q.get_job(jid).attempts == 1


# --------------------------------------------------------------------------
# autoscale controller
# --------------------------------------------------------------------------

def _status(
    pending=0, backoff=0, stale=0, running=0, done=False,
    live=0, idle=0, throughput=None,
):
    """A synthetic campaign_status.json rollup for decide()."""
    workers = []
    for i in range(live):
        workers.append(
            {
                "worker_id": f"w{i}",
                "current_job": None if i < idle else f"job{i}",
            }
        )
    return {
        "queue": {
            "pending": pending, "backoff": backoff, "stale": stale,
            "running": running,
        },
        "fleet": {"live": workers},
        "done": done,
        "throughput_jobs_per_s": throughput,
    }


class TestAutoscaleController:
    def _controller(self, tmp_path, **policy):
        from peasoup_tpu.campaign.autoscale import (
            AutoscaleController,
            AutoscalePolicy,
        )

        spawned, retired = [], []
        c = AutoscaleController(
            str(tmp_path),
            AutoscalePolicy(**policy),
            spawn=spawned.append,
            retire=retired.append,
        )
        return c, spawned, retired

    def test_never_exceeds_max_workers(self, tmp_path):
        c, _, _ = self._controller(
            tmp_path, min_workers=1, max_workers=3, cooldown_s=0.0,
            backlog_per_worker=1.0,
        )
        # huge backlog, fleet already at max: no up decision
        st = _status(pending=100, running=3, live=3)
        assert c.decide(st, now=1000.0) is None
        # below max: scales up one at a time
        st = _status(pending=100, running=2, live=2)
        d = c.decide(st, now=1000.0)
        assert d["action"] == "up"

    def test_never_retires_below_min(self, tmp_path):
        c, _, _ = self._controller(
            tmp_path, min_workers=2, max_workers=4, cooldown_s=0.0,
        )
        # empty queue, idle workers, but at the floor: no retirement
        st = _status(live=2, idle=2)
        assert c.decide(st, now=1000.0) is None
        st = _status(live=3, idle=3)
        d = c.decide(st, now=1000.0)
        assert d["action"] == "down"

    def test_cooldown_honoured(self, tmp_path):
        c, _, _ = self._controller(
            tmp_path, min_workers=1, max_workers=4, cooldown_s=30.0,
            backlog_per_worker=1.0,
        )
        c.last_action_unix = 1000.0
        st = _status(pending=50, live=1)
        assert c.decide(st, now=1010.0) is None  # in cooldown
        d = c.decide(st, now=1031.0)
        assert d and d["action"] == "up"

    def test_floor_restore_exempt_from_cooldown(self, tmp_path):
        c, _, _ = self._controller(
            tmp_path, min_workers=2, max_workers=4, cooldown_s=1e9,
        )
        c.last_action_unix = 1000.0
        st = _status(pending=1, live=1)  # below the floor
        d = c.decide(st, now=1001.0)
        assert d and d["action"] == "up"

    def test_drained_campaign_never_scales(self, tmp_path):
        c, _, _ = self._controller(
            tmp_path, min_workers=1, max_workers=4, cooldown_s=0.0,
        )
        assert c.decide(_status(done=True, live=0), now=1000.0) is None

    def test_bounds_over_synthetic_trace(self, tmp_path):
        """Drive decide() through a whole campaign arc — ramp, steady,
        drain — applying each decision to the synthetic fleet; the
        bounds hold at every step."""
        c, _, _ = self._controller(
            tmp_path, min_workers=1, max_workers=3, cooldown_s=10.0,
            backlog_per_worker=1.0,
        )
        live, t = 1, 0.0
        trace = []
        for step in range(60):
            t += 5.0
            backlog = max(0, 40 - step)
            st = _status(
                pending=backlog, running=min(live, backlog),
                live=live, idle=max(0, live - backlog),
            )
            d = c.decide(st, now=t)
            if d is not None:
                c.last_action_unix = t  # decide() is pure: apply here
                live += 1 if d["action"] == "up" else -1
                trace.append((t, d["action"], live))
            assert 1 <= live <= 3, trace
        assert any(a == "up" for _, a, _ in trace)
        assert any(a == "down" for _, a, _ in trace)

    def test_step_logs_decisions_into_rollup(self, tmp_path, monkeypatch):
        """step() acts and persists the decision log; the campaign
        rollup embeds it."""
        import peasoup_tpu.campaign.autoscale as autoscale_mod

        c, spawned, _ = self._controller(
            tmp_path, min_workers=1, max_workers=4, cooldown_s=0.0,
            backlog_per_worker=1.0,
        )
        monkeypatch.setattr(
            autoscale_mod, "build_status",
            lambda root: _status(pending=10, live=1),
        )
        d = c.step(now=2000.0)
        assert d["action"] == "up" and spawned == [d["worker_id"]]
        from peasoup_tpu.campaign.rollup import build_status

        st = build_status(str(tmp_path))
        assert st["autoscale"]["decisions"][0]["action"] == "up"
        assert st["autoscale"]["spawned_total"] == 1

    def test_inverted_bounds_rejected(self, tmp_path):
        from peasoup_tpu.campaign.autoscale import (
            AutoscaleController,
            AutoscalePolicy,
        )

        with pytest.raises(ValueError, match="inverted"):
            AutoscaleController(
                str(tmp_path),
                AutoscalePolicy(min_workers=5, max_workers=2),
            )

    def test_retire_marker_honoured_between_jobs(self, tmp_path):
        """Scale-down: a worker observing its retire marker leaves the
        fleet cleanly — deregistered, marker consumed."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            run_worker,
            save_campaign_config,
        )

        root = str(tmp_path)
        save_campaign_config(
            root, CampaignConfig(warmup=False)
        )
        q = JobQueue(root)
        # one job stuck in backoff far in the future: the worker idles
        q.add_job(
            Job(
                job_id="j", input="x.fil",
                next_eligible_unix=time.time() + 3600,
            )
        )
        reg = WorkerRegistry(root)
        out = {}

        def work():
            out["tally"] = run_worker(
                root, worker_id="r1", poll_s=0.05
            )

        t = threading.Thread(target=work)
        t.start()
        deadline = time.monotonic() + 20
        while not reg.live() and time.monotonic() < deadline:
            time.sleep(0.01)
        reg.request_retire("r1", requester="test")
        t.join(timeout=30)
        assert not t.is_alive(), "worker ignored the retire request"
        assert reg.entries() == []  # deregistered
        assert reg.retire_requested("r1") is None  # marker consumed


# --------------------------------------------------------------------------
# elastic membership scenarios
# --------------------------------------------------------------------------

class TestElasticMembership:
    def test_late_joiner_prefers_warm_bucket(self, tmp_path):
        """Satellite: a worker joining mid-campaign claims warm-bucket
        jobs first — the done records other workers left behind carry
        the warm hint, and the joiner's claim ranking uses it."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            CampaignRunner,
            save_campaign_config,
        )

        root = str(tmp_path)
        save_campaign_config(root, CampaignConfig(warmup=False))
        q = JobQueue(root)
        warm = (8, 8, 4096)
        cold = (16, 8, 8192)
        # FIFO would pick the cold job (earlier id); the warm hint
        # from a finished peer's done record must override
        q.add_job(Job(job_id="a-cold", input="a.fil", bucket=cold))
        q.add_job(Job(job_id="b-warm", input="b.fil", bucket=warm))
        q.add_job(Job(job_id="c-done", input="c.fil", bucket=warm))
        peer = q.try_claim("c-done", "old-worker")
        q.complete(peer, bucket=list(warm), warmup_s=1.25)

        joiner = CampaignRunner(root, worker_id="late-joiner")
        assert tuple(warm) in joiner._warm_bucket_hint()
        claim = q.claim_next(
            "late-joiner", warm_buckets=joiner._warm_bucket_hint()
        )
        assert claim.job.job_id == "b-warm"

    def test_sigkilled_worker_reaped_and_requeued_exactly_once(
        self, tmp_path
    ):
        """Satellite: a REAL subprocess registers, claims a job, and
        is SIGKILLed holding it. The lease expires, the claim reap
        consumes exactly one attempt, and the registry reap removes
        the corpse's membership entry."""
        root = str(tmp_path)
        q = JobQueue(root, lease_s=0.5)
        q.add_job(Job(job_id="j", input="x.fil"))
        script = (
            "import sys, time\n"
            "from peasoup_tpu.campaign.queue import JobQueue\n"
            "from peasoup_tpu.campaign.registry import WorkerRegistry\n"
            "root = sys.argv[1]\n"
            "q = JobQueue(root, lease_s=0.5)\n"
            "WorkerRegistry(root, lease_s=0.5).register('victim')\n"
            "claim = q.claim_next('victim')\n"
            "assert claim is not None\n"
            "print('CLAIMED', flush=True)\n"
            "time.sleep(60)\n"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", script, root],
            stdout=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        try:
            line = proc.stdout.readline().decode()
            assert "CLAIMED" in line, line
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert q.state("j") in ("running", "stale")  # corpse holds it
        time.sleep(0.6)  # lease expires
        assert q.reap_stale() == ["j"]
        assert q.reap_stale() == []  # exactly once
        job = q.get_job("j")
        assert job.attempts == 1
        assert q.state("j") in ("pending", "backoff")
        reg = WorkerRegistry(root, lease_s=0.5)
        assert reg.reap() == ["victim"]
        assert reg.entries() == []

    def test_worker_kill_leaves_registry_entry_for_peers(self, tmp_path):
        """The in-process SIGKILL model (WorkerKilled) must leave the
        membership entry behind like a real kill — peers reap it."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            run_worker,
            save_campaign_config,
        )
        from peasoup_tpu.resilience import WorkerKilled

        root = str(tmp_path)
        save_campaign_config(
            root, CampaignConfig(warmup=False, lease_s=0.2)
        )
        q = JobQueue(root, lease_s=0.2)
        q.add_job(Job(job_id="j", input="/nonexistent/x.fil"))
        faults.configure("worker.kill:at=1")
        with pytest.raises(WorkerKilled):
            run_worker(root, worker_id="victim", poll_s=0.05)
        faults.configure(None)
        reg = WorkerRegistry(root, lease_s=0.2)
        assert [e["worker_id"] for e in reg.entries()] == ["victim"]
        time.sleep(0.25)
        assert reg.reap() == ["victim"]


# --------------------------------------------------------------------------
# fleet soak schedule + rollup fleet section
# --------------------------------------------------------------------------

class TestFleetRoles:
    def test_roles_deterministic_and_complete(self):
        from peasoup_tpu.tools.chaos import _fleet_roles

        a = _fleet_roles(11, 4)
        b = _fleet_roles(11, 4)
        c = _fleet_roles(12, 4)
        assert a == b
        assert a != c
        assert sum(r["kill"] for r in a) == 1
        assert sum(bool(r["max_jobs"]) for r in a) == 1
        assert sum(r["late"] for r in a) == 1
        # a victim is never also the late joiner, and at least one
        # plain drainer remains
        for r in a:
            assert not (r["kill"] and r["late"])
        assert any(
            not r["kill"] and not r["max_jobs"] and not r["late"]
            for r in a
        )
        # exactly one worker carries the flaky-read schedule, one
        # carries the skew, and both embed the seed
        flaky = [r for r in a if "fil.read" in r["faults"]]
        skewed = [r for r in a if "clock.skew" in r["faults"]]
        assert len(flaky) == 1 and len(skewed) == 1
        assert all("seed=11" in r["faults"] for r in flaky + skewed)
        assert not flaky[0]["kill"] and not skewed[0]["kill"]
        # default roles carry no gang group
        assert all(not r["group"] for r in a)

    def test_roles_gang_group_assignment(self):
        """With gangs scheduled, exactly two workers share pod0 —
        the flaky drainer and the late joiner — and neither is a kill
        victim or a single-job leaver (the gang must stay able to
        assemble)."""
        from peasoup_tpu.tools.chaos import _fleet_roles

        roles = _fleet_roles(11, 4, gangs=1)
        pod = [r for r in roles if r["group"] == "pod0"]
        assert len(pod) == 2
        assert not any(r["kill"] or r["max_jobs"] for r in pod)
        assert any(r["late"] for r in pod)  # assembly-over-time drill
        assert _fleet_roles(11, 4, gangs=1) == roles  # deterministic

    def test_roles_reject_fleet_without_a_drainer(self):
        from peasoup_tpu.tools.chaos import _fleet_roles

        with pytest.raises(ValueError, match="drainer"):
            _fleet_roles(1, 2, kills=1, late_joiners=1)

    def test_fleet_soak_rejects_too_few_jobs(self, tmp_path):
        from peasoup_tpu.tools.chaos import run_fleet_soak

        with pytest.raises(ValueError, match="one job per worker"):
            run_fleet_soak(str(tmp_path), None, 1, n_workers=4, n_obs=2)


class TestRollupFleetSection:
    def test_fleet_membership_and_throughput_in_rollup(self, tmp_path):
        from peasoup_tpu.campaign.rollup import build_status

        root = str(tmp_path)
        q = JobQueue(root)
        reg = WorkerRegistry(root, lease_s=30.0)
        reg.register("w1")
        reg.beat("w1", jobs_done=2, current_job="j2")
        for i, t in enumerate((100.0, 200.0)):
            q.add_job(Job(job_id=f"j{i}", input=f"{i}.fil"))
            c = q.try_claim(f"j{i}", "w1")
            q.complete(c)
            # pin finished_unix for a deterministic rate
            path = q._p("done", f"j{i}")
            with open(path) as f:
                doc = json.load(f)
            doc["finished_unix"] = t
            doc["worker_id"] = "w1"
            with open(path, "w") as f:
                json.dump(doc, f)
        st = build_status(root, q)
        [live] = st["fleet"]["live"]
        assert live["worker_id"] == "w1"
        assert live["jobs_done"] == 2 and live["current_job"] == "j2"
        w1 = st["fleet"]["workers"]["w1"]
        assert w1["done"] == 2
        assert w1["jobs_per_h"] == 36.0  # 1 interval over 100 s
        assert st["degraded_jobs"] == 0
        assert st["corrupt_artifact_files"] == 0

    def test_degraded_and_corrupt_tallies(self, tmp_path):
        from peasoup_tpu.campaign.rollup import build_status

        root = str(tmp_path)
        q = JobQueue(root)
        q.add_job(Job(job_id="j", input="x.fil"))
        c = q.try_claim("j", "w1")
        q.complete(c, degraded=True)
        (tmp_path / "jobs").mkdir()
        (tmp_path / "jobs" / "a.ckpt.corrupt").write_text("torn")
        st = build_status(root, q)
        assert st["degraded_jobs"] == 1
        assert st["corrupt_artifact_files"] == 1


# --------------------------------------------------------------------------
# the real thing (slow): 4 worker processes, kill + churn + skew
# --------------------------------------------------------------------------

@pytest.mark.slow
class TestFleetSoakEndToEnd:
    def test_fleet_soak_survives(self, tmp_path):
        from peasoup_tpu.tools.chaos import run_fleet_soak

        sec = run_fleet_soak(
            str(tmp_path), None, seed=11, n_workers=4, n_obs=6,
            lease_s=1.0,
        )
        assert sec["violations"] == []
        # 6 base obs + 1 urgent (the preemption drill's priority job)
        assert sec["queue"]["done"] == 7
        assert sec["kills"] and sec["late_joins"]
        assert sec["recovery"]["worker.kill"]["reaped_retries"] >= 1
        assert sec["recovery"]["fil.read"]["injected"] == 2
        assert sec["preemption"]["jobs_resumed"] >= 1
        assert sec["preemption"]["latency_s"]
        assert sec["gang"]["done"] == 1
        assert sec["autoscale"]["ups"] >= 1

    def test_fleet_soak_long(self, tmp_path):
        """The hours-long variant: a bigger fleet over many more
        observations, with every drill scaled up — the closest CI gets
        to a production campaign day. Runtime scales with machine; it
        exists to be run on real hardware, not in the fast subset."""
        from peasoup_tpu.tools.chaos import run_fleet_soak

        sec = run_fleet_soak(
            str(tmp_path), None, seed=23, n_workers=6, n_obs=24,
            nsamps=1 << 13, lease_s=2.0, kills=2, leavers=2,
            late_joiners=1, timeout_s=7200.0,
        )
        assert sec["violations"] == []
        assert sec["queue"]["done"] == 25  # 24 base + 1 urgent
        assert sec["preemption"]["jobs_resumed"] >= 1
        assert sec["gang"]["done"] == 1
        assert sec["autoscale"]["ups"] >= 1
