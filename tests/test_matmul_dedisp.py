"""MXU banded-matmul dedispersion engine + fused-chain tests (ISSUE
12): matmul-vs-gather parity as a property across nbits / odd shapes /
zero-DM / the max-DM bucket edge, the matmul-staged subband engine,
the ULP contract for float inputs, the planner's third alternative
(cost profile recorded, never selected analytically), the tuner's
measured engine race (winner only when faster), the DM-scaled smear
budgets, the search-side knob grid's warm-bucket zero-measurement
contract, fused-kernel bitwise gates in interpret mode, and the
roofline stage taxonomy."""

import numpy as np
import pytest

from peasoup_tpu.ops.dedisperse import (
    dedisperse_block,
    dedisperse_matmul,
    dedisperse_subband,
    matmul_band,
    output_scale,
    subband_groups,
)
from peasoup_tpu.perf import tuning
from peasoup_tpu.plan.dedisp_plan import (
    DedispPlan,
    dm_smear_budgets,
    effective_delay_table,
    matmul_cost_profile,
    subband_group_spans,
)
from peasoup_tpu.plan.dm_plan import DMPlan

GEO = dict(
    nsamps=4096, nchans=16, tsamp=0.000256, fch1=1400.0, foff=-16.0,
    dm_start=0.0, dm_end=30.0,
)
SURVEY = dict(
    nsamps=1 << 18, nchans=1024, tsamp=1e-5, fch1=1500.0, foff=-0.29,
    dm_start=0.0, dm_end=300.0,
)


def _data(nbits, nsamps, nchans, seed=0):
    rng = np.random.default_rng(seed)
    hi = (1 << nbits) - 1
    return rng.integers(0, hi + 1, size=(nsamps, nchans), dtype=np.uint8)


# --------------------------------------------------------------------------
# matmul-vs-gather parity as a property
# --------------------------------------------------------------------------

class TestMatmulParity:
    @pytest.mark.parametrize("nbits", [1, 2, 4, 8])
    def test_bitwise_across_nbits(self, nbits):
        plan = DMPlan.create(**GEO)
        delays = plan.delay_samples()
        data = _data(nbits, GEO["nsamps"], GEO["nchans"], seed=nbits)
        kill = np.ones(GEO["nchans"], dtype=np.float32)
        kill[5] = 0.0
        scale = output_scale(nbits, GEO["nchans"] - 1)
        ref = np.asarray(
            dedisperse_block(
                data, delays, kill, out_nsamps=plan.out_nsamps,
                scale=scale,
            )
        )
        got = np.asarray(
            dedisperse_matmul(
                data, delays, kill, plan.out_nsamps, scale=scale, block=8
            )
        )
        assert np.array_equal(got, ref)

    @pytest.mark.parametrize(
        "nsamps,nchans", [(3001, 13), (4097, 7), (2050, 17)]
    )
    def test_odd_shapes(self, nsamps, nchans):
        """Non-multiple-of-tile geometries: odd sample counts, prime
        channel counts — the block/band padding must stay inert."""
        geo = dict(GEO, nsamps=nsamps, nchans=nchans)
        plan = DMPlan.create(**geo)
        delays = plan.delay_samples()
        data = _data(2, nsamps, nchans, seed=1)
        kill = np.ones(nchans, dtype=np.float32)
        ref = np.asarray(
            dedisperse_block(
                data, delays, kill, out_nsamps=plan.out_nsamps
            )
        )
        got = np.asarray(
            dedisperse_matmul(
                data, delays, kill, plan.out_nsamps, block=8
            )
        )
        assert np.array_equal(got, ref)

    def test_zero_dm_and_max_dm_edge(self):
        """Zero-DM trials (all-zero delays: band collapses to the
        quantum) and the max-DM bucket edge (out_nsamps pinned to the
        last valid sample window)."""
        plan = DMPlan.create(**GEO)
        delays = plan.delay_samples()
        data = _data(4, GEO["nsamps"], GEO["nchans"], seed=2)
        kill = np.ones(GEO["nchans"], dtype=np.float32)
        zero = np.zeros_like(delays[:4])
        ref = np.asarray(
            dedisperse_block(data, zero, kill, out_nsamps=plan.out_nsamps)
        )
        got = np.asarray(
            dedisperse_matmul(data, zero, kill, plan.out_nsamps)
        )
        assert np.array_equal(got, ref)
        # max-DM edge: the LAST trials only, with the tightest valid
        # output length (t_in - max delay)
        tail = delays[-4:]
        out = GEO["nsamps"] - int(tail.max())
        ref = np.asarray(
            dedisperse_block(data, tail, kill, out_nsamps=out)
        )
        got = np.asarray(dedisperse_matmul(data, tail, kill, out))
        assert np.array_equal(got, ref)

    def test_channel_chunking_matches(self):
        """A tiny chunk_bytes forces the channel-chunk recursion; f32
        partial accumulation stays bitwise for integer inputs."""
        plan = DMPlan.create(**GEO)
        delays = plan.delay_samples()
        data = _data(2, GEO["nsamps"], GEO["nchans"], seed=3)
        kill = np.ones(GEO["nchans"], dtype=np.float32)
        whole = np.asarray(
            dedisperse_matmul(data, delays, kill, plan.out_nsamps)
        )
        chunked = np.asarray(
            dedisperse_matmul(
                data, delays, kill, plan.out_nsamps,
                chunk_bytes=4 * (plan.out_nsamps + 64) * 3,
            )
        )
        assert np.array_equal(whole, chunked)

    def test_float_inputs_within_ulp_tolerance(self):
        """Pure-f32 filterbanks: the conv may re-associate the channel
        sum, so the contract is a pinned ULP tolerance (documented in
        ops/dedisperse.py), not bitwise equality."""
        plan = DMPlan.create(**GEO)
        delays = plan.delay_samples()
        rng = np.random.default_rng(4)
        data = rng.normal(10.0, 2.0, size=(GEO["nsamps"], GEO["nchans"]))
        data = data.astype(np.float32)
        kill = np.ones(GEO["nchans"], dtype=np.float32)
        ref = np.asarray(
            dedisperse_block(
                data, delays, kill, out_nsamps=plan.out_nsamps,
                quantize=False,
            )
        )
        got = np.asarray(
            dedisperse_matmul(
                data, delays, kill, plan.out_nsamps, quantize=False
            )
        )
        # <= 4 ULP of the accumulated magnitude (C=16 f32 adds)
        tol = 4 * np.spacing(np.maximum(np.abs(ref), 1.0))
        assert (np.abs(got - ref) <= tol).all()

    @pytest.mark.parametrize("nbits", [1, 8])
    @pytest.mark.parametrize("max_smear", [0.0, 1.0])
    def test_subband_matmul_stages_bitwise(self, nbits, max_smear):
        """The matmul-staged subband engine is bitwise the scan-staged
        one — and therefore inherits its effective-delay-table parity
        contract."""
        plan = DMPlan.create(**GEO)
        delays = plan.delay_samples()
        data = _data(nbits, GEO["nsamps"], GEO["nchans"], seed=nbits)
        kill = np.ones(GEO["nchans"], dtype=np.float32)
        scale = output_scale(nbits, GEO["nchans"])
        scan = np.asarray(
            dedisperse_subband(
                data, delays, kill, plan.out_nsamps, nsub=4,
                max_smear=max_smear, scale=scale,
            )
        )
        mm = np.asarray(
            dedisperse_subband(
                data, delays, kill, plan.out_nsamps, nsub=4,
                max_smear=max_smear, scale=scale, use_matmul=True,
            )
        )
        assert np.array_equal(mm, scan)


# --------------------------------------------------------------------------
# DM-scaled smear budgets
# --------------------------------------------------------------------------

class TestDmScaledSmear:
    def _budgets(self, plan, geo, loss=0.1, floor=1.0):
        return dm_smear_budgets(
            plan.dm_list, tsamp=geo["tsamp"], fch1=geo["fch1"],
            foff=geo["foff"], nchans=geo["nchans"],
            pulse_width_us=64.0, max_snr_loss=loss, floor=floor,
        )

    def test_budgets_grow_with_dm_and_respect_floor(self):
        plan = DMPlan.create(**SURVEY)
        b = self._budgets(plan, SURVEY)
        assert b.shape == (plan.ndm,)
        assert (b >= 1.0).all()
        assert b[-1] > b[0]  # high-DM trials absorb more smear

    def test_budgeted_grouping_coarser_and_engine_twin(self):
        """Per-trial budgets admit more trials per group at high DM;
        the planner's vectorised grouping stays span-for-span the
        engine's, and the effective table honours each trial's own
        budget."""
        plan = DMPlan.create(**SURVEY)
        dt = plan.delay_samples()[:400]
        b = self._budgets(plan, SURVEY)[:400]
        flat = subband_group_spans(dt, 32, 1.0)
        scaled = subband_group_spans(dt, 32, 1.0, b)
        assert len(scaled) <= len(flat)
        assert [
            (lo, hi) for lo, hi, _ in scaled
        ] == subband_groups(dt, 32, 1.0, b)
        eff = effective_delay_table(dt, 32, 1.0, b)
        per_trial = np.abs(eff - dt).max(axis=1)
        assert (per_trial <= np.ceil(b)).all()

    def test_select_records_scaled_smear_provenance(self):
        plan = DMPlan.create(**SURVEY)
        p = DedispPlan.select(
            plan, nbits=2, tsamp=SURVEY["tsamp"], fch1=SURVEY["fch1"],
            foff=SURVEY["foff"],
        )
        assert p.engine == "subband"
        assert p.smear_dm_scaled and p.smear_loss_budget == 0.1
        assert p.predicted_loss <= 0.1
        flat = DedispPlan.select(
            plan, nbits=2, tsamp=SURVEY["tsamp"], fch1=SURVEY["fch1"],
            foff=SURVEY["foff"], dm_scale_smear=False,
        )
        assert not flat.smear_dm_scaled
        # scaled budgets can only merge more trials per group
        assert p.n_groups <= flat.n_groups


# --------------------------------------------------------------------------
# planner third alternative
# --------------------------------------------------------------------------

class TestMatmulPlanning:
    def test_select_profiles_matmul_but_never_picks_it(self):
        plan = DMPlan.create(**GEO)
        p = DedispPlan.select(
            plan, nbits=8, tsamp=GEO["tsamp"], fch1=GEO["fch1"],
            foff=GEO["foff"],
        )
        assert p.engine in ("exact", "subband")  # never "matmul"
        assert p.cost_matmul > 0
        assert p.matmul_band >= matmul_band(plan.delay_samples()[:1])
        prof = matmul_cost_profile(plan.delay_samples(), plan.out_nsamps)
        assert prof["effective"] == pytest.approx(p.cost_matmul)
        assert prof["macs"] > 0 and prof["bytes"] > 0

    def test_plan_doc_round_trips_new_fields(self):
        p = DedispPlan(
            engine="matmul", cost_matmul=10.0, matmul_candidate=True,
            accel_bucket=16, pallas_block=256, subband_matmul=True,
            smear_dm_scaled=True, smear_loss_budget=0.1,
        )
        doc = p.to_doc()
        assert DedispPlan.from_doc(doc) == p
        s = p.summary()
        assert s["engine"] == "matmul" and s["matmul_candidate"]


# --------------------------------------------------------------------------
# tuner: measured engine race + knob grid + warm zero-measurement
# --------------------------------------------------------------------------

BUCKET = (16, 8, 4096, 0.000256, 1400.0, -16.0)
OVR = {"dm_end": 30.0}


class TestEngineRace:
    def _race(self, monkeypatch, timings):
        """Run resolve with deterministic fake measurements: engine
        race entries read from ``timings``, everything else a constant
        (ranking within knob grids is irrelevant here)."""
        import peasoup_tpu.perf.tuning as tun

        def fake_measure(call, reps):
            tun._TUNER_INVOCATIONS += 1
            return timings.pop(0) if timings else 1e-3

        monkeypatch.setattr(tun, "_measure", fake_measure)
        return tun

    def test_matmul_wins_only_when_measured_faster(self, tmp_path):
        """The real race on THIS backend: whatever engine the tuner
        records as winner must hold the minimum measured median among
        the raced engines — the acceptance contract."""
        path = str(tmp_path / "tc.json")
        p = tuning.resolve_plan_for_bucket(BUCKET, "search", OVR, path)
        raced = {
            t["params"]["engine"]: t["median_s"]
            for t in p.trials
            if "engine" in t["params"]
        }
        assert "exact" in raced  # exact always races
        winner_name = (
            "subband_matmul"
            if p.engine == "subband" and p.subband_matmul
            else p.engine
        )
        if winner_name in raced:
            assert raced[winner_name] == min(raced.values())
        # provenance: the race landed in the persisted plan
        doc = tuning.load_cache(path)
        tuning.validate_cache(doc)

    def test_warm_bucket_zero_measurements_with_new_knobs(self, tmp_path):
        """The satellite contract: the extended knob grid (dm_block,
        accel_bucket, pallas block, engine race) still resolves warm
        buckets with ZERO measurement calls, and the knobs persist."""
        path = str(tmp_path / "tc.json")
        p1 = tuning.resolve_plan_for_bucket(BUCKET, "search", OVR, path)
        assert p1.dm_block in tuning.DM_BLOCK_CANDIDATES
        assert p1.accel_bucket in tuning.ACCEL_BUCKET_CANDIDATES
        n = tuning.measurement_count()
        p2 = tuning.resolve_plan_for_bucket(BUCKET, "search", OVR, path)
        assert tuning.measurement_count() == n
        assert p2.source == "cache"
        assert p2.dm_block == p1.dm_block
        assert p2.accel_bucket == p1.accel_bucket
        assert p2.engine == p1.engine

    def test_forced_outcomes_with_fake_timings(self, tmp_path, monkeypatch):
        """Deterministic winner selection: when the fake clock makes
        matmul faster, the tuner promotes it; when slower, the current
        engine stays — provenance lands in plan.trials either way."""
        from peasoup_tpu.plan.dedisp_plan import DedispPlan as DP

        tun = self._race(monkeypatch, [])

        def run_race(exact_s, matmul_s):
            plan = DP(engine="exact", matmul_candidate=True)
            trials, meds = [], {}
            tun._race_engines(
                plan, trials, meds,
                None, None, None, 128, 1.0, 1,
                lambda *a, **k: None,  # dedisperse_device
                lambda *a, **k: None,  # dedisperse_matmul
                lambda *a, **k: None,  # dedisperse_subband
            )
            return plan, meds

        self._race(monkeypatch, [exact := 0.002, 0.001])
        plan, meds = run_race(exact, 0.001)
        assert meds == {"exact": 0.002, "matmul": 0.001}
        assert plan.engine == "matmul" and plan.source == "tuned"
        self._race(monkeypatch, [0.001, 0.002])
        plan, meds = run_race(0.001, 0.002)
        assert plan.engine == "exact"


# --------------------------------------------------------------------------
# fused chains: bitwise twins in interpret mode
# --------------------------------------------------------------------------

class TestFusedChains:
    def test_spchain_kernel_bitwise_vs_twin(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops.pallas.spchain import boxcar_dec_best_pallas
        from peasoup_tpu.ops.singlepulse import (
            boxcar_dec_best_twin,
            default_widths,
            prefix_sum_padded,
            width_extent,
            width_scales,
        )

        widths = default_widths(8)
        scales = width_scales(widths)
        span, dec = 1024, 32
        tpad = 3 * span
        wext = width_extent(widths)
        rng = np.random.default_rng(0)
        nvalid = tpad - span // 3
        norm = rng.normal(size=(4, nvalid)).astype(np.float32)
        norm[1, 500:516] += 25.0
        norm[2, 64] = norm[2, 64 + dec - 1] = 30.0  # in-block tie edges
        csum = prefix_sum_padded(jnp.asarray(norm), tpad, wext)
        got = boxcar_dec_best_pallas(
            csum, widths, scales, nvalid, tpad, dec, span=span,
            interpret=True,
        )
        ref = boxcar_dec_best_twin(csum, widths, scales, nvalid, tpad, dec)
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))

    def test_spchain_routing_in_search_fn_bitwise(self):
        """The whole fused single-pulse program (normalise -> fused
        sweep+dec-fold -> compact) emits bitwise the unfused program's
        events. Interpret mode exercises the kernel route on CPU."""
        import peasoup_tpu.ops.singlepulse as sp

        rng = np.random.default_rng(1)
        trials = rng.normal(30.0, 4.0, size=(3, 4096)).astype(np.float32)
        trials[1, 1000:1008] += 40.0
        widths = sp.default_widths(6)

        def run(fused):
            # bypass the lru_cache'd builder so interpret-mode kernels
            # can ride the fused route on CPU
            norm = sp.normalise_trials(trials)
            bmax, barg, bwidx = sp.boxcar_dec_best(
                norm, widths, 32,
                fused_span=1024 if fused else 0, interpret=fused,
            )
            return map(np.asarray, (bmax, barg, bwidx))

        for g, r in zip(run(True), run(False)):
            np.testing.assert_array_equal(g, r)

    def test_specchain_kernel_vs_twin_interpret(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops.pallas.specchain import (
            SPEC_BLOCK,
            interp_deredden_zap_pallas,
            s0_envelope,
        )
        from peasoup_tpu.ops.spectrum import interp_deredden_zap

        rng = np.random.default_rng(2)
        nbins = SPEC_BLOCK + 257  # odd, straddles two tiles
        d = 10  # forces the row pad
        re = jnp.asarray(rng.normal(size=(d, nbins)).astype(np.float32))
        im = jnp.asarray(rng.normal(size=(d, nbins)).astype(np.float32))
        med = jnp.asarray((0.5 + rng.random((d, nbins))).astype(np.float32))
        zap = np.zeros(nbins, dtype=bool)
        zap[3] = True  # birdie inside the zeroed low bins
        zap[100:104] = True
        zap[SPEC_BLOCK - 1 : SPEC_BLOCK + 1] = True  # tile boundary
        got = interp_deredden_zap_pallas(
            re, im, med, jnp.asarray(zap), interpret=True
        )
        ref = interp_deredden_zap(re, im, med, jnp.asarray(zap))
        # parts: pure select/divide — BITWISE. amplitude: FMA-class
        # envelope (the dftspec/interbin discipline; see s0_envelope)
        np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(ref[0]))
        np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(ref[1]))
        s_g, s_r = np.asarray(got[2]), np.asarray(ref[2])
        assert (np.abs(s_g - s_r) <= s0_envelope(s_r)).all()

    def test_specchain_twin_matches_unfused_stanza(self):
        """The fused twin replays the historical complex chain
        (deredden -> zap_birdies -> form_interpolated) to numerical
        identity on the values the pipeline consumes."""
        import jax.numpy as jnp

        from peasoup_tpu.ops.rednoise import deredden
        from peasoup_tpu.ops.spectrum import (
            form_interpolated,
            interp_deredden_zap,
        )
        from peasoup_tpu.ops.zap import zap_birdies

        rng = np.random.default_rng(3)
        nbins = 513
        fser = (
            rng.normal(size=(4, nbins)) + 1j * rng.normal(size=(4, nbins))
        ).astype(np.complex64)
        med = (0.5 + rng.random((4, nbins))).astype(np.float32)
        zap = np.zeros(nbins, dtype=bool)
        zap[50:60] = True
        old = zap_birdies(deredden(jnp.asarray(fser), jnp.asarray(med)),
                          jnp.asarray(zap))
        s0_old = form_interpolated(old)
        re_d, im_d, s0 = interp_deredden_zap(
            jnp.asarray(np.real(fser)), jnp.asarray(np.imag(fser)),
            jnp.asarray(med), jnp.asarray(zap),
        )
        np.testing.assert_allclose(
            np.asarray(re_d), np.real(np.asarray(old)), rtol=1e-6,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(s0), np.asarray(s0_old), rtol=1e-6, atol=1e-6
        )


# --------------------------------------------------------------------------
# roofline stage taxonomy
# --------------------------------------------------------------------------

class TestRoofline:
    def test_every_program_maps_to_a_stage(self):
        from peasoup_tpu.ops.registry import registered_programs
        from peasoup_tpu.perf.roofline import STAGES, stage_for_program

        for spec in registered_programs():
            assert stage_for_program(spec.name) in STAGES

    def test_dedisp_programs_share_the_dedisperse_stage(self):
        from peasoup_tpu.perf.roofline import stage_for_program

        for name in (
            "ops.dedisperse.dedisperse_block",
            "ops.dedisperse.dedisperse_matmul_block",
            "ops.dedisperse.subband_stage1_matmul",
        ):
            assert stage_for_program(name) == "dedisperse"

    def test_roofline_fields_math(self):
        from peasoup_tpu.perf.roofline import (
            device_peaks,
            roofline_fields,
            stage_roofline,
        )

        assert device_peaks("TPU v5 lite") == (49e12, 819e9)
        assert device_peaks("cpu") is None
        # memory-bound: low intensity
        f = roofline_fields(1.0, 1e9, 1e9, "TPU v5 lite")
        assert f["bound"] == "memory"
        assert f["intensity_flops_per_byte"] == 1.0
        assert f["peak_fraction"] == pytest.approx(
            1e9 / 819e9, abs=1e-4  # the record rounds to 4 decimals
        )
        # compute-bound: huge intensity
        f = roofline_fields(1.0, 1e15, 1e9, "TPU v5 lite")
        assert f["bound"] == "compute"
        # unknown device: ratios stay null, measured fields survive
        f = roofline_fields(2.0, 1e9, 4e9, "cpu")
        assert f["peak_fraction"] is None
        assert f["achieved_bytes_per_s"] == pytest.approx(2e9)
        tbl = stage_roofline(
            {"dedisperse": (1.0, 1e9), "other": (0.0, 0)},
            {"dedisperse": 1e9}, "TPU v5 lite",
        )
        assert tbl["dedisperse"]["bound"] == "memory"
        assert tbl["other"]["achieved_flops_per_s"] is None

    def test_microbench_doc_carries_stages_and_dedisp(self, tmp_path):
        from peasoup_tpu.perf.microbench import (
            run_microbench,
            validate_perf,
        )

        doc = run_microbench(
            reps=1,
            programs=[
                "ops.dedisperse.dedisperse_matmul_block",
                "ops.spectrum.interp_deredden_zap",
            ],
        )
        validate_perf(doc)
        assert doc["version"] == 2
        progs = doc["programs"]
        assert progs["ops.dedisperse.dedisperse_matmul_block"]["stage"] == (
            "dedisperse"
        )
        assert progs["ops.spectrum.interp_deredden_zap"]["stage"] == (
            "spectrum_chain"
        )
        assert doc["stages"]["dedisperse"]["programs"] == 1
        assert doc["dedisp"]["engine"] == "exact"


# --------------------------------------------------------------------------
# driver: forced engines produce identical candidates (the CI smoke's
# in-process twin)
# --------------------------------------------------------------------------

def test_forced_engine_three_way_candidates(tmp_path):
    from peasoup_tpu.io.sigproc import (
        Filterbank,
        SigprocHeader,
        read_filterbank,
        write_filterbank,
    )
    from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig

    nsamps, nchans, tsamp, fch1, foff = 1 << 12, 8, 0.000256, 1400.0, -16.0
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=20.0,
    )
    delays = plan.delay_samples()[plan.ndm // 2]
    rng = np.random.default_rng(5)
    data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
    for s0 in range(100, nsamps - 200, 128):
        for c in range(nchans):
            data[s0 + delays[c] : s0 + 4 + delays[c], c] += 14.0
    hdr = SigprocHeader(
        source_name="3WAY", tsamp=tsamp, tstart=55000.0, fch1=fch1,
        foff=foff, nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    path = str(tmp_path / "smoke.fil")
    write_filterbank(
        path,
        Filterbank(
            header=hdr,
            data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
        ),
    )
    fil = read_filterbank(path)

    def cands(**kw):
        res = PeasoupSearch(
            SearchConfig(dm_end=20.0, min_snr=6.0, **kw)
        ).run(fil)
        return [(c.dm, c.acc, c.freq, c.snr, c.nh) for c in res.candidates]

    exact = cands()
    assert exact  # the injected pulsar was found
    assert cands(dedisp_engine="matmul") == exact
    # exact-subband (max_smear=0) completes the three-way
    assert cands(subbands=4, subband_smear=0.0) == exact
    assert cands(subbands=4, subband_smear=0.0, subband_matmul=True) == exact
