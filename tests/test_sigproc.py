"""I/O tests: header + bit packing round-trips, tutorial.fil golden header."""

import io

import numpy as np
import pytest

from peasoup_tpu.io import (
    SigprocHeader,
    read_sigproc_header,
    write_sigproc_header,
    read_filterbank,
    unpack_bits,
    pack_bits,
)


def test_header_roundtrip():
    hdr = SigprocHeader(
        source_name="FAKE PSR",
        tsamp=6.4e-5,
        tstart=56000.0,
        fch1=1510.0,
        foff=-1.09,
        nchans=64,
        nbits=8,
        nifs=1,
        data_type=1,
    )
    buf = io.BytesIO()
    write_sigproc_header(buf, hdr)
    # append fake data so nsamples can be derived from file size
    nsamps = 1000
    buf.write(b"\x00" * (nsamps * hdr.nchans))
    buf.seek(0)
    rhdr = read_sigproc_header(buf)
    assert rhdr.source_name == "FAKE PSR"
    assert rhdr.tsamp == pytest.approx(6.4e-5)
    assert rhdr.fch1 == 1510.0
    assert rhdr.foff == -1.09
    assert rhdr.nchans == 64
    assert rhdr.nsamples == nsamps  # derived from file size (header.hpp:394-401)


@pytest.mark.parametrize("nbits", [1, 2, 4, 8])
def test_pack_unpack_roundtrip(nbits, rng):
    n = 64
    samples = rng.integers(0, 1 << nbits, size=n).astype(np.uint8)
    packed = pack_bits(samples, nbits)
    assert packed.size == n * nbits // 8
    unpacked = unpack_bits(packed, nbits)
    np.testing.assert_array_equal(unpacked, samples)


def test_tutorial_header(tutorial_fil):
    """Header values must match the golden overview.xml echo."""
    fil = read_filterbank(tutorial_fil)
    h = fil.header
    assert h.nchans == 64
    assert h.nbits == 2
    assert h.tsamp == pytest.approx(0.00032)
    assert h.fch1 == pytest.approx(1510.0)
    assert h.foff == pytest.approx(-1.09)
    assert h.nsamples == 187520
    assert h.tstart == pytest.approx(50000.0)
    assert "250" in h.source_name and "30" in h.source_name
    assert fil.data.shape == (187520, 64)
    # 2-bit data: all values in [0, 3]
    assert fil.data.max() <= 3


def test_tutorial_data_has_signal(tutorial_fil):
    """Folding the raw (DM=0-ish low DM) data at P=250 ms should already
    show structure: variance across phase bins well above noise-only."""
    fil = read_filterbank(tutorial_fil)
    x = fil.data.sum(axis=1).astype(np.float64)  # zero-DM time series
    period_samps = 0.25 / fil.tsamp
    phases = (np.arange(x.size) / period_samps) % 1.0
    bins = (phases * 64).astype(int)
    prof = np.bincount(bins, weights=x, minlength=64) / np.bincount(
        bins, minlength=64
    )
    # contrast between peak and mean should be clear
    assert prof.max() - prof.mean() > 5 * prof.std() / 8
