"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from peasoup_tpu.parallel import (
    make_mesh,
    device_count,
    make_sharded_search_fn,
    baseline_beam,
    sharded_coincidence,
)
from peasoup_tpu.parallel.sharded_search import place_trials
from peasoup_tpu.pipeline.accel_search import make_search_fn
from peasoup_tpu.pipeline.search import _level_windows


def test_virtual_mesh_has_8_devices():
    assert device_count() == 8


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape == {"dm": 8}
    mesh2 = make_mesh({"beam": 2, "dm": -1})
    assert mesh2.shape == {"beam": 2, "dm": 4}
    with pytest.raises(ValueError):
        make_mesh({"dm": 3})


class TestShardedSearch:
    def make_inputs(self, ndm=8, size=4096, n_accs=4):
        rng = np.random.default_rng(3)
        t = np.arange(size)
        tims = []
        for d in range(ndm):
            x = rng.normal(30, 3, size=size)
            x += 10.0 * (((t * 0.000256) / 0.016) % 1.0 < 0.1)  # P=16ms pulsar
            tims.append(np.clip(np.rint(x), 0, 255))
        tims = np.asarray(tims, dtype=np.uint8)
        afs = np.zeros((ndm, n_accs), dtype=np.float32)
        windows = _level_windows(size, 2, 0.1, 1100.0, 0.000256)
        zap = np.zeros(size // 2 + 1, dtype=bool)
        return tims, afs, zap, windows

    def test_matches_single_device(self):
        tims, afs, zap, windows = self.make_inputs()
        size = tims.shape[1]
        kw = dict(size=size, nsamps_valid=size, nharms=2, max_peaks=64,
                  pos5=10, pos25=100)
        mesh = make_mesh()
        sharded = make_sharded_search_fn(mesh, threshold=6.0)
        peaks = sharded(
            place_trials(mesh, tims), jnp.asarray(afs), jnp.asarray(zap),
            jnp.asarray(windows), **kw,
        )
        single = make_search_fn(6.0)
        for d in range(tims.shape[0]):
            ref = single(jnp.asarray(tims[d]), jnp.asarray(afs[d]),
                         jnp.asarray(zap), jnp.asarray(windows), **kw)
            np.testing.assert_array_equal(np.asarray(peaks.idxs)[d],
                                          np.asarray(ref.idxs))
            np.testing.assert_allclose(np.asarray(peaks.snrs)[d],
                                       np.asarray(ref.snrs), rtol=2e-5, atol=1e-4)
            np.testing.assert_array_equal(np.asarray(peaks.counts)[d],
                                          np.asarray(ref.counts))

    def test_finds_the_pulsar_on_every_shard(self):
        tims, afs, zap, windows = self.make_inputs()
        mesh = make_mesh()
        sharded = make_sharded_search_fn(mesh, threshold=6.0)
        peaks = sharded(
            place_trials(mesh, tims), jnp.asarray(afs), jnp.asarray(zap),
            jnp.asarray(windows), size=tims.shape[1],
            nsamps_valid=tims.shape[1], nharms=2, max_peaks=64, pos5=10,
            pos25=100,
        )
        counts = np.asarray(peaks.counts)
        assert (counts.sum(axis=(1, 2)) > 0).all()  # every DM shard fired


class TestShardedCoincidence:
    def test_matches_unsharded(self):
        rng = np.random.default_rng(0)
        beams = rng.normal(size=(8, 512)).astype(np.float32)
        beams[:, 100] = 10.0  # all beams -> RFI
        beams[0, 200] = 10.0  # one beam -> keep
        mesh = make_mesh({"beam": 8})
        out = np.asarray(
            sharded_coincidence(mesh, jnp.asarray(beams), 4.0, 4)
        )
        from peasoup_tpu.ops import coincidence_mask

        ref = np.asarray(coincidence_mask(jnp.asarray(beams), 4.0, 4))
        np.testing.assert_array_equal(out, ref)
        assert out[100] == 0.0 and out[200] == 1.0

    def test_beam_axis_smaller_than_mesh_padding(self):
        # 6 real beams padded to 8 with -inf so they never fire
        rng = np.random.default_rng(1)
        beams = rng.normal(size=(6, 256)).astype(np.float32)
        beams[:, 50] = 99.0
        pad = np.full((2, 256), -np.inf, dtype=np.float32)
        stacked = np.concatenate([beams, pad])
        mesh = make_mesh({"beam": 8})
        out = np.asarray(sharded_coincidence(mesh, jnp.asarray(stacked), 4.0, 4))
        assert out[50] == 0.0


class TestBaselineBeam:
    def test_outputs_normalised(self):
        rng = np.random.default_rng(2)
        x = np.clip(rng.normal(50, 5, size=4096), 0, 255).astype(np.uint8)
        spec, tim = baseline_beam(jnp.asarray(x), size=4096, pos5=10, pos25=100)
        spec, tim = np.asarray(spec), np.asarray(tim)
        assert spec.shape == (2049,)
        assert tim.shape == (4096,)
        assert abs(np.mean(tim)) < 0.1  # normalised
        assert np.std(tim) == pytest.approx(1.0, rel=0.1)


class TestMultihost:
    """Multi-host helpers (parallel/multihost.py). Single-process here:
    initialize() must no-op, global_mesh must build over all (virtual)
    devices with the DCN axis leading, and the per-process slice must
    cover the axis exactly."""

    def test_initialize_noop_single_process(self):
        from peasoup_tpu.parallel import multihost

        multihost.initialize()  # no coordinator -> no-op

    def test_global_mesh_dcn_axis_leading(self):
        from peasoup_tpu.parallel import multihost

        mesh = multihost.global_mesh({"dm": -1, "beam": 2}, dcn_axis="beam")
        assert mesh.axis_names[0] == "beam"
        assert mesh.shape["beam"] == 2
        assert mesh.shape["beam"] * mesh.shape["dm"] == len(
            mesh.devices.reshape(-1)
        )

    def test_process_local_slice_covers_axis(self):
        from peasoup_tpu.parallel import multihost

        mesh = multihost.global_mesh({"dm": -1})
        lo, hi = multihost.process_local_slice(mesh, "dm")
        assert (lo, hi) == (0, mesh.shape["dm"])  # single process

    def test_dm_slice_for_process_partitions(self):
        from peasoup_tpu.parallel.multihost import dm_slice_for_process

        for ndm, nproc in [(59, 4), (8, 8), (7, 3), (100, 1), (3, 5)]:
            slices = [dm_slice_for_process(ndm, nproc, p) for p in range(nproc)]
            # contiguous, ordered, exactly covering [0, ndm)
            assert slices[0][0] == 0 and slices[-1][1] == ndm
            for (a, b), (c, d) in zip(slices, slices[1:]):
                assert b == c and b - a >= d - c  # balanced, larger first
            sizes = [b - a for a, b in slices]
            assert max(sizes) - min(sizes) <= 1

    def test_allgather_pickled_single_process(self):
        from peasoup_tpu.parallel.multihost import _allgather_pickled

        assert _allgather_pickled(b"payload") == [b"payload"]

    def test_run_search_single_process_degrades(self, tmp_path):
        """run_search with one process must be exactly the local
        driver path."""
        from peasoup_tpu.parallel.multihost import run_search
        from peasoup_tpu.pipeline.search import SearchConfig
        from tests.test_pipeline import make_synthetic_fil
        from peasoup_tpu.io.sigproc import read_filterbank

        path, _, _ = make_synthetic_fil(tmp_path, nsamps=1 << 13)
        fil = read_filterbank(path)
        res = run_search(fil, SearchConfig(dm_end=10.0, nharmonics=1, limit=5))
        assert len(res.candidates) <= 5


class TestShardedDedispersion:
    """dedisperse_sharded: the DM-trial axis of the shift-and-sum engine
    sharded over the mesh (reference analogue: dedisp_create_plan_multi,
    dedisperser.hpp:25-31)."""

    def make_fil(self, nsamps=4096, nchans=32, seed=7):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 4, size=(nsamps, nchans)).astype(np.uint8)

    def make_delays(self, ndm, nchans, max_delay=200, seed=8):
        rng = np.random.default_rng(seed)
        # monotone-in-channel delay curves like a real DM table
        base = np.sort(rng.integers(0, max_delay, size=(ndm, nchans)), axis=1)
        return np.asarray(base[:, ::-1], dtype=np.int32)  # high freq first

    @pytest.mark.parametrize("ndm", [16, 59])  # 59: pad (not /8)
    def test_bitwise_matches_single_device(self, ndm):
        from peasoup_tpu.ops.dedisperse import dedisperse_device
        from peasoup_tpu.parallel.sharded_dedisperse import dedisperse_sharded

        fil = self.make_fil()
        delays = self.make_delays(ndm, fil.shape[1])
        kill = np.ones(fil.shape[1], dtype=np.int32)
        kill[3] = 0
        out_nsamps = fil.shape[0] - int(delays.max())
        single = np.asarray(
            dedisperse_device(fil, delays, kill, out_nsamps, block=16)
        )
        mesh = make_mesh({"dm": 8})
        sharded = np.asarray(
            dedisperse_sharded(fil, delays, kill, out_nsamps, mesh, block=4)
        )
        assert sharded.shape[0] >= ndm  # padded to a mesh-axis multiple
        np.testing.assert_array_equal(sharded[:ndm], single)

    def test_output_is_sharded_on_mesh(self):
        from peasoup_tpu.parallel.sharded_dedisperse import dedisperse_sharded

        fil = self.make_fil()
        delays = self.make_delays(16, fil.shape[1])
        kill = np.ones(fil.shape[1], dtype=np.int32)
        mesh = make_mesh({"dm": 8})
        out = dedisperse_sharded(
            fil, delays, kill, fil.shape[0] - int(delays.max()), mesh
        )
        # trials must materialise distributed over the 'dm' axis: one
        # shard of 2 rows per device, no full-array replica anywhere
        assert len(out.sharding.device_set) == 8
        shard_rows = {s.data.shape[0] for s in out.addressable_shards}
        assert shard_rows == {2}

    def test_row_gather_regroups_on_mesh(self):
        from peasoup_tpu.parallel.sharded_dedisperse import (
            dedisperse_sharded,
            make_row_gather,
        )

        fil = self.make_fil()
        delays = self.make_delays(24, fil.shape[1])
        kill = np.ones(fil.shape[1], dtype=np.int32)
        out_nsamps = fil.shape[0] - int(delays.max())
        mesh = make_mesh({"dm": 8})
        trials = dedisperse_sharded(fil, delays, kill, out_nsamps, mesh)
        # a search chunk regrouping: arbitrary row order, truncated time
        idx = np.asarray([5, 17, 2, 9, 23, 0, 11, 14], dtype=np.int32)
        tim_len = out_nsamps - 64
        rows = make_row_gather(mesh, "dm", tim_len)(trials, jnp.asarray(idx))
        assert rows.shape == (8, tim_len)
        assert len(rows.sharding.device_set) == 8  # stays on the mesh
        np.testing.assert_array_equal(
            np.asarray(rows), np.asarray(trials)[idx, :tim_len]
        )

    def test_pallas_path_bitwise_on_mesh(self):
        """Per-shard Pallas blocked-roll kernel (interpret mode on the
        CPU mesh) matches the jnp sharded path and the single-device
        engine bitwise — the multi-chip analogue of dedisp's per-GPU
        kernels."""
        from peasoup_tpu.ops.dedisperse import dedisperse_device
        from peasoup_tpu.parallel.sharded_dedisperse import dedisperse_sharded

        fil = self.make_fil(nsamps=2048, nchans=32)
        delays = np.sort(self.make_delays(24, 32, max_delay=150), axis=0)
        kill = np.ones(32, dtype=np.int32)
        out_nsamps = fil.shape[0] - int(delays.max())
        mesh = make_mesh({"dm": 8})
        single = np.asarray(
            dedisperse_device(fil, delays, kill, out_nsamps, block=16)
        )
        pallas = np.asarray(
            dedisperse_sharded(
                fil, delays, kill, out_nsamps, mesh,
                use_pallas=True, interpret=True,
            )
        )
        np.testing.assert_array_equal(pallas[:24], single)
