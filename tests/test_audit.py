"""Tests for the static-analysis subsystem (peasoup-audit).

Three layers:

* the AST engine against the fixture snippets in ``tests/data/audit/``
  — each fixture annotates its own expected hits (``expect[PSAxxx]``)
  and misses (``ok:`` comments), so every rule is exercised positively
  AND negatively from one source of truth;
* the baseline ratchet + suppression mechanics;
* the contract engine against toy registered programs with injected
  hazards (f64 op, oversized constant, donation mismatch, host
  callback, trace failure) plus the real ops registry.
"""

import functools
import json
import re
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from peasoup_tpu.analysis.astlint import ModuleContext, lint_source
from peasoup_tpu.analysis.contracts import (
    ContractConfig,
    audit_program,
    audit_programs,
)
from peasoup_tpu.analysis.findings import Baseline, Finding
from peasoup_tpu.analysis.rules import all_rules
from peasoup_tpu.analysis.runner import (
    AUDIT_SCHEMA_PATH,
    render_text,
    run_audit,
    write_report,
)
from peasoup_tpu.obs.schema import SchemaError, validate
from peasoup_tpu.ops.registry import ProgramSpec, registered_programs, sds
from peasoup_tpu.tools.audit import main as audit_main

REPO_ROOT = Path(__file__).resolve().parent.parent
FIXTURE_DIR = Path(__file__).parent / "data" / "audit"
FIXTURES = sorted(FIXTURE_DIR.glob("ps[apk]*.py"))

_PATH_RE = re.compile(r"#\s*audit-path:\s*(\S+)")
_EXPECT_RE = re.compile(r"expect\[([A-Z]{3}\d{3})\]")


def _load_fixture(path: Path):
    """(source, lint-relpath, expected {(line, rule), ...})."""
    source = path.read_text()
    m = _PATH_RE.search(source)
    assert m, f"{path.name}: missing '# audit-path:' header"
    expected = set()
    for lineno, line in enumerate(source.splitlines(), 1):
        for rule in _EXPECT_RE.findall(line):
            expected.add((lineno, rule))
    return source, m.group(1), expected


class TestFixtureRules:
    """Every fixture's expect[] annotations match the engine exactly."""

    @pytest.mark.parametrize(
        "fixture", FIXTURES, ids=[p.stem for p in FIXTURES]
    )
    def test_fixture(self, fixture):
        source, relpath, expected = _load_fixture(fixture)
        assert expected, f"{fixture.name}: no expect[] annotations"
        findings, _ = lint_source(source, relpath)
        got = {(f.line, f.rule) for f in findings}
        missing = expected - got
        surprise = got - expected
        assert not missing, f"{fixture.name}: rules not raised: {missing}"
        assert not surprise, (
            f"{fixture.name}: unexpected findings: "
            f"{[(f.line, f.rule, f.message) for f in findings if (f.line, f.rule) in surprise]}"
        )

    def test_every_rule_has_positive_and_negative_coverage(self):
        """Each of the >=10 rule IDs appears in some fixture with at
        least one expected hit, and every fixture also contains clean
        lines (negative cases) the engine must NOT flag."""
        rules = set(all_rules())
        assert len(rules) >= 10
        covered = set()
        for fixture in FIXTURES:
            source, relpath, expected = _load_fixture(fixture)
            covered |= {rule for _, rule in expected}
            assert "# ok:" in source, (
                f"{fixture.name}: needs negative (ok) cases too"
            )
        assert rules <= covered, f"rules without fixtures: {rules - covered}"

    def test_syntax_error_is_a_finding_not_a_crash(self):
        findings, _ = lint_source(
            "def broken(:\n", "peasoup_tpu/ops/x.py"
        )
        assert [f.rule for f in findings] == ["PSA000"]

    def test_rules_are_path_scoped(self):
        # print() is fine in tools/, flagged in pipeline/
        src = "print('hi')\n"
        assert not lint_source(src, "peasoup_tpu/tools/x.py")[0]
        assert [
            f.rule
            for f in lint_source(src, "peasoup_tpu/pipeline/x.py")[0]
        ] == ["PSA007"]


class TestSuppressions:
    SRC = (
        "import time\n"
        "def f():\n"
        "    t0 = time.time(){comment}\n"
        "    return t0\n"
    )

    def test_reasoned_suppression_drops_finding(self):
        src = self.SRC.format(
            comment="  # audit: ignore[PSA006] -- epoch for the lease"
        )
        findings, suppressed = lint_source(src, "peasoup_tpu/obs/x.py")
        assert not findings
        assert suppressed == 1

    def test_bare_suppression_is_inactive_and_reported(self):
        src = self.SRC.format(comment="  # audit: ignore[PSA006]")
        findings, suppressed = lint_source(src, "peasoup_tpu/obs/x.py")
        assert suppressed == 0
        rules = sorted(f.rule for f in findings)
        assert rules == ["PSA000", "PSA006"]  # finding + inactive note

    def test_own_line_suppression_covers_next_code_line(self):
        src = (
            "import time\n"
            "def f():\n"
            "    # audit: ignore[PSA006] -- epoch timestamp\n"
            "    t0 = time.time()\n"
            "    return t0\n"
        )
        findings, suppressed = lint_source(src, "peasoup_tpu/obs/x.py")
        assert not findings and suppressed == 1

    def test_suppression_is_rule_specific(self):
        src = self.SRC.format(
            comment="  # audit: ignore[PSA001] -- wrong rule"
        )
        findings, _ = lint_source(src, "peasoup_tpu/obs/x.py")
        assert [f.rule for f in findings] == ["PSA006"]


class TestBaseline:
    def _findings(self, n=2, line=7):
        return [
            Finding(
                rule="PSA006",
                severity="warning",
                path="peasoup_tpu/obs/x.py",
                line=line + i,
                col=4,
                message="m",
                source_line=f"t{i} = time.time()",
            )
            for i in range(n)
        ]

    def test_round_trip(self, tmp_path):
        findings = self._findings()
        path = str(tmp_path / "baseline.json")
        Baseline.from_findings(findings).save(path)
        loaded = Baseline.load(path)
        new, old, resolved = loaded.apply(findings)
        assert not new and not resolved
        assert len(old) == 2 and all(f.baselined for f in old)

    def test_fingerprint_survives_line_shift(self):
        a = self._findings(1, line=7)[0]
        b = self._findings(1, line=99)[0]
        assert a.fingerprint == b.fingerprint

    def test_new_copy_of_baselined_hazard_still_fails(self):
        one = self._findings(1)
        baseline = Baseline.from_findings(one)
        # same stripped source line twice -> same fingerprint, count 1
        dupe = self._findings(1)[0]
        new, old, _ = baseline.apply(one + [dupe])
        assert len(old) == 1 and len(new) == 1

    def test_resolved_entries_reported(self):
        findings = self._findings(2)
        baseline = Baseline.from_findings(findings)
        new, old, resolved = baseline.apply(findings[:1])
        assert not new and len(old) == 1
        assert resolved == [findings[1].fingerprint]

    def test_rejects_foreign_schema(self, tmp_path):
        path = tmp_path / "bad.json"
        path.write_text(json.dumps({"schema": "something.else"}))
        with pytest.raises(ValueError, match="not a"):
            Baseline.load(str(path))


class TestJitScopeAnalysis:
    """The shared machinery rules lean on."""

    def test_scan_body_is_a_jit_scope(self):
        src = (
            "import jax\n"
            "def outer(xs):\n"
            "    def body(c, x):\n"
            "        return c + x, None\n"
            "    return jax.lax.scan(body, 0.0, xs)\n"
        )
        ctx = ModuleContext(src, "peasoup_tpu/ops/x.py")
        bodies = [
            info.how
            for node, info in ctx.jit_scopes.items()
            if getattr(node, "name", "") == "body"
        ]
        assert bodies == ["traced-body"]

    def test_static_argnames_are_not_tracers(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('n',))\n"
            "def f(x, n):\n"
            "    return x * n\n"
        )
        ctx = ModuleContext(src, "peasoup_tpu/ops/x.py")
        (info,) = [
            i for n, i in ctx.jit_scopes.items()
            if getattr(n, "name", "") == "f"
        ]
        assert info.static_names == {"n"}
        assert info.tracer_names() == {"x"}

    def test_metadata_reads_are_not_tracer_references(self):
        src = "def f(x):\n    return x.shape[0] + len(x)\n"
        ctx = ModuleContext(src, "peasoup_tpu/ops/x.py")
        import ast as _ast

        ret = ctx.tree.body[0].body[0].value
        assert isinstance(ret, _ast.BinOp)
        assert not ctx.references_tracer(ret, {"x"})
        src2 = "def f(x):\n    return x + 1\n"
        ctx2 = ModuleContext(src2, "peasoup_tpu/ops/x.py")
        ret2 = ctx2.tree.body[0].body[0].value
        assert ctx2.references_tracer(ret2, {"x"})


def _toy(name, fn, args, donate=(), allow=()):
    return ProgramSpec(
        name=name,
        build=lambda: (fn, args, {}),
        donate=donate,
        allow_custom_calls=allow,
    )


class TestContractEngine:
    def test_injected_f64_op_flagged(self):
        spec = _toy(
            "toy.f64",
            lambda x: x * np.float64(2.0),
            (sds((8,), "float32"),),
        )
        assert [f.rule for f in audit_program(spec)] == ["PSC101"]

    def test_oversized_constant_flagged_and_threshold_respected(self):
        big = jnp.arange(300_000, dtype=jnp.float32)  # 1.2 MB
        spec = _toy(
            "toy.const", lambda x: x + big.sum(), (sds((8,), "float32"),)
        )
        assert [f.rule for f in audit_program(spec)] == ["PSC103"]
        cfg = ContractConfig(max_const_bytes=2 << 20)
        assert not audit_program(spec, cfg)

    def test_host_callback_flagged(self):
        def cb(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2,
                jax.ShapeDtypeStruct((8,), np.float32),
                x,
            )

        spec = _toy("toy.callback", cb, (sds((8,), "float32"),))
        findings = audit_program(spec)
        assert findings and all(f.rule == "PSC102" for f in findings)
        assert "callback" in findings[0].message

    def test_donation_mismatch_both_directions(self):
        declared = _toy(
            "toy.nodonate",
            jax.jit(lambda x: x + 1),
            (sds((8,), "float32"),),
            donate=(0,),
        )
        (f,) = audit_program(declared)
        assert f.rule == "PSC104" and f.severity == "error"
        undeclared = _toy(
            "toy.donates",
            jax.jit(lambda x: x + 1, donate_argnums=(0,)),
            (sds((8,), "float32"),),
        )
        (f,) = audit_program(undeclared)
        assert f.rule == "PSC104" and f.severity == "warning"

    def test_trace_failure_is_a_finding(self):
        spec = _toy(
            "toy.broken",
            lambda x: jnp.dot(x, jnp.zeros((3, 3), jnp.float32)),
            (sds((8,), "float32"),),
        )
        (f,) = audit_program(spec)
        assert f.rule == "PSC105"

    def test_clean_program_passes(self):
        spec = _toy(
            "toy.clean",
            lambda x: (x * jnp.float32(2.0)).sum(),
            (sds((8,), "float32"),),
        )
        assert not audit_program(spec)

    def test_per_program_custom_call_allowlist(self):
        def cb(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2,
                jax.ShapeDtypeStruct((8,), np.float32),
                x,
            )

        # callbacks are flagged even when allowlisted by target name:
        # the marker check is deliberate (a host round trip is never a
        # benign custom call), so only non-callback targets can be
        # allowlisted. Verify allowlisting an ordinary target works by
        # relying on the default allowlist accepting the FFT target.
        spec = _toy(
            "toy.fft",
            lambda x: jnp.fft.rfft(x).real,
            (sds((32,), "float32"),),
        )
        assert not [
            f for f in audit_program(spec) if f.rule == "PSC102"
        ]
        spec2 = _toy("toy.cb", cb, (sds((8,), "float32"),))
        assert [f.rule for f in audit_program(spec2)] == ["PSC102"]


class TestOpsRegistry:
    def test_registry_enumerates_the_ops_programs(self):
        specs = registered_programs()
        names = [s.name for s in specs]
        assert len(names) == len(set(names))
        assert len(names) >= 15
        assert all(n.startswith("ops.") for n in names)
        # every ops module with jitted entry points contributes
        prefixes = {n.split(".")[1] for n in names}
        for mod in (
            "dedisperse", "spectrum", "rednoise", "resample",
            "harmonics", "peaks", "fold", "ffa", "singlepulse",
            "coincidence",
        ):
            assert mod in prefixes, f"no registered programs from {mod}"

    def test_real_registry_is_contract_clean(self):
        # asserted off the shared four-engine pass (one trace of the
        # registry per test session, not one per test)
        result = _full_audit_result()
        assert len(result.programs_checked) >= 15
        assert result.clean, render_text(result, verbose=True)


def render_text_findings(findings):
    return "\n".join(f.render() for f in findings)


class TestRunnerAndCLI:
    def _mini_repo(self, tmp_path, violate=True):
        pkg = tmp_path / "peasoup_tpu" / "pipeline"
        pkg.mkdir(parents=True)
        body = "print('hi')\n" if violate else "x = 1\n"
        (pkg / "mod.py").write_text(body)
        return tmp_path

    def test_exit_0_on_clean_tree(self, tmp_path, capsys):
        root = self._mini_repo(tmp_path, violate=False)
        rc = audit_main(
            ["--root", str(root), "--no-contracts", "--no-kernels", "--no-mc"]
        )
        assert rc == 0
        assert "0 new" in capsys.readouterr().out

    def test_exit_1_on_new_finding(self, tmp_path, capsys):
        root = self._mini_repo(tmp_path)
        rc = audit_main(
            ["--root", str(root), "--no-contracts", "--no-kernels", "--no-mc"]
        )
        assert rc == 1
        assert "PSA007" in capsys.readouterr().out

    def test_exit_2_on_internal_error(self, tmp_path, capsys):
        root = self._mini_repo(tmp_path)
        bad = tmp_path / "bad_baseline.json"
        bad.write_text("{not json")
        rc = audit_main(
            [
                "--root", str(root), "--no-contracts", "--no-kernels", "--no-mc",
                "--baseline", str(bad),
            ]
        )
        assert rc == 2

    def test_write_baseline_ratchet_cycle(self, tmp_path, capsys):
        root = self._mini_repo(tmp_path)
        baseline = tmp_path / "baseline.json"
        args = [
            "--root", str(root), "--no-contracts", "--no-kernels", "--no-mc",
            "--baseline", str(baseline),
        ]
        assert audit_main(args) == 1  # new finding
        assert audit_main(args + ["--write-baseline"]) == 0
        assert audit_main(args) == 0  # tolerated now
        # a second violation is NEW even with the first baselined
        mod = root / "peasoup_tpu" / "pipeline" / "mod.py"
        mod.write_text(mod.read_text() + "print('again')\n")
        assert audit_main(args) == 1
        # fix everything: stale baseline is fine unless --strict-resolved
        mod.write_text("x = 1\n")
        assert audit_main(args) == 0
        assert audit_main(args + ["--strict-resolved"]) == 1
        assert audit_main(args + ["--write-baseline"]) == 0
        data = json.loads(baseline.read_text())
        assert data["fingerprints"] == {}
        capsys.readouterr()

    def test_json_report_validates_against_checked_in_schema(
        self, tmp_path
    ):
        root = self._mini_repo(tmp_path)
        result = run_audit(str(root), contracts=False, kernels=False)
        out = tmp_path / "audit.json"
        write_report(result, str(out))
        doc = json.loads(out.read_text())
        assert doc["schema"] == "peasoup_tpu.audit"
        assert doc["summary"]["new"] == 1
        with open(AUDIT_SCHEMA_PATH) as f:
            schema = json.load(f)
        validate(doc, schema)  # double-check independently
        doc["summary"]["new"] = -1
        with pytest.raises(SchemaError):
            validate(doc, schema)

    def test_rule_filter(self, tmp_path):
        root = self._mini_repo(tmp_path)
        result = run_audit(
            str(root), contracts=False, kernels=False,
            rule_ids=["PSA006"],
        )
        assert not result.findings  # PSA007 filtered out
        with pytest.raises(ValueError, match="unknown rule ids"):
            run_audit(
                str(root), contracts=False, kernels=False,
                rule_ids=["NOPE"],
            )

    def test_list_rules(self, capsys):
        assert audit_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule_id in all_rules():
            assert rule_id in out
        assert "PSC101" in out

    def test_render_text_summarises_baselined(self):
        result = run_audit(
            str(REPO_ROOT), contracts=False, kernels=False,
            baseline_path=str(REPO_ROOT / "audit_baseline.json"),
        )
        text = render_text(result)
        assert "peasoup-audit:" in text


class TestRepoIsClean:
    """The acceptance gate: the tree audits clean with the checked-in
    baseline (AST engine here; the contract engine is covered by
    TestOpsRegistry.test_real_registry_is_contract_clean)."""

    def test_ast_engine_clean_on_repo(self):
        result = run_audit(
            str(REPO_ROOT),
            contracts=False,
            kernels=False,
            baseline_path=str(REPO_ROOT / "audit_baseline.json"),
        )
        assert result.clean, render_text(result, verbose=True)
        assert result.files_scanned > 50

    def test_cli_end_to_end_subprocess(self):
        """The exact command check.sh runs, exit code included."""
        proc = subprocess.run(
            [
                sys.executable, "-m", "peasoup_tpu.tools.audit",
                "--root", str(REPO_ROOT),
                "--baseline", str(REPO_ROOT / "audit_baseline.json"),
                "--no-contracts", "--no-kernels", "--no-mc",
            ],
            capture_output=True,
            text=True,
            cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# --------------------------------------------------------------------------
# bucket-ladder contracts (engine 2, ladder mode)
# --------------------------------------------------------------------------


def _ladder_toy(name, leak_rung=None, hook=True):
    """Toy spec whose ShapeCtx hook builds a tiny program at the ctx's
    nsamps — optionally leaking f64 at exactly one ladder rung (the
    rung-dependent drift class the ladder pass exists to catch)."""

    def param(ctx):
        if ctx.fft_size <= 0:  # accept one ctx variant per rung
            return None
        n = int(ctx.nsamps)
        factor = (
            np.float64(2.0) if n == leak_rung else np.float32(2.0)
        )
        return (lambda x: x * factor, (sds((n,), "float32"),), {})

    return ProgramSpec(
        name=name,
        build=lambda: (
            lambda x: x * jnp.float32(2.0),
            (sds((64,), "float32"),),
            {},
        ),
        param=param if hook else None,
    )


class TestLadderContracts:
    def test_rungs_walk_the_campaign_ladder(self):
        from peasoup_tpu.analysis.contracts import ladder_rungs
        from peasoup_tpu.campaign.runner import bucket_nsamps

        rungs = ladder_rungs(2048, 3)
        assert rungs == [2048, 3072, 4096]
        assert all(bucket_nsamps(r) == r for r in rungs)

    def test_ladder_ctxs_cover_every_hook_family(self):
        from peasoup_tpu.analysis.contracts import ladder_shape_ctxs

        ctxs = ladder_shape_ctxs(2048)
        assert any(c.widths for c in ctxs)  # spsearch
        assert any(c.fft_size > 0 for c in ctxs)  # search
        assert any(c.stream_chunk > 0 for c in ctxs)  # streaming
        assert any(c.subbands > 0 for c in ctxs)  # subband
        assert any(c.subband_matmul for c in ctxs)  # subband matmul
        assert any(c.nbits < 8 for c in ctxs)  # sub-byte unpacker
        assert any(c.pos25 > c.pos5 >= 0 for c in ctxs)  # rednoise

    def test_clean_toy_covers_all_rungs(self):
        from peasoup_tpu.analysis.contracts import audit_programs_ladder

        rep = audit_programs_ladder(specs=[_ladder_toy("toy.clean")])
        assert not rep.findings
        assert rep.coverage["toy.clean"] == rep.rungs

    def test_rung_only_f64_leak_is_caught_and_tagged(self):
        """The acceptance fixture: clean at the representative shapes
        AND at rung 2048, f64 at rung 3072 only — invisible to the
        representative pass, pinned by the ladder."""
        from peasoup_tpu.analysis.contracts import audit_programs_ladder

        toy = _ladder_toy("toy.rung_leak", leak_rung=3072)
        assert not audit_program(toy)  # representative shapes: clean
        rep = audit_programs_ladder(specs=[toy], rungs=[2048, 3072])
        assert [f.rule for f in rep.findings] == ["PSC101"]
        assert rep.findings[0].path == (
            "ops-registry/toy.rung_leak@nsamps=3072"
        )

    def test_missing_hook_is_a_coverage_finding(self):
        from peasoup_tpu.analysis.contracts import audit_programs_ladder

        rep = audit_programs_ladder(
            specs=[_ladder_toy("toy.nohook", hook=False)]
        )
        assert [f.rule for f in rep.findings] == ["PSC106"]
        assert rep.coverage["toy.nohook"] == []

    def test_raising_hook_is_a_finding_not_a_crash(self):
        from peasoup_tpu.analysis.contracts import audit_programs_ladder

        def bad_hook(ctx):
            raise RuntimeError("boom")

        spec = ProgramSpec(
            name="toy.raises",
            build=lambda: (lambda x: x, (sds((8,), "float32"),), {}),
            param=bad_hook,
        )
        rep = audit_programs_ladder(specs=[spec], rungs=[2048])
        assert any(f.rule == "PSC105" for f in rep.findings)
        assert any(f.rule == "PSC106" for f in rep.findings)

    def test_real_registry_is_ladder_clean(self):
        """Every registered program is covered at >= 2 rungs and no
        rung-dependent drift exists (the check.sh gate's ladder half;
        asserted off the shared four-engine pass)."""
        result = _full_audit_result()
        assert result.clean
        assert len(result.ladder_rungs) >= 2
        assert set(result.ladder_coverage) == {
            s.name for s in registered_programs()
        }
        assert all(
            len(covered) >= 2
            for covered in result.ladder_coverage.values()
        ), result.ladder_coverage


# --------------------------------------------------------------------------
# Pallas kernel contracts (engine 4)
# --------------------------------------------------------------------------


class TestKernelEngine:
    def _spec(self, **overrides):
        import dataclasses

        from peasoup_tpu.ops.pallas.registry import kernel_specs

        spec = next(
            s for s in kernel_specs() if s.name == "pallas.boxcar"
        )
        return dataclasses.replace(spec, **overrides)

    def test_real_kernel_registry_is_clean(self):
        result = _full_audit_result()
        assert len(result.kernels_checked) >= 9
        assert result.clean

    def test_registry_covers_every_pallas_module(self):
        """Every ops/pallas module that builds a kernel has a spec —
        the PSK201 cross-reference from the registry side."""
        from peasoup_tpu.ops.pallas.registry import kernel_specs

        pallas_dir = REPO_ROOT / "peasoup_tpu" / "ops" / "pallas"
        modules = {
            p.stem
            for p in pallas_dir.glob("*.py")
            if p.stem not in ("__init__", "registry")
            and "pallas_call" in p.read_text()
        }
        registered = {
            s.module.rsplit(".", 1)[-1] for s in kernel_specs()
        }
        assert modules == registered

    def test_deleted_probe_is_flagged(self):
        """The acceptance fixture: a kernel whose probe was deleted
        must fail the gate (PSK202), and run_audit maps it to new
        findings (CLI exit 1)."""
        from peasoup_tpu.analysis.kernels import audit_kernels

        doctored = self._spec(probe="probe_pallas_deleted")
        rep = audit_kernels(specs=[doctored])
        assert [f.rule for f in rep.findings] == ["PSK202"]
        assert "deleted" in rep.findings[0].message
        result = run_audit(
            str(REPO_ROOT), ast_engine=False, contracts=False,
            kernel_specs=[doctored],
        )
        assert not result.clean  # exit 1 through the CLI mapping

    def test_unreferenced_twin_is_flagged(self):
        from peasoup_tpu.analysis.kernels import audit_kernels

        doctored = self._spec(
            twin="peasoup_tpu.ops.spectrum.spectrum_stats"
        )
        rep = audit_kernels(specs=[doctored])
        assert [f.rule for f in rep.findings] == ["PSK202"]
        assert "vacuous" in rep.findings[0].message

    def test_broken_build_is_flagged(self):
        from peasoup_tpu.analysis.kernels import audit_kernels

        def broken_build(interpret=True):
            raise ValueError("geometry drifted")

        doctored = self._spec(build=broken_build)
        rep = audit_kernels(specs=[doctored])
        assert [f.rule for f in rep.findings] == ["PSK203"]

    def test_mosaic_skipped_off_tpu_and_forced_flag(self):
        from peasoup_tpu.analysis.kernels import audit_kernel

        spec = self._spec()
        # mosaic=False: interpret-only (the CPU CI path) — clean
        assert not audit_kernel(spec, mosaic=False)


# --------------------------------------------------------------------------
# the four-engine acceptance gate
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _full_audit_result():
    """ONE four-engine pass over the repo, shared by the acceptance
    tests below (the engines are deterministic and read-only; running
    them once keeps the suite inside the tier-1 wall budget)."""
    return run_audit(
        str(REPO_ROOT),
        baseline_path=str(REPO_ROOT / "audit_baseline.json"),
    )


class TestFourEngineAcceptance:
    def test_full_audit_is_clean_with_empty_baseline(self):
        """The exact check.sh gate: all four engines over the repo,
        EMPTY checked-in baseline, exit 0."""
        baseline = REPO_ROOT / "audit_baseline.json"
        assert json.loads(baseline.read_text())["fingerprints"] == {}
        result = _full_audit_result()
        assert result.clean, render_text(result, verbose=True)
        assert result.files_scanned > 100
        assert len(result.programs_checked) >= 30
        assert len(result.kernels_checked) >= 9
        assert len(result.ladder_rungs) >= 2
        assert all(
            len(v) >= 2 for v in result.ladder_coverage.values()
        ), result.ladder_coverage
        # the manifest round-trips the checked-in v2 schema
        man = result.to_manifest()
        assert man["version"] >= 2
        with open(AUDIT_SCHEMA_PATH) as f:
            validate(man, json.load(f))

    def _mini_repo(self, tmp_path, relpath, body):
        mod = tmp_path / "peasoup_tpu" / relpath
        mod.parent.mkdir(parents=True, exist_ok=True)
        mod.write_text(body)
        return tmp_path

    def test_injected_nonatomic_queue_write_exits_1(self, tmp_path):
        root = self._mini_repo(
            tmp_path, "campaign/writer.py",
            "import os\n"
            "def publish(doc, root):\n"
            "    path = os.path.join(root, 'queue', 'jobs', 'j.json')\n"
            "    with open(path, 'w') as f:\n"
            "        f.write(doc)\n",
        )
        rc = audit_main(
            ["--root", str(root), "--no-contracts", "--no-kernels", "--no-mc"]
        )
        assert rc == 1

    def test_injected_unguarded_thread_exits_1(self, tmp_path):
        root = self._mini_repo(
            tmp_path, "obs/spawn.py",
            "import threading\n"
            "def tick():\n"
            "    pass\n"
            "def go():\n"
            "    threading.Thread(target=tick, daemon=True).start()\n",
        )
        rc = audit_main(
            ["--root", str(root), "--no-contracts", "--no-kernels", "--no-mc"]
        )
        assert rc == 1

    def test_engine_toggles_silence_their_rules(self, tmp_path):
        root = self._mini_repo(
            tmp_path, "obs/spawn.py",
            "import threading\n"
            "def tick():\n"
            "    pass\n"
            "def go():\n"
            "    threading.Thread(target=tick, daemon=True).start()\n",
        )
        rc = audit_main(
            [
                "--root", str(root), "--no-contracts", "--no-kernels", "--no-mc",
                "--no-protocol",
            ]
        )
        assert rc == 0  # PSP104 is engine 3's; toggled off

    def test_rung_only_f64_leak_fails_the_gate(self):
        toy = _ladder_toy("toy.gate_leak", leak_rung=3072)
        result = run_audit(
            str(REPO_ROOT), ast_engine=False, kernels=False,
            program_specs=[toy],
        )
        assert not result.clean
        assert any(
            f.rule == "PSC101" and f.path.endswith("@nsamps=3072")
            for f in result.new
        )
