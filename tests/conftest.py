"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (SURVEY.md §4: mesh tests via
xla_force_host_platform_device_count). Must run before jax is imported.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Force CPU for tests even if the ambient env selects a TPU platform:
# numeric op tests must be deterministic and mesh tests need 8 devices.
# The axon sitecustomize overrides jax_platforms via jax.config at
# interpreter start, so the env var alone is not enough — override the
# config again before any backend initialises.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the golden-recall gate compiles the full
# search program; repeat suite runs should pay that once, not per run.
try:
    _cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "peasoup_tpu", "jax-tests",
    )
    os.makedirs(_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass  # read-only home: run without the cache

import numpy as np
import pytest


@pytest.fixture(scope="session")
def tutorial_fil():
    path = "/root/reference/example_data/tutorial.fil"
    if not os.path.exists(path):
        pytest.skip("tutorial.fil not available")
    return path


@pytest.fixture(scope="session")
def golden_xml():
    path = "/root/reference/example_output/overview.xml"
    if not os.path.exists(path):
        pytest.skip("golden overview.xml not available")
    return open(path).read()


@pytest.fixture(scope="session")
def golden_dm_list(golden_xml):
    import re

    dms = [
        float(m)
        for m in re.findall(r"<trial id='\d+'>([-\d.e+]+)</trial>", golden_xml)
    ]
    return np.array(dms[:59])


@pytest.fixture
def rng():
    return np.random.default_rng(42)
