"""Resilience-layer tests: the error taxonomy, the retry policy, the
degradation ladder, unified corrupt-artifact recovery, the fault
registry's grammar/determinism/zero-cost contract, the threaded call
sites (filterbank reads, queue claims, sqlite ingest, checkpoint
writes, OOM rungs), and the background-thread crash guard satellites.
"""

import errno
import json
import multiprocessing
import os
import sqlite3
import time

import numpy as np
import pytest

from peasoup_tpu import resilience as R
from peasoup_tpu.obs import RunTelemetry
from peasoup_tpu.resilience import faults
from peasoup_tpu.resilience.stats import STATS


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts fault-free with zeroed accounting."""
    faults.configure(None)
    STATS.reset()
    yield
    faults.configure(None)
    STATS.reset()


# --------------------------------------------------------------------------
# taxonomy
# --------------------------------------------------------------------------

class TestTaxonomy:
    @pytest.mark.parametrize(
        "exc,want",
        [
            (R.TransientIOError(errno.EIO, "x"), R.TRANSIENT),
            (OSError(errno.EIO, "x"), R.TRANSIENT),
            (OSError(errno.EAGAIN, "x"), R.TRANSIENT),
            (sqlite3.OperationalError("database is locked"), R.TRANSIENT),
            (sqlite3.OperationalError("database table is busy"),
             R.TRANSIENT),
            (TimeoutError("t"), R.TRANSIENT),
            (MemoryError(), R.RESOURCE_EXHAUSTED),
            (RuntimeError("RESOURCE_EXHAUSTED: oom"),
             R.RESOURCE_EXHAUSTED),
            (R.CorruptArtifactError("torn"), R.CORRUPT),
            (EOFError(), R.CORRUPT),
            (FileNotFoundError(2, "gone"), R.FATAL),  # protocol state
            (PermissionError(13, "denied"), R.FATAL),
            (ValueError("bad input"), R.FATAL),
            (sqlite3.OperationalError("no such table: x"), R.FATAL),
        ],
    )
    def test_classify(self, exc, want):
        assert R.classify(exc) == want

    def test_json_decode_is_corrupt(self):
        with pytest.raises(json.JSONDecodeError) as ei:
            json.loads("{torn")
        assert R.classify(ei.value) == R.CORRUPT

    def test_bad_zipfile_is_corrupt(self):
        import zipfile

        assert R.classify(zipfile.BadZipFile("torn npz")) == R.CORRUPT

    def test_worker_killed_is_not_an_exception(self):
        """The simulated SIGKILL must bypass every `except Exception`
        recovery path, like the real thing."""
        assert not isinstance(R.WorkerKilled("x"), Exception)
        assert isinstance(R.WorkerKilled("x"), BaseException)


# --------------------------------------------------------------------------
# retry policy
# --------------------------------------------------------------------------

class TestRetryPolicy:
    def test_recovers_and_emits_events(self):
        pol = R.RetryPolicy(max_attempts=3, base_delay_s=0.001)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise R.TransientIOError(errno.EIO, "flaky")
            return "ok"

        tel = RunTelemetry()
        with tel.activate():
            assert pol.call(flaky, site="t.site") == "ok"
        kinds = [e["kind"] for e in tel.events]
        assert kinds.count("resilience_retry") == 2
        assert "resilience_recovered" in kinds
        retry = next(e for e in tel.events if e["kind"] == "resilience_retry")
        assert retry["site"] == "t.site"
        assert retry["error_class"] == R.TRANSIENT
        snap = STATS.snapshot()
        assert snap["retries"]["t.site"] == 2
        assert snap["recoveries"]["t.site"] == 1

    def test_gives_up_after_budget(self):
        pol = R.RetryPolicy(max_attempts=2, base_delay_s=0.001)
        tel = RunTelemetry()
        with tel.activate(), pytest.raises(R.TransientIOError):
            pol.call(
                lambda: (_ for _ in ()).throw(
                    R.TransientIOError(errno.EIO, "always")
                ),
                site="t.giveup",
            )
        assert any(
            e["kind"] == "resilience_giveup" for e in tel.events
        )
        snap = STATS.snapshot()
        assert snap["giveups"]["t.giveup"] == 1
        assert snap["degraded"] is True

    def test_fatal_raises_immediately(self):
        pol = R.RetryPolicy(max_attempts=5, base_delay_s=0.001)
        calls = {"n": 0}

        def fatal():
            calls["n"] += 1
            raise ValueError("broken program")

        with pytest.raises(ValueError):
            pol.call(fatal, site="t.fatal")
        assert calls["n"] == 1  # no retries burned on a fatal class

    def test_deterministic_jitter(self):
        a = R.RetryPolicy(jitter=0.5)
        b = R.RetryPolicy(jitter=0.5)
        assert [a.delay(k, "s") for k in (1, 2, 3)] == [
            b.delay(k, "s") for k in (1, 2, 3)
        ]
        # and distinct sites get distinct (but stable) schedules
        assert a.delay(1, "s1") != a.delay(1, "s2")


# --------------------------------------------------------------------------
# degradation ladder
# --------------------------------------------------------------------------

class TestDegradationLadder:
    def test_steps_in_order_with_events(self):
        tel = RunTelemetry()
        with tel.activate():
            lad = R.DegradationLadder("t.lad", ("shrink", "subband", "cpu"))
            lad.step("shrink", dm_block=64)
            lad.step("shrink", dm_block=32)  # same rung repeats fine
            lad.step("subband")
            with pytest.raises(ValueError):
                lad.step("shrink")  # never climbs back up
            lad.exhausted()
        degs = [e for e in tel.events if e["kind"] == "degradation"]
        assert [d["rung"] for d in degs] == ["shrink", "shrink", "subband"]
        assert [d["rung_index"] for d in degs] == [0, 0, 1]
        assert any(
            e["kind"] == "degradation_exhausted" for e in tel.events
        )
        assert STATS.snapshot()["degradations"]["t.lad:shrink"] == 2

    def test_unknown_rung_is_a_programming_error(self):
        lad = R.DegradationLadder("t.lad2", ("a",))
        with pytest.raises(ValueError):
            lad.step("nope")


# --------------------------------------------------------------------------
# load_or_recover (the unified corrupt-artifact policy)
# --------------------------------------------------------------------------

class TestLoadOrRecover:
    def test_missing_returns_default(self, tmp_path):
        out = R.load_or_recover(
            str(tmp_path / "nope.json"),
            lambda p: json.load(open(p)),
            default={"fresh": True},
            kind="test artifact",
        )
        assert out == {"fresh": True}
        # absence is normal, not corruption
        assert STATS.snapshot()["corrupt_artifacts"] == {}

    def test_corrupt_quarantines_not_deletes(self, tmp_path, caplog):
        path = tmp_path / "art.json"
        path.write_text("{torn")
        tel = RunTelemetry()
        with caplog.at_level("WARNING", logger="peasoup_tpu"):
            with tel.activate():
                out = R.load_or_recover(
                    str(path), lambda p: json.load(open(p)),
                    default=None, kind="test artifact",
                    action="regenerating",
                )
        assert out is None
        assert not path.exists()
        q = tmp_path / "art.json.corrupt"
        assert q.exists() and q.read_text() == "{torn"  # forensics kept
        assert any(
            "discarding unreadable test artifact" in r.message
            for r in caplog.records
        )
        ev = next(e for e in tel.events if e["kind"] == "corrupt_artifact")
        assert ev["quarantined_to"] == str(q)
        assert STATS.snapshot()["corrupt_artifacts"]["test artifact"] == 1

    def test_quarantine_false_keeps_file(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text("{torn")
        out = R.load_or_recover(
            str(path), lambda p: json.load(open(p)),
            default=None, kind="baseline", quarantine=False,
        )
        assert out is None
        assert path.exists()  # checked-in files are never renamed


# --------------------------------------------------------------------------
# fault registry
# --------------------------------------------------------------------------

class TestFaultRegistry:
    def test_grammar_rejects_unknown_site_and_bad_kv(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.parse_faults("nope.site:n=1")
        with pytest.raises(ValueError, match="malformed"):
            faults.parse_faults("fil.read:n")
        with pytest.raises(ValueError, match="unknown fault option"):
            faults.parse_faults("fil.read:zz=1")

    def test_bare_site_fires_once(self):
        faults.configure("fil.read")
        with pytest.raises(R.TransientIOError, match="injected"):
            faults.fire("fil.read", "a")
        faults.fire("fil.read", "b")  # budget spent: silent

    def test_at_ordinal_and_at_context(self):
        faults.configure("db.ingest:at=2,worker.kill:at=jobX")
        faults.fire("db.ingest", "first")
        with pytest.raises(sqlite3.OperationalError, match="locked"):
            faults.fire("db.ingest", "second")
        faults.fire("worker.kill", "jobA")  # no context match
        with pytest.raises(R.WorkerKilled):
            faults.fire("worker.kill", "jobX-77")
        faults.fire("worker.kill", "jobX-77")  # fires once

    def test_probability_schedule_is_seed_deterministic(self):
        a = faults.parse_faults("fil.read:p=0.4:n=999", seed=11)
        b = faults.parse_faults("fil.read:p=0.4:n=999", seed=11)
        c = faults.parse_faults("fil.read:p=0.4:n=999", seed=12)
        draw = lambda pl: [
            pl.rules["fil.read"].should_fire("") for _ in range(64)
        ]
        da, db_, dc = draw(a), draw(b), draw(c)
        assert da == db_
        assert da != dc
        assert any(da) and not all(da)

    def test_injected_exception_is_attributable(self):
        faults.configure("checkpoint.write:n=1")
        with pytest.raises(R.TransientIOError) as ei:
            faults.fire("checkpoint.write", "ck")
        assert "[injected:checkpoint.write#1]" in str(ei.value)

    def test_env_var_activation_and_explicit_override(self, monkeypatch):
        monkeypatch.setenv("PEASOUP_FAULTS", "fil.read:n=1")
        faults._ENV_CHECKED = False  # simulate a fresh process
        assert faults.active_plan() is not None
        faults.configure(None)  # explicit wins over env
        assert faults.active_plan() is None
        faults.fire("fil.read", "x")  # disabled: no raise

    def test_disabled_fire_is_cheap_and_silent(self):
        faults.configure(None)
        t0 = time.perf_counter()
        for _ in range(10000):
            faults.fire("fil.read", "hot")
        dt = time.perf_counter() - t0
        assert dt < 0.5  # ~tens of ns/call; generous CI bound
        assert STATS.snapshot()["faults_injected"] == {}


# --------------------------------------------------------------------------
# threaded call sites
# --------------------------------------------------------------------------

def _write_tiny_fil(path, nsamps=256, nchans=4):
    from peasoup_tpu.io.sigproc import (
        Filterbank,
        SigprocHeader,
        write_filterbank,
    )

    hdr = SigprocHeader(
        source_name="T", tsamp=1e-3, fch1=1400.0, foff=-16.0,
        nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    data = np.zeros((nsamps, nchans), np.uint8) + 32
    write_filterbank(path, Filterbank(header=hdr, data=data))
    return path


class TestCallSites:
    def test_read_filterbank_survives_flaky_reads(self, tmp_path):
        from peasoup_tpu.io.sigproc import read_filterbank

        path = _write_tiny_fil(str(tmp_path / "a.fil"))
        faults.configure("fil.read:n=2")
        fil = read_filterbank(path)
        assert fil.nsamps == 256
        snap = STATS.snapshot()
        assert snap["faults_injected"]["fil.read"] == 2
        assert snap["recoveries"]["fil.read"] == 1

    def test_read_filterbank_gives_up_when_budget_spent(self, tmp_path):
        from peasoup_tpu.io.sigproc import read_filterbank

        path = _write_tiny_fil(str(tmp_path / "b.fil"))
        faults.configure("fil.read:n=99")
        with pytest.raises(R.TransientIOError):
            read_filterbank(path)
        assert STATS.snapshot()["giveups"]["fil.read"] == 1

    def test_short_read_is_transient_then_fatal(self, tmp_path):
        """A payload shorter than the header's declared nsamples (a
        recorder still appending, or a torn copy) is transient: it
        retries, then raises when the budget is spent. Needs an
        explicit nsamples header keyword — without one the reader
        derives nsamples from the file size and can't see the tear."""
        import struct

        from peasoup_tpu.io.sigproc import read_filterbank

        def ws(f, s):
            b = s.encode()
            f.write(struct.pack("<i", len(b)))
            f.write(b)

        path = str(tmp_path / "c.fil")
        with open(path, "wb") as f:
            ws(f, "HEADER_START")
            for key, val in (
                ("nchans", 4), ("nbits", 8), ("nsamples", 256),
                ("nifs", 1), ("data_type", 1),
            ):
                ws(f, key)
                f.write(struct.pack("<i", val))
            for key, val in (
                ("tsamp", 1e-3), ("fch1", 1400.0), ("foff", -16.0),
            ):
                ws(f, key)
                f.write(struct.pack("<d", val))
            ws(f, "HEADER_END")
            f.write(b"\x20" * (256 * 4 - 64))  # 64 bytes short
        with pytest.raises(R.TransientIOError, match="short read"):
            read_filterbank(path)
        assert STATS.snapshot()["retries"]["fil.read"] >= 1

    def test_queue_claim_survives_injected_io_failure(self, tmp_path):
        from peasoup_tpu.campaign.queue import Job, JobQueue

        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="j1", input="x.fil"))
        faults.configure("queue.claim:n=1")
        claim = q.try_claim("j1", "w1")
        assert claim is not None  # retried through the injection
        assert STATS.snapshot()["recoveries"]["queue.claim"] == 1

    def test_checkpoint_write_retries_and_load_quarantines(self, tmp_path):
        from peasoup_tpu.pipeline.checkpoint import SearchCheckpoint

        base = str(tmp_path / "s.ckpt")
        payload = {
            0: (
                np.zeros((2, 4), np.int32),
                np.zeros((4,), np.float32),
                np.asarray(0, np.int32),
            )
        }
        ck = SearchCheckpoint(base, "k")
        faults.configure("checkpoint.write:n=1")
        ck.save(payload)  # survives the injected write failure
        assert sorted(ck.load()) == [0]
        assert STATS.snapshot()["recoveries"]["checkpoint.write"] == 1
        # now corrupt on disk: load quarantines (satellite migration of
        # the old discard-with-warning contract)
        faults.configure(None)
        with open(base, "r+b") as f:
            f.truncate(20)
        assert ck.load() == {}
        assert os.path.exists(base + ".corrupt")
        assert not os.path.exists(base)
        # a fresh save over the damage fully recovers
        ck.save(payload)
        assert sorted(ck.load()) == [0]

    def test_checkpoint_slice_corrupt_sibling_quarantined(self, tmp_path):
        """A damaged per-slice store must not poison the union load,
        and its .corrupt quarantine must not re-enter _store_files."""
        from peasoup_tpu.pipeline.checkpoint import SearchCheckpoint

        base = str(tmp_path / "m.ckpt")

        def payload(k):
            return {
                0: (
                    np.full((2, 4), k, np.int32),
                    np.zeros((4,), np.float32),
                    np.asarray(0, np.int32),
                )
            }

        SearchCheckpoint(base, "k", slice_bounds=(0, 4)).save(payload(0))
        SearchCheckpoint(base, "k", slice_bounds=(4, 8)).save(payload(4))
        with open(base + ".dm4-8", "r+b") as f:
            f.truncate(10)
        union = SearchCheckpoint(base, "k").load()
        assert sorted(union) == [0]
        assert os.path.exists(base + ".dm4-8.corrupt")
        # and a second load does not trip over the quarantined file
        assert sorted(SearchCheckpoint(base, "k").load()) == [0]

    def test_cache_corrupt_fault_drills_tuning_recovery(self, tmp_path):
        from peasoup_tpu.perf import tuning

        path = str(tmp_path / "tc.json")
        tuning.save_cache(path, {
            "schema": tuning.TUNING_SCHEMA,
            "version": tuning.TUNING_VERSION,
            "devices": {},
        })
        faults.configure("cache.corrupt:n=1")
        doc = tuning.load_cache(path)  # injected corruption -> empty
        assert doc["devices"] == {}
        assert os.path.exists(path + ".corrupt")
        snap = STATS.snapshot()
        assert snap["faults_injected"]["cache.corrupt"] == 1
        assert snap["corrupt_artifacts"]["tuning cache"] == 1

    def test_db_ingest_retries_through_injected_lock(self, tmp_path):
        """The injected SQLITE_BUSY drill: the ingest transaction is
        retried whole and lands exactly once."""
        from peasoup_tpu.campaign.db import CandidateDB

        job_dir = tmp_path / "job"
        _make_overview(str(job_dir))
        faults.configure("db.ingest:n=2")
        with CandidateDB(str(tmp_path / "c.sqlite")) as db:
            counts = db.ingest_job("j1", str(job_dir), "in.fil")
            assert counts["single_pulse"] == 1
            assert len(db.candidates_for("j1")) == 1
        snap = STATS.snapshot()
        assert snap["retries"]["db.ingest"] == 2
        assert snap["recoveries"]["db.ingest"] == 1


def _make_overview(job_dir):
    """A minimal real overview.xml via the production writer."""
    from peasoup_tpu.core.candidates import SinglePulseCandidate
    from peasoup_tpu.io.output import OutputFileWriter
    from peasoup_tpu.io.sigproc import SigprocHeader
    from peasoup_tpu.pipeline.single_pulse import SinglePulseConfig

    os.makedirs(job_dir, exist_ok=True)
    hdr = SigprocHeader(
        source_name="T", tsamp=1e-3, fch1=1400.0, foff=-16.0,
        nchans=4, nbits=8, nifs=1, data_type=1, nsamples=256,
    )
    cand = SinglePulseCandidate(
        dm=10.0, dm_idx=3, snr=9.5, time_s=0.1, sample=100, width=4,
        width_idx=2, members=5,
    )
    w = OutputFileWriter()
    w.add_misc_info()
    w.add_header(hdr)
    w.add_dm_list(np.asarray([0.0, 5.0, 10.0]))
    w.add_single_pulse_section(
        SinglePulseConfig(), "in.fil", (1, 2, 4), [cand]
    )
    w.to_file(os.path.join(job_dir, "overview.xml"))


class TestTwoProcessDBContention:
    def test_racing_ingesters_both_land(self, tmp_path):
        """Satellite regression: a second PROCESS holding the write
        lock must surface as busy/locked and be absorbed by the retry
        layer, with both writes landing (tiny busy_timeout forces the
        contention through OUR policy instead of sqlite's wait)."""
        from peasoup_tpu.campaign.db import CandidateDB

        db_path = str(tmp_path / "c.sqlite")
        job_dir = str(tmp_path / "job")
        _make_overview(job_dir)
        # schema init up front so the subprocess needs no setup
        CandidateDB(db_path).close()
        ctx = multiprocessing.get_context("spawn")
        started = ctx.Event()
        proc = ctx.Process(
            target=_hold_write_lock, args=(db_path, started, 0.2)
        )
        proc.start()
        try:
            assert started.wait(10.0)
            with CandidateDB(db_path, busy_timeout_ms=20) as db:
                db.ingest_job("j1", job_dir, "in.fil")
        finally:
            proc.join(10.0)
        assert proc.exitcode == 0
        with CandidateDB(db_path) as db:
            assert len(db.candidates_for("j1")) == 1
            rows = db._query(
                "SELECT COUNT(*) AS n FROM candidates "
                "WHERE job_id = 'locker'"
            )
            assert rows[0]["n"] == 1
        assert STATS.snapshot()["retries"].get("db.ingest", 0) >= 1


def _hold_write_lock(db_path, started, hold_s):
    conn = sqlite3.connect(db_path, timeout=10.0)
    conn.execute("PRAGMA busy_timeout=10000")
    conn.execute("BEGIN IMMEDIATE")
    conn.execute(
        "INSERT INTO observations (job_id, input) VALUES ('locker', 'x')"
    )
    conn.execute(
        "INSERT INTO candidates (job_id, kind, dm, snr) "
        "VALUES ('locker', 'single_pulse', 1.0, 9.0)"
    )
    started.set()
    time.sleep(hold_s)
    conn.commit()
    conn.close()


# --------------------------------------------------------------------------
# degradation rungs fire in order, bitwise-equal where guaranteed
# --------------------------------------------------------------------------

class TestDegradationRungs:
    def test_sp_oom_rung_fires_and_results_match(self, tmp_path):
        """device.oom injection at the single-pulse wave dispatch:
        the shrink rung fires, emits its ladder event, and the
        candidate set is bitwise-equal to the fault-free run (the
        ladder's guarantee for the shrink rung)."""
        from test_campaign import make_obs

        from peasoup_tpu.io.sigproc import read_filterbank
        from peasoup_tpu.pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )

        path = make_obs(str(tmp_path / "o.fil"))
        fil = read_filterbank(path)
        cfg = SinglePulseConfig(
            dm_end=20.0, min_snr=7.0, n_widths=6, dm_block=8,
            outdir=str(tmp_path),
        )
        want = SinglePulseSearch(cfg).run(fil)

        faults.configure("device.oom:at=1")
        tel = RunTelemetry()
        with tel.activate():
            got = SinglePulseSearch(cfg).run(fil)
        degs = [e for e in tel.events if e["kind"] == "degradation"]
        assert degs and degs[0]["ladder"] == "spsearch.memory"
        assert degs[0]["rung"] == "dm_block_shrink"
        assert any(
            e["kind"] == "sp_oom_shrink_retry" for e in tel.events
        )
        assert len(got.candidates) == len(want.candidates) > 0
        for a, b in zip(want.candidates, got.candidates):
            assert (a.dm_idx, a.sample, a.width) == (
                b.dm_idx, b.sample, b.width
            )
            assert a.snr == b.snr  # bitwise: same shapes per trial


# --------------------------------------------------------------------------
# background-thread crash guard (satellite)
# --------------------------------------------------------------------------

class TestThreadCrashGuard:
    def test_guard_thread_emits_event_and_degrades(self):
        tel = RunTelemetry()

        def boom():
            raise RuntimeError("thread bug")

        exc = R.guard_thread("t-thread", boom, telemetry=tel)
        assert isinstance(exc, RuntimeError)
        ev = next(e for e in tel.events if e["kind"] == "thread_crashed")
        assert ev["thread"] == "t-thread"
        snap = STATS.snapshot()
        assert snap["thread_crashes"]["t-thread"] == 1
        assert snap["degraded"] is True
        # ... which every run's status section now reports
        assert tel.snapshot_sections()["resilience"]["degraded"] is True

    def test_warmer_crash_does_not_kill_the_job(self, tmp_path, monkeypatch):
        """Satellite: a crashing _BucketWarmer thread must emit
        thread_crashed on the job's telemetry and leave the campaign
        job runnable (warmup is an optimisation, not a dependency)."""
        from peasoup_tpu.campaign import runner as runner_mod
        from peasoup_tpu.campaign.runner import _BucketWarmer

        def explode(*a, **k):
            raise RuntimeError("warmup bug")

        monkeypatch.setattr(
            "peasoup_tpu.perf.warmup.warm_bucket", explode
        )
        tel = RunTelemetry()
        w = _BucketWarmer(
            (4, 8, 256, 1e-3, 1400.0, -16.0), "spsearch", {},
            str(tmp_path / "scratch"), "dryrun", telemetry=tel,
        )
        w.start()
        stats = w.result(timeout=30.0)
        assert "crashed" in stats["error"]
        assert any(
            e["kind"] == "thread_crashed"
            and e["thread"] == "campaign-warmup"
            for e in tel.events
        )
        assert STATS.snapshot()["thread_crashes"]["campaign-warmup"] == 1
        assert runner_mod is not None  # keep the import referenced

    def test_stream_reader_crash_is_structured(self, tmp_path):
        """Satellite: the stream reader thread emits thread_crashed
        (plus the existing stream_reader_error) instead of dying
        invisibly."""
        from peasoup_tpu.stream.driver import StreamConfig, StreamingSearch

        self._outdir = tmp_path

        from peasoup_tpu.io.stream_source import StreamFormat

        class ExplodingSource:
            format = StreamFormat(
                nchans=4, nbits=8, tsamp=1e-3, fch1=1400.0, foff=-16.0
            )
            block_samples = 64

            def blocks(self):
                raise RuntimeError("reader bug")
                yield  # pragma: no cover

            def close(self):
                pass

        cfg = StreamConfig(
            outdir=str(self._outdir), dm_end=5.0, chunk_samples=128,
            n_widths=3, decimate=8, warmup=False,
        )
        tel = RunTelemetry()
        with tel.activate(), pytest.raises(RuntimeError):
            StreamingSearch(cfg).run(ExplodingSource())
        kinds = [e["kind"] for e in tel.events]
        assert "thread_crashed" in kinds
        assert "stream_reader_error" in kinds
        assert STATS.snapshot()["thread_crashes"][
            "peasoup-stream-reader"
        ] == 1

    def test_clock_skew_reap_degrades_to_extra_attempt(self, tmp_path):
        """clock.skew drill: a reaper whose clock runs fast reaps a
        live claim early — the job burns one attempt but is never
        lost (it re-queues claimable), and the injection is
        attributable in the stats."""
        from peasoup_tpu.campaign.queue import Job, JobQueue

        q = JobQueue(str(tmp_path), lease_s=30.0, backoff_base_s=0.0)
        q.add_job(Job(job_id="j1", input="x.fil"))
        claim = q.try_claim("j1", "w1")
        assert claim is not None
        faults.configure("clock.skew:skew=3600")
        reaped = q.reap_stale()
        assert reaped == ["j1"]  # skewed clock saw the lease expired
        faults.configure(None)
        job = q.get_job("j1")
        assert job.attempts == 1  # one attempt burned, job not lost
        assert q.state("j1") in ("pending", "backoff")
        assert q.try_claim("j1", "w2") is not None  # still claimable
        assert STATS.snapshot()["faults_injected"]["clock.skew"] == 1


# --------------------------------------------------------------------------
# multihost fault sites (barrier / merge)
# --------------------------------------------------------------------------

class TestMultihostFaultSites:
    def test_barrier_injection_is_transient(self):
        """A host dying at the allgather barrier must fail the step
        classified TRANSIENT (fast, retryable) — never hang."""
        from peasoup_tpu.parallel.multihost import _allgather_pickled

        faults.configure("multihost.barrier:n=1")
        with pytest.raises(R.TransientIOError) as ei:
            _allgather_pickled(b"payload", context="search:candidates")
        assert R.classify(ei.value) == R.TRANSIENT
        assert "[injected:multihost.barrier#1]" in str(ei.value)
        # budget spent: the single-process identity path works again
        assert _allgather_pickled(b"payload", context="x") == [b"payload"]
        assert STATS.snapshot()["faults_injected"]["multihost.barrier"] == 1

    def test_merge_injection_is_transient(self):
        import pickle

        from peasoup_tpu.parallel.multihost import _unpickle_all

        blob = pickle.dumps({"cands": [1, 2]})
        faults.configure("multihost.merge:n=1")
        with pytest.raises(R.TransientIOError) as ei:
            _unpickle_all([blob], context="spsearch:events")
        assert R.classify(ei.value) == R.TRANSIENT
        assert _unpickle_all([blob], context="x") == [{"cands": [1, 2]}]
        assert STATS.snapshot()["faults_injected"]["multihost.merge"] == 1

    def test_real_collective_error_reclassified_transient(self):
        """A distributed-runtime failure signature (coordinator
        deadline, dropped connection) re-raises as TransientIOError;
        a programming error propagates unchanged."""
        from peasoup_tpu.parallel.multihost import (
            _classify_collective_error,
        )

        with pytest.raises(R.TransientIOError):
            _classify_collective_error(
                RuntimeError("DEADLINE_EXCEEDED: barrier timed out"),
                "search:candidates",
            )
        with pytest.raises(ValueError, match="bad shape"):
            _classify_collective_error(ValueError("bad shape"), "x")

    def test_sites_zero_cost_when_off(self):
        faults.configure(None)
        t0 = time.perf_counter()
        for _ in range(10000):
            faults.fire("multihost.barrier", "hot")
            faults.fire("multihost.merge", "hot")
        assert time.perf_counter() - t0 < 0.5
        assert STATS.snapshot()["faults_injected"] == {}


# --------------------------------------------------------------------------
# cache.corrupt through the persistent XLA compilation cache
# --------------------------------------------------------------------------

class TestCacheCorruptWarmup:
    @pytest.fixture()
    def scratch_cache(self, tmp_path, monkeypatch):
        """Point the persistent compilation cache at a scratch dir for
        the duration (resetting jax's lazily-initialised cache object
        so the dir change takes effect mid-process), restoring the
        suite's shared cache after."""
        import jax

        def _reset():
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:
                pass

        cache = str(tmp_path / "xla_cache")
        old = jax.config.jax_compilation_cache_dir
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", cache)
        _reset()
        yield cache
        jax.config.update("jax_compilation_cache_dir", old)
        _reset()

    def test_garbled_entry_quarantines_and_recompiles(self, scratch_cache):
        """The acceptance drill: a cache.corrupt injection during
        warmup quarantines the persistent cache's entries to
        ``*.corrupt`` and the program recompiles — warmup reports NO
        error, and the quarantine is attributable."""
        import glob as _glob

        from peasoup_tpu.ops.registry import registered_programs
        from peasoup_tpu.perf.warmup import warm_registry
        from peasoup_tpu.utils.cache import cache_entry_paths

        name = registered_programs()[0].name
        cold = warm_registry(programs=[name])
        assert cold.programs[0].error is None
        entries = cache_entry_paths(scratch_cache)
        assert entries  # the cold compile populated the cache
        # garble a real entry's bytes, then schedule the injection
        faults.configure("cache.corrupt:n=1")
        faults.maybe_corrupt_file(entries[0], context="xla-cache-entry")
        faults.configure("cache.corrupt:n=1")  # re-arm for the seam
        rep = warm_registry(programs=[name])
        assert rep.programs[0].error is None  # recovered, not crashed
        corrupt = _glob.glob(os.path.join(scratch_cache, "*.corrupt"))
        assert corrupt  # forensics kept aside
        assert cache_entry_paths(scratch_cache) == []  # all quarantined
        snap = STATS.snapshot()
        assert snap["corrupt_artifacts"]["xla cache"] >= 1
        assert snap["faults_injected"]["cache.corrupt"] >= 1
        # and a clean pass repopulates the cache from scratch
        faults.configure(None)
        again = warm_registry(programs=[name])
        assert again.programs[0].error is None

    def test_quarantine_helper_renames_not_deletes(self, tmp_path):
        from peasoup_tpu.utils.cache import (
            cache_entry_paths,
            quarantine_cache_entries,
        )

        d = tmp_path / "cache"
        d.mkdir()
        (d / "entry1").write_bytes(b"\x00CHAOS-CORRUPT\x00")
        (d / "entry2").write_bytes(b"fine")
        q = quarantine_cache_entries(str(d))
        assert len(q) == 2
        assert (d / "entry1.corrupt").read_bytes().startswith(b"\x00CHAOS")
        assert cache_entry_paths(str(d)) == []
        assert STATS.snapshot()["corrupt_artifacts"]["xla cache"] == 1

    def test_non_corrupt_compile_error_still_reported(self, scratch_cache):
        """A genuine trace/compile failure must NOT trigger the cache
        quarantine — it is a finding, not a torn artifact."""
        from peasoup_tpu.perf.warmup import _compile_with_cache_recovery

        import jax

        def broken(x):
            raise ValueError("genuine trace bug")

        err = _compile_with_cache_recovery(
            jax, broken, (jax.ShapeDtypeStruct((4,), "float32"),), {},
            "broken", scratch_cache,
        )
        assert err is not None and "genuine trace bug" in err
        assert STATS.snapshot()["corrupt_artifacts"] == {}


# --------------------------------------------------------------------------
# device.oom fall-through: shrink -> (subband ->) CPU instead of raising
# --------------------------------------------------------------------------

class TestOOMFallThrough:
    def test_sp_exhaustion_falls_through_to_cpu_bitwise(self, tmp_path):
        """Single-pulse driver: exhausting the shrink rung
        (dm_block=4 -> 2 -> 1, three injections) steps the cpu_backend
        rung instead of raising, and the candidates are bitwise-equal
        to the fault-free run."""
        from test_campaign import make_obs

        from peasoup_tpu.io.sigproc import read_filterbank
        from peasoup_tpu.pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )

        path = make_obs(str(tmp_path / "o.fil"))
        fil = read_filterbank(path)
        cfg = SinglePulseConfig(
            dm_end=20.0, min_snr=7.0, n_widths=6, dm_block=4,
            outdir=str(tmp_path),
        )
        want = SinglePulseSearch(cfg).run(fil)
        faults.configure("device.oom:n=3")
        tel = RunTelemetry()
        with tel.activate():
            got = SinglePulseSearch(cfg).run(fil)
        rungs = [
            (e["ladder"], e["rung"]) for e in tel.events
            if e["kind"] == "degradation"
        ]
        assert rungs == [
            ("spsearch.memory", "dm_block_shrink"),
            ("spsearch.memory", "dm_block_shrink"),
            ("spsearch.memory", "cpu_backend"),
        ]
        assert not any(
            e["kind"] == "degradation_exhausted" for e in tel.events
        )
        assert len(got.candidates) == len(want.candidates) > 0
        for a, b in zip(want.candidates, got.candidates):
            assert (a.dm_idx, a.sample, a.width) == (
                b.dm_idx, b.sample, b.width
            )
            assert a.snr == b.snr  # bitwise
        assert STATS.snapshot()["degradations"][
            "spsearch.memory:cpu_backend"
        ] == 1

    def test_search_falls_through_subband_then_cpu_bitwise(self, tmp_path):
        """Periodicity driver: three injections exhaust the shrink
        rung into the exact-subband rung; a fourth OOMs the subband
        attempt into the CPU rung. Both paths must produce candidates
        bitwise-equal to the fault-free run (max_smear=0 subbanding is
        the direct sum; the CPU rung re-runs the identical programs)."""
        import numpy as np

        from peasoup_tpu.io.sigproc import read_filterbank
        from peasoup_tpu.perf.warmup import synthetic_bucket_observation
        from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig

        bucket = (8, 8, 4096, 0.000256, 1400.0, -16.0)
        fil = synthetic_bucket_observation(
            bucket, str(tmp_path / "o.fil")
        )
        cfg = SearchConfig(
            dm_end=20.0, min_snr=7.0, dm_block=4, outdir=str(tmp_path),
            limit=50,
        )
        want = PeasoupSearch(cfg).run(fil)
        assert len(want.candidates) > 0  # the pulse train is periodic

        def sig(res):
            return [
                (c.dm_idx, c.nh, c.acc, c.freq, c.snr)
                for c in res.candidates
            ]

        # n=3: shrink x2 -> subband rung runs clean
        faults.configure("device.oom:n=3")
        tel = RunTelemetry()
        with tel.activate():
            got = PeasoupSearch(cfg).run(fil)
        rungs = [
            e["rung"] for e in tel.events if e["kind"] == "degradation"
        ]
        assert rungs == ["dm_block_shrink", "dm_block_shrink", "subband"]
        assert sig(got) == sig(want)

        # n=6: the subband rung's own shrink sequence (restarted at
        # the full block) OOMs to the floor too -> CPU rung
        faults.configure("device.oom:n=6")
        tel = RunTelemetry()
        with tel.activate():
            got2 = PeasoupSearch(cfg).run(fil)
        rungs2 = [
            e["rung"] for e in tel.events if e["kind"] == "degradation"
        ]
        # in-rung shrinks after the subband step are events, not
        # ladder steps (a ladder never climbs back up)
        assert rungs2 == [
            "dm_block_shrink", "dm_block_shrink", "subband", "cpu_backend",
        ]
        assert sum(
            1 for e in tel.events if e["kind"] == "oom_shrink_retry"
        ) == 4
        assert sig(got2) == sig(want)
        assert np.isfinite([c.snr for c in got2.candidates]).all()

    def test_degraded_flag_lands_in_done_record(self, tmp_path):
        """A campaign job that descended a ladder completes with
        degraded=true in its done record (and the rollup tallies it)."""
        from test_campaign import make_obs

        from peasoup_tpu.campaign.queue import JobQueue, job_id_for
        from peasoup_tpu.campaign.rollup import build_status
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            bucket_for_input,
            enqueue_entries,
            run_worker,
            save_campaign_config,
        )

        root = str(tmp_path / "camp")
        obs = make_obs(str(tmp_path / "o.fil"))
        save_campaign_config(
            root,
            CampaignConfig(
                warmup=False,
                config={
                    "dm_end": 20.0, "min_snr": 7.0, "n_widths": 6,
                    "dm_block": 4,
                },
            ),
        )
        q = JobQueue(root)
        enqueue_entries(q, [{"input": obs}], "spsearch")
        faults.configure("device.oom:n=3")  # exhausts into the cpu rung
        tally = run_worker(root, worker_id="w1", poll_s=0.05)
        faults.configure(None)
        assert tally["done"] == 1
        [done] = q.done_records()
        assert done["degraded"] is True
        assert done["resilience"]["degradations"][
            "spsearch.memory:cpu_backend"
        ] == 1
        st = build_status(root, q)
        assert st["degraded_jobs"] == 1
