"""Campaign orchestration tests: queue semantics under racing workers,
stale-claim reaping, retry/backoff/quarantine, shape buckets, the
candidate database, the rollup, and the end-to-end acceptance run
(2 concurrent workers over a 4-observation manifest with one corrupt
file, compiled-program reuse asserted from the telemetry JIT stats).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from peasoup_tpu.campaign.queue import Job, JobQueue, job_id_for
from peasoup_tpu.campaign.rollup import build_status, write_status
from peasoup_tpu.campaign.runner import (
    CampaignConfig,
    CampaignRunner,
    bucket_for_input,
    bucket_nsamps,
    enqueue_entries,
    pad_to_nsamps,
    parse_manifest,
    save_campaign_config,
)
from peasoup_tpu.io.sigproc import (
    Filterbank,
    SigprocHeader,
    read_filterbank,
    write_filterbank,
)
from peasoup_tpu.plan.dm_plan import DMPlan


def make_obs(
    path, nsamps=4096, nchans=8, seed=0, tsamp=0.000256, fch1=1400.0,
    foff=-16.0, dm_end=20.0, amp=14.0,
):
    """Tiny observation with one dispersed pulse at the middle trial."""
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=dm_end, pulse_width=64.0, tol=1.10,
    )
    delays = plan.delay_samples()[plan.ndm // 2]
    rng = np.random.default_rng(seed)
    data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
    for c in range(nchans):
        data[1500 + delays[c] : 1504 + delays[c], c] += amp
    hdr = SigprocHeader(
        source_name=f"OBS{seed}", tsamp=tsamp, tstart=55000.0 + seed,
        fch1=fch1, foff=foff, nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    write_filterbank(
        path,
        Filterbank(
            header=hdr,
            data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
        ),
    )
    return path


def make_corrupt_obs(path, donor):
    """Valid-looking start, truncated INSIDE the sigproc header — the
    reader raises 'unterminated sigproc header' deterministically."""
    with open(donor, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[:40])
    return path


def enqueue_n(queue, n, bucket=(8, 8, 4096)):
    for i in range(n):
        queue.add_job(
            Job(job_id=f"job{i:02d}", input=f"/nonexistent/{i}.fil",
                bucket=bucket)
        )


# --------------------------------------------------------------------------
# queue semantics
# --------------------------------------------------------------------------

class TestQueueSemantics:
    def test_enqueue_idempotent(self, tmp_path):
        q = JobQueue(str(tmp_path))
        job = Job(job_id="a", input="x.fil")
        assert q.add_job(job) is True
        assert q.add_job(job) is False
        assert q.job_ids() == ["a"]

    def test_two_workers_race_exactly_once(self, tmp_path):
        """ISSUE satellite: two workers hammering one queue process
        each job exactly once — the O_EXCL claim is the only winner
        selection."""
        q1 = JobQueue(str(tmp_path), lease_s=30.0)
        q2 = JobQueue(str(tmp_path), lease_s=30.0)
        enqueue_n(q1, 20)
        processed: dict[str, list] = {"w1": [], "w2": []}

        def worker(q, name):
            while True:
                claim = q.claim_next(name)
                if claim is None:
                    if q.drained():
                        return
                    time.sleep(0.005)
                    continue
                processed[name].append(claim.job.job_id)
                q.complete(claim)

        t1 = threading.Thread(target=worker, args=(q1, "w1"))
        t2 = threading.Thread(target=worker, args=(q2, "w2"))
        t1.start(); t2.start()
        t1.join(timeout=30); t2.join(timeout=30)
        everything = processed["w1"] + processed["w2"]
        assert sorted(everything) == sorted(set(everything))  # no dupes
        assert len(everything) == 20  # no losses
        assert q1.counts()["done"] == 20
        # the work was actually shared (both won at least one claim)
        assert processed["w1"] and processed["w2"]

    def test_stale_claim_reaped_after_sigkill(self, tmp_path):
        """ISSUE satellite: a SIGKILLed worker never releases — its
        lease expires and any other worker re-queues the job (one
        failed attempt consumed)."""
        q = JobQueue(str(tmp_path), lease_s=0.1, max_attempts=5)
        enqueue_n(q, 1)
        claim = q.try_claim("job00", "doomed-worker")
        assert claim is not None
        # the doomed worker is SIGKILLed here: no release, no renewal
        assert q.state("job00") == "running"
        time.sleep(0.15)
        assert q.state("job00") == "stale"
        reaped = q.reap_stale()
        assert reaped == ["job00"]
        job = q.get_job("job00")
        assert job.attempts == 1
        assert "lease expired" in job.last_error
        assert "doomed-worker" in job.last_error
        # re-queued: another worker claims it once the backoff elapses
        time.sleep(q.backoff_base_s * 1.1)
        c2 = q.claim_next("rescuer")
        assert c2 is not None and c2.job.job_id == "job00"

    def test_renewed_claim_survives_reaper(self, tmp_path):
        q = JobQueue(str(tmp_path), lease_s=0.1)
        enqueue_n(q, 1)
        claim = q.try_claim("job00", "alive")
        time.sleep(0.12)
        q.renew(claim)  # live worker: lease fresh again
        assert q.reap_stale() == []
        assert q.state("job00") == "running"

    def test_backoff_then_quarantine_then_retry(self, tmp_path):
        """ISSUE satellite: N failures land in quarantine; `retry`
        re-queues with a reset budget."""
        q = JobQueue(
            str(tmp_path), lease_s=30.0, max_attempts=3,
            backoff_base_s=0.05,
        )
        enqueue_n(q, 1)
        for attempt in range(1, 4):
            deadline = time.time() + 5
            claim = None
            while claim is None and time.time() < deadline:
                claim = q.claim_next("w")
                if claim is None:
                    time.sleep(0.01)  # exponential backoff in effect
            assert claim is not None, f"attempt {attempt} never eligible"
            state = q.fail(claim, f"boom {attempt}")
            assert state == ("quarantined" if attempt == 3 else "backoff")
        assert q.state("job00") == "quarantined"
        assert q.claim_next("w") is None  # never claimed again
        rows = q.quarantined()
        assert len(rows) == 1 and rows[0]["attempts"] == 3
        assert "boom 3" in rows[0]["last_error"]

        assert q.retry("job00") is True
        assert q.state("job00") == "pending"
        assert q.get_job("job00").attempts == 0
        assert q.claim_next("w") is not None
        # retry of a non-quarantined job is a no-op
        assert q.retry("job00") is False

    def test_backoff_is_exponential(self, tmp_path):
        q = JobQueue(
            str(tmp_path), lease_s=30.0, max_attempts=10,
            backoff_base_s=2.0,
        )
        enqueue_n(q, 1)
        delays = []
        for _ in range(3):
            jid = "job00"
            job = q.get_job(jid)
            job.next_eligible_unix = 0.0  # force eligibility
            q._record_failure(jid, "x")
            delays.append(q.get_job(jid).next_eligible_unix - time.time())
        assert delays[0] == pytest.approx(2.0, abs=0.5)
        assert delays[1] == pytest.approx(4.0, abs=0.5)
        assert delays[2] == pytest.approx(8.0, abs=0.5)

    def test_claim_next_prefers_previous_bucket(self, tmp_path):
        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="a1", input="a1.fil", bucket=(8, 8, 1024)))
        q.add_job(Job(job_id="b1", input="b1.fil", bucket=(8, 8, 2048)))
        q.add_job(Job(job_id="a2", input="a2.fil", bucket=(8, 8, 1024)))
        c = q.claim_next("w", prefer_bucket=(8, 8, 2048))
        assert c.job.job_id == "b1"
        # with b-bucket drained, the remainder comes grouped by bucket
        c2 = q.claim_next("w", prefer_bucket=(8, 8, 2048))
        assert c2.job.bucket == (8, 8, 1024)


# --------------------------------------------------------------------------
# buckets + padding
# --------------------------------------------------------------------------

class TestBuckets:
    def test_ladder_rungs(self):
        assert bucket_nsamps(4096) == 4096
        assert bucket_nsamps(4097) == 6144  # 3 * 2048
        assert bucket_nsamps(6145) == 8192
        assert bucket_nsamps(3900) == 4096
        assert bucket_nsamps(3072) == 3072
        # worst-case padding on the default ladder (rungs at 1x and
        # 1.5x per octave) stays under 50%
        for n in range(1000, 20000, 7):
            assert n <= bucket_nsamps(n) < n * 1.5

    def test_explicit_ladder(self):
        assert bucket_nsamps(1000, [512, 2048]) == 2048
        # beyond the explicit ladder: default rungs take over
        assert bucket_nsamps(5000, [512, 2048]) == 6144

    def test_pad_to_nsamps_median_fill(self, tmp_path):
        path = make_obs(str(tmp_path / "o.fil"), nsamps=4000)
        fil = read_filterbank(path)
        padded, orig = pad_to_nsamps(fil, 4096)
        assert orig == 4000
        assert padded.nsamps == 4096
        assert padded.header.nsamples == 4096
        med = np.median(fil.data, axis=0)
        assert np.array_equal(
            padded.data[4000:],
            np.broadcast_to(
                np.rint(med).astype(np.uint8), (96, fil.nchans)
            ),
        )
        # already at (or beyond) target: untouched
        same, orig2 = pad_to_nsamps(fil, 4000)
        assert same is fil and orig2 == 4000

    def test_bucket_for_input(self, tmp_path):
        p1 = make_obs(str(tmp_path / "a.fil"), nsamps=4000)
        p2 = make_obs(str(tmp_path / "b.fil"), nsamps=3900, seed=1)
        p3 = make_obs(str(tmp_path / "c.fil"), nsamps=8192, seed=2)
        b1, b2, b3 = (bucket_for_input(p) for p in (p1, p2, p3))
        assert b1 == b2  # both pad to 4096: one compiled program set
        assert b1 != b3
        corrupt = make_corrupt_obs(str(tmp_path / "x.fil"), p1)
        assert bucket_for_input(corrupt) is None


# --------------------------------------------------------------------------
# manifest parsing
# --------------------------------------------------------------------------

class TestManifest:
    def test_paths_json_lines_comments(self, tmp_path):
        man = tmp_path / "obs.txt"
        man.write_text(
            "# survey night 1\n"
            "rel.fil\n"
            "/abs/path.fil\n"
            "\n"
            '{"input": "j.fil", "config": {"min_snr": 8.5}}\n'
        )
        entries = parse_manifest(str(man))
        assert entries[0]["input"] == str(tmp_path / "rel.fil")
        assert entries[1]["input"] == "/abs/path.fil"
        assert entries[2]["input"] == str(tmp_path / "j.fil")
        assert entries[2]["config"] == {"min_snr": 8.5}

    def test_enqueue_entries_idempotent_and_validating(self, tmp_path):
        q = JobQueue(str(tmp_path))
        entries = [{"input": str(tmp_path / "a.fil")}]
        assert enqueue_entries(q, entries, "spsearch") == 1
        assert enqueue_entries(q, entries, "spsearch") == 0
        with pytest.raises(ValueError, match="unknown pipeline"):
            enqueue_entries(
                q, [{"input": "b.fil", "pipeline": "nope"}], "spsearch"
            )

    def test_job_id_stable_and_distinct(self):
        assert job_id_for("/a/obs.fil") == job_id_for("/a/obs.fil")
        assert job_id_for("/a/obs.fil") != job_id_for("/b/obs.fil")


# --------------------------------------------------------------------------
# rollup
# --------------------------------------------------------------------------

class TestRollup:
    def test_states_and_failures_land_in_status(self, tmp_path):
        root = str(tmp_path)
        q = JobQueue(root, lease_s=30.0, max_attempts=3,
                     backoff_base_s=60.0)
        enqueue_n(q, 4)
        done = q.try_claim("job00", "w")
        q.complete(done, n_candidates=7)
        q.fail(q.try_claim("job01", "w"), "transient oops")
        running = q.try_claim("job02", "w")
        assert running is not None
        doc = write_status(root, q)
        assert doc["schema"] == "peasoup_tpu.campaign_status"
        assert doc["queue"]["total"] == 4
        assert doc["queue"]["done"] == 1
        assert doc["queue"]["running"] == 1
        assert doc["queue"]["backoff"] == 1
        assert doc["queue"]["pending"] == 1
        assert doc["done"] is False
        assert doc["candidates_total"] == 7
        assert doc["running_jobs"][0]["job_id"] == "job02"
        [fl] = doc["failures"]
        assert fl["job_id"] == "job01" and "oops" in fl["last_error"]
        # the file itself round-trips
        with open(os.path.join(root, "campaign_status.json")) as f:
            assert json.load(f) == doc

    def test_throughput_and_eta(self, tmp_path):
        root = str(tmp_path)
        q = JobQueue(root, lease_s=30.0)
        enqueue_n(q, 4)
        for i in range(2):
            q.complete(q.try_claim(f"job{i:02d}", "w"))
        # synthesise spaced finish times for a deterministic rate
        for i, t in ((0, 100.0), (1, 200.0)):
            p = os.path.join(root, "queue", "done", f"job{i:02d}.json")
            with open(p) as f:
                doc = json.load(f)
            doc["finished_unix"] = t
            with open(p, "w") as f:
                json.dump(doc, f)
        st = build_status(root, q)
        assert st["throughput_jobs_per_s"] == pytest.approx(0.01)
        assert st["eta_s"] == pytest.approx(200.0)


# --------------------------------------------------------------------------
# end-to-end acceptance
# --------------------------------------------------------------------------

class TestCampaignEndToEnd:
    def test_two_workers_four_obs_with_corruption(self, tmp_path):
        """ISSUE acceptance: a 4-observation manifest with 2 concurrent
        workers — every observation processed exactly once, the corrupt
        one quarantined after its retry budget, candidates from all
        completed jobs queryable in sqlite, and a same-bucket successor
        compiling 0 new XLA programs (telemetry JIT stats)."""
        data = tmp_path / "data"
        data.mkdir()
        # three lengths, one shape bucket (all pad to 4096)
        paths = [
            make_obs(str(data / f"obs{i}.fil"), nsamps=n, seed=i)
            for i, n in enumerate((4096, 4000, 3900))
        ]
        corrupt = make_corrupt_obs(str(data / "bad.fil"), paths[0])
        root = str(tmp_path / "camp")
        campaign = save_campaign_config(
            root,
            CampaignConfig(
                pipeline="spsearch",
                config={"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6},
                lease_s=30.0,
                max_attempts=2,
                backoff_base_s=0.05,
                heartbeat_interval=0.2,
            ),
        )
        queue = JobQueue(
            root, lease_s=campaign.lease_s,
            max_attempts=campaign.max_attempts,
            backoff_base_s=campaign.backoff_base_s,
        )
        entries = [{"input": p} for p in paths + [corrupt]]
        assert enqueue_entries(queue, entries, "spsearch") == 4

        runners = [
            CampaignRunner(root, worker_id=f"w{i}") for i in (1, 2)
        ]
        tallies = [None, None]

        def work(i):
            tallies[i] = runners[i].run(poll_s=0.05)

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert all(not t.is_alive() for t in threads)

        # every good observation done exactly once, corrupt quarantined
        counts = queue.counts()
        assert counts == {
            "total": 4, "pending": 0, "backoff": 0, "running": 0,
            "stale": 0, "done": 3, "quarantined": 1, "throttled": 0,
        }
        done = queue.done_records()
        assert sorted(d["job_id"] for d in done) == sorted(
            job_id_for(p) for p in paths
        )
        total_done = sum(t["done"] for t in tallies)
        assert total_done == 3  # 3 completions across both workers
        [quarantined] = queue.quarantined()
        assert quarantined["job_id"] == job_id_for(corrupt)
        assert quarantined["attempts"] == 2
        assert "unterminated sigproc header" in quarantined["last_error"]

        # compiled-program reuse under AOT warmup: same bucket
        # everywhere, and each worker warms the bucket on a background
        # thread before its first job dispatches — so the FIRST job of
        # the bucket reports 0 new XLA compilations exactly like its
        # warm-bucket successors (the compiles happened in warmup,
        # attributed to no job)
        by_finish = sorted(
            done, key=lambda d: float(d["finished_unix"])
        )
        assert all(d["bucket"] == by_finish[0]["bucket"] for d in done)
        assert by_finish[0]["jit_programs_compiled"] == 0
        assert all(d["jit_programs_compiled"] == 0 for d in done)
        # the warmup itself is on the record: the first-of-bucket job
        # of at least one worker carries its warmup stats
        warmed = [d for d in done if d.get("warmup_s") is not None]
        assert warmed, "no done record carries warmup stats"
        assert all(d["warmup_s"] > 0 for d in warmed)
        assert all(d["warmup"]["error"] is None for d in warmed)

        # per-job observability stack: heartbeat + manifest per job dir
        from peasoup_tpu.obs.schema import validate_manifest
        from peasoup_tpu.obs.telemetry import load_manifest

        for d in done:
            job_dir = os.path.join(root, "jobs", d["job_id"])
            man = load_manifest(os.path.join(job_dir, "telemetry.json"))
            validate_manifest(man)
            assert man["context"]["command"] == "campaign-job"
            with open(os.path.join(job_dir, "status.json")) as f:
                hb = json.load(f)
            assert hb["done"] is True
            assert os.path.exists(
                os.path.join(job_dir, "candidates.singlepulse")
            )

        # survey DB: candidates from ALL completed jobs queryable
        from peasoup_tpu.campaign.db import CandidateDB

        with CandidateDB(
            os.path.join(root, "candidates.sqlite")
        ) as db:
            stats = db.counts()
            assert stats["observations"] == 3
            assert stats["candidates"]["single_pulse"] >= 3
            top = db.top_candidates(kind="single_pulse", limit=10)
            assert {t["job_id"] for t in top} == {
                job_id_for(p) for p in paths
            }
            assert all(t["snr"] >= 7.0 for t in top)
            # injected pulse lands at the same DM in every observation
            dms = {round(t["dm"], 3) for t in top[:3]}
            assert len(dms) == 1

        # rollup: schema-valid, complete, quarantine tallied, warmup
        # seconds aggregated
        st = build_status(root, queue)
        assert st["done"] is True
        assert st["queue"]["done"] == 3
        assert [q["job_id"] for q in st["quarantined"]] == [
            job_id_for(corrupt)
        ]
        assert st["warmup_jobs"] == len(warmed)
        assert st["warmup_total_s"] == pytest.approx(
            sum(d["warmup_s"] for d in warmed)
        )

        # retry re-queues the quarantined job and a worker re-fails it
        # back into quarantine (the input really is corrupt)
        assert queue.retry(job_id_for(corrupt))
        tally = CampaignRunner(root, worker_id="w3").run(poll_s=0.05)
        assert tally["quarantined"] == 1
        assert queue.counts()["quarantined"] == 1

    def test_ingest_idempotent_reingest(self, tmp_path):
        """campaign ingest: re-ingesting a job replaces, not
        duplicates, its rows."""
        from peasoup_tpu.campaign.db import CandidateDB

        path = make_obs(str(tmp_path / "o.fil"))
        root = str(tmp_path / "camp")
        save_campaign_config(
            root,
            CampaignConfig(
                pipeline="spsearch",
                config={"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6},
                backoff_base_s=0.05,
            ),
        )
        queue = JobQueue(root)
        enqueue_entries(queue, [{"input": path}], "spsearch")
        CampaignRunner(root, worker_id="w").run(poll_s=0.05)
        jid = job_id_for(path)
        db_path = os.path.join(root, "candidates.sqlite")
        with CandidateDB(db_path) as db:
            n1 = len(db.candidates_for(jid))
            assert n1 >= 1
            db.ingest_job(jid, os.path.join(root, "jobs", jid), path)
            assert len(db.candidates_for(jid)) == n1


# --------------------------------------------------------------------------
# the ffa campaign pipeline (satellite) + quarantine pruning (satellite)
# --------------------------------------------------------------------------

def make_periodic_obs(path, nsamps=1 << 14, nchans=8, tsamp=0.008, P=2.51):
    """Observation with a strong slow pulsar (no dispersion) for the
    FFA pipeline: ~50 pulses of period P over nsamps*tsamp seconds."""
    rng = np.random.default_rng(7)
    t = np.arange(nsamps) * tsamp
    pulse = 40.0 * ((t % P) / P < 0.03)
    data = np.clip(
        rng.normal(100, 6, size=(nsamps, nchans)) + pulse[:, None],
        0, 255,
    ).astype(np.uint8)
    hdr = SigprocHeader(
        source_name="FFAOBS", tsamp=tsamp, tstart=55000.0, fch1=1500.0,
        foff=-1.0, nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    write_filterbank(path, Filterbank(header=hdr, data=data))
    return path


class TestFFACampaignPipeline:
    def test_ffa_job_end_to_end(self, tmp_path):
        """Satellite: pipeline 'ffa' dispatches the FFA driver through
        the same bucket/telemetry/done-record path as the other
        pipelines — the injected pulsar comes back in candidates.ffa,
        the overview.xml parses through the existing periodicity
        reader, and the candidates ingest into the campaign DB."""
        from peasoup_tpu.campaign.db import CandidateDB
        from peasoup_tpu.campaign.runner import run_worker
        from peasoup_tpu.obs.schema import validate_manifest
        from peasoup_tpu.tools.parsers import OverviewFile

        P = 2.51
        root = str(tmp_path / "camp")
        obs = make_periodic_obs(str(tmp_path / "ffa.fil"))
        save_campaign_config(
            root,
            CampaignConfig(
                pipeline="ffa",
                warmup=False,
                config={
                    "dm_end": 5.0, "p_start": 1.0, "p_end": 6.0,
                    "min_dc": 0.01, "min_snr": 8.0,
                },
            ),
        )
        q = JobQueue(root)
        enqueue_entries(q, [{"input": obs}], "ffa")
        tally = run_worker(root, worker_id="w1", poll_s=0.05)
        assert tally == {
            "done": 1, "failed": 0, "quarantined": 0, "released": 0,
            "lost": 0,
        }
        jid = q.job_ids()[0]
        [done] = q.done_records()
        assert done["pipeline"] == "ffa"
        assert done["bucket"] is not None  # same shape-bucket path
        assert done["n_candidates"] >= 1
        job_dir = os.path.join(root, "jobs", jid)
        # the text table holds the injected period
        with open(os.path.join(job_dir, "candidates.ffa")) as f:
            rows = [
                ln.split() for ln in f if not ln.startswith("#")
            ]
        periods = [float(r[0]) for r in rows]
        assert any(abs(p - P) / P < 2e-3 for p in periods), periods
        # overview.xml parses through the existing periodicity reader
        ov = OverviewFile(os.path.join(job_dir, "overview.xml"))
        assert len(ov.candidates) == len(rows)
        assert any(
            abs(float(c["period"]) - P) / P < 2e-3 for c in ov.candidates
        )
        assert ov.dm_list.size >= 1
        # telemetry manifest valid, with the ffa stage timers
        with open(os.path.join(job_dir, "telemetry.json")) as f:
            man = json.load(f)
        validate_manifest(man)
        assert "ffa_search" in man["timers"]
        # ... and the DB ingested the rows as periodicity candidates
        with CandidateDB(os.path.join(root, "candidates.sqlite")) as db:
            cands = db.candidates_for(jid)
        assert len(cands) == len(rows)
        assert all(c["kind"] == "periodicity" for c in cands)

    def test_manifest_accepts_ffa_and_priority(self, tmp_path):
        obs = make_obs(str(tmp_path / "a.fil"))
        q = JobQueue(str(tmp_path / "c"))
        n = enqueue_entries(
            q,
            [{"input": obs, "pipeline": "ffa", "priority": 4}],
            "spsearch",
        )
        assert n == 1
        job = q.get_job(q.job_ids()[0])
        assert job.pipeline == "ffa"
        assert job.priority == 4

    def test_unknown_pipeline_still_rejected(self, tmp_path):
        obs = make_obs(str(tmp_path / "a.fil"))
        q = JobQueue(str(tmp_path / "c"))
        with pytest.raises(ValueError, match="unknown pipeline"):
            enqueue_entries(q, [{"input": obs, "pipeline": "nope"}], "nope")


class TestPruneCorrupt:
    def _plant(self, root, age_days=0.0):
        jobs = os.path.join(root, "jobs", "j1")
        os.makedirs(jobs, exist_ok=True)
        path = os.path.join(jobs, "search.ckpt.npz.corrupt")
        with open(path, "w") as f:
            f.write("torn bytes")
        if age_days:
            old = time.time() - age_days * 86400
            os.utime(path, (old, old))
        return path

    def test_prune_dry_run_keeps_files(self, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path)
        path = self._plant(root, age_days=3)
        rc = main(
            ["prune", "-w", root, "--corrupt", "--dry-run"]
        )
        assert rc == 0
        assert os.path.exists(path)
        out = capsys.readouterr().out
        assert "would delete 1" in out

    def test_prune_respects_age_filter(self, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path)
        old = self._plant(root, age_days=10)
        fresh = os.path.join(root, "tuning_cache.json.corrupt")
        with open(fresh, "w") as f:
            f.write("{torn")
        rc = main(
            ["prune", "-w", root, "--corrupt", "--older-than-days", "7"]
        )
        assert rc == 0
        assert not os.path.exists(old)
        assert os.path.exists(fresh)  # younger than the cutoff
        # the rollup counts what remains
        q = JobQueue(root)
        q.add_job(Job(job_id="j", input="x.fil"))
        st = build_status(root, q)
        assert st["corrupt_artifact_files"] == 1

    def test_prune_requires_a_selector(self, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        assert main(["prune", "-w", str(tmp_path)]) == 1
        assert "--corrupt" in capsys.readouterr().out


class TestPruneProfiles:
    """ISSUE 15 satellite: profile-capture retention —
    `peasoup-campaign prune --profiles --older-than-days N` over the
    on-demand jax.profiler capture dirs, counted in the rollup."""

    def _plant_capture(self, root, name, age_days=0.0, nbytes=64):
        cap = os.path.join(root, "profiles", name)
        os.makedirs(cap, exist_ok=True)
        with open(os.path.join(cap, "trace.json.gz"), "wb") as f:
            f.write(b"x" * nbytes)
        if age_days:
            old = time.time() - age_days * 86400
            os.utime(cap, (old, old))
        return cap

    def test_rollup_counts_capture_dirs(self, tmp_path):
        root = str(tmp_path)
        self._plant_capture(root, "w1-100", nbytes=100)
        self._plant_capture(root, "w2-200", nbytes=50)
        q = JobQueue(root)
        q.add_job(Job(job_id="j", input="x.fil"))
        st = build_status(root, q)
        assert st["profiles"] == {"captures": 2, "bytes": 150}

    def test_prune_profiles_respects_age_and_dry_run(
        self, tmp_path, capsys
    ):
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path)
        old = self._plant_capture(root, "w1-100", age_days=10)
        fresh = self._plant_capture(root, "w1-200")
        rc = main(
            [
                "prune", "-w", root, "--profiles",
                "--older-than-days", "7", "--dry-run",
            ]
        )
        assert rc == 0
        assert os.path.isdir(old) and os.path.isdir(fresh)
        assert "would delete 1" in capsys.readouterr().out
        rc = main(
            [
                "prune", "-w", root, "--profiles",
                "--older-than-days", "7",
            ]
        )
        assert rc == 0
        assert not os.path.exists(old)
        assert os.path.isdir(fresh)  # younger than the cutoff
        q = JobQueue(root)
        q.add_job(Job(job_id="j", input="x.fil"))
        st = build_status(root, q)
        assert st["profiles"]["captures"] == 1

    def test_prune_both_selectors_compose(self, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path)
        cap = self._plant_capture(root, "w1-100", age_days=2)
        bad = os.path.join(root, "x.json.corrupt")
        with open(bad, "w") as f:
            f.write("{torn")
        rc = main(["prune", "-w", root, "--profiles", "--corrupt"])
        assert rc == 0
        assert not os.path.exists(cap) and not os.path.exists(bad)
        assert "deleted 2" in capsys.readouterr().out
