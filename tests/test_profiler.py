"""End-to-end profiler smoke test (VERDICT r1 item 8).

Captures a jax.profiler.trace of a tiny search run and asserts the
reference's four NVTX span names (SURVEY section 5: "Dedisperse",
"DM-Loop" as host TraceAnnotations; "Acceleration-Loop",
"Harmonic summing" as named_scope op metadata inside the jitted
program) are all present in the captured trace.
"""

import glob
import gzip
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu.io import read_filterbank
from peasoup_tpu.pipeline import PeasoupSearch, SearchConfig

from test_pipeline import make_synthetic_fil


def test_trace_contains_host_spans(tmp_path):
    """Host-side TraceAnnotations ("Dedisperse", "DM-Loop") appear in a
    captured jax.profiler trace of a real tiny run."""
    path, _, _ = make_synthetic_fil(tmp_path, nsamps=1 << 13, nchans=8)
    fil = read_filterbank(str(path))
    cfg = SearchConfig(dm_end=20.0, nharmonics=2, npdmp=0, limit=20)
    search = PeasoupSearch(cfg)
    search.run(fil)  # compile outside the trace

    tdir = str(tmp_path / "trace")
    with jax.profiler.trace(tdir):
        search.run(fil)

    files = glob.glob(
        os.path.join(tdir, "**", "*.trace.json.gz"), recursive=True
    )
    assert files, f"no trace file captured under {tdir}"
    text = ""
    for f in files:
        events = json.load(gzip.open(f))
        text += json.dumps(events)

    for span in ("Dedisperse", "DM-Loop"):
        assert span in text, f"span {span!r} missing from profiler trace"


def test_jitted_program_carries_device_scopes():
    """The in-jit named_scope spans ("Acceleration-Loop",
    "Harmonic summing", NVTX parity: pipeline_multi.cu:207,
    harmonicfolder.hpp:28) are baked into the program's op metadata —
    device profiles group the covered ops under them."""
    import jax.numpy as jnp

    from peasoup_tpu.pipeline.accel_search import search_block_core
    from peasoup_tpu.pipeline.search import _level_windows

    size, nharms = 2048, 2
    tims = jnp.zeros((2, size), jnp.uint8)
    afs = jnp.zeros((2, 2), jnp.float32)
    zap = jnp.zeros(size // 2 + 1, bool)
    win = jnp.asarray(_level_windows(size, nharms, 0.1, 1100.0, 0.000256))
    lowered = jax.jit(
        lambda t, a: search_block_core(
            t, a, zap, win, threshold=6.0, size=size, nsamps_valid=size,
            nharms=nharms, max_peaks=16, pos5=8, pos25=80,
        )
    ).lower(tims, afs)
    try:
        text = lowered.as_text(debug_info=True)
    except TypeError:
        # this toolchain predates the debug_info kwarg AND strips
        # location metadata from the plain rendering — the scope
        # names exist but are unobservable here
        pytest.skip("Lowered.as_text lacks debug_info on this jax")
    assert "Acceleration-Loop" in text
    assert "Harmonic summing" in text
