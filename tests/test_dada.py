"""DadaHeader parity tests (reference: include/data_types/header.hpp:52-161)
plus the write path (tofile/write_dada) the streaming replay source and
the stream tests use to synthesise valid DADA segments."""

import os

import numpy as np
import pytest

from peasoup_tpu.io.dada import DADA_HDR_SIZE, DadaHeader, write_dada

HDR = """HDR_VERSION 1.0
HDR_SIZE 4096
BW 400
FREQ 1382.0
NANT 1
NCHAN 1024
NDIM 2
NPOL 1
NBIT 8
TSAMP 0.00064
SOURCE J0437-4715
RA 04:37:15.8
DEC -47:15:09.1
TELESCOPE MeerKAT
INSTRUMENT CBF
OBS_OFFSET 0
FILE_SIZE 8388608
BYTES_PER_SECOND 1600000000
UTC_START 2014-02-13-05:52:12
ANT_ID 3
FILE_NUMBER 7
"""


def _write_dada(path, payload_bytes):
    raw = HDR.encode().ljust(DADA_HDR_SIZE, b"\x00")
    with open(path, "wb") as f:
        f.write(raw)
        f.write(np.zeros(payload_bytes, dtype=np.uint8).tobytes())


def test_dada_header_roundtrip(tmp_path):
    path = tmp_path / "x.dada"
    _write_dada(path, 1024 * 2 * 100)  # nchan*2*nsamps
    h = DadaHeader.fromfile(path)
    assert h.header_version == 1.0
    assert h.nchan == 1024 and h.nbit == 8 and h.npol == 1
    assert h.freq == 1382.0 and h.bw == 400.0
    assert h.tsamp == 0.00064
    assert h.source_name == "J0437-4715"
    assert h.telescope == "MeerKAT" and h.ant_id == 3 and h.file_no == 7
    assert h.utc_start == "2014-02-13-05:52:12"
    assert h.filesize == 1024 * 2 * 100
    # reference quirk: nsamples = filesize/nchan/nant/npol/2
    assert h.nsamples == 100
    assert h.dada_filesize == 8388608


def test_dada_missing_keys_are_defaults(tmp_path):
    path = tmp_path / "y.dada"
    with open(path, "wb") as f:
        f.write(b"HDR_VERSION 1.0\n".ljust(DADA_HDR_SIZE, b"\x00"))
    h = DadaHeader.fromfile(path)
    assert h.nchan == 0 and h.source_name == "" and h.nsamples == 0


def test_dada_comment_lines_are_ignored(tmp_path):
    path = tmp_path / "c.dada"
    hdr = (
        "# recorder dump v2\n"
        "# NCHAN 9999  (commented out: must not shadow the live key)\n"
        "HDR_VERSION 1.0\n"
        "NCHAN 512\n"
        "NBIT 8\n"
        "  # indented comment with FREQ 1.0 inside\n"
        "FREQ 1284.0\n"
    )
    with open(path, "wb") as f:
        f.write(hdr.encode().ljust(DADA_HDR_SIZE, b"\x00"))
    h = DadaHeader.fromfile(path)
    assert h.nchan == 512
    assert h.freq == 1284.0


def test_dada_trailing_nuls_do_not_leak_into_values(tmp_path):
    path = tmp_path / "n.dada"
    # last key/value flush against the NUL padding (no trailing \n)
    hdr = b"HDR_VERSION 1.0\nSOURCE J1234-56"
    with open(path, "wb") as f:
        f.write(hdr.ljust(DADA_HDR_SIZE, b"\x00"))
        f.write(b"\x00" * 64)
    h = DadaHeader.fromfile(path)
    assert h.source_name == "J1234-56"


def test_dada_tofile_roundtrip(tmp_path):
    path = tmp_path / "rt.dada"
    payload = np.arange(1024 * 2 * 10, dtype=np.uint8)
    src = DadaHeader(
        header_version=1.0, bw=400.0, freq=1382.0, nant=1, nchan=1024,
        ndim=2, npol=1, nbit=8, tsamp=0.00064,
        source_name="J0437-4715", ra="04:37:15.8", dec="-47:15:09.1",
        telescope="MeerKAT", instrument="CBF", dada_filesize=8388608,
        bytes_per_sec=1600000000, utc_start="2014-02-13-05:52:12",
        ant_id=3, file_no=7,
    )
    src.tofile(path, payload)
    assert os.path.getsize(path) == DADA_HDR_SIZE + payload.size
    h = DadaHeader.fromfile(path)
    for fname in (
        "header_version", "bw", "freq", "nant", "nchan", "ndim",
        "npol", "nbit", "tsamp", "source_name", "ra", "dec",
        "telescope", "instrument", "dada_filesize", "bytes_per_sec",
        "utc_start", "ant_id", "file_no",
    ):
        assert getattr(h, fname) == getattr(src, fname), fname
    assert h.filesize == payload.size
    # reference quirk preserved: nsamples = filesize/nchan/nant/npol/2
    assert h.nsamples == 10


def test_write_dada_helper(tmp_path):
    path = tmp_path / "w.dada"
    payload = np.zeros((100, 16), dtype=np.uint8)
    h = write_dada(path, payload, nchan=16, nbit=8, freq=1284.0, bw=64.0)
    assert h.nchan == 16
    back = DadaHeader.fromfile(path)
    assert back.nchan == 16 and back.freq == 1284.0 and back.bw == 64.0
    assert back.filesize == payload.size


def test_dada_tofile_rejects_oversized_header(tmp_path):
    h = DadaHeader(source_name="x" * (DADA_HDR_SIZE + 1))
    with pytest.raises(ValueError, match="exceeds"):
        h.tofile(tmp_path / "big.dada")
