"""DadaHeader parity tests (reference: include/data_types/header.hpp:52-161)."""

import numpy as np

from peasoup_tpu.io.dada import DADA_HDR_SIZE, DadaHeader

HDR = """HDR_VERSION 1.0
HDR_SIZE 4096
BW 400
FREQ 1382.0
NANT 1
NCHAN 1024
NDIM 2
NPOL 1
NBIT 8
TSAMP 0.00064
SOURCE J0437-4715
RA 04:37:15.8
DEC -47:15:09.1
TELESCOPE MeerKAT
INSTRUMENT CBF
OBS_OFFSET 0
FILE_SIZE 8388608
BYTES_PER_SECOND 1600000000
UTC_START 2014-02-13-05:52:12
ANT_ID 3
FILE_NUMBER 7
"""


def _write_dada(path, payload_bytes):
    raw = HDR.encode().ljust(DADA_HDR_SIZE, b"\x00")
    with open(path, "wb") as f:
        f.write(raw)
        f.write(np.zeros(payload_bytes, dtype=np.uint8).tobytes())


def test_dada_header_roundtrip(tmp_path):
    path = tmp_path / "x.dada"
    _write_dada(path, 1024 * 2 * 100)  # nchan*2*nsamps
    h = DadaHeader.fromfile(path)
    assert h.header_version == 1.0
    assert h.nchan == 1024 and h.nbit == 8 and h.npol == 1
    assert h.freq == 1382.0 and h.bw == 400.0
    assert h.tsamp == 0.00064
    assert h.source_name == "J0437-4715"
    assert h.telescope == "MeerKAT" and h.ant_id == 3 and h.file_no == 7
    assert h.utc_start == "2014-02-13-05:52:12"
    assert h.filesize == 1024 * 2 * 100
    # reference quirk: nsamples = filesize/nchan/nant/npol/2
    assert h.nsamples == 100
    assert h.dada_filesize == 8388608


def test_dada_missing_keys_are_defaults(tmp_path):
    path = tmp_path / "y.dada"
    with open(path, "wb") as f:
        f.write(b"HDR_VERSION 1.0\n".ljust(DADA_HDR_SIZE, b"\x00"))
    h = DadaHeader.fromfile(path)
    assert h.nchan == 0 and h.source_name == "" and h.nsamples == 0
