"""Native C++ host runtime: parity against the pure-Python oracles."""

import numpy as np
import pytest

from peasoup_tpu import native
from peasoup_tpu.core import Candidate
from peasoup_tpu.io.sigproc import pack_bits
from peasoup_tpu.pipeline.distill import (
    AccelerationDistiller,
    DMDistiller,
    HarmonicDistiller,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@pytest.mark.parametrize("nbits", [1, 2, 4, 8])
def test_unpack_bits_parity(nbits, rng):
    samples = rng.integers(0, 1 << nbits, size=4096).astype(np.uint8)
    packed = pack_bits(samples, nbits)
    out = native.unpack_bits(packed, nbits)
    np.testing.assert_array_equal(out, samples)


def test_cluster_peaks_parity(rng):
    # random sparse crossings, ascending indices
    from peasoup_tpu.ops import peaks as peaks_mod

    n = 500
    idxs = np.sort(rng.choice(100000, size=n, replace=False)).astype(np.int32)
    snrs = rng.uniform(9, 50, size=n).astype(np.float32)

    nat = native.cluster_peaks(idxs, snrs, n, 30)
    # force the Python path
    py_idx, py_snr = [], []
    ii = 0
    while ii < n:
        cpeak, cidx, last = snrs[ii], idxs[ii], idxs[ii]
        ii += 1
        while ii < n and (idxs[ii] - last) < 30:
            if snrs[ii] > cpeak:
                cpeak, cidx, last = snrs[ii], idxs[ii], idxs[ii]
            ii += 1
        py_idx.append(cidx)
        py_snr.append(cpeak)
    np.testing.assert_array_equal(nat[0], py_idx)
    np.testing.assert_allclose(nat[1], py_snr, rtol=1e-6)


def random_cands(rng, n=300):
    cands = []
    for _ in range(n):
        f0 = rng.uniform(0.5, 100.0)
        # half the candidates are near-harmonics of a smaller set
        if rng.random() < 0.5 and cands:
            base = cands[rng.integers(0, len(cands))]
            f0 = base.freq * rng.integers(1, 5) * (1 + rng.normal(0, 3e-5))
        cands.append(
            Candidate(
                dm=float(rng.uniform(0, 100)),
                dm_idx=int(rng.integers(0, 50)),
                acc=float(rng.choice([-5.0, 0.0, 5.0])),
                nh=int(rng.integers(0, 5)),
                snr=float(rng.uniform(9, 100)),
                freq=float(f0),
            )
        )
    return cands


def clone(cands):
    return [
        Candidate(dm=c.dm, dm_idx=c.dm_idx, acc=c.acc, nh=c.nh, snr=c.snr,
                  freq=c.freq)
        for c in cands
    ]


def summarize(cands):
    return [(round(c.freq, 9), round(c.snr, 5), c.count_assoc()) for c in cands]


@pytest.mark.parametrize(
    "maker",
    [
        lambda: HarmonicDistiller(1e-4, 16, keep_related=True),
        lambda: HarmonicDistiller(1e-4, 16, keep_related=True,
                                  fractional_harms=False),
        lambda: AccelerationDistiller(40.0, 1e-4, keep_related=True),
        lambda: DMDistiller(1e-4, keep_related=True),
        lambda: DMDistiller(1e-4, keep_related=False),
    ],
)
def test_distill_parity(maker, rng):
    cands = random_cands(rng)
    d_native = maker()
    out_native = d_native.distill(clone(cands))

    d_python = maker()
    d_python._native = lambda cands: None  # force the Python loop
    out_python = d_python.distill(clone(cands))

    assert summarize(out_native) == summarize(out_python)


def test_distill_empty_and_single():
    d = DMDistiller(1e-4, keep_related=True)
    assert d.distill([]) == []
    one = [Candidate(freq=10.0, snr=20.0)]
    assert len(d.distill(one)) == 1
