"""The Fourier-domain acceleration search (ISSUE 19): template-bank
math, the batched correlation program, and end-to-end recovery of
injected accelerated/jerked pulsars through the FDAS driver.

The injection recipes are the SAME conventions the device code claims:

* constant acceleration uses the time-domain resampler's inverse map
  (tests/test_accel_recovery.py) so the identical filterbank feeds
  both search paths — the cross-validation gate asserts FDAS and the
  resampling search agree on (P, acc, DM);
* jerk uses the template's own phase model
  ``phi(u) = b0*u + z*u^2/2 + w*u^3/6`` (u = t/T), so a detection at
  trial (z, w) proves the bank's sign/centre conventions end to end.

The halving tests pin the OOM ladder's contract: any template-batch
split of the bank is BITWISE-identical to the unsplit dispatch
(ops/fdas.py pads the FFT row batch to _ROW_ALIGN so the backend's
vector-remainder path never sees a data row).
"""

import os

import numpy as np
import pytest

from peasoup_tpu.fdas.templates import (
    auto_segment,
    bank_geometry,
    build_template_bank,
    effective_zmax,
    template_half_width,
    w_trials,
    z_trials,
)
from peasoup_tpu.io.sigproc import (
    Filterbank,
    SigprocHeader,
    read_filterbank,
    write_filterbank,
)
from peasoup_tpu.ops.registry import ShapeCtx, registered_programs
from peasoup_tpu.ops.resample import accel_factor
from peasoup_tpu.pipeline.fdas import SPEED_OF_LIGHT, FdasConfig, FdasSearch
from peasoup_tpu.plan.dm_plan import DMPlan

NCHANS, TSAMP = 8, 0.004
FCH1, FOFF = 1500.0, -20.0
FFTN = 1 << 15  # choose_fft_size lands here after the dedisp trim
SIZE = FFTN + 64
P_INJ, DM_INJ = 0.02, 60.0
TOBS = FFTN * TSAMP  # 131.072 s
F0 = 1.0 / P_INJ


def _a_for_z(z: float) -> float:
    """Line-of-sight acceleration whose Fourier drift is z bins:
    z = -a*f*T^2/c."""
    return -z * SPEED_OF_LIGHT / (F0 * TOBS * TOBS)


def _make_fil(path, accel=0.0, z=None, w=0.0, seed=7):
    """Synthetic filterbank with one injected pulsar at DM_INJ.

    ``accel`` injects via the resampler's inverse map (exactly
    periodic after time-domain resampling at that acceleration);
    ``z``/``w`` inject via the FDAS template phase model directly.
    """
    rng = np.random.default_rng(seed)
    plan = DMPlan.create(SIZE + 64, NCHANS, TSAMP, FCH1, FOFF, 0.0, 100.0)
    nsamps = SIZE + plan.max_delay
    j = np.arange(nsamps, dtype=np.float64)
    if z is None:
        af = float(accel_factor(np.array([accel]), TSAMP)[0])
        ginv = j - af * j * (j - FFTN)
        phase = ginv * TSAMP / P_INJ
    else:
        u = j / FFTN
        b0 = F0 * TOBS - (z / 2.0 + w / 6.0)  # mean frequency == F0
        phase = b0 * u + z * u * u / 2.0 + w * u ** 3 / 6.0
    pulse = ((phase % 1.0) < 0.08) * 20.0
    delays = np.rint(
        (np.float32(DM_INJ) * np.abs(plan.delays)).astype(np.float32)
    ).astype(int)
    data = rng.normal(100, 8, size=(nsamps, NCHANS))
    for c in range(NCHANS):
        src = np.clip(j - delays[c], 0, nsamps - 1).astype(int)
        data[:, c] += pulse[src]
    hdr = SigprocHeader(
        source_name="fdas_inj", data_type=1, nchans=NCHANS, nbits=8,
        nifs=1, tsamp=TSAMP, tstart=50000.0, fch1=FCH1, foff=FOFF,
    )
    write_filterbank(
        path,
        Filterbank(header=hdr, data=np.clip(data, 0, 255).astype(np.uint8)),
    )
    return path


def _fdas_config(**kw):
    base = dict(
        dm_start=50.0, dm_end=70.0, zmax=32.0, zstep=2.0,
        nharmonics=2, limit=20,
    )
    base.update(kw)
    return FdasConfig(**base)


# --------------------------------------------------------------- bank


class TestTemplates:
    def test_zero_drift_template_is_exact_delta(self):
        """Row 0 (z=w=0) must be a unit impulse so the z=0 trial
        reproduces the plain periodicity spectrum bit for bit."""
        bank = build_template_bank(16.0)
        row0 = np.asarray(bank.templates[0])
        assert bank.zs[0] == 0.0 and bank.ws[0] == 0.0
        assert row0[bank.half] == 1.0 + 0.0j
        assert np.all(np.delete(row0, bank.half) == 0.0)

    def test_rows_unit_energy(self):
        bank = build_template_bank(32.0, 20.0)
        energy = np.sum(np.abs(np.asarray(bank.templates)) ** 2, axis=1)
        np.testing.assert_allclose(energy, 1.0, rtol=1e-3)

    def test_trial_grids(self):
        zs = z_trials(16.0, 2.0)
        assert zs[0] == 0.0 and len(zs) == 17
        assert set(zs) == {float(z) for z in range(-16, 18, 2)}
        assert np.abs(zs).max() == 16.0
        assert list(w_trials(0.0)) == [0.0]
        ws = w_trials(20.0, 20.0)
        assert set(ws) == {0.0, 20.0, -20.0}

    def test_bank_geometry_matches_built_bank(self):
        for zmax, wmax in ((16.0, 0.0), (32.0, 20.0)):
            bank = build_template_bank(zmax, wmax)
            nt, width, seg = bank_geometry(zmax, wmax)
            assert bank.ntemplates == nt
            assert bank.templates.shape == (nt, width)
            assert seg == auto_segment(width)

    def test_effective_zmax_roundtrip(self):
        """effective_zmax folds the jerk widening into one int the
        ShapeCtx can carry: the recovered width is exact."""
        for zmax, wmax in ((16.0, 0.0), (32.0, 20.0), (64.0, 40.0)):
            ez = effective_zmax(zmax, wmax)
            assert template_half_width(ez) == template_half_width(zmax, wmax)

    def test_auto_segment_power_of_two(self):
        for width in (33, 65, 129, 513):
            seg = auto_segment(width)
            assert seg & (seg - 1) == 0
            assert seg - (width - 1) > 0  # valid overlap-save step


# -------------------------------------------------------- correlation


class TestCorrelateBank:
    def test_matches_direct_evaluation(self):
        """Overlap-save output == the direct matched-filter sum
        out[t, r] = sum_j fser[r-half+j] * conj(tmpl[t, j])."""
        import jax.numpy as jnp

        from peasoup_tpu.ops.fdas import correlate_bank

        rng = np.random.default_rng(3)
        nbins, width = 700, 33
        half = (width - 1) // 2
        fser = (
            rng.standard_normal(nbins) + 1j * rng.standard_normal(nbins)
        ).astype(np.complex64)
        tmpl = (
            rng.standard_normal((4, width))
            + 1j * rng.standard_normal((4, width))
        ).astype(np.complex64)
        out = np.asarray(
            correlate_bank(jnp.asarray(fser), jnp.asarray(tmpl), segment=1024)
        )
        fpad = np.pad(fser, (half, half))
        direct = np.stack([
            np.array([
                np.sum(fpad[r:r + width] * np.conj(tmpl[t]))
                for r in range(nbins)
            ])
            for t in range(4)
        ])
        np.testing.assert_allclose(out, direct, rtol=2e-4, atol=2e-4)

    def test_row_split_bitwise(self):
        """Any row-batch split of the bank is bitwise-identical to the
        unsplit call — the invariant the OOM ladder's template-batch
        halving rung relies on."""
        import jax.numpy as jnp

        from peasoup_tpu.ops.fdas import correlate_bank

        rng = np.random.default_rng(0)
        nbins = 2049
        fser = (
            rng.standard_normal(nbins) + 1j * rng.standard_normal(nbins)
        ).astype(np.complex64)
        bank = build_template_bank(16.0)
        tmpl = np.asarray(bank.templates)
        seg = auto_segment(bank.templates.shape[1])
        full = np.asarray(
            correlate_bank(jnp.asarray(fser), jnp.asarray(tmpl), segment=seg)
        )
        for at in (1, 5, 9):
            parts = [
                np.asarray(correlate_bank(
                    jnp.asarray(fser), jnp.asarray(t), segment=seg
                ))
                for t in (tmpl[:at], tmpl[at:])
            ]
            split = np.concatenate(parts, axis=0)
            assert np.array_equal(
                full.view(np.float32), split.view(np.float32)
            ), f"split at {at} not bitwise"

    def test_program_bitwise_under_template_batch_halving(self):
        """The FULL jitted program, dispatched driver-style (batches
        padded by repeating the last row), produces bitwise-identical
        peak sets for any template-batch size."""
        import jax.numpy as jnp

        from peasoup_tpu.ops.fdas import make_fdas_search_fn

        rng = np.random.default_rng(1)
        size = 4096
        tims = rng.integers(0, 40, size=(3, size), dtype=np.uint8)
        bank = build_template_bank(16.0)
        tmpl = np.asarray(bank.templates)
        ntmpl = tmpl.shape[0]
        seg = auto_segment(tmpl.shape[1])
        nbins = size // 2 + 1
        zap = np.zeros(nbins, bool)
        wins = np.array([[2, nbins]] * 3, np.int32)
        fn = make_fdas_search_fn(6.0)
        kw = dict(size=size, nsamps_valid=size, segment=seg, nharms=2,
                  max_peaks=32, pos5=2, pos25=10)

        def run(tm):
            r = fn(jnp.asarray(tims), jnp.asarray(tm), jnp.asarray(zap),
                   jnp.asarray(wins), **kw)
            return [np.asarray(a) for a in r]

        full = run(tmpl)
        for tb in (9, 4):
            parts = []
            for s in range(0, ntmpl, tb):
                b = tmpl[s:s + tb]
                if b.shape[0] < tb:
                    b = np.concatenate(
                        [b, np.repeat(b[-1:], tb - b.shape[0], axis=0)]
                    )
                parts.append((min(s + tb, ntmpl) - s, run(b)))
            for k in range(4):
                split = np.concatenate(
                    [r[k][:, :, :n] for n, r in parts], axis=2
                )
                assert np.array_equal(
                    np.ascontiguousarray(full[k]).view(np.uint8),
                    np.ascontiguousarray(split).view(np.uint8),
                ), f"output {k} not bitwise at tb={tb}"

    def test_segment_too_short_raises(self):
        import jax.numpy as jnp

        from peasoup_tpu.ops.fdas import correlate_bank

        fser = jnp.zeros(100, jnp.complex64)
        tmpl = jnp.zeros((2, 65), jnp.complex64)
        with pytest.raises(ValueError, match="too short"):
            correlate_bank(fser, tmpl, segment=64)


# ----------------------------------------------------------- registry


class TestRegistry:
    def test_param_hook_builds_driver_shapes(self):
        """The ShapeCtx hook maps an fdas ctx to the exact
        (dm_block, template_batch) tile the driver dispatches —
        uint8 trials trimmed to the valid length, complex64 templates
        at the geometry-formula width."""
        by_name = {s.name: s for s in registered_programs()}
        ctx = ShapeCtx(
            nsamps=4096, nchans=8, nbits=8, ndm=16, out_nsamps=4000,
            dm_block=4, dedisp_block=16, fft_size=4096, nharms=2,
            max_peaks=32, pos5=2, pos25=10, min_snr=9.0,
            fdas_templates=8, fdas_zmax=32, fdas_segment=1024,
        )
        width = 2 * template_half_width(32) + 1
        fn, args, kwargs = by_name[
            "ops.fdas.fdas_correlate_search"
        ].build_for(ctx)
        assert args[0].shape == (4, 4000) and args[0].dtype == "uint8"
        assert args[1].shape == (8, width)
        assert args[1].dtype == "complex64"
        assert kwargs["size"] == 4096 and kwargs["nsamps_valid"] == 4000
        assert kwargs["segment"] == 1024 and kwargs["nharms"] == 2

        fn, args, kwargs = by_name["ops.fdas.correlate_bank"].build_for(ctx)
        assert args[0].shape == (4096 // 2 + 1,)
        assert args[1].shape == (8, width)
        assert kwargs == {"segment": 1024}

    def test_param_hook_declines_non_fdas_ctx(self):
        by_name = {s.name: s for s in registered_programs()}
        ctx = ShapeCtx(
            nsamps=4096, nchans=8, nbits=8, ndm=16, out_nsamps=4000,
            dm_block=4, dedisp_block=16, fft_size=4096,
        )
        assert by_name["ops.fdas.fdas_correlate_search"].build_for(ctx) is None
        assert by_name["ops.fdas.correlate_bank"].build_for(ctx) is None

    def test_shape_ctx_for_fdas_bucket(self):
        """perf.warmup derives the fdas ctx with the driver's own
        geometry formulas, so hook-compiled shapes match dispatch."""
        from peasoup_tpu.perf.warmup import shape_ctx_for_bucket

        bucket = (8, 8, 4096, 0.000256, 1400.0, -16.0)
        ctx = shape_ctx_for_bucket(
            bucket, "fdas", {"dm_end": 20.0, "zmax": 16.0}
        )
        nt, width, seg = bank_geometry(16.0)
        assert ctx.fdas_templates == min(nt, 64)
        assert ctx.fdas_segment == seg
        assert ctx.fdas_zmax == effective_zmax(16.0, 0.0)
        assert 2 * template_half_width(ctx.fdas_zmax) + 1 == width
        assert 1 <= ctx.dm_block <= max(1, ctx.ndm)
        assert ctx.fft_size > 0


# ----------------------------------------------------------- recovery


@pytest.fixture(scope="module")
def fdas_fils(tmp_path_factory):
    """One filterbank per injection scenario, shared by the module."""
    d = tmp_path_factory.mktemp("fdasfil")
    return {
        "z0": _make_fil(str(d / "z0.fil"), accel=0.0),
        "midz": _make_fil(str(d / "midz.fil"), accel=_a_for_z(-24.0)),
        "edge": _make_fil(str(d / "edge.fil"), accel=_a_for_z(-32.0)),
        "jerk": _make_fil(str(d / "jerk.fil"), z=-12.0, w=-20.0),
    }


def _assert_period(top):
    assert abs(1.0 / top.freq - P_INJ) / P_INJ < 1e-4, 1.0 / top.freq


class TestRecovery:
    def test_z0_parity_with_time_domain_search(self, fdas_fils):
        """Unaccelerated pulsar: the z=0 template row reproduces the
        plain periodicity search EXACTLY (same top frequency and S/N),
        and the candidate's acceleration fields are exactly zero."""
        from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig

        fil = read_filterbank(fdas_fils["z0"])
        fres = FdasSearch(_fdas_config()).run(fil)
        assert fres.candidates
        ftop = fres.candidates[0]
        _assert_period(ftop)
        assert ftop.z == 0.0 and ftop.w == 0.0
        assert ftop.fdot == 0.0 and ftop.fddot == 0.0
        assert ftop.acc == 0.0
        assert ftop.snr > 50.0

        tres = PeasoupSearch(SearchConfig(
            dm_start=50.0, dm_end=70.0, acc_start=-30.0, acc_end=30.0,
            acc_pulse_width=834.0, nharmonics=2, npdmp=1, limit=20,
        )).run(fil)
        ttop = tres.candidates[0]
        assert ttop.acc == 0.0
        assert ftop.freq == ttop.freq  # exact: the z=0 row is a delta
        assert ftop.snr == ttop.snr

    @pytest.mark.parametrize("key,z_inj", [("midz", -24.0), ("edge", -32.0)])
    def test_recovers_injected_acceleration(self, fdas_fils, key, z_inj):
        """Mid-grid and zmax-edge drifts: the matching template wins
        and the reported f-dot is within 5% of the injected value
        (ISSUE 19 satellite gate)."""
        res = FdasSearch(_fdas_config()).run(read_filterbank(fdas_fils[key]))
        assert res.candidates
        top = res.candidates[0]
        _assert_period(top)
        assert top.z == z_inj, (top.z, top.snr)
        acc_inj = _a_for_z(z_inj)
        fdot_inj = -acc_inj * F0 / SPEED_OF_LIGHT
        assert abs(top.fdot - fdot_inj) / abs(fdot_inj) < 0.05
        assert abs(top.acc - acc_inj) / acc_inj < 0.05
        assert top.snr > 9.5
        # the DM grid is coarse at this narrow fractional bandwidth:
        # within one trial spacing of the injected DM
        assert abs(top.dm - DM_INJ) < 11.0

    def test_recovers_injected_jerk(self, fdas_fils):
        """With the f-ddot plane on, the (z, w) trial matching the
        injected phase model wins both axes."""
        cfg = _fdas_config(zmax=16.0, wmax=20.0, wstep=20.0)
        res = FdasSearch(cfg).run(read_filterbank(fdas_fils["jerk"]))
        assert res.candidates
        assert res.n_templates == 17 * 3  # z grid x w in {0, +20, -20}
        top = res.candidates[0]
        _assert_period(top)
        assert top.z == -12.0 and top.w == -20.0
        fddot_inj = -20.0 / TOBS ** 3
        assert abs(top.fddot - fddot_inj) / abs(fddot_inj) < 0.05
        assert top.snr > 10.0

    def test_cross_validation_with_time_domain_search(self, fdas_fils):
        """The tentpole gate: FDAS and the time-domain resampling
        search recover the SAME injected constant-acceleration pulsar
        from the SAME filterbank — matching period, acceleration
        (within both grids' quanta) and DM trial."""
        from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig
        from peasoup_tpu.plan.accel_plan import AccelerationPlan

        fil = read_filterbank(fdas_fils["midz"])
        ftop = FdasSearch(_fdas_config()).run(fil).candidates[0]
        ttop = PeasoupSearch(SearchConfig(
            dm_start=50.0, dm_end=70.0, acc_start=7000.0, acc_end=10000.0,
            acc_pulse_width=1000.0, nharmonics=2, npdmp=1, limit=20,
        )).run(fil).candidates[0]
        assert abs(1.0 / ftop.freq - 1.0 / ttop.freq) / P_INJ < 1e-4
        assert abs(ftop.dm - ttop.dm) < 11.0
        # acceleration agreement bounded by the two grid quanta: the
        # time-domain trial step plus FDAS's zstep in acceleration
        plan = AccelerationPlan(
            acc_lo=7000.0, acc_hi=10000.0, tol=1.10, pulse_width=1000.0,
            nsamps=FFTN, tsamp=TSAMP,
            cfreq=FCH1 + (NCHANS / 2) * FOFF, bw=FOFF,
        )
        quantum = plan.step(ttop.dm) + abs(_a_for_z(2.0))
        assert abs(ftop.acc - ttop.acc) <= quantum, (ftop.acc, ttop.acc)
        assert ftop.acc > 0 and ttop.acc > 0

    def test_template_block_invariant_results(self, fdas_fils):
        """Driver-level halving: shrinking template_block (what the
        OOM ladder does under device pressure) leaves the full
        candidate list identical."""
        fil = read_filterbank(fdas_fils["edge"])

        def cands(tb):
            res = FdasSearch(_fdas_config(template_block=tb)).run(fil)
            return [
                (c.freq, c.snr, c.dm, c.z, c.w, c.nh, c.acc, c.fdot)
                for c in res.candidates
            ]

        full = cands(0)  # auto: the whole bank in one dispatch
        assert full
        assert cands(8) == full
        assert cands(5) == full

    def test_writes_fdas_outputs(self, fdas_fils, tmp_path):
        """overview.xml carries the <fdas_search> section and the
        (f, f-dot) candidate fields, and the text table round-trips."""
        import xml.etree.ElementTree as ET

        from peasoup_tpu.io.output import (
            OutputFileWriter,
            write_fdas_candidates,
        )

        fil = read_filterbank(fdas_fils["midz"])
        cfg = _fdas_config(outdir=str(tmp_path))
        res = FdasSearch(cfg).run(fil)
        writer = OutputFileWriter()
        writer.add_fdas_section(cfg, res.zs, res.ws)
        writer.add_candidates_fdas(res.candidates, {})
        xml_path = os.path.join(str(tmp_path), "overview.xml")
        writer.to_file(xml_path)
        root = ET.parse(xml_path).getroot()
        sec = root.find("fdas_search")
        assert sec is not None
        assert sec.find("search_parameters/zmax") is not None
        trials = sec.find("fdot_trials")
        assert trials is not None
        assert int(trials.get("count")) == len(res.zs)
        cand = root.find("candidates/candidate")
        assert cand is not None
        assert float(cand.find("fdot").text) != 0.0
        assert cand.find("z") is not None

        txt = os.path.join(str(tmp_path), "candidates.fdas")
        write_fdas_candidates(txt, res.candidates)
        lines = open(txt).read().strip().splitlines()
        assert "fdot" in lines[0]
        assert len(lines) == len(res.candidates) + 1
