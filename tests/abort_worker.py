"""Worker process for the SIGTERM abort-forensics test.

Launched by tests/test_live_obs.py: runs a real (tiny) `peasoup` CLI
search with the status.json heartbeat enabled, so the parent can wait
for the heartbeat to appear (proof the flight recorder is armed — the
recorder installs before the first snapshot), SIGTERM the run
mid-flight, and assert the forensics: flight.json plus a partial
telemetry manifest marked ``"aborted": true``.

Usage: python abort_worker.py <fil_path> <outdir>
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "peasoup_tpu", "jax-tests",
    )
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    fil_path, outdir = sys.argv[1], sys.argv[2]
    from peasoup_tpu.cli.peasoup import main as peasoup_main

    return peasoup_main(
        [
            "-i", fil_path,
            "-o", outdir,
            "--dm_end", "40",
            "-n", "2",
            "--limit", "20",
            "--status-json", os.path.join(outdir, "status.json"),
            "--heartbeat-interval", "0.05",
        ]
    )


if __name__ == "__main__":
    sys.exit(main())
