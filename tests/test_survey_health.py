"""Survey health console tests (ISSUE 16): the declarative alert
engine (threshold/absence/burn-rate lifecycle over the fleet metrics,
persisted transitions, lock discipline), the data-quality sentinels
(per-observation gauges, campaign baselines, injection recovery), the
ALERTS exposition series, the status portal endpoints, and the rollup
/watch integration."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from peasoup_tpu.obs.alerts import (
    AlertEngine,
    alerts_exposition,
    counter_increase,
    default_rules,
    evaluate_campaign,
    load_alerts,
    validate_snapshot,
)
from peasoup_tpu.obs.health import (
    build_baselines,
    data_quality_summary,
    enqueue_sentinel,
    observation_quality,
    quality_findings,
    sentinel_findings,
    sentinel_status,
)
from peasoup_tpu.obs.metrics import (
    MetricsRecorder,
    load_series,
    parse_exposition,
    prometheus_exposition,
)
from peasoup_tpu.obs.schema import SchemaError


def _gauge_rule(value=5.0, for_s=0.0, window_s=900.0):
    return {
        "name": "queue_backlog",
        "kind": "threshold",
        "metric": "queue_depth",
        "metric_kind": "gauge",
        "op": ">",
        "value": value,
        "for_s": for_s,
        "window_s": window_s,
        "severity": "warn",
    }


def _gauge_samples(points):
    """{"w0": [gauge samples at (t, value), ...]}"""
    return {
        "w0": [
            {"t": float(t), "kind": "gauge", "name": "queue_depth",
             "value": float(v)}
            for t, v in points
        ]
    }


# --------------------------------------------------------------------------
# alert engine lifecycle
# --------------------------------------------------------------------------

class TestAlertLifecycle:
    def test_pending_then_firing_then_resolved(self, tmp_path):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule(for_s=10.0)])
        hot = _gauge_samples([(100.0, 9.0)])
        s1 = eng.evaluate(samples=hot, now=105.0)
        assert [(a["rule"], a["state"]) for a in s1["alerts"]] == [
            ("queue_backlog", "pending")
        ]
        s2 = eng.evaluate(samples=hot, now=120.0)
        assert s2["alerts"][0]["state"] == "firing"
        assert s2["alerts"][0]["firing_since_unix"] == 120.0
        cold = _gauge_samples([(100.0, 9.0), (125.0, 0.0)])
        s3 = eng.evaluate(samples=cold, now=130.0)
        assert s3["alerts"][0]["state"] == "resolved"
        assert s3["alerts"][0]["resolved_unix"] == 130.0

    def test_zero_for_fires_immediately_with_full_lifecycle_log(
        self, tmp_path
    ):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule(for_s=0.0)])
        snap = eng.evaluate(
            samples=_gauge_samples([(100.0, 9.0)]), now=101.0
        )
        assert snap["alerts"][0]["state"] == "firing"
        log = [
            json.loads(ln)
            for ln in open(
                os.path.join(str(tmp_path), "queue", "alerts.jsonl")
            )
        ]
        assert [(r["from"], r["to"]) for r in log] == [
            ("inactive", "pending"), ("pending", "firing")
        ]
        assert all(r["t_unix"] == 101.0 for r in log)

    def test_pending_that_recovers_never_logs_firing(self, tmp_path):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule(for_s=60.0)])
        eng.evaluate(samples=_gauge_samples([(100.0, 9.0)]), now=105.0)
        s2 = eng.evaluate(
            samples=_gauge_samples([(100.0, 9.0), (106.0, 1.0)]),
            now=110.0,
        )
        # pending -> inactive: dropped from the snapshot entirely
        assert s2["alerts"] == []
        states = [
            json.loads(ln)["to"]
            for ln in open(
                os.path.join(str(tmp_path), "queue", "alerts.jsonl")
            )
        ]
        assert "firing" not in states

    def test_resolved_expires_after_retention(self, tmp_path):
        from peasoup_tpu.obs.alerts import RESOLVED_RETENTION_S

        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule()])
        eng.evaluate(samples=_gauge_samples([(100.0, 9.0)]), now=105.0)
        s = eng.evaluate(samples=_gauge_samples([(100.0, 9.0)]),
                         now=110.0)
        assert s["alerts"][0]["state"] in ("pending", "firing")
        s = eng.evaluate(
            samples=_gauge_samples([(100.0, 0.0)]), now=120.0
        )
        assert s["alerts"][0]["state"] == "resolved"
        s = eng.evaluate(
            samples=_gauge_samples([(100.0, 0.0)]),
            now=120.0 + RESOLVED_RETENTION_S + 1.0,
        )
        assert s["alerts"] == []

    def test_refire_after_resolution_is_a_new_alert(self, tmp_path):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule()])
        eng.evaluate(samples=_gauge_samples([(100.0, 9.0)]), now=101.0)
        eng.evaluate(samples=_gauge_samples([(100.0, 0.0)]), now=110.0)
        s = eng.evaluate(samples=_gauge_samples([(115.0, 9.0)]),
                         now=116.0)
        firing = [a for a in s["alerts"] if a["state"] == "firing"]
        assert len(firing) == 1 and firing[0]["since_unix"] == 116.0

    def test_snapshot_schema_valid_and_rejects_drift(self, tmp_path):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule()])
        snap = eng.evaluate(
            samples=_gauge_samples([(100.0, 9.0)]), now=101.0
        )
        validate_snapshot(snap)
        bad = json.loads(json.dumps(snap))
        bad["alerts"][0]["state"] = "screaming"
        with pytest.raises(SchemaError):
            validate_snapshot(bad)

    def test_live_lock_skips_evaluation(self, tmp_path):
        root = str(tmp_path)
        eng = AlertEngine(root, rules=[_gauge_rule()])
        os.makedirs(os.path.join(root, "queue"), exist_ok=True)
        with open(
            os.path.join(root, "queue", "alerts.lock"), "x"
        ) as f:
            json.dump({"pid": 1, "t_unix": 1e18}, f)
        snap = eng.evaluate(
            samples=_gauge_samples([(100.0, 9.0)]), now=101.0
        )
        assert snap["alerts"] == []  # another evaluator holds the lock

    def test_stale_lock_taken_over(self, tmp_path):
        root = str(tmp_path)
        eng = AlertEngine(root, rules=[_gauge_rule()], lock_stale_s=1.0)
        os.makedirs(os.path.join(root, "queue"), exist_ok=True)
        with open(
            os.path.join(root, "queue", "alerts.lock"), "x"
        ) as f:
            json.dump({"pid": 1, "t_unix": 10.0}, f)
        snap = eng.evaluate(
            samples=_gauge_samples([(100.0, 9.0)]), now=101.0
        )
        assert snap["alerts"]  # dead evaluator's lock was reaped
        assert not os.path.exists(
            os.path.join(root, "queue", "alerts.lock")
        )


class TestRules:
    def test_absence_pages_only_stalled_live_workers(self, tmp_path):
        rules = [r for r in default_rules(heartbeat_s=2.0)
                 if r["kind"] == "absence"]
        eng = AlertEngine(str(tmp_path), rules=rules)
        samples = {
            "fresh": [{"t": 99.0, "kind": "gauge",
                       "name": "worker_heartbeat_unix", "value": 99.0}],
            "stalled": [{"t": 10.0, "kind": "gauge",
                         "name": "worker_heartbeat_unix", "value": 10.0}],
            "dead": [{"t": 5.0, "kind": "gauge",
                      "name": "worker_heartbeat_unix", "value": 5.0}],
        }
        snap = eng.evaluate(
            samples=samples, now=100.0,
            live_sources=["fresh", "stalled"],  # dead has deregistered
        )
        assert [a["labels"] for a in snap["alerts"]] == [
            {"worker": "stalled"}
        ]

    def test_burn_rate_needs_every_window_burning(self, tmp_path):
        rules = [r for r in default_rules()
                 if r["name"] == "job_failure_burn_rate"]
        eng = AlertEngine(str(tmp_path), rules=rules)

        def counters(points, name):
            return [
                {"t": float(t), "kind": "counter", "name": name,
                 "value": float(v)}
                for t, v in points
            ]

        # an old streak of failures outside the short window: the long
        # window burns but the short one is clean -> no alert
        now = 10_000.0
        samples = {"w0": (
            counters([(now - 1500, 5.0)], "jobs_failed_total")
            + counters([(now - 1500, 1.0), (now - 100, 2.0)],
                       "jobs_done_total")
        )}
        assert eng.evaluate(samples=samples, now=now)["alerts"] == []
        # failures continuing into the short window -> fires
        samples["w0"] += counters([(now - 50, 10.0)],
                                  "jobs_failed_total")
        snap = eng.evaluate(samples=samples, now=now)
        assert snap["alerts"][0]["state"] == "firing"
        assert snap["alerts"][0]["severity"] == "page"

    def test_counter_increase_survives_rotation_and_restart(self):
        # rotation keeps the newest tail with cumulative totals carried
        # in recorder memory: the pre-window sample seeds the baseline
        samples = {"w0": [
            {"t": 50.0, "kind": "counter", "name": "c_total",
             "value": 40.0},
            {"t": 110.0, "kind": "counter", "name": "c_total",
             "value": 45.0},
        ]}
        assert counter_increase(samples, "c_total", 100.0, 200.0) == 5.0
        # a value DROP is a process-restart reset, not a negative delta
        samples["w0"].append(
            {"t": 120.0, "kind": "counter", "name": "c_total",
             "value": 2.0}
        )
        assert counter_increase(samples, "c_total", 100.0, 200.0) == 7.0

    def test_recompile_budget_not_refired_after_rotation(self, tmp_path):
        """A resolved alert must stay resolved when rotation rewrites
        the metrics file but the counter total has stopped growing."""
        rule = {
            "name": "jit_recompile_budget", "kind": "threshold",
            "metric": "jit_programs_compiled_total",
            "metric_kind": "counter", "select": "increase",
            "op": ">", "value": 5.0, "window_s": 60.0,
            "severity": "warn",
        }
        mpath = str(
            tmp_path / "queue" / "workers" / "w0.metrics.jsonl"
        )
        rec = MetricsRecorder(mpath, max_bytes=1600, keep_bytes=600)
        for _ in range(10):
            rec.counter("jit_programs_compiled_total")
        eng = AlertEngine(str(tmp_path), rules=[rule])
        t_spike = max(
            s["t"] for s in load_series(mpath)
        )
        snap = eng.evaluate(
            samples={"w0": load_series(mpath)}, now=t_spike + 1.0
        )
        assert snap["alerts"][0]["state"] == "firing"
        # the storm stops; rotation churns the file (totals carried)
        for _ in range(60):
            rec.gauge("queue_depth", 0.0)
        rotated = load_series(mpath)
        assert len(rotated) < 70  # rotation really dropped old lines
        s2 = eng.evaluate(
            samples={"w0": rotated}, now=t_spike + 120.0
        )
        assert s2["alerts"][0]["state"] == "resolved"
        s3 = eng.evaluate(
            samples={"w0": rotated}, now=t_spike + 130.0
        )
        assert s3["alerts"][0]["state"] == "resolved"  # no re-fire
        states = [
            json.loads(ln)["to"]
            for ln in open(
                os.path.join(str(tmp_path), "queue", "alerts.jsonl")
            )
        ]
        assert states.count("firing") == 1

    def test_threshold_with_no_data_is_silent(self, tmp_path):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule()])
        assert eng.evaluate(samples={}, now=100.0)["alerts"] == []


# --------------------------------------------------------------------------
# ALERTS exposition
# --------------------------------------------------------------------------

class TestAlertsExposition:
    def test_round_trip_with_metrics(self, tmp_path):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule()])
        snap = eng.evaluate(
            samples=_gauge_samples([(100.0, 9.0)]), now=101.0
        )
        text = (
            prometheus_exposition(_gauge_samples([(100.0, 9.0)]))
            + alerts_exposition(snap)
        )
        rows = parse_exposition(text)
        alerts = [r for r in rows if r[0] == "ALERTS"]
        assert alerts == [(
            "ALERTS",
            {"alertname": "queue_backlog", "alertstate": "firing",
             "severity": "warn"},
            1.0,
        )]

    def test_resolved_alerts_not_exported(self, tmp_path):
        eng = AlertEngine(str(tmp_path), rules=[_gauge_rule()])
        eng.evaluate(samples=_gauge_samples([(100.0, 9.0)]), now=101.0)
        snap = eng.evaluate(
            samples=_gauge_samples([(100.0, 0.0)]), now=110.0
        )
        assert snap["alerts"][0]["state"] == "resolved"
        assert alerts_exposition(snap) == ""

    def test_empty_snapshot_renders_nothing(self):
        assert alerts_exposition({"alerts": []}) == ""


# --------------------------------------------------------------------------
# data-quality sentinels
# --------------------------------------------------------------------------

class TestObservationQuality:
    def _clean(self, nsamps=2048, nchans=16, seed=0):
        rng = np.random.default_rng(seed)
        return rng.normal(32, 4, (nsamps, nchans)).clip(
            0, 255
        ).astype(np.uint8)

    def test_clean_observation_scores_clean(self):
        q = observation_quality(
            self._clean(), n_candidates=5, n_dm_trials=50, nbits=8
        )
        assert q["zap_fraction"] == 0.0
        assert q["clip_fraction"] < 0.01
        assert q["candidate_rate"] == pytest.approx(0.1)

    def test_rfi_storm_raises_occupancy_and_clipping(self):
        data = self._clean().astype(np.float32)
        data[:, 3] += 200.0
        data[:, 7] *= 30.0
        data = data.clip(0, 255).astype(np.uint8)
        q = observation_quality(data, nbits=8)
        assert q["zap_fraction"] >= 2.0 / 16.0
        assert q["clip_fraction"] > 0.05

    def test_dead_channel_counted(self):
        data = self._clean()
        data[:, 5] = 32
        q = observation_quality(data, nbits=8)
        assert q["dead_channels"] >= 1

    def test_degenerate_inputs(self):
        assert observation_quality(np.zeros((0, 0))) == {}
        assert observation_quality(np.zeros(16)) == {}

    def test_baselines_exclude_sentinels_and_flag_outliers(self):
        done = [
            {"job_id": f"j{i}",
             "quality": {"zap_fraction": 0.0, "clip_fraction": 0.0,
                         "candidate_rate": 0.05 + 0.002 * i}}
            for i in range(6)
        ]
        done.append(
            {"job_id": "sent", "sentinel": True,
             "quality": {"zap_fraction": 0.9, "clip_fraction": 0.9,
                         "candidate_rate": 50.0}}
        )
        base = build_baselines(done)
        assert base["candidate_rate"]["n"] == 6
        assert base["candidate_rate"]["median"] < 0.1
        assert quality_findings(done) == []  # sentinel never judged
        done.append(
            {"job_id": "storm",
             "quality": {"zap_fraction": 0.5, "clip_fraction": 0.0,
                         "candidate_rate": 30.0}}
        )
        flagged = quality_findings(done)
        assert {f["labels"]["job"] for f in flagged} == {"storm"}
        metrics = {f["labels"]["metric"] for f in flagged}
        assert "candidate_rate" in metrics
        summary = data_quality_summary(done)
        assert summary["jobs"] == 7  # sentinel not a baseline job
        assert summary["outliers"] == flagged

    def test_small_campaigns_never_flagged(self):
        done = [
            {"job_id": "a", "quality": {"candidate_rate": 0.1}},
            {"job_id": "b", "quality": {"candidate_rate": 99.0}},
        ]
        assert quality_findings(done) == []  # n < min_n: no baseline


# --------------------------------------------------------------------------
# campaign end-to-end: sentinel recovery + portal + rollup + watch
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def health_campaign(tmp_path_factory):
    """A tiny campaign (one survey obs + one injection sentinel)
    drained by one worker, with alerts evaluated along the way."""
    from test_campaign import make_obs

    from peasoup_tpu.campaign.queue import Job, JobQueue, job_id_for
    from peasoup_tpu.campaign.runner import (
        CampaignConfig,
        bucket_for_input,
        run_worker,
        save_campaign_config,
    )

    tmp = tmp_path_factory.mktemp("health")
    root = str(tmp / "camp")
    os.makedirs(root)
    save_campaign_config(
        root,
        CampaignConfig(
            pipeline="spsearch",
            config={"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6},
            warmup=False, heartbeat_interval=0.2, backoff_base_s=0.05,
        ),
    )
    q = JobQueue(root)
    fil = make_obs(str(tmp / "obs0.fil"))
    jid = job_id_for(fil)
    q.add_job(
        Job(job_id=jid, input=fil, pipeline="spsearch",
            bucket=bucket_for_input(fil))
    )
    truth = enqueue_sentinel(root, queue=q, seed=11)
    tally = run_worker(root, worker_id="w1", poll_s=0.05)
    return root, jid, truth, tally


class TestSentinelRecovery:
    def test_campaign_drained(self, health_campaign):
        _, _, _, tally = health_campaign
        assert tally["done"] == 2

    def test_sentinel_recovered(self, health_campaign):
        root, _, truth, _ = health_campaign
        rows = sentinel_status(root)
        assert [r["status"] for r in rows] == ["recovered"]
        assert rows[0]["job_id"] == truth["job_id"]
        assert sentinel_findings(root) == []

    def test_sentinel_claims_last(self, health_campaign):
        """priority=-1: the survey observation was searched first."""
        root, jid, truth, _ = health_campaign
        done = json.load(
            open(os.path.join(root, "queue", "done", f"{jid}.json"))
        )
        sdone = json.load(
            open(os.path.join(
                root, "queue", "done", f"{truth['job_id']}.json"
            ))
        )
        assert sdone.get("sentinel") is True
        assert done.get("sentinel") is None
        assert done["finished_unix"] <= sdone["finished_unix"]

    def test_broken_search_is_missed_and_alerts(self, health_campaign):
        """An impossible S/N floor simulates a search that no longer
        finds the injection: status missed, sentinel alert fires."""
        root, _, truth, _ = health_campaign
        sdir = os.path.join(root, "queue", "sentinels")
        broken = dict(truth, min_snr=1e9, job_id=truth["job_id"])
        path = os.path.join(sdir, f"{truth['job_id']}.json")
        orig = open(path).read()
        try:
            with open(path + ".tmp", "w") as f:
                json.dump(broken, f)
            os.replace(path + ".tmp", path)
            rows = sentinel_status(root)
            assert rows[0]["status"] == "missed"
            findings = sentinel_findings(root)
            assert findings and findings[0]["labels"] == {
                "job": truth["job_id"]
            }
            snap = evaluate_campaign(root)
            missed = [
                a for a in snap["alerts"]
                if a["rule"] == "sentinel_unrecovered"
            ]
            assert missed and missed[0]["state"] == "firing"
            assert missed[0]["severity"] == "page"
        finally:
            with open(path + ".tmp", "w") as f:
                f.write(orig)
            os.replace(path + ".tmp", path)
            evaluate_campaign(root)  # resolve it again

    def test_quality_gauges_in_done_record_and_metrics(
        self, health_campaign
    ):
        root, jid, _, _ = health_campaign
        done = json.load(
            open(os.path.join(root, "queue", "done", f"{jid}.json"))
        )
        assert "quality" in done
        assert set(done["quality"]) >= {
            "zap_fraction", "clip_fraction", "candidate_rate"
        }
        from peasoup_tpu.obs.metrics import fleet_samples

        names = {
            r["name"] for r in fleet_samples(root)["w1"]
        }
        assert "dq_candidate_rate" in names
        assert "worker_heartbeat_unix" in names

    def test_worker_wrote_alerts_snapshot(self, health_campaign):
        root, _, _, _ = health_campaign
        snap = load_alerts(root)
        validate_snapshot(snap)
        assert snap["updated_unix"] > 0
        assert os.path.exists(
            os.path.join(root, "queue", "alerts.jsonl")
        )

    def test_rollup_embeds_alerts_and_data_quality(
        self, health_campaign
    ):
        from peasoup_tpu.campaign.rollup import build_status

        root, _, truth, _ = health_campaign
        st = build_status(root)
        assert "invalid" not in st["alerts"]
        assert set(st["alerts"]) >= {"firing", "pending", "resolved"}
        dq = st["data_quality"]
        assert dq["sentinels"] == {
            "total": 1, "pending": 0, "recovered": 1, "missed": 0
        }
        assert dq["jobs"] >= 1

    def test_watch_renders_health_sections(self, health_campaign):
        from peasoup_tpu.campaign.rollup import build_status
        from peasoup_tpu.tools.watch import render_campaign_status

        root, _, _, _ = health_campaign
        st = build_status(root)
        out = render_campaign_status(st)
        assert "sentinels: 1 recovered" in out
        # inject a firing alert + a missed sentinel: loud lines
        st["alerts"] = {
            "firing": 1, "pending": 0, "resolved": 0,
            "active": [{
                "rule": "worker_heartbeat_stalled", "state": "firing",
                "severity": "page", "labels": {"worker": "w9"},
                "value": 99.0, "message": "no beat", "since_unix": 1.0,
            }],
        }
        st["data_quality"]["sentinels"]["missed"] = 1
        out = render_campaign_status(st)
        assert "1 firing" in out
        assert "worker_heartbeat_stalled" in out and "worker=w9" in out
        assert "MISSED" in out


class TestPortal:
    @pytest.fixture()
    def portal(self, health_campaign):
        import socket

        from peasoup_tpu.obs.portal import serve_portal

        root, jid, truth, _ = health_campaign
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        n_requests = 6
        srv = threading.Thread(
            target=serve_portal,
            args=(root,),
            kwargs={"port": port, "max_requests": n_requests},
            daemon=True,
        )
        srv.start()
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(base + "/alerts", timeout=2)
                break
            except OSError:
                time.sleep(0.05)
        yield base, root, jid
        # drain any unconsumed request budget so the server exits now
        # instead of the join riding its full timeout
        for _ in range(n_requests):
            if not srv.is_alive():
                break
            try:
                urllib.request.urlopen(base + "/alerts", timeout=1)
            except OSError:
                break
            srv.join(timeout=0.2)
        srv.join(timeout=5)

    def _get(self, url):
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.headers.get("Content-Type"), \
                resp.read()

    def test_endpoints(self, portal):
        base, root, jid = portal
        code, ctype, body = self._get(base + "/metrics")
        assert code == 200 and ctype.startswith("text/plain")
        rows = parse_exposition(body.decode())
        assert any(r[0] == "peasoup_jobs_done_total" for r in rows)

        code, ctype, body = self._get(base + "/status")
        st = json.loads(body)
        assert code == 200 and st["schema"] == (
            "peasoup_tpu.campaign_status"
        )
        assert "alerts" in st and "data_quality" in st

        code, _, body = self._get(base + "/alerts")
        validate_snapshot(json.loads(body))

        code, _, body = self._get(base + f"/jobs/{jid}")
        doc = json.loads(body)
        assert doc["job"]["job_id"] == jid
        assert doc["done"]["job_id"] == jid
        assert doc["trace"]["connected"]

        code, ctype, body = self._get(base + "/")
        assert code == 200 and b"/metrics" in body

    def test_unknown_job_is_404_not_traversal(self, portal):
        base, _, _ = portal
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._get(base + "/jobs/../../etc/passwd")
        assert exc.value.code == 404


class TestCLI:
    def test_alerts_command(self, health_campaign, capsys):
        from peasoup_tpu.cli.campaign import main

        root, _, _, _ = health_campaign
        rc = main(["alerts", "-w", root, "--evaluate"])
        out = capsys.readouterr().out
        assert rc in (0, 2)
        rc = main(["alerts", "-w", root, "--json"])
        snap = json.loads(capsys.readouterr().out)
        validate_snapshot(snap)

    def test_sentinel_check_command(self, health_campaign, capsys):
        from peasoup_tpu.cli.campaign import main

        root, _, truth, _ = health_campaign
        assert main(["sentinel", "-w", root, "--check"]) == 0
        out = capsys.readouterr().out
        assert "recovered" in out and truth["job_id"] in out

    def test_serve_command_bounded(self, health_campaign):
        import socket

        from peasoup_tpu.cli.campaign import main

        root, _, _, _ = health_campaign
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        th = threading.Thread(
            target=main,
            args=(
                ["serve", "-w", root, "--port", str(port),
                 "--max-requests", "1"],
            ),
            daemon=True,
        )
        th.start()
        deadline = time.monotonic() + 10
        body = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2
                ) as resp:
                    body = resp.read().decode()
                break
            except OSError:
                time.sleep(0.05)
        th.join(timeout=10)
        assert body is not None
        parse_exposition(body)
