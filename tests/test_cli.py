"""CLI tests: run the peasoup + coincidencer mains on synthetic data."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu.cli.coincidencer import birdies_from_mask, main as coin_main
from peasoup_tpu.cli.peasoup import build_parser, main as peasoup_main
from peasoup_tpu.tools import CandidateFileParser, OverviewFile
from test_pipeline import make_synthetic_fil


def test_parser_defaults_match_reference():
    args = build_parser().parse_args(["-i", "x.fil"])
    assert args.dm_end == 100.0
    assert args.dm_tol == 1.10
    assert args.num_threads == 14
    assert args.limit == 1000
    assert args.min_snr == 9.0
    assert args.max_harm == 16
    assert args.nharmonics == 4
    assert args.freq_tol == 0.0001


def test_peasoup_cli_end_to_end(tmp_path):
    path, period, dm = make_synthetic_fil(tmp_path)
    outdir = tmp_path / "out"
    rc = peasoup_main(
        [
            "-i", str(path), "-o", str(outdir), "--dm_end", "40",
            "-n", "2", "--npdmp", "2", "--limit", "20",
        ]
    )
    assert rc == 0
    ov = OverviewFile(str(outdir / "overview.xml"))
    assert len(ov.candidates) > 0
    assert "reading" in ov.execution_times
    top = ov.candidates[0]
    ratio = top["period"] / period
    assert min(abs(ratio - r) for r in (0.25, 0.5, 1.0, 2.0, 4.0)) < 0.01
    with CandidateFileParser(str(outdir / "candidates.peasoup")) as p:
        rec = p.read_candidate(int(top["byte_offset"]))
        assert len(rec["hits"]) == top["nassoc"] + 1


def test_coincidencer_cli(tmp_path):
    # 4 beams: same noise stats; one has a per-beam signal
    paths = []
    for b in range(4):
        beam_dir = tmp_path / f"b{b}"
        beam_dir.mkdir()
        p, _, _ = make_synthetic_fil(
            beam_dir, nsamps=1 << 13, amp=0.0, seed=100 + b
        )
        paths.append(str(p))
    samp_out = tmp_path / "mask.txt"
    spec_out = tmp_path / "birdies.txt"
    rc = coin_main(
        [*paths, "--o", str(samp_out), "--o2", str(spec_out), "--thresh", "4",
         "--beam_thresh", "3"]
    )
    assert rc == 0
    lines = samp_out.read_text().strip().splitlines()
    assert lines[0] == "#0 1"
    mask = np.array([int(x) for x in lines[1:]])
    # full dedispersed length, NOT truncated to a power of two
    # (coincidencer.cpp:136); DM=0 -> max_delay 0 -> all 8192 samples
    assert mask.size == 1 << 13
    assert mask.mean() > 0.9  # pure noise: almost everything kept


def test_birdies_from_mask():
    mask = np.array([1, 1, 0, 0, 0, 1, 0, 1])
    b = birdies_from_mask(mask, bin_width=2.0)
    # run of 3 zeros ending at index 4: freq=(4-1.5)*2=5.0 width=6.0
    assert b[0] == (5.0, 6.0)
    assert b[1] == ((6 - 0.5) * 2.0, 2.0)


def test_multibeam_rfi_loop(tmp_path):
    """The reference's full multibeam OPERATIONAL loop in one pipeline
    (VERDICT r3 item 5; src/coincidencer.cpp:46-215 +
    misc/default_zaplist.txt workflow): synthesize B beams sharing a
    zero-DM RFI pulse train, coincidencer them into a birdie list +
    sample mask, feed the artifacts into a peasoup search via -z/-k,
    and assert the planted tone is zapped from the candidate list while
    the (single-beam) pulsar survives. A control run without -z proves
    the zap — not luck — removed the tone."""
    from peasoup_tpu.io.sigproc import (
        Filterbank, SigprocHeader, write_filterbank,
    )

    nbeams, nsamps, nchans, tsamp = 5, 1 << 15, 16, 0.000256
    p_rfi, p_psr, dm_psr = 0.05, 0.064, 20.0
    fch1, foff = 1400.0, -8.0
    rng = np.random.default_rng(11)
    t = np.arange(nsamps)
    rfi = 18.0 * ((((t * tsamp) / p_rfi) % 1.0) < 0.04)  # zero-DM train
    freqs = fch1 + np.arange(nchans) * foff
    delays = 4.148808e3 * dm_psr * (freqs**-2 - fch1**-2) / tsamp
    paths = []
    for b in range(nbeams):
        data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
        data += rfi[:, None]  # the tone fires in EVERY beam
        if b == 0:  # the pulsar lives in one beam only
            for c in range(nchans):
                phase = ((t - delays[c]) * tsamp / p_psr) % 1.0
                data[:, c] += 10.0 * (phase < 0.03)
        hdr = SigprocHeader(
            source_name=f"BEAM{b}", tsamp=tsamp, tstart=55000.0, fch1=fch1,
            foff=foff, nchans=nchans, nbits=8, nifs=1, data_type=1,
        )
        path = tmp_path / f"beam{b}.fil"
        write_filterbank(path, Filterbank(
            header=hdr, data=np.clip(np.rint(data), 0, 255).astype(np.uint8)
        ))
        paths.append(str(path))

    # --- stage 1: coincidencer over the beams -> mask + birdie list ---
    samp_out, spec_out = tmp_path / "rfi.eb_mask", tmp_path / "birdies.txt"
    rc = coin_main(
        [*paths, "--o", str(samp_out), "--o2", str(spec_out),
         "--thresh", "4", "--beam_thresh", "4"]
    )
    assert rc == 0
    mask = np.array(
        [int(x) for x in samp_out.read_text().strip().splitlines()[1:]]
    )
    # the sample mask flags the pulse-train samples (multibeam in time)
    assert mask.size == nsamps
    on = rfi > 0
    assert mask[on].mean() < 0.5 < mask[~on].mean()
    birdies = np.loadtxt(spec_out)
    assert birdies.ndim == 2 and len(birdies) >= 1
    f_rfi = 1.0 / p_rfi
    # some birdie row must cover the tone's fundamental
    cover = np.abs(birdies[:, 0] - f_rfi) <= birdies[:, 1] / 2 + 0.5
    assert cover.any(), birdies

    # --- stage 2: peasoup search consuming the artifacts via -z/-k ---
    killfile = tmp_path / "chans.kill"
    killfile.write_text("1\n" * nchans)

    def run(outname, zap):
        outdir = tmp_path / outname
        argv = [
            "-i", paths[0], "-o", str(outdir), "--dm_end", "40",
            "-n", "2", "--limit", "50", "-k", str(killfile),
        ]
        if zap:
            argv += ["-z", str(spec_out)]
        assert peasoup_main(argv) == 0
        return OverviewFile(str(outdir / "overview.xml")).candidates

    def near_tone(cands):
        per = np.asarray([float(c["period"]) for c in cands])
        return np.abs(1.0 / per - f_rfi) < 0.02 * f_rfi

    control = run("out_nozap", zap=False)
    assert near_tone(control).any(), "control must detect the planted tone"
    zapped = run("out_zap", zap=True)
    assert not near_tone(zapped).any(), "birdie zap must remove the tone"
    # the pulsar (or a harmonic) survives the zap at ~the right DM; at
    # this tiny tobs the DM response is broad, so the crowned tie
    # member may sit anywhere in the cluster — some matching candidate
    # must carry the true DM
    best = zapped[0]
    ratio = float(best["period"]) / p_psr
    assert min(abs(ratio - r) for r in (0.25, 0.5, 1.0, 2.0, 4.0)) < 0.01
    psr_dms = [
        float(c["dm"])
        for c in zapped
        if min(
            abs(float(c["period"]) / p_psr - r)
            for r in (0.25, 0.5, 1.0, 2.0, 4.0)
        ) < 0.01
    ]
    assert min(abs(d - dm_psr) for d in psr_dms) < 10.0, psr_dms


def test_campaign_cli_subcommands(tmp_path, capsys):
    """Campaign CLI: run a 2-observation manifest (one corrupt) with a
    single worker invocation, then drive status/quarantine-list/
    retry/ingest through the CLI surface."""
    import json

    from peasoup_tpu.cli.campaign import main as camp_main
    from test_campaign import make_corrupt_obs, make_obs

    data = tmp_path / "data"
    data.mkdir()
    good = make_obs(str(data / "good.fil"))
    make_corrupt_obs(str(data / "bad.fil"), good)
    manifest = tmp_path / "obs.txt"
    manifest.write_text("data/good.fil\ndata/bad.fil\n")
    camp = tmp_path / "camp"

    rc = camp_main(
        [
            "run", "-w", str(camp), "--manifest", str(manifest),
            "--pipeline", "spsearch",
            "--config", '{"dm_end": 20, "min_snr": 7, "n_widths": 6}',
            "--max-attempts", "2", "--backoff", "0.05", "--poll", "0.05",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 2  # quarantine present -> non-zero, distinct from crash
    assert "enqueued 2 new" in out
    assert "1 done" in out and "1 quarantined" in out

    assert camp_main(["status", "-w", str(camp), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "peasoup_tpu.campaign_status"
    assert doc["queue"]["done"] == 1
    assert doc["queue"]["quarantined"] == 1

    assert camp_main(["quarantine-list", "-w", str(camp)]) == 0
    assert "unterminated sigproc header" in capsys.readouterr().out

    assert camp_main(["retry", "-w", str(camp), "--all"]) == 0
    assert "re-queued" in capsys.readouterr().out

    assert camp_main(["ingest", "-w", str(camp)]) == 0
    assert "1 jobs" in capsys.readouterr().out
