"""CLI tests: run the peasoup + coincidencer mains on synthetic data."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu.cli.coincidencer import birdies_from_mask, main as coin_main
from peasoup_tpu.cli.peasoup import build_parser, main as peasoup_main
from peasoup_tpu.tools import CandidateFileParser, OverviewFile
from test_pipeline import make_synthetic_fil


def test_parser_defaults_match_reference():
    args = build_parser().parse_args(["-i", "x.fil"])
    assert args.dm_end == 100.0
    assert args.dm_tol == 1.10
    assert args.num_threads == 14
    assert args.limit == 1000
    assert args.min_snr == 9.0
    assert args.max_harm == 16
    assert args.nharmonics == 4
    assert args.freq_tol == 0.0001


def test_peasoup_cli_end_to_end(tmp_path):
    path, period, dm = make_synthetic_fil(tmp_path)
    outdir = tmp_path / "out"
    rc = peasoup_main(
        [
            "-i", str(path), "-o", str(outdir), "--dm_end", "40",
            "-n", "2", "--npdmp", "2", "--limit", "20",
        ]
    )
    assert rc == 0
    ov = OverviewFile(str(outdir / "overview.xml"))
    assert len(ov.candidates) > 0
    assert "reading" in ov.execution_times
    top = ov.candidates[0]
    ratio = top["period"] / period
    assert min(abs(ratio - r) for r in (0.25, 0.5, 1.0, 2.0, 4.0)) < 0.01
    with CandidateFileParser(str(outdir / "candidates.peasoup")) as p:
        rec = p.read_candidate(int(top["byte_offset"]))
        assert len(rec["hits"]) == top["nassoc"] + 1


def test_coincidencer_cli(tmp_path):
    # 4 beams: same noise stats; one has a per-beam signal
    paths = []
    for b in range(4):
        beam_dir = tmp_path / f"b{b}"
        beam_dir.mkdir()
        p, _, _ = make_synthetic_fil(
            beam_dir, nsamps=1 << 13, amp=0.0, seed=100 + b
        )
        paths.append(str(p))
    samp_out = tmp_path / "mask.txt"
    spec_out = tmp_path / "birdies.txt"
    rc = coin_main(
        [*paths, "--o", str(samp_out), "--o2", str(spec_out), "--thresh", "4",
         "--beam_thresh", "3"]
    )
    assert rc == 0
    lines = samp_out.read_text().strip().splitlines()
    assert lines[0] == "#0 1"
    mask = np.array([int(x) for x in lines[1:]])
    # full dedispersed length, NOT truncated to a power of two
    # (coincidencer.cpp:136); DM=0 -> max_delay 0 -> all 8192 samples
    assert mask.size == 1 << 13
    assert mask.mean() > 0.9  # pure noise: almost everything kept


def test_birdies_from_mask():
    mask = np.array([1, 1, 0, 0, 0, 1, 0, 1])
    b = birdies_from_mask(mask, bin_width=2.0)
    # run of 3 zeros ending at index 4: freq=(4-1.5)*2=5.0 width=6.0
    assert b[0] == (5.0, 6.0)
    assert b[1] == ((6 - 0.5) * 2.0, 2.0)
