"""Candidate-ranking tests: batched feature extraction (bitwise
determinism, batch-size invariance, OOM-halving invariance, zero
steady-state recompiles), deterministic training + isotonic
calibration, model-artifact validation and fingerprinting, the
v3->v4 schema migration, the sky-position association gate, the
held-out ROC gate, and the end-to-end scored sift (DB columns,
report tiers, portal triage page, `peasoup-rank` CLI).
"""

import json
import os
import sqlite3

import numpy as np
import pytest

from peasoup_tpu.campaign.db import (
    _SCHEMA_V1,
    SCHEMA_VERSION,
    CandidateDB,
    SchemaVersionError,
)
from peasoup_tpu.io.sigproc import (
    Filterbank,
    SigprocHeader,
    write_filterbank,
)
from peasoup_tpu.obs.telemetry import RunTelemetry
from peasoup_tpu.ops.candidate_features import (
    DM_CURVE_POINTS,
    FEATURE_NAMES,
    NFEATURES,
)
from peasoup_tpu.rank.model import (
    DEFAULT_MODEL_PATH,
    SCORE_TIER1,
    SCORE_TIER2,
    RankModel,
    model_fingerprint,
    score_tier,
)
from peasoup_tpu.rank.score import (
    extract_features,
    neutral_dm_curve,
    score_fold_products,
)
from peasoup_tpu.rank.train import (
    evaluate_model,
    isotonic_calibration,
    roc_auc,
    synth_fold_products,
    train_model,
)
from peasoup_tpu.resilience import faults
from peasoup_tpu.resilience.stats import STATS
from peasoup_tpu.sift.dedup import (
    dedup_candidates,
    packed_position_deg,
    position_gate_ok,
    sky_separation_deg,
)
from peasoup_tpu.sift.repeats import repeat_sources
from peasoup_tpu.sift.service import SiftConfig, SiftRun

P0 = 0.714519699726  # J0332+5434 (B0329+54)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    STATS.reset()
    yield
    faults.configure(None)
    STATS.reset()


def _products(n=13, seed=3):
    prof, subints, dm_curve, labels, kinds = synth_fold_products(n, seed)
    return prof, subints, dm_curve


# --------------------------------------------------------------------------
# batched feature extraction
# --------------------------------------------------------------------------

class TestFeatureExtraction:
    def test_shapes_finite_and_bitwise_deterministic(self):
        prof, subints, dmc = _products()
        a = extract_features(prof, subints, dmc, batch=8)
        b = extract_features(prof, subints, dmc, batch=8)
        assert a.shape == (13, NFEATURES)
        assert a.dtype == np.float32
        assert np.all(np.isfinite(a))
        assert np.array_equal(a, b)

    def test_batch_size_invariance(self):
        """ISSUE satellite: feature rows are independent, so any batch
        width (padded by recycling rows) is bitwise-identical."""
        prof, subints, dmc = _products()
        want = extract_features(prof, subints, dmc, batch=64)
        for batch in (1, 5, 13):
            got = extract_features(prof, subints, dmc, batch=batch)
            assert np.array_equal(got, want), f"batch={batch}"

    def test_bitwise_equal_under_device_oom(self):
        """ISSUE satellite: an injected device.oom halves the batch
        (rank.features DegradationLadder rung) and the feature matrix
        stays bitwise-equal to the fault-free run."""
        prof, subints, dmc = _products()
        want = extract_features(prof, subints, dmc, batch=8)
        faults.configure("device.oom:at=1")
        tel = RunTelemetry()
        with tel.activate():
            got = extract_features(prof, subints, dmc, batch=8)
        degs = [e for e in tel.events if e["kind"] == "degradation"]
        assert degs and degs[0]["ladder"] == "rank.features"
        assert degs[0]["rung"] == "batch_shrink"
        assert np.array_equal(got, want)

    def test_oom_exhaustion_raises_at_batch_one(self):
        prof, subints, dmc = _products(n=3)
        faults.configure("device.oom:n=99")
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            extract_features(prof, subints, dmc, batch=2)

    def test_empty_input(self):
        feats = extract_features(
            np.empty((0, 64), np.float32),
            np.empty((0, 16, 64), np.float32),
            neutral_dm_curve(0),
        )
        assert feats.shape == (0, NFEATURES)

    def test_zero_steady_state_recompiles(self):
        """ISSUE satellite: warm same-width batches reuse ONE compiled
        feature program and ONE compiled scorer apply — the compile
        counter stays at zero after the first batch."""
        from peasoup_tpu.campaign.runner import jit_programs_compiled

        model = RankModel.from_file()
        prof, subints, dmc = _products(n=9, seed=5)
        score_fold_products(model, prof, subints, dmc, batch=8)  # warm
        tel = RunTelemetry()
        with tel.activate():
            for seed in (6, 7):
                p, s, d = _products(n=9, seed=seed)
                feats, scores = score_fold_products(
                    model, p, s, d, batch=8
                )
                assert feats.shape == (9, NFEATURES)
                assert len(scores) == 9
        assert jit_programs_compiled(tel) == 0


# --------------------------------------------------------------------------
# training, calibration, the ROC gate
# --------------------------------------------------------------------------

class TestTraining:
    def test_train_deterministic_from_seed(self):
        """ISSUE satellite: same seed -> identical artifact document,
        identical fingerprint."""
        kw = dict(seed=7, n_examples=120, steps=30, hidden=8)
        a = train_model(**kw)
        b = train_model(**kw)
        assert a == b
        assert a["fingerprint"] == b["fingerprint"]

    def test_roc_auc_reference_points(self):
        labels = np.array([0.0, 0.0, 1.0, 1.0])
        assert roc_auc(labels, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
        assert roc_auc(labels, np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
        assert roc_auc(labels, np.array([0.5, 0.5, 0.5, 0.5])) == 0.5

    def test_isotonic_calibration_monotone(self):
        """ISSUE satellite: the PAV fit is a valid calibration map —
        strictly increasing x, non-decreasing y, [0, 1] endpoints."""
        rng = np.random.default_rng(0)
        raw = rng.uniform(0.0, 1.0, 200)
        labels = (rng.uniform(0.0, 1.0, 200) < raw).astype(np.float64)
        xs, ys = isotonic_calibration(raw, labels)
        assert xs[0] == 0.0 and xs[-1] == 1.0
        assert all(b > a for a, b in zip(xs, xs[1:]))
        assert all(b >= a for a, b in zip(ys, ys[1:]))
        assert ys[0] >= 0.0 and ys[-1] <= 1.0

    def test_shipped_calibration_monotone(self):
        model = RankModel.from_file()
        grid = np.linspace(0.0, 1.0, 101)
        cal = model.calibrate(grid)
        assert np.all(np.diff(cal) >= 0.0)
        assert np.all((cal >= 0.0) & (cal <= 1.0))

    def test_shipped_model_passes_roc_gate(self):
        """ISSUE acceptance: held-out injected ROC AUC >= 0.95 for the
        checked-in artifact (the CI gate `peasoup-rank eval` holds)."""
        model = RankModel.from_file()
        ev = evaluate_model(model, n_examples=240)
        assert ev["auc"] >= 0.95
        assert ev["fingerprint"] == model.fingerprint
        assert ev["pulsar_tier1_frac"] > ev["foil_tier1_frac"]
        assert ev["median_pulsar_score"] > ev["median_foil_score"]


# --------------------------------------------------------------------------
# model artifact validation
# --------------------------------------------------------------------------

class TestModelArtifact:
    def _doc(self):
        with open(DEFAULT_MODEL_PATH) as f:
            return json.load(f)

    def test_shipped_artifact_loads_and_fingerprints(self):
        model = RankModel.from_file()
        assert model.fingerprint.startswith("sha256:")
        assert model.fingerprint == model_fingerprint(model.doc)
        assert model.doc["feature_names"] == list(FEATURE_NAMES)

    def test_tampered_weights_rejected(self):
        doc = self._doc()
        doc["w2"][0] = float(doc["w2"][0]) + 0.5
        with pytest.raises(ValueError, match="fingerprint mismatch"):
            RankModel(doc)

    def test_non_monotone_calibration_rejected(self):
        doc = self._doc()
        doc["calibration"] = {"x": [0.0, 0.5, 1.0], "y": [0.0, 0.8, 0.4]}
        doc["fingerprint"] = model_fingerprint(doc)
        with pytest.raises(ValueError, match="not monotone"):
            RankModel(doc)

    def test_wrong_feature_set_rejected(self):
        doc = self._doc()
        doc["feature_names"][0] = "bogus_feature"
        doc["fingerprint"] = model_fingerprint(doc)
        with pytest.raises(ValueError, match="different"):
            RankModel(doc)

    def test_score_tier_mapping(self):
        assert score_tier(0.99) == 1
        assert score_tier(SCORE_TIER1) == 1
        assert score_tier(0.6) == 2
        assert score_tier(SCORE_TIER2) == 2
        assert score_tier(0.1) == 3


# --------------------------------------------------------------------------
# schema v4 migration
# --------------------------------------------------------------------------

class TestDBSchemaV4:
    def _legacy_v1(self, path: str) -> None:
        conn = sqlite3.connect(path)
        conn.executescript(_SCHEMA_V1)
        conn.execute(
            "INSERT INTO observations (job_id, input, source_name, "
            "tstart, tsamp, nchans, nsamps, ingested_unix) VALUES "
            "('j1', 'a.fil', 'SRC', 55000.0, 2.56e-4, 8, 4096, 0)"
        )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period) "
            "VALUES ('j1', 'periodicity', 26.7, 9.0, 0.714)"
        )
        conn.commit()
        conn.close()

    def _sift_columns(self, db):
        return {
            r[1]
            for r in db._conn.execute(
                "PRAGMA table_info(sift_candidates)"
            )
        }

    def test_fresh_db_has_score_columns(self, tmp_path):
        with CandidateDB(str(tmp_path / "c.sqlite")) as db:
            assert db.schema_version() == SCHEMA_VERSION
            assert {"score", "score_tier", "model_fp"} <= (
                self._sift_columns(db)
            )

    def test_legacy_migrates_to_v4_idempotent(self, tmp_path):
        """ISSUE satellite: a pre-ranking DB gains the score columns
        in place (rows preserved); a second open finds nothing to do."""
        path = str(tmp_path / "c.sqlite")
        self._legacy_v1(path)
        for _ in range(2):
            with CandidateDB(path) as db:
                assert db.schema_version() == SCHEMA_VERSION
                assert {"score", "score_tier", "model_fp"} <= (
                    self._sift_columns(db)
                )
                cands = db.all_candidates("periodicity")
                assert len(cands) == 1 and cands[0]["dm"] == 26.7

    def test_future_version_refused_loudly(self, tmp_path):
        path = str(tmp_path / "c.sqlite")
        self._legacy_v1(path)
        conn = sqlite3.connect(path)
        conn.execute(f"PRAGMA user_version = {SCHEMA_VERSION + 7}")
        conn.commit()
        conn.close()
        with pytest.raises(SchemaVersionError, match="newer"):
            CandidateDB(path)

    def test_update_sift_scores(self, tmp_path):
        row = {
            "kind": "periodicity", "label": "candidate", "tier": 2,
            "dm": 10.0, "snr": 9.0, "period": 0.5, "job_ids": ["j1"],
        }
        with CandidateDB(str(tmp_path / "c.sqlite")) as db:
            db.ingest_sift_run("run1", {}, [row], [], [])
            [cat] = db.sift_catalogue()
            assert cat["score"] is None
            db.update_sift_scores([
                {"id": cat["id"], "score": 0.91, "score_tier": 1,
                 "model_fp": "sha256:feedc0de00000000"},
            ])
            [cat] = db.sift_catalogue()
            assert cat["score"] == 0.91
            assert cat["score_tier"] == 1
            assert cat["model_fp"] == "sha256:feedc0de00000000"


# --------------------------------------------------------------------------
# sky-position association gate
# --------------------------------------------------------------------------

class TestSkyPositionGate:
    def test_packed_position_decodes(self):
        ra, dec = packed_position_deg(123000.0, -453000.0)
        assert abs(ra - 187.5) < 1e-9  # 12h30m -> 187.5 deg
        assert abs(dec - (-45.5)) < 1e-9

    def test_separation_reference_points(self):
        assert sky_separation_deg(5.0, 5.0, 5.0, 5.0) == 0.0
        assert abs(sky_separation_deg(0, 0, 180, 0) - 180.0) < 1e-9
        assert abs(sky_separation_deg(10, 20, 10, 21) - 1.0) < 1e-9

    def test_gate_disabled_or_missing_position_passes(self):
        a = {"src_raj": 0.0, "src_dej": 0.0}
        b = {"src_raj": 120000.0, "src_dej": 0.0}  # 180 deg away
        assert position_gate_ok(a, b, 0.0)  # disabled
        assert position_gate_ok(a, {"src_raj": None, "src_dej": None}, 1.0)
        assert position_gate_ok(a, {}, 1.0)
        assert not position_gate_ok(a, b, 1.0)

    def _row(self, rid, job, period, raj, dej, snr=9.0):
        return {
            "id": rid, "job_id": job, "period": period, "dm": 30.0,
            "snr": snr, "src_raj": raj, "src_dej": dej,
        }

    def test_dedup_antipodal_harmonic_not_merged(self):
        """ISSUE satellite: a harmonic coincidence between antipodal
        pointings stays two catalogue rows under the gate (and still
        merges with the gate off)."""
        lead = self._row(1, "j0", P0, 0.0, 0.0, snr=12.0)
        harm = self._row(2, "j1", P0 / 2, 120000.0, 0.0)
        gated = dedup_candidates([lead, harm], pos_tol_deg=3.0)
        assert len(gated) == 2
        merged = dedup_candidates([lead, harm], pos_tol_deg=0.0)
        assert len(merged) == 1 and len(merged[0]["members"]) == 2

    def test_dedup_adjacent_beams_still_merge(self):
        # 0h04m (1 deg RA) and 0d30m (0.5 deg dec) away: ~1.1 deg
        lead = self._row(1, "j0", P0, 0.0, 0.0, snr=12.0)
        harm = self._row(2, "j1", P0 / 2, 400.0, 3000.0)
        [group] = dedup_candidates([lead, harm], pos_tol_deg=3.0)
        assert len(group["members"]) == 2
        assert group["n_obs"] == 2

    def test_repeat_sources_position_split(self):
        """A DM-coincident single-pulse chain from antipodal pointings
        is not one RRAT: the position split leaves each half below
        min_obs and the 'source' disappears."""
        rows = []
        rid = 0
        for job, raj, tstart in (
            ("j0", 0.0, 55000.0), ("j1", 120000.0, 55000.01),
        ):
            for k in (1, 3, 7):
                rows.append({
                    "id": rid, "job_id": job, "dm": 40.0, "snr": 8.0,
                    "time_s": 0.05 + k * 0.5, "obs_tstart": tstart,
                    "src_raj": raj, "src_dej": 0.0,
                })
                rid += 1
        merged = repeat_sources(rows, min_pulses=4, pos_tol_deg=0.0)
        assert len(merged) == 1 and merged[0]["n_obs"] == 2
        assert repeat_sources(rows, min_pulses=4, pos_tol_deg=3.0) == []


# --------------------------------------------------------------------------
# end-to-end: the scored sift, report tiers, portal triage, the CLI
# --------------------------------------------------------------------------

def _seed_campaign(camp):
    """A 2-observation campaign: the injected pulsar fundamental in
    obs0, its 1/2 harmonic in obs1, plus one unrelated candidate —
    both observations stamped tenant 'alice'."""
    camp.mkdir(exist_ok=True)
    nsamps, nchans, tsamp = 4096, 8, 0.000256
    rng = np.random.default_rng(0)
    with CandidateDB(str(camp / "candidates.sqlite")) as db:
        conn = db._conn
        for i in range(2):
            data = np.clip(
                np.rint(rng.normal(32.0, 4.0, size=(nsamps, nchans))),
                0, 255,
            ).astype(np.uint8)
            hdr = SigprocHeader(
                source_name=f"OBS{i}", tsamp=tsamp,
                tstart=55000.0 + i * 0.01, fch1=1400.0, foff=-16.0,
                nchans=nchans, nbits=8, nifs=1, data_type=1,
                ibeam=i + 1,
            )
            write_filterbank(
                str(camp / f"obs{i}.fil"),
                Filterbank(header=hdr, data=data),
            )
            conn.execute(
                "INSERT INTO observations (job_id, input, source_name,"
                " tstart, tsamp, nchans, nsamps, ingested_unix, beam,"
                " src_raj, src_dej, tenant) VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?)",
                (f"job{i}", str(camp / f"obs{i}.fil"), f"OBS{i}",
                 55000.0 + i * 0.01, tsamp, nchans, nsamps, 0.0,
                 i + 1, 0.0, 0.0, "alice"),
            )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period, "
            "acc, nh) VALUES ('job0', 'periodicity', 26.76, 12.0, ?, "
            "0.0, 2)", (P0,),
        )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period, "
            "acc, nh) VALUES ('job1', 'periodicity', 26.80, 9.0, ?, "
            "0.0, 1)", (P0 / 2,),
        )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period, "
            "acc, nh) VALUES ('job1', 'periodicity', 80.0, 8.0, "
            "0.1234, 0.0, 1)"
        )
        conn.commit()
    return camp


@pytest.fixture(scope="module")
def scored_camp(tmp_path_factory):
    camp = _seed_campaign(tmp_path_factory.mktemp("rankcamp") / "camp")
    tel = RunTelemetry()
    with tel.activate():
        summary = SiftRun(
            SiftConfig(workdir=str(camp), fold_batch=8)
        ).run()
    return camp, summary, list(tel.events)


class TestScoredSiftEndToEnd:
    def test_catalogue_rows_scored(self, scored_camp):
        """ISSUE acceptance: the sift run scores every folded
        catalogue row — calibrated probability, tier, and the model
        fingerprint land in the v4 columns, the DM curve in the fold
        stamp."""
        camp, summary, events = scored_camp
        assert "sift_scored" in [e["kind"] for e in events]
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            cat = db.sift_catalogue()
            scored = [c for c in cat if c["score"] is not None]
            assert scored
            for c in scored:
                assert 0.0 <= c["score"] <= 1.0
                assert c["score_tier"] in (1, 2, 3)
                assert c["model_fp"].startswith("sha256:")
                fold = json.loads(c["fold_json"])
                assert len(fold["dm_curve"]) == DM_CURVE_POINTS
            # one model scored the whole catalogue
            assert len({c["model_fp"] for c in scored}) == 1

    def test_report_carries_score_tiers(self, scored_camp, tmp_path):
        from peasoup_tpu.sift.report import (
            build_report,
            render_html,
            write_report,
        )

        camp, _, _ = scored_camp
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            doc = build_report(db)
        assert doc["model_fp"] and doc["model_fp"].startswith("sha256:")
        assert sum(doc["score_tiers"].values()) >= 1
        html = render_html(doc)
        assert "s-tier" in html and doc["model_fp"] in html
        # the document stays schema-valid with the new fields
        write_report(
            doc, str(tmp_path / "r.json"), str(tmp_path / "r.html")
        )

    def test_report_tenant_view(self, scored_camp):
        from peasoup_tpu.sift.report import build_report

        camp, _, _ = scored_camp
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            alice = build_report(db, tenant="alice")
            ghost = build_report(db, tenant="nosuch")
        assert alice["tenant"] == "alice"
        assert alice["observations"] == 2
        assert len(alice["catalogue"]) >= 1
        assert ghost["observations"] == 0
        assert ghost["catalogue"] == []

    def test_portal_candidate_triage_page(self, scored_camp, tmp_path):
        from peasoup_tpu.obs.portal import _candidates_body

        camp, _, _ = scored_camp
        body = _candidates_body(str(camp))
        assert body is not None
        text = body.decode()
        assert "sha256:" in text and "tier" in text
        # the tenant-scoped view renders the same rows for the
        # stamping tenant; a bad tenant name or missing DB 404s (None)
        assert _candidates_body(str(camp), tenant="alice") is not None
        assert _candidates_body(str(camp), tenant="../evil") is None
        assert _candidates_body(str(tmp_path)) is None

    def test_rank_score_cli_rescored_in_place(self, scored_camp):
        """`peasoup-rank score` re-scores the sifted DB from stored
        fold products alone (no raw data touched)."""
        from peasoup_tpu.cli.rank import main

        camp, _, _ = scored_camp
        assert main(["score", "-w", str(camp)]) == 0
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            cat = db.sift_catalogue()
            rescored = [c for c in cat if c.get("fold_json")]
            assert rescored
            assert all(c["score"] is not None for c in rescored)

    def test_tenant_scoped_sift_run(self, tmp_path):
        camp = _seed_campaign(tmp_path / "camp")
        conn = sqlite3.connect(str(camp / "candidates.sqlite"))
        conn.execute(
            "UPDATE observations SET tenant = 'bob' "
            "WHERE job_id = 'job1'"
        )
        conn.commit()
        conn.close()
        summary = SiftRun(
            SiftConfig(workdir=str(camp), fold=False, tenant="alice")
        ).run()
        assert summary["observations"] == 1
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            cat = db.sift_catalogue()
            assert cat
            for c in cat:
                assert json.loads(c["job_ids"]) == ["job0"]


class TestRankCLI:
    def test_train_writes_loadable_artifact(self, tmp_path):
        from peasoup_tpu.cli.rank import main

        out = str(tmp_path / "m.json")
        rc = main([
            "train", "-o", out, "--seed", "3", "--examples", "120",
            "--steps", "30", "--hidden", "8",
        ])
        assert rc == 0
        model = RankModel.from_file(out)
        assert model.doc["seed"] == 3
        assert model.fingerprint == model_fingerprint(model.doc)

    def test_eval_gate_exit_codes(self, tmp_path):
        """ISSUE acceptance: `peasoup-rank eval` exits 0 at the CI
        threshold and 2 below an unreachable one."""
        from peasoup_tpu.cli.rank import main

        out = str(tmp_path / "eval.json")
        assert main([
            "eval", "--examples", "160", "--min-auc", "0.95",
            "--json", out,
        ]) == 0
        with open(out) as f:
            ev = json.load(f)
        assert ev["auc"] >= 0.95
        assert main([
            "eval", "--examples", "160", "--min-auc", "1.01",
        ]) == 2
