# audit-path: peasoup_tpu/ops/pallas/psk206.py
"""Fixture: PSK201 (unregistered kernel module) + PSK206 (scalar
prefetch vs kernel arity)."""
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(w_ref, x_ref, o_ref, win_ref, sem):
    o_ref[:] = x_ref[:]


def build_bad(n):
    grid_spec = pltpu.PrefetchScalarGridSpec(  # expect[PSK206]
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[pl.BlockSpec((8, 128), memory_space=pltpu.VMEM)],
        scratch_shapes=[
            pltpu.VMEM((1024,), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(  # expect[PSK201]
        partial(_kernel),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )


def build_good(n):
    grid_spec = pltpu.PrefetchScalarGridSpec(  # ok: 1+1+1+2 == 5 refs
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[pl.BlockSpec((8, 128), memory_space=pltpu.VMEM)],
        scratch_shapes=[
            pltpu.VMEM((1024,), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return grid_spec
