# audit-path: peasoup_tpu/ops/fixture_np_array.py
"""Fixture: PSA004 — dtype-less np.array literals."""
import numpy as np


def stage_constants(existing):
    a = np.array([1.0, 2.0, 3.0])  # expect[PSA004]
    b = np.array([x * 2 for x in range(4)])  # expect[PSA004]
    c = np.array([1.0, 2.0], dtype=np.float32)  # ok: explicit dtype
    d = np.asarray(existing)  # ok: conversion, not a literal
    return a, b, c, d
