# audit-path: peasoup_tpu/stream/psp106.py
"""Fixture: PSP106 — ambient telemetry does not cross thread
boundaries uncopied."""
import contextvars
import threading

from peasoup_tpu.obs.telemetry import current as current_telemetry
from peasoup_tpu.resilience import guard_thread


def _noop():
    return None


def _bad_body():
    guard_thread("x", _noop)
    current_telemetry().event("tick")  # expect[PSP106]


def spawn_bad():
    t = threading.Thread(target=_bad_body, daemon=True)
    t.start()


def _good_body(tel):
    guard_thread("x", _noop, telemetry=tel)
    tel.event("tick")  # ok: telemetry handed in explicitly


def spawn_good(tel):
    t = threading.Thread(target=lambda: _good_body(tel), daemon=True)
    t.start()


def _copied_body():
    guard_thread("x", _noop)
    current_telemetry().event("tick")  # ok: context copied at spawn


def spawn_copied():
    ctx = contextvars.copy_context()
    t = threading.Thread(
        target=lambda: ctx.run(_copied_body), daemon=True
    )
    t.start()
