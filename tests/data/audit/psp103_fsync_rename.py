# audit-path: peasoup_tpu/pipeline/psp103.py
"""Fixture: PSP103 — fsync before rename in durability-marked
helpers."""
import os
import tempfile


def save_checkpoint(path, blob):
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d)
    with os.fdopen(fd, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)  # expect[PSP103]


def save_checkpoint_durably(path, blob):
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d)
    with os.fdopen(fd, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # ok: data blocks flushed before the rename


def rewrite_snapshot(path, text):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)  # ok: not durability-marked (reconstructible)
