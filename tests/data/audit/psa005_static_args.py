# audit-path: peasoup_tpu/ops/fixture_static_args.py
"""Fixture: PSA005 — non-hashable / array-valued static jit args."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("widths",))
def mutable_default(x, widths=[1, 2, 4]):  # expect[PSA005]
    return x * len(widths)


@partial(jax.jit, static_argnames=("mask",))
def array_static(x, mask: jax.Array):  # expect[PSA005]
    return x * mask


def helper(x, n):
    return x * n


jitted_helper = jax.jit(helper, static_argnums=[1])  # expect[PSA005]


@partial(jax.jit, static_argnames=("n", "mode"))
def good_static(x, n: int = 4, mode: str = "conv"):  # ok: hashable
    return x * n
