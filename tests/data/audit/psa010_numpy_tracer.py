# audit-path: peasoup_tpu/ops/fixture_numpy_tracer.py
"""Fixture: PSA010 — numpy ops applied to tracers inside jit."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def np_on_tracer(x):
    m = np.sum(x)  # expect[PSA010]
    c = np.clip(x, 0.0, 1.0)  # expect[PSA010]
    s = np.float32(2.0)  # ok: host scalar constant
    k = np.log2(x.shape[0])  # ok: shape metadata is concrete
    return m + jnp.sum(c) * s * k


def host_numpy(x):
    return np.sum(x)  # ok: not jitted
