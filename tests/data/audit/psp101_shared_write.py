# audit-path: peasoup_tpu/campaign/psp101.py
"""Fixture: PSP101 — non-atomic writes to shared artifact paths."""
import os
import tempfile


def bad_queue_write(doc):
    path = os.path.join("campaign", "queue", "jobs", "a.json")
    with open(path, "w") as f:  # expect[PSP101]
        f.write("x")


def bad_status_rewrite(text, root):
    status = root + "/status.json"
    with open(status, "w") as f:  # expect[PSP101]
        f.write(text)


def good_atomic_rewrite(path, text):
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    with os.fdopen(fd, "w") as f:  # ok: fd write of a mkstemp tmp file
        f.write(text)
    os.replace(tmp, path)


def good_tmp_suffix(path, text):
    tmp = path + ".tmp"  # ok: the tmp half of the atomic idiom
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def good_append_recorder(root, line):
    log = os.path.join(root, "queue", "workers", "w.metrics.jsonl")
    with open(log, "a") as f:  # ok: append-only recorder
        f.write(line)


def good_private_scratch(text):
    with open("scratch.txt", "w") as f:  # ok: not a shared artifact
        f.write(text)
