# audit-path: peasoup_tpu/ops/pallas/psk204.py
"""Fixture: PSK204/PSK205 — tile shapes vs the TPU quanta (static
lint only: no pallas_call, so PSK201 stays quiet)."""
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

GOOD = pl.BlockSpec((8, 128), memory_space=pltpu.VMEM)  # ok: on-quanta
WIDE = pl.BlockSpec((16, 256), memory_space=pltpu.VMEM)  # ok: multiples
UNIT = pl.BlockSpec((1, 128), memory_space=pltpu.VMEM)  # ok: unit dim
SMEM = pl.BlockSpec((1, 1), memory_space=pltpu.SMEM)  # ok: untiled scalars
BAD_LANE = pl.BlockSpec((8, 96), memory_space=pltpu.VMEM)  # expect[PSK204]
BAD_SUB = pl.BlockSpec((6, 128), memory_space=pltpu.VMEM)  # expect[PSK204]
SCRATCH_OK = pltpu.VMEM((16, 128), jnp.bfloat16)  # ok: 16-row bf16 quantum
SCRATCH_BAD = pltpu.VMEM((8, 128), jnp.bfloat16)  # expect[PSK205]
SCRATCH_F32 = pltpu.VMEM((8, 128), jnp.float32)  # ok: 8-row f32 quantum
