# audit-path: peasoup_tpu/campaign/psp102.py
"""Fixture: PSP102 — delete where the quarantine policy requires
rename."""
import json
import os
import tempfile


def bad_delete_on_parse_error(path):
    try:
        with open(path) as f:
            return json.load(f)
    except json.JSONDecodeError:
        os.remove(path)  # expect[PSP102]
        return None


def good_quarantine_rename(path):
    try:
        with open(path) as f:
            return json.load(f)
    except json.JSONDecodeError:
        os.rename(path, path + ".corrupt")  # ok: rename keeps forensics
        return None


def good_tmp_cleanup(path, text):
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except OSError:
        os.unlink(tmp)  # ok: tmp cleanup on the write error path
        raise
