# audit-path: peasoup_tpu/ops/fixture_host_sync.py
"""Fixture: PSA001 — host syncs inside jitted/scan bodies."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def jitted_item(x):
    return x.sum().item()  # expect[PSA001]


@partial(jax.jit, static_argnames=("n",))
def jitted_mixed(x, n):
    y = float(x)  # expect[PSA001]
    z = jax.device_get(x)  # expect[PSA001]
    w = np.asarray(x)  # expect[PSA001]
    k = float(n)  # ok: n is a static argument
    m = int(x.shape[0])  # ok: shape metadata is concrete
    return y, z, w, k, m


def scan_user(xs):
    def body(c, x):
        return c + x.item(), None  # expect[PSA001]

    return jax.lax.scan(body, 0.0, xs)


def host_driver(x):
    return float(np.asarray(x).sum())  # ok: plain host code, not jitted
