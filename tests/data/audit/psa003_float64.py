# audit-path: peasoup_tpu/ops/fixture_float64.py
"""Fixture: PSA003 — float64 in device code."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def f64_in_jit(x):
    y = x.astype(np.float64)  # expect[PSA003]
    z = x * np.float64(2.0)  # expect[PSA003]
    w = jnp.zeros(4, dtype="float64")  # expect[PSA003]
    return y, z, w


def jnp_f64_on_host(x):
    return jnp.float64(x)  # expect[PSA003]


def host_staging(vals):
    k = np.arange(8, dtype=np.float64)  # ok: host staging math
    return np.asarray(vals, dtype=np.float64) + k  # ok: host f64


@jax.jit
def f32_everywhere(x):
    return x.astype(jnp.float32) * np.float32(2.0)  # ok: f32
