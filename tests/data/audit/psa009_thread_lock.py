# audit-path: peasoup_tpu/obs/fixture_thread_lock.py
"""Fixture: PSA009 — thread-shared mutation outside a lock (the
PSP deepenings fire on the same hazards: the unguarded thread
target is PSP104, and the lock-owned attributes mutated lock-free
are PSP105)."""
import threading


class Worker:
    def __init__(self):
        self._count = 0
        self._items = []
        self._lock = threading.Lock()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)  # expect[PSP104]
        self._thread.start()

    def _run(self):
        self._count += 1  # expect[PSA009] expect[PSP105]
        self._items.append(1)  # expect[PSA009] expect[PSP105]
        with self._lock:
            self._count += 1  # ok: guarded
            self._items.append(2)  # ok: guarded


class NotThreaded:
    def __init__(self):
        self._count = 0

    def bump(self):
        self._count += 1  # ok: no thread spawned by this class
