# audit-path: peasoup_tpu/obs/fixture_thread_lock.py
"""Fixture: PSA009 — thread-shared mutation outside a lock."""
import threading


class Worker:
    def __init__(self):
        self._count = 0
        self._items = []
        self._lock = threading.Lock()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        self._count += 1  # expect[PSA009]
        self._items.append(1)  # expect[PSA009]
        with self._lock:
            self._count += 1  # ok: guarded
            self._items.append(2)  # ok: guarded


class NotThreaded:
    def __init__(self):
        self._count = 0

    def bump(self):
        self._count += 1  # ok: no thread spawned by this class
