# audit-path: peasoup_tpu/campaign/psp107.py
"""Fixture: PSP107 — direct delete of a shared artifact path."""
import os
import uuid


def bad_delete_claim(root, job_id):
    # read-check-delete: between the exists() and the unlink a renewer
    # may have republished the claim — the unlink destroys theirs
    path = os.path.join(root, "queue", "claims", job_id + ".json")
    if os.path.exists(path):
        os.unlink(path)  # expect[PSP107]


def bad_remove_job(root, job_id):
    jpath = os.path.join(root, "jobs", job_id + ".json")
    os.remove(jpath)  # expect[PSP107]


def good_tombstone_dance(root, job_id):
    path = os.path.join(root, "queue", "claims", job_id + ".json")
    tomb = path + ".reap." + uuid.uuid4().hex[:8]
    os.rename(path, tomb)  # ok: rename transfers ownership first
    os.unlink(tomb)  # ok: tombstone is ours alone to consume


def good_release_tombstone(root, job_id):
    path = os.path.join(root, "queue", "claims", job_id + ".json")
    tomb = path + ".release." + uuid.uuid4().hex[:8]
    os.rename(path, tomb)  # ok: release dance, same idiom
    os.unlink(tomb)  # ok: verified tombstone consumption


def good_quarantine(root, name):
    path = os.path.join(root, "queue", "jobs", name)
    os.rename(path, path + ".corrupt")  # ok: forensics survive
