# audit-path: peasoup_tpu/ops/fixture_tracer_branch.py
"""Fixture: PSA002 — Python control flow on tracer values."""
from functools import partial

import jax


@jax.jit
def branch_on_tracer(x):
    if x.sum() > 0:  # expect[PSA002]
        return x
    return -x


@jax.jit
def loop_on_tracer(x):
    while x > 0:  # expect[PSA002]
        x = x - 1
    return x


@partial(jax.jit, static_argnames=("flag",))
def static_and_structural(x, flag):
    if flag:  # ok: static argument
        return x * 2
    if x is None:  # ok: structural None check
        return x
    if x.ndim == 2:  # ok: shape metadata
        return x.sum(axis=0)
    return x


def host_branch(x):
    if x > 0:  # ok: not jitted
        return x
    return -x
