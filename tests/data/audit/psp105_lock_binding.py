# audit-path: peasoup_tpu/stream/psp105.py
"""Fixture: PSP105 — lock-owned attributes never mutate lock-free."""
import threading

from peasoup_tpu.resilience import guard_thread


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []  # ok: no thread exists during __init__
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        guard_thread("recorder", self._loop)

    def _loop(self):
        with self._lock:
            self._events.append("tick")  # ok: owning lock held

    def drain(self):
        with self._lock:
            out = list(self._events)
            self._events.clear()  # ok: same lock as the appender
        return out

    def reset(self):
        self._events = []  # expect[PSP105]
