# audit-path: peasoup_tpu/pipeline/fixture_print.py
"""Fixture: PSA007 — print() in library code."""
from peasoup_tpu.obs.log import get_logger

log = get_logger("fixture")


def report(x):
    print("value", x)  # expect[PSA007]
    log.info("value %s", x)  # ok: the library logger
