# audit-path: peasoup_tpu/ops/pallas/psk207.py
"""Fixture: PSK207 — lane-retiling reshape in a kernel without a
declared retile-fallback ladder (the module is unregistered, so
PSK201 fires on the pallas_call too)."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    chunk = x_ref[...].reshape(1, 256)  # ok: unit-row keeps the lanes
    flat = chunk.reshape(-1)  # ok: flatten
    tile = flat.reshape(8, 32)  # expect[PSK207]
    o_ref[:] = tile


def build():
    return pl.pallas_call(  # expect[PSK201]
        _kernel,
        out_shape=jax.ShapeDtypeStruct((8, 32), jnp.float32),
    )
