# audit-path: peasoup_tpu/obs/fixture_atomic_write.py
"""Fixture: PSA008 — non-atomic JSON writes to shared files."""
import json
import os


def write_status(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)  # expect[PSA008]


def write_status_dumps(path, doc):
    with open(path, "w") as f:
        f.write(json.dumps(doc))  # expect[PSA008]


def write_atomic(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)  # ok: os.replace below makes it atomic
    os.replace(tmp, path)


def read_back(path):
    with open(path) as f:  # ok: read mode
        return json.load(f)
