# audit-path: peasoup_tpu/obs/fixture_time_time.py
"""Fixture: PSA006 — time.time() where perf_counter is required."""
import time


def measure(fn):
    t0 = time.time()  # expect[PSA006]
    fn()
    return time.time() - t0  # expect[PSA006]


class Snapshotter:
    def stamp(self):
        self.created_unix = time.time()  # ok: epoch timestamp
        now = time.time()  # ok: conventional epoch name
        return {"updated_unix": time.time(), "now": now}  # ok: epoch


def right_way(fn):
    t0 = time.perf_counter()  # ok: monotonic duration clock
    fn()
    return time.perf_counter() - t0
