# audit-path: peasoup_tpu/obs/psp104.py
"""Fixture: PSP104 — thread bodies must run under guard_thread."""
import threading

from peasoup_tpu.resilience import guard_thread


def work():
    return 1


def spawn_bad():
    t = threading.Thread(target=work, daemon=True)  # expect[PSP104]
    t.start()
    return t


def _guarded():
    guard_thread("worker", work)


def spawn_good():
    t = threading.Thread(target=_guarded, daemon=True)  # ok: guarded
    t.start()
    return t


class BadLoop(threading.Thread):
    def run(self):  # expect[PSP104]
        work()


class GoodLoop(threading.Thread):
    def run(self):  # ok: run body under the crash guard
        guard_thread("loop", work)
