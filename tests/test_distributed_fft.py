"""Distributed (sequence-parallel) FFT vs single-device jnp.fft, on the
virtual 8-device CPU mesh (conftest forces the platform + device count)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from peasoup_tpu.parallel.distributed_fft import (
    distributed_fft,
    distributed_rfft,
    unshuffle_fft_order,
)
from peasoup_tpu.parallel.mesh import make_mesh


@pytest.fixture
def rng():
    return np.random.default_rng(7)


@pytest.fixture(params=[2, 4, 8])
def mesh(request):
    p = request.param
    if len(jax.devices()) < p:
        pytest.skip(f"need {p} devices")
    return make_mesh({"seq": p}, devices=jax.devices()[:p])


class TestDistributedFFT:
    def test_c2c_matches_jnp(self, rng, mesh):
        p = mesh.shape["seq"]
        n = 64 * p * p
        x = (rng.normal(size=n) + 1j * rng.normal(size=n)).astype(np.complex64)
        got2d = distributed_fft(jnp.asarray(x), mesh, "seq")
        got = unshuffle_fft_order(np.asarray(got2d))
        want = np.fft.fft(x)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-2)

    def test_c2c_rejects_bad_length(self, mesh):
        p = mesh.shape["seq"]
        with pytest.raises(ValueError):
            distributed_fft(jnp.zeros(p * p + 1, jnp.complex64), mesh, "seq")

    def test_rfft_matches_jnp(self, rng, mesh):
        p = mesh.shape["seq"]
        n = 128 * p * p
        x = rng.normal(size=n).astype(np.float32)
        got = np.asarray(distributed_rfft(jnp.asarray(x), mesh, "seq"))
        want = np.fft.rfft(x)[: n // 2]
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-2)

    def test_rfft_on_pulsed_signal(self, rng, mesh):
        """End-use shape: a pulsar-like periodic signal's fundamental
        bin must carry the same power as the single-chip transform."""
        p = mesh.shape["seq"]
        n = 128 * p * p
        t = np.arange(n)
        x = (rng.normal(size=n) + 5.0 * ((t % 100) < 10)).astype(np.float32)
        got = np.asarray(distributed_rfft(jnp.asarray(x), mesh, "seq"))
        want = np.fft.rfft(x)[: n // 2]
        fund = n // 100
        assert abs(got[fund] - want[fund]) / abs(want[fund]) < 1e-4
        np.testing.assert_allclose(np.abs(got), np.abs(want), rtol=2e-4,
                                   atol=2e-2)

    def test_rfft_rejects_bad_length(self, mesh):
        with pytest.raises(ValueError):
            distributed_rfft(jnp.zeros(6, jnp.float32), mesh, "seq")
