"""Inventory drift guard: every component PARITY.md claims (mapping
SURVEY.md §2 line by line) must actually exist under its documented
name, so a rename or removal that forgets the docs fails loudly
instead of leaving PARITY.md citing symbols that no longer exist."""

import importlib

import pytest

# (module, [symbols]) — the public names PARITY.md cites
INVENTORY = [
    # §2a device/kernel components
    ("peasoup_tpu.ops.harmonics", ["harmonic_sums"]),
    ("peasoup_tpu.ops.spectrum", [
        "form_power", "form_interpolated", "spectrum_stats", "normalise",
    ]),
    ("peasoup_tpu.ops.resample", [
        "resample_accel", "resample_select", "resample_accel_quadratic",
        "accel_factor",
    ]),
    ("peasoup_tpu.ops.pallas.resample", [
        "resample_block_pallas", "choose_block",
    ]),
    ("peasoup_tpu.ops.peaks", [
        "find_peaks_device", "cluster_peaks", "cluster_peaks_device",
    ]),
    ("peasoup_tpu.ops.pallas.peaks", ["find_cluster_peaks_pallas"]),
    ("peasoup_tpu.ops.fold", ["fold_time_series", "fold_bins_np"]),
    ("peasoup_tpu.ops.fold_optimise", ["FoldOptimiser"]),
    ("peasoup_tpu.ops.rednoise", [
        "median_scrunch5", "linear_stretch", "running_median", "deredden",
        "whiten_fseries",
    ]),
    ("peasoup_tpu.ops.zap", ["birdie_mask", "zap_birdies"]),
    ("peasoup_tpu.ops.coincidence", ["coincidence_mask"]),
    ("peasoup_tpu.ops.correlate", ["find_delays"]),
    ("peasoup_tpu.ops.dedisperse", [
        "dedisperse_block", "dedisperse_device", "dedisperse",
        "dedisperse_subband", "subband_groups", "unpack_fil_device",
        "fil_to_device", "output_scale",
    ]),
    ("peasoup_tpu.ops.pallas.dedisperse", [
        "dedisperse_pallas", "plan_spread", "pallas_hbm_bytes",
    ]),
    ("peasoup_tpu.ops.ffa", [
        "ffa_transform", "ffa_search_block", "ffa_search_series",
        "boxcar_snr", "collapse_periods",
    ]),
    # §2b host-side components
    ("peasoup_tpu.io.sigproc", [
        "read_sigproc_header", "write_sigproc_header", "SigprocHeader",
        "Filterbank", "read_filterbank", "write_filterbank",
        "read_timeseries",
    ]),
    ("peasoup_tpu.io.dada", ["DadaHeader"]),
    ("peasoup_tpu.io.masks", ["read_killfile", "read_zapfile"]),
    ("peasoup_tpu.io.output", ["OutputFileWriter", "CandidateFileWriter"]),
    ("peasoup_tpu.io.xml_writer", ["Element"]),
    ("peasoup_tpu.core.candidates", [
        "Candidate", "CandidateCollection",
    ]),
    ("peasoup_tpu.plan.dm_plan", [
        "DMPlan", "generate_dm_list", "delay_table", "max_delay_samples",
    ]),
    ("peasoup_tpu.plan.accel_plan", ["AccelerationPlan"]),
    ("peasoup_tpu.plan.fft_plan", ["choose_fft_size", "prev_power_of_two"]),
    ("peasoup_tpu.pipeline.search", [
        "PeasoupSearch", "SearchConfig", "SearchResult",
        "PartialSearchResult",
    ]),
    ("peasoup_tpu.pipeline.distill", [
        "HarmonicDistiller", "AccelerationDistiller", "DMDistiller",
    ]),
    ("peasoup_tpu.pipeline.score", ["CandidateScorer"]),
    ("peasoup_tpu.pipeline.folder", ["MultiFolder"]),
    ("peasoup_tpu.pipeline.checkpoint", ["SearchCheckpoint"]),
    # §2c application entry points
    ("peasoup_tpu.cli.peasoup", ["main", "build_parser"]),
    ("peasoup_tpu.cli.ffa", ["main"]),
    ("peasoup_tpu.cli.coincidencer", ["main"]),
    ("peasoup_tpu.cli.accmap", ["main"]),
    # §2d post-processing
    ("peasoup_tpu.tools.parsers", ["OverviewFile", "CandidateFileParser"]),
    ("peasoup_tpu.tools.plotting", ["CandidatePlotter"]),
    ("peasoup_tpu.tools.as_text", ["main"]),
    # §2e parallelism & communication
    ("peasoup_tpu.parallel.mesh", ["make_mesh", "device_count"]),
    ("peasoup_tpu.parallel.sharded_search", [
        "make_sharded_search_fn", "place_trials",
    ]),
    ("peasoup_tpu.parallel.coincidence", [
        "sharded_coincidence", "baseline_beam",
    ]),
    ("peasoup_tpu.parallel.distributed_fft", ["distributed_rfft"]),
    ("peasoup_tpu.parallel.multihost", [
        "initialize", "global_mesh", "process_local_slice",
        "dm_slice_for_process", "run_search",
    ]),
    # §5 auxiliary subsystems
    ("peasoup_tpu.utils.trace", ["trace_span", "Stopwatch"]),
    ("peasoup_tpu.utils.progress", ["ProgressBar"]),
    ("peasoup_tpu.utils.debug", ["dump_buffer"]),
    ("peasoup_tpu.native", ["available"]),
    # observability: run telemetry manifest + structured logging
    ("peasoup_tpu.obs.telemetry", [
        "RunTelemetry", "current", "load_manifest",
    ]),
    ("peasoup_tpu.obs.log", ["get_logger", "configure", "resolve_level"]),
    ("peasoup_tpu.tools.report", ["render", "diff"]),
    ("peasoup_tpu.tools.scope_trace", [
        "scope_trace", "parse_trace_events", "result_from_trace_file",
    ]),
]


@pytest.mark.parametrize(
    "module,symbols", INVENTORY, ids=[m for m, _ in INVENTORY]
)
def test_component_exists(module, symbols):
    mod = importlib.import_module(module)
    missing = [s for s in symbols if not hasattr(mod, s)]
    assert not missing, f"{module} is missing documented symbols: {missing}"


def test_collect_pods_method():
    """PARITY.md maps the reference's collect_candidates assoc-tree
    flattening (candidates.hpp:78-84) to Candidate.collect_pods."""
    from peasoup_tpu.core.candidates import Candidate

    assert callable(getattr(Candidate, "collect_pods"))
