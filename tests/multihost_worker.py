"""Worker process for the real 2-process multi-host test.

Launched by tests/test_multihost.py with JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID in the env: initialises
jax.distributed over CPU (4 virtual devices per process), runs the
multi-host search driver (parallel/multihost.py:run_search) on the
given filterbank, and dumps the finalized candidate list so the parent
can compare it bitwise against a single-process run.

Usage: python multihost_worker.py <fil_path> <out_pickle> <cfg_json>
(cfg_json = JSON dict of SearchConfig fields — single source of truth
lives in the launching test)
"""

import json
import os
import pickle
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "peasoup_tpu", "jax-tests",
    )
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    fil_path, out_path = sys.argv[1], sys.argv[2]
    cfg_fields = json.loads(sys.argv[3]) if len(sys.argv) > 3 else {}

    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel import multihost
    from peasoup_tpu.pipeline import SearchConfig

    fil = read_filterbank(fil_path)
    cfg = SearchConfig(**cfg_fields)
    res = multihost.run_search(fil, cfg)
    rows = [
        (c.freq, c.snr, c.dm, c.acc, c.nh, c.folded_snr, c.opt_period)
        for c in res.candidates
    ]
    with open(out_path, "wb") as f:
        pickle.dump(
            {
                "rank": jax.process_index(),
                "nproc": jax.process_count(),
                "rows": rows,
                "n_accel_trials": res.n_accel_trials,
            },
            f,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
