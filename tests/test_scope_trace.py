"""Unit tests for the per-scope trace attribution tool
(tools/scope_trace.py) — the source of NOTES.md's device-time numbers
and bench.py's official value anchor."""

import gzip
import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu.tools.scope_trace import (
    ScopeResult,
    parse_trace_events,
    result_from_trace_file,
    scope_trace,
)


def _synthetic_trace() -> dict:
    """A minimal profiler trace document: one TPU device track, one
    host track (must be ignored), X events with/without hlo_category."""
    return {
        "traceEvents": [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 9, "name": "process_name",
             "args": {"name": "python host"}},
            # device op with scope + bytes
            {"ph": "X", "pid": 7, "dur": 1500.0,
             "args": {"hlo_category": "convolution",
                      "tf_op": "jit(search_dm_block)/Harmonic summing/conv",
                      "raw_bytes_accessed": 2 * 10**9}},
            # device op without bytes (field absent -> 0)
            {"ph": "X", "pid": 7, "dur": 500.0,
             "args": {"hlo_category": "fusion"}},
            # host-track op: same shape, wrong pid -> excluded
            {"ph": "X", "pid": 9, "dur": 9999.0,
             "args": {"hlo_category": "fusion", "tf_op": "host/op"}},
            # device-track metadata event (not ph=X) -> excluded
            {"ph": "C", "pid": 7, "dur": 123.0,
             "args": {"hlo_category": "copy"}},
        ]
    }


def test_parse_trace_events_filters_device_tracks():
    rows = parse_trace_events(_synthetic_trace())
    assert rows == [
        ("jit(search_dm_block)/Harmonic summing/conv", 1500.0, 2 * 10**9),
        ("", 500.0, 0),
    ]


def test_result_from_trace_file_round_trip(tmp_path):
    """The scope_trace parser runs against a trace.json.gz on disk —
    no TPU needed, which is exactly how the telemetry subsystem's
    --capture-device-trace output gets unit-tested."""
    path = tmp_path / "t.trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump(_synthetic_trace(), f)
    res = result_from_trace_file(str(path))
    assert res.device_s == pytest.approx(2e-3)
    rows = dict((k, (s, gb)) for k, s, gb in res.table(depth=2))
    assert rows["jit(search_dm_block)/Harmonic summing"][0] == pytest.approx(1.5e-3)
    assert rows["jit(search_dm_block)/Harmonic summing"][1] == pytest.approx(2.0)
    assert rows["<unscoped>"][0] == pytest.approx(5e-4)
    ph = res.phase_seconds()
    assert ph["search"] == pytest.approx(1.5e-3)
    assert ph["other"] == pytest.approx(5e-4)


def test_table_aggregates_by_scope_prefix():
    r = ScopeResult()
    r.events = [
        ("jit(f)/Stage-A/mul", 1000.0, 10**9),
        ("jit(f)/Stage-A/add", 2000.0, 2 * 10**9),
        ("jit(f)/Stage-B/dot", 3000.0, 0),
        ("", 500.0, 5 * 10**8),
    ]
    assert r.device_s == pytest.approx(6.5e-3)
    rows = dict((k, (s, gb)) for k, s, gb in r.table(depth=2))
    assert rows["jit(f)/Stage-A"][0] == pytest.approx(3e-3)
    assert rows["jit(f)/Stage-A"][1] == pytest.approx(3.0)
    assert rows["jit(f)/Stage-B"][0] == pytest.approx(3e-3)
    assert rows["<unscoped>"][1] == pytest.approx(0.5)
    # depth 1 merges the stages
    rows1 = dict((k, s) for k, s, _ in r.table(depth=1))
    assert rows1["jit(f)"] == pytest.approx(6e-3)


def test_scope_trace_without_tpu_yields_empty_not_error():
    """On CPU backends the trace has no TPU process tracks: the result
    must be an empty (0.0 s) ScopeResult, never an exception — bench.py
    keys its min-wall fallback off exactly this."""
    with scope_trace() as res:
        np.asarray(jax.numpy.arange(8) * 2).sum()
    # conftest pins the suite to the CPU backend: the TPU-pid filter
    # must therefore match NOTHING — a regression here would anchor
    # bench.py's official value on bogus CPU durations
    assert res.events == []
    assert res.device_s == 0.0


def test_bench_device_busy_helper_returns_float():
    import bench

    v = bench._device_busy_seconds(lambda: None)
    assert isinstance(v, float) and v >= 0.0


def test_bench_median_is_a_true_median():
    """Even-count sample sets (a failed trace shrinks odd to even) must
    average the middle pair, not report the upper element as 'median'."""
    import bench

    assert bench._median([]) == 0.0
    assert bench._median([3.0]) == 3.0
    assert bench._median([5.0, 1.0, 3.0]) == 3.0
    assert bench._median([4.0, 1.0]) == pytest.approx(2.5)
    assert bench._median([1.0, 9.0, 2.0, 4.0]) == pytest.approx(3.0)


def test_phase_seconds_classifies_pipeline_jits():
    """bench.py --survey's device anchor: the per-phase split must
    route each pipeline jit to its phase and keep the rest visible in
    'other' (mis-attribution may never hide)."""
    r = ScopeResult()
    r.events = [
        ("jit(search_dm_block)/Harmonic summing", 1e6, 0),
        ("jit(compact_peaks_device)/jit(_take)", 2e6, 0),
        ("jit(resample_select_packed_planes)/select_n", 1e6, 0),
        ("jit(run)/pallas_call:", 3e6, 0),       # dedispersion wrapper
        ("jit(unpack_fil_device)/and:", 1e6, 0),
        ("jit(dedisperse_block)/while", 1e6, 0),
        ("jit(_deredden_tim)/fft", 2e6, 0),
        ("jit(fold_bins)/scatter", 1e6, 0),
        ("jit(mystery_op)/mul", 5e5, 0),
    ]
    ph = r.phase_seconds()
    assert ph["search"] == pytest.approx(4.0)
    assert ph["dedisp"] == pytest.approx(5.0)
    assert ph["fold"] == pytest.approx(3.0)
    assert ph["other"] == pytest.approx(0.5)
    assert sum(ph.values()) == pytest.approx(r.device_s)
