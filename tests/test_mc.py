"""Audit engine 5: protocol model checking.

Covers, bottom-up:

* the virtual filesystem's load-bearing op semantics (O_EXCL
  exclusivity, buffer-until-close torn files, rename atomicity and
  POSIX ctime/mtime, exactly-once ``os.link``, fsync-vs-host-crash
  durability),
* scheduler determinism (one schedule -> one bit-identical trace),
* crash injection (a SIGKILLed task's cleanup cannot mutate shared
  state; crash points enumerate the killable op surface),
* the explorer on a seeded lost-update race: found with POR on and
  off (the soundness spot-check), minimized, replayed, deduped,
* the scenario library: green end-to-end against the real protocol
  modules, and — with the queue's *old* non-atomic ``complete``
  monkeypatched back in — a deliberately re-seeded exactly-once race
  caught as a PSM finding whose embedded schedule replays
  bit-identically.
"""

from __future__ import annotations

import json

import pytest

from peasoup_tpu.analysis.mc.crash import enumerate_crash_points
from peasoup_tpu.analysis.mc.explorer import (
    Scenario,
    explore,
    minimize,
    replay,
    run_schedule,
    schedule_to_str,
    str_to_schedule,
)
from peasoup_tpu.analysis.mc.invariants import MCContext, require
from peasoup_tpu.analysis.mc.scenarios import (
    run_mc,
    scenario_names,
    scenarios,
)
from peasoup_tpu.analysis.mc.vfs import MCEnv, OpDesc, conflicts
from peasoup_tpu.campaign import queue as qmod

# ---------------------------------------------------------------------------
# virtual filesystem semantics
# ---------------------------------------------------------------------------


class TestVfsSemantics:
    def test_o_excl_create_admits_exactly_one(self):
        env = MCEnv()
        flags = env.os.O_CREAT | env.os.O_EXCL | env.os.O_WRONLY
        env.os.open("/camp/queue/claims/j1.json", flags)
        with pytest.raises(FileExistsError):
            env.os.open("/camp/queue/claims/j1.json", flags)

    def test_write_buffers_until_close_publishes(self):
        env = MCEnv()
        f = env.open("/camp/doc.json", "w")
        f.write('{"k": 1}')
        # the torn-file window: created (truncated), nothing published
        assert env.fs.read("/camp/doc.json") == ""
        f.close()
        assert json.loads(env.fs.read("/camp/doc.json")) == {"k": 1}

    def test_abandoned_fd_is_a_torn_file(self):
        # os.close without fdopen().close() publishes nothing — the
        # SIGKILL-mid-write model every crash scenario leans on
        env = MCEnv()
        fd, tmp = env.tempfile.mkstemp(dir="/camp", suffix=".tmp")
        f = env.os.fdopen(fd, "w")
        f.write("data")
        env.os.close(fd)
        assert env.fs.read(tmp) == ""

    def test_rename_is_atomic_and_bumps_ctime_not_mtime(self):
        env = MCEnv()
        vf = env.fs.create("/camp/a.json", env.clock, excl=True)
        env.fs.publish(vf, "one", env.clock)
        t0 = env.clock
        env.clock += 50.0
        env.os.replace("/camp/a.json", "/camp/b.json")
        assert not env.fs.exists("/camp/a.json")
        assert env.fs.read("/camp/b.json") == "one"
        st = env.fs.stat("/camp/b.json")
        assert st.st_ctime == t0 + 50.0  # rename bumps ctime...
        assert st.st_mtime == t0  # ...but never mtime

    def test_link_is_exactly_once(self):
        env = MCEnv()
        vf = env.fs.create("/camp/tmp0", env.clock, excl=True)
        env.fs.publish(vf, "rec", env.clock)
        env.os.link("/camp/tmp0", "/camp/done.json")
        with pytest.raises(FileExistsError):
            env.os.link("/camp/tmp0", "/camp/done.json")
        assert env.fs.read("/camp/done.json") == "rec"

    def test_host_crash_drops_unsynced_keeps_synced(self):
        env = MCEnv()
        fd1, t1 = env.tempfile.mkstemp(dir="/camp", suffix=".tmp")
        f1 = env.os.fdopen(fd1, "w")
        f1.write("gone")
        f1.close()  # published but never fsynced
        fd2, t2 = env.tempfile.mkstemp(dir="/camp", suffix=".tmp")
        f2 = env.os.fdopen(fd2, "w")
        f2.write("kept")
        f2.flush()
        env.os.fsync(fd2)
        f2.close()
        env.fs.host_crash()
        assert not env.fs.exists(t1)
        assert env.fs.read(t2) == "kept"

    def test_fd_binds_inode_across_rename(self):
        # a write in flight lands in the inode wherever its NAME went —
        # exactly the hazard reap_stale's torn-tombstone putback covers
        env = MCEnv()
        flags = env.os.O_CREAT | env.os.O_EXCL | env.os.O_WRONLY
        fd = env.os.open("/camp/claim.json", flags)
        f = env.os.fdopen(fd, "w")
        f.write('{"worker_id": "w1"}')
        env.os.rename("/camp/claim.json", "/camp/claim.json.reap.0")
        f.close()
        doc = json.loads(env.fs.read("/camp/claim.json.reap.0"))
        assert doc == {"worker_id": "w1"}

    def test_conflicts_are_symmetric_on_shared_paths(self):
        r = OpDesc("read", "/a", reads=frozenset({"/a"}))
        w = OpDesc("rename", "/a", writes=frozenset({"/a", "/b"}))
        other = OpDesc("read", "/c", reads=frozenset({"/c"}))
        assert conflicts(r, w) and conflicts(w, r)
        assert not conflicts(r, other)


# ---------------------------------------------------------------------------
# a seeded lost-update race (read-modify-write without exclusion)
# ---------------------------------------------------------------------------

_COUNTER = "/camp/queue/counter.json"


def _counter_scenario() -> Scenario:
    def setup(ctx: MCContext) -> None:
        env = ctx.env
        vf = env.fs.create(_COUNTER, env.clock, excl=True)
        env.fs.publish(vf, json.dumps({"n": 0}), env.clock)

    def bump(name: str):
        def body(ctx: MCContext) -> None:
            env = ctx.env
            doc = json.loads(env.open(_COUNTER).read())
            tmp = f"{_COUNTER}.tmp.{name}"
            f = env.open(tmp, "w")
            f.write(json.dumps({"n": doc["n"] + 1}))
            f.close()
            env.os.replace(tmp, _COUNTER)

        return body

    def invariant(ctx: MCContext) -> None:
        n = (ctx.read_json(_COUNTER) or {}).get("n")
        require(n == 2, f"lost update: n={n} after two increments")

    return Scenario(
        name="seeded_lost_update",
        rule="PSM301",
        module="tests/test_mc.py",
        description="unsynchronized read-modify-write of one doc",
        setup=setup,
        tasks=(("w1", bump("w1"), False), ("w2", bump("w2"), False)),
        invariant=invariant,
        max_kills=0,
    )


class TestExplorer:
    def test_seeded_race_found_with_and_without_por(self):
        # the POR soundness spot-check: pruning must not lose the
        # interleaving where both workers read the same snapshot
        s = _counter_scenario()
        full = explore(s, budget=200, por=False, stop_on_first=False)
        por = explore(s, budget=200, por=True, stop_on_first=False)
        assert full.violations, "seeded race not found without POR"
        assert {m for m, _ in full.violations} == {
            m for m, _ in por.violations
        }
        assert por.schedules <= full.schedules

    def test_violating_schedule_replays_deterministically(self):
        s = _counter_scenario()
        res = explore(s, budget=200, stop_on_first=True)
        msg, chosen = res.violations[0]
        r1 = run_schedule(s, chosen)
        r2 = run_schedule(s, chosen)
        assert r1.violation == r2.violation == msg
        assert r1.trace == r2.trace  # bit-identical replay

    def test_minimize_yields_shortest_reproducing_prefix(self):
        s = _counter_scenario()
        res = explore(s, budget=200, stop_on_first=True)
        msg, chosen = res.violations[0]
        mini = minimize(s, chosen, msg)
        assert len(mini) <= len(chosen)
        assert run_schedule(s, mini).violation == msg
        if mini:  # any shorter prefix must NOT reproduce
            assert run_schedule(s, mini[:-1]).violation != msg

    def test_schedule_string_round_trip(self):
        assert str_to_schedule("-") == ()
        assert schedule_to_str(()) == "-"
        sched = ("1", "K0", "0")
        assert str_to_schedule(schedule_to_str(sched)) == sched

    def test_default_schedule_is_sequential_and_green(self):
        run = run_schedule(_counter_scenario())
        assert run.violation is None
        assert run.tasks == {"w1": "done", "w2": "done"}


# ---------------------------------------------------------------------------
# crash injection
# ---------------------------------------------------------------------------

_ARTIFACT = "/camp/queue/a.json"


def _kill_cleanup_scenario() -> Scenario:
    def setup(ctx: MCContext) -> None:
        env = ctx.env
        vf = env.fs.create(_ARTIFACT, env.clock, excl=True)
        env.fs.publish(vf, "{}", env.clock)

    def w(ctx: MCContext) -> None:
        env = ctx.env
        try:
            env.open(_ARTIFACT).read()
        finally:
            # a real worker's except/finally cleanup: under SIGKILL
            # this must never run
            env.os.unlink(_ARTIFACT)

    def invariant(ctx: MCContext) -> None:
        killed = any(":KILLED:" in e for e in ctx.env.trace)
        if killed:
            require(
                ctx.exists(_ARTIFACT),
                "a killed task's cleanup mutated shared state",
            )
        else:
            require(not ctx.exists(_ARTIFACT), "cleanup did not run")

    return Scenario(
        name="kill_cleanup",
        rule="PSM302",
        module="tests/test_mc.py",
        description="SIGKILL model: cleanup handlers cannot run",
        setup=setup,
        tasks=(("w", w, True),),
        invariant=invariant,
        max_kills=1,
    )


class TestCrashInjection:
    def test_killed_cleanup_cannot_mutate(self):
        s = _kill_cleanup_scenario()
        run = run_schedule(s, ("K0",))  # kill parked at the first op
        assert run.violation is None
        assert run.tasks["w"] == "killed"
        assert any(e.startswith("w:KILLED:") for e in run.trace)
        # the finally-block unlink never executed
        assert not any(e.startswith("w:unlink") for e in run.trace)

    def test_crash_points_enumerate_the_killable_op_surface(self):
        s = _kill_cleanup_scenario()
        # crash-free run: read + cleanup unlink = two killable ops
        assert enumerate_crash_points(s) == 2

    def test_exploration_covers_every_crash_point_green(self):
        res = explore(
            _kill_cleanup_scenario(), budget=100, stop_on_first=False
        )
        assert res.exhausted
        assert not res.violations

    def test_unkillable_scenarios_have_no_crash_points(self):
        assert enumerate_crash_points(_counter_scenario()) == 0


# ---------------------------------------------------------------------------
# the scenario library against the real protocol modules
# ---------------------------------------------------------------------------


class TestScenarioLibrary:
    def test_library_covers_the_protocol_surface(self):
        names = scenario_names()
        assert len(names) >= 10
        blob = "|".join(names)
        for protocol in (
            "claim", "reap", "preempt", "gang", "registry", "tenant",
            "alerts",
        ):
            assert protocol in blob, f"no scenario covers {protocol}"

    def test_full_library_is_green(self):
        rep = run_mc(budget=60)
        assert rep.violations == 0, [
            f.message for f in rep.findings
        ]
        assert not rep.findings
        assert rep.scenarios == len(scenario_names())
        assert rep.schedules > 0
        assert rep.crash_points > 0  # kills were actually injected

    def test_subset_selection_and_unknown_name(self):
        rep = run_mc(names=["claim_race"], budget=30)
        assert rep.scenarios == 1
        assert rep.per_scenario[0]["name"] == "claim_race"
        with pytest.raises(ValueError, match="unknown mc scenario"):
            run_mc(names=["no_such_scenario"])


# ---------------------------------------------------------------------------
# the acceptance drill: re-seed the queue's pre-dance complete() and
# catch the exactly-once violation with a replayable schedule
# ---------------------------------------------------------------------------


def _old_complete(self, claim, **info):
    """The pre-tombstone-dance implementation: publish the done record
    unconditionally (tmp + os.replace, so the second publication
    silently overwrites the first) and blindly unlink the claim."""
    done = self._p(qmod._DONE, claim.job.job_id)
    qmod._atomic_write_json(
        done,
        {
            "job_id": claim.job.job_id,
            "worker_id": claim.worker_id,
            **info,
        },
    )
    try:
        qmod.os.unlink(claim.path)
    except FileNotFoundError:
        pass
    self.clear_preempt(claim.job.job_id)
    return True


class TestSeededQueueRace:
    @pytest.fixture()
    def doctored_queue(self, monkeypatch):
        monkeypatch.setattr(qmod.JobQueue, "complete", _old_complete)

    def _scenario(self):
        return {s.name: s for s in scenarios()}["zombie_complete"]

    def test_seeded_race_is_caught_and_replays_bit_identically(
        self, doctored_queue
    ):
        rep = run_mc(names=["zombie_complete"], budget=400)
        assert rep.violations >= 1
        f = rep.findings[0]
        assert f.rule == "PSM301"
        assert f.severity == "error"
        assert f.path == "peasoup_tpu/campaign/queue.py"
        assert "schedule=" in f.source_line
        # replay straight from the finding, twice: bit-identical
        sched = f.source_line.split("schedule=", 1)[1].strip()
        s = self._scenario()
        r1 = replay(s, sched)
        r2 = replay(s, sched)
        assert r1.violation is not None
        assert r1.violation in f.message
        assert r1.trace == r2.trace
        assert r1.violation == r2.violation

    def test_fixed_queue_passes_the_same_scenario(self):
        rep = run_mc(names=["zombie_complete"], budget=400)
        assert rep.violations == 0
