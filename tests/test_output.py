"""Output writer + post-processing parser round-trip tests."""

import struct

import numpy as np
import pytest

from peasoup_tpu.core import Candidate, CANDIDATE_POD_DTYPE
from peasoup_tpu.io.output import CandidateFileWriter, OutputFileWriter
from peasoup_tpu.io.sigproc import SigprocHeader
from peasoup_tpu.io.xml_writer import Element, fmt
from peasoup_tpu.pipeline import SearchConfig
from peasoup_tpu.tools import OverviewFile, CandidateFileParser


def make_cands():
    c0 = Candidate(dm=19.76, dm_idx=6, acc=0.0, nh=4, snr=86.9, freq=4.000962)
    c0.assoc.append(Candidate(dm=23.0, dm_idx=7, acc=0.0, nh=3, snr=73.9, freq=3.999))
    c0.fold = np.arange(64 * 16, dtype=np.float32).reshape(16, 64)
    c0.opt_period = 0.249986
    c1 = Candidate(dm=9.9, dm_idx=3, acc=-5.0, nh=4, snr=52.6, freq=2.0012)
    return [c0, c1]


class TestXmlWriter:
    def test_fmt_matches_cpp_setprecision15(self):
        # float32(1.1) printed as double with 15 significant digits
        assert fmt(float(np.float32(1.1))) == "1.10000002384186"
        assert fmt(float(np.float32(0.05))) == "0.0500000007450581"
        assert fmt(True) == "1"
        assert fmt(0) == "0"
        assert fmt(3.3133590221405) == "3.3133590221405"

    def test_structure(self):
        root = Element("peasoup_search")
        trials = root.append(Element("dedispersion_trials"))
        trials.add_attribute("count", 2)
        for i, v in enumerate([0.0, 3.3133590221405]):
            t = Element("trial", v)
            t.add_attribute("id", i)
            trials.append(t)
        s = root.to_string(header=True)
        assert s.startswith("<?xml version='1.0' encoding='ISO-8859-1'?>\n")
        assert "<dedispersion_trials count='2'>" in s
        assert "<trial id='1'>3.3133590221405</trial>" in s


class TestBinaryWriter:
    def test_roundtrip(self, tmp_path):
        cands = make_cands()
        w = CandidateFileWriter(str(tmp_path))
        path = w.write_binary(cands)
        assert w.byte_mapping[0] == 0
        with open(path, "rb") as f:
            assert f.read(4) == b"FOLD"
            nbins, nints = struct.unpack("<ii", f.read(8))
            assert (nbins, nints) == (64, 16)
        with CandidateFileParser(path) as p:
            rec0 = p.read_candidate(w.byte_mapping[0])
            assert rec0["fold"].shape == (16, 64)
            np.testing.assert_allclose(rec0["fold"], cands[0].fold)
            assert len(rec0["hits"]) == 2  # self + 1 assoc
            assert rec0["hits"][0]["snr"] == pytest.approx(86.9)
            assert rec0["hits"][1]["dm"] == pytest.approx(23.0)
            rec1 = p.read_candidate(w.byte_mapping[1])
            assert rec1["fold"] is None
            assert len(rec1["hits"]) == 1
            assert rec1["hits"][0]["acc"] == pytest.approx(-5.0)

    def test_pod_layout_is_24_bytes(self):
        assert CANDIDATE_POD_DTYPE.itemsize == 24

    def test_write_binaries_per_cand(self, tmp_path):
        w = CandidateFileWriter(str(tmp_path))
        names = w.write_binaries(make_cands())
        assert len(names) == 2
        assert "cand_0000" in names[0]


class TestOverviewRoundtrip:
    def test_full_overview(self, tmp_path):
        cands = make_cands()
        w = CandidateFileWriter(str(tmp_path))
        w.write_binary(cands)
        hdr = SigprocHeader(
            source_name="FAKE", tsamp=0.00032, fch1=1510.0, foff=-1.09,
            nchans=64, nbits=2, nsamples=187520,
        )
        cfg = SearchConfig(dm_end=250.0, acc_start=-5.0, acc_end=5.0, npdmp=10)
        out = OutputFileWriter()
        out.add_misc_info()
        out.add_header(hdr)
        out.add_search_parameters(cfg, "tutorial.fil")
        out.add_dm_list([0.0, 3.3133590221405])
        out.add_acc_list([0.0, -5.0, 5.0])
        out.add_device_info()
        out.add_candidates(cands, w.byte_mapping)
        out.add_timing_info({"total": 1.5, "searching": 1.0})
        path = tmp_path / "overview.xml"
        out.to_file(str(path))

        ov = OverviewFile(str(path))
        assert ov.header["nchans"] == "64"
        assert ov.search_parameters["dm_tol"] == "1.10000002384186"
        np.testing.assert_allclose(ov.dm_list, [0.0, 3.3133590221405])
        np.testing.assert_allclose(ov.acc_list, [0.0, -5.0, 5.0])
        assert len(ov.candidates) == 2
        assert ov.candidates[0]["snr"] == pytest.approx(86.9, rel=1e-5)
        assert ov.candidates[0]["nassoc"] == 1
        assert ov.execution_times["total"] == 1.5
        assert "PERIOD" in ov.make_predictor(0)

    def test_parses_golden_overview(self, golden_xml, tmp_path):
        path = tmp_path / "golden.xml"
        path.write_text(golden_xml)
        ov = OverviewFile(str(path))
        assert len(ov.dm_list) == 59
        assert len(ov.candidates) == 10
        assert ov.candidates[0]["snr"] == pytest.approx(86.9626, rel=1e-5)
        assert ov.candidates[0]["period"] == pytest.approx(0.2499399, rel=1e-6)
