"""Fleet observability tests (ISSUE 14): the time-series metrics
recorder + Prometheus exposition, cross-process trace correlation +
Chrome/Perfetto export, on-demand device profiling, the rollup
throughput-decay fix, mixed-schema watch/report tolerance, and the
DM-time bowtie diagnostic."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from peasoup_tpu.obs import metrics as obs_metrics
from peasoup_tpu.obs import trace as obs_trace
from peasoup_tpu.obs.metrics import (
    MetricsRecorder,
    fleet_samples,
    load_series,
    parse_exposition,
    prometheus_exposition,
    serve_metrics,
    validate_sample,
)
from peasoup_tpu.obs.schema import SchemaError
from peasoup_tpu.obs.trace import (
    Tracer,
    export_chrome_trace,
    job_span,
    load_spans,
    new_trace_id,
    trace_paths,
    trace_summary,
)


# --------------------------------------------------------------------------
# metrics recorder
# --------------------------------------------------------------------------

class TestMetricsRecorder:
    def test_counter_is_cumulative(self, tmp_path):
        p = str(tmp_path / "w.metrics.jsonl")
        r = MetricsRecorder(p)
        r.counter("jobs_done_total")
        r.counter("jobs_done_total", 2)
        vals = [s["value"] for s in load_series(p)]
        assert vals == [1, 3]

    def test_counter_series_independent_per_label_set(self, tmp_path):
        p = str(tmp_path / "w.metrics.jsonl")
        r = MetricsRecorder(p)
        r.counter("preemptions_total", event="released")
        r.counter("preemptions_total", event="retire")
        r.counter("preemptions_total", event="released")
        series = [
            (s["labels"]["event"], s["value"]) for s in load_series(p)
        ]
        assert series == [("released", 1), ("retire", 1), ("released", 2)]

    def test_every_line_schema_valid(self, tmp_path):
        p = str(tmp_path / "w.metrics.jsonl")
        r = MetricsRecorder(p)
        r.counter("a_total")
        r.gauge("queue_depth", 4, state="pending")
        r.observe("lat_seconds", 0.25)
        samples = load_series(p, validate=True)  # raises on drift
        assert [s["kind"] for s in samples] == ["counter", "gauge", "hist"]

    def test_schema_rejects_bad_sample(self):
        with pytest.raises(SchemaError):
            validate_sample({"t": 1.0, "name": "x", "kind": "nope",
                             "value": 1.0})
        with pytest.raises(SchemaError):
            validate_sample({"t": 1.0, "name": "x", "kind": "gauge"})
        with pytest.raises(SchemaError):
            validate_sample(
                {"t": 1.0, "name": "x", "kind": "gauge", "value": 1.0,
                 "labels": {"a": 3}}  # label values must be strings
            )

    def test_rotation_bounds_file_and_keeps_counters_monotone(
        self, tmp_path
    ):
        p = str(tmp_path / "w.metrics.jsonl")
        r = MetricsRecorder(p, max_bytes=2000, keep_bytes=800)
        for _ in range(200):
            r.counter("spam_total")
        assert os.path.getsize(p) <= 2100  # bounded (one line slack)
        vals = [s["value"] for s in load_series(p, validate=True)]
        # the newest tail survived and the cumulative total kept
        # counting across the rotation (carried in recorder memory)
        assert vals == sorted(vals)
        assert vals[-1] == 200
        assert len(vals) < 200

    def test_disabled_recorder_writes_nothing(self, tmp_path):
        p = str(tmp_path / "w.metrics.jsonl")
        r = MetricsRecorder(p, enabled=False)
        r.counter("a_total")
        r.gauge("g", 1)
        r.observe("h", 1)
        assert not os.path.exists(p)

    def test_torn_tail_skipped(self, tmp_path):
        p = str(tmp_path / "w.metrics.jsonl")
        r = MetricsRecorder(p)
        r.gauge("g", 1)
        with open(p, "a") as f:
            f.write('{"t": 5, "name": "g", "ki')  # writer died mid-line
        assert len(load_series(p)) == 1


# --------------------------------------------------------------------------
# exposition + aggregation
# --------------------------------------------------------------------------

class TestExposition:
    def _samples(self, tmp_path):
        p = str(tmp_path / "w1.metrics.jsonl")
        r = MetricsRecorder(p)
        r.counter("jobs_done_total")
        r.gauge("queue_depth", 3, state="pending")
        for v in (0.1, 0.4, 2.0):
            r.observe("preemption_latency_seconds", v)
        return {"w1": load_series(p)}

    def test_exposition_renders_and_parses(self, tmp_path):
        text = prometheus_exposition(self._samples(tmp_path))
        parsed = parse_exposition(text)
        by_name = {}
        for name, labels, value in parsed:
            by_name.setdefault(name, []).append((labels, value))
        assert by_name["peasoup_jobs_done_total"][0][1] == 1
        [(labels, depth)] = by_name["peasoup_queue_depth"]
        assert labels == {"state": "pending", "worker": "w1"}
        assert depth == 3
        # histogram triplet: cumulative buckets + sum + count
        assert by_name["peasoup_preemption_latency_seconds_count"][0][1] == 3
        assert by_name["peasoup_preemption_latency_seconds_sum"][0][1] == (
            pytest.approx(2.5)
        )
        buckets = {
            labels["le"]: v
            for labels, v in by_name[
                "peasoup_preemption_latency_seconds_bucket"
            ]
        }
        assert buckets["+Inf"] == 3
        assert buckets["0.25"] == 1
        # TYPE comments present
        assert "# TYPE peasoup_queue_depth gauge" in text
        assert "# TYPE peasoup_jobs_done_total counter" in text
        assert (
            "# TYPE peasoup_preemption_latency_seconds histogram" in text
        )

    def test_gauge_last_value_wins(self, tmp_path):
        p = str(tmp_path / "w1.metrics.jsonl")
        r = MetricsRecorder(p)
        r.gauge("queue_depth", 5, state="pending")
        r.gauge("queue_depth", 2, state="pending")
        text = prometheus_exposition({"w1": load_series(p)})
        [(_, labels, v)] = [
            t for t in parse_exposition(text)
            if t[0] == "peasoup_queue_depth"
        ]
        assert v == 2

    def test_label_escaping_round_trips(self, tmp_path):
        p = str(tmp_path / "w1.metrics.jsonl")
        r = MetricsRecorder(p)
        r.gauge("g", 1, reason='he said "no", then \\left')
        text = prometheus_exposition({"w1": load_series(p)})
        [(_, labels, _v)] = parse_exposition(
            "\n".join(
                ln for ln in text.splitlines() if not ln.startswith("#")
            )
        )
        assert labels["reason"] == 'he said "no", then \\left'

    def test_parse_rejects_malformed(self):
        with pytest.raises(ValueError):
            parse_exposition("peasoup_x{le=0.5} 1")  # unquoted label
        with pytest.raises(ValueError):
            parse_exposition("not a metric line at all!!! x")

    def test_series_query_orders_and_tags(self, tmp_path):
        samples = {
            "w2": [{"t": 2.0, "name": "queue_depth", "kind": "gauge",
                    "value": 1.0}],
            "w1": [{"t": 1.0, "name": "queue_depth", "kind": "gauge",
                    "value": 4.0}],
        }
        s = obs_metrics.series(samples, "queue_depth", "gauge")
        assert [(r["source"], r["value"]) for r in s] == [
            ("w1", 4.0), ("w2", 1.0),
        ]

    def test_fleet_samples_globs_workers_dir(self, tmp_path):
        root = tmp_path / "camp"
        wdir = root / "queue" / "workers"
        wdir.mkdir(parents=True)
        for w in ("a", "b"):
            MetricsRecorder(str(wdir / f"{w}.metrics.jsonl")).gauge("g", 1)
        assert sorted(fleet_samples(str(root))) == ["a", "b"]

    def test_serve_metrics_http_endpoint(self, tmp_path):
        root = tmp_path / "camp"
        wdir = root / "queue" / "workers"
        wdir.mkdir(parents=True)
        MetricsRecorder(str(wdir / "w.metrics.jsonl")).counter("up_total")
        # port 0 → ephemeral; serve exactly one request on a thread
        srv = threading.Thread(
            target=serve_metrics,
            args=(str(root),),
            kwargs={"port": 0, "max_requests": 1},
            daemon=True,
        )
        # find the port by racing is fragile; instead serve on a fixed
        # ephemeral port chosen by binding a socket first
        import socket as _socket

        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        srv = threading.Thread(
            target=serve_metrics,
            args=(str(root),),
            kwargs={"port": port, "max_requests": 1},
            daemon=True,
        )
        srv.start()
        deadline = time.monotonic() + 5
        body = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2
                ) as resp:
                    body = resp.read().decode()
                break
            except OSError:
                time.sleep(0.05)
        srv.join(timeout=5)
        assert body is not None and "peasoup_up_total" in body
        parse_exposition(body)


# --------------------------------------------------------------------------
# tracer
# --------------------------------------------------------------------------

class TestTracer:
    def test_span_instant_span_at(self, tmp_path):
        p = str(tmp_path / "trace-w1.jsonl")
        tr = Tracer(p, "t" * 16, worker="w1")
        with tr.span("wave", wave=0):
            pass
        tr.instant("checkpoint_saved", wave=0)
        tr.span_at("claim_wait", 100.0, 0.5)
        tr.close()
        spans = load_spans(p)
        names = [s["name"] for s in spans]
        assert sorted(names) == ["checkpoint_saved", "claim_wait", "wave"]
        summ = trace_summary(spans)
        assert summ["connected"] and summ["unclosed"] == 0
        assert summ["workers"] == ["w1"]
        by = {s["name"]: s for s in spans}
        assert by["claim_wait"]["ts_unix"] == 100.0
        assert by["claim_wait"]["dur_s"] == 0.5
        assert by["checkpoint_saved"]["instant"] is True

    def test_close_force_ends_open_spans(self, tmp_path):
        p = str(tmp_path / "trace-w1.jsonl")
        tr = Tracer(p, "t" * 16, worker="w1")
        tr.begin("job_attempt")
        tr.close()
        [span] = load_spans(p)
        assert span["forced_end"] is True
        assert isinstance(span["dur_s"], float)
        assert trace_summary([span])["unclosed"] == 0

    def test_disabled_tracer_writes_nothing(self, tmp_path):
        p = str(tmp_path / "trace-w1.jsonl")
        tr = Tracer(p, "t" * 16, enabled=False)
        with tr.span("x"):
            pass
        tr.instant("y")
        tr.close()
        assert not os.path.exists(p)

    def test_job_span_noop_without_ambient_tracer(self):
        with job_span("wave", wave=0):  # must not raise or write
            pass

    def test_job_span_uses_ambient_tracer(self, tmp_path):
        p = str(tmp_path / "trace-w1.jsonl")
        tr = Tracer(p, "t" * 16, worker="w1")
        with tr.activate():
            with job_span("wave", wave=3):
                pass
        tr.close()
        [span] = load_spans(p)
        assert span["name"] == "wave" and span["args"]["wave"] == 3

    def test_telemetry_bridge_stages_and_instants(self, tmp_path):
        from peasoup_tpu.obs.telemetry import RunTelemetry

        p = str(tmp_path / "trace-w1.jsonl")
        tel = RunTelemetry()
        tr = Tracer(p, "t" * 16, worker="w1")
        tr.attach(tel)
        tel.set_stage("reading")
        tel.set_stage("searching")  # closes reading, opens searching
        tel.event("dedisp_plan", engine="exact")
        tr.close()
        spans = load_spans(p)
        names = {s["name"] for s in spans}
        assert {"stage:reading", "stage:searching", "dedisp_plan"} <= names
        reading = next(s for s in spans if s["name"] == "stage:reading")
        assert "forced_end" not in reading  # closed by the transition
        plan = next(s for s in spans if s["name"] == "dedisp_plan")
        assert plan["instant"] is True and plan["args"]["engine"] == "exact"
        # detach on close: later events must not write
        n = len(spans)
        tel.event("late")
        assert len(load_spans(p)) == n

    def test_two_workers_one_connected_trace(self, tmp_path):
        tid = new_trace_id()
        job_dir = tmp_path / "jobs" / "j1"
        for w in ("w1", "w2"):
            tr = Tracer(
                str(job_dir / f"trace-{w}.jsonl"), tid, worker=w
            )
            with tr.span("job_attempt"):
                pass
            tr.close()
        spans = load_spans(trace_paths(str(job_dir)))
        summ = trace_summary(spans)
        assert summ["connected"] is True
        assert summ["workers"] == ["w1", "w2"]
        assert summ["trace_ids"] == [tid]

    def test_different_trace_ids_not_connected(self, tmp_path):
        job_dir = tmp_path / "j"
        for w, tid in (("w1", "a" * 16), ("w2", "b" * 16)):
            tr = Tracer(str(job_dir / f"trace-{w}.jsonl"), tid, worker=w)
            tr.instant("x")
            tr.close()
        assert trace_summary(
            load_spans(trace_paths(str(job_dir)))
        )["connected"] is False

    def test_chrome_export(self, tmp_path):
        p = str(tmp_path / "trace-w1.jsonl")
        tr = Tracer(p, "t" * 16, worker="w1")
        with tr.span("wave"):
            pass
        tr.instant("mark")
        tr.close()
        doc = export_chrome_trace(
            load_spans(p),
            extra_instants=[
                {"name": "autoscale:up", "ts_unix": time.time()}
            ],
        )
        evs = doc["traceEvents"]
        phs = [e["ph"] for e in evs]
        assert "M" in phs and "X" in phs and "i" in phs
        meta = [e for e in evs if e["ph"] == "M"]
        assert {m["args"]["name"] for m in meta} == {"w1", "campaign"}
        x = next(e for e in evs if e["ph"] == "X")
        assert x["args"]["trace_id"] == "t" * 16
        assert x["ts"] >= 0 and x["dur"] >= 0
        # json-serialisable end to end
        json.dumps(doc)

    def test_load_spans_skips_torn_tail(self, tmp_path):
        p = str(tmp_path / "trace-w1.jsonl")
        tr = Tracer(p, "t" * 16, worker="w1")
        tr.instant("ok")
        tr.close()
        with open(p, "a") as f:
            f.write('{"trace_id": "t", "name": "torn"')
        assert [s["name"] for s in load_spans(p)] == ["ok"]


# --------------------------------------------------------------------------
# on-demand profiling
# --------------------------------------------------------------------------

class TestProfiler:
    def test_cpu_guarded_noop(self, tmp_path):
        from peasoup_tpu.obs.profiler import capture_device_profile

        out = capture_device_profile(str(tmp_path / "prof"), 0.2)
        assert out["captured"] is False
        assert "cpu" in (out["skipped"] or "")
        assert not os.path.exists(str(tmp_path / "prof"))

    def test_allow_cpu_really_captures(self, tmp_path):
        from peasoup_tpu.obs.profiler import capture_device_profile

        out = capture_device_profile(
            str(tmp_path / "prof"), 0.2, allow_cpu=True
        )
        assert out["captured"] is True
        assert os.path.isdir(out["outdir"])
        assert out["seconds"] >= 0.2

    def test_duration_is_bounded(self, tmp_path):
        from peasoup_tpu.obs import profiler

        t0 = time.perf_counter()
        out = profiler.capture_device_profile(
            str(tmp_path / "p"), duration_s=10_000.0
        )
        # CPU no-op returns immediately, but the requested duration
        # must already be clamped to the ceiling
        assert out["requested_s"] == profiler.MAX_CAPTURE_S
        assert time.perf_counter() - t0 < profiler.MAX_CAPTURE_S

    def test_registry_request_round_trip(self, tmp_path):
        from peasoup_tpu.campaign.registry import WorkerRegistry

        reg = WorkerRegistry(str(tmp_path))
        reg.register("w1")
        assert reg.profile_requested("w1") is None
        reg.request_profile("w1", seconds=2.5, requester="op")
        req = reg.profile_requested("w1")
        assert req["seconds"] == 2.5 and req["requester"] == "op"
        reg.clear_profile("w1")
        assert reg.profile_requested("w1") is None

    def test_orphaned_profile_request_reaped(self, tmp_path):
        from peasoup_tpu.campaign.registry import WorkerRegistry

        reg = WorkerRegistry(str(tmp_path))
        reg.register("gone")
        reg.request_profile("gone")
        reg.deregister("gone")
        # deregister answers the pending request
        assert reg.profile_requested("gone") is None
        reg.register("gone2")
        reg.request_profile("gone2")
        os.unlink(reg._path("gone2"))  # simulated SIGKILL + reap
        reg.reap()
        assert reg.profile_requested("gone2") is None

    def test_metrics_file_survives_deregister(self, tmp_path):
        from peasoup_tpu.campaign.registry import WorkerRegistry

        reg = WorkerRegistry(str(tmp_path))
        reg.register("w1")
        MetricsRecorder(reg.metrics_path("w1")).gauge("g", 1)
        reg.deregister("w1")
        reg.reap()
        assert os.path.exists(reg.metrics_path("w1"))


# --------------------------------------------------------------------------
# trace-id propagation through the queue protocol
# --------------------------------------------------------------------------

class TestTracePropagation:
    def test_enqueue_mints_and_claim_carries(self, tmp_path):
        from peasoup_tpu.campaign.queue import Job, JobQueue

        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="a", input="x.fil"))
        job = q.get_job("a")
        assert len(job.trace_id) == 16
        claim = q.try_claim("a", "w1")
        doc = json.load(open(claim.path))
        assert doc["trace_id"] == job.trace_id

    def test_preempt_request_carries_trace_id(self, tmp_path):
        from peasoup_tpu.campaign.queue import Job, JobQueue

        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="a", input="x.fil"))
        q.try_claim("a", "w1")
        assert q.request_preempt("a", requester="t") is True
        req = q.preempt_request("a")
        assert req["trace_id"] == q.get_job("a").trace_id

    def test_doc_round_trip_preserves_trace_id(self):
        from peasoup_tpu.campaign.queue import Job

        job = Job(job_id="a", input="x.fil", trace_id="f" * 16)
        assert Job.from_doc(job.to_doc()).trace_id == "f" * 16
        # older records without the field load as empty (re-minted on
        # a future enqueue, never a KeyError)
        doc = job.to_doc()
        del doc["trace_id"]
        assert Job.from_doc(doc).trace_id == ""


class TestCarriedResilience:
    """A released (preempted/retired) attempt's survived-fault
    counters must ride the job record into the resumed run's done
    record — otherwise the rollup can no longer attribute injected
    faults whose attempt was revoked (found by the fleet chaos gate
    when the preempt drill landed on the flaky reader's claim)."""

    def test_queue_carry_accumulates(self, tmp_path):
        from peasoup_tpu.campaign.queue import Job, JobQueue

        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="a", input="x.fil"))
        claim = q.try_claim("a", "w1")
        q.record_carried_resilience(
            claim, {"retries": {"fil.read": 2},
                    "faults_injected": {"fil.read": 2}}
        )
        q.release(claim)
        claim2 = q.try_claim("a", "w2")
        q.record_carried_resilience(
            claim2, {"retries": {"fil.read": 1}}
        )
        assert claim2.job.carried_resilience == {
            "retries": {"fil.read": 3},
            "faults_injected": {"fil.read": 2},
        }
        # persisted: a fresh read sees it too
        assert q.get_job("a").carried_resilience["retries"] == {
            "fil.read": 3
        }

    def test_empty_delta_is_noop(self, tmp_path):
        from peasoup_tpu.campaign.queue import Job, JobQueue

        q = JobQueue(str(tmp_path))
        q.add_job(Job(job_id="a", input="x.fil"))
        claim = q.try_claim("a", "w1")
        before = json.load(open(q._p("jobs", "a")))
        q.record_carried_resilience(claim, {})
        assert json.load(open(q._p("jobs", "a"))) == before

    def test_doc_round_trip(self):
        from peasoup_tpu.campaign.queue import Job

        job = Job(
            job_id="a", input="x.fil",
            carried_resilience={"retries": {"fil.read": 2}},
        )
        assert Job.from_doc(job.to_doc()).carried_resilience == {
            "retries": {"fil.read": 2}
        }
        doc = job.to_doc()
        del doc["carried_resilience"]  # pre-PR-14 record
        assert Job.from_doc(doc).carried_resilience == {}


class TestOrphanedResilience:
    """A LOST attempt (lease reaped from under a live run) may not
    touch the job record or publish a done record — so its survived
    faults spool to the worker's own append-only sidecar and the
    rollup folds them in. Found by the fleet chaos gate after the
    exactly-once hardening: the flaky-reader faults fired, the
    attempt was reaped, and the rollup showed no recovery marks."""

    def test_spool_and_rollup_fold(self, tmp_path):
        from peasoup_tpu.campaign.queue import JobQueue
        from peasoup_tpu.campaign.rollup import build_status

        q = JobQueue(str(tmp_path))
        q.record_orphaned_resilience(
            "w0", "j1",
            {"retries": {"fil.read:/x": 2},
             "recoveries": {"fil.read:/x": 1}},
        )
        q.record_orphaned_resilience(
            "w0", "j2", {"retries": {"db.tx": 1}}
        )
        q.record_orphaned_resilience("w1", "j1", {})  # no-op
        recs = q.orphaned_resilience()
        assert [r["job_id"] for r in recs] == ["j1", "j2"]
        res = build_status(str(tmp_path), q)["resilience"]
        assert res["retries"] == {"fil.read:/x": 2, "db.tx": 1}
        assert res["recoveries"] == {"fil.read:/x": 1}
        assert res["orphaned_attempts"]["total"] == 2

    def test_torn_tail_line_skipped(self, tmp_path):
        from peasoup_tpu.campaign.queue import JobQueue

        q = JobQueue(str(tmp_path))
        q.record_orphaned_resilience(
            "w0", "j1", {"retries": {"fil.read": 1}}
        )
        # a worker killed mid-append leaves a torn final line
        spool = os.path.join(q.qdir, "resilience", "w0.jsonl")
        with open(spool, "a") as f:
            f.write('{"job_id": "j2", "resil')
        assert len(q.orphaned_resilience()) == 1


# --------------------------------------------------------------------------
# rollup: throughput decay + clamped ages (ISSUE satellite)
# --------------------------------------------------------------------------

class TestRollupRates:
    def _campaign(self, tmp_path, lease_s=1.0):
        from peasoup_tpu.campaign.queue import JobQueue
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            save_campaign_config,
        )

        root = str(tmp_path / "camp")
        os.makedirs(root, exist_ok=True)
        save_campaign_config(root, CampaignConfig(lease_s=lease_s))
        return root, JobQueue(root, lease_s=lease_s)

    def _done(self, queue, job_id, worker, finished_unix):
        from peasoup_tpu.campaign.queue import _atomic_write_json

        _atomic_write_json(
            queue._p("done", job_id),
            {
                "job_id": job_id, "worker_id": worker,
                "finished_unix": finished_unix, "attempts": 1,
                "n_candidates": 0,
            },
        )

    def test_departed_worker_rate_ages_out(self, tmp_path):
        from peasoup_tpu.campaign.registry import WorkerRegistry
        from peasoup_tpu.campaign.rollup import build_status

        root, q = self._campaign(tmp_path)
        now_unix = time.time()
        # a departed worker that finished two jobs HOURS ago, and a
        # live one that finished two jobs just now
        self._done(q, "j1", "ghost", now_unix - 7200.0)
        self._done(q, "j2", "ghost", now_unix - 7000.0)
        self._done(q, "j3", "alive", now_unix - 60.0)
        self._done(q, "j4", "alive", now_unix - 1.0)
        WorkerRegistry(root, lease_s=60.0).register("alive")
        st = build_status(root, q)
        workers = st["fleet"]["workers"]
        assert workers["alive"]["live"] is True
        assert workers["alive"]["jobs_per_h"] is not None
        assert workers["ghost"]["live"] is False
        assert workers["ghost"]["jobs_per_h"] is None  # aged out
        assert workers["ghost"]["rate_stale"] is True
        assert workers["ghost"]["last_done_age_s"] >= 6000

    def test_recently_departed_worker_keeps_rate(self, tmp_path):
        from peasoup_tpu.campaign.rollup import build_status

        root, q = self._campaign(tmp_path)
        now_unix = time.time()
        self._done(q, "j1", "leaver", now_unix - 20.0)
        self._done(q, "j2", "leaver", now_unix - 5.0)
        st = build_status(root, q)
        rec = st["fleet"]["workers"]["leaver"]
        # within the decay window: history still meaningful
        assert rec["live"] is False
        assert rec["jobs_per_h"] is not None

    def test_ages_clamped_under_clock_skew(self, tmp_path):
        """A skewed peer's done record / heartbeat stamped in OUR
        future must clamp to zero, never render negative."""
        from peasoup_tpu.campaign.registry import WorkerRegistry
        from peasoup_tpu.campaign.rollup import build_status

        root, q = self._campaign(tmp_path)
        now_unix = time.time()
        self._done(q, "j1", "skewed", now_unix + 3600.0)
        reg = WorkerRegistry(root, lease_s=60.0)
        reg.register("skewed")
        # lease stamped far in the future (skewed writer clock)
        reg.beat("skewed", expires_unix=now_unix + 7200.0)
        path = reg._path("skewed")
        doc = json.load(open(path))
        doc["expires_unix"] = now_unix + 7200.0
        from peasoup_tpu.campaign.registry import _atomic_write_json

        _atomic_write_json(path, doc)
        st = build_status(root, q)
        [w] = [
            x for x in st["fleet"]["live"]
            if x["worker_id"] == "skewed"
        ]
        assert w["last_beat_s"] >= 0.0
        assert st["fleet"]["workers"]["skewed"]["last_done_age_s"] == 0.0


# --------------------------------------------------------------------------
# mixed-schema tolerance: report --merge + watch (ISSUE satellite)
# --------------------------------------------------------------------------

def _manifest(version, run_id, **extra):
    man = {
        "schema": "peasoup_tpu.telemetry",
        "version": version,
        "run_id": run_id,
        "created_unix": 1700000000.0 + version,
    }
    man.update(extra)
    return man


class TestMixedSchemaShards:
    def test_merge_v1_v2_v3_side_by_side(self, tmp_path):
        """Shards written by three manifest generations merge without
        KeyError; hosts missing a stage are skipped AND attributed."""
        from peasoup_tpu.obs.schema import validate_manifest
        from peasoup_tpu.tools.report import merge_manifests, render

        v1 = json.load(
            open(os.path.join(os.path.dirname(__file__), "data",
                              "manifest_v1.json"))
        )
        v2 = _manifest(
            2, "v2run", process_index=1, process_count=3,
            hostname="h2", duration_s=4.0,
            timers={"searching": 2.0, "dedispersion": 1.0},
            counters={"search.dm_trials_done": 64},
            events=[{"t": 0.1, "kind": "stage", "name": "searching"}],
            aborted=True, abort_reason="sigterm",
        )
        v3 = _manifest(
            3, "v3run", process_index=2, process_count=3,
            hostname="h3", duration_s=5.0,
            timers={"searching": 3.5, "dedispersion": "garbage"},
            counters={"search.dm_trials_done": 64},
            gauges={"memory.peak_bytes": 5.0},
            events=[],
            streaming={"chunks_done": 2},
        )
        merged = merge_manifests([v1, v2, v3])
        validate_manifest(merged)  # merged manifest stays schema-valid
        assert merged["n_hosts"] == 3
        # dedispersion is numeric on v1 + v2 but garbage on the v3
        # shard -> straggler stats over 2 hosts with the broken host
        # attributed as missing, never a KeyError / poisoned ranking
        strag = merged["straggler"]["timers"]["dedispersion"]
        assert strag["n_hosts"] == 2
        assert [m["hostname"] for m in strag["missing"]] == ["h3"]
        assert merged["timers"]["searching"] == 9.0  # max across hosts
        assert merged["aborted"] is True
        render(merged)  # renders without KeyError too

    def test_merge_gang_member_shards(self, tmp_path):
        """telemetry.proc<rank>.json shards from a gang job (leader +
        member, different workers/pids) merge into one manifest."""
        from peasoup_tpu.tools.report import merge_manifests

        shards = [
            _manifest(
                3, "gangrun", process_index=r, process_count=2,
                hostname=f"w{r}", pid=100 + r, duration_s=2.0 + r,
                timers={"searching": 1.0 + r},
                counters={"search.dm_trials_done": 32},
                events=[{"t": 0.0, "kind": "multihost_slice",
                         "process": r}],
            )
            for r in range(2)
        ]
        merged = merge_manifests(shards)
        assert merged["counters"]["search.dm_trials_done"] == 64
        assert merged["straggler"]["imbalance"]["slowest"][
            "hostname"
        ] == "w1"
        # events carry their host tag
        assert {e["process_index"] for e in merged["events"]} == {0, 1}

    def test_watch_renders_old_and_new_snapshots(self):
        """render_status/render_campaign_status over snapshots missing
        every new-generation key: .get() tolerance, no KeyError."""
        from peasoup_tpu.tools.watch import (
            render_campaign_status,
            render_status,
        )

        out = render_status({"run_id": "r", "stage": "searching"})
        assert "searching" in out
        # a minimal old-schema campaign rollup (no fleet/preemptions/
        # metrics/autoscale keys at all)
        out = render_campaign_status(
            {"root": "/c", "queue": {"total": 2, "done": 1}}
        )
        assert "1/2" in out
        # and a new-schema one with every section populated
        out = render_campaign_status(
            {
                "root": "/c",
                "queue": {"total": 2, "done": 2},
                "fleet": {
                    "live": [{"worker_id": "w1", "jobs_done": 2}],
                    "workers": {
                        "w1": {"done": 2, "jobs_per_h": 3.0,
                               "live": True},
                        "ghost": {"done": 1, "jobs_per_h": None,
                                  "rate_stale": True, "live": False},
                    },
                },
                "preemptions": {"jobs": 1, "total": 1,
                                "outstanding_requests": 0,
                                "latency_s": {"mean": 1.0, "max": 2.0}},
                "gang_jobs": 1,
                "done": True,
            }
        )
        assert "preemptions" in out and "complete" in out


# --------------------------------------------------------------------------
# watch --history + report --timeline
# --------------------------------------------------------------------------

class TestTimelines:
    def test_metrics_history_renders_sparklines(self, tmp_path):
        from peasoup_tpu.tools.watch import render_metrics_history

        p = str(tmp_path / "w.metrics.jsonl")
        r = MetricsRecorder(p)
        for depth in (5, 4, 3, 2, 1, 0):
            r.gauge("queue_depth", depth, state="pending")
        r.counter("jobs_done_total")
        r.observe("preemption_latency_seconds", 1.5)
        out = render_metrics_history({"w": load_series(p)})
        assert "queue depth [pending]" in out
        assert "max 5" in out
        assert "preempt latency" in out

    def test_metrics_history_empty(self):
        from peasoup_tpu.tools.watch import render_metrics_history

        assert "no metrics samples" in render_metrics_history({})

    def test_report_timeline_gantt(self):
        from peasoup_tpu.tools.report import render_timeline

        man = _manifest(
            3, "r1", duration_s=10.0,
            events=[
                {"t": 0.0, "kind": "stage", "name": "reading"},
                {"t": 1.0, "kind": "stage", "name": "searching"},
                {"t": 9.0, "kind": "stage", "name": "writing"},
                {"t": 5.0, "kind": "dedisp_plan", "engine": "exact"},
            ],
        )
        out = render_timeline(man)
        assert "reading" in out and "searching" in out
        assert "#" in out and "*" in out

    def test_report_timeline_no_stages(self):
        from peasoup_tpu.tools.report import render_timeline

        out = render_timeline(_manifest(1, "old", events=[]))
        assert "no stage events" in out


# --------------------------------------------------------------------------
# bowtie diagnostic (ISSUE satellite)
# --------------------------------------------------------------------------

class TestBowtie:
    def _bowtie_events(self, n=60, dm0=40.0, t0=5.0):
        """Synthetic bowtie: S/N peaks at the true DM, fades away from
        it, detection times constant (one pulse seen at many trials)."""
        rng = np.random.default_rng(0)
        dms = np.linspace(dm0 - 10, dm0 + 10, n)
        snrs = 12.0 * np.exp(-0.5 * ((dms - dm0) / 3.0) ** 2) + 6.0
        times = np.full(n, t0) + rng.normal(0, 0.01, n)
        widths = np.full(n, 4, dtype=int)
        return times, dms, snrs, widths

    def test_svg_renders_events(self):
        from peasoup_tpu.tools.plotting import render_bowtie_svg

        times, dms, snrs, widths = self._bowtie_events()
        svg = render_bowtie_svg(times, dms, snrs, widths=widths)
        assert svg.startswith("<svg")
        assert svg.count("<circle") == len(times)
        assert "DM" in svg and "Time (s)" in svg
        # strongest event drawn with the biggest radius
        radii = [
            float(part.split('r="')[1].split('"')[0])
            for part in svg.split("<circle")[1:]
        ]
        assert max(radii) > min(radii)

    def test_svg_empty_events(self):
        from peasoup_tpu.tools.plotting import render_bowtie_svg

        svg = render_bowtie_svg([], [], [])
        assert "no single-pulse events" in svg

    def test_min_snr_filter(self):
        from peasoup_tpu.tools.plotting import render_bowtie_svg

        times, dms, snrs, _ = self._bowtie_events()
        svg = render_bowtie_svg(times, dms, snrs, min_snr=10.0)
        assert svg.count("<circle") == int((snrs >= 10.0).sum())

    def test_bowtie_from_singlepulse_table(self, tmp_path):
        from peasoup_tpu.core.candidates import SinglePulseCandidate
        from peasoup_tpu.io.output import write_singlepulse
        from peasoup_tpu.tools.plotting import bowtie_from_singlepulse

        times, dms, snrs, widths = self._bowtie_events(n=10)
        cands = [
            SinglePulseCandidate(
                dm=float(d), snr=float(s), time_s=float(t),
                sample=int(t / 0.000256), width=int(w), width_idx=0,
                dm_idx=i, members=3,
            )
            for i, (t, d, s, w) in enumerate(
                zip(times, dms, snrs, widths)
            )
        ]
        path = str(tmp_path / "c.singlepulse")
        write_singlepulse(path, cands)
        svg = bowtie_from_singlepulse(path)
        assert svg.count("<circle") == 10

    def test_bowtie_from_db(self, tmp_path):
        from peasoup_tpu.campaign.db import CandidateDB
        from peasoup_tpu.tools.plotting import bowtie_from_db

        db_path = str(tmp_path / "candidates.sqlite")
        with CandidateDB(db_path) as db:
            conn = db._conn
            for i in range(2):
                conn.execute(
                    "INSERT INTO observations (job_id, input, "
                    "source_name, tstart, tsamp, nchans, nsamps, "
                    "ingested_unix) VALUES (?,?,?,?,?,?,?,?)",
                    (f"job{i}", f"/o{i}.fil", f"O{i}",
                     55000.0 + i * 0.01, 0.000256, 8, 4096, 0.0),
                )
                for k in range(3):
                    conn.execute(
                        "INSERT INTO candidates (job_id, kind, dm, "
                        "snr, time_s, sample, width, members) VALUES "
                        "(?, 'single_pulse', ?, ?, ?, ?, 4, 3)",
                        (f"job{i}", 40.0 + k, 8.0 + k, 0.5 * k,
                         int(0.5 * k / 0.000256)),
                    )
            conn.commit()
        svg = bowtie_from_db(db_path)
        assert svg.count("<circle") == 6
        # one job only
        svg = bowtie_from_db(db_path, job_id="job0")
        assert svg.count("<circle") == 3

    def test_sift_report_links_bowtie(self):
        from peasoup_tpu.sift.report import render_html

        doc = {
            "schema": "peasoup_tpu.sift_report", "version": 1,
            "generated_unix": 0.0,
            "run": {"run_id": "r", "created_unix": 0.0, "config": {},
                    "n_folded": 0, "n_catalogue": 0, "n_known": 0,
                    "n_rfi": 0, "n_sp_sources": 0},
            "observations": 0, "candidates": {},
            "tiers": {}, "labels": {}, "known_sources": [],
            "catalogue": [], "sp_sources": [], "campaign": None,
        }
        html = render_html(doc, bowtie_href="bowtie.svg")
        assert 'href=\'bowtie.svg\'' in html or "bowtie.svg" in html
        assert "bowtie.svg" not in render_html(doc)


# --------------------------------------------------------------------------
# campaign end-to-end (one tiny observation, full stack)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def obs_campaign(tmp_path_factory):
    """One spsearch job through run_worker with metrics+trace on."""
    from test_campaign import make_obs

    from peasoup_tpu.campaign.queue import Job, JobQueue, job_id_for
    from peasoup_tpu.campaign.runner import (
        CampaignConfig,
        bucket_for_input,
        run_worker,
        save_campaign_config,
    )

    tmp = tmp_path_factory.mktemp("fleetobs")
    fil = make_obs(str(tmp / "obs0.fil"))
    root = str(tmp / "camp")
    os.makedirs(root)
    save_campaign_config(
        root,
        CampaignConfig(
            pipeline="spsearch",
            config={"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6},
            warmup=False, heartbeat_interval=0.2, backoff_base_s=0.05,
        ),
    )
    q = JobQueue(root)
    jid = job_id_for(fil)
    q.add_job(
        Job(job_id=jid, input=fil, pipeline="spsearch",
            bucket=bucket_for_input(fil))
    )
    tally = run_worker(root, worker_id="w1", poll_s=0.05)
    return root, jid, tally


class TestCampaignEndToEnd:
    def test_job_completes(self, obs_campaign):
        _, _, tally = obs_campaign
        assert tally["done"] == 1

    def test_metrics_written_and_valid(self, obs_campaign):
        root, _, _ = obs_campaign
        samples = fleet_samples(root, validate=True)
        assert "w1" in samples
        names = {r["name"] for r in samples["w1"]}
        assert {
            "queue_depth", "jobs_done_total", "job_duration_seconds",
            "stage_seconds_total", "claim_wait_seconds",
        } <= names
        text = prometheus_exposition(samples)
        assert parse_exposition(text)

    def test_trace_connected_with_expected_spans(self, obs_campaign):
        root, jid, _ = obs_campaign
        from peasoup_tpu.campaign.queue import JobQueue

        spans = load_spans(
            trace_paths(os.path.join(root, "jobs", jid))
        )
        summ = trace_summary(spans)
        assert summ["connected"] and summ["unclosed"] == 0
        names = set(summ["span_names"])
        assert {
            "job_attempt", "claim_wait", "wave", "checkpoint",
            "stage:dedispersion", "stage:searching",
        } <= names
        # the trace id is the one minted at enqueue
        assert summ["trace_ids"] == [
            JobQueue(root).get_job(jid).trace_id
        ]

    def test_chrome_export_of_real_job(self, obs_campaign):
        root, jid, _ = obs_campaign
        doc = export_chrome_trace(
            load_spans(trace_paths(os.path.join(root, "jobs", jid)))
        )
        assert len(doc["traceEvents"]) > 5
        json.dumps(doc)

    def test_rollup_metrics_summary(self, obs_campaign):
        root, _, _ = obs_campaign
        from peasoup_tpu.campaign.rollup import build_status

        st = build_status(root)
        assert st["metrics"]["files"] >= 1
        assert st["metrics"]["bytes"] > 0

    def test_profile_request_observed_as_cpu_noop(self, obs_campaign):
        """Plant a profile.request, run the watcher directly: request
        cleared, capture announced (skipped on CPU) in the metrics."""
        from peasoup_tpu.campaign.runner import CampaignRunner

        root, _, _ = obs_campaign
        runner = CampaignRunner(root, worker_id="w1")
        runner.registry.register("w1")
        runner.registry.request_profile("w1", seconds=0.2)
        runner._observe_profile()
        assert runner._profile_thread is not None
        runner._profile_thread.join(timeout=10)
        assert runner.registry.profile_requested("w1") is None
        samples = load_series(runner.metrics.path)
        caps = [
            s for s in samples
            if s["name"] == "profile_captures_total"
        ]
        assert caps and caps[-1]["labels"]["outcome"] == "skipped"
        runner.registry.deregister("w1")


# --------------------------------------------------------------------------
# CLI surface
# --------------------------------------------------------------------------

class TestCLI:
    def test_metrics_command(self, obs_campaign, capsys):
        from peasoup_tpu.cli.campaign import main

        root, _, _ = obs_campaign
        assert main(["metrics", "-w", root]) == 0
        out = capsys.readouterr().out
        assert "peasoup_jobs_done_total" in out
        parse_exposition(out)

    def test_metrics_command_no_files(self, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        assert main(["metrics", "-w", str(tmp_path)]) == 1

    def test_trace_command(self, obs_campaign, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        root, jid, _ = obs_campaign
        out_path = str(tmp_path / "t.json")
        assert main(["trace", "-w", root, "-o", out_path]) == 0
        doc = json.load(open(out_path))
        assert doc["traceEvents"]
        assert jid in capsys.readouterr().out

    def test_trace_command_empty(self, tmp_path):
        from peasoup_tpu.cli.campaign import main

        assert main(["trace", "-w", str(tmp_path)]) == 1

    def test_profile_command_requires_live_worker(
        self, obs_campaign, capsys
    ):
        from peasoup_tpu.campaign.registry import WorkerRegistry
        from peasoup_tpu.cli.campaign import main

        root, _, _ = obs_campaign
        assert main(["profile", "-w", root, "nobody"]) == 1
        reg = WorkerRegistry(root)
        reg.register("wlive")
        try:
            assert main(
                ["profile", "-w", root, "wlive", "--seconds", "1"]
            ) == 0
            assert reg.profile_requested("wlive") is not None
        finally:
            reg.deregister("wlive")

    def test_watch_history_cli(self, obs_campaign, capsys):
        from peasoup_tpu.tools.watch import main

        root, _, _ = obs_campaign
        assert main([root, "--history"]) == 0
        assert "queue depth" in capsys.readouterr().out

    def test_bowtie_cli(self, obs_campaign, tmp_path, capsys):
        from peasoup_tpu.tools.plotting import bowtie_main

        root, _, _ = obs_campaign
        out = str(tmp_path / "b.svg")
        assert bowtie_main(
            [os.path.join(root, "candidates.sqlite"), "-o", out]
        ) == 0
        assert open(out).read().startswith("<svg")


# --------------------------------------------------------------------------
# trace span links (Perfetto flow ids)
# --------------------------------------------------------------------------

class TestFlowLinks:
    def _linked_spans(self, tmp_path):
        """Two processes' span files carrying one shared flow id (the
        gang-barrier shape) plus an unrelated span."""
        from peasoup_tpu.obs.trace import flow_id_for

        tid = new_trace_id()
        fid = flow_id_for("gang-e1", "merge", 0)
        for w in ("leader", "member"):
            tr = Tracer(
                str(tmp_path / f"trace-{w}.jsonl"), tid, worker=w
            )
            with tr.span("gang_barrier", cat="sched", flow_id=fid):
                pass
            with tr.span("wave"):
                pass
            tr.close()
        return load_spans(
            [str(tmp_path / f"trace-{w}.jsonl")
             for w in ("leader", "member")]
        )

    def test_flow_id_deterministic_across_ranks(self):
        from peasoup_tpu.obs.trace import flow_id_for

        a = flow_id_for("gang-e1", "merge", 3)
        b = flow_id_for("gang-e1", "merge", 3)
        c = flow_id_for("gang-e1", "merge", 4)
        assert a == b != c
        assert 0 <= a <= 0xFFFFFFFF

    def test_summary_counts_linked_flows(self, tmp_path):
        spans = self._linked_spans(tmp_path)
        summ = trace_summary(spans)
        assert summ["n_flows"] == 1
        assert summ["flows_linked"] == 1
        # spans without a flow id stay plain
        assert sum("flow_id" in s for s in spans) == 2

    def test_single_worker_flow_not_linked(self, tmp_path):
        from peasoup_tpu.obs.trace import flow_id_for

        tr = Tracer(str(tmp_path / "trace-w.jsonl"), new_trace_id(),
                    worker="w")
        with tr.span("gang_barrier", flow_id=flow_id_for("g", "b", 0)):
            pass
        tr.close()
        summ = trace_summary(load_spans(str(tmp_path / "trace-w.jsonl")))
        assert summ["n_flows"] == 1 and summ["flows_linked"] == 0

    def test_export_emits_flow_event_chain(self, tmp_path):
        spans = self._linked_spans(tmp_path)
        doc = export_chrome_trace(spans)
        flows = [
            e for e in doc["traceEvents"] if e["ph"] in ("s", "t", "f")
        ]
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert len({e["id"] for e in flows}) == 1
        ends = [e for e in flows if e["ph"] == "f"]
        assert all(e["bp"] == "e" for e in ends)
        # flow events bind to their slices: same pid appears in both
        slice_pids = {
            e["pid"] for e in doc["traceEvents"]
            if e["ph"] == "X" and e["name"] == "gang_barrier"
        }
        assert {e["pid"] for e in flows} == slice_pids
        json.dumps(doc)

    def test_gang_comm_ranks_share_flow_id(self, tmp_path):
        """Both GangComm ranks tag the same barrier round with the
        same flow id, independently computed."""
        from peasoup_tpu.parallel.multihost import GangComm

        gdir = str(tmp_path / "gang-e0")
        tracers, threads = [], []

        def member(rank: int) -> None:
            tr = Tracer(
                str(tmp_path / f"trace-r{rank}.jsonl"),
                "t" * 16, worker=f"r{rank}",
            )
            tracers.append(tr)
            comm = GangComm(gdir, nprocs=2, rank=rank, timeout_s=20.0)
            with tr.activate():
                blobs = comm.allgather(
                    f"blob{rank}".encode(), context="merge"
                )
            assert blobs == [b"blob0", b"blob1"]
            tr.close()

        for rank in (0, 1):
            t = threading.Thread(target=member, args=(rank,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=30)
        spans = load_spans(
            [str(tmp_path / f"trace-r{r}.jsonl") for r in (0, 1)]
        )
        barriers = [s for s in spans if s["name"] == "gang_barrier"]
        assert len(barriers) == 2
        assert barriers[0]["flow_id"] == barriers[1]["flow_id"]
        summ = trace_summary(spans)
        assert summ["flows_linked"] == 1


@pytest.mark.slow
class TestProfilerRealCapture:
    """Real (non-guarded) jax.profiler capture through the worker's
    request protocol — the TPU-soak coverage the roadmap carried. On
    CPU runs the capture path is exercised via allow_cpu; on an
    accelerator backend it captures for real with no override."""

    def test_start_profile_capture_end_to_end(self, tmp_path):
        import jax

        from peasoup_tpu.obs.profiler import start_profile_capture

        backend = jax.default_backend()
        rec = MetricsRecorder(str(tmp_path / "w.metrics.jsonl"))
        out = str(tmp_path / "prof")
        th = start_profile_capture(
            out, 0.3, metrics=rec, allow_cpu=(backend == "cpu")
        )
        th.join(timeout=30)
        caps = [
            s for s in load_series(rec.path)
            if s["name"] == "profile_captures_total"
        ]
        assert caps and caps[-1]["labels"]["outcome"] == "captured"
        assert os.path.isdir(out) and os.listdir(out)
