"""Streaming real-time search subsystem tests (sources -> queue ->
chunk program -> driver -> triggers -> CLI -> observability).

Acceptance gates (ISSUE 7): streaming-equals-batch on a replayed
recording (boundary-spanning injected pulses included), a rate-limited
replay finishing with zero drops + populated latency-SLO fields + zero
XLA programs compiled after the first chunk, and drop/gap accounting
under the drop_oldest backpressure policy.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from peasoup_tpu.io.dada import write_dada
from peasoup_tpu.io.sigproc import (
    Filterbank,
    SigprocHeader,
    read_filterbank,
    write_filterbank,
)
from peasoup_tpu.io.stream_source import (
    DadaStreamSource,
    FileTailSource,
    ReplaySource,
    StreamBlock,
)
from peasoup_tpu.obs.telemetry import RunTelemetry
from peasoup_tpu.ops.singlepulse import make_single_pulse_search_fn
from peasoup_tpu.ops.streaming import make_stream_chunk_fn, stream_geometry
from peasoup_tpu.plan.dm_plan import DMPlan
from peasoup_tpu.stream import (
    BoundedBlockQueue,
    StreamConfig,
    StreamingSearch,
)
from peasoup_tpu.tools.parsers import read_singlepulse

NSAMPS, NCHANS, TSAMP, FCH1, FOFF = 1 << 12, 8, 0.000256, 1400.0, -16.0
PULSES = (900, 2040)  # 2040 spans the 1024-chunk deferred boundary


def _plan(nsamps=NSAMPS):
    return DMPlan.create(
        nsamps=nsamps, nchans=NCHANS, tsamp=TSAMP, fch1=FCH1, foff=FOFF,
        dm_start=0.0, dm_end=20.0, pulse_width=64.0, tol=1.10,
    )


@pytest.fixture(scope="module")
def stream_fil(tmp_path_factory):
    """A small filterbank with two strong dispersed pulses, one right
    at a chunk boundary's deferred zone."""
    tmp = tmp_path_factory.mktemp("stream")
    plan = _plan()
    delays = plan.delay_samples()[plan.ndm // 2]
    rng = np.random.default_rng(3)
    data = rng.normal(32.0, 4.0, size=(NSAMPS, NCHANS))
    for s0 in PULSES:
        for c in range(NCHANS):
            data[s0 + delays[c] : s0 + 4 + delays[c], c] += 16.0
    hdr = SigprocHeader(
        source_name="STREAMTEST", tsamp=TSAMP, tstart=55000.0,
        fch1=FCH1, foff=FOFF, nchans=NCHANS, nbits=8, nifs=1,
        data_type=1,
    )
    path = tmp / "stream.fil"
    write_filterbank(
        path,
        Filterbank(
            header=hdr,
            data=np.clip(np.rint(data), 0, 255).astype(np.uint8),
        ),
    )
    return str(path)


def _stream_cfg(outdir, **kw):
    base = dict(
        outdir=str(outdir), dm_end=20.0, min_snr=7.0, n_widths=6,
        decimate=8, chunk_samples=1024, latency_slo_s=30.0,
        warmup=False,
    )
    base.update(kw)
    return StreamConfig(**base)


# --------------------------------------------------------------------------
# sources
# --------------------------------------------------------------------------

class TestSources:
    def test_replay_fixed_blocks(self, stream_fil):
        fil = read_filterbank(stream_fil)
        src = ReplaySource(fil, block_samples=640, rate=0.0)
        blocks = list(src.blocks())
        assert all(b.data.shape == (640, NCHANS) for b in blocks)
        assert [b.seq for b in blocks] == list(range(len(blocks)))
        assert [b.start_sample for b in blocks] == [
            640 * i for i in range(len(blocks))
        ]
        # 4096 = 6*640 + 256: final block padded, nvalid marks it
        assert blocks[-1].final and blocks[-1].nvalid == 256
        assert not any(b.final for b in blocks[:-1])
        assert (blocks[-1].data[256:] == 0).all()
        total = np.concatenate(
            [b.data[: b.nvalid] for b in blocks]
        )
        np.testing.assert_array_equal(total, fil.data)

    def test_replay_paces_release(self, stream_fil):
        fil = read_filterbank(stream_fil)
        # 4096 samples * 256us ~ 1.05 s of data at 8x ~ 0.13 s floor
        src = ReplaySource(fil, block_samples=1024, rate=8.0)
        t0 = time.perf_counter()
        blocks = list(src.blocks())
        elapsed = time.perf_counter() - t0
        assert len(blocks) == 4
        assert elapsed >= 0.9 * (NSAMPS * TSAMP / 8.0)
        arrivals = [b.t_arrival_s for b in blocks]
        assert arrivals == sorted(arrivals)

    def test_file_tail_follows_growth(self, stream_fil, tmp_path):
        fil = read_filterbank(stream_fil)
        path = tmp_path / "grow.fil"
        blob = open(stream_fil, "rb").read()
        hdr_size = len(blob) - NSAMPS * NCHANS
        half = hdr_size + (NSAMPS // 2) * NCHANS
        with open(path, "wb") as f:
            f.write(blob[:half])

        def _finish():
            time.sleep(0.2)
            with open(path, "ab") as f:
                f.write(blob[half:])
            open(str(path) + ".complete", "w").close()

        t = threading.Thread(target=_finish)
        t.start()
        src = FileTailSource(str(path), block_samples=768, poll_s=0.02)
        blocks = list(src.blocks())
        t.join()
        got = np.concatenate([b.data[: b.nvalid] for b in blocks])
        np.testing.assert_array_equal(got, fil.data)
        assert blocks[-1].final

    def test_dada_segments_roundtrip(self, tmp_path):
        rng = np.random.default_rng(0)
        payload = rng.integers(0, 255, size=(600, 16), dtype=np.uint8)
        common = dict(
            header_version=1.0, bw=64.0, freq=1382.0, nant=1,
            nchan=16, npol=1, nbit=8, tsamp=256.0,  # us, PSRDADA-style
            source_name="J0000+00",
        )
        write_dada(
            tmp_path / "2020_0001.dada", payload[:256], **common
        )
        write_dada(
            tmp_path / "2020_0002.dada", payload[256:], file_no=1,
            **common,
        )
        open(tmp_path / "obs.complete", "w").close()
        src = DadaStreamSource(str(tmp_path), block_samples=128)
        assert src.format.nchans == 16
        assert src.format.tsamp == pytest.approx(256e-6)
        # FREQ is the band centre; channel 0 sits at the top edge
        assert src.format.foff == pytest.approx(-4.0)
        assert src.format.fch1 == pytest.approx(1382.0 + 30.0)
        blocks = list(src.blocks())
        got = np.concatenate([b.data[: b.nvalid] for b in blocks])
        # segment boundary (256) is mid-block (128*2=256... next block
        # spans both segments when sizes don't align); use odd sizes
        np.testing.assert_array_equal(got, payload)
        assert blocks[-1].final


# --------------------------------------------------------------------------
# backpressure queue
# --------------------------------------------------------------------------

def _blk(seq, n=64):
    return StreamBlock(
        seq=seq, start_sample=seq * n,
        data=np.zeros((n, 4), np.uint8), nvalid=n,
    )


class TestBoundedQueue:
    def test_block_policy_never_drops(self):
        q = BoundedBlockQueue(2, "block")
        q.put(_blk(0))
        q.put(_blk(1))
        got = []

        def _drain():
            time.sleep(0.1)
            while True:
                b = q.get(timeout=0.5)
                if b is None:
                    break
                got.append(b.seq)

        t = threading.Thread(target=_drain)
        t.start()
        q.put(_blk(2))  # blocks until the drainer frees a slot
        q.close()
        t.join()
        assert got == [0, 1, 2]
        assert q.drops.blocks == 0

    def test_drop_oldest_accounts(self):
        q = BoundedBlockQueue(2, "drop_oldest")
        for seq in range(5):
            q.put(_blk(seq))
        q.close()
        kept = []
        while True:
            b = q.get(timeout=0.1)
            if b is None:
                break
            kept.append(b.seq)
        assert kept == [3, 4]  # oldest dropped first
        assert q.drops.blocks == 3
        assert q.drops.samples == 3 * 64

    def test_rejects_unknown_policy(self):
        with pytest.raises(ValueError, match="policy"):
            BoundedBlockQueue(2, "drop_newest")


# --------------------------------------------------------------------------
# chunk program vs batch program
# --------------------------------------------------------------------------

class TestStreamChunkProgram:
    def test_geometry_validation(self):
        widths = (1, 2, 4, 8)
        assert stream_geometry(widths, 1024, 8) == 8
        assert stream_geometry((1, 2, 4, 8, 16, 32), 1024, 8) == 32
        with pytest.raises(ValueError, match="multiples"):
            stream_geometry(widths, 1000, 16)
        with pytest.raises(ValueError, match="narrower"):
            stream_geometry((1, 64), 1024, 8, hold=8)
        with pytest.raises(ValueError, match="chunk_len"):
            stream_geometry((1,), 8, 8, hold=16)

    def test_chunked_events_match_batch(self, rng):
        """The streaming sweep over tiled windows finds exactly the
        batch event set — including pulses inside the deferred
        boundary zone — with S/N differing only by the window-local
        normalisation moments."""
        D, T, L, H, dec = 3, 4096, 1024, 64, 8
        widths = (1, 2, 4, 8)
        x = rng.normal(30.0, 4.0, size=(D, T))
        for d, s, w, a in [
            (0, 500, 4, 22.0), (1, 2040, 8, 14.0), (2, 3500, 2, 28.0)
        ]:
            x[d, s : s + w] += a
        x = np.clip(np.rint(x), 0, 255).astype(np.uint8)

        batch = make_single_pulse_search_fn(widths, 7.0, 64, dec, 0)
        bs, bw, bsn, bc = (np.asarray(v) for v in batch(jnp.asarray(x)))
        bev = {}
        for d in range(D):
            for i in range(min(int(bc[d]), 64)):
                bev[(d, int(bs[d, i]), int(bw[d, i]))] = float(bsn[d, i])

        fn = make_stream_chunk_fn(widths, 7.0, 64, dec, H, L)
        sev = {}
        tail = jnp.zeros((D, H), jnp.uint8)
        w = H + L
        nchunks = T // L
        for k in range(nchunks):
            new = jnp.asarray(x[:, k * L : (k + 1) * L])
            valid_lo = H if k == 0 else 0
            final = k == nchunks - 1
            ss, sw, ssn, sc = (
                np.asarray(v)
                for v in fn(
                    tail, new, jnp.int32(valid_lo), jnp.int32(w),
                    jnp.int32(valid_lo // dec),
                    jnp.int32((w if final else L) // dec),
                )
            )
            origin = k * L - H
            for d in range(D):
                for i in range(min(int(sc[d]), 64)):
                    sev[(d, origin + int(ss[d, i]), int(sw[d, i]))] = (
                        float(ssn[d, i])
                    )
            tail = new[:, L - H :]
        assert set(bev) == set(sev)
        assert (1, 2040, 3) in sev  # the boundary-spanning pulse
        for key, snr in bev.items():
            assert sev[key] == pytest.approx(snr, rel=0.1)

    def test_single_compiled_program_for_all_phases(self):
        """First chunk, steady state, and drain differ only in traced
        scalars: one compiled program covers the stream's life."""
        fn = make_stream_chunk_fn((1, 2, 4), 6.0, 16, 8, 8, 256)
        tail = jnp.zeros((2, 8), jnp.uint8)
        new = jnp.zeros((2, 256), jnp.uint8)
        # one lowering serves every phase's scalar settings
        assert fn.lower(
            tail, new, jnp.int32(0), jnp.int32(264), jnp.int32(0),
            jnp.int32(32),
        ) is not None
        for args in ((8, 264, 1, 32), (0, 264, 0, 32), (0, 100, 0, 33)):
            fn(tail, new, *(jnp.int32(a) for a in args))

    def test_registry_ctx_hook_builds_production_shapes(self):
        from peasoup_tpu.ops.registry import ShapeCtx, registered_programs

        spec = {s.name: s for s in registered_programs()}[
            "ops.streaming.stream_chunk_search"
        ]
        ctx = ShapeCtx(
            nsamps=1054, nchans=8, nbits=8, ndm=21, out_nsamps=1024,
            dm_block=21, dedisp_block=21, widths=(1, 2, 4, 8),
            min_snr=7.0, max_events=64, decimate=8,
            stream_chunk=1024, stream_hold=32,
        )
        built = spec.build_for(ctx)
        assert built is not None
        fn, args, kwargs = built
        assert args[0].shape == (21, 32)
        assert args[1].shape == (21, 1024)
        # a batch (non-streaming) ctx skips the hook entirely
        assert spec.build_for(
            ShapeCtx(
                nsamps=1054, nchans=8, nbits=8, ndm=21,
                out_nsamps=1024, dm_block=21, dedisp_block=21,
                widths=(1, 2, 4, 8),
            )
        ) is None


# --------------------------------------------------------------------------
# the driver: streaming equals batch
# --------------------------------------------------------------------------

class TestStreamingSearch:
    @pytest.fixture(scope="class")
    def both_results(self, stream_fil, tmp_path_factory):
        from peasoup_tpu.pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )

        fil = read_filterbank(stream_fil)
        common = dict(dm_end=20.0, min_snr=7.0, n_widths=6, decimate=8)
        batch = SinglePulseSearch(
            SinglePulseConfig(use_pallas=False, **common)
        ).run(fil)
        outdir = tmp_path_factory.mktemp("stream_out")
        tel = RunTelemetry()
        with tel.activate():
            stream = StreamingSearch(
                _stream_cfg(outdir, **common)
            ).run(ReplaySource(fil, 256, rate=0.0))
        return batch, stream, str(outdir), tel

    def test_candidates_match_batch(self, both_results):
        batch, stream, _, _ = both_results
        bkeys = {(c.dm_idx, c.sample, c.width) for c in batch.candidates}
        skeys = {
            (c.dm_idx, c.sample, c.width) for c in stream.candidates
        }
        assert bkeys == skeys
        assert len(batch.candidates) == len(stream.candidates)
        bsnr = {
            (c.dm_idx, c.sample): c.snr for c in batch.candidates
        }
        for c in stream.candidates:
            assert c.snr == pytest.approx(
                bsnr[(c.dm_idx, c.sample)], rel=0.1
            )

    def test_boundary_pulse_recovered(self, both_results):
        _, stream, _, _ = both_results
        samples = {c.sample for c in stream.candidates}
        for s0 in PULSES:
            assert any(abs(s - s0) <= 8 for s in samples)

    def test_zero_drops_and_zero_steady_recompiles(self, both_results):
        _, stream, _, _ = both_results
        assert stream.drops == {
            "blocks": 0, "samples": 0, "gap_samples": 0,
        }
        assert stream.jit_programs_steady == 0
        # first-chunk compiles may legitimately be 0 too (persistent
        # compilation cache warm from an earlier run of these shapes)
        assert stream.jit_programs_first_chunk >= 0
        assert stream.n_chunks == 4

    def test_latency_slo_fields_populated(self, both_results):
        _, stream, _, _ = both_results
        lat = stream.latency
        assert lat["slo"] == 30.0
        assert lat["p50"] is not None and lat["p50"] > 0
        assert lat["p95"] is not None and lat["p95"] >= lat["p50"]
        assert lat["misses"] == 0

    def test_trigger_stream_on_disk(self, both_results):
        _, stream, outdir, _ = both_results
        lines = [
            json.loads(ln)
            for ln in open(os.path.join(outdir, "triggers.jsonl"))
        ]
        assert len(lines) == stream.n_triggers == len(stream.candidates)
        assert [t["seq"] for t in lines] == list(
            range(1, len(lines) + 1)
        )
        for t in lines:
            assert t["schema"] == "peasoup_tpu.trigger"
            assert t["latency_s"] is not None and t["latency_s"] > 0
        # triggers are emitted in time order as clusters confirm
        samples = [t["sample"] for t in lines]
        assert samples == sorted(samples)
        # the rolling table is the batch .singlepulse format
        cands = read_singlepulse(
            os.path.join(outdir, "candidates.singlepulse")
        )
        assert len(cands) == len(stream.candidates)

    def test_streaming_section_in_status_and_manifest(
        self, both_results, tmp_path
    ):
        from peasoup_tpu.obs.schema import load_schema, validate
        from peasoup_tpu.tools.watch import render_status

        _, stream, _, tel = both_results
        sections = tel.snapshot_sections()
        assert "streaming" in sections
        sec = sections["streaming"]
        assert sec["chunks_done"] == stream.n_chunks
        assert sec["drops"] == {"blocks": 0, "samples": 0}
        assert sec["latency_s"]["p95"] is not None
        man = tel.write(str(tmp_path / "telemetry.json"))
        assert man["streaming"]["triggers"] == stream.n_triggers
        validate(man, load_schema())
        # the watcher renders the section (schema-dispatched)
        txt = render_status(
            {
                "schema": "peasoup_tpu.status", "version": 2,
                "run_id": "r", "seq": 1, "streaming": sec,
            }
        )
        assert "stream: chunk" in txt and "latency p50" in txt

    def test_gap_from_upstream_drop_is_filled_and_accounted(
        self, stream_fil, tmp_path
    ):
        """A block dropped upstream (queue drop_oldest, dead ring
        writer) appears as a start_sample gap: the driver zero-fills
        it, accounts the samples, and still finds pulses elsewhere."""
        fil = read_filterbank(stream_fil)

        class GappySource(ReplaySource):
            def blocks(self):
                for blk in super().blocks():
                    if blk.seq == 7:  # samples 1792..2047: kills P2040's
                        continue  # left context but not P900
                    yield blk

        tel = RunTelemetry()
        with tel.activate():
            res = StreamingSearch(_stream_cfg(tmp_path)).run(
                GappySource(fil, 256, rate=0.0)
            )
        assert res.drops["gap_samples"] == 256
        kinds = [e["kind"] for e in tel.events]
        assert "stream_gap_fill" in kinds
        assert any(abs(c.sample - 900) <= 8 for c in res.candidates)

    def test_max_chunks_stops_early(self, stream_fil, tmp_path):
        fil = read_filterbank(stream_fil)
        res = StreamingSearch(
            _stream_cfg(tmp_path / "mc", max_chunks=2)
        ).run(ReplaySource(fil, 256, rate=0.0))
        assert res.n_chunks == 2
        # the truncated stream covers samples [0, 2048): both pulses
        # are inside (2040 sits in chunk 1's final-flush zone), and
        # nothing beyond the cut can have been emitted
        assert any(abs(c.sample - 900) <= 8 for c in res.candidates)
        assert all(c.sample < 2048 for c in res.candidates)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

class TestStreamCLI:
    def test_replay_end_to_end(self, stream_fil, tmp_path):
        from peasoup_tpu.cli.stream import main
        from peasoup_tpu.obs.heartbeat import load_status
        from peasoup_tpu.obs.telemetry import load_manifest

        out = tmp_path / "out"
        rc = main(
            [
                "--replay", stream_fil, "--rate", "16",
                "-o", str(out), "--dm_end", "20", "-m", "7",
                "--n_widths", "6", "--chunk", "1024",
                "--decimate", "8", "--latency-slo", "30",
                "--status-json", str(out / "status.json"),
            ]
        )
        assert rc == 0
        st = load_status(str(out / "status.json"))
        assert st["done"] is True
        sec = st["streaming"]
        assert sec["drops"]["blocks"] == 0
        assert sec["jit_programs_steady"] == 0
        assert sec["triggers"] >= 2
        man = load_manifest(str(out / "telemetry.json"))
        assert man["streaming"]["triggers"] == sec["triggers"]
        assert os.path.getsize(out / "triggers.jsonl") > 0

    def test_version_flag(self, capsys):
        from peasoup_tpu.cli.stream import main

        with pytest.raises(SystemExit):
            main(["--version"])
        assert "peasoup_tpu" in capsys.readouterr().out


# --------------------------------------------------------------------------
# shared measurement path (perf/measure.py)
# --------------------------------------------------------------------------

class TestMeasure:
    def test_median_even_and_odd(self):
        from peasoup_tpu.perf.measure import median

        assert median([3.0, 1.0, 2.0]) == 2.0
        assert median([4.0, 1.0, 2.0, 3.0]) == 2.5
        assert median([]) == 0.0

    def test_timed_samples_runs_prepare_outside_timer(self):
        from peasoup_tpu.perf.measure import timed_samples

        calls = {"prepare": 0, "call": 0}

        def prepare():
            calls["prepare"] += 1

        def call():
            calls["call"] += 1

        samples = timed_samples(call, 5, prepare=prepare)
        assert len(samples) == 5
        assert calls == {"prepare": 5, "call": 5}
        assert samples == sorted(samples)

    def test_bench_py_uses_shared_path(self):
        """bench.py's timing helpers ARE the perf ones (no duplicate
        measurement code between the BENCH protocol and the ratchet)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_under_test",
            os.path.join(os.path.dirname(__file__), "..", "bench.py"),
        )
        src = open(spec.origin).read()
        assert "peasoup_tpu.perf.measure" in src
        assert "def _median" not in src
        assert "def _device_busy_seconds" not in src
