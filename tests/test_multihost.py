"""REAL multi-process multi-host test (VERDICT r1 item 6).

Launches two `JAX_PLATFORMS=cpu` subprocesses with
``jax.distributed.initialize`` (coordinator on localhost, 4 virtual
devices each) running parallel/multihost.py:run_search — the allgather
sizing, the pickled candidate exchange, and the owner-fold routing all
execute over a live coordination service instead of the sequential
two-slice simulation (tests/test_pipeline.py keeps that as the fast
check). Both ranks' finalized candidate lists must be identical to each
other and bitwise equal to a single-process run.
"""

import os
import pickle
import socket
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu.io import read_filterbank
from peasoup_tpu.pipeline import PeasoupSearch, SearchConfig

from test_pipeline import make_synthetic_fil

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch(rank, nproc, port, fil, out, cfg_fields):
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
    env["JAX_NUM_PROCESSES"] = str(nproc)
    env["JAX_PROCESS_ID"] = str(rank)
    return subprocess.Popen(
        [sys.executable, WORKER, fil, out, json.dumps(cfg_fields)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _run_workers(tmp_path, fil_path, cfg_fields, attempts=2):
    """Launch the 2-process job; retry once with a fresh port if it
    fails (the free-port probe is racy on a busy host)."""
    last = None
    for _ in range(attempts):
        port = _free_port()
        outs = [str(tmp_path / f"rank{r}.pkl") for r in range(2)]
        procs = [
            _launch(r, 2, port, fil_path, outs[r], cfg_fields)
            for r in range(2)
        ]
        logs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=900)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("multi-host worker timed out")
            logs.append(out)
        if all(p.returncode == 0 for p in procs):
            return outs
        last = "\n".join(
            f"rank{r} rc={p.returncode}\n{log[-2000:]}"
            for r, (p, log) in enumerate(zip(procs, logs))
        )
    pytest.fail(f"multi-host workers failed after {attempts} attempts:\n{last}")


@pytest.mark.parametrize("npdmp", [4])
def test_two_process_run_matches_single(tmp_path, npdmp):
    path, _, _ = make_synthetic_fil(tmp_path)
    fil = read_filterbank(str(path))
    cfg_fields = dict(dm_end=40.0, nharmonics=2, npdmp=npdmp, limit=100)
    single = PeasoupSearch(SearchConfig(**cfg_fields)).run(fil)
    assert len(single.candidates) > 0

    outs = _run_workers(tmp_path, str(path), cfg_fields)

    results = []
    for o in outs:
        with open(o, "rb") as f:
            results.append(pickle.load(f))
    assert {r["nproc"] for r in results} == {2}
    assert results[0]["rows"] == results[1]["rows"]  # identical everywhere
    assert (
        results[0]["n_accel_trials"]
        == results[1]["n_accel_trials"]
        == single.n_accel_trials
    )

    ours = [
        (c.freq, c.snr, c.dm, c.acc, c.nh, c.folded_snr, c.opt_period)
        for c in single.candidates
    ]
    got = [tuple(row) for row in results[0]["rows"]]
    assert len(got) == len(ours)
    for a, b in zip(ours, got):
        assert a == b, (a, b)
