"""The f64 divergence oracle (tools/divergence.py) IS the parity
instrument: it reproduced the golden CUDA S/N to every printed digit and
localized the round-2 0.6% gap to the dedisp delay constant.  These
tests pin (a) oracle == golden, (b) our jitted f32 chain == oracle to
FFT-ULP bounds, so any future drift in either direction fails loudly.
"""

import os

import numpy as np
import pytest

from peasoup_tpu.tools.divergence import (
    oracle_cluster_max,
    oracle_dedisperse,
    oracle_delay_samples,
    oracle_delay_table,
    oracle_max_delay,
    oracle_search_trial,
)

GOLDEN_DIR = "/root/reference/example_output"
TUTORIAL = "/root/reference/example_data/tutorial.fil"

pytestmark = pytest.mark.skipif(
    not os.path.exists(TUTORIAL), reason="tutorial data not available"
)


@pytest.fixture(scope="module")
def fil():
    from peasoup_tpu.io.sigproc import read_filterbank

    return read_filterbank(TUTORIAL)


def _trial(fil, dm, accs=(0.0,)):
    h = fil.header
    size = 131072
    bw = float(np.float32(1.0 / (np.float32(size) * np.float32(h.tsamp))))
    pos5, pos25 = int(0.05 / bw), int(0.5 / bw)
    tab = oracle_delay_table(h.fch1, h.foff, h.nchans, h.tsamp)
    delays = oracle_delay_samples(np.array([dm]), tab)[0]
    tim = oracle_dedisperse(fil.data, delays, size)
    return (
        oracle_search_trial(tim, size, h.tsamp, list(accs), pos5, pos25),
        tim,
        size,
        bw,
    )


@pytest.mark.skipif(
    not os.path.exists(GOLDEN_DIR), reason="golden outputs not available"
)
def test_oracle_matches_golden_snr(fil):
    """The oracle reproduces the golden candidates' S/N to <2e-5 rel —
    including the high-DM ones the 4.148808e3 constant got 0.6% wrong."""
    golden = [  # (dm, freq, nh, golden_snr) from example_output/overview.xml
        (19.762409210205078, 1 / 0.249939903165736, 4, 86.96260833740234),
        (239.3756103515625, 1 / 0.249660952380952, 2, 42.91218948364258),
    ]
    for dm, freq, nh, gsnr in golden:
        o, _, _, bw = _trial(fil, dm)
        lvl = o["acc"][0.0]["levels"][nh]
        snr = oracle_cluster_max(lvl, int(round(freq * 2**nh / bw)))
        assert abs(snr - gsnr) / gsnr < 2e-5, (dm, nh, snr, gsnr)


def test_delay_table_dedisp_constants(fil):
    """The delay table must use dedisp's rounded 4.15e3; plan
    delay_samples must agree with the oracle's f32-product rounding."""
    from peasoup_tpu.plan.dm_plan import DMPlan

    h = fil.header
    tab = oracle_delay_table(h.fch1, h.foff, h.nchans, h.tsamp)
    plan = DMPlan.create(
        h.nsamples, h.nchans, h.tsamp, h.fch1, h.foff, 0.0, 250.0
    )
    np.testing.assert_array_equal(np.abs(plan.delays), np.abs(tab))
    np.testing.assert_array_equal(
        plan.delay_samples(), oracle_delay_samples(plan.dm_list, tab)
    )
    assert plan.max_delay == oracle_max_delay(float(plan.dm_list[-1]), tab)


def test_pipeline_chain_matches_oracle_membership(fil):
    """Our jitted per-trial chain tracks the oracle to FFT-ULP bounds:
    identical S/N-9 threshold membership on every level, |dS/N| small.
    (On the CPU test backend the FFT is tighter than TPU's; the bound
    covers both.)"""
    import jax
    import jax.numpy as jnp

    from peasoup_tpu.ops.harmonics import harmonic_sums
    from peasoup_tpu.ops.rednoise import whiten_fseries
    from peasoup_tpu.ops.spectrum import form_interpolated, spectrum_stats

    o, tim, size, bw = _trial(fil, 0.0)
    pos5, pos25 = int(0.05 / bw), int(0.5 / bw)

    @jax.jit
    def chain(x32):
        fser = whiten_fseries(x32, pos5=pos5, pos25=pos25)
        s0 = form_interpolated(fser)
        mean, _, std = spectrum_stats(s0)
        xd = jnp.fft.irfft(fser, n=size)
        f = jnp.fft.rfft(xd)
        sn = (form_interpolated(f) - mean) / std
        return [sn] + harmonic_sums(sn, nharms=4)

    ours = [np.asarray(v, np.float64) for v in chain(
        jnp.asarray(tim[:size], jnp.float32)
    )]
    for lvl in range(5):
        ref = o["acc"][0.0]["levels"][lvl]
        assert np.array_equal(ours[lvl] > 9.0, ref > 9.0), lvl
        assert np.max(np.abs(ours[lvl] - ref)) < 5e-3, lvl


def test_compare_trial_report(fil):
    """The harness's own stage-by-stage report path (the CLI main):
    every stage of the jitted chain tracks the oracle."""
    from peasoup_tpu.tools.divergence import main

    assert main(["--dm", "0.0"]) == 0
