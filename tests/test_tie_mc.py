"""Unit tests for the acc-tie Monte-Carlo replay (tools/tie_mc.py) on a
SYNTHETIC capture — the golden-run integration proof lives in
tests/test_recall.py::test_acc_tie_crowns_are_noise; these tests pin the
replay mechanics themselves (distill chain wiring, crown lookup,
perturbation plumbing) without the ~100 s pipeline fixture."""

import numpy as np
import pytest

from peasoup_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime required for the replay"
)


def _capture(snrs, freqs, dm_of_seg, seg_counts, accs):
    """Minimal capture dict: one accel trial per DM (segments == DMs),
    all rows harmonic level 0, accel index 0."""
    n = len(snrs)
    return {
        "freqs": np.asarray(freqs, np.float64),
        "snr": np.asarray(snrs, np.float64),
        "lvl": np.zeros(n, np.int32),
        "a": np.zeros(n, np.int32),
        "seg_counts": np.asarray(seg_counts, np.int64),
        "dm_of_seg": np.asarray(dm_of_seg, np.int64),
        "acc_tab": np.asarray(accs, np.float64).reshape(-1, 1),
        "dm_list": np.linspace(0.0, 10.0, len(accs)),
        "harm_tol": np.float64(1e-4),
        "harm_max": np.int64(16),
        "harm_frac": np.bool_(False),
        "acc_tobs_over_c": np.float64(1e-7),
        "acc_tol": np.float64(1e-4),
        "freq_tol": np.float64(1e-4),
        "max_harm": np.int64(16),
    }


def test_replay_crowns_strongest_and_absorbs_related():
    from peasoup_tpu.tools.tie_mc import crowns_for_golden, replay

    # two DMs, same frequency, different S/N: the DM distiller must
    # crown the stronger row; an unrelated frequency survives alongside
    cap = _capture(
        snrs=[12.0, 20.0, 9.5],
        freqs=[100.0, 100.0, 37.0],
        dm_of_seg=[0, 1],
        seg_counts=[2, 1],  # rows 0,1 -> DM 0; row 2 -> DM 1
        accs=[1.0, -2.0],
    )
    # seg 0 (dm 0) holds the two equal-frequency rows — the harmonic
    # distill inside the segment absorbs the weaker one; seg 1 (dm 1)
    # holds the unrelated 37.0 Hz row
    cands = replay(cap, cap["snr"])
    got = {round(c.freq, 3): (c.snr, c.dm_idx) for c in cands}
    assert got[100.0][0] == 20.0  # strongest equal-freq row crowned
    assert 37.0 in got
    crowns = crowns_for_golden(cands, np.asarray([100.0, 37.0]))
    assert crowns[0] is not None and crowns[0][1] == 20.0
    assert crowns[1] is not None and crowns[1][1] == 9.5


def test_replay_responds_to_snr_vector():
    """The same capture replayed with a different S/N vector must crown
    the other row — the perturbation plumbing the MC relies on."""
    from peasoup_tpu.tools.tie_mc import crowns_for_golden, replay

    cap = _capture(
        snrs=[12.0, 20.0],
        freqs=[100.0, 100.0],
        dm_of_seg=[0, 1],
        seg_counts=[1, 1],
        accs=[1.0, -2.0],
    )
    base = crowns_for_golden(replay(cap, cap["snr"]), np.asarray([100.0]))
    flipped = crowns_for_golden(
        replay(cap, np.asarray([30.0, 20.0])), np.asarray([100.0])
    )
    assert base[0][1] == 20.0 and base[0][0] == -2.0
    assert flipped[0][1] == 30.0 and flipped[0][0] == 1.0


def test_mc_reports_stable_when_gaps_exceed_delta():
    """Well-separated S/N values must NOT flag as unstable at a delta
    far below the gap — the converse of the golden-run noise proof."""
    from peasoup_tpu.tools.tie_mc import mc_crown_stability

    cap = _capture(
        snrs=[12.0, 20.0],
        freqs=[100.0, 100.0],
        dm_of_seg=[0, 1],
        seg_counts=[1, 1],
        accs=[1.0, -2.0],
    )
    res = mc_crown_stability(
        cap, np.asarray([100.0]), n_draws=20, delta=1e-3, seed=0
    )
    assert res["unstable"] == [False]
    assert res["baseline"][0][1] == 20.0
