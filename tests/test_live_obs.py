"""Live observability tests: the status.json heartbeat + stall
watchdog, the crash flight recorder (including real-SIGTERM abort
forensics in a subprocess), per-host manifest merging with straggler
statistics, manifest schema validation, and the report tool's
older-schema tolerance."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from argparse import Namespace

import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu import obs
from peasoup_tpu.cli import live_observability
from peasoup_tpu.utils import Stopwatch
from test_pipeline import make_synthetic_fil


def _args(**kw):
    base = dict(
        status_json=None, heartbeat_interval=0.02,
        no_flight_recorder=False,
    )
    base.update(kw)
    return Namespace(**base)


# --------------------------------------------------------------------------
# telemetry live-state plumbing
# --------------------------------------------------------------------------

def test_stage_and_progress_tracking():
    t = obs.RunTelemetry()
    assert t.current_stage is None
    t.set_stage("plan")
    assert t.current_stage == "plan"
    t.set_stage("plan")  # idempotent: no duplicate event
    assert [e["kind"] for e in t.events] == ["stage"]
    with t.stage("searching"):
        assert t.current_stage == "searching"
        with t.stage("inner"):
            assert t.current_stage == "inner"
        assert t.current_stage == "searching"
    t.set_progress(3, 10, unit="chunks")
    assert t.progress_state["done"] == 3.0
    assert t.progress_state["total"] == 10.0
    assert t.progress_state["unit"] == "chunks"
    # NOOP absorbs both without state
    obs.NOOP.set_stage("x")
    obs.NOOP.set_progress(1, 2)
    assert obs.NOOP.current_stage is None
    assert obs.NOOP.progress_state == {}


def test_event_listeners():
    t = obs.RunTelemetry()
    seen = []
    t.add_listener(seen.append)
    t.event("a", x=1)

    def boom(rec):
        raise RuntimeError("listener bug")

    t.add_listener(boom)
    t.event("b")  # a broken listener must not break recording
    t.remove_listener(seen.append)
    t.event("c")
    assert [r["kind"] for r in seen] == ["a", "b"]
    assert [r["kind"] for r in t.events] == ["a", "b", "c"]


def test_manifest_v2_tags_and_aborted(tmp_path):
    t = obs.RunTelemetry(run_id="v2")
    t.set_stage("searching")
    t.set_progress(2, 8, unit="chunks")
    man = t.write(str(tmp_path / "m.json"))
    assert man["version"] == obs.MANIFEST_VERSION >= 2
    assert man["process_index"] == 0
    assert man["process_count"] >= 1
    assert "aborted" not in man
    aborted = t.write(
        str(tmp_path / "a.json"), aborted=True, abort_reason="signal:TERM"
    )
    assert aborted["aborted"] is True
    assert aborted["abort_reason"] == "signal:TERM"
    assert aborted["stage_at_abort"] == "searching"
    assert aborted["progress_at_abort"]["done"] == 2.0
    assert obs.load_manifest(str(tmp_path / "a.json"))["aborted"] is True


# --------------------------------------------------------------------------
# heartbeat + stall watchdog
# --------------------------------------------------------------------------

def test_heartbeat_snapshots_progress(tmp_path):
    t = obs.RunTelemetry(run_id="hb")
    path = str(tmp_path / "status.json")
    hb = obs.Heartbeat(t, path, interval=0.02, stall_timeout=100.0)
    with hb:
        t.set_stage("searching")
        t.set_progress(1, 10, unit="chunks")
        time.sleep(0.1)
        s1 = obs.load_status(path)
        t.set_progress(6, 10, unit="chunks")
        time.sleep(0.1)
        s2 = obs.load_status(path)
    final = obs.load_status(path)
    assert s1["schema"] == obs.STATUS_SCHEMA
    assert s2["seq"] > s1["seq"]
    assert s2["progress"]["done"] > s1["progress"]["done"]
    assert s2["progress"]["frac"] == pytest.approx(0.6)
    assert s2["progress"]["rate_per_s"] > 0
    assert s2["progress"]["eta_s"] is not None
    assert s2["stage"] == "searching"
    assert final["done"] is True
    assert final["run_id"] == "hb"
    # stopping twice is harmless
    hb.stop()


def test_heartbeat_stall_watchdog(tmp_path):
    t = obs.RunTelemetry(run_id="stall")
    path = str(tmp_path / "status.json")
    hb = obs.Heartbeat(t, path, interval=0.02, stall_timeout=0.08)
    with hb:
        t.set_stage("searching")
        t.set_progress(1, 10)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if any(e["kind"] == "stall" for e in t.events):
                break
            time.sleep(0.02)
        st = obs.load_status(path)
        assert st["stalled"] is True
        stall = next(e for e in t.events if e["kind"] == "stall")
        assert stall["stage"] == "searching"
        assert stall["stalled_for_s"] >= 0.08
        # exactly one stall event per episode (no oscillation)
        time.sleep(0.2)
        assert sum(e["kind"] == "stall" for e in t.events) == 1
        # progress resumes -> recovery event, stalled clears
        t.set_progress(2, 10)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if any(e["kind"] == "stall_recovered" for e in t.events):
                break
            time.sleep(0.02)
        assert any(e["kind"] == "stall_recovered" for e in t.events)
        time.sleep(0.06)
        assert obs.load_status(path)["stalled"] is False


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------

def test_flight_ring_is_bounded_and_dump_writes_both(tmp_path):
    t = obs.RunTelemetry(run_id="fr")
    t.set_context(command="unit")
    t.set_stage("searching")
    t.set_progress(4, 9, unit="chunks")
    fpath = str(tmp_path / "flight.json")
    mpath = str(tmp_path / "telemetry.json")
    fr = obs.FlightRecorder(t, fpath, manifest_path=mpath, ring=64)
    for i in range(200):
        t.event("tick", i=i)
    doc = fr.dump("unit-test")
    fr.close()
    assert fr.dump("again") is None  # at most once
    flight = obs.load_flight(fpath)
    assert flight["schema"] == obs.FLIGHT_SCHEMA
    assert flight["reason"] == "unit-test"
    assert flight["stage"] == "searching"
    assert flight["progress"]["done"] == 4.0
    ticks = [e for e in flight["events"] if e["kind"] == "tick"]
    assert len(flight["events"]) == 64  # ring bound
    assert ticks[-1]["i"] == 199  # ... keeping the most recent
    man = obs.load_manifest(mpath)
    assert man["aborted"] is True
    assert man["abort_reason"] == "unit-test"
    assert doc["run_id"] == "fr"


def test_live_observability_dumps_on_exception(tmp_path):
    t = obs.RunTelemetry(run_id="exc")
    prev_term = signal.getsignal(signal.SIGTERM)
    mpath = str(tmp_path / "telemetry.json")
    with pytest.raises(RuntimeError, match="boom"):
        with live_observability(
            t,
            _args(status_json=str(tmp_path / "status.json")),
            str(tmp_path),
            mpath,
        ):
            t.event("before_crash")
            raise RuntimeError("boom")
    flight = obs.load_flight(str(tmp_path / "flight.json"))
    assert flight["reason"] == "exception:RuntimeError"
    assert "boom" in flight["exception"]
    assert any(e["kind"] == "before_crash" for e in flight["events"])
    assert obs.load_manifest(mpath)["aborted"] is True
    # heartbeat left a final snapshot; handlers were restored
    assert obs.load_status(str(tmp_path / "status.json"))["done"] is True
    assert signal.getsignal(signal.SIGTERM) is prev_term


def test_live_observability_clean_exit_leaves_no_flight(tmp_path):
    t = obs.RunTelemetry(run_id="clean")
    with live_observability(t, _args(), str(tmp_path), None):
        t.event("fine")
    assert not (tmp_path / "flight.json").exists()


# --------------------------------------------------------------------------
# end-to-end: heartbeat through the peasoup CLI, SIGTERM forensics
# --------------------------------------------------------------------------

def test_e2e_status_json_snapshots(tmp_path):
    """Acceptance: a tiny end-to-end run with --status-json produces at
    least two distinct snapshots with progress advancing between them."""
    from peasoup_tpu.cli.peasoup import main as peasoup_main

    path, _, _ = make_synthetic_fil(tmp_path)
    outdir = tmp_path / "out"
    status = tmp_path / "status.json"
    snaps: dict[int, dict] = {}
    stop = threading.Event()

    def watcher():
        while not stop.is_set():
            try:
                with open(status) as f:
                    st = json.load(f)
                snaps[st["seq"]] = st
            except (OSError, json.JSONDecodeError, KeyError):
                pass
            time.sleep(0.005)

    th = threading.Thread(target=watcher, daemon=True)
    th.start()
    try:
        rc = peasoup_main(
            ["-i", str(path), "-o", str(outdir), "--dm_end", "40",
             "-n", "2", "--limit", "20",
             "--status-json", str(status),
             "--heartbeat-interval", "0.02"]
        )
    finally:
        stop.set()
        th.join(timeout=5)
    assert rc == 0
    final = obs.load_status(str(status))
    snaps[final["seq"]] = final
    assert len(snaps) >= 2, "expected at least two distinct snapshots"
    first = snaps[min(snaps)]
    last = snaps[max(snaps)]
    assert last["done"] is True
    # progress advanced between the snapshots: the first beat fires
    # before the search loop (no/zero progress), the last carries the
    # completed chunk counter
    assert last["progress"] is not None
    assert last["progress"]["done"] == last["progress"]["total"] > 0
    assert (
        first.get("progress") is None
        or first["progress"]["done"] < last["progress"]["done"]
        or first["stage"] != last["stage"]
    )
    # the searching stage was visible live in at least one snapshot
    stages = {s.get("stage") for s in snaps.values()}
    assert "searching" in stages or "done" in stages
    # clean exit: no flight dump, manifest not marked aborted
    assert not (outdir / "flight.json").exists()
    man = obs.load_manifest(str(outdir / "telemetry.json"))
    assert "aborted" not in man
    kinds = [e["kind"] for e in man["events"]]
    assert "stage" in kinds
    assert "pallas_peaks_sub" in kinds


def test_sigterm_leaves_flight_and_aborted_manifest(tmp_path):
    """Acceptance: a SIGTERM'd run leaves flight.json + a partial
    manifest marked aborted (real process, real signal)."""
    path, _, _ = make_synthetic_fil(tmp_path)
    outdir = tmp_path / "out"
    outdir.mkdir()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    worker = os.path.join(os.path.dirname(__file__), "abort_worker.py")
    proc = subprocess.Popen(
        [sys.executable, worker, str(path), str(outdir)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        # the heartbeat's first snapshot lands only after the flight
        # recorder is armed (live_observability orders it so): once
        # status.json exists, SIGTERM forensics are guaranteed
        status = outdir / "status.json"
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and proc.poll() is None:
            if status.exists():
                break
            time.sleep(0.05)
        assert status.exists(), "run never wrote a heartbeat"
        time.sleep(0.2)  # let the run get properly underway
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    stderr = proc.stderr.read().decode("utf-8", "replace")
    assert proc.returncode == -signal.SIGTERM, (
        f"expected SIGTERM death, got rc={proc.returncode}; "
        f"stderr tail: {stderr[-800:]}"
    )
    flight = obs.load_flight(str(outdir / "flight.json"))
    assert flight["reason"] == "signal:SIGTERM"
    assert flight["signum"] == int(signal.SIGTERM)
    man = obs.load_manifest(str(outdir / "telemetry.json"))
    assert man["aborted"] is True
    assert man["abort_reason"] == "signal:SIGTERM"
    # the partial manifest is schema-valid and renders like any other
    obs.validate_manifest(man)
    from peasoup_tpu.tools.report import render

    assert "ABORTED" in render(man)


# --------------------------------------------------------------------------
# multi-host shard merging + straggler stats
# --------------------------------------------------------------------------

def _shard(tmp_path, idx, hostname, timers, run_id="merge-run"):
    t = obs.RunTelemetry(run_id=f"{run_id}-p{idx}")
    t.set_context(command="peasoup", process_index=idx)
    for k, v in timers.items():
        t.add_timer(k, v)
    t.incr("search.dm_trials_done", 50 + idx)
    t.gauge("memory.peak_bytes", 1e9 * (1 + idx))
    t.event("multihost_slice", process=idx)
    man = t.to_manifest()
    man["process_index"] = idx
    man["process_count"] = 2
    man["hostname"] = hostname
    man["duration_s"] = timers.get("searching", 1.0) + 1.0
    p = tmp_path / f"telemetry.proc{idx}.json"
    p.write_text(json.dumps(man))
    return str(p)


def test_report_merge_straggler_stats(tmp_path, capsys):
    """Acceptance: merging >=2 per-host shards produces one manifest
    with per-host straggler statistics."""
    from peasoup_tpu.tools.report import main as report_main

    a = _shard(tmp_path, 0, "host-a",
               {"searching": 10.0, "dedispersion": 2.0, "total": 13.0})
    b = _shard(tmp_path, 1, "host-b",
               {"searching": 14.0, "dedispersion": 2.5, "total": 17.5})
    merged_path = tmp_path / "merged.json"
    rc = report_main(["--merge", a, b, "-o", str(merged_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "straggler" in out
    assert "host-b" in out

    merged = obs.load_manifest(str(merged_path))
    obs.validate_manifest(merged)
    assert merged["merged"] is True
    assert merged["n_hosts"] == 2
    assert [h["process_index"] for h in merged["hosts"]] == [0, 1]
    # timers: max across hosts (a stage finishes with its slowest host)
    assert merged["timers"]["searching"] == 14.0
    # counters sum, gauges high-water
    assert merged["counters"]["search.dm_trials_done"] == 101
    assert merged["gauges"]["memory.peak_bytes"] == 2e9
    strag = merged["straggler"]["timers"]["searching"]
    assert strag["min"] == 10.0 and strag["max"] == 14.0
    assert strag["spread"] == pytest.approx(4.0)
    assert strag["mean"] == pytest.approx(12.0)
    assert strag["slowest"] == {
        "process_index": 1, "hostname": "host-b",
    }
    imb = merged["straggler"]["imbalance"]
    assert imb["slowest"]["hostname"] == "host-b"
    assert imb["ratio"] > 1.0
    # merged events carry their host tag, in time order
    assert all("process_index" in e for e in merged["events"])
    # the merged manifest renders like any other
    rc = report_main([str(merged_path)])
    assert rc == 0
    assert "hosts (2)" in capsys.readouterr().out


def test_report_merge_skips_shards_missing_a_stage(tmp_path):
    """Satellite regression: a shard that never reached a stage
    (aborted early, older writer, partial manifest) is SKIPPED in that
    stage's straggler entry — no KeyError, no phantom 0.0 ranked as
    the fastest host — and recorded as missing; a shard without
    duration_s stays out of the imbalance ranking; non-numeric timer
    values are dropped rather than poisoning the math."""
    from peasoup_tpu.tools.report import merge_manifests

    a = json.loads(open(_shard(
        tmp_path, 0, "host-a",
        {"searching": 10.0, "dedispersion": 2.0})).read())
    b = json.loads(open(_shard(
        tmp_path, 1, "host-b",
        {"searching": 14.0, "dedispersion": 2.5})).read())
    c = json.loads(open(_shard(
        tmp_path, 2, "host-c",
        {"dedispersion": 1.0})).read())
    # host-c aborted before the searching stage: no timer, no duration,
    # and one corrupted timer value
    del c["duration_s"]
    c["timers"]["plan"] = "corrupt"
    c["aborted"] = True

    merged = merge_manifests([a, b, c])
    obs.validate_manifest(merged)

    strag = merged["straggler"]["timers"]["searching"]
    assert strag["n_hosts"] == 2
    assert strag["min"] == 10.0  # NOT 0.0 from the missing shard
    assert strag["slowest"] == {"process_index": 1, "hostname": "host-b"}
    assert strag["missing"] == [
        {"process_index": 2, "hostname": "host-c"}
    ]
    # all three hosts carry dedispersion: no missing list there
    ded = merged["straggler"]["timers"]["dedispersion"]
    assert ded["n_hosts"] == 3 and "missing" not in ded
    # the corrupt value neither crashes nor appears anywhere
    assert "plan" not in merged["timers"]
    assert "plan" not in merged["hosts"][2]["timers"]
    # imbalance ranks only hosts that reported a duration
    imb = merged["straggler"]["imbalance"]
    assert imb["slowest"]["hostname"] == "host-b"
    assert imb["mean_s"] == pytest.approx((11.0 + 15.0) / 2)
    # the merged manifest still renders
    from peasoup_tpu.tools.report import render

    assert "host-c" in render(merged)


def test_report_merge_all_shards_partial(tmp_path):
    """Degenerate hardening case: EVERY shard lacks duration_s — the
    merge must still succeed with a zeroed imbalance block."""
    from peasoup_tpu.tools.report import merge_manifests

    shards = []
    for i in range(2):
        man = json.loads(
            open(_shard(tmp_path, i, f"h{i}", {"plan": 0.1 * (i + 1)})).read()
        )
        del man["duration_s"]
        shards.append(man)
    merged = merge_manifests(shards)
    obs.validate_manifest(merged)
    assert merged["straggler"]["imbalance"]["ratio"] == 1.0
    assert merged["straggler"]["timers"]["plan"]["n_hosts"] == 2


def test_report_merge_needs_two_shards(tmp_path):
    from peasoup_tpu.tools.report import main as report_main

    a = _shard(tmp_path, 0, "host-a", {"searching": 1.0})
    with pytest.raises(SystemExit):
        report_main(["--merge", a])


# --------------------------------------------------------------------------
# schema validation + older-manifest tolerance
# --------------------------------------------------------------------------

FIXTURE_V1 = os.path.join(
    os.path.dirname(__file__), "data", "manifest_v1.json"
)


def test_schema_validates_fresh_and_fixture(tmp_path):
    t = obs.RunTelemetry(run_id="schema")
    t.incr("c")
    t.gauge("g", 1.0)
    with t.stage("s"):
        pass
    t.event("e", a=1)
    obs.validate_manifest(t.to_manifest())
    obs.validate_manifest(
        t.to_manifest(aborted=True, abort_reason="x")
    )
    obs.validate_manifest(obs.load_manifest(FIXTURE_V1))


def test_schema_rejects_malformed():
    t = obs.RunTelemetry(run_id="bad")
    man = t.to_manifest()
    man["timers"] = {"searching": "fast"}  # must be numeric
    with pytest.raises(obs.SchemaError, match="searching"):
        obs.validate_manifest(man)
    man = t.to_manifest()
    del man["run_id"]
    with pytest.raises(obs.SchemaError, match="run_id"):
        obs.validate_manifest(man)
    with pytest.raises(obs.SchemaError, match="const"):
        obs.validate_manifest({**t.to_manifest(), "schema": "nope"})


def test_validate_manifest_cli(tmp_path, capsys):
    from peasoup_tpu.tools.validate_manifest import main as vmain

    assert vmain(["--fresh", FIXTURE_V1]) == 0
    assert "schema-valid" in capsys.readouterr().out
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": "peasoup_tpu.telemetry"}))
    assert vmain([str(bad)]) == 1


def test_report_tolerates_older_manifests(tmp_path, capsys):
    """Satellite: render/diff must .get() keys newer than a manifest's
    schema version instead of KeyError'ing."""
    from peasoup_tpu.tools.report import diff, main as report_main, render

    # the checked-in v1 fixture renders
    assert report_main([FIXTURE_V1]) == 0
    out = capsys.readouterr().out
    assert "legacy-v1-fixture" in out
    # a BARE minimal manifest (only the keys v1 required) renders and
    # diffs against a modern one without KeyError
    bare = {
        "schema": obs.MANIFEST_SCHEMA,
        "version": 1,
        "run_id": "bare",
        "created_unix": 0.0,
    }
    assert "bare" in render(bare)
    modern = obs.RunTelemetry(run_id="modern")
    modern.add_timer("searching", 1.0)
    text = diff(bare, modern.to_manifest())
    assert "bare" in text and "modern" in text and "(new)" in text
    # and load_manifest accepts v1 files (forward-compat stays rejected:
    # covered by test_obs.test_manifest_rejects_foreign_and_newer)
    assert obs.load_manifest(FIXTURE_V1)["version"] == 1


# --------------------------------------------------------------------------
# watch tool
# --------------------------------------------------------------------------

def test_watch_once_renders(tmp_path, capsys):
    from peasoup_tpu.tools.watch import main as watch_main

    t = obs.RunTelemetry(run_id="watched")
    t.set_stage("searching")
    t.set_progress(3, 12, unit="chunks")
    t.event("wave_plan", n_waves=2)
    path = str(tmp_path / "status.json")
    hb = obs.Heartbeat(t, path, interval=60.0, stall_timeout=0)
    hb.start()
    hb.stop()
    assert watch_main(["--once", path]) == 0
    out = capsys.readouterr().out
    assert "watched" in out
    assert "stage=searching" in out
    assert "chunks" in out
    assert "wave_plan" in out
    assert "run complete" in out  # final snapshot carries done
    # missing file: --once fails fast
    assert watch_main(["--once", str(tmp_path / "nope.json")]) == 1


def test_watch_campaign_rollup_renders(tmp_path, capsys):
    """Satellite: watch pointed at a campaign directory (or its
    campaign_status.json) renders the survey rollup — queue depths,
    retrying jobs with errors, quarantine — and detects the snapshot
    kind by schema, so one invocation works on both."""
    from peasoup_tpu.campaign.queue import Job, JobQueue
    from peasoup_tpu.campaign.rollup import write_status
    from peasoup_tpu.tools.watch import main as watch_main

    root = str(tmp_path / "camp")
    q = JobQueue(root, lease_s=30.0, max_attempts=2, backoff_base_s=60.0)
    for i in range(3):
        q.add_job(Job(job_id=f"job{i}", input=f"obs{i}.fil"))
    q.complete(q.try_claim("job0", "w1"), n_candidates=5)
    q.fail(q.try_claim("job1", "w1"), "flaky io")
    q.fail(q.try_claim("job1", "w1", now=time.time() + 120), "flaky io")
    write_status(root, q)

    # directory argument resolves to the rollup inside it
    assert watch_main(["--once", root]) == 0
    out = capsys.readouterr().out
    assert "campaign" in out
    assert "1/3 done" in out
    assert "quarantined=1" in out
    assert "QUARANTINED job1" in out and "flaky io" in out

    # the explicit file path works too, and a drained campaign says so
    q.complete(q.try_claim("job2", "w2"), n_candidates=1)
    q.retry("job1")
    q.complete(q.try_claim("job1", "w2"), n_candidates=0)
    write_status(root, q)
    assert watch_main(
        ["--once", os.path.join(root, "campaign_status.json")]
    ) == 0
    out = capsys.readouterr().out
    assert "3/3 done" in out
    assert "campaign complete" in out


# --------------------------------------------------------------------------
# satellites: Stopwatch context manager, peaks probe resolution, flags
# --------------------------------------------------------------------------

def test_stopwatch_context_manager_and_named_double_stop():
    with Stopwatch("DM-Loop") as sw:
        time.sleep(0.001)
    assert sw.elapsed > 0.0
    with pytest.raises(RuntimeError, match="DM-Loop"):
        sw.stop()  # second stop: clear error naming the span
    # unnamed stopwatches still raise clearly
    with pytest.raises(RuntimeError, match="not running"):
        Stopwatch().stop()
    # accumulation across with-blocks is preserved
    first = sw.elapsed
    with sw:
        pass
    assert sw.elapsed >= first


def test_trace_span_names_its_stopwatch():
    from peasoup_tpu.utils import trace_span

    sw = Stopwatch()
    with trace_span("Acceleration-Loop", sw):
        pass
    assert sw.name == "Acceleration-Loop"
    with pytest.raises(RuntimeError, match="Acceleration-Loop"):
        sw.stop()


def test_peaks_sub_resolution_recorded():
    from peasoup_tpu.ops.pallas import peaks

    res = peaks.SUB_RESOLUTION
    assert res["sub"] in (8, 24) or res["sub"] % 8 == 0
    assert res["source"] in ("env", "probe")
    if res["source"] == "probe":
        # conftest pins JAX_PLATFORMS=cpu, so the cpu shortcut (or a
        # cached verdict) resolved it — either way the verdict is there
        assert "verdict" in res


@pytest.mark.parametrize("which", ["peasoup", "ffa", "coincidencer"])
def test_cli_live_flags_plumbed(which):
    if which == "peasoup":
        from peasoup_tpu.cli.peasoup import build_parser

        base = ["-i", "x.fil"]
    elif which == "ffa":
        from peasoup_tpu.cli.ffa import build_parser

        base = ["-i", "x.fil"]
    else:
        from peasoup_tpu.cli.coincidencer import build_parser

        base = ["a.fil", "b.fil"]
    args = build_parser().parse_args(
        base + ["--status-json", "s.json", "--heartbeat-interval",
                "0.5", "--no-flight-recorder"]
    )
    assert args.status_json == "s.json"
    assert args.heartbeat_interval == 0.5
    assert args.no_flight_recorder is True
    args = build_parser().parse_args(base)
    assert args.status_json is None
    assert args.heartbeat_interval == 5.0
    assert args.no_flight_recorder is False
