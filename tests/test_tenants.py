"""Multi-tenant survey service tests (ISSUE 17): the file-backed
tenant registry and quota spec, quota-checked admission through the
submission front end (CLI/HTTP/watch-folder) with its append-only
journal, claim-time throttling (max_running and the rolling
device-seconds budget) with release, the per-tenant usage ledger,
per-tenant alert scoping/routing, journal rotation with the
restart-no-refire guarantee, the incremental sift watermark, and the
cross-tenant warm-bucket zero-recompile acceptance run."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from peasoup_tpu.campaign.ingest import (
    ingest_watch_folders,
    read_submissions,
    submit_observation,
    submissions_path,
)
from peasoup_tpu.campaign.queue import Job, JobQueue, job_id_for
from peasoup_tpu.campaign.rollup import build_status, write_status
from peasoup_tpu.campaign.tenants import (
    Tenant,
    TenantRegistry,
    throttle_map,
    valid_tenant_name,
)
from peasoup_tpu.campaign.usage import build_usage, load_usage
from peasoup_tpu.obs.alerts import (
    AlertEngine,
    default_rules,
    evaluate_campaign,
    tenant_journal_path,
)
from peasoup_tpu.obs.metrics import rotate_journal


def _tenant_rules():
    return [r for r in default_rules() if r.get("route") == "tenant"]


def _quota_rule():
    [r] = [r for r in _tenant_rules() if r["kind"] == "tenant_quota"]
    return r


def _done_record(root, job_id, tenant, finished, duration,
                 bytes_read=0, compiled=0, attempts=1, n_candidates=0):
    """A synthetic done record planted straight into queue/done/ —
    the raw artifact usage and the budget window are rolled from."""
    ddir = os.path.join(root, "queue", "done")
    os.makedirs(ddir, exist_ok=True)
    with open(os.path.join(ddir, f"{job_id}.json"), "w") as f:
        json.dump({
            "job_id": job_id, "tenant": tenant,
            "finished_unix": finished, "duration_s": duration,
            "bytes_read": bytes_read,
            "jit_programs_compiled": compiled,
            "attempts": attempts, "n_candidates": n_candidates,
        }, f)


def _obs_file(tmp_path, name="obs.fil", seed=0):
    from test_campaign import make_obs

    return make_obs(str(tmp_path / name), nsamps=4096, seed=seed)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

class TestTenantRegistry:
    def test_create_mints_token_and_collides_o_excl(self, tmp_path):
        reg = TenantRegistry(str(tmp_path))
        t = reg.create(Tenant(name="alice", max_running=2))
        assert t.token and len(t.token) == 32
        with pytest.raises(FileExistsError):
            reg.create(Tenant(name="alice"))
        got = reg.get("alice")
        assert got.max_running == 2 and got.token == t.token

    def test_by_token_constant_time_lookup(self, tmp_path):
        reg = TenantRegistry(str(tmp_path))
        a = reg.create(Tenant(name="alice"))
        reg.create(Tenant(name="bob"))
        assert reg.by_token(a.token).name == "alice"
        assert reg.by_token("") is None
        assert reg.by_token("not-a-token") is None

    def test_update_and_remove(self, tmp_path):
        reg = TenantRegistry(str(tmp_path))
        t = reg.create(Tenant(name="alice"))
        t.max_queued = 7
        reg.update(t)
        assert reg.get("alice").max_queued == 7
        assert reg.remove("alice") is True
        assert reg.get("alice") is None
        assert reg.remove("alice") is False

    def test_names_are_filesystem_safe(self, tmp_path):
        assert valid_tenant_name("survey-A_2")
        for bad in ("", "a/b", "..", ".hidden", "a.b",
                    "x" * 49, "a b"):
            assert not valid_tenant_name(bad)
        with pytest.raises(ValueError):
            TenantRegistry(str(tmp_path)).create(Tenant(name="a/b"))


# --------------------------------------------------------------------------
# admission + journal
# --------------------------------------------------------------------------

class TestAdmission:
    def test_every_decision_is_journaled(self, tmp_path):
        root = str(tmp_path / "camp")
        reg = TenantRegistry(root)
        reg.create(Tenant(name="alice", max_queued=1, priority_max=2))
        obs = _obs_file(tmp_path, "a0.fil")
        obs2 = _obs_file(tmp_path, "a1.fil")

        # unknown tenant
        e = submit_observation(root, "nobody", obs)
        assert not e["accepted"] and "unknown tenant" in e["reason"]
        # missing input
        e = submit_observation(root, "alice", str(tmp_path / "no.fil"))
        assert not e["accepted"] and "not found" in e["reason"]
        # accepted, priority clamped to the ceiling (never rejected)
        e = submit_observation(root, "alice", obs, priority=9)
        assert e["accepted"] and e["priority_capped"]
        assert e["priority"] == 2
        q = JobQueue(root)
        job = q.get_job(e["job_id"])
        assert job.tenant == "alice" and job.priority == 2
        # duplicate
        e = submit_observation(root, "alice", obs)
        assert not e["accepted"] and "duplicate" in e["reason"]
        # max_queued ceiling
        e = submit_observation(root, "alice", obs2)
        assert not e["accepted"] and "max_queued" in e["reason"]

        journal = read_submissions(root)
        assert len(journal) == 5
        assert [j["accepted"] for j in journal] == [
            False, False, True, False, False,
        ]
        assert all(j["via"] == "cli" and "t_unix" in j for j in journal)

    def test_torn_journal_tail_is_skipped(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(name="alice"))
        submit_observation(root, "alice", "/nope.fil")
        with open(submissions_path(root), "a") as f:
            f.write('{"torn": ')
        assert len(read_submissions(root)) == 1

    def test_watch_folder_submits_fresh_drops_silently_skips_known(
        self, tmp_path
    ):
        root = str(tmp_path / "camp")
        wdir = tmp_path / "drop"
        wdir.mkdir()
        TenantRegistry(root).create(
            Tenant(name="alice", watch_dir=str(wdir))
        )
        obs = _obs_file(wdir, "fresh.fil")
        (wdir / "notes.txt").write_text("ignored")
        out = ingest_watch_folders(root)
        assert [e["accepted"] for e in out] == [True]
        assert out[0]["via"] == "watch"
        assert JobQueue(root).get_job(job_id_for(obs)) is not None
        # the second poll sees nothing new and journals NOTHING
        n = len(read_submissions(root))
        assert ingest_watch_folders(root) == []
        assert len(read_submissions(root)) == n

    def test_cli_ingest_folder_door(self, tmp_path, capsys):
        # drive the actual CLI entry point (one-shot and bounded-poll
        # modes), not just the library function behind it
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path / "camp")
        wdir = tmp_path / "drop"
        wdir.mkdir()
        TenantRegistry(root).create(
            Tenant(name="alice", watch_dir=str(wdir))
        )
        obs = _obs_file(wdir, "fresh.fil")
        assert main(["ingest-folder", "-w", root]) == 0
        assert "accepted" in capsys.readouterr().out
        assert JobQueue(root).get_job(job_id_for(obs)) is not None
        assert main([
            "ingest-folder", "-w", root,
            "--poll", "0.05", "--max-runtime", "0.15",
        ]) == 0


# --------------------------------------------------------------------------
# claim-time throttling
# --------------------------------------------------------------------------

class TestThrottle:
    def test_max_running_parks_then_releases(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(name="alice", max_running=1))
        q = JobQueue(root)
        for i in range(2):
            q.add_job(Job(job_id=f"j{i}", input=f"/x{i}.fil",
                          tenant="alice"))
        t0 = time.time()
        c = q.try_claim("j0", "w1", now=t0)
        assert c is not None
        # past the throttle cache TTL: the second job parks
        t1 = t0 + 0.6
        assert q.try_claim("j1", "w2", now=t1) is None
        assert q.state("j1", now=t1) == "throttled"
        assert q.counts()["throttled"] == 1
        # completion frees the slot; the parked job claims
        q.complete(c, duration_s=0.1)
        t2 = t0 + 1.2
        assert q.state("j1", now=t2) == "pending"
        assert q.try_claim("j1", "w2", now=t2) is not None

    def test_claim_revalidation_excludes_own_unwritten_claim(
        self, tmp_path
    ):
        # a tenant with max_running=1 and ONE job: the winner's own
        # in-flight claim must not count against the quota
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(name="alice", max_running=1))
        q = JobQueue(root)
        q.add_job(Job(job_id="j0", input="/x.fil", tenant="alice"))
        assert q.try_claim("j0", "w1") is not None

    def test_device_seconds_budget_slides(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(
            name="alice", device_seconds=10.0, window_s=100.0,
        ))
        now = 1_000_000.0
        _done_record(root, "old", "alice", now - 50.0, 20.0)
        m = throttle_map(root, now=now)
        assert m["alice"]["quota"] == "device_seconds"
        assert m["alice"]["spent_device_s"] == 20.0
        # the window slides past the spend: throttle releases
        assert throttle_map(root, now=now + 200.0) == {}
        # another tenant is unaffected
        TenantRegistry(root).create(Tenant(
            name="bob", device_seconds=10.0, window_s=100.0,
        ))
        assert "bob" not in throttle_map(root, now=now)

    def test_unlimited_tenant_never_throttles(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(name="alice"))
        now = 1_000_000.0
        _done_record(root, "d0", "alice", now - 1.0, 9999.0)
        assert throttle_map(root, now=now) == {}


# --------------------------------------------------------------------------
# usage ledger
# --------------------------------------------------------------------------

class TestUsageLedger:
    def test_totals_roll_from_done_records(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(
            name="alice", device_seconds=100.0, window_s=50.0,
        ))
        now = 1_000_000.0
        _done_record(root, "d0", "alice", now - 10.0, 3.0,
                     bytes_read=100, compiled=5, attempts=2,
                     n_candidates=7)
        _done_record(root, "d1", "alice", now - 200.0, 4.0,
                     bytes_read=50, compiled=0, n_candidates=1)
        doc = build_usage(root, now=now)
        u = doc["tenants"]["alice"]
        assert u["jobs_done"] == 2
        assert u["device_seconds"] == 7.0
        assert u["bytes_read"] == 150
        assert u["jit_programs_compiled"] == 5
        assert u["candidates"] == 8
        # d0 took 2 attempts: one was a failure
        assert u["jobs_failed"] == 1
        # the rolling window only sees d0 (d1 is 200s old, window 50s)
        assert u["window"]["device_seconds"] == 3.0
        assert u["window"]["budget"] == 100.0

    def test_unregistered_stamp_still_accounts(self, tmp_path):
        root = str(tmp_path / "camp")
        os.makedirs(os.path.join(root, "queue"), exist_ok=True)
        _done_record(root, "d0", "ghost", 1.0, 2.0)
        doc = build_usage(root)
        assert doc["tenants"]["ghost"]["jobs_done"] == 1

    def test_write_usage_rides_the_rollup(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(name="alice"))
        q = JobQueue(root)
        q.add_job(Job(job_id="j0", input="/x.fil", tenant="alice"))
        _done_record(root, "j0", "alice", time.time(), 1.5)
        st = write_status(root, queue=q)
        assert "alice" in st["tenants"]
        ledger = load_usage(root)
        assert ledger["tenants"]["alice"]["device_seconds"] == 1.5

    def test_rollup_tenants_section_counts_states(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(name="alice", max_running=1))
        q = JobQueue(root)
        for i in range(3):
            q.add_job(Job(job_id=f"j{i}", input=f"/x{i}.fil",
                          tenant="alice"))
        assert q.try_claim("j0", "w1") is not None
        time.sleep(0.6)  # past the throttle cache TTL
        st = build_status(root, queue=JobQueue(root))
        rec = st["tenants"]["alice"]
        assert rec["running"] == 1
        assert rec["throttled"] == 2
        assert rec["throttle"] and "max_running" in rec["throttle"]
        assert rec["quota"]["max_running"] == 1

    def test_pre_tenant_rollup_schema_tolerated(self, tmp_path):
        from peasoup_tpu.tools.watch import render_campaign_status

        # a status doc written before the tenants/usage sections
        out = render_campaign_status({"queue": {"total": 1, "done": 1}})
        assert "tenants" not in out
        out = render_campaign_status({
            "queue": {"total": 2, "done": 0, "throttled": 2},
            "tenants": {"alice": {
                "queued": 0, "throttled": 2,
                "window_device_s": 5.0, "device_s_budget": 10.0,
                "throttle": "max_running reached (1/1)",
            }},
            "usage": {"alice": {"jobs_failed": 3}},
        })
        assert "throttled=2" in out
        assert "alice" in out and "THROTTLED" in out
        assert "dev-s 5.0/10" in out and "failed=3" in out


# --------------------------------------------------------------------------
# per-tenant alert scoping + routing
# --------------------------------------------------------------------------

class TestAlertRouting:
    def _findings(self, *names):
        return [
            {"labels": {"tenant": n}, "value": 1.0,
             "message": f"{n} over quota"}
            for n in names
        ]

    def test_quota_rule_fires_per_tenant_and_routes(self, tmp_path):
        root = str(tmp_path)
        eng = AlertEngine(root, rules=[_quota_rule()])
        s = eng.evaluate(samples={}, now=100.0,
                         tenant_findings=self._findings("alice", "bob"))
        by_tenant = {
            a["labels"]["tenant"]: a["state"] for a in s["alerts"]
        }
        assert by_tenant == {"alice": "firing", "bob": "firing"}
        # each tenant got its own journal, beside the fleet journal
        for name in ("alice", "bob"):
            lines = [
                json.loads(ln) for ln in
                open(tenant_journal_path(root, name))
            ]
            assert [t["to"] for t in lines] == ["pending", "firing"]
            assert all(
                t["labels"]["tenant"] == name for t in lines
            )
        fleet = open(os.path.join(root, "queue", "alerts.jsonl")).read()
        assert fleet.count('"to":"firing"') == 2
        # release: resolution routes too
        s = eng.evaluate(samples={}, now=200.0,
                         tenant_findings=self._findings("bob"))
        states = {
            a["labels"]["tenant"]: a["state"] for a in s["alerts"]
        }
        assert states["alice"] == "resolved"
        assert states["bob"] == "firing"
        lines = [
            json.loads(ln) for ln in
            open(tenant_journal_path(root, "alice"))
        ]
        assert [t["to"] for t in lines] == [
            "pending", "firing", "resolved",
        ]

    def test_evaluate_campaign_derives_quota_findings(self, tmp_path):
        root = str(tmp_path / "camp")
        TenantRegistry(root).create(Tenant(name="alice", max_running=1))
        q = JobQueue(root)
        q.add_job(Job(job_id="j0", input="/x.fil", tenant="alice"))
        assert q.try_claim("j0", "w1") is not None
        snap = evaluate_campaign(root)
        hits = [
            a for a in snap["alerts"]
            if a["rule"] == "tenant_quota_exhausted"
        ]
        assert len(hits) == 1
        assert hits[0]["labels"]["tenant"] == "alice"
        assert hits[0]["state"] == "firing"
        assert os.path.exists(tenant_journal_path(root, "alice"))

    def test_tenant_burn_rate_groups_by_label(self, tmp_path):
        [rule] = [
            r for r in _tenant_rules() if r["kind"] == "burn_rate"
        ]
        eng = AlertEngine(str(tmp_path), rules=[rule])

        def counter(t, name, value, tenant):
            return {"t": t, "kind": "counter", "name": name,
                    "value": value, "labels": {"tenant": tenant}}

        now = 10_000.0
        samples = {"w0": []}
        for i, t in enumerate(
            [now - 1700 + 100 * k for k in range(17)]
        ):
            # alice burns (every job fails); bob is healthy
            samples["w0"].append(
                counter(t, "jobs_failed_total", float(i), "alice"))
            samples["w0"].append(
                counter(t, "jobs_done_total", 0.0, "alice"))
            samples["w0"].append(
                counter(t, "jobs_failed_total", 0.0, "bob"))
            samples["w0"].append(
                counter(t, "jobs_done_total", float(i), "bob"))
        s = eng.evaluate(samples=samples, now=now)
        assert [
            (a["labels"]["tenant"], a["state"]) for a in s["alerts"]
        ] == [("alice", "firing")]
        assert "[tenant=alice]" in s["alerts"][0]["message"]


# --------------------------------------------------------------------------
# journal rotation + restart-no-refire
# --------------------------------------------------------------------------

class TestJournalRotation:
    def test_rotation_keeps_newest_complete_lines(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        with open(path, "w") as f:
            for i in range(200):
                f.write(json.dumps({"i": i, "pad": "x" * 90}) + "\n")
        size = os.path.getsize(path)
        assert rotate_journal(path, max_bytes=size + 1) is False
        assert rotate_journal(path, max_bytes=size // 2) is True
        kept = [json.loads(ln) for ln in open(path)]
        assert kept  # tail survived
        assert kept[-1]["i"] == 199  # newest line kept
        assert kept[0]["i"] > 0  # oldest rotated away
        assert [r["i"] for r in kept] == list(
            range(kept[0]["i"], 200)
        )  # contiguous: no torn line at the cut

    def test_rotation_is_restart_no_refire_safe(self, tmp_path):
        root = str(tmp_path)
        rule = _quota_rule()
        eng = AlertEngine(root, rules=[rule])
        findings = [{"labels": {"tenant": "alice"}, "value": 1.0,
                     "message": "over"}]
        eng.evaluate(samples={}, now=100.0, tenant_findings=findings)
        fleet = os.path.join(root, "queue", "alerts.jsonl")
        tj = tenant_journal_path(root, "alice")
        assert rotate_journal(fleet, max_bytes=1, keep_bytes=1) is True
        assert rotate_journal(tj, max_bytes=1, keep_bytes=1) is True
        # a fresh engine (restart) restores state from the SNAPSHOT,
        # not the journal: the still-true condition must not re-fire
        eng2 = AlertEngine(root, rules=[rule])
        s = eng2.evaluate(samples={}, now=200.0,
                          tenant_findings=findings)
        assert s["alerts"][0]["state"] == "firing"
        assert open(fleet).read().count('"to":"firing"') == 0
        assert open(tj).read().count('"to":"firing"') == 0

    def test_prune_journals_cli(self, tmp_path):
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path / "camp")
        qdir = os.path.join(root, "queue")
        os.makedirs(qdir)
        names = ("alerts.jsonl", "submissions.jsonl",
                 "alerts.alice.jsonl")
        for name in names:
            with open(os.path.join(qdir, name), "w") as f:
                for i in range(2000):
                    f.write(json.dumps({"i": i, "pad": "x" * 30})
                            + "\n")
        rc = main(["prune", "-w", root, "--journals",
                   "--max-bytes", "8192"])
        assert rc == 0
        for name in names:
            assert 0 < os.path.getsize(
                os.path.join(qdir, name)
            ) <= 8192


# --------------------------------------------------------------------------
# submission portal
# --------------------------------------------------------------------------

class TestSubmissionPortal:
    N_REQUESTS = 12

    @pytest.fixture()
    def portal(self, tmp_path):
        import socket

        from peasoup_tpu.obs.portal import serve_portal

        root = str(tmp_path / "camp")
        reg = TenantRegistry(root)
        alice = reg.create(Tenant(name="alice", priority_max=1))
        _done_record(root, "d0", "alice", time.time(), 2.0)
        # the obs sits inside the portal's --data-root; anything
        # outside it (tmp_path itself) must bounce off confinement
        (tmp_path / "stage").mkdir()
        obs = _obs_file(tmp_path / "stage")
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        srv = threading.Thread(
            target=serve_portal, args=(root,),
            kwargs={
                "port": port,
                "max_requests": self.N_REQUESTS,
                "data_roots": [str(tmp_path / "stage")],
            },
            daemon=True,
        )
        srv.start()
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(base + "/usage", timeout=2)
                break
            except OSError:
                time.sleep(0.05)
        yield base, root, alice, obs
        for _ in range(self.N_REQUESTS):
            if not srv.is_alive():
                break
            try:
                urllib.request.urlopen(base + "/usage", timeout=1)
            except OSError:
                break
            srv.join(timeout=0.2)
        srv.join(timeout=5)

    def _post(self, base, body, token=None):
        req = urllib.request.Request(
            base + "/submit", data=json.dumps(body).encode(),
            headers={"Authorization": f"Bearer {token}"} if token
            else {},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read() or b"{}")

    def test_submit_and_tenant_pages(self, portal, tmp_path):
        base, root, alice, obs = portal
        # no/bad token -> 401, nothing journaled
        code, _ = self._post(base, {"input": obs})
        assert code == 401
        code, _ = self._post(base, {"input": obs}, token="wrong")
        assert code == 401
        assert read_submissions(root) == []
        # a real, readable file OUTSIDE the data-root/watch_dir
        # allowlist -> 403 (confinement, not existence), journaled as
        # a rejection so the audit trail shows the attempt
        outside = _obs_file(tmp_path, "outside.fil", seed=1)
        code, entry = self._post(
            base, {"input": outside}, token=alice.token
        )
        assert code == 403 and not entry["accepted"]
        assert "data-root" in entry["reason"]
        assert JobQueue(root).get_job(job_id_for(outside)) is None
        # authenticated: accepted, journaled via=http, priority capped
        code, entry = self._post(
            base, {"input": obs, "priority": 5}, token=alice.token
        )
        assert code == 200 and entry["accepted"]
        assert entry["via"] == "http" and entry["priority_capped"]
        job = JobQueue(root).get_job(entry["job_id"])
        assert job.tenant == "alice" and job.priority == 1
        # duplicate -> 409, malformed -> 400
        code, entry = self._post(base, {"input": obs},
                                 token=alice.token)
        assert code == 409 and "duplicate" in entry["reason"]
        code, _ = self._post(base, {"nope": 1}, token=alice.token)
        assert code == 400
        assert len(read_submissions(root)) == 3

        with urllib.request.urlopen(base + "/tenants", timeout=5) as r:
            body = r.read().decode()
        assert "alice" in body and "/tenants/alice" in body
        with urllib.request.urlopen(
            base + "/tenants/alice", timeout=5
        ) as r:
            page = r.read().decode()
        assert "priority_max" in page and "jobs_done" in page
        with urllib.request.urlopen(base + "/usage", timeout=5) as r:
            ledger = json.loads(r.read())
        assert ledger["tenants"]["alice"]["device_seconds"] == 2.0

    def test_unknown_tenant_page_is_404(self, portal):
        base, _, _, _ = portal
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                base + "/tenants/../../etc", timeout=5
            )
        assert exc.value.code == 404


# --------------------------------------------------------------------------
# incremental sift watermark
# --------------------------------------------------------------------------

class TestIncrementalSift:
    def _seed(self, tmp_path):
        from test_sift import seed_campaign

        return seed_campaign(tmp_path)

    def test_noop_until_new_observations_land(self, tmp_path, capsys):
        from peasoup_tpu.campaign.db import CandidateDB
        from peasoup_tpu.cli.sift import main

        camp = self._seed(tmp_path)
        db_path = str(camp / "candidates.sqlite")
        assert main(["run", "-w", str(camp), "--no-fold"]) == 0
        with CandidateDB(db_path) as db:
            run1 = db.latest_sift_run()
            wm = json.loads(run1["config"])["watermark_rowid"]
            assert wm == db.max_observation_rowid() > 0

        # no new observations: --incremental exits 0 touching nothing
        report = camp / "sift"
        before = {
            p: os.path.getmtime(p)
            for p in [str(f) for f in report.rglob("*")]
        }
        assert main(
            ["run", "-w", str(camp), "--no-fold", "--incremental"]
        ) == 0
        assert "nothing to do" in capsys.readouterr().out
        with CandidateDB(db_path) as db:
            # the run row is untouched (latest run wins wholesale, and
            # a no-op must not replace it)
            assert db.latest_sift_run()["run_id"] == run1["run_id"]
        after = {
            p: os.path.getmtime(p)
            for p in [str(f) for f in report.rglob("*")]
        }
        assert after == before

        # one new observation: the incremental run re-sifts
        with CandidateDB(db_path) as db:
            db._conn.execute(
                "INSERT INTO observations (job_id, input, source_name,"
                " tstart, tsamp, nchans, nsamps, ingested_unix) "
                "VALUES ('jobN', '/new.fil', 'NEW', 55002.0, "
                "0.000256, 8, 4096, 0.0)"
            )
            db._conn.commit()
        assert main(
            ["run", "-w", str(camp), "--no-fold", "--incremental"]
        ) == 0
        with CandidateDB(db_path) as db:
            run2 = db.latest_sift_run()
            assert run2["run_id"] != run1["run_id"]
            new_wm = json.loads(run2["config"])["watermark_rowid"]
            assert new_wm > wm

    def test_reingest_bumps_the_watermark(self, tmp_path):
        # INSERT OR REPLACE gives a re-ingested observation a fresh
        # rowid: re-running a job counts as new data for the sift
        from peasoup_tpu.campaign.db import CandidateDB

        camp = self._seed(tmp_path)
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            before = db.max_observation_rowid()
            db._conn.execute(
                "INSERT OR REPLACE INTO observations (job_id, input) "
                "VALUES ('job0', '/re.fil')"
            )
            db._conn.commit()
            assert db.max_observation_rowid() > before


# --------------------------------------------------------------------------
# cross-tenant warm state (ISSUE acceptance)
# --------------------------------------------------------------------------

class TestCrossTenantWarmState:
    def test_second_tenant_in_warm_bucket_compiles_nothing(
        self, tmp_path
    ):
        """Two tenants submit same-bucket observations through the
        front end; one worker runs both. The second job lands in the
        already-warm bucket and must compile ZERO new XLA programs —
        tenancy is an accounting boundary, not a compilation one."""
        from peasoup_tpu.campaign.runner import (
            CampaignConfig,
            CampaignRunner,
            save_campaign_config,
        )

        root = str(tmp_path / "camp")
        reg = TenantRegistry(root)
        reg.create(Tenant(name="alice"))
        reg.create(Tenant(name="bob"))
        save_campaign_config(root, CampaignConfig(
            pipeline="spsearch",
            config={"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6},
            lease_s=30.0, max_attempts=2, backoff_base_s=0.05,
        ))
        # same nchans/nbits and padded nsamps -> one shape bucket
        a = _obs_file(tmp_path, "alice.fil", seed=1)
        b = _obs_file(tmp_path, "bob.fil", seed=2)
        e1 = submit_observation(root, "alice", a)
        e2 = submit_observation(root, "bob", b)
        assert e1["accepted"] and e2["accepted"]

        tally = CampaignRunner(root, worker_id="w1").run(poll_s=0.05)
        assert tally["done"] == 2
        done = sorted(
            JobQueue(root).done_records(),
            key=lambda d: d["finished_unix"],
        )
        assert {d["tenant"] for d in done} == {"alice", "bob"}
        # the second tenant's observation landed in the bucket the
        # first (or the warmup) already compiled: zero new XLA programs
        assert done[1]["jit_programs_compiled"] == 0
        # and the ledger slices compile counts by tenant stamp
        usage = build_usage(root)["tenants"]
        second = done[1]["tenant"]
        assert usage[second]["jit_programs_compiled"] == 0
        assert usage[second]["jobs_done"] == 1
        assert usage[second]["device_seconds"] == pytest.approx(
            done[1]["duration_s"]
        )
        assert usage[second]["bytes_read"] == os.path.getsize(
            done[1]["input"]
        )


# --------------------------------------------------------------------------
# tenant admin CLI (ISSUE 19 satellite): rotate-token / set-quota
# --------------------------------------------------------------------------

class TestTenantAdminCLI:
    def test_rotate_token_invalidates_old_immediately(
        self, tmp_path, capsys
    ):
        """Token rotation takes effect at the next by_token read: the
        registry record is the single source of truth, no cache."""
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path / "camp")
        reg = TenantRegistry(root)
        alice = reg.create(Tenant(name="alice"))
        old = alice.token
        assert main(["tenant", "rotate-token", "alice", "-w", root]) == 0
        out = capsys.readouterr().out
        assert "token rotated" in out and "invalid immediately" in out
        fresh = reg.get("alice")
        assert fresh.token != old
        assert reg.by_token(old) is None
        assert reg.by_token(fresh.token).name == "alice"
        # audited, but the secret never lands in the journal
        [entry] = [
            s for s in read_submissions(root)
            if s.get("kind") == "tenant_admin"
        ]
        assert entry["action"] == "rotate-token"
        assert entry["tenant"] == "alice"
        assert entry["token_suffix"] == fresh.token[-6:]
        journal = open(submissions_path(root)).read()
        assert fresh.token not in journal and old not in journal

    def test_rotate_token_rejected_at_portal(self, tmp_path):
        """End to end through the HTTP front door: a submission with
        the pre-rotation bearer token gets 401, the new token works."""
        import socket

        from peasoup_tpu.cli.campaign import main
        from peasoup_tpu.obs.portal import serve_portal

        root = str(tmp_path / "camp")
        reg = TenantRegistry(root)
        alice = reg.create(Tenant(name="alice"))
        old = alice.token
        (tmp_path / "stage").mkdir()
        obs = _obs_file(tmp_path / "stage")
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        srv = threading.Thread(
            target=serve_portal, args=(root,),
            kwargs={
                "port": port, "max_requests": 4,
                "data_roots": [str(tmp_path / "stage")],
            },
            daemon=True,
        )
        srv.start()
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(base + "/usage", timeout=2)
                break
            except OSError:
                time.sleep(0.05)

        assert main(["tenant", "rotate-token", "alice", "-w", root]) == 0
        new = TenantRegistry(root).get("alice").token

        def post(token):
            req = urllib.request.Request(
                base + "/submit",
                data=json.dumps({"input": obs}).encode(),
                headers={"Authorization": f"Bearer {token}"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                return exc.code, json.loads(exc.read() or b"{}")

        code, body = post(old)
        assert code == 401 and "token" in body.get("error", "")
        code, body = post(new)
        assert code == 200 and body["accepted"]
        srv.join(timeout=5)

    def test_set_quota_edits_only_given_flags(self, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path / "camp")
        reg = TenantRegistry(root)
        reg.create(Tenant(
            name="bob", max_queued=5, max_running=2,
            device_seconds=100.0, window_s=600.0, priority_max=1,
        ))
        assert main([
            "tenant", "set-quota", "bob", "-w", root,
            "--max-running", "4",
        ]) == 0
        t = reg.get("bob")
        assert t.max_running == 4
        # every other quota untouched
        assert t.max_queued == 5 and t.device_seconds == 100.0
        assert t.window_s == 600.0 and t.priority_max == 1
        # -1 clears the priority ceiling
        assert main([
            "tenant", "set-quota", "bob", "-w", root,
            "--priority-max", "-1",
        ]) == 0
        assert reg.get("bob").priority_max is None
        # no flags -> usage error, nothing changed, nothing journaled
        capsys.readouterr()
        assert main(["tenant", "set-quota", "bob", "-w", root]) == 2
        assert "no quota flags" in capsys.readouterr().err
        audits = [
            s for s in read_submissions(root)
            if s.get("kind") == "tenant_admin"
        ]
        assert len(audits) == 2
        assert audits[0]["changes"] == {"max_running": 4}

    def test_admin_actions_require_a_name(self, tmp_path, capsys):
        from peasoup_tpu.cli.campaign import main

        root = str(tmp_path / "camp")
        TenantRegistry(root)
        for action in ("rotate-token", "set-quota", "show", "remove"):
            assert main(["tenant", action, "-w", root]) == 2
            assert "name is required" in capsys.readouterr().err

    def test_portal_tenant_page_hides_admin_entries(self, tmp_path):
        """The tenant page's recent-submissions listing shows real
        submissions, not the admin audit rows (those carry no job)."""
        from peasoup_tpu.cli.campaign import main
        from peasoup_tpu.obs.portal import _tenant_page_body

        root = str(tmp_path / "camp")
        reg = TenantRegistry(root)
        reg.create(Tenant(name="alice"))
        obs = _obs_file(tmp_path)
        submit_observation(root, "alice", obs)
        assert main(["tenant", "rotate-token", "alice", "-w", root]) == 0
        page = _tenant_page_body(root, "alice").decode()
        assert os.path.basename(obs) in page
        assert "tenant_admin" not in page and "rotate-token" not in page
