"""Observability subsystem tests: the telemetry.json manifest schema
(round-trip, versioned, stable keys), the library logger and its CLI
plumbing, and the tools/report.py renderer."""

import json
import logging

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from peasoup_tpu import obs
from peasoup_tpu.obs import telemetry as tele
from test_pipeline import make_synthetic_fil


# --------------------------------------------------------------------------
# RunTelemetry core
# --------------------------------------------------------------------------

def test_manifest_round_trip(tmp_path):
    t = obs.RunTelemetry(run_id="r1")
    t.set_context(command="unit-test")
    t.incr("widgets")
    t.incr("widgets", 2)
    t.gauge("level", 5.0)
    t.gauge_max("peak", 10)
    t.gauge_max("peak", 7)  # high-water: must stay 10
    with t.stage("phase_a"):
        pass
    t.add_timer("phase_a", 1.5)  # accumulates onto the stage timer
    t.event("adaptive_thing", old=1, new=2)
    t.record_jit("/jax/core/compile", 0.25)

    path = str(tmp_path / "telemetry.json")
    written = t.write(path)
    man = obs.load_manifest(path)
    assert man == json.loads(json.dumps(written))  # JSON round-trip
    assert man["schema"] == obs.MANIFEST_SCHEMA
    assert man["version"] == obs.MANIFEST_VERSION
    assert man["run_id"] == "r1"
    assert man["context"]["command"] == "unit-test"
    assert man["counters"]["widgets"] == 3
    assert man["gauges"]["level"] == 5.0
    assert man["gauges"]["peak"] == 10
    assert man["timers"]["phase_a"] >= 1.5
    assert man["jit"]["/jax/core/compile"] == {
        "count": 1, "seconds": 0.25,
    }
    ev = next(e for e in man["events"] if e["kind"] == "adaptive_thing")
    assert ev["old"] == 1 and ev["new"] == 2
    assert ev["t"] >= 0.0  # monotonic offset from run start
    # stable top-level key order: schema/version lead
    assert list(man)[:3] == ["schema", "version", "run_id"]


def test_manifest_rejects_foreign_and_newer(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"schema": "something_else", "version": 1}))
    with pytest.raises(ValueError, match="not a"):
        obs.load_manifest(str(p))
    p.write_text(json.dumps(
        {"schema": obs.MANIFEST_SCHEMA,
         "version": obs.MANIFEST_VERSION + 1}
    ))
    with pytest.raises(ValueError, match="newer"):
        obs.load_manifest(str(p))


def test_current_defaults_to_noop_and_activation_scopes():
    assert obs.current() is obs.NOOP
    assert not obs.NOOP.enabled
    # the noop sink absorbs everything without accumulating state
    obs.NOOP.incr("x")
    obs.NOOP.event("y", a=1)
    with obs.NOOP.stage("z"):
        pass
    assert obs.NOOP.counters == {} and obs.NOOP.events == []
    assert obs.NOOP.timers == {}

    t = obs.RunTelemetry()
    with t.activate():
        assert obs.current() is t
        obs.current().incr("seen")
    assert obs.current() is obs.NOOP
    assert t.counters == {"seen": 1}


def test_jit_listener_routes_to_active_telemetry_only():
    t = obs.RunTelemetry()
    with t.activate():
        jax.jit(lambda x: x * 2 + 1)(np.arange(4.0)).block_until_ready()
    # jax.monitoring names vary by version; anything compile/lowering
    # shaped must have landed while active, nothing after deactivation
    n_before = sum(c for c, _ in t.jit.values())
    jax.jit(lambda x: x * 3 - 1)(np.arange(4.0)).block_until_ready()
    assert sum(c for c, _ in t.jit.values()) == n_before
    if t.jit:  # compile events observed on this jax version
        assert all(
            "compile" in k or "lower" in k for k in t.jit
        )


def test_capture_device_memory_never_raises():
    t = obs.RunTelemetry()
    t.capture_device_memory("anywhere")  # CPU: memory_stats absent
    # either nothing recorded or a positive high-water mark
    for v in t.gauges.values():
        assert v > 0


# --------------------------------------------------------------------------
# logger + CLI plumbing
# --------------------------------------------------------------------------

def test_resolve_level_precedence(monkeypatch):
    monkeypatch.delenv("PEASOUP_LOG_LEVEL", raising=False)
    assert obs.resolve_level(None) == logging.WARNING
    assert obs.resolve_level(None, verbose=True) == logging.INFO
    assert obs.resolve_level("debug") == logging.DEBUG
    assert obs.resolve_level("ERROR", verbose=True) == logging.ERROR
    assert obs.resolve_level(logging.DEBUG) == logging.DEBUG
    monkeypatch.setenv("PEASOUP_LOG_LEVEL", "error")
    assert obs.resolve_level(None) == logging.ERROR
    assert obs.resolve_level(None, verbose=True) == logging.INFO
    with pytest.raises(ValueError, match="unknown log level"):
        obs.resolve_level("shout")


def test_configure_is_idempotent_and_gates_levels():
    import io

    buf = io.StringIO()
    logger = obs.configure_logging("info", stream=buf)
    n_handlers = len(logger.handlers)
    obs.configure_logging("debug", stream=buf)
    assert len(logger.handlers) == n_handlers  # no handler stacking

    obs.configure_logging("warning", stream=buf)
    child = obs.get_logger("pipeline.search")
    child.info("hidden")
    child.warning("visible %d", 7)
    out = buf.getvalue()
    assert "hidden" not in out
    assert "visible 7" in out
    assert "peasoup_tpu.pipeline.search" in out


def test_get_logger_naming():
    assert obs.get_logger().name == "peasoup_tpu"
    assert obs.get_logger("obs").name == "peasoup_tpu.obs"


@pytest.mark.parametrize("which", ["peasoup", "ffa", "coincidencer"])
def test_cli_flags_plumbed(which):
    if which == "peasoup":
        from peasoup_tpu.cli.peasoup import build_parser

        base = ["-i", "x.fil"]
    elif which == "ffa":
        from peasoup_tpu.cli.ffa import build_parser

        base = ["-i", "x.fil"]
    else:
        from peasoup_tpu.cli.coincidencer import build_parser

        base = ["a.fil", "b.fil"]
    args = build_parser().parse_args(
        base + ["--log-level", "debug", "--metrics-json", "m.json",
                "--capture-device-trace"]
    )
    assert args.log_level == "debug"
    assert args.metrics_json == "m.json"
    assert args.capture_device_trace is True
    # defaults: no level override, no manifest path, no tracing
    args = build_parser().parse_args(base)
    assert args.log_level is None
    assert args.metrics_json is None
    assert args.capture_device_trace is False


# --------------------------------------------------------------------------
# end-to-end: search run -> manifest -> report
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def searched(tmp_path_factory):
    """One CLI search shared by the manifest/report/xml assertions."""
    from peasoup_tpu.cli.peasoup import main as peasoup_main

    tmp_path = tmp_path_factory.mktemp("obs_e2e")
    path, period, dm = make_synthetic_fil(tmp_path)
    outdir = tmp_path / "out"
    metrics = tmp_path / "metrics.json"
    rc = peasoup_main(
        ["-i", str(path), "-o", str(outdir), "--dm_end", "40",
         "-n", "2", "--limit", "20", "--metrics-json", str(metrics)]
    )
    assert rc == 0
    return outdir, metrics


def test_search_manifest_contents(searched):
    outdir, metrics = searched
    man = obs.load_manifest(str(metrics))
    # stage timers: the superset of overview.xml execution_times
    for key in ("reading", "plan", "dedispersion", "searching",
                "search_device", "search_host", "distilling", "scoring",
                "folding", "writing", "total"):
        assert key in man["timers"], key
        assert man["timers"][key] >= 0.0
    # candidate counts per stage
    for key in ("candidates.per_dm_distill", "candidates.per_dm_total",
                "candidates.post_dm_distill",
                "candidates.post_harmonic_distill", "candidates.final",
                "candidates.written"):
        assert key in man["gauges"], key
    assert man["gauges"]["candidates.written"] > 0
    assert man["gauges"]["search.n_dm_trials"] > 0
    assert man["gauges"]["search.n_accel_trials"] > 0
    # the adaptive-event log records the wave/device geometry
    kinds = [e["kind"] for e in man["events"]]
    assert "device_plan" in kinds
    assert "wave_plan" in kinds
    wave = next(e for e in man["events"] if e["kind"] == "wave_plan")
    assert wave["n_chunks"] >= wave["n_waves"] >= 1
    # jit stats: may be empty when every program was already compiled
    # earlier in this process (jax's in-memory executable cache emits no
    # monitoring events on a hit) — but whatever landed must be
    # compile/lowering shaped
    for key, st in man["jit"].items():
        assert "compile" in key or "lower" in key
        assert st["count"] >= 1 and st["seconds"] >= 0.0
    assert man["platform"]["backend"] == "cpu"


def test_default_manifest_lands_next_to_overview(tmp_path):
    from peasoup_tpu.cli.peasoup import main as peasoup_main

    path, _, _ = make_synthetic_fil(tmp_path, nsamps=1 << 13)
    outdir = tmp_path / "out"
    rc = peasoup_main(
        ["-i", str(path), "-o", str(outdir), "--dm_end", "10", "-n", "1",
         "--limit", "5"]
    )
    assert rc == 0
    assert (outdir / "overview.xml").exists()
    man = obs.load_manifest(str(outdir / "telemetry.json"))
    assert man["context"]["command"] == "peasoup"


def test_overview_xml_gains_new_stage_keys(searched):
    outdir, _ = searched
    from peasoup_tpu.tools import OverviewFile

    ov = OverviewFile(str(outdir / "overview.xml"))
    for key in ("plan", "distilling", "scoring", "writing",
                "dedispersion", "searching", "total", "reading"):
        assert key in ov.execution_times, key


def test_report_renders_and_diffs(searched, tmp_path, capsys):
    from peasoup_tpu.tools.report import main as report_main

    _, metrics = searched
    assert report_main([str(metrics)]) == 0
    out = capsys.readouterr().out
    assert "stage timers" in out
    assert "dedispersion" in out
    assert "adaptive events" in out
    assert "wave_plan" in out

    # diff against a doctored copy: renamed run, slower dedispersion
    man = obs.load_manifest(str(metrics))
    man["run_id"] = "after"
    man["timers"]["dedispersion"] += 1.0
    other = tmp_path / "after.json"
    other.write_text(json.dumps(man))
    assert report_main([str(metrics), str(other)]) == 0
    out = capsys.readouterr().out
    assert "after" in out.splitlines()[0]
    assert "dedispersion" in out
    assert "+1" in out  # the delta column

    with pytest.raises(SystemExit):
        report_main([str(metrics), str(other), str(other)])


def test_ffa_cli_writes_manifest(tmp_path):
    from peasoup_tpu.cli.ffa import main as ffa_main

    path, _, _ = make_synthetic_fil(tmp_path, nsamps=1 << 13)
    out = tmp_path / "ffa.xml"
    metrics = tmp_path / "ffa_telemetry.json"
    rc = ffa_main(
        ["-i", str(path), "-o", str(out), "--dm_end", "5",
         "--p_start", "1.0", "--p_end", "1.3",
         "--metrics-json", str(metrics)]
    )
    assert rc == 0
    man = obs.load_manifest(str(metrics))
    for key in ("reading", "dedispersion", "ffa_search", "total"):
        assert key in man["timers"], key
    assert man["context"]["command"] == "peasoup-ffa"
    # the XML execution_times table mirrors the manifest's timers
    xml = out.read_text()
    assert "<ffa_search>" in xml and "<total>" in xml
