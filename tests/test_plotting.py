"""Diagnostic-sheet plotter test (VERDICT r1 item 9): one command
renders the full candidate diagnostic (profile x2 phases, subints +
stats, parameter table, per-harmonic DM/acc scatters, DM-acc plane,
all-candidate overview with crosshair) headlessly from a real
pipeline run's outputs."""

import os

import numpy as np
import pytest

pytest.importorskip("matplotlib")
jax = pytest.importorskip("jax")

from test_pipeline import make_synthetic_fil


@pytest.fixture(scope="module")
def run_outputs(tmp_path_factory):
    """Small end-to-end CLI run with folding so FOLD blocks exist."""
    from peasoup_tpu.cli.peasoup import main

    tmp = tmp_path_factory.mktemp("plotrun")
    path, _, _ = make_synthetic_fil(tmp)
    outdir = str(tmp / "out")
    rc = main(
        ["-i", str(path), "-o", outdir, "--dm_end", "40",
         "-n", "2", "--npdmp", "3", "--limit", "50"]
    )
    assert rc == 0
    return outdir


def test_full_sheet_renders(run_outputs, tmp_path):
    from peasoup_tpu.tools.parsers import CandidateFileParser, OverviewFile
    from peasoup_tpu.tools.plotting import CandidatePlotter

    ov = OverviewFile(os.path.join(run_outputs, "overview.xml"))
    assert len(ov.candidates) > 0
    out = str(tmp_path / "cand0.png")
    with CandidateFileParser(
        os.path.join(run_outputs, "candidates.peasoup")
    ) as cp:
        CandidatePlotter(ov, cp).plot(0, out)
    assert os.path.exists(out) and os.path.getsize(out) > 20_000


def test_cli_entry(run_outputs, tmp_path):
    from peasoup_tpu.tools.plotting import main

    out = str(tmp_path / "cli.png")
    rc = main(
        [
            os.path.join(run_outputs, "overview.xml"),
            os.path.join(run_outputs, "candidates.peasoup"),
            "0", "-o", out,
        ]
    )
    assert rc == 0 and os.path.exists(out)


def test_unfolded_candidate_renders(run_outputs, tmp_path):
    """Candidates beyond npdmp have no FOLD block; the sheet must still
    render (the reference plotter requires a fold)."""
    from peasoup_tpu.tools.parsers import CandidateFileParser, OverviewFile
    from peasoup_tpu.tools.plotting import CandidatePlotter

    ov = OverviewFile(os.path.join(run_outputs, "overview.xml"))
    unfolded = None
    with CandidateFileParser(
        os.path.join(run_outputs, "candidates.peasoup")
    ) as cp:
        for i, row in enumerate(ov.candidates):
            if cp.read_candidate(int(row["byte_offset"]))["fold"] is None:
                unfolded = i
                break
        if unfolded is None:
            pytest.skip("every candidate was folded")
        out = str(tmp_path / "nofold.png")
        CandidatePlotter(ov, cp).plot(unfolded, out)
    assert os.path.exists(out)
