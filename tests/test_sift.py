"""Survey sifting tests: DB schema versioning/migration, batched
survey-fold bitwise parity with the per-observation folder (including
across a shape-bucket boundary and under an injected device OOM),
known-pulsar cross-match ladders, campaign-level dedup, multi-beam
coincidence vetoing, RRAT period inference, the end-to-end sift run +
report, and the peasoup-sift CLI.
"""

import json
import os
import sqlite3

import numpy as np
import pytest

from peasoup_tpu.campaign.db import (
    _SCHEMA_V1,
    SCHEMA_VERSION,
    CandidateDB,
    SchemaVersionError,
)
from peasoup_tpu.core.candidates import Candidate
from peasoup_tpu.io.sigproc import (
    Filterbank,
    SigprocHeader,
    write_filterbank,
)
from peasoup_tpu.obs.telemetry import RunTelemetry
from peasoup_tpu.pipeline.folder import MultiFolder, fold_geometry
from peasoup_tpu.resilience import faults
from peasoup_tpu.resilience.stats import STATS
from peasoup_tpu.sift.crossmatch import (
    harmonic_identify,
    load_catalogue,
    match_candidate,
)
from peasoup_tpu.sift.dedup import dedup_candidates, multibeam_veto
from peasoup_tpu.sift.fold import (
    FoldCandidate,
    FoldObservation,
    SurveyFolder,
)
from peasoup_tpu.sift.repeats import infer_period, repeat_sources
from peasoup_tpu.sift.service import SiftConfig, SiftRun

P0 = 0.714519699726  # J0332+5434 (B0329+54)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    STATS.reset()
    yield
    faults.configure(None)
    STATS.reset()


# --------------------------------------------------------------------------
# database schema versioning + migration
# --------------------------------------------------------------------------

class TestDBSchema:
    def _legacy_v1(self, path: str) -> None:
        conn = sqlite3.connect(path)
        conn.executescript(_SCHEMA_V1)
        conn.execute(
            "INSERT INTO observations (job_id, input, source_name, "
            "tstart, tsamp, nchans, nsamps, ingested_unix) VALUES "
            "('j1', 'a.fil', 'SRC', 55000.0, 2.56e-4, 8, 4096, 0)"
        )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period) "
            "VALUES ('j1', 'periodicity', 26.7, 9.0, 0.714)"
        )
        conn.commit()
        conn.close()

    def test_fresh_db_opens_at_current_version(self, tmp_path):
        with CandidateDB(str(tmp_path / "c.sqlite")) as db:
            assert db.schema_version() == SCHEMA_VERSION
            # sift tables exist and start empty
            assert db.sift_catalogue() == []
            assert db.latest_sift_run() is None
            # v2 observation columns exist
            cols = {
                r[1]
                for r in db._conn.execute(
                    "PRAGMA table_info(observations)"
                )
            }
            assert {"beam", "src_raj", "src_dej"} <= cols

    def test_legacy_v1_migrates_up_in_place(self, tmp_path):
        """ISSUE satellite (up): a pre-sift campaign DB upgrades in
        place, keeping its rows and gaining the new tables/columns."""
        path = str(tmp_path / "c.sqlite")
        self._legacy_v1(path)
        with CandidateDB(path) as db:
            assert db.schema_version() == SCHEMA_VERSION
            obs = db.observations()
            assert len(obs) == 1 and obs[0]["job_id"] == "j1"
            assert obs[0]["beam"] is None  # migrated rows: unknown beam
            cands = db.all_candidates("periodicity")
            assert len(cands) == 1 and cands[0]["dm"] == 26.7
            assert db.sift_catalogue() == []
        # idempotent: a second open finds nothing to do
        with CandidateDB(path) as db:
            assert db.schema_version() == SCHEMA_VERSION

    def test_future_version_refused_loudly(self, tmp_path):
        """ISSUE satellite (down): a DB from a newer peasoup_tpu is
        refused, never silently misread."""
        path = str(tmp_path / "c.sqlite")
        self._legacy_v1(path)
        conn = sqlite3.connect(path)
        conn.execute(f"PRAGMA user_version = {SCHEMA_VERSION + 7}")
        conn.commit()
        conn.close()
        with pytest.raises(SchemaVersionError, match="newer"):
            CandidateDB(path)

    def test_sift_ingest_replaces_wholesale(self, tmp_path):
        path = str(tmp_path / "c.sqlite")
        row = {
            "kind": "periodicity", "label": "candidate", "tier": 2,
            "dm": 10.0, "snr": 9.0, "period": 0.5, "job_ids": ["j1"],
        }
        with CandidateDB(path) as db:
            db.ingest_sift_run("run1", {}, [row, dict(row, dm=11.0)],
                               [], [])
            assert len(db.sift_catalogue()) == 2
            db.ingest_sift_run("run2", {}, [row], [], [
                {"dm": 40.0, "n_obs": 2, "n_pulses": 5,
                 "best_snr": 8.0, "period_s": 0.5,
                 "period_frac_resid": 0.001, "job_ids": ["j1", "j2"],
                 "toas_s": [0.0, 0.5]},
            ])
            # latest run wins wholesale
            assert len(db.sift_catalogue()) == 1
            assert db.latest_sift_run()["run_id"] == "run2"
            assert len(db.sift_sp_sources()) == 1


# --------------------------------------------------------------------------
# batched survey fold: bitwise parity with pipeline/folder.py
# --------------------------------------------------------------------------

def make_trials(ndm: int, nsamps: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    trials = rng.integers(20, 45, size=(ndm, nsamps), dtype=np.uint8)
    # a periodic brightening so folds/optimiser see structure
    period = max(64, nsamps // 37)
    for s in range(0, nsamps, period):
        trials[:, s : s + 3] += 40
    return trials


def multifolder_outcomes(trials, trials_nsamps, tsamp, cands):
    """The per-observation reference path on the same candidates."""
    mf = MultiFolder(trials, trials_nsamps, tsamp)
    return {
        o["cand_idx"]: o
        for o in mf.fold_outcomes(list(cands), len(cands))
    }


def survey_obs(job_id, trials, trials_nsamps, tsamp, cands):
    return FoldObservation(
        job_id=job_id, trials=trials, trials_nsamps=trials_nsamps,
        tsamp=tsamp,
        cands=[
            FoldCandidate(
                key=i, period=1.0 / c.freq, acc=c.acc, dm_row=c.dm_idx
            )
            for i, c in enumerate(cands)
        ],
    )


class TestSurveyFoldParity:
    TSAMP = 0.000256

    def _cands(self, ndm, nsamps, seed=1):
        rng = np.random.default_rng(seed)
        out = []
        for i in range(6):
            p = float(rng.uniform(0.004, 0.05))
            out.append(
                Candidate(
                    dm=float(i), dm_idx=int(rng.integers(0, ndm)),
                    acc=float(rng.uniform(-20, 20)), snr=9.0,
                    freq=1.0 / p,
                )
            )
        return out

    def test_bitwise_equal_to_multifolder(self):
        """ISSUE satellite: the batched survey fold is bitwise-equal
        to the per-observation folder path on the same candidates."""
        trials = make_trials(4, 4000)
        cands = self._cands(4, 4000)
        want = multifolder_outcomes(trials, 4000, self.TSAMP, cands)
        got = SurveyFolder(batch=4).fold_outcomes(
            [survey_obs("jobA", trials, 4000, self.TSAMP, cands)]
        )
        assert len(got) == len(want) == len(cands)
        for o in got:
            ref = want[o["key"]]
            assert o["opt_sn"] == ref["opt_sn"]
            assert o["opt_period"] == ref["opt_period"]
            assert np.array_equal(o["opt_fold"], ref["opt_fold"])

    def test_parity_across_shape_bucket_boundary(self):
        """Two observations on opposite sides of a power-of-two
        boundary (sizes 2048 and 4096) fold in one pass, each
        bitwise-equal to its own MultiFolder."""
        obs = []
        want = {}
        for j, nsamps in enumerate((4000, 4160)):
            geom = fold_geometry(nsamps, self.TSAMP)
            assert geom[0] == (2048 if j == 0 else 4096)
            trials = make_trials(3, nsamps, seed=j)
            cands = self._cands(3, nsamps, seed=10 + j)
            want[f"job{j}"] = multifolder_outcomes(
                trials, nsamps, self.TSAMP, cands
            )
            obs.append(
                survey_obs(f"job{j}", trials, nsamps, self.TSAMP, cands)
            )
        got = SurveyFolder(batch=4).fold_outcomes(obs)
        assert len(got) == 12
        for o in got:
            ref = want[o["job_id"]][o["key"]]
            assert o["opt_sn"] == ref["opt_sn"]
            assert o["opt_period"] == ref["opt_period"]
            assert np.array_equal(o["opt_fold"], ref["opt_fold"])

    def test_bitwise_equal_under_device_oom(self):
        """ISSUE satellite: an injected device.oom mid-pass shrinks
        the batch (DegradationLadder rung) and the outcomes stay
        bitwise-equal to the fault-free run."""
        trials = make_trials(4, 4000, seed=3)
        cands = self._cands(4, 4000, seed=4)
        obs = [survey_obs("jobA", trials, 4000, self.TSAMP, cands)]
        want = {
            o["key"]: o for o in SurveyFolder(batch=4).fold_outcomes(obs)
        }
        faults.configure("device.oom:at=1")
        tel = RunTelemetry()
        with tel.activate():
            got = SurveyFolder(batch=4).fold_outcomes(obs)
        degs = [e for e in tel.events if e["kind"] == "degradation"]
        assert degs and degs[0]["ladder"] == "sift.fold"
        assert degs[0]["rung"] == "batch_shrink"
        assert len(got) == len(want)
        for o in got:
            ref = want[o["key"]]
            assert o["opt_sn"] == ref["opt_sn"]
            assert o["opt_period"] == ref["opt_period"]
            assert np.array_equal(o["opt_fold"], ref["opt_fold"])

    def test_oom_exhaustion_raises_at_batch_one(self):
        trials = make_trials(2, 4000, seed=5)
        cands = self._cands(2, 4000, seed=6)
        obs = [survey_obs("jobA", trials, 4000, self.TSAMP, cands)]
        faults.configure("device.oom:n=99")
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            SurveyFolder(batch=2).fold_outcomes(obs)

    def test_zero_steady_state_recompiles(self):
        """Many same-bucket batches reuse ONE compiled fold program
        and ONE compiled optimiser (compile counters at zero after the
        first batch)."""
        from peasoup_tpu.campaign.runner import jit_programs_compiled

        trials = make_trials(6, 4000, seed=7)
        folder = SurveyFolder(batch=4)
        obs0 = [survey_obs("warm", trials, 4000, self.TSAMP,
                           self._cands(6, 4000, seed=8))]
        folder.fold_outcomes(obs0)  # compiles once
        tel = RunTelemetry()
        with tel.activate():
            for seed in (9, 10, 11):
                got = folder.fold_outcomes(
                    [
                        survey_obs(
                            f"obs{seed}", trials, 4000, self.TSAMP,
                            self._cands(6, 4000, seed=seed),
                        )
                    ]
                )
                assert got
        assert jit_programs_compiled(tel) == 0

    def test_period_gates_match_multifolder(self):
        trials = make_trials(2, 4000, seed=12)
        cands = [
            Candidate(dm_idx=0, acc=0.0, snr=9.0, freq=1.0 / 20.0),
            Candidate(dm_idx=0, acc=0.0, snr=9.0, freq=1.0 / 5e-4),
        ]
        got = SurveyFolder(batch=2).fold_outcomes(
            [survey_obs("jobA", trials, 4000, self.TSAMP, cands)]
        )
        assert got == []  # both outside (min_period, max_period)


# --------------------------------------------------------------------------
# sifting passes
# --------------------------------------------------------------------------

class TestCrossmatch:
    def test_harmonic_ladder_identities(self):
        assert harmonic_identify(P0, P0)[:2] == (1, 1)
        assert harmonic_identify(P0 / 2, P0)[:2] == (1, 2)
        assert harmonic_identify(P0 / 3, P0)[:2] == (1, 3)
        assert harmonic_identify(2 * P0, P0)[:2] == (2, 1)
        assert harmonic_identify(1.5 * P0, P0)[:2] == (3, 2)
        assert harmonic_identify(0.123, P0) is None
        # tolerance edge
        assert harmonic_identify(P0 * 1.001, P0, tol=2e-3) is not None
        assert harmonic_identify(P0 * 1.01, P0, tol=2e-3) is None

    def test_match_candidate_dm_gate(self):
        cat = load_catalogue()
        m = match_candidate(P0, 26.8, cat)
        assert m is not None and m["psr"] == "J0332+5434"
        assert m["harmonic"] == "1/1"
        # right period, hopeless DM: no match
        assert match_candidate(P0, 200.0, cat) is None
        # harmonic detection still identifies the source
        m2 = match_candidate(P0 / 4, 26.0, cat)
        assert m2 is not None and m2["harmonic"] == "1/4"

    def test_catalogue_validation(self, tmp_path):
        bad = tmp_path / "cat.json"
        bad.write_text(json.dumps({"schema": "nope", "pulsars": []}))
        with pytest.raises(ValueError, match="known_pulsars"):
            load_catalogue(str(bad))
        bad.write_text(
            json.dumps(
                {
                    "schema": "peasoup_tpu.known_pulsars",
                    "pulsars": [{"name": "X", "period_s": -1, "dm": 0}],
                }
            )
        )
        with pytest.raises(ValueError, match="bad catalogue entry"):
            load_catalogue(str(bad))

    def test_checked_in_catalogue_loads(self):
        cat = load_catalogue()
        assert len(cat) >= 15
        names = {p["name"] for p in cat}
        assert {"J0332+5434", "J0534+2200", "J0835-4510"} <= names


class TestDedup:
    def test_harmonics_merge_across_observations(self):
        cands = [
            {"id": 1, "job_id": "a", "period": P0, "dm": 26.7,
             "snr": 12.0},
            {"id": 2, "job_id": "b", "period": P0 / 2, "dm": 26.9,
             "snr": 9.0},
            {"id": 3, "job_id": "c", "period": 0.1234, "dm": 80.0,
             "snr": 8.0},
        ]
        groups = dedup_candidates(cands)
        assert len(groups) == 2
        lead = groups[0]
        assert lead["leader"]["id"] == 1  # strongest wins
        assert {m["id"] for m in lead["members"]} == {1, 2}
        assert lead["n_obs"] == 2
        member = next(m for m in lead["members"] if m["id"] == 2)
        assert member["harmonic"] == "1/2"

    def test_dm_gate_prevents_merge(self):
        cands = [
            {"id": 1, "job_id": "a", "period": P0, "dm": 10.0,
             "snr": 12.0},
            {"id": 2, "job_id": "b", "period": P0, "dm": 40.0,
             "snr": 9.0},
        ]
        assert len(dedup_candidates(cands, dm_tol=2.0)) == 2

    def test_multibeam_veto_reuses_coincidence_op(self):
        # the same (period, DM) cell firing in 5 beams is RFI; a
        # single-beam candidate survives
        rfi = [
            {"id": i, "period": 0.02, "dm": 15.0, "snr": 9.0,
             "beam": i + 1}
            for i in range(5)
        ]
        psr = [{"id": 99, "period": P0, "dm": 26.7, "snr": 12.0,
                "beam": 3}]
        vetoed = multibeam_veto(
            rfi + psr, snr_thresh=6.0, beam_thresh=4
        )
        assert vetoed == {0, 1, 2, 3, 4}
        # too few beams overall: the veto stands down entirely
        assert multibeam_veto(rfi[:2] + psr, beam_thresh=4) == set()
        # no beam provenance recorded: nothing vetoed
        nobeam = [dict(r, beam=None) for r in rfi]
        assert multibeam_veto(nobeam + psr, beam_thresh=4) == set()


class TestRepeats:
    def test_gcd_period_recovery_within_tolerance(self):
        p = 0.7321
        toas = np.asarray([0.0, 3 * p, 7 * p, 18 * p, 40 * p])
        toas = toas + np.random.default_rng(0).normal(
            0, 0.002, size=toas.shape
        )
        fit = infer_period(toas)
        assert fit is not None
        period, resid = fit
        assert abs(period - p) / p < 0.01
        assert resid < 0.02

    def test_largest_consistent_period_wins(self):
        p = 0.5
        toas = np.asarray([0.0, 2 * p, 3 * p, 7 * p])
        period, _ = infer_period(toas)
        assert abs(period - p) / p < 1e-6

    def test_incommensurate_toas_yield_no_period(self):
        toas = np.asarray([0.0, 1.0, 2.0 + np.pi / 10.0])
        assert infer_period(toas, phase_tol=0.02) is None

    def test_association_needs_obs_and_pulse_floor(self):
        rows = [
            {"id": 1, "job_id": "a", "dm": 40.0, "snr": 8.0,
             "obs_tstart": 55000.0, "time_s": 0.5},
            {"id": 2, "job_id": "a", "dm": 40.1, "snr": 8.5,
             "obs_tstart": 55000.0, "time_s": 1.5},
            {"id": 3, "job_id": "b", "dm": 40.2, "snr": 7.5,
             "obs_tstart": 55000.01, "time_s": 1.0},
            # far-away DM: its own (too small) group
            {"id": 4, "job_id": "b", "dm": 90.0, "snr": 9.0,
             "obs_tstart": 55000.01, "time_s": 2.0},
        ]
        srcs = repeat_sources(rows, min_pulses=3, min_obs=2)
        assert len(srcs) == 1
        assert srcs[0]["n_pulses"] == 3 and srcs[0]["n_obs"] == 2
        # single-observation group fails the min_obs floor
        assert repeat_sources(rows[:2], min_pulses=2, min_obs=2) == []


# --------------------------------------------------------------------------
# the end-to-end sift run + report + CLI
# --------------------------------------------------------------------------

def seed_campaign(tmp_path, with_rfi=False):
    """A 2-observation campaign DB: an injected known pulsar (B0329
    fundamental in obs0, its 1/2 harmonic in obs1 — the cross-obs
    duplicate), a repeated single-pulse source (P = 0.5 s across both
    observations), and optionally a multi-beam RFI comb."""
    camp = tmp_path / "camp"
    camp.mkdir(exist_ok=True)
    nsamps, nchans, tsamp = 4096, 8, 0.000256
    rng = np.random.default_rng(0)
    prrat = 0.5
    with CandidateDB(str(camp / "candidates.sqlite")) as db:
        conn = db._conn
        nobs = 6 if with_rfi else 2
        for i in range(nobs):
            data = np.clip(
                np.rint(rng.normal(32.0, 4.0, size=(nsamps, nchans))),
                0, 255,
            ).astype(np.uint8)
            hdr = SigprocHeader(
                source_name=f"OBS{i}", tsamp=tsamp,
                tstart=55000.0 + i * 0.01, fch1=1400.0, foff=-16.0,
                nchans=nchans, nbits=8, nifs=1, data_type=1,
                ibeam=i + 1,
            )
            write_filterbank(
                str(camp / f"obs{i}.fil"),
                Filterbank(header=hdr, data=data),
            )
            conn.execute(
                "INSERT INTO observations (job_id, input, source_name,"
                " tstart, tsamp, nchans, nsamps, ingested_unix, beam,"
                " src_raj, src_dej) VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                (f"job{i}", str(camp / f"obs{i}.fil"), f"OBS{i}",
                 55000.0 + i * 0.01, tsamp, nchans, nsamps, 0.0,
                 i + 1, 0.0, 0.0),
            )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period, "
            "acc, nh) VALUES ('job0', 'periodicity', 26.76, 12.0, ?, "
            "0.0, 2)", (P0,),
        )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period, "
            "acc, nh) VALUES ('job1', 'periodicity', 26.80, 9.0, ?, "
            "0.0, 1)", (P0 / 2,),
        )
        conn.execute(
            "INSERT INTO candidates (job_id, kind, dm, snr, period, "
            "acc, nh) VALUES ('job1', 'periodicity', 80.0, 8.0, "
            "0.1234, 0.0, 1)"
        )
        if with_rfi:
            for i in range(6):  # one comb in every beam
                conn.execute(
                    "INSERT INTO candidates (job_id, kind, dm, snr, "
                    "period, acc, nh) VALUES (?, 'periodicity', 5.0, "
                    "9.5, 0.02, 0.0, 1)", (f"job{i}",),
                )
        for i, ks in enumerate([(1, 3, 7), (2, 5, 11)]):
            for k in ks:
                t = 0.05 + k * prrat
                conn.execute(
                    "INSERT INTO candidates (job_id, kind, dm, snr, "
                    "time_s, sample, width, members) VALUES "
                    "(?, 'single_pulse', ?, 8.0, ?, ?, 4, 3)",
                    (f"job{i}", 40.0 + 0.1 * i, t, int(t / tsamp)),
                )
        conn.commit()
    return camp


class TestSiftEndToEnd:
    def test_run_flags_known_merges_duplicates_finds_rrat(self, tmp_path):
        camp = seed_campaign(tmp_path)
        cfg = SiftConfig(
            workdir=str(camp), fold_batch=8, sp_min_pulses=4
        )
        tel = RunTelemetry()
        with tel.activate():
            summary = SiftRun(cfg).run()
        assert summary["n_folded"] == 3
        assert summary["n_known"] == 1
        assert summary["n_sp_sources"] == 1
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            cat = db.sift_catalogue()
            assert len(cat) == 2
            known = next(c for c in cat if c["label"] == "known")
            # the injected pulsar: cross-matched, harmonic duplicate
            # merged across observations into ONE catalogue row
            assert known["known_source"] == "J0332+5434"
            assert known["tier"] == 1
            assert known["n_obs"] == 2 and known["members"] == 2
            assert json.loads(known["job_ids"]) == ["job0", "job1"]
            # folded: the postage stamp rode along as inline JSON
            fold = json.loads(known["fold_json"])
            assert len(fold["prof"]) == cfg.fold_nbins
            assert len(fold["subints"]) == cfg.fold_nints
            matches = db.sift_known_matches()
            assert {m["harmonic"] for m in matches} == {"1/1", "1/2"}
            # the repeated single-pulse source with its inferred period
            [src] = db.sift_sp_sources()
            assert src["n_pulses"] == 6 and src["n_obs"] == 2
            assert abs(src["period_s"] - 0.5) / 0.5 < 0.01
        # observability: stage events + the sift status section
        kinds = [e["kind"] for e in tel.events]
        assert "sift_folded" in kinds and "sift_done" in kinds
        sections = tel.snapshot_sections()
        assert sections["sift"]["stage"] == "done"

    def test_multibeam_rfi_vetoed_e2e(self, tmp_path):
        camp = seed_campaign(tmp_path, with_rfi=True)
        cfg = SiftConfig(
            workdir=str(camp), fold=False, sp_min_pulses=4,
            beam_thresh=4,
        )
        SiftRun(cfg).run()
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            cat = db.sift_catalogue()
            rfi = [c for c in cat if c["label"] == "rfi"]
            assert len(rfi) == 1  # the comb deduped into one row
            assert rfi[0]["members"] == 6
            known = [c for c in cat if c["label"] == "known"]
            assert len(known) == 1  # the pulsar survived the veto

    def test_fold_outcomes_match_multifolder_e2e(self, tmp_path):
        """Acceptance: the service's batched fold over re-dedispersed
        DB candidates is bitwise-equal to MultiFolder on the same
        trials."""
        camp = seed_campaign(tmp_path)
        cfg = SiftConfig(workdir=str(camp), fold_batch=8)
        run = SiftRun(cfg)
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            obs_rows = db.observations()
            cands = db.all_candidates("periodicity")
        fold_inputs = run.build_fold_inputs(obs_rows, cands)
        assert len(fold_inputs) == 2
        # canonicalise periods through the folder's freq round trip
        # (MultiFolder consumes 1/freq; 1/(1/p) is a ULP off p, and
        # this test pins the fold machinery, not float inversion)
        for fi in fold_inputs:
            for c in fi.cands:
                c.period = 1.0 / (1.0 / c.period)
        got = {
            o["key"]: o
            for o in SurveyFolder(batch=8).fold_outcomes(fold_inputs)
        }
        n = 0
        for fi in fold_inputs:
            ref_cands = [
                Candidate(
                    dm_idx=c.dm_row, acc=c.acc, snr=9.0,
                    freq=1.0 / c.period,
                )
                for c in fi.cands
            ]
            want = multifolder_outcomes(
                fi.trials, fi.trials_nsamps, fi.tsamp, ref_cands
            )
            for i, c in enumerate(fi.cands):
                o = got[c.key]
                assert o["opt_sn"] == want[i]["opt_sn"]
                assert o["opt_period"] == want[i]["opt_period"]
                assert np.array_equal(
                    o["opt_fold"], want[i]["opt_fold"]
                )
                n += 1
        assert n == 3

    def test_missing_input_file_skips_observation(self, tmp_path):
        camp = seed_campaign(tmp_path)
        os.unlink(camp / "obs1.fil")
        cfg = SiftConfig(
            workdir=str(camp), fold_batch=8, sp_min_pulses=4
        )
        tel = RunTelemetry()
        with tel.activate():
            summary = SiftRun(cfg).run()
        assert summary["n_folded"] == 1  # only obs0's candidate folded
        skips = [
            e for e in tel.events if e["kind"] == "sift_obs_skipped"
        ]
        assert len(skips) == 1 and skips[0]["job_id"] == "job1"
        # the sift still completes: crossmatch/dedup use trial periods
        assert summary["n_known"] == 1

    def test_missing_db_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="campaign database"):
            SiftRun(SiftConfig(workdir=str(tmp_path))).run()

    def test_report_schema_valid_and_self_contained(self, tmp_path):
        from peasoup_tpu.sift.report import (
            build_report,
            render_html,
            validate_report,
        )

        camp = seed_campaign(tmp_path)
        SiftRun(
            SiftConfig(workdir=str(camp), fold_batch=8, sp_min_pulses=4)
        ).run()
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            doc = build_report(db, None)
        validate_report(doc)
        assert doc["labels"]["known"] == 1
        assert doc["known_sources"][0]["psr"] == "J0332+5434"
        page = render_html(doc)
        # self-contained: the full report JSON is inline and the page
        # references no external assets
        assert '<script type="application/json" id="sift-report">' in page
        assert "http://" not in page and "https://" not in page
        embedded = page.split('id="sift-report">')[1].split("</script>")[0]
        assert (
            json.loads(embedded.replace("<\\/", "</"))["run"]["run_id"]
            == doc["run"]["run_id"]
        )

    def test_report_schema_rejects_drift(self, tmp_path):
        from peasoup_tpu.obs.schema import SchemaError
        from peasoup_tpu.sift.report import build_report, validate_report

        camp = seed_campaign(tmp_path)
        SiftRun(
            SiftConfig(workdir=str(camp), fold=False, sp_min_pulses=4)
        ).run()
        with CandidateDB(str(camp / "candidates.sqlite")) as db:
            doc = build_report(db, None)
        doc["catalogue"][0]["label"] = "maybe"
        with pytest.raises(SchemaError):
            validate_report(doc)


class TestSiftCLI:
    def test_run_and_report(self, tmp_path, capsys):
        from peasoup_tpu.cli.sift import main as sift_main
        from peasoup_tpu.obs.schema import validate_manifest
        from peasoup_tpu.obs.telemetry import load_manifest

        camp = seed_campaign(tmp_path)
        rc = sift_main(
            ["run", "-w", str(camp), "--fold-batch", "8",
             "--config", '{"sp_min_pulses": 4}']
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "1 known" in out and "repeat single-pulse" in out
        man = load_manifest(str(camp / "sift" / "telemetry.json"))
        validate_manifest(man)
        assert man["sift"]["stage"] == "done"
        rc = sift_main(
            ["report", "-w", str(camp), "--print-summary"]
        )
        assert rc == 0
        assert "t1=1" in capsys.readouterr().out
        assert os.path.getsize(camp / "sift" / "report.html") > 1000
        doc = json.loads((camp / "sift" / "report.json").read_text())
        assert doc["schema"] == "peasoup_tpu.sift_report"

    def test_watch_renders_sift_section(self, tmp_path):
        from peasoup_tpu.cli.sift import main as sift_main
        from peasoup_tpu.obs.heartbeat import load_status
        from peasoup_tpu.tools.watch import render_status

        camp = seed_campaign(tmp_path)
        assert sift_main(
            ["run", "-w", str(camp), "--no-fold",
             "--config", '{"sp_min_pulses": 4}']
        ) == 0
        st = load_status(str(camp / "sift" / "status.json"))
        text = render_status(st)
        assert "sift:" in text and "pass=done" in text

    def test_bad_config_key_and_missing_db(self, tmp_path, capsys):
        from peasoup_tpu.cli.sift import main as sift_main

        camp = seed_campaign(tmp_path)
        assert sift_main(
            ["run", "-w", str(camp), "--config", '{"bogus": 1}']
        ) == 2
        assert "unknown SiftConfig keys" in capsys.readouterr().err
        assert sift_main(["report", "-w", str(tmp_path / "empty")]) == 2
