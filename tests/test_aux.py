"""Tests for the auxiliary subsystems: cross-beam correlator,
checkpoint/resume, stopwatch/trace spans, progress bar."""

import io
import os

import numpy as np
import pytest

from peasoup_tpu.ops.correlate import baseline_pairs, find_delays
from peasoup_tpu.pipeline.checkpoint import SearchCheckpoint
from peasoup_tpu.utils import ProgressBar, Stopwatch, trace_span


# --------------------------------------------------------------------------
# correlator (reference: DelayFinder, include/transforms/correlator.hpp)
# --------------------------------------------------------------------------

def test_baseline_pairs_order():
    pairs = baseline_pairs(4)
    # reference loop order: ii outer, jj=ii+1.. inner (correlator.hpp:62-69)
    assert pairs.tolist() == [
        [0, 1], [0, 2], [0, 3], [1, 2], [1, 3], [2, 3]
    ]


def test_find_delays_recovers_known_lags():
    rng = np.random.default_rng(42)
    n = 1024
    base = rng.normal(size=n).astype(np.float32)
    lags = {1: 7, 2: -11}  # beam index -> circular shift vs beam 0
    beams = np.stack(
        [base] + [np.roll(base, lags[i]) for i in (1, 2)]
    )
    res = find_delays(beams, max_delay=32)
    got = {tuple(p): int(l) for p, l in zip(res.pairs.tolist(), res.lag)}
    # cc(x, y) peaks at lag where y = roll(x, lag)
    assert got[(0, 1)] == 7
    assert got[(0, 2)] == -11
    assert got[(1, 2)] == -18  # relative shift between beams 1 and 2


def test_find_delays_distance_window_convention():
    """distance indexes [pos lags 0..D-1, neg lags -D..-1] like the
    reference's two D2H copies (correlator.hpp:77-78)."""
    n = 256
    x = np.zeros(n, dtype=np.float32)
    x[10] = 1.0
    y = np.roll(x, -3)  # negative lag
    res = find_delays(np.stack([x, y]), max_delay=8)
    assert int(res.distance[0]) == 2 * 8 - 3
    assert int(res.lag[0]) == -3


def test_find_delays_complex_input_and_validation():
    rng = np.random.default_rng(0)
    z = (rng.normal(size=(2, 128)) + 1j * rng.normal(size=(2, 128))).astype(
        np.complex64
    )
    res = find_delays(z, max_delay=16)
    assert res.power.shape == (1,)
    with pytest.raises(ValueError):
        find_delays(z, max_delay=100)  # > nsamps/2
    with pytest.raises(ValueError):
        find_delays(z[0], max_delay=4)  # not 2-D


# --------------------------------------------------------------------------
# checkpoint/resume
# --------------------------------------------------------------------------

def _fake_results(dm_idxs, seed=0):
    rng = np.random.default_rng(seed)
    out = {}
    for d in dm_idxs:
        out[d] = (
            rng.integers(0, 1000, size=(5, 3, 4)).astype(np.int32),
            rng.normal(size=(5, 3, 4)).astype(np.float32),
            rng.integers(0, 4, size=(5, 3)).astype(np.int32),
        )
    return out


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ck.npz")
    ck = SearchCheckpoint(path, "key1")
    results = _fake_results([0, 3, 7])
    ck.save(results)
    restored = SearchCheckpoint(path, "key1").load()
    assert sorted(restored) == [0, 3, 7]
    for d in results:
        for a, b in zip(results[d], restored[d]):
            np.testing.assert_array_equal(a, b)


def test_checkpoint_config_mismatch_discards(tmp_path):
    path = str(tmp_path / "ck.npz")
    SearchCheckpoint(path, "key1").save(_fake_results([1]))
    assert SearchCheckpoint(path, "DIFFERENT").load() == {}


def test_checkpoint_missing_and_corrupt(tmp_path):
    assert SearchCheckpoint(str(tmp_path / "nope.npz"), "k").load() == {}
    bad = tmp_path / "bad.npz"
    bad.write_bytes(b"not an npz at all")
    assert SearchCheckpoint(str(bad), "k").load() == {}
    # unified resilience semantics: the damaged store is quarantined
    # (renamed, never deleted), so the torn bytes survive for forensics
    assert not bad.exists()
    assert (tmp_path / "bad.npz.corrupt").read_bytes() == b"not an npz at all"


def test_checkpoint_atomic_no_tmp_left(tmp_path):
    path = str(tmp_path / "ck.npz")
    ck = SearchCheckpoint(path, "key")
    ck.save(_fake_results([0]))
    ck.save(_fake_results([0, 1]))  # overwrite
    leftovers = [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    assert leftovers == []
    assert sorted(ck.load()) == [0, 1]


def test_search_resume_end_to_end(tutorial_fil, tmp_path):
    """A checkpointed re-run must reproduce the uncheckpointed result."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig

    fil = read_filterbank(tutorial_fil)
    common = dict(dm_end=30.0, acc_start=0.0, acc_end=0.0, npdmp=0)
    ref = PeasoupSearch(SearchConfig(**common)).run(fil)

    path = str(tmp_path / "search.ckpt.npz")
    first = PeasoupSearch(
        SearchConfig(checkpoint_file=path, **common)
    ).run(fil)
    assert os.path.exists(path)
    resumed = PeasoupSearch(
        SearchConfig(checkpoint_file=path, **common)
    ).run(fil)

    for a, b in ((first, ref), (resumed, ref)):
        assert len(a.candidates) == len(b.candidates)
        for ca, cb in zip(a.candidates, b.candidates):
            assert ca.freq == cb.freq and ca.snr == cb.snr
            assert ca.dm == cb.dm and ca.acc == cb.acc


# --------------------------------------------------------------------------
# stopwatch / trace / progress
# --------------------------------------------------------------------------

def test_stopwatch_accumulates():
    sw = Stopwatch()
    sw.start(); sw.stop()
    first = sw.elapsed
    sw.start(); sw.stop()
    assert sw.getTime() >= first  # accumulates across start/stop pairs
    sw.reset()
    assert sw.elapsed == 0.0
    with pytest.raises(RuntimeError):
        sw.stop()


def test_trace_span_times_and_nests():
    sw = Stopwatch()
    with trace_span("DM-Loop", sw):
        with trace_span("Acceleration-Loop"):
            pass
    assert sw.elapsed >= 0.0


def test_progress_bar_output():
    buf = io.StringIO()
    pb = ProgressBar(stream=buf, min_interval=0.0)
    pb.start()
    pb.update(0.5)
    pb.stop()
    out = buf.getvalue()
    assert "50.0%" in out and "100.0%" in out and "ETA" in out
    pb.update(0.9)  # after stop: no-op
    assert "90" not in buf.getvalue()


class TestAccmapCli:
    """accmap CLI (reference src/accmap.cpp — broken as shipped there;
    working here over .fil/.tim beams)."""

    def test_finds_planted_delay(self, tmp_path, capsys):
        import numpy as np

        from peasoup_tpu.cli.accmap import main
        from peasoup_tpu.io import write_filterbank
        from peasoup_tpu.io.sigproc import Filterbank, SigprocHeader

        rng = np.random.default_rng(0)
        n, nchans = 4096, 4
        base = rng.normal(100, 5, size=n + 64)
        files = []
        for k, off in enumerate((0, 17)):
            data = np.clip(
                base[off : off + n, None]
                + rng.normal(0, 0.5, size=(n, nchans)),
                0, 255,
            ).astype(np.uint8)
            hdr = SigprocHeader(
                source_name=f"b{k}", data_type=1, nchans=nchans, nbits=8,
                nifs=1, tsamp=0.001, tstart=50000.0, fch1=1500.0, foff=-1.0,
            )
            path = str(tmp_path / f"beam{k}.fil")
            write_filterbank(path, Filterbank(header=hdr, data=data))
            files.append(path)
        assert main(files + ["-d", "64"]) == 0
        out = capsys.readouterr().out
        assert "lag" in out
        lag = int(out.split("lag ")[1].split(" ")[0])
        assert abs(abs(lag) - 17) <= 1, out


class TestDumpBuffer:
    def test_roundtrip(self, tmp_path):
        import numpy as np
        import jax.numpy as jnp

        from peasoup_tpu.utils import dump_buffer

        x = np.arange(100, dtype=np.float32) * 0.5
        path = str(tmp_path / "buf.bin")
        dump_buffer(jnp.asarray(x), path)
        back = np.fromfile(path, dtype=np.float32)
        np.testing.assert_array_equal(back, x)


class TestOOMContract:
    """Pin the _is_oom signature against the REAL exception the current
    JAX raises on allocation failure, and cover the shrink-retry path
    (VERDICT r1 item 10)."""

    def test_is_oom_recognises_real_jax_oom(self):
        import jax.numpy as jnp

        from peasoup_tpu.pipeline.search import _is_oom

        # beyond the 48-bit virtual address space: fails unconditionally
        # at allocation on every host (a merely-huge size can mmap fine
        # under overcommit and get the process OOM-killed instead)
        with pytest.raises(Exception) as ei:
            jnp.zeros((1 << 55,), jnp.float32).block_until_ready()
        assert _is_oom(ei.value), (
            "JAX's real OOM exception no longer matches _is_oom: "
            f"{type(ei.value).__name__}: {str(ei.value)[:200]}"
        )
        assert not _is_oom(ValueError("unrelated"))

    def test_search_shrinks_blocks_on_device_oom(self, monkeypatch, caplog):
        """First dispatch at full block size raises an OOM-shaped error;
        the driver must halve the blocks and complete the search with
        identical candidates — logging the retry and recording it as a
        structured telemetry event (old/new dm_block)."""
        from test_pipeline import make_synthetic_fil

        import tempfile

        from peasoup_tpu.io import read_filterbank
        from peasoup_tpu.pipeline import PeasoupSearch, SearchConfig
        from peasoup_tpu.pipeline.search import PeasoupSearch as PS

        with tempfile.TemporaryDirectory() as td:
            import pathlib

            path, _, _ = make_synthetic_fil(pathlib.Path(td))
            fil = read_filterbank(str(path))
            cfg = dict(dm_end=40.0, nharmonics=2, npdmp=0, limit=50,
                       dm_block=8)
            want = PeasoupSearch(SearchConfig(**cfg)).run(fil)

            search = PeasoupSearch(SearchConfig(**cfg))
            orig = PS._dispatch_chunk
            fails = {"n": 0}

            def flaky(self, chunk, *a, **k):
                if len(chunk[0]) > 4:  # full-size block: pretend OOM
                    fails["n"] += 1
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: Out of memory allocating "
                        "99999999999 bytes (fault injection)"
                    )
                return orig(self, chunk, *a, **k)

            monkeypatch.setattr(PS, "_dispatch_chunk", flaky)
            import logging

            from peasoup_tpu import obs

            tel = obs.RunTelemetry()
            with caplog.at_level(logging.WARNING, logger="peasoup_tpu"):
                with tel.activate():
                    got = search.run(fil)
            assert any(
                "retrying with" in r.getMessage() for r in caplog.records
            )
            ooms = [
                e for e in tel.events if e["kind"] == "oom_shrink_retry"
            ]
            assert ooms and ooms[0]["dm_block_old"] == 8
            assert ooms[0]["dm_block_new"] == 4
            assert fails["n"] >= 1
            assert len(got.candidates) == len(want.candidates) > 0
            # halved blocks change the batched-FFT shape, which nudges
            # f32 accumulation in the last bits — candidates must agree
            # to fp noise, not bitwise
            for a, b in zip(want.candidates, got.candidates):
                assert a.freq == b.freq
                assert abs(a.snr - b.snr) < 1e-4 * max(1.0, abs(a.snr))


def test_checkpoint_slice_union_and_filter(tmp_path):
    """Per-slice stores are GLOBAL-keyed: a reader with any other slice
    bounds (or none) sees the union, filtered and re-localised."""
    base = str(tmp_path / "ck.npz")
    # two "processes" write disjoint slices with LOCAL keys
    SearchCheckpoint(base, "k", slice_bounds=(0, 3)).save(_fake_results([0, 1, 2]))
    SearchCheckpoint(base, "k", slice_bounds=(3, 6)).save(_fake_results([0, 2], seed=1))
    # a single-process reader sees every completed global trial
    full = SearchCheckpoint(base, "k").load()
    assert sorted(full) == [0, 1, 2, 3, 5]
    # a differently-sliced reader gets its window, re-localised
    part = SearchCheckpoint(base, "k", slice_bounds=(2, 6)).load()
    assert sorted(part) == [0, 1, 3]  # globals 2, 3, 5


def test_checkpoint_process_count_independent(tutorial_fil, tmp_path):
    """A checkpoint written by a 2-process (sliced) run resumes in a
    1-process run with ZERO re-searched trials (VERDICT r2 item 7)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.pipeline.search import PeasoupSearch, SearchConfig

    fil = read_filterbank(tutorial_fil)
    base = str(tmp_path / "search.ckpt.npz")
    common = dict(dm_end=30.0, acc_start=0.0, acc_end=0.0, npdmp=0)

    ref_search = PeasoupSearch(SearchConfig(**common))
    ndm = ref_search.build_dm_plan(fil).ndm
    assert ndm >= 4
    ref = ref_search.run(fil)

    # "two processes": disjoint slices, each checkpointing to the base
    k = ndm // 2
    PeasoupSearch(SearchConfig(checkpoint_file=base, **common)).run(
        fil, dm_slice=(0, k), finalize=False
    )
    PeasoupSearch(SearchConfig(checkpoint_file=base, **common)).run(
        fil, dm_slice=(k, ndm), finalize=False
    )

    # one process resumes the union; every trial must restore
    resumer = PeasoupSearch(SearchConfig(checkpoint_file=base, **common))
    waves_searched = []
    orig = PeasoupSearch._search_wave

    def spy(self, todo, *a, **kw):
        waves_searched.append(len(todo))
        return orig(self, todo, *a, **kw)

    PeasoupSearch._search_wave = spy
    try:
        resumed = resumer.run(fil)
    finally:
        PeasoupSearch._search_wave = orig
    assert waves_searched == [], waves_searched  # zero re-searched trials
    assert len(resumed.candidates) == len(ref.candidates) > 0
    for ca, cb in zip(resumed.candidates, ref.candidates):
        assert ca.freq == cb.freq and ca.snr == cb.snr and ca.dm == cb.dm
