"""Golden end-to-end recall gate vs the reference CUDA run.

The reference ships tutorial.fil plus the outputs of `peasoup -i
tutorial.fil --dm_end 250 --acc_start -5 --acc_end 5 --npdmp 10 -p`
(SURVEY.md section 4: example_output/{overview.xml,candidates.peasoup},
10 candidates, top one P=249.94 ms at DM=19.76 with S/N 87).  This test
runs our full pipeline with the same flags through the real CLI (so the
output writers are exercised too) and gates on 100% recall of the 10
golden candidates via peasoup_tpu.tools.recall.

Parity status after the round-3 delay-math fix (dedisp's 4.15e3
constant + f32 rounding chain, see plan/dm_plan.py and
tools/divergence.py):
- freq: BIT-EXACT (f32) on all 10; DM: bit-exact; nh: exact.
- snr: within 2e-4 relative on every candidate (was 0.6% in round 2 —
  the residual is TPU-vs-cuFFT FFT ULP, measured <= 4.2e-3 absolute
  S/N against the f64 oracle; see PARITY.md ULP analysis).
- acc: every candidate's acc is a member of the exact-tie cluster
  {0, -5, +5} (at tutorial scale |a|<=5 shifts < 0.5 samples, so all
  three accel trials produce BITWISE-IDENTICAL spectra).  The
  reference crowns a tie member via std::sort's unstable arrangement;
  we replay the same libstdc++ introsort (native ps_snr_sort_perm) and
  match the crowned member on exactly 6 of 10.  Round 5 CLOSED the
  question of the other four: the Monte-Carlo proof
  (test_acc_tie_crowns_are_noise, PARITY.md r5) shows ALL TEN crowns
  flip under S/N perturbations 40x below the combined FFT-rounding
  bound — crown identity is comparator noise, and 6/10 is within
  chance of the 10/3 a uniform 3-way draw expects.
"""

import os

import numpy as np
import pytest

from peasoup_tpu.tools.recall import GOLDEN_OVERVIEW, match_golden

GOLDEN_DIR = os.path.dirname(GOLDEN_OVERVIEW)

pytestmark = pytest.mark.skipif(
    not os.path.exists(GOLDEN_OVERVIEW), reason="golden outputs not available"
)


@pytest.fixture(scope="session")
def golden_run_outdir(tutorial_fil, tmp_path_factory):
    """One full golden-flags CLI run per test session (~100 s on CPU).
    Also captures the raw pre-sort distill rows (PEASOUP_TIE_CAPTURE)
    so the acc-tie Monte-Carlo proof reuses this run."""
    from peasoup_tpu.cli.peasoup import main

    outdir = str(tmp_path_factory.mktemp("golden_run"))
    os.environ["PEASOUP_TIE_CAPTURE"] = os.path.join(
        outdir, "tie_capture.npz"
    )
    try:
        rc = main(
            [
                "-i", tutorial_fil,
                "-o", outdir,
                "--dm_end", "250",
                "--acc_start", "-5",
                "--acc_end", "5",
                "--npdmp", "10",
            ]
        )
    finally:
        os.environ.pop("PEASOUP_TIE_CAPTURE", None)
    assert rc == 0
    return outdir


def test_golden_recall_100pct(golden_run_outdir):
    rep = match_golden(os.path.join(golden_run_outdir, "overview.xml"))
    print("\n" + rep.summary())
    assert rep.n_golden == 10
    assert rep.recall == 1.0, rep.summary()
    # Every matched candidate's S/N within 25% (measured: within 0.6%).
    assert rep.snr_ok_frac == 1.0, rep.summary()


def test_golden_matches_are_tight(golden_run_outdir):
    """Beyond recall: frequency and DM bit-exact, nh exact, S/N within
    5e-4 (measured 2e-4), acc within the exact-tie cluster with the
    crowned winner matching the reference's std::sort arrangement on
    exactly the measured 6/10 (crown identity is PROVEN comparator
    noise — test_acc_tie_crowns_are_noise / PARITY.md r5), and the ten
    golden candidates occupy the top ten ranks of our list.

    Gates are set to the round-3 MEASURED state, not loose floors, so
    any drift is caught.  The CLI run under test uses the production
    default dedupe_accel=ON; brute force is covered transitively by the
    bitwise dedupe==brute equality test
    (tests/test_pipeline.py::test_identity_dedupe_bitwise_equal)."""
    rep = match_golden(os.path.join(golden_run_outdir, "overview.xml"))
    n_acc_exact = 0
    for m in rep.matches:
        assert m.matched
        assert m.dfreq_rel == 0.0, m
        assert m.ddm == 0.0, m
        assert m.dnh == 0, m
        assert abs(m.dsnr_rel) < 5e-4, m
        # tutorial-scale accel trials are exact ties (resample shift
        # under half a sample): any crowned member is value-identical
        assert m.golden_acc + m.dacc in (-5.0, 0.0, 5.0), m
        n_acc_exact += m.dacc == 0.0
    # EXACT measured state (r5): crown identity is PROVEN comparator
    # noise for all ten candidates (test_acc_tie_crowns_are_noise /
    # PARITY.md r5 closure) — any value in 0..10 would be equally
    # "correct"; this equality is a numerics-drift tripwire only.
    # If a deliberate numeric change flips it, re-measure and repin.
    assert n_acc_exact == 6, [m.dacc for m in rep.matches]
    # every golden candidate at its EXACT golden rank: the final order
    # is max(snr, folded_snr) desc (folder.hpp:25-31), so this also
    # pins fold-S/N parity at the rank-deciding level (the r3 f32-tsamp
    # fold fix closed the last rank swap)
    assert [m.our_rank for m in rep.matches] == list(range(10)), [
        m.our_rank for m in rep.matches
    ]


def test_golden_binary_parses(golden_run_outdir):
    """Our candidates.peasoup is byte-offset addressable like the
    reference's (output_stats.hpp:221-270) and FOLD blocks exist for the
    npdmp=10 folded candidates."""
    from peasoup_tpu.tools.parsers import CandidateFileParser, OverviewFile

    o = OverviewFile(os.path.join(golden_run_outdir, "overview.xml"))
    with CandidateFileParser(
        os.path.join(golden_run_outdir, "candidates.peasoup")
    ) as p:
        n_folds = 0
        for row in o.candidates:
            rec = p.read_candidate(int(row["byte_offset"]))
            assert len(rec["hits"]) >= 1
            if rec["fold"] is not None:
                n_folds += 1
                assert np.isfinite(rec["fold"]).all()
    assert n_folds >= 10


def test_golden_fold_parity(golden_run_outdir):
    """Quantitative fold parity vs the golden FOLD blocks (VERDICT r2
    item 6): shift-aligned profile correlation > 0.9995, opt_period
    matching the reference's quirk formula (folder.hpp:330) to f32
    print precision, folded_snr within 2% (measured after the r3
    f32-tsamp fold fix: corr >= 0.9998, |dsnr| <= 0.25% — the fold's
    phase-bin assignment now replays the reference's f32 tsamp, so the
    residual is FFT ULP on the dereddened input plus the reference's
    own nondeterministic atomicAdd ordering)."""
    from peasoup_tpu.tools.parsers import CandidateFileParser, OverviewFile

    def folds(ov_path, pea_path):
        out = {}
        ov = OverviewFile(ov_path)
        with CandidateFileParser(pea_path) as p:
            for row in ov.candidates:
                rec = p.read_candidate(int(row["byte_offset"]))
                key = (
                    round(float(row["dm"]), 4),
                    round(1 / float(row["period"]), 5),
                )
                out[key] = (
                    rec["fold"],
                    float(row["folded_snr"]),
                    float(row["opt_period"]),
                )
        return out

    g = folds(
        os.path.join(GOLDEN_DIR, "overview.xml"),
        os.path.join(GOLDEN_DIR, "candidates.peasoup"),
    )
    o = folds(
        os.path.join(golden_run_outdir, "overview.xml"),
        os.path.join(golden_run_outdir, "candidates.peasoup"),
    )
    n_checked = 0
    for key, (gf, gfs, gop) in g.items():
        assert key in o, (key, sorted(o))
        of, ofs, oop = o[key]
        if gf is None or of is None:
            continue
        gp = np.asarray(gf, np.float64).reshape(16, 64).sum(axis=0)
        op = np.asarray(of, np.float64).reshape(16, 64).sum(axis=0)
        gp = (gp - gp.mean()) / gp.std()
        op = (op - op.mean()) / op.std()
        corr = max(
            np.corrcoef(gp, np.roll(op, s))[0, 1] for s in range(64)
        )
        assert corr > 0.9995, (key, corr)
        assert abs(oop - gop) / gop < 1e-6, (key, oop, gop)
        assert abs(ofs - gfs) / max(gfs, 1.0) < 0.02, (key, ofs, gfs)
        n_checked += 1
    assert n_checked >= 10


def test_acc_tie_crowns_are_noise(golden_run_outdir):
    """The acc-tie closure proof (PARITY.md round 5, VERDICT r4 item
    4): every golden candidate's crowned acceleration flips under iid
    S/N perturbations of 1e-5 — 40x below the combined FFT-rounding
    bound of the two implementations (ours <= 4.2e-3, CUDA ~1e-4) —
    so crown identity is comparator noise, not a reproducible target.
    Also checks the offline replay is faithful: unperturbed replay
    crowns == the actual CLI run's crowns."""
    from peasoup_tpu.tools.parsers import OverviewFile
    from peasoup_tpu.tools.tie_mc import (
        crowns_for_golden, load_capture, mc_crown_stability, replay,
    )

    cap_path = os.path.join(golden_run_outdir, "tie_capture.npz")
    assert os.path.exists(cap_path), "driver capture hook did not fire"
    cap = load_capture(cap_path)
    g = OverviewFile(GOLDEN_OVERVIEW).candidates
    golden_freqs = 1.0 / np.asarray([float(r["period"]) for r in g])

    # replay fidelity: same crowns as the real run
    ours = OverviewFile(
        os.path.join(golden_run_outdir, "overview.xml")
    ).candidates
    base = crowns_for_golden(replay(cap, cap["snr"]), golden_freqs)
    assert all(b is not None for b in base)
    our_by_freq = {}
    for r in ours:
        our_by_freq[round(1.0 / float(r["period"]), 4)] = float(r["acc"])
    for gf, b in zip(golden_freqs, base):
        key = round(float(gf), 4)
        assert key in our_by_freq, (key, sorted(our_by_freq))
        assert abs(our_by_freq[key] - b[0]) < 1e-9, (key, our_by_freq[key], b)

    # the proof: at delta ONE-FORTIETH of the combined bound, every
    # crown is unstable (measured: ~uniform over the {0,-5,+5} tie
    # cluster at 200 draws; 30 draws make P(all-same-by-chance) ~ 3e-14
    # per candidate, so this cannot flake)
    res = mc_crown_stability(
        cap, golden_freqs, n_draws=30, delta=1e-5, seed=2
    )
    assert sum(res["unstable"]) == 10, res["histograms"]


# ---- fast unit tests of the matcher itself (no pipeline run) ----------


def test_matcher_self_match():
    rep = match_golden(GOLDEN_OVERVIEW, GOLDEN_OVERVIEW)
    assert rep.recall == 1.0
    for m in rep.matches:
        assert m.dfreq_rel == 0.0 and m.ddm == 0.0 and m.dnh == 0


def test_matcher_rejects_unrelated():
    from peasoup_tpu.tools.parsers import OverviewFile

    g = OverviewFile(GOLDEN_OVERVIEW).candidates
    shifted = g.copy()
    shifted["period"] = shifted["period"] * 1.5  # off-tolerance everywhere
    rep = match_golden(shifted, g)
    assert rep.recall == 0.0
