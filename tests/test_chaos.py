"""Chaos-soak harness tests: the end-to-end survival contract under a
seeded compound fault schedule (flaky reads + sqlite contention + a
worker kill), the stream replay drill, and the report/CLI plumbing.
These are the acceptance tests for the composition of every recovery
path — the unit drills live in test_resilience.py.
"""

import json
import os

import pytest

from peasoup_tpu.resilience import faults
from peasoup_tpu.resilience.stats import STATS
from peasoup_tpu.tools import chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    STATS.reset()
    yield
    faults.configure(None)
    STATS.reset()


class TestCampaignSoak:
    def test_compound_schedule_survives(self, tmp_path):
        """The acceptance schedule: flaky reads + one sqlite lock +
        one worker kill over a 3-obs campaign. Every invariant must
        hold: exactly-once, bitwise-equal candidates, clean tree,
        valid telemetry, bounded + attributed recovery."""
        sec = chaos.run_campaign_soak(
            str(tmp_path),
            "fil.read:p=0.25:n=4,db.ingest:at=1,worker.kill:at=obs0",
            seed=7,
            n_obs=3,
            lease_s=0.8,
        )
        assert sec["violations"] == []
        assert sec["queue"]["done"] == 3
        assert sec["queue"]["quarantined"] == 0
        assert sec["chaos"]["workers_killed"] == 1
        inj = {r["site"] for r in sec["injections"]["injected"]}
        assert "worker.kill" in inj
        # attribution: each fired transient site shows recovery marks
        stats = sec["stats"]
        for site in inj & {"fil.read", "db.ingest"}:
            assert stats["retries"].get(site) or stats[
                "recoveries"
            ].get(site), (site, stats)
        # the kill's recovery is the reaper: the killed job re-ran
        from peasoup_tpu.campaign.queue import JobQueue

        done = JobQueue(os.path.join(tmp_path, "chaos")).done_records()
        assert any(int(d.get("attempts", 1)) > 1 for d in done)
        # rollup carries the aggregated per-job resilience deltas
        from peasoup_tpu.campaign.rollup import load_campaign_status

        st = load_campaign_status(
            os.path.join(tmp_path, "chaos", "campaign_status.json")
        )
        assert "resilience" in st

    def test_rejects_non_transient_schedule(self, tmp_path):
        with pytest.raises(ValueError, match="unknown fault site"):
            chaos.run_campaign_soak(str(tmp_path), "bogus.site:n=1", 1)


class TestStreamSoak:
    def test_replay_faults_reproduce_triggers(self, tmp_path):
        sec = chaos.run_stream_soak(
            str(tmp_path), "fil.read:at=replay:n=2", seed=7
        )
        assert sec["violations"] == []
        assert sec["n_triggers"] >= 1
        assert sec["stats"]["faults_injected"]["fil.read"] == 2
        assert sec["stats"]["recoveries"].get("fil.read", 0) >= 1

    def test_rejects_non_stream_sites(self, tmp_path):
        with pytest.raises(ValueError, match="fil.read only"):
            chaos.run_stream_soak(str(tmp_path), "worker.kill", 1)


class TestCLI:
    def test_main_writes_report_and_exits_zero(self, tmp_path, capsys):
        rc = chaos.main(
            [
                "--mode", "stream", "-o", str(tmp_path),
                "--seed", "7",
            ]
        )
        assert rc == 0
        with open(tmp_path / "chaos_report.json") as f:
            report = json.load(f)
        assert report["schema"] == chaos.REPORT_SCHEMA
        assert report["ok"] is True
        assert report["stream"]["violations"] == []
        out = capsys.readouterr().out
        assert "SURVIVED" in out
