"""Single-pulse search subsystem tests (ops -> pipeline -> CLI -> IO).

Acceptance gates (ISSUE 3): injection recovery with analytic
matched-filter S/N, one-cluster clustering of a broad pulse, and
``.singlepulse`` + overview.xml round-trips through the parsers.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from peasoup_tpu.io.sigproc import (
    Filterbank,
    SigprocHeader,
    read_filterbank,
    write_filterbank,
)
from peasoup_tpu.ops.singlepulse import (
    boxcar_best,
    boxcar_best_twin,
    default_widths,
    make_single_pulse_search_fn,
    matched_filter_snr,
    normalise_trials,
    plan_pad,
    prefix_sum_padded,
    width_extent,
    width_scales,
)
from peasoup_tpu.pipeline.single_pulse import (
    SinglePulseConfig,
    SinglePulseSearch,
    cluster_events_fof,
    _EVENT_DTYPE,
)
from peasoup_tpu.plan.dm_plan import DMPlan


# --------------------------------------------------------------------------
# device ops
# --------------------------------------------------------------------------

class TestBoxcarOps:
    def test_best_plane_matches_bruteforce(self, rng):
        x = rng.normal(size=(3, 3000)).astype(np.float32)
        x[1, 700:716] += 6.0
        widths = default_widths(6)
        norm = np.asarray(normalise_trials(jnp.asarray(x)))
        best, bw = boxcar_best(jnp.asarray(norm), widths)
        best, bw = np.asarray(best), np.asarray(bw)
        t = x.shape[1]
        for d in range(x.shape[0]):
            planes = np.full((len(widths), t), -np.inf)
            for k, w in enumerate(widths):
                conv = np.convolve(norm[d], np.ones(w), "valid")
                planes[k, : t - w + 1] = conv * (1.0 / np.sqrt(w)).astype(
                    np.float32
                )
            ref_best = planes.max(axis=0)
            ref_w = planes.argmax(axis=0)
            assert np.allclose(best[d, :t], ref_best, rtol=2e-5, atol=2e-5)
            # argmax ties broken identically off-noise is not guaranteed
            # by float assoc; check where the margin is clear
            margin = np.partition(planes, -2, axis=0)
            clear = ref_best - margin[-2] > 1e-3
            assert np.array_equal(bw[d, :t][clear], ref_w[clear])

    def test_validity_tail_is_masked(self, rng):
        x = rng.normal(size=(1, 1500)).astype(np.float32)
        widths = (1, 4, 16)
        best, bw = map(np.asarray, boxcar_best(jnp.asarray(x), widths))
        t = 1500
        # a boxcar starting past t - w must never win: the last 15
        # samples can only carry widths whose window still fits
        for j in range(t - 16, t):
            wsel = widths[bw[0, j]]
            assert j + wsel <= t
        # padded region (t..tpad) is all -inf
        assert np.all(np.isneginf(best[0, t:]))

    def test_normalise_is_zero_mean_unit_std(self, rng):
        x = (rng.normal(40.0, 5.0, size=(4, 8192))).astype(np.float32)
        n = np.asarray(normalise_trials(jnp.asarray(x)))
        assert np.abs(n.mean(axis=1)).max() < 0.05
        assert np.abs(n.std(axis=1) - 1.0).max() < 0.05

    def test_normalise_resists_bright_pulse(self, rng):
        x = rng.normal(0.0, 1.0, size=(1, 8192)).astype(np.float32)
        y = x.copy()
        y[0, 100:160] += 50.0  # would inflate a naive std by ~4x
        nx = np.asarray(normalise_trials(jnp.asarray(x)))
        ny = np.asarray(normalise_trials(jnp.asarray(y)))
        # the clipped re-estimate must keep the noise scale unchanged
        assert np.allclose(nx[0, 200:], ny[0, 200:], atol=0.05)

    def test_search_fn_finds_pulse_at_exact_sample(self, rng):
        x = rng.normal(size=(2, 6000)).astype(np.float32)
        t0, w, amp = 2500, 8, 6.0
        x[1, t0 : t0 + w] += amp
        widths = default_widths(6)
        fn = make_single_pulse_search_fn(widths, 6.0, 64, 32, 0)
        samples, widx, snrs, counts = map(np.asarray, fn(jnp.asarray(x)))
        assert counts[0] == 0
        assert counts[1] >= 1
        k = np.argmax(snrs[1])
        assert abs(int(samples[1, k]) - t0) <= 1
        assert widths[int(widx[1, k])] == w
        # the matched filter integrates the window's noise too: one
        # realization scatters by ~N(0, 1) around the expectation
        exp = matched_filter_snr(amp, w, 1.0)
        assert abs(float(snrs[1, k]) - exp) < 3.5


class TestPallasBoxcar:
    """Interpret-mode kernel vs the jnp twin: BITWISE (the same gate
    probe_pallas_boxcar applies on real TPU toolchains)."""

    @pytest.mark.parametrize("t,nw", [(5000, 8), (20000, 11)])
    def test_bitwise_vs_twin(self, rng, t, nw):
        from peasoup_tpu.ops.pallas.boxcar import boxcar_best_pallas

        x = rng.normal(size=(3, t)).astype(np.float32)
        x[0, t // 2 : t // 2 + 12] += 20.0
        widths = default_widths(nw)
        tpad, span = plan_pad(t)
        wext = width_extent(widths)
        norm = normalise_trials(jnp.asarray(x))
        csum = prefix_sum_padded(norm, tpad, wext)
        scales = width_scales(widths)
        gb, gw = boxcar_best_pallas(
            csum, widths, scales, t, tpad, span=span, interpret=True
        )
        rb, rw = boxcar_best_twin(csum, widths, scales, t, tpad)
        assert np.array_equal(np.asarray(gb), np.asarray(rb))
        assert np.array_equal(np.asarray(gw), np.asarray(rw))

    def test_geometry_guard(self, rng):
        from peasoup_tpu.ops.pallas.boxcar import boxcar_best_pallas

        widths = default_widths(4)
        csum = jnp.zeros((1, 2048 + 1024), jnp.float32)
        with pytest.raises(ValueError):
            boxcar_best_pallas(
                csum, widths, width_scales(widths), 2000, 2048, span=999,
                interpret=True,
            )


class TestSpchainRetileFallback:
    """The Mosaic retile fallback ladder (ISSUE 13 satellite): when the
    toolchain probe rejects the fused spchain kernel's (span/dec, dec)
    reshape at the full tile span, the driver tries RETILED spans
    before dropping to the boxcar kernel, and only then the jnp twin —
    each fallback logged as a resilience degradation rung (and none of
    it on backends without Pallas at all, where the twin is the design
    point)."""

    def _patch(self, monkeypatch, supports, spchain_ok, boxcar_ok):
        import peasoup_tpu.ops.pallas as pallas_mod

        monkeypatch.setattr(
            pallas_mod, "backend_supports_pallas", lambda: supports
        )
        monkeypatch.setattr(
            pallas_mod, "probe_pallas_spchain",
            lambda nw, span, dec: spchain_ok(span),
        )
        monkeypatch.setattr(
            pallas_mod, "probe_pallas_boxcar",
            lambda nw, span: boxcar_ok,
        )

    def test_full_span_accepted_no_rung(self, monkeypatch):
        from peasoup_tpu.pipeline.single_pulse import select_sp_kernels

        self._patch(monkeypatch, True, lambda s: True, True)
        widths = default_widths(6)
        assert select_sp_kernels(widths, 8192, 16384, 32, True) == (
            0, 8192, None,
        )

    def test_retiled_span_fallback(self, monkeypatch):
        """Full span rejected, half span accepted: the fused kernel
        still runs — retiled — and the rung names the retile."""
        from peasoup_tpu.pipeline.single_pulse import select_sp_kernels

        self._patch(
            monkeypatch, True, lambda s: s <= 4096, True
        )
        widths = default_widths(6)
        assert select_sp_kernels(widths, 8192, 16384, 32, True) == (
            0, 4096, "spchain_retile",
        )

    def test_boxcar_fallback_when_no_retile_fits(self, monkeypatch):
        from peasoup_tpu.pipeline.single_pulse import select_sp_kernels

        self._patch(monkeypatch, True, lambda s: False, True)
        widths = default_widths(6)
        assert select_sp_kernels(widths, 8192, 16384, 32, True) == (
            8192, 0, "boxcar_kernel",
        )

    def test_jnp_twin_last_rung(self, monkeypatch):
        from peasoup_tpu.pipeline.single_pulse import select_sp_kernels

        self._patch(monkeypatch, True, lambda s: False, False)
        widths = default_widths(6)
        assert select_sp_kernels(widths, 8192, 16384, 32, True) == (
            0, 0, "jnp_twin",
        )

    def test_no_rung_on_backends_without_pallas(self, monkeypatch):
        """CPU (or any backend the probes decline wholesale): the twin
        is the design point — no degradation is logged."""
        from peasoup_tpu.pipeline.single_pulse import select_sp_kernels

        self._patch(monkeypatch, False, lambda s: False, False)
        widths = default_widths(6)
        assert select_sp_kernels(widths, 8192, 16384, 32, True) == (
            0, 0, None,
        )
        # and with use_pallas off nothing probes at all
        assert select_sp_kernels(widths, 8192, 16384, 32, False) == (
            0, 0, None,
        )

    def test_driver_logs_degradation_event(self, monkeypatch, tmp_path):
        """End-to-end: a pallas-capable backend whose probes reject
        everything runs the twin AND flips the resilience degradation
        table — operators see the fallback, candidates stay correct."""
        from peasoup_tpu.io.sigproc import read_filterbank
        from peasoup_tpu.resilience.stats import STATS

        path, _, _ = make_sp_fil(
            tmp_path, nsamps=1 << 12, dm_end=20.0, t0=1500
        )
        fil = read_filterbank(path)
        cfg = SinglePulseConfig(dm_end=20.0, min_snr=7.0, n_widths=6)
        ref = SinglePulseSearch(cfg).run(fil)
        self._patch(monkeypatch, True, lambda s: False, False)
        STATS.reset()
        got = SinglePulseSearch(cfg).run(fil)
        deg = STATS.snapshot()["degradations"]
        assert deg.get("spsearch.kernel:jnp_twin") == 1, deg
        assert [
            (c.dm_idx, c.sample, c.width, c.snr) for c in got.candidates
        ] == [
            (c.dm_idx, c.sample, c.width, c.snr) for c in ref.candidates
        ]

    def test_retiled_kernel_bitwise_vs_twin(self, rng):
        """A retiled (smaller-than-plan) span is still bitwise the
        twin — the geometry the fallback ladder routes to is gated by
        the same oracle as the full span."""
        from peasoup_tpu.ops.pallas.spchain import boxcar_dec_best_pallas
        from peasoup_tpu.ops.singlepulse import boxcar_dec_best_twin

        t, dec = 4096, 32
        x = rng.normal(size=(2, t)).astype(np.float32)
        x[1, 700:712] += 20.0
        widths = default_widths(6)
        tpad, span = plan_pad(t)  # span == tpad == 4096 here
        retiled = span // 2  # 2048: divides tpad, multiple of dec
        wext = width_extent(widths)
        norm = normalise_trials(jnp.asarray(x))
        csum = prefix_sum_padded(norm, tpad, wext)
        scales = width_scales(widths)
        got = boxcar_dec_best_pallas(
            csum, widths, scales, t, tpad, dec, span=retiled,
            interpret=True,
        )
        ref = boxcar_dec_best_twin(csum, widths, scales, t, tpad, dec)
        for g, r in zip(got, ref):
            assert np.array_equal(np.asarray(g), np.asarray(r))


# --------------------------------------------------------------------------
# friends-of-friends clustering
# --------------------------------------------------------------------------

class TestClustering:
    def test_links_width_ladder_and_dm_chain(self):
        widths = (1, 2, 4, 8, 16, 32, 64)
        # one pulse seen at 3 DM trials, several widths, nearby samples
        rows = [
            (10, 5000, 3, 12.0), (10, 4996, 4, 10.0), (11, 5001, 3, 11.0),
            (12, 5002, 3, 9.0), (11, 4970, 6, 8.0),
            # and a second, unrelated pulse far away in time
            (10, 9000, 0, 7.5),
        ]
        ev = np.asarray(rows, dtype=_EVENT_DTYPE)
        clusters = cluster_events_fof(ev, widths, dm_link=2, dec=32)
        sizes = sorted(len(c) for c in clusters)
        assert sizes == [1, 5]

    def test_dm_gap_splits(self):
        widths = (1, 2, 4)
        rows = [(0, 100, 0, 8.0), (10, 100, 0, 8.0)]
        ev = np.asarray(rows, dtype=_EVENT_DTYPE)
        clusters = cluster_events_fof(ev, widths, dm_link=2, dec=0)
        assert len(clusters) == 2

    def test_empty(self):
        ev = np.asarray([], dtype=_EVENT_DTYPE)
        assert cluster_events_fof(ev, (1, 2)) == []


# --------------------------------------------------------------------------
# pipeline-level: synthetic injections
# --------------------------------------------------------------------------

def make_sp_fil(
    tmp_path,
    nsamps=1 << 15,
    nchans=16,
    tsamp=0.000256,
    fch1=1400.0,
    foff=-8.0,
    dm_end=60.0,
    t0=9000,
    width=8,
    amp=9.0,
    seed=3,
    name="sp.fil",
):
    """8-bit filterbank with one dispersed top-hat pulse injected with
    the search's OWN delay table at the middle DM trial, so the
    analytic matched-filter S/N applies exactly at that trial."""
    plan = DMPlan.create(
        nsamps=nsamps, nchans=nchans, tsamp=tsamp, fch1=fch1, foff=foff,
        dm_start=0.0, dm_end=dm_end, pulse_width=64.0, tol=1.10,
    )
    idx = plan.ndm // 2
    delays = plan.delay_samples()[idx]
    rng = np.random.default_rng(seed)
    data = rng.normal(32.0, 4.0, size=(nsamps, nchans))
    for c in range(nchans):
        lo = t0 + delays[c]
        data[lo : lo + width, c] += amp
    data = np.clip(np.rint(data), 0, 255).astype(np.uint8)
    hdr = SigprocHeader(
        source_name="SPFAKE", tsamp=tsamp, tstart=55000.0, fch1=fch1,
        foff=foff, nchans=nchans, nbits=8, nifs=1, data_type=1,
    )
    path = tmp_path / name
    write_filterbank(path, Filterbank(header=hdr, data=data))
    return path, plan, idx


class TestInjectionRecovery:
    def test_recovers_injected_pulse(self, tmp_path):
        """ISSUE acceptance: right DM trial, right time sample, width
        within one log-spaced step, S/N within 10% of the analytic
        matched-filter expectation."""
        nchans, width, amp = 16, 8, 9.0
        t0 = 9000
        path, plan, idx = make_sp_fil(
            tmp_path, nchans=nchans, width=width, amp=amp, t0=t0
        )
        fil = read_filterbank(path)
        cfg = SinglePulseConfig(dm_end=60.0, min_snr=7.0, n_widths=8)
        res = SinglePulseSearch(cfg).run(fil)
        assert len(res.candidates) >= 1
        top = res.candidates[0]
        assert top.dm_idx == idx
        assert abs(top.sample - t0) <= 2
        # detected width within one octave step of the injected width
        k_true = int(np.log2(width))
        assert abs(top.width_idx - k_true) <= 1
        # analytic matched filter: the dedispersed trial sums nchans
        # channels (noise std 4 each -> 16) and scales by
        # output_scale(8, 16) = 1/16, so sigma = 1.0 and the summed
        # pulse amplitude is nchans * amp / 16
        exp = matched_filter_snr(nchans * amp * (1.0 / 16.0), width, 1.0)
        assert abs(top.snr / exp - 1.0) < 0.10

    def test_broad_pulse_is_one_cluster_and_roundtrips(self, tmp_path):
        """ISSUE acceptance: ONE candidate cluster for a broad pulse
        (not one per width/DM trial), and the .singlepulse table + XML
        section round-trip through the parsers."""
        from peasoup_tpu.io.output import (
            OutputFileWriter,
            write_singlepulse,
        )
        from peasoup_tpu.tools.parsers import OverviewFile, read_singlepulse

        width = 64
        path, plan, idx = make_sp_fil(
            tmp_path, width=width, amp=4.0, t0=8000, name="broad.fil"
        )
        fil = read_filterbank(path)
        cfg = SinglePulseConfig(dm_end=60.0, min_snr=7.0, n_widths=10)
        res = SinglePulseSearch(cfg).run(fil)
        assert res.n_events > 1  # the pulse fired many (trial, width) cells
        assert len(res.candidates) == 1
        top = res.candidates[0]
        assert abs(top.width_idx - int(np.log2(width))) <= 1
        assert top.members > 1
        assert top.sample_lo <= top.sample <= top.sample_hi
        assert top.dm_idx_lo <= top.dm_idx <= top.dm_idx_hi

        # round-trip: text table
        table_path = str(tmp_path / "cands.singlepulse")
        write_singlepulse(table_path, res.candidates)
        tab = read_singlepulse(table_path)
        assert len(tab) == 1
        assert int(tab["sample"][0]) == top.sample
        assert int(tab["width"][0]) == top.width
        assert tab["snr"][0] == pytest.approx(top.snr, rel=1e-4)
        assert tab["dm"][0] == pytest.approx(top.dm, rel=1e-5)
        assert int(tab["members"][0]) == top.members

        # round-trip: overview.xml single-pulse section
        w = OutputFileWriter()
        w.add_misc_info()
        w.add_header(fil.header)
        w.add_dm_list(res.dm_list)
        w.add_single_pulse_section(cfg, str(path), res.widths, res.candidates)
        w.add_timing_info(res.timers)
        xml_path = str(tmp_path / "overview.xml")
        w.to_file(xml_path)
        ov = OverviewFile(xml_path)
        assert list(ov.sp_widths) == [int(x) for x in res.widths]
        assert len(ov.sp_candidates) == 1
        row = ov.sp_candidates[0]
        assert int(row["sample"]) == top.sample
        assert int(row["width"]) == top.width
        assert row["snr"] == pytest.approx(top.snr, rel=1e-4)
        assert float(ov.sp_parameters["min_snr"]) == cfg.min_snr
        # the periodicity candidate table stays empty/absent — the two
        # sections are disjoint
        assert len(ov.candidates) == 0

    def test_checkpoint_resume_reuses_trials(self, tmp_path):
        path, plan, idx = make_sp_fil(tmp_path, name="ck.fil")
        fil = read_filterbank(path)
        ck = str(tmp_path / "sp.ckpt")
        cfg = SinglePulseConfig(
            dm_end=60.0, min_snr=7.0, n_widths=8, checkpoint_file=ck
        )
        res1 = SinglePulseSearch(cfg).run(fil)
        assert os.path.exists(ck)

        # resume: every trial restores; the dedispersion stage is
        # skipped entirely (the resume fast path) and the candidate
        # list is identical
        res2 = SinglePulseSearch(cfg).run(fil)
        assert res2.timers["dedispersion"] < res1.timers["dedispersion"]
        assert len(res2.candidates) == len(res1.candidates)
        for a, b in zip(res1.candidates, res2.candidates):
            assert (a.dm_idx, a.sample, a.width, a.members) == (
                b.dm_idx, b.sample, b.width, b.members
            )
            assert a.snr == pytest.approx(b.snr)

        # a config that changes per-trial results invalidates the key
        cfg3 = SinglePulseConfig(
            dm_end=60.0, min_snr=8.5, n_widths=8, checkpoint_file=ck
        )
        from peasoup_tpu.pipeline.single_pulse import make_checkpoint_key

        k1 = make_checkpoint_key(
            cfg, fil, plan.ndm, SinglePulseSearch(cfg).widths_for(1024)
        )
        k3 = make_checkpoint_key(
            cfg3, fil, plan.ndm, SinglePulseSearch(cfg3).widths_for(1024)
        )
        assert k1 != k3

    def test_sharded_matches_single_device(self, tmp_path):
        """The 'dm' mesh path (virtual CPU devices) must reproduce the
        single-device candidate list."""
        path, plan, idx = make_sp_fil(tmp_path, name="mesh.fil")
        fil = read_filterbank(path)
        base = dict(dm_end=60.0, min_snr=7.0, n_widths=8)
        r1 = SinglePulseSearch(SinglePulseConfig(**base)).run(fil)
        r2 = SinglePulseSearch(
            SinglePulseConfig(**base, shard_devices=2)
        ).run(fil)
        key = lambda r: [
            (c.dm_idx, c.sample, c.width, round(c.snr, 4))
            for c in r.candidates
        ]
        assert key(r1) == key(r2)

    def test_sliced_event_merge_matches_full_run(self, tmp_path):
        """Satellite (multi-host spsearch): per-slice partial runs
        allgather-merged and finalized must reproduce the full run's
        clustered candidate list — the single-process twin of
        parallel/multihost.py:run_single_pulse_search (slice, merge
        events with GLOBAL dm_idx, cluster globally)."""
        from peasoup_tpu.parallel.multihost import dm_slice_for_process
        from peasoup_tpu.pipeline.single_pulse import (
            PartialSinglePulseResult,
        )

        path, plan, idx = make_sp_fil(tmp_path, name="slices.fil")
        fil = read_filterbank(path)
        cfg = SinglePulseConfig(dm_end=60.0, min_snr=7.0, n_widths=8)
        search = SinglePulseSearch(cfg)
        full = search.run(fil)

        parts = []
        for pid in range(3):
            lo, hi = dm_slice_for_process(plan.ndm, 3, pid)
            part = search.run(fil, dm_slice=(lo, hi), finalize=False)
            # events come back with GLOBAL dm_idx, inside the slice
            if len(part.events):
                assert part.events["dm_idx"].min() >= lo
                assert part.events["dm_idx"].max() < hi
            parts.append(part)
        merged = PartialSinglePulseResult(
            events=np.concatenate([p.events for p in parts]),
            dm_list=plan.dm_list,
            widths=parts[0].widths,
            timers=parts[0].timers,
            nsamps=parts[0].nsamps,
            n_overflowed=sum(p.n_overflowed for p in parts),
            t_total_start=parts[0].t_total_start,
        )
        got = search.finalize(fil, merged)
        key = lambda r: sorted(
            (c.dm_idx, c.sample, c.width, round(c.snr, 4))
            for c in r.candidates
        )
        assert key(got) == key(full)
        assert got.candidates[0].dm_idx == idx

    def test_run_single_pulse_search_single_process(self, tmp_path):
        """The multihost driver degrades to the plain search when
        process_count == 1 (every CI/CPU invocation)."""
        from peasoup_tpu.parallel.multihost import run_single_pulse_search

        path, plan, idx = make_sp_fil(tmp_path, name="mh1.fil")
        fil = read_filterbank(path)
        cfg = SinglePulseConfig(dm_end=60.0, min_snr=7.0, n_widths=8)
        res = run_single_pulse_search(fil, cfg)
        assert len(res.candidates) >= 1
        assert res.candidates[0].dm_idx == idx


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

class TestSpsearchCLI:
    def test_end_to_end(self, tmp_path):
        from peasoup_tpu.cli.spsearch import main as sp_main
        from peasoup_tpu.obs.schema import validate_manifest
        from peasoup_tpu.obs.telemetry import load_manifest
        from peasoup_tpu.tools.parsers import OverviewFile, read_singlepulse

        path, plan, idx = make_sp_fil(tmp_path, name="cli.fil")
        outdir = tmp_path / "out"
        rc = sp_main(
            [
                "-i", str(path), "-o", str(outdir), "--dm_end", "60",
                "-m", "7", "--n_widths", "8",
                "--status-json", str(outdir / "status.json"),
            ]
        )
        assert rc == 0
        tab = read_singlepulse(str(outdir / "candidates.singlepulse"))
        assert len(tab) >= 1
        assert int(tab["dm_idx"][0]) == idx
        ov = OverviewFile(str(outdir / "overview.xml"))
        assert len(ov.sp_candidates) == len(tab)
        assert "searching" in ov.execution_times
        assert "clustering" in ov.execution_times
        man = load_manifest(str(outdir / "telemetry.json"))
        validate_manifest(man)
        assert man["context"]["command"] == "spsearch"
        assert man["gauges"]["sp.n_dm_trials"] == plan.ndm
        assert man["gauges"]["candidates.written"] == len(tab)

    def test_version_flag(self, capsys):
        """Satellite: every CLI prints package + JAX version and the
        active backend."""
        import peasoup_tpu
        from peasoup_tpu.cli.coincidencer import build_parser as coin_bp
        from peasoup_tpu.cli.ffa import build_parser as ffa_bp
        from peasoup_tpu.cli.peasoup import build_parser as peasoup_bp
        from peasoup_tpu.cli.spsearch import build_parser as sp_bp

        for bp in (peasoup_bp, ffa_bp, coin_bp, sp_bp):
            with pytest.raises(SystemExit) as exc:
                bp().parse_args(["--version"])
            assert exc.value.code == 0
            out = capsys.readouterr().out
            assert peasoup_tpu.__version__ in out
            assert jax.__version__ in out
            assert "backend" in out
