"""Perf subsystem tests: AOT warmup (representative + bucket-
parameterised), the per-program microbenchmarks and their perf.json
schema, the perf-regression ratchet (baseline round-trip, tolerance
edges, regression/missing-program detection, --write-baseline cycle),
the registry completeness gate, and the peasoup-perf CLI exit codes.
"""

import copy
import json
import os

import pytest

from peasoup_tpu.obs.schema import SchemaError
from peasoup_tpu.ops.registry import (
    REGISTRY_ALIASES,
    ShapeCtx,
    _jit_entry_points_in,
    registered_programs,
    unregistered_entry_points,
)
from peasoup_tpu.perf.microbench import (
    load_perf,
    run_microbench,
    validate_perf,
    write_perf,
)
from peasoup_tpu.perf.ratchet import (
    baseline_from_perf,
    check_perf,
    load_baseline,
    timing_applies,
    write_baseline,
)
from peasoup_tpu.perf.warmup import (
    shape_ctx_for_bucket,
    warm_bucket,
    warm_registry,
)
from peasoup_tpu.tools.perf import main as perf_main

# small, fast programs for the subset tests (full-registry coverage is
# the check.sh gate and test_full_bench_against_repo_baseline)
FAST = [
    "ops.spectrum.form_power",
    "ops.spectrum.normalise",
    "ops.zap.zap_birdies",
]

BUCKET = (8, 8, 4096, 0.000256, 1400.0, -16.0)
SP_OVERRIDES = {"dm_end": 20.0, "min_snr": 7.0, "n_widths": 6}


@pytest.fixture
def fresh_cache(tmp_path, monkeypatch):
    """Point the persistent compilation cache at an empty directory
    (and restore the default location afterwards — the jax config is
    process-global)."""
    from peasoup_tpu.utils.cache import enable_compilation_cache

    cache = str(tmp_path / "xla_cache")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", cache)
    yield cache
    monkeypatch.undo()
    enable_compilation_cache()


# --------------------------------------------------------------------------
# registry completeness gate
# --------------------------------------------------------------------------

class TestRegistryCompleteness:
    def test_every_jit_entry_point_registered(self):
        """The gate itself: every top-level jitted entry point in ops/
        must have a registry entry (same name, underscore-stripped
        name, or REGISTRY_ALIASES) — otherwise it silently escapes
        warmup, contracts and benchmarks. Fix by registering it next
        to the op (and, for a new module, adding it to
        _PROGRAM_MODULES)."""
        assert unregistered_entry_points() == []

    def test_detector_finds_all_jit_idioms(self, tmp_path):
        """The AST detector sees decorated jits, partial(jax.jit, ...)
        statics, jit assignments, and lru_cache'd builders returning
        jax.jit(...) — the four idioms ops/ actually uses."""
        src = '''
import jax
from functools import lru_cache, partial

@jax.jit
def plain(x):
    return x

@partial(jax.jit, static_argnames=("n",))
def with_statics(x, *, n):
    return x * n

assigned = jax.jit(lambda x: x + 1)

@lru_cache(maxsize=None)
def builder(n):
    def run(x):
        return x * n
    return jax.jit(run)

def not_jitted(x):
    return x
'''
        p = tmp_path / "fake_ops.py"
        p.write_text(src)
        found = _jit_entry_points_in(str(p), "ops.fake_ops")
        assert sorted(found) == [
            "ops.fake_ops.assigned",
            "ops.fake_ops.builder",
            "ops.fake_ops.plain",
            "ops.fake_ops.with_statics",
        ]

    def test_aliases_point_at_real_registrations(self):
        names = {s.name for s in registered_programs()}
        for target in REGISTRY_ALIASES.values():
            assert target in names


# --------------------------------------------------------------------------
# AOT warmup
# --------------------------------------------------------------------------

class TestWarmup:
    def test_cold_then_warm(self, fresh_cache):
        """First pass compiles into the empty persistent cache; a
        second pass must trigger zero real recompiles — served by
        jax's in-memory executable cache within one process, by the
        persistent cache across processes (test_cold_start_next_
        process)."""
        cold = warm_registry(programs=FAST)
        assert cold.cache_dir == fresh_cache
        assert len(cold.programs) == len(FAST)
        assert not cold.errors
        assert cold.compiled == len(FAST)
        assert cold.cache_hits == 0
        warm = warm_registry(programs=FAST)
        assert warm.compiled == 0

    def test_cold_start_next_process(self, fresh_cache):
        """The point of the subsystem: after one warmup, a FRESH
        process cold-starts warm — every compile request is a
        persistent-cache hit, zero XLA compiles run. Both passes run
        in subprocesses: within one process jax's in-memory executable
        cache would serve the repeat compile without ever touching the
        persistent layer, which is not the cross-process contract
        being pinned here."""

        def warm_in_subprocess():
            import subprocess
            import sys

            code = (
                "import json\n"
                "from peasoup_tpu.perf.warmup import warm_registry\n"
                f"rep = warm_registry(programs={FAST!r})\n"
                "print(json.dumps([rep.compiled, rep.cache_hits,"
                " len(rep.errors)]))\n"
            )
            env = dict(
                os.environ, JAX_PLATFORMS="cpu",
                JAX_COMPILATION_CACHE_DIR=fresh_cache,
            )
            repo = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )
            env["PYTHONPATH"] = (
                repo + os.pathsep + env.get("PYTHONPATH", "")
            )
            out = subprocess.run(
                [sys.executable, "-c", code], env=env, cwd=repo,
                capture_output=True, text=True, timeout=300, check=True,
            )
            return json.loads(out.stdout.strip())

        compiled, hits, errors = warm_in_subprocess()
        assert (compiled, hits, errors) == (len(FAST), 0, 0)
        compiled, hits, errors = warm_in_subprocess()
        assert (compiled, hits, errors) == (0, len(FAST), 0)

    def test_report_doc_shape(self, fresh_cache):
        rep = warm_registry(programs=FAST[:1])
        doc = rep.to_doc()
        assert doc["programs"] == 1
        assert doc["per_program"][0]["name"] == FAST[0]
        assert doc["per_program"][0]["error"] is None
        assert doc["seconds"] >= 0

    def test_shape_ctx_for_bucket(self):
        """The ctx derives the bucket's production geometry with the
        drivers' own machinery: a real DM-trial count, the capped
        width bank, a positive wave block."""
        ctx = shape_ctx_for_bucket(BUCKET, "spsearch", SP_OVERRIDES)
        assert ctx.nsamps == 4096 and ctx.nchans == 8 and ctx.nbits == 8
        assert ctx.ndm > 0
        assert 0 < ctx.out_nsamps <= ctx.nsamps
        assert ctx.widths and max(ctx.widths) <= ctx.out_nsamps // 4
        assert 1 <= ctx.dm_block <= max(1, ctx.ndm)

    def test_param_hooks_build_production_shapes(self):
        """The ShapeCtx hooks map a ctx to the driver-sized build spec
        (singlepulse: one dm_block x out_nsamps wave), and decline
        inapplicable ctxs (sub-byte unpacker on an 8-bit bucket,
        boxcar programs on a width-less periodicity ctx)."""
        by_name = {s.name: s for s in registered_programs()}
        ctx = shape_ctx_for_bucket(BUCKET, "spsearch", SP_OVERRIDES)

        spec = by_name["ops.singlepulse.single_pulse_search"]
        fn, args, kwargs = spec.build_for(ctx)
        assert args[0].shape == (ctx.dm_block, ctx.out_nsamps)

        assert by_name["ops.dedisperse.unpack_fil_device"].build_for(
            ctx
        ) is None  # nbits=8: bytes upload unpacked

        dry = ShapeCtx(
            nsamps=4096, nchans=8, nbits=2, ndm=16, out_nsamps=4000,
            dm_block=4, dedisp_block=16, widths=(),
        )
        assert spec.build_for(dry) is None
        fn, args, kwargs = by_name[
            "ops.dedisperse.unpack_fil_device"
        ].build_for(dry)
        assert kwargs == {"nbits": 2, "nsamps": 4096, "nchans": 8}

    def test_shape_ctx_derives_fold_bucket(self):
        """ISSUE 13 satellite: the campaign ctx carries the SIFT fold
        bucket (fold_batch/fold_nsamps/fold_nbins/fold_nints) derived
        from the same dedispersed trial length the survey folder will
        bucket on — so warm_bucket pre-compiles the survey-fold
        program too."""
        from peasoup_tpu.pipeline.folder import fold_geometry

        by_name = {s.name: s for s in registered_programs()}
        for pipeline in ("spsearch", "search"):
            ctx = shape_ctx_for_bucket(BUCKET, pipeline, SP_OVERRIDES)
            assert ctx.fold_batch == 64
            assert ctx.fold_nsamps == fold_geometry(
                ctx.out_nsamps, BUCKET[3]
            )[0]
            assert ctx.fold_nbins == 64 and ctx.fold_nints == 16
            built = by_name[
                "ops.survey_fold.survey_fold_batch"
            ].build_for(ctx)
            assert built is not None
            _, args, kwargs = built
            assert args[0].shape == (64, ctx.fold_nsamps)
            assert kwargs == {"nbins": 64, "nints": 16}
        # overrides flow through (the sift batch knobs)
        ctx = shape_ctx_for_bucket(
            BUCKET, "spsearch",
            {**SP_OVERRIDES, "fold_batch": 16, "fold_nbins": 32},
        )
        assert ctx.fold_batch == 16 and ctx.fold_nbins == 32

    def test_warm_bucket_aot(self, fresh_cache):
        """AOT bucket warmup compiles the hook-parameterised programs
        at production shapes without executing anything. The bucket is
        deliberately one no other test uses, so the cold pass really
        compiles regardless of what the shared process traced before."""
        bucket = (16, 8, 6144, 0.000512, 1200.0, -8.0)
        stats = warm_bucket(
            bucket, "spsearch", SP_OVERRIDES, scratch_dir="", mode="aot"
        )
        assert stats["error"] is None
        assert stats["programs_compiled"] > 0
        assert stats["seconds"] > 0
        again = warm_bucket(
            bucket, "spsearch", SP_OVERRIDES, scratch_dir="", mode="aot"
        )
        assert again["programs_compiled"] == 0  # everything already warm

    def test_warm_bucket_dryrun(self, fresh_cache, tmp_path):
        """Dryrun warmup runs the real pipeline over a synthetic
        bucket-shaped observation and cleans up its scratch dir. (The
        compile count is not asserted: when earlier tests in the same
        process already traced these programs, the in-process jit
        caches legitimately serve everything — which is exactly the
        warm steady state. The cold-path count is pinned by the
        campaign e2e and the subprocess test above.)"""
        scratch = tmp_path / "scratch"
        stats = warm_bucket(
            BUCKET, "spsearch", SP_OVERRIDES, str(scratch), mode="dryrun"
        )
        assert stats["error"] is None
        assert stats["mode"] == "dryrun"
        assert stats["seconds"] > 0
        assert not scratch.exists()

    def test_warm_bucket_never_raises(self, tmp_path):
        stats = warm_bucket(
            ("garbage",), "spsearch", {}, str(tmp_path / "s"),
            mode="dryrun",
        )
        assert stats["error"] is not None
        assert stats["programs_compiled"] == 0


# --------------------------------------------------------------------------
# microbench + perf.json schema
# --------------------------------------------------------------------------

class TestMicrobench:
    def test_subset_bench_and_schema(self, fresh_cache, tmp_path):
        doc = run_microbench(reps=2, programs=FAST)
        assert doc["totals"]["programs"] == len(FAST)
        assert doc["totals"]["errors"] == 0
        for rec in doc["programs"].values():
            assert rec["error"] is None
            assert rec["reps"] == 2
            assert rec["execute_min_s"] <= rec["execute_median_s"]
            assert len(rec["execute_all_s"]) == 2
            assert rec["args"]  # shape signature recorded
        validate_perf(doc)
        path = tmp_path / "perf.json"
        write_perf(doc, str(path))
        assert load_perf(str(path))["programs"].keys() == doc[
            "programs"
        ].keys()

    def test_schema_rejects_malformed(self, fresh_cache):
        doc = run_microbench(reps=1, programs=FAST[:1])
        bad = copy.deepcopy(doc)
        bad["programs"][FAST[0]]["execute_median_s"] = "fast"
        with pytest.raises(SchemaError):
            validate_perf(bad)
        bad = copy.deepcopy(doc)
        del bad["totals"]
        with pytest.raises(SchemaError):
            validate_perf(bad)

    def test_broken_program_reports_error(self, fresh_cache):
        """A registry entry that stops building/tracing yields a
        record with error set (and fails the ratchet as
        program_error), not a crash."""
        from peasoup_tpu.ops.registry import ProgramSpec

        def bad_build():
            raise RuntimeError("registration drifted")

        doc = run_microbench(
            specs=[ProgramSpec(name="ops.fake.broken", build=bad_build)],
            reps=1,
        )
        rec = doc["programs"]["ops.fake.broken"]
        assert "registration drifted" in rec["error"]
        assert doc["totals"]["errors"] == 1
        validate_perf(doc)


# --------------------------------------------------------------------------
# the ratchet
# --------------------------------------------------------------------------

def _perf_doc(**programs) -> dict:
    """Minimal hand-built perf doc for ratchet unit tests."""
    recs = {}
    for name, median in programs.items():
        recs[name] = {
            "error": None,
            "args": ["f4[8]"],
            "compile_s": 0.1,
            "compile_cache_hit": False,
            "backend_compile_s": 0.1,
            "execute_median_s": median,
            "execute_min_s": median,
            "execute_mean_s": median,
            "execute_all_s": [median],
            "reps": 1,
        }
    return {
        "schema": "peasoup_tpu.perf",
        "version": 1,
        "created_unix": 0.0,
        "backend": "tpu",
        "device_kind": "fake",
        "jax_version": "0",
        "cache_dir": None,
        "reps": 1,
        "programs": recs,
        "totals": {"programs": len(recs), "errors": 0},
    }


class TestRatchet:
    def test_baseline_round_trip(self, tmp_path):
        doc = _perf_doc(**{"ops.a.x": 0.001, "ops.b.y": 0.002})
        base = baseline_from_perf(doc)
        path = tmp_path / "base.json"
        write_baseline(base, str(path))
        loaded = load_baseline(str(path))
        assert loaded == base
        assert loaded["programs"]["ops.a.x"]["execute_median_s"] == 0.001
        assert loaded["backend"] == "tpu"
        problems, _ = check_perf(doc, loaded, timing="on")
        assert problems == []

    def test_load_rejects_wrong_schema(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"schema": "something_else"}))
        with pytest.raises(ValueError):
            load_baseline(str(p))

    def test_tolerance_edges(self):
        base = baseline_from_perf(_perf_doc(**{"ops.a.x": 0.001}))
        base["tolerance"] = 1.5
        # exactly at the limit passes; epsilon above fails
        at = _perf_doc(**{"ops.a.x": 0.0015})
        problems, _ = check_perf(at, base, timing="on")
        assert problems == []
        over = _perf_doc(**{"ops.a.x": 0.0015001})
        problems, _ = check_perf(over, base, timing="on")
        assert [p.kind for p in problems] == ["slower"]
        assert "ops.a.x" in problems[0].render()

    def test_per_program_tolerance_override(self):
        base = baseline_from_perf(_perf_doc(**{"ops.a.x": 0.001}))
        base["programs"]["ops.a.x"]["tolerance"] = 10.0
        fast = _perf_doc(**{"ops.a.x": 0.009})
        assert check_perf(fast, base, timing="on")[0] == []

    def test_missing_program_fails_everywhere(self):
        """A deleted registry program is structural: it fails even with
        the timing ratchet off (the CPU CI mode)."""
        base = baseline_from_perf(
            _perf_doc(**{"ops.a.x": 0.001, "ops.b.y": 0.002})
        )
        doc = _perf_doc(**{"ops.a.x": 0.001})
        problems, _ = check_perf(doc, base, timing="off")
        assert [p.kind for p in problems] == ["missing_program"]
        assert problems[0].program == "ops.b.y"

    def test_program_error_fails(self):
        base = baseline_from_perf(_perf_doc(**{"ops.a.x": 0.001}))
        doc = _perf_doc(**{"ops.a.x": 0.001})
        doc["programs"]["ops.a.x"]["error"] = "TypeError: boom"
        problems, _ = check_perf(doc, base, timing="off")
        assert [p.kind for p in problems] == ["program_error"]

    def test_compile_ratchet_skips_cache_hits(self):
        base = baseline_from_perf(_perf_doc(**{"ops.a.x": 0.001}))
        slow = _perf_doc(**{"ops.a.x": 0.001})
        slow["programs"]["ops.a.x"]["compile_s"] = 100.0
        problems, _ = check_perf(slow, base, timing="on")
        assert [p.kind for p in problems] == ["compile_slower"]
        # a cache-served compile measures deserialisation, not XLA
        slow["programs"]["ops.a.x"]["compile_cache_hit"] = True
        assert check_perf(slow, base, timing="on")[0] == []

    def test_new_program_is_notice_not_problem(self):
        base = baseline_from_perf(_perf_doc(**{"ops.a.x": 0.001}))
        doc = _perf_doc(**{"ops.a.x": 0.001, "ops.new.z": 0.5})
        problems, notices = check_perf(doc, base, timing="on")
        assert problems == []
        assert any("ops.new.z" in n for n in notices)

    def test_timing_applies_matrix(self):
        tpu = {"backend": "tpu"}
        cpu = {"backend": "cpu"}
        assert timing_applies(tpu, tpu, "auto") is True
        assert timing_applies(cpu, cpu, "auto") is False  # CPU = weather
        assert timing_applies(tpu, cpu, "auto") is False  # cross-backend
        assert timing_applies(cpu, cpu, "on") is True
        assert timing_applies(tpu, tpu, "off") is False

    def test_baseline_excludes_broken_programs(self):
        doc = _perf_doc(**{"ops.a.x": 0.001, "ops.b.y": 0.002})
        doc["programs"]["ops.b.y"]["error"] = "broke"
        base = baseline_from_perf(doc)
        assert set(base["programs"]) == {"ops.a.x"}


# --------------------------------------------------------------------------
# the CLI (exit codes are the contract scripts/check.sh relies on)
# --------------------------------------------------------------------------

class TestPerfCLI:
    def _bench(self, tmp_path) -> str:
        out = str(tmp_path / "perf.json")
        assert perf_main(
            ["bench", "-o", out, "--reps", "1",
             "--programs", ",".join(FAST)]
        ) == 0
        return out

    def test_bench_check_write_baseline_cycle(
        self, fresh_cache, tmp_path, capsys
    ):
        perf = self._bench(tmp_path)
        base = str(tmp_path / "perf_baseline.json")
        # no baseline yet: internal error, not a silent pass
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base, "--no-warm"]
        ) == 2
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base,
             "--write-baseline"]
        ) == 0
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base, "--no-warm"]
        ) == 0
        # the warm invariant restricts itself to the perf doc's
        # programs (a subset bench must not flag the rest of the
        # registry as cold), and everything it re-lowers is warm
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base]
        ) == 0
        capsys.readouterr()

    def test_check_detects_injected_slowdown(
        self, fresh_cache, tmp_path, capsys
    ):
        perf = self._bench(tmp_path)
        base = str(tmp_path / "perf_baseline.json")
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base,
             "--write-baseline"]
        ) == 0
        doc = load_perf(perf)
        doc["programs"][FAST[0]]["execute_median_s"] *= 10
        write_perf(doc, perf)
        # structural-only (CPU auto) still passes...
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base, "--no-warm"]
        ) == 0
        # ...the timing ratchet catches it
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base, "--no-warm",
             "--timing", "on"]
        ) == 1
        out = capsys.readouterr().out
        assert "slower" in out

    def test_check_detects_deleted_program(
        self, fresh_cache, tmp_path, capsys
    ):
        perf = self._bench(tmp_path)
        base = str(tmp_path / "perf_baseline.json")
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base,
             "--write-baseline"]
        ) == 0
        doc = load_perf(perf)
        del doc["programs"][FAST[0]]
        doc["totals"]["programs"] -= 1
        write_perf(doc, perf)
        assert perf_main(
            ["check", "--perf", perf, "--baseline", base, "--no-warm"]
        ) == 1
        assert "missing_program" in capsys.readouterr().out

    def test_corrupt_perf_json_is_internal_error(self, tmp_path, capsys):
        p = tmp_path / "perf.json"
        p.write_text("{not json")
        assert perf_main(["check", "--perf", str(p)]) == 2
        capsys.readouterr()

    def test_warmup_cli(self, fresh_cache, capsys):
        assert perf_main(
            ["warmup", "--programs", ",".join(FAST)]
        ) == 0
        out = capsys.readouterr().out
        assert f"{len(FAST)} programs" in out


# --------------------------------------------------------------------------
# acceptance: the repo's checked-in baseline matches the live registry
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_full_bench_against_repo_baseline(fresh_cache, tmp_path, capsys):
    """`peasoup-perf bench && peasoup-perf check` against the
    checked-in perf_baseline.json — the ISSUE acceptance command. On
    CPU the timing ratchet is auto-off; the structural invariants
    (all 30 programs present, compiling, executing; registry
    complete; warm pass pure cache hits) do the gating."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    perf = str(tmp_path / "perf.json")
    assert perf_main(["bench", "-o", perf, "--reps", "2"]) == 0
    assert perf_main(
        ["check", "--perf", perf, "--baseline",
         os.path.join(repo, "perf_baseline.json")]
    ) == 0
    capsys.readouterr()


def test_repo_baseline_covers_registry():
    """Fast structural acceptance: the checked-in baseline and the
    live registry agree on the program set, so a deleted program (or
    an unpinned new one) is caught without running a bench."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = load_baseline(os.path.join(repo, "perf_baseline.json"))
    assert set(base["programs"]) == {
        s.name for s in registered_programs()
    }
